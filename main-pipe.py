#!/usr/bin/env python
"""Recipe 4: pipeline-parallel training.

TPU-native twin of reference `main-pipe.py` (which does not run as written —
syntax errors at main-pipe.py:63-64,72; SURVEY §2.9 — so this implements its
documented intent). The reference builds an `nn.Sequential` of stages pinned
to successive GPUs, embeddings on the first stage and norm+lm_head on the
last (main-pipe.py:52-77), wraps it in GPipe-style `Pipe(chunks=num_stages)`
(main-pipe.py:79-83) over single-process TensorPipe RPC (main-pipe.py:21-28).

Here the pipeline is a `shard_map` over a `stage` mesh axis: stacked layer
parameters shard across stages, `lax.ppermute` (XLA collective-permute over
ICI) moves activations + the threaded mask/targets stage-to-stage, and a
`lax.scan` runs the micro-batch schedule — no RPC, no wrapper modules, and
the backward comes from autodiff instead of Pipe's hand-built one. The
stage count defaults to the device count (twin of
`num_stages = torch.cuda.device_count()`, main-pipe.py:93) and micro-batch
count equals stage count (`chunks=num_stages`, main-pipe.py:83).

Interleaved virtual stages (round 22): `--pipeline_schedule 1f1b
--virtual_stages V` splits each device's layer block into V non-contiguous
chunks (device d owns chunks d, d+S, ..., d+(V-1)S), shrinking the
warm-up/cool-down bubble toward (S-1)/(M*V) at the same micro-batch count
(bench.py `pipe_interleave` measures it). MoE rides along: `--num_experts 8
--moe_dispatch pallas` runs the meshless dropless dispatch inside each
stage's chunks — the buffer dispatches ('xla'/'a2a') need an expert mesh
axis the pipeline does not carry and are rejected by name.

Run: `python main-pipe.py --batch_size 64 --num_layers 8 ...`
(num_layers must divide by the stage count).
"""

from tpukit.flags import parse_flags
from tpukit.pipeline import Pipeline, Pipeline1F1B
from tpukit.train import fit


def main(argv=None):
    flags = parse_flags(
        argv, pipeline_schedule=True, num_experts=True, default_experts=0
    )
    cls = Pipeline1F1B if flags.pipeline_schedule == "1f1b" else Pipeline
    # 4x micro-batches per stage shrink the GPipe bubble (divergence from
    # the reference's chunks=num_stages; --microbatches N restores it)
    return fit(
        flags,
        cls(
            num_microbatches=flags.microbatches or "4x",
            moe_dispatch=flags.moe_dispatch if flags.num_experts else None,
        ),
    )


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
