#!/usr/bin/env python
"""Render a tpukit metrics JSONL (`--metrics_log run.jsonl`) into a
human-readable run summary.

The trainer's StepLogger writes one JSON object per line, discriminated by
`kind` (docs/DESIGN.md "Telemetry & observability"): "train" window records
(loss, tokens/sec, MFU, goodput breakdown, HBM gauges, optional norms),
"xla" once-per-compile static analysis (FLOPs, bytes, peak memory,
per-collective comm bytes), "validation"/"epoch" per-epoch records,
"spike"/"straggler" sentinel events, and "compile_cache" hit/miss counts.
Train windows from a prefetching run additionally carry
`prefetch_stall_s`/`prefetch_occupancy` (round-7 host overlap), rendered
in the training section. Round-8 failure observability adds "watchdog"
(hang/bundle events — bundles themselves render via tools/flightview.py),
"divergence"/"divergence_check" (cross-replica checksums), and
"anomaly_trace" (trace-on-anomaly lifecycle). Round-9 recovery adds
"rollback" (in-process restores: count, steps lost, quarantined
checkpoints), "preempt" (graceful SIGTERM/SIGINT checkpoint-and-exit),
"retry" (transient host-I/O attempts absorbed by backoff), and "chaos"
(the fault-injection audit trail). Round-10 expert parallelism adds an
all-to-all dispatch audit line to the "xla" section (the strategy's
closed-form payload vs the compiled HLO's) and renders bench.py's
`moe_ep_comm` record when pointed at a bench JSON; round 11 renders the
`moe_dispatch_ladder` record (xla vs a2a vs pallas at e8 top-1/top-2,
active-FLOPs-normalized MFU — ROADMAP #3). Round 12 adds the quantized
grad-collective audit line to the "xla" section (--comm_dtype: the
closed-form compressed payload vs the compiled HLO, dtype-aware so it is
exact on CPU too) and renders bench.py's `quant_comm` record with the
bytes-on-the-wire headline. Round-13 elastic resize adds "resize"
(reshard-on-restore: the topology change, bytes read, stale files swept)
and "ckpt_prune" (--keep_checkpoints retention) to the recovery section,
plus bench.py's `elastic_restore` record. Round-14 serving adds "serve"
(per-window continuous-batching telemetry: tokens/s, slot occupancy,
admit/evict counts, prefill/decode/sync wall split, latency percentiles)
and "serve_summary" (whole-run serving headline) rendered as a
"== serving ==" section, bench.py's `serving` record (continuous
batching vs serial per-request decode on the same stream), and the
`--min_serve_tps` CI gate. Round-17 speculative decoding adds the spec
block on serve windows/summaries (acceptance rate, accepted-tokens
histogram, draft/verify wall split) and the `--min_accept_rate` gate.
Round-20 request tracing adds "trace_event"/"trace" rows (raw span events
and per-request span trees — rendered in depth by tools/traceview.py),
per-phase p50/p99 + dispatch-vs-device attribution on serve/fleet
summaries, and the `--min_trace_complete` completeness-invariant gate.
Round-21 fused decode adds bench.py's `decode_fused` record (the kernel
win and the dispatch-amortization win rendered separately) and the
`--min_decode_speedup` gate on the amortization ratio — the number that
transfers from CPU loopback, because the kernel cost cancels out of it.
Round-22 metrics plane adds "slo" rows (per-window compliance +
error-budget burn per `--slo` target) and "metrics" epilogues (compact
per-series summaries from tpukit/obs/metrics.py), rendered as
"== slo ==" / "== metrics ==" sections; `--compare baseline.jsonl`
diffs two runs' metric summaries (per-histogram p50/p99 deltas plus the
tokens/s headline); the `--min_slo_compliance` and
`--max_regression_pct` gates CI them; bench.py's `metrics_overhead`
record (pure-observer proof: token parity + <1% throughput) renders
too. Round-25 interleaved pipelines add bench.py's `pipe_interleave`
record (the tick-table bubble grid for V virtual stages per device plus
wall cross-checks) and `pipe_moe` (pipeline x pallas-dispatch MoE loss
parity), rendered as "== pipeline ==" sections and gated by
`--min_bubble_gain` — the grid is deterministic schedule accounting, so
the gate transfers from CPU. The accreted per-gate argparse/dispatch
boilerplate is
consolidated into the declarative GATES table below — one row per gate,
checker functions unchanged. This tool needs NOTHING but
the file — no jax import, so it runs anywhere the log was copied to.

Usage: python tools/report.py run.jsonl [--min_goodput 0.8]
                                        [--min_serve_tps 100]
                                        [--min_accept_rate 0.3]
                                        [--min_trace_complete 1.0]
                                        [--min_decode_speedup 1.0]
                                        [--min_bubble_gain 0.5]
                                        [--min_slo_compliance 0.99]
                                        [--compare baseline.jsonl]
                                        [--max_regression_pct 10]
"""

from __future__ import annotations

import argparse
import json
import sys


def human_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.2f} TiB"


def human_count(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= scale:
            return f"{n / scale:.2f}{suffix}"
    return f"{n:.0f}"


def load(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn final line from a killed run
    return records


def _rows(records: list[dict], kind: str) -> list[dict]:
    return [r for r in records if r.get("kind") == kind]


def _fmt_fractions(frac: dict) -> str:
    return " ".join(
        f"{k}={v * 100:.0f}%"
        for k, v in sorted(frac.items(), key=lambda kv: -kv[1])
        if v >= 0.005
    )


def _fmt_labels(labels) -> str:
    """Compact `{k=v,...}` suffix for a metric series; empty labels
    render as nothing."""
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _fmt_seconds(v) -> str:
    """Latency cell: ms below 1s, seconds above, '-' for empty series."""
    if v is None:
        return "-"
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.2f}s"


def _phase_lines(r: dict) -> list[str]:
    """Round-20 request-trace rows on a serve_summary / fleet_summary:
    per-phase p50/p99 walls and the span-tree completeness fraction."""
    out = []
    p50, p99 = r.get("phase_p50"), r.get("phase_p99")
    if isinstance(p50, dict) and isinstance(p99, dict):
        cells = [
            f"{ph} {1e3 * p50[ph]:.1f}/{1e3 * p99[ph]:.1f}"
            for ph in ("queue_wait", "prefill", "handoff", "decode",
                       "sync_stall", "other")
            if p50.get(ph) is not None
        ]
        if cells:
            out.append("  request phases p50/p99 (ms): " + "  ".join(cells))
    comp = r.get("trace_complete")
    if comp is not None:
        out.append(f"  traces: {100 * comp:.0f}% complete span trees"
                   + ("" if comp >= 1.0 else "  <- INCOMPLETE TREES"))
    # round-22: the recorder's ring evictions, surfaced per summary — a
    # saturated ring silently truncates span trees, so a nonzero count
    # gets a visible warning instead of hiding in the raw record
    dropped = r.get("trace_dropped")
    if dropped:
        by_rep = r.get("trace_dropped_by_replica")
        out.append(
            f"  trace ring evicted {dropped} span event(s)"
            + (f" ({', '.join(f'r{k}: {v}' for k, v in sorted(by_rep.items()))})"
               if by_rep else "")
            + "  <- DROPPED EVENTS (grow --trace_capacity)")
    slo_c = r.get("slo_overall_compliance")
    if slo_c is not None:
        out.append(f"  slo: overall compliance {100 * slo_c:.2f}%"
                   + ("" if slo_c >= 1.0 else "  (see == slo ==)"))
    return out


def summarize(records: list[dict]) -> str:
    out: list[str] = []
    w = out.append

    train = _rows(records, "train")
    times = [r["time"] for r in records if "time" in r]
    w("== run ==")
    if times:
        w(f"  duration: {max(times) - min(times):.1f}s "
          f"({len(records)} records, {len(train)} train windows)")

    if train:
        last = train[-1]
        losses = [r["loss"] for r in train if r.get("loss") is not None]
        tps = [r["tokens_per_sec"] for r in train if r.get("tokens_per_sec")]
        mfu = [r["mfu"] for r in train if r.get("mfu")]
        w("== training ==")
        w(f"  steps: {last.get('step', '-')}   "
          f"loss first->last: {losses[0]:.4f} -> {losses[-1]:.4f}   "
          f"min: {min(losses):.4f}")
        if tps:
            w(f"  tokens/sec last: {human_count(tps[-1])}   best: {human_count(max(tps))}"
              + (f"   MFU last: {mfu[-1] * 100:.1f}%   best: {max(mfu) * 100:.1f}%"
                 if mfu else ""))
        goodput = [r["goodput"] for r in train if r.get("goodput") is not None]
        if goodput:
            mean_gp = sum(goodput) / len(goodput)
            w(f"  goodput (time in compiled step): mean {mean_gp * 100:.1f}%  "
              f"min {min(goodput) * 100:.1f}%")
            span_keys: dict[str, list[float]] = {}
            for r in train:
                for k, v in (r.get("spans") or {}).items():
                    span_keys.setdefault(k, []).append(v)
            w("  span split (mean): "
              + _fmt_fractions({k: sum(v) / len(v) for k, v in span_keys.items()}))
        # round-7 prefetch gauges: how much of the window wall-clock the
        # training thread still blocked on input AFTER overlap, and how
        # full the prefetch buffer ran (near-depth = producer ahead,
        # near-0 = input bound)
        pstall = [
            (r["prefetch_stall_s"], r.get("window_s", 0.0))
            for r in train
            if r.get("prefetch_stall_s") is not None
        ]
        if pstall:
            tot_win = sum(wsec for _, wsec in pstall)
            share = sum(s for s, _ in pstall) / tot_win if tot_win else 0.0
            occ = [
                r["prefetch_occupancy"] for r in train
                if r.get("prefetch_occupancy") is not None
            ]
            w(f"  prefetch: stall {share * 100:.1f}% of window wall-clock"
              + (f"   buffer occupancy mean {sum(occ) / len(occ):.2f}"
                 if occ else ""))
        hbm_peaks = [
            (r.get("hbm") or {}).get("peak_bytes_in_use")
            or (r.get("hbm") or {}).get("bytes_in_use")
            for r in train
        ]
        hbm_peaks = [p for p in hbm_peaks if p]
        if hbm_peaks:
            limit = next(
                ((r.get("hbm") or {}).get("bytes_limit") for r in train
                 if (r.get("hbm") or {}).get("bytes_limit")), None)
            w(f"  peak HBM in use: {human_bytes(max(hbm_peaks))}"
              + (f" of {human_bytes(limit)}" if limit else ""))
        norms = [r for r in train if "grad_norm" in r]
        if norms:
            gn = [r["grad_norm"] for r in norms]
            w(f"  grad norm last: {gn[-1]:.4g}   max: {max(gn):.4g}   "
              f"param norm last: {norms[-1].get('param_norm', float('nan')):.4g}")

    for r in _rows(records, "xla"):
        w(f"== xla static analysis: {r.get('fn', '?')} "
          f"[{r.get('strategy', '?')}] ==")
        w(f"  flops/step: {human_count(r.get('flops'))}   "
          f"bytes accessed/step: {human_bytes(r.get('bytes_accessed'))}")
        mem = r.get("memory") or {}
        if mem:
            w(f"  memory: args {human_bytes(mem.get('argument_size_in_bytes'))}  "
              f"temp {human_bytes(mem.get('temp_size_in_bytes'))}  "
              f"peak est {human_bytes(mem.get('peak_bytes_estimate'))}")
        coll = r.get("collectives") or {}
        # Declared-empty (comm_ops = (), e.g. single device: EVERY collective
        # is a surprise) is distinct from undeclared (key absent in a foreign
        # log: nothing can be flagged).
        raw_expected = r.get("expected_comm_ops")
        expected = None if raw_expected is None else set(raw_expected)
        if coll:
            w("  comm bytes/step (from compiled HLO):")
            for op, rec in sorted(coll.items(), key=lambda kv: -kv[1]["bytes"]):
                flag = (
                    "  <- UNEXPECTED"
                    if expected is not None and op not in expected
                    else ""
                )
                w(f"    {op:<20} x{rec['count']:<4} {human_bytes(rec['bytes'])}{flag}")
        elif expected:
            w(f"  comm: none found (strategy expected {sorted(expected)})")
        # round-10 hand-scheduled dispatch audit: the strategy's closed-form
        # all-to-all payload vs what the compiled HLO actually moves.
        # Round-12 expectations carry a "wire" marker: the formula already
        # priced in the backend's wire dtype (XLA:CPU upcasts bf16 payloads
        # to f32), so bytes compare EXACTLY — no soft excuse. Older logs
        # without the marker keep the CPU bf16-upcast allowance.
        a2a_exp = r.get("a2a_expected")
        if a2a_exp is not None:
            meas = coll.get("all-to-all") or {"count": 0, "bytes": 0}
            count_ok = meas["count"] == a2a_exp.get("count")
            bytes_ok = meas["bytes"] == a2a_exp.get("bytes")
            dtype_aware = a2a_exp.get("wire") is not None
            if count_ok and bytes_ok:
                verdict = "  OK"
            elif count_ok and not dtype_aware and r.get("backend") == "cpu":
                # pre-round-12 record: the expectation was the nominal
                # accelerator size, so CPU's bf16->f32 upcast doubled the
                # measured bytes legitimately
                verdict = "  counts OK (bytes differ: CPU bf16-upcast)"
            else:
                verdict = "  <- MISMATCH"
            w(f"  all-to-all dispatch audit: measured x{meas['count']} "
              f"{human_bytes(meas['bytes'])} vs expected "
              f"x{a2a_exp.get('count')} {human_bytes(a2a_exp.get('bytes'))}"
              + (f" [{a2a_exp['wire']}]" if dtype_aware else "")
              + verdict)
        # round-12 quantized grad-collective audit (--comm_dtype): the
        # closed-form compressed grad payload (ddp two-shot all-reduce /
        # fsdp reduce-scatter a2a) vs the compiled HLO, op kind by op kind.
        # Always dtype-aware, so a byte drift is a hard flag everywhere.
        gexp = r.get("quant_grad_expected")
        if gexp is not None:
            w(f"  quantized grad audit (--comm_dtype "
              f"{r.get('comm_dtype', '?')}):")
            for op, rec in sorted(gexp.items()):
                meas = coll.get(op) or {"count": 0, "bytes": 0}
                ok = (meas["count"] == rec["count"]
                      and meas["bytes"] == rec["bytes"])
                w(f"    {op:<12} measured x{meas['count']} "
                  f"{human_bytes(meas['bytes'])} vs expected x{rec['count']} "
                  f"{human_bytes(rec['bytes'])}"
                  + ("  OK" if ok else "  <- MISMATCH"))
        # round-16 hlolint verdicts (tpukit/analysis): the rule-engine
        # summary fit() stamped on the record — CommPlan diff + the named
        # anti-pattern rules, one line unless something fired.
        hl = r.get("hlolint")
        if hl is not None:
            if hl.get("clean"):
                line = "  hlolint: clean"
            else:
                line = (f"  hlolint: {hl.get('errors', '?')} violation(s) "
                        f"<- {', '.join(hl.get('violations') or [])}")
            if hl.get("warnings"):
                line += (f"   ({hl['warnings']} warning(s): "
                         f"{', '.join(hl.get('warned') or [])})")
            ov = hl.get("overlap")
            if ov:
                line += (f"   overlap: {ov.get('overlapped', 0)}/"
                         f"{ov.get('pairs', 0)} async pairs hide compute")
            og = hl.get("overlap_gate")
            if og:
                line += (f"   overlap gate: {og.get('overlappable', 0)}/"
                         f"{og.get('declared', 0)} bucket wires hidden"
                         + (" OK" if og.get("ok") else " <- FAIL"))
            w(line)

    # standalone hlolint findings (tools/hlolint.py --out, or its JSONL
    # appended to a run log): grouped by world/source, errors first
    hlolint_rows = _rows(records, "hlolint")
    if hlolint_rows:
        w("== xla static analysis: hlolint findings ==")
        by_src: dict[str, list] = {}
        for r in hlolint_rows:
            by_src.setdefault(r.get("world") or r.get("source") or "?", []).append(r)
        for src, rows in sorted(by_src.items()):
            errs = sum(1 for r in rows if r.get("severity") == "error")
            w(f"  {src}: {len(rows)} finding(s), {errs} error(s)")
            for r in rows:
                w(f"    [{r.get('severity', '?'):<5}] {r.get('rule', '?')}: "
                  f"{r.get('message', '')}")

    val = _rows(records, "validation")
    epochs = _rows(records, "epoch")
    if val or epochs:
        w("== epochs ==")
    for r in val:
        w(f"  epoch {r.get('epoch', '?')}: val loss {r.get('loss', float('nan')):.4f}  "
          f"accuracy {r.get('accuracy', float('nan')):.2f}%")
    for r in epochs:
        w(f"  epoch {r.get('epoch', '?')} wallclock {r.get('total_s', 0):.1f}s  "
          f"goodput {r.get('goodput', 0) * 100:.1f}%  "
          f"[{_fmt_fractions(r.get('fractions') or {})}]")

    spikes = _rows(records, "spike")
    if spikes:
        w("== sentinel events ==")
        for r in spikes:
            w(f"  {r.get('event', '?'):<6} step {r.get('step', '?'):<8} "
              f"loss {r.get('loss')}"
              + (f"  (mean {r['mean']:.4f} std {r['std']:.4f})"
                 if r.get("mean") is not None else "")
              + f"  action={r.get('action', '?')}")
    stragglers = _rows(records, "straggler")
    if stragglers:
        w("== stragglers ==")
        for r in stragglers:
            w(f"  step {r.get('step', '?')}: {r.get('stragglers')}")
    # round-9 recovery: in-process rollbacks, graceful preemption, retried
    # transient I/O, and the chaos audit trail; round-13 elastic resizes
    # (reshard-on-restore) and checkpoint-retention prunes render here too
    # — recovery is the section an operator reads after a relaunch, and a
    # topology change IS a recovery event.
    rollbacks = _rows(records, "rollback")
    preempts = _rows(records, "preempt")
    retries = _rows(records, "retry")
    chaos = _rows(records, "chaos")
    resizes = _rows(records, "resize")
    prunes = _rows(records, "ckpt_prune")
    if rollbacks or preempts or retries or chaos or resizes or prunes:
        w("== recovery ==")
    for r in resizes:
        w(f"  resized: {r.get('mismatch', '?')} — resumed step "
          f"{r.get('step', '?')} from {r.get('checkpoint', '?')} "
          f"({r.get('format', '?')} reshard, "
          f"{human_bytes(r.get('bytes_read'))} in "
          f"{r.get('blocks_read', '?')} blocks, {r.get('wall_s', '?')}s"
          + (f"; swept {len(r['swept'])} stale file(s)"
             if r.get("swept") else "")
          + ")")
    if rollbacks:
        lost = sum(r.get("steps_lost", 0) for r in rollbacks)
        w(f"  rollbacks: {len(rollbacks)}   total steps lost: {lost}")
        for r in rollbacks:
            w(f"    #{r.get('seq', '?')} [{r.get('reason', '?')}] at step "
              f"{r.get('anomaly_step', '?')} -> restored step "
              f"{r.get('target_step', '?')} "
              f"({r.get('steps_lost', '?')} steps lost"
              + (f", {len(r['quarantined'])} checkpoint(s) quarantined"
                 if r.get("quarantined") else "")
              + ")")
    for r in preempts:
        w(f"  preempted: {r.get('signal', '?')} at step {r.get('step', '?')} "
          f"-> checkpoint {r.get('checkpoint', '?')} "
          f"(resume at epoch {r.get('epoch', '?')}, "
          f"batch {r.get('batch_in_epoch', '?')})")
    if retries:
        by_label: dict[str, int] = {}
        for r in retries:
            by_label[r.get("label", "?")] = by_label.get(r.get("label", "?"), 0) + 1
        w(f"  io retries: {len(retries)} ("
          + "  ".join(f"{k} x{v}" for k, v in sorted(by_label.items())) + ")")
    if chaos:
        # occurrence-indexed I/O faults also carry a drain-time "step"
        # (the trainer stamps one on every chaos event), so the
        # occurrence — the index the spec named — must win when present
        w(f"  chaos faults fired: {len(chaos)} ("
          + ", ".join(
              f"{r.get('fault', '?')}@{r.get('occurrence', r.get('step', '?'))}"
              for r in chaos) + ")")
    if prunes:
        total = sum(len(r.get("pruned") or []) for r in prunes)
        w(f"  checkpoint retention: {total} pruned over {len(prunes)} "
          f"sweep(s) (--keep_checkpoints {prunes[-1].get('keep', '?')})")
    # round-8 failure observability: hang-watchdog events, cross-replica
    # divergence, anomaly-trace lifecycle
    watchdog = _rows(records, "watchdog")
    if watchdog:
        w("== watchdog ==")
        for r in watchdog:
            if r.get("event") == "hang":
                w(f"  HANG surfaced at step {r.get('step', '?')} "
                  f"(total {r.get('hangs', '?')}); bundles: "
                  + ", ".join(r.get("bundles") or []))
            else:
                w(f"  bundle [{r.get('reason', '?')}] step {r.get('step', '?')}: "
                  f"{r.get('bundle', '?')}")
        w("  (render a bundle: python tools/flightview.py <bundle.json>)")
    divergence = _rows(records, "divergence")
    if divergence:
        w("== DIVERGENCE ==")
        for r in divergence:
            for m in r.get("mismatches") or []:
                w(f"  step {m.get('checksum_step', '?')}: process "
                  f"{m.get('process', '?')} checksum {m.get('checksum')} "
                  f"!= majority {m.get('expected')}")
    div_checks = _rows(records, "divergence_check")
    if div_checks:
        last = div_checks[-1]
        w("== divergence checks ==")
        w(f"  {len(div_checks)} checks"
          + (", no mismatches" if not divergence else "")
          + f"; last: step {last.get('step', '?')} "
          f"checksum {last.get('checksum')}")
    traces = _rows(records, "anomaly_trace")
    if traces:
        w("== anomaly trace ==")
        for r in traces:
            ev = r.get("event", "?")
            line = f"  {ev} at step {r.get('step', '?')}"
            if ev == "armed":
                line += f" (reason: {r.get('reason', '?')})"
            if r.get("dir"):
                line += f" -> {r['dir']}"
            w(line)
    # round-14 serving (tpukit/serve): per-window continuous-batching
    # telemetry + the whole-run summary. Rendered for both a recipe-9
    # --metrics_log and any log a ServeEngine wrote into.
    serve_wins = _rows(records, "serve")
    serve_sums = _rows(records, "serve_summary")
    if serve_wins or serve_sums:
        w("== serving ==")
    for r in serve_sums:
        w(f"  {r.get('requests', '?')} requests over {r.get('slots', '?')} "
          f"slots (buckets {r.get('buckets', '?')}, used "
          f"{r.get('buckets_used', '?')}): "
          f"{human_count(r.get('tokens_per_sec'))} tokens/s  "
          f"occupancy {100 * (r.get('mean_occupancy') or 0):.0f}%")
        p50e, p99e = r.get("p50_e2e_s"), r.get("p99_e2e_s")
        p50t, p99t = r.get("p50_token_s"), r.get("p99_token_s")
        if p50e is not None:
            w(f"  latency e2e p50/p99: {p50e * 1e3:.1f}/{p99e * 1e3:.1f} ms   "
              f"per-token p50/p99: {p50t * 1e3:.2f}/{p99t * 1e3:.2f} ms")
        w(f"  {r.get('generated_tokens', '?')} tokens in "
          f"{r.get('decode_steps', '?')} decode steps over "
          f"{r.get('wall_s', 0):.2f}s  (prefill {r.get('prefill_s', 0):.2f}s"
          f" / decode {r.get('decode_s', 0):.2f}s"
          f" / sync {r.get('sync_s', 0):.2f}s"
          + (f" / other {r['other_s']:.2f}s" if r.get("other_s") is not None
             else "")
          + f")   evicted: "
          f"{r.get('evicted_eos', 0)} eos, {r.get('evicted_length', 0)} length")
        # round-20 dispatch-vs-device attribution: the decode loop's
        # async-dispatch wall vs the wall spent at the per-quantum sync
        disp, dev = r.get("dispatch_overhead_s"), r.get("device_s")
        if disp is not None and dev is not None:
            tot = max(disp + dev, 1e-12)
            w(f"  dispatch vs device: {disp:.2f}s dispatch "
              f"({100 * disp / tot:.0f}%) / {dev:.2f}s device sync "
              f"({100 * dev / tot:.0f}%)")
        for ln in _phase_lines(r):
            w(ln)
        # round-15 paged KV: pool pressure + the prefill work prefix
        # reuse deleted (fields only present on paged runs)
        if r.get("page_size"):
            hit_s = r.get("admit_latency_hit_s")
            cold_s = r.get("admit_latency_cold_s")
            w(f"  paged KV: {r.get('num_pages', '?')} pages x "
              f"{r.get('page_size', '?')} tokens ({r.get('kv_dtype', '?')}), "
              f"occupancy {100 * (r.get('page_occupancy') or 0):.0f}%, "
              f"{r.get('pages_per_request') or 0:.1f} pages/request   "
              f"prefix hits {r.get('prefix_hits', 0)} "
              f"({100 * (r.get('prefix_hit_rate') or 0):.0f}%), "
              f"{r.get('prefix_pages_reused', 0)} pages skipped"
              + (f"   admit hit/cold {hit_s * 1e3:.1f}/{cold_s * 1e3:.1f} ms"
                 if hit_s is not None and cold_s is not None else ""))
        # round-17 speculative decoding: acceptance health + the
        # draft/verify wall split (fields only present on --draft runs)
        sp = r.get("spec")
        if isinstance(sp, dict):
            rate = sp.get("accept_rate")
            w(f"  speculative ({sp.get('draft', '?')}, k={sp.get('k', '?')}): "
              f"accepted {sp.get('accepted', 0)}/{sp.get('proposed', 0)} "
              f"draft tokens"
              + (f" ({100 * rate:.0f}%)" if rate is not None else "")
              + (f"   draft {r.get('draft_s', 0):.2f}s / verify "
                 f"{r.get('verify_s', 0):.2f}s"))
            hist = sp.get("accepted_hist")
            if hist:
                total = max(sum(hist), 1)
                w("  appended/verify histogram: "
                  + "  ".join(f"{i}:{100 * h / total:.0f}%"
                              for i, h in enumerate(hist)))
    if serve_wins:
        occ = [r["occupancy"] for r in serve_wins if r.get("occupancy") is not None]
        tps = [r["tokens_per_sec"] for r in serve_wins if r.get("tokens_per_sec")]
        w(f"  {len(serve_wins)} serve windows: occupancy mean "
          f"{100 * sum(occ) / len(occ):.0f}%"
          + (f"   tokens/s last {human_count(tps[-1])} best "
             f"{human_count(max(tps))}" if tps else "")
          + f"   queue depth last {serve_wins[-1].get('queue_depth', '?')}")

    # round-19 fleet serving (tpukit/serve/fleet): the router's aggregate
    # records plus the per-replica serve windows it tagged — fleet
    # tokens/s, per-replica occupancy spread, fleet p50/p99 e2e latency
    # (ROADMAP #1a), failure/requeue and autoscale accounting.
    fleet_wins = _rows(records, "fleet")
    fleet_sums = _rows(records, "fleet_summary")
    fleet_events = _rows(records, "fleet_event")
    if fleet_wins or fleet_sums:
        w("== fleet ==")
    for r in fleet_sums:
        w(f"  {r.get('requests', '?')} requests over "
          f"{r.get('replicas_final', '?')} replica(s) "
          f"(peak {r.get('replicas_peak', '?')}): "
          f"{human_count(r.get('tokens_per_sec'))} fleet tokens/s  "
          f"({r.get('generated_tokens', '?')} tokens in "
          f"{r.get('wall_s', 0):.2f}s)")
        p50, p99 = r.get("p50_e2e_s"), r.get("p99_e2e_s")
        if p50 is not None:
            w(f"  fleet latency e2e p50/p99: "
              f"{p50 * 1e3:.1f}/{p99 * 1e3:.1f} ms")
        for ln in _phase_lines(r):
            w(ln)
        if r.get("kills") or r.get("requeued"):
            dups = r.get("duplicate_completions", 0)
            w(f"  failures: {r.get('kills', 0)} replica kill(s), "
              f"{r.get('requeued', 0)} request(s) re-queued, "
              f"{dups} duplicate completion(s)"
              + ("" if not dups else "  <- EXACTLY-ONCE VIOLATED"))
        # round-24 fleet recovery: the crash-tolerance plane's accounting —
        # liveness deaths, lease revocation/requeue, deadline misses,
        # backpressure sheds, terminal failures, ledger replay, retried
        # transient I/O. Rendered whenever any of it is nonzero (or a
        # ledger ran), so a clean run stays one line shorter.
        led = r.get("ledger")
        recovery = [r.get("replicas_dead"), r.get("leases_revoked"),
                    r.get("deadline_misses"), r.get("rejected"),
                    r.get("request_failures"), r.get("retry_total"),
                    r.get("respawns")]
        if any(recovery) or isinstance(led, dict):
            n_req = max(r.get("requests") or 0, 1)
            miss = r.get("deadline_misses", 0) or 0
            w(f"  fleet recovery: {r.get('replicas_dead', 0) or 0} liveness "
              f"death(s), {r.get('leases_revoked', 0) or 0} lease(s) "
              f"revoked, {r.get('requeued', 0)} requeued, "
              f"{miss} deadline miss(es) "
              f"({100.0 * miss / n_req:.1f}%), "
              f"{r.get('rejected', 0) or 0} shed by backpressure, "
              f"{r.get('request_failures', 0) or 0} terminal failure(s)"
              + (f", {r.get('retry_total')} transient I/O retried"
                 if r.get("retry_total") else "")
              + (f", {r.get('respawns')} respawn(s)"
                 if r.get("respawns") else ""))
        if isinstance(led, dict):
            w(f"  ledger: {led.get('completed', 0)} durable completion "
              f"record(s), {led.get('replayed', 0)} replayed on restart, "
              f"{led.get('duplicates', 0)} duplicate record(s)"
              + ("" if not led.get("duplicates")
                 else "  <- EXACTLY-ONCE VIOLATED"))
        codes = r.get("worker_exit_codes")
        if isinstance(codes, dict) and codes:
            w("  worker exit codes: " + "  ".join(
                f"r{k}={'SIGKILL' if v == -9 else v}"
                for k, v in sorted(codes.items(), key=lambda kv: str(kv[0]))))
        if r.get("scale_ups") or r.get("scale_downs"):
            w(f"  autoscale: {r.get('scale_ups', 0)} up / "
              f"{r.get('scale_downs', 0)} down")
        dp = r.get("disagg_prefill")
        if isinstance(dp, dict):
            w(f"  disaggregated prefill: {dp.get('handoffs', 0)} handoffs, "
              f"{dp.get('worker_prefix_hits', 0)} worker prefix hits, "
              f"{dp.get('worker_pages_reused', 0)} pages of prefill "
              f"skipped")
        if r.get("params_placements") is not None:
            w(f"  cold start: {r['params_placements']} params placement(s) "
              f"from one host copy")
    # per-replica occupancy spread from the replica-tagged serve windows
    # (each replica is a full engine emitting its own kind="serve" rows)
    by_rep: dict = {}
    for r in serve_wins:
        if r.get("replica") is not None and r.get("occupancy") is not None:
            by_rep.setdefault(r["replica"], []).append(r["occupancy"])
    if by_rep and (fleet_wins or fleet_sums):
        means = {k: sum(v) / len(v) for k, v in sorted(by_rep.items(),
                                                       key=lambda kv: str(kv[0]))}
        spread = (max(means.values()) - min(means.values())
                  if len(means) > 1 else 0.0)
        w("  per-replica occupancy: "
          + "  ".join(f"r{k}={100 * m:.0f}%" for k, m in means.items())
          + f"   spread {100 * spread:.0f}%")
    if fleet_wins:
        occ = [r["occupancy"] for r in fleet_wins
               if r.get("occupancy") is not None]
        tps = [r["tokens_per_sec"] for r in fleet_wins
               if r.get("tokens_per_sec")]
        w(f"  {len(fleet_wins)} fleet windows: occupancy mean "
          f"{100 * sum(occ) / max(len(occ), 1):.0f}%"
          + (f"   tokens/s last {human_count(tps[-1])} best "
             f"{human_count(max(tps))}" if tps else "")
          + f"   queue depth last {fleet_wins[-1].get('queue_depth', '?')}")
    if fleet_events:
        w(f"  events: " + ", ".join(
            f"{r.get('event', '?')}"
            + (f"(r{r['replica']})" if r.get("replica") is not None else "")
            for r in fleet_events))

    # round-22 SLO accounting (tpukit/obs/metrics.py): declared targets,
    # cumulative compliance, and error-budget burn. The LAST record
    # carries the run-level cumulative rows (sample-weighted), earlier
    # ones are per-window snapshots; burn > 1 means the run is consuming
    # error budget faster than the objective allows.
    slo_rows = _rows(records, "slo")
    if slo_rows:
        last = slo_rows[-1]
        w("== slo ==")
        oc = last.get("overall_compliance")
        w(f"  {len(slo_rows)} slo window(s); overall compliance: "
          + (f"{100 * oc:.2f}%" if oc is not None else "no samples"))
        for t in last.get("targets") or []:
            cc, cb = t.get("cum_compliance"), t.get("cum_burn")
            if cc is None:
                w(f"  {t.get('slo', '?'):<20} no samples")
                continue
            met = cc >= (t.get("q") or 0)
            w(f"  {t.get('slo', '?'):<20} compliance {100 * cc:.2f}% "
              f"over {t.get('cum_n', '?')} samples   burn {cb:.2f}x budget"
              + ("" if met else "  <- VIOLATED"))
    # round-22 metrics epilogues: the registry's compact per-series
    # summaries (full bucket tables live in --metrics_dir snapshots).
    # Counters one line, histograms a small table — enough to eyeball a
    # run without the live dashboard (tools/top.py renders the same
    # registry continuously).
    for r in _rows(records, "metrics"):
        w(f"== metrics ({r.get('source', '?')}) ==")
        counters = r.get("counters") or []
        if counters:
            w("  counters: " + "  ".join(
                f"{c['name']}{_fmt_labels(c.get('labels'))}="
                f"{human_count(c['value'])}"
                for c in counters))
        hists = r.get("hists") or []
        if hists:
            w(f"  {'histogram':<36} {'count':>8} {'p50':>10} {'p99':>10}")
            for h in hists:
                p50, p99 = h.get("p50"), h.get("p99")
                # the `_s` suffix convention names the time-valued series;
                # everything else (token counts, ...) renders as a count
                fmt = (_fmt_seconds if h["name"].endswith("_s")
                       else lambda v: "-" if v is None else human_count(v))
                w(f"  {h['name'] + _fmt_labels(h.get('labels')):<36} "
                  f"{human_count(h.get('count')):>8} "
                  f"{fmt(p50):>10} {fmt(p99):>10}")

    cache_rows = _rows(records, "compile_cache")
    if cache_rows:
        w("== compile cache ==")
    for r in cache_rows:
        hits, misses = r.get("hits"), r.get("misses")
        w(f"  {r.get('dir', '?')}: "
          + (f"hits {hits}  misses {misses}  "
             if hits is not None else "")
          + f"entries {r.get('entries', '-')} (+{r.get('new_entries', 0)} this run)")
    # bench.py output is itself one JSON line, so `python tools/report.py
    # bench.json` renders it too; the round-10 moe_ep_comm record is the
    # EP dispatch audit (expected vs measured all-to-all, remat warnings).
    for r in records:
        moe = r.get("moe_ep_comm")
        if not isinstance(moe, dict):
            continue
        w("== moe ep comm (bench) ==")
        mesh = moe.get("mesh") or {}
        w(f"  mesh {mesh}  dispatch {moe.get('dispatch', '?')}   "
          f"tokens/sec/chip {human_count(moe.get('tokens_per_sec_per_chip'))}")
        exp, meas = moe.get("expected_a2a") or {}, moe.get("measured_a2a") or {}
        w(f"  all-to-all: measured x{meas.get('count', 0)} "
          f"{human_bytes(meas.get('bytes', 0))} vs expected "
          f"x{exp.get('count', 0)} {human_bytes(exp.get('bytes', 0))}"
          + ("  OK" if moe.get("bytes_match") else "  <- MISMATCH"))
        warns = moe.get("involuntary_remat_warnings")
        if warns is not None:
            w(f"  involuntary-remat warnings at compile: {warns}"
              + ("" if warns == 0 else "  <- GSPMD replicate-repartition!"))
    # round-12 quantized collectives (ROADMAP #2): f32 vs bf16 vs int8
    # --comm_dtype per strategy rung, with the bytes-on-the-wire cut as
    # the headline and the loss delta as the tolerance-gate number.
    for r in records:
        qc = r.get("quant_comm")
        if not isinstance(qc, list) or not qc:
            continue
        w("== quantized collectives (bench, --comm_dtype) ==")
        int8_ratios = []
        for row in qc:
            if "error" in row:
                w(f"  {row.get('strategy', '?'):<5} "
                  f"{row.get('comm_dtype', '?'):<5} ERROR {row['error']}")
                continue
            ratio = row.get("wire_ratio_vs_f32")
            delta = row.get("loss_delta_vs_f32")
            match = row.get("bytes_match")
            warns = row.get("involuntary_remat_warnings")
            w(f"  {row['strategy']:<5} {row['comm_dtype']:<5} "
              f"wire {human_bytes(row.get('wire_bytes'))}"
              + (f" ({ratio * 100:.1f}% of f32)" if ratio is not None else "")
              + f"   {human_count(row.get('tokens_per_sec_per_chip'))} tok/s/chip"
              + (f"   dloss vs f32 {delta:+.4g}" if delta is not None else "")
              + ("" if match is None
                 else ("   audit OK" if match else "   audit <- MISMATCH"))
              + ("" if not warns else f"   remat warnings {warns}!"))
            if row["comm_dtype"] == "int8" and ratio:
                int8_ratios.append(ratio)
        if int8_ratios:
            cut = 1.0 / (sum(int8_ratios) / len(int8_ratios))
            w(f"  headline: int8 payloads move ~{cut:.1f}x fewer bytes on "
              f"the wire than f32 (mean over strategy rungs)")
    # round-18 overlap schedule (ROADMAP #5): f32 vs int8 vs int8+buckets
    # per strategy — the wire cut and the overlap win separately visible;
    # overlap_frac is the gated schedule property (--min_overlap_frac),
    # step time the wall-clock observable.
    for r in records:
        co = r.get("comm_overlap")
        if not isinstance(co, list) or not co:
            continue
        w("== overlap-scheduled collectives (bench, --grad_buckets) ==")
        for row in co:
            if "error" in row:
                w(f"  {row.get('strategy', '?'):<5} "
                  f"{row.get('comm_dtype', '?'):<5} "
                  f"b{row.get('grad_buckets', '?')} ERROR {row['error']}")
                continue
            label = (f"{row['comm_dtype']}"
                     + (f"+overlap(b{row['grad_buckets']})"
                        if row.get("grad_buckets") else ""))
            ov = row.get("overlap") or {}
            frac = ov.get("overlap_frac")
            rel = row.get("step_time_vs_f32")
            warns = row.get("involuntary_remat_warnings")
            match = row.get("bytes_match")
            w(f"  {row['strategy']:<5} {label:<16} "
              f"step {row.get('step_time_s', 0) * 1e3:.2f}ms"
              + (f" ({rel * 100:.1f}% of f32)" if rel is not None else "")
              + f"   {human_count(row.get('tokens_per_sec_per_chip'))} tok/s/chip"
              + (f"   overlap {ov.get('overlappable', '?')}/"
                 f"{ov.get('declared', '?')} wires hidden"
                 + (" OK" if ov.get("gate_ok") else " <- GATE FAIL")
                 if frac is not None else "")
              + ("" if match is None
                 else ("   audit OK" if match else "   audit <- MISMATCH"))
              + ("" if not warns else f"   remat warnings {warns}!"))
    # round-25 interleaved pipeline (--virtual_stages): the tick-table
    # bubble grid (the gated, backend-free numbers) plus the timed rungs'
    # wall cross-check, and the pipeline x MoE pallas parity rung.
    for r in records:
        pi = r.get("pipe_interleave")
        if not isinstance(pi, dict):
            continue
        w("== pipeline (bench, --virtual_stages) ==")
        if "error" in pi:
            w(f"  ERROR {pi['error']}")
            continue
        w(f"  stages {pi.get('stages', '?')}  microbatches "
          f"{pi.get('microbatches', '?')}  layers {pi.get('layers', '?')}")
        by_m: dict = {}
        for row in pi.get("bubble_table") or []:
            by_m.setdefault(row.get("micro"), []).append(row)
        for m, rows_m in sorted(by_m.items()):
            cells = " -> ".join(
                f"V{row['virtual_stages']} {row['bubble_frac']:.3f}"
                for row in sorted(rows_m,
                                  key=lambda x: x["virtual_stages"]))
            w(f"  bubble @M={m}: {cells}")
        for row in pi.get("rungs") or []:
            if "error" in row:
                w(f"  V={row.get('virtual_stages', '?')}  ERROR "
                  f"{row['error']}")
                continue
            wall = row.get("wall_ratio_vs_flat")
            w(f"  V={row['virtual_stages']}  bubble "
              f"{row.get('bubble_frac', 0):.3f}   predicted "
              f"{row.get('predicted_ratio_vs_flat', 0) * 100:.1f}% of flat"
              + (f"   wall {wall * 100:.1f}%" if wall is not None else "")
              + f"   {human_count(row.get('tokens_per_sec_per_chip'))} "
              f"tok/s/chip")
        if pi.get("caveat"):
            w(f"  caveat: {pi['caveat']}")
    for r in records:
        pm = r.get("pipe_moe")
        if not isinstance(pm, dict):
            continue
        w("== pipeline x moe (bench, --moe_dispatch pallas) ==")
        if "error" in pm:
            w(f"  ERROR {pm['error']}")
            continue
        w(f"  {pm.get('stages', '?')} stages x V={pm.get('virtual_stages', '?')}"
          f" M={pm.get('microbatches', '?')}, e{pm.get('num_experts', '?')} "
          f"{pm.get('dispatch', '?')} dispatch: "
          f"{human_count(pm.get('tokens_per_sec_per_chip'))} tok/s/chip")
        w(f"  loss parity vs single device: {pm.get('loss', '?')} vs "
          f"{pm.get('ref_loss', '?')} (delta {pm.get('loss_delta', '?')})"
          + ("  OK" if pm.get("parity_ok") else "  <- MISMATCH"))
    # round-13 elastic restore (ROADMAP #5): what a reshard-on-restore
    # relaunch costs — wall-clock, bytes read, host RSS high-water delta,
    # and the byte-parity bit vs a direct restore. Rendered under the
    # recovery banner: a topology change is a recovery event.
    for r in records:
        er = r.get("elastic_restore")
        if not isinstance(er, dict):
            continue
        w("== recovery: elastic restore (bench) ==")
        if "error" in er:
            w(f"  ERROR {er['error']}")
            continue
        fw, tw = er.get("from_world") or {}, er.get("to_world") or {}
        w(f"  {fw.get('strategy', '?')}@{fw.get('devices', '?')} -> "
          f"{tw.get('strategy', '?')}@{tw.get('devices', '?')}: "
          f"{er.get('restore_wall_s', '?')}s   "
          f"read {human_bytes(er.get('bytes_read'))} in "
          f"{er.get('blocks_read', '?')} blocks "
          f"(state {human_bytes(er.get('state_bytes'))})")
        overhead = er.get("rss_overhead_bytes")
        w(f"  host RSS high-water delta: "
          f"{human_bytes(er.get('peak_rss_delta_bytes'))}"
          + (f" (scratch overhead above resident state: "
             f"{human_bytes(overhead)})" if overhead is not None else "")
          + "   parity vs direct restore: "
          + ("OK" if er.get("parity_ok") else "<- MISMATCH"))
    # round-14 serving bench (ROADMAP #1): continuous batching vs serial
    # per-request decode on the SAME seeded synthetic stream — the >= 2x
    # tokens/s headline plus the latency/occupancy numbers a capacity
    # planner reads.
    for r in records:
        sv = r.get("serving")
        if not isinstance(sv, dict):
            continue
        w("== serving (bench, continuous vs serial) ==")
        if "error" in sv:
            w(f"  ERROR {sv['error']}")
            continue
        w(f"  stream: {sv.get('requests', '?')} requests, "
          f"{sv.get('generated_tokens', '?')} generated tokens, "
          f"{sv.get('slots', '?')} slots, buckets {sv.get('buckets', '?')}")
        rows = (("continuous", sv.get("continuous")),
                ("serial", sv.get("serial")),
                ("serial_cached", sv.get("serial_cached")))
        for name, row in rows:
            if not row:
                continue
            p50, p99 = row.get("p50_e2e_s"), row.get("p99_e2e_s")
            w(f"  {name:<14} {human_count(row.get('tokens_per_sec'))} tokens/s"
              + (f"   e2e p50/p99 {p50 * 1e3:.1f}/{p99 * 1e3:.1f} ms"
                 if p50 is not None else "")
              + (f"   occupancy {100 * row['mean_occupancy']:.0f}%"
                 if row.get("mean_occupancy") is not None else ""))
        sp = sv.get("speedup")
        if sp is not None:
            w(f"  headline: continuous batching {sp:.2f}x serial "
              f"per-request generate on the same stream"
              + ("" if sp >= 2.0 else "  <- BELOW the 2x acceptance bar"))
        spc = sv.get("speedup_vs_cached")
        if spc is not None:
            w(f"  vs the strongest serial baseline (forced cached "
              f"while_loop): {spc:.2f}x")
    # round-15 paged-KV bench (ROADMAP #2): ring vs paged vs paged+int8 at
    # EQUAL KV HBM — the >= 2x concurrent-slots bar with int8 pages, the
    # exact-parity bit, and prefix-hit vs cold admit latency.
    for r in records:
        pk = r.get("paged_kv")
        if not isinstance(pk, dict):
            continue
        w("== paged kv (bench, equal KV HBM) ==")
        if "error" in pk:
            w(f"  ERROR {pk['error']}")
            continue
        w(f"  stream: {pk.get('requests', '?')} requests, buckets "
          f"{pk.get('buckets', '?')}, page {pk.get('page_size', '?')} tokens")
        for name in ("ring", "paged", "paged_int8"):
            row = pk.get(name)
            if not row:
                continue
            w(f"  {name:<11} {human_count(row.get('tokens_per_sec'))} tokens/s"
              f"   slots {row.get('max_live_slots', '?')}/"
              f"{row.get('slots', '?')} live   KV "
              f"{human_bytes(row.get('kv_bytes'))}")
        ratio = pk.get("slots_at_equal_hbm_ratio")
        if ratio is not None:
            w(f"  headline: {ratio:.2f}x concurrent slots at equal KV HBM "
              f"with int8 pages"
              + ("" if ratio >= 2.0 else "  <- BELOW the 2x acceptance bar"))
        w("  paged f32 parity vs ring: "
          + ("token-exact" if pk.get("parity_ok") else "<- MISMATCH")
          + (f"   int8 token agreement {100 * pk['int8_token_agreement']:.1f}%"
             if pk.get("int8_token_agreement") is not None else ""))
        px = pk.get("prefix") or {}
        if px.get("hits") is not None:
            hit_s, cold_s = px.get("admit_latency_hit_s"), px.get("admit_latency_cold_s")
            w(f"  shared-prefix stream: {px['hits']} hits "
              f"({100 * (px.get('hit_rate') or 0):.0f}% of admissions), "
              f"{px.get('pages_reused', 0)} pages of prefill skipped"
              + (f"   admit latency hit/cold {hit_s * 1e3:.1f}/"
                 f"{cold_s * 1e3:.1f} ms" if hit_s is not None
                 and cold_s is not None else ""))
    # round-21 fused-decode bench (ROADMAP #2/#4): the kernel win and the
    # dispatch-amortization win rendered SEPARATELY — the bench isolates
    # them so neither can hide behind the other, and the renderer keeps
    # them apart for the same reason.
    for r in records:
        df = r.get("decode_fused")
        if not isinstance(df, dict):
            continue
        w("== fused decode (bench, --fused_decode) ==")
        if "error" in df:
            w(f"  ERROR {df['error']}")
            continue
        w(f"  stream: {df.get('requests', '?')} requests, "
          f"{df.get('slots', '?')} slots, page {df.get('page_size', '?')} "
          f"tokens, window {df.get('window_quanta', '?')} quanta")
        for name in ("unfused_q1", "fused_q1", "fused_loop"):
            row = df.get(name)
            if not row:
                continue
            disp = row.get("mean_dispatch_ms_per_quantum")
            dev = row.get("mean_device_ms_per_quantum")
            w(f"  {name:<11} {human_count(row.get('tokens_per_sec'))} "
              f"tokens/s   {row.get('quanta', '?')} quanta / "
              f"{row.get('decode_steps', '?')} steps"
              + (f"   dispatch/device {disp:.2f}/{dev:.2f} ms per quantum"
                 if disp is not None and dev is not None else "")
              + (f"   trace {df_tc:.2f}" if (df_tc := row.get(
                    "trace_complete")) is not None else ""))
        ks, am = df.get("kernel_speedup"), df.get("amortization_speedup")
        if ks is not None:
            w(f"  kernel win (fused vs unfused @ quantum=1): {ks:.2f}x"
              + ("" if ks >= 1.0 else "  (interpret-mode CPU: the kernel "
                 "runs as a scanned emulation — expected on this backend)"))
        if am is not None:
            w(f"  amortization win (on-device loop vs per-step dispatch): "
              f"{am:.2f}x  <- the gated, backend-transferable number")
        w("  token parity across all rungs: "
          + ("exact" if df.get("parity_ok") else "<- MISMATCH"))
    # round-22 metrics-overhead bench: the pure-observer proof. Tokens
    # must be bit-identical with the metrics plane on vs --no_metrics,
    # and the throughput cost must stay under the 1% budget; the
    # snapshot-publish wall is the only new I/O and is timed separately.
    for r in records:
        mo = r.get("metrics_overhead")
        if not isinstance(mo, dict):
            continue
        w("== metrics overhead (bench, pure-observer proof) ==")
        if "error" in mo:
            w(f"  ERROR {mo['error']}")
            continue
        off, on = mo.get("tokens_per_sec_off"), mo.get("tokens_per_sec_on")
        frac = mo.get("overhead_frac")
        w(f"  {mo.get('requests', '?')} requests: "
          f"{human_count(off)} tokens/s metrics-off vs {human_count(on)} on"
          + (f"   overhead {100 * frac:.2f}%"
             + ("" if frac <= 0.01 else "  <- ABOVE the 1% budget")
             if frac is not None else ""))
        w("  token parity on vs off: "
          + ("bit-identical" if mo.get("tokens_bit_identical")
             else "<- MISMATCH")
          + (f"   snapshot publish {mo['snapshot_publish_s'] * 1e3:.2f} ms"
             if mo.get("snapshot_publish_s") is not None else "")
          + (f"   ({mo['series']} series)"
             if mo.get("series") is not None else ""))
    # round-19 fleet bench (ROADMAP #1): the replica scaling curve at
    # equal total devices + the disaggregated-prefill admit-latency
    # comparison, with the CPU-loopback caveat carried in-record.
    for r in records:
        fs = r.get("fleet_serving")
        if not isinstance(fs, dict):
            continue
        w("== fleet serving (bench, replicas at equal total devices) ==")
        if "error" in fs:
            w(f"  ERROR {fs['error']}")
            continue
        w(f"  stream: {fs.get('requests', '?')} requests, "
          f"{fs.get('slots_per_replica', '?')} slots/replica, "
          f"{fs.get('total_devices', '?')} total devices"
          + ("" if fs.get("meshed") else " (meshless rungs)"))
        for row in fs.get("rungs") or []:
            if "error" in row:
                w(f"  {row.get('replicas', '?')}x  ERROR {row['error']}")
                continue
            p99 = row.get("p99_e2e_s")
            w(f"  {row['replicas']}x replicas "
              f"({row.get('devices_per_replica', 0)} dev each): "
              f"{human_count(row.get('tokens_per_sec'))} tokens/s"
              + (f"   e2e p99 {p99 * 1e3:.1f} ms" if p99 is not None else "")
              + (f"   admit {row['mean_admit_latency_s'] * 1e3:.1f} ms"
                 if row.get("mean_admit_latency_s") is not None else ""))
        sc = fs.get("scaling_2x_vs_1")
        if sc is not None:
            w(f"  headline: 2 replicas = {sc:.2f}x the 1-replica fleet "
              f"tokens/s at equal total devices"
              + ("" if sc > 1.5 else "  <- BELOW the 1.5x acceptance bar"))
        w("  cross-rung token parity: "
          + ("OK" if fs.get("parity_ok") else "<- MISMATCH"))
        dp = fs.get("disagg_prefill")
        if isinstance(dp, dict):
            if "error" in dp:
                w(f"  disagg prefill probe ERROR {dp['error']}")
            else:
                ca, da = (dp.get("colocated_admit_latency_s"),
                          dp.get("disagg_admit_latency_s"))
                w(f"  prefill: colocated admit "
                  f"{(ca or 0) * 1e3:.1f} ms vs disaggregated "
                  f"{(da or 0) * 1e3:.1f} ms   ({dp.get('handoffs', '?')} "
                  f"handoffs, {dp.get('worker_prefix_hits', '?')} worker "
                  f"prefix hits)")
        if fs.get("caveat"):
            w(f"  caveat: {fs['caveat']}")
    # round-11 dispatch ladder (ROADMAP #3): the three MoE dataflows side
    # by side at e8 top-1/top-2, MFU normalized by ACTIVE FLOPs (top_k
    # experts + router per token) so padding/dispatch waste reads as lost
    # MFU rather than inflating the FLOP count.
    for r in records:
        ladder = r.get("moe_dispatch_ladder")
        if not isinstance(ladder, list) or not ladder:
            continue
        w("== moe dispatch ladder (bench, active-FLOPs MFU) ==")
        for row in ladder:
            if "error" in row:
                w(f"  {row.get('dispatch', '?'):<7} top{row.get('top_k', '?')}"
                  f"  ERROR {row['error']}")
                continue
            mfu_a = row.get("mfu_active")
            w(f"  {row['dispatch']:<7} top{row['top_k']}  "
              f"{human_count(row.get('tokens_per_sec_per_chip'))} tok/s/chip"
              + (f"   active-FLOPs MFU {mfu_a * 100:.1f}%"
                 if mfu_a is not None else ""))
    return "\n".join(out)


def check_min_goodput(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Cheap perf-regression gate (`--min_goodput`): mean goodput over the
    run's train windows must reach `threshold`. Returns (ok, message)."""
    gp = [
        r["goodput"] for r in _rows(records, "train")
        if r.get("goodput") is not None
    ]
    if not gp:
        return False, "--min_goodput: no train windows with goodput in the log"
    mean_gp = sum(gp) / len(gp)
    verdict = "OK" if mean_gp >= threshold else "FAIL"
    return mean_gp >= threshold, (
        f"--min_goodput {verdict}: mean goodput {mean_gp:.3f} over "
        f"{len(gp)} windows (threshold {threshold:.3f})"
    )


def check_min_serve_tps(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Serving-throughput CI gate (`--min_serve_tps`): the run's
    `kind="serve_summary"` tokens/s must reach `threshold`. Returns
    (ok, message) — missing summary fails, a serving regression must not
    hide behind an empty log."""
    sums = [r for r in _rows(records, "serve_summary")
            if r.get("tokens_per_sec") is not None]
    if not sums:
        return False, "--min_serve_tps: no serve_summary record in the log"
    tps = sums[-1]["tokens_per_sec"]
    verdict = "OK" if tps >= threshold else "FAIL"
    return tps >= threshold, (
        f"--min_serve_tps {verdict}: {tps:.1f} tokens/s "
        f"(threshold {threshold:.1f})"
    )


def check_min_accept_rate(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Speculative-decoding health gate (`--min_accept_rate`, round 17):
    the run's `kind="serve_summary"` spec acceptance rate must reach
    `threshold`. Returns (ok, message) — a log without a spec summary
    fails, so the gate can't pass vacuously when someone drops `--draft`
    from the smoke invocation."""
    sums = [r for r in _rows(records, "serve_summary")
            if isinstance(r.get("spec"), dict)
            and r["spec"].get("accept_rate") is not None]
    if not sums:
        return False, ("--min_accept_rate: no serve_summary with a spec "
                       "accept_rate in the log (was the run --draft'ed?)")
    sp = sums[-1]["spec"]
    rate = sp["accept_rate"]
    verdict = "OK" if rate >= threshold else "FAIL"
    return rate >= threshold, (
        f"--min_accept_rate {verdict}: {rate:.3f} "
        f"({sp.get('accepted', 0)}/{sp.get('proposed', 0)} draft tokens, "
        f"{sp.get('draft', '?')} k={sp.get('k', '?')}; "
        f"threshold {threshold:.3f})"
    )


def check_min_fleet_tps(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Fleet-throughput CI gate (`--min_fleet_tps`, round 19): the run's
    `kind="fleet_summary"` tokens/s must reach `threshold`, AND the
    exactly-once invariant must hold (zero duplicate completions — a
    killed replica's requests must re-queue, not double-emit). Returns
    (ok, message) — a log without a fleet summary fails, so the gate
    can't pass vacuously when someone drops `--replicas` from the smoke
    invocation (the `--min_accept_rate` discipline)."""
    sums = [r for r in _rows(records, "fleet_summary")
            if r.get("tokens_per_sec") is not None]
    if not sums:
        return False, ("--min_fleet_tps: no fleet_summary record in the "
                       "log (was the run --replicas'ed?)")
    s = sums[-1]
    tps = s["tokens_per_sec"]
    dups = s.get("duplicate_completions", 0)
    ok = tps >= threshold and not dups
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--min_fleet_tps {verdict}: {tps:.1f} fleet tokens/s over "
        f"{s.get('replicas_peak', '?')} peak replica(s), "
        f"{s.get('requeued', 0)} re-queued, {dups} duplicate completion(s) "
        f"(threshold {threshold:.1f}"
        + ("" if not dups else "; duplicates violate exactly-once")
        + ")"
    )


def check_max_deadline_miss_pct(records: list[dict],
                                threshold: float) -> tuple[bool, str]:
    """Deadline-miss CI gate (`--max_deadline_miss_pct`, round 24): the
    last `kind="fleet_summary"` record's deadline_misses as a percentage
    of served requests must be <= `threshold`. Returns (ok, message) — a
    log without a fleet summary, or a summary missing the
    deadline_misses field (a pre-round-24 log), FAILS: the gate can't
    pass vacuously against a run that never accounted deadlines (the
    `--min_accept_rate` discipline)."""
    sums = _rows(records, "fleet_summary")
    if not sums:
        return False, ("--max_deadline_miss_pct: no fleet_summary record "
                       "in the log (was the run --replicas'ed?)")
    s = sums[-1]
    miss = s.get("deadline_misses")
    if miss is None:
        return False, ("--max_deadline_miss_pct: fleet_summary carries no "
                       "deadline_misses field (pre-round-24 log? rerun "
                       "with the current recipe)")
    n_req = s.get("requests") or 0
    pct = 100.0 * miss / n_req if n_req else 0.0
    ok = pct <= threshold
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--max_deadline_miss_pct {verdict}: {miss}/{n_req} requests "
        f"missed their deadline ({pct:.2f}%; threshold {threshold:.2f}%)"
    )


def check_min_trace_complete(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Trace-completeness CI gate (`--min_trace_complete`, round 20): the
    fraction of `kind="trace"` span trees satisfying the completeness
    invariant (closed — enqueue, >=1 admit, exactly one finish — AND
    named phase walls summing to <= e2e + 1e-3 s) must reach
    `threshold`. Returns (ok, message) — a log without trace rows fails,
    so the gate can't pass vacuously when someone passes `--no_trace` to
    the smoke invocation (the `--min_accept_rate` discipline)."""
    trees = _rows(records, "trace")
    if not trees:
        return False, ("--min_trace_complete: no trace record in the log "
                       "(was the run started with --no_trace?)")
    n_complete = sum(1 for t in trees if t.get("complete"))
    n_open = sum(1 for t in trees if not t.get("closed"))
    frac = n_complete / len(trees)
    ok = frac >= threshold
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--min_trace_complete {verdict}: {n_complete}/{len(trees)} span "
        f"trees complete ({frac:.3f}; {n_open} open; threshold "
        f"{threshold:.3f})"
    )


def check_min_overlap_frac(records: list[dict], threshold: float) -> tuple[bool, str]:
    """Overlap-schedule gate (`--min_overlap_frac`, round 18): every
    bucketed rung of the bench `comm_overlap` record must have
    overlap_frac (overlappable / declared bucket wires, from the
    promoted hlolint `overlap` rule) >= `threshold`. Returns
    (ok, message) — a log without any overlap rung fails, so the gate
    can't pass vacuously when someone drops the bucketed rungs from the
    bench invocation. The fraction is the static schedule property: on
    CPU virtual devices wall-clock overlap is noise, the structure is
    what CI pins."""
    fracs, broken = [], []
    for r in records:
        co = r.get("comm_overlap")
        if not isinstance(co, list):
            continue
        for row in co:
            if not isinstance(row, dict) or not row.get("grad_buckets"):
                continue
            # every BUCKETED rung must carry a verdict: an errored rung
            # or one missing its overlap block is a gate failure, not a
            # skipped sample — else a crashed strategy passes silently
            name = f"{row.get('strategy', '?')}/b{row.get('grad_buckets')}"
            ov = row.get("overlap")
            if "error" in row or not isinstance(ov, dict) \
                    or ov.get("overlap_frac") is None:
                broken.append(name)
                continue
            if ov.get("gate_ok") is False:
                broken.append(name + " (gate FAIL)")
            fracs.append((name, ov["overlap_frac"]))
    if not fracs and not broken:
        return False, ("--min_overlap_frac: no comm_overlap rung with an "
                       "overlap verdict in the log (did the bench run the "
                       "--grad_buckets rungs?)")
    if broken:
        return False, (
            f"--min_overlap_frac FAIL: bucketed rung(s) without a passing "
            f"overlap verdict: {', '.join(broken)}"
        )
    worst_name, worst = min(fracs, key=lambda sf: sf[1])
    ok = worst >= threshold
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--min_overlap_frac {verdict}: min overlap_frac {worst:.3f} "
        f"({worst_name}) over {len(fracs)} bucketed rungs "
        f"(threshold {threshold:.3f})"
    )


def check_min_decode_speedup(records: list[dict],
                             threshold: float) -> tuple[bool, str]:
    """Fused-decode gate (`--min_decode_speedup`, round 21): the bench
    `decode_fused` record's AMORTIZATION speedup (on-device while-loop
    window vs per-step dispatch, same kernel both sides) must be >=
    `threshold`, with token parity intact across all three rungs. The
    kernel_speedup stays informational: on CPU loopback the pallas
    interpret emulation inverts it, but the identical kernel cost cancels
    out of the amortization ratio, so THAT number transfers to the real
    backend. A log without the fused record fails — dropping the rung
    from the bench invocation must not pass the gate vacuously."""
    for r in records:
        df = r.get("decode_fused")
        if not isinstance(df, dict):
            continue
        if "error" in df:
            return False, f"--min_decode_speedup FAIL: rung errored: {df['error']}"
        if not df.get("parity_ok"):
            return False, ("--min_decode_speedup FAIL: fused rungs are not "
                           "token-identical to the unfused engine")
        am = df.get("amortization_speedup")
        if am is None:
            return False, ("--min_decode_speedup FAIL: decode_fused record "
                           "carries no amortization_speedup")
        ok = am >= threshold
        verdict = "OK" if ok else "FAIL"
        ks = df.get("kernel_speedup")
        return ok, (
            f"--min_decode_speedup {verdict}: amortization "
            f"{am:.2f}x (threshold {threshold:.2f}"
            + (f"; kernel {ks:.2f}x informational" if ks is not None else "")
            + ")"
        )
    return False, ("--min_decode_speedup: no decode_fused record in the log "
                   "(did the bench run the fused rungs?)")


def check_min_bubble_gain(records: list[dict],
                          threshold: float) -> tuple[bool, str]:
    """Interleaved-pipeline gate (`--min_bubble_gain`, round 25): the
    bench `pipe_interleave` record's bubble grid must show, at EVERY
    micro-batch count, (a) a strictly decreasing bubble fraction as
    virtual stages grow (1 -> 2 -> 4) and (b) a relative bubble cut
    (1 - bubble[max V]/bubble[V=1]) >= `threshold`. The grid is
    tick-table accounting, deterministic on any backend — the wall
    numbers stay informational (CPU loopback, the --min_overlap_frac
    discipline) — but every TIMED rung must also have run without
    error, so a machine that stopped compiling cannot pass on pure
    math. A log without the record fails: dropping the rung from the
    bench invocation must not pass the gate vacuously."""
    for r in records:
        pi = r.get("pipe_interleave")
        if not isinstance(pi, dict):
            continue
        if "error" in pi:
            return False, f"--min_bubble_gain FAIL: record errored: {pi['error']}"
        broken = [
            f"V={row.get('virtual_stages', '?')}: {row['error']}"
            for row in pi.get("rungs") or [] if "error" in row
        ]
        if broken:
            return False, ("--min_bubble_gain FAIL: errored timed rung(s): "
                           + "; ".join(broken))
        by_m: dict = {}
        for row in pi.get("bubble_table") or []:
            by_m.setdefault(row.get("micro"), []).append(row)
        if not by_m:
            return False, ("--min_bubble_gain FAIL: record carries no "
                           "bubble_table grid")
        worst = None  # (gain, micro, fracs)
        for m, rows_m in sorted(by_m.items()):
            rows_m = sorted(rows_m, key=lambda x: x["virtual_stages"])
            fracs = [row["bubble_frac"] for row in rows_m]
            if any(b >= a for a, b in zip(fracs, fracs[1:])):
                return False, (
                    f"--min_bubble_gain FAIL: bubble fraction not strictly "
                    f"decreasing at M={m}: "
                    + " -> ".join(f"{f:.4f}" for f in fracs))
            gain = 1.0 - fracs[-1] / fracs[0]
            if worst is None or gain < worst[0]:
                worst = (gain, m, fracs)
        ok = worst[0] >= threshold
        verdict = "OK" if ok else "FAIL"
        return ok, (
            f"--min_bubble_gain {verdict}: min relative bubble cut "
            f"{worst[0]:.3f} at M={worst[1]} "
            f"({worst[2][0]:.4f} -> {worst[2][-1]:.4f}) over "
            f"{len(by_m)} micro counts (threshold {threshold:.3f})"
        )
    return False, ("--min_bubble_gain: no pipe_interleave record in the log "
                   "(did the bench run the interleave rungs?)")


# ---- round-22 cross-run comparison (--compare baseline.jsonl) ------------


def _metric_series(records: list[dict]) -> tuple[dict, dict]:
    """Index the LAST `kind="metrics"` epilogue per source: histograms
    keyed by (source, name, labels) and tokens/s-style gauges the same
    way. Later epilogues supersede earlier ones (a train run followed by
    a serve run in one log compares source by source)."""
    hists: dict = {}
    gauges: dict = {}
    for r in _rows(records, "metrics"):
        src = r.get("source", "?")
        for h in r.get("hists") or []:
            key = (src, h["name"], tuple(sorted((h.get("labels") or {}).items())))
            hists[key] = h
        for g in r.get("gauges") or []:
            if g["name"].endswith("tokens_per_sec"):
                key = (src, g["name"],
                       tuple(sorted((g.get("labels") or {}).items())))
                gauges[key] = g["value"]
    return hists, gauges


def compare_runs(current: list[dict], baseline: list[dict],
                 baseline_path: str = "") -> dict:
    """Diff two runs' metric summaries: per-histogram p50/p99 deltas
    (positive = current slower — a regression for latency series) and
    tokens/s deltas (negative = regression). Returns a `kind="compare"`
    record; worst_regression_pct is the single gated number — the worst
    drift across every comparable series, sign-normalized so bigger is
    always worse."""
    cur_h, cur_g = _metric_series(current)
    base_h, base_g = _metric_series(baseline)
    rows, thr_rows = [], []
    worst: tuple | None = None

    def consider(delta_pct: float, name: str):
        nonlocal worst
        if worst is None or delta_pct > worst[0]:
            worst = (delta_pct, name)

    for key in sorted(set(cur_h) & set(base_h), key=str):
        src, name, lk = key
        bh, ch = base_h[key], cur_h[key]
        row = {"source": src, "name": name, "labels": dict(lk)}
        have = False
        for q in ("p50", "p99"):
            b, c = bh.get(q), ch.get(q)
            if b is None or c is None or b <= 0:
                continue
            d = 100.0 * (c - b) / b
            row[f"base_{q}"], row[f"cur_{q}"] = b, c
            row[f"{q}_delta_pct"] = d
            have = True
            # only the `_s` (time-valued) series gate as latency
            # regressions; count-valued histograms are informational
            if name.endswith("_s"):
                consider(d, f"{src}/{name}{_fmt_labels(dict(lk))} {q}")
        if have:
            rows.append(row)
    for key in sorted(set(cur_g) & set(base_g), key=str):
        src, name, lk = key
        b, c = base_g[key], cur_g[key]
        if not b:
            continue
        d = 100.0 * (c - b) / b
        thr_rows.append({"source": src, "name": name, "labels": dict(lk),
                         "base": b, "cur": c, "delta_pct": d})
        consider(-d, f"{src}/{name} tokens/s")
    # summary-record throughput rides along even without metrics
    # epilogues, so --compare works on pre-round-22 baselines too
    for kind in ("serve_summary", "fleet_summary"):
        b = [r for r in _rows(baseline, kind) if r.get("tokens_per_sec")]
        c = [r for r in _rows(current, kind) if r.get("tokens_per_sec")]
        if b and c:
            bv, cv = b[-1]["tokens_per_sec"], c[-1]["tokens_per_sec"]
            d = 100.0 * (cv - bv) / bv
            thr_rows.append({"source": kind, "name": "tokens_per_sec",
                             "labels": {}, "base": bv, "cur": cv,
                             "delta_pct": d})
            consider(-d, f"{kind} tokens/s")
    return {
        "kind": "compare", "baseline": baseline_path,
        "rows": rows, "throughput": thr_rows,
        "worst_regression_pct": None if worst is None else worst[0],
        "worst_name": None if worst is None else worst[1],
    }


def render_compare(cmp: dict) -> str:
    out: list[str] = []
    w = out.append
    w(f"== compare (vs {cmp.get('baseline') or 'baseline'}) ==")
    rows, thr = cmp.get("rows") or [], cmp.get("throughput") or []
    if not rows and not thr:
        w("  no comparable metric series between the runs")
        return "\n".join(out)
    for t in thr:
        w(f"  {t['source'] + '/' + t['name'] + _fmt_labels(t['labels']):<44} "
          f"{human_count(t['base']):>9} -> {human_count(t['cur']):>9} "
          f"tokens/s  {t['delta_pct']:+.1f}%"
          + ("" if t["delta_pct"] >= 0 else "  <- SLOWER"))
    if rows:
        w(f"  {'histogram':<40} {'p50 base->cur':>22} {'Δ%':>7} "
          f"{'p99 base->cur':>22} {'Δ%':>7}")
    for row in rows:
        fmt = (_fmt_seconds if row["name"].endswith("_s")
               else lambda v: "-" if v is None else human_count(v))
        cells = f"  {row['name'] + _fmt_labels(row['labels']):<40}"
        for q in ("p50", "p99"):
            d = row.get(f"{q}_delta_pct")
            if d is None:
                cells += f" {'-':>22} {'-':>7}"
            else:
                cells += (f" {fmt(row[f'base_{q}']) + ' -> ' + fmt(row[f'cur_{q}']):>22}"
                          f" {d:+6.1f}%")
        w(cells)
    wr = cmp.get("worst_regression_pct")
    if wr is not None:
        w(f"  worst regression: {wr:+.1f}% ({cmp.get('worst_name')})")
    return "\n".join(out)


def check_min_slo_compliance(records: list[dict],
                             threshold: float) -> tuple[bool, str]:
    """SLO gate (`--min_slo_compliance`, round 22): the LAST
    `kind="slo"` record's overall_compliance (the worst cumulative
    per-target compliance, sample-weighted) must reach `threshold`.
    Returns (ok, message) — a log without slo rows fails, so the gate
    can't pass vacuously when someone drops `--slo` from the smoke
    invocation; so does a declared target that never saw a sample."""
    slo = _rows(records, "slo")
    if not slo:
        return False, ("--min_slo_compliance: no slo record in the log "
                       "(was the run started with --slo?)")
    last = slo[-1]
    comp = last.get("overall_compliance")
    if comp is None:
        return False, ("--min_slo_compliance FAIL: declared slo targets "
                       "saw no samples")
    targets = [t for t in last.get("targets") or []
               if t.get("cum_compliance") is not None]
    worst = min(targets, key=lambda t: t["cum_compliance"]) if targets else None
    ok = comp >= threshold
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--min_slo_compliance {verdict}: overall compliance {comp:.4f} "
        f"over {len(slo)} slo window(s)"
        + (f", worst target {worst['slo']} at "
           f"{worst['cum_compliance']:.4f} (burn {worst['cum_burn']:.2f}x)"
           if worst is not None else "")
        + f" (threshold {threshold:.4f})"
    )


def check_max_regression_pct(records: list[dict],
                             threshold: float) -> tuple[bool, str]:
    """Cross-run regression gate (`--max_regression_pct`, round 22):
    the `--compare` diff's worst sign-normalized drift (latency p50/p99
    up, or tokens/s down) must stay <= `threshold` percent. Reads the
    `kind="compare"` record main() appends after diffing, so it slots
    into the same declarative gate table as every other checker; without
    `--compare` there is nothing to gate and the check fails loudly."""
    cmps = _rows(records, "compare")
    if not cmps:
        return False, ("--max_regression_pct: no comparison in the log "
                       "(pass --compare baseline.jsonl)")
    cmp = cmps[-1]
    worst = cmp.get("worst_regression_pct")
    if worst is None:
        return False, ("--max_regression_pct FAIL: no comparable metric "
                       "series between the runs")
    ok = worst <= threshold
    verdict = "OK" if ok else "FAIL"
    return ok, (
        f"--max_regression_pct {verdict}: worst drift {worst:+.1f}% "
        f"({cmp.get('worst_name')}) vs baseline "
        f"(threshold {threshold:.1f}%)"
    )


# ---- the gate table (round 22) -------------------------------------------
#
# Every CI gate is one row: (flag dest, metavar, checker, help). main()
# generates the argparse options AND the check-dispatch loop from this
# table, so a new gate is a one-row diff instead of the two copy-pasted
# blocks each of the first five gates accreted. Row order is evaluation
# order (and --help order) — it preserves the pre-table behavior exactly.
# Checkers keep the uniform (records, threshold) -> (ok, message)
# contract; anything extra a checker needs (the --compare diff) is
# materialized into `records` first.

GATES: tuple = (
    ("min_goodput", "FRACTION", check_min_goodput,
     "assert mean train-window goodput >= FRACTION (exit 2 below "
     "it) — a cheap perf regression gate for CI"),
    ("min_serve_tps", "TOKENS_PER_SEC", check_min_serve_tps,
     "assert the serve_summary tokens/s >= this (exit 2 below it) "
     "— the serving-throughput regression gate for CI"),
    ("min_accept_rate", "FRACTION", check_min_accept_rate,
     "assert the serve_summary speculative-decoding acceptance "
     "rate >= FRACTION (exit 2 below it, or when the log has no spec "
     "summary) — the draft-health regression gate for CI"),
    ("min_fleet_tps", "TOKENS_PER_SEC", check_min_fleet_tps,
     "assert the fleet_summary tokens/s >= this with zero "
     "duplicate completions (exit 2 below it, or when the log has no "
     "fleet summary) — the fleet-serving regression gate for CI"),
    ("max_deadline_miss_pct", "PERCENT", check_max_deadline_miss_pct,
     "assert the fleet_summary's deadline_misses <= PERCENT of served "
     "requests (exit 2 above it, or when the log has no fleet summary "
     "or the summary predates deadline accounting) — the round-24 "
     "request-deadline regression gate for CI"),
    ("min_trace_complete", "FRACTION", check_min_trace_complete,
     "assert the fraction of complete request span trees "
     "(kind=\"trace\" rows: closed AND phase walls summing to e2e "
     "within 1e-3 s) >= FRACTION (exit 2 below it, or when the log "
     "has no trace rows) — the tracing-integrity gate for CI"),
    ("min_overlap_frac", "FRACTION", check_min_overlap_frac,
     "assert every bucketed comm_overlap bench rung's "
     "overlap_frac (hlolint-measured hidden-wires fraction) >= "
     "FRACTION (exit 2 below it, or when the log has no overlap "
     "rung) — the overlap-schedule regression gate for CI"),
    ("min_decode_speedup", "RATIO", check_min_decode_speedup,
     "assert the decode_fused bench record's amortization_speedup "
     "(on-device scheduler loop vs per-step dispatch) >= RATIO with "
     "token parity intact (exit 2 below it, or when the log has no "
     "decode_fused record) — the round-21 fused-decode regression gate"),
    ("min_bubble_gain", "FRACTION", check_min_bubble_gain,
     "assert the pipe_interleave bench record's relative bubble cut "
     "(1 - bubble[max V]/bubble[V=1], tick-table accounting) >= FRACTION "
     "at EVERY micro count, strictly decreasing in V, with no errored "
     "timed rung (exit 2 otherwise, or when the log has no "
     "pipe_interleave record) — the round-25 interleaved-pipeline gate"),
    ("min_slo_compliance", "FRACTION", check_min_slo_compliance,
     "assert the run's cumulative SLO compliance (worst target in the "
     "last kind=\"slo\" record) >= FRACTION (exit 2 below it, or when "
     "the log has no slo rows) — the round-22 SLO regression gate for CI"),
    ("max_regression_pct", "PERCENT", check_max_regression_pct,
     "assert the --compare diff's worst drift (latency p50/p99 up or "
     "tokens/s down, sign-normalized) <= PERCENT (exit 2 above it, or "
     "without --compare) — the round-22 cross-run regression gate"),
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log", help="metrics JSONL written via --metrics_log")
    ap.add_argument(
        "--compare", default=None, metavar="BASELINE_JSONL",
        help="diff this run's metric summaries (kind=\"metrics\" "
        "histogram p50/p99, tokens/s headline) against a baseline run's "
        "JSONL; gate the worst drift with --max_regression_pct",
    )
    for dest, metavar, _check, help_text in GATES:
        ap.add_argument(
            f"--{dest}", type=float, default=None, metavar=metavar,
            help=help_text,
        )
    args = ap.parse_args(argv)
    records = load(args.log)
    if not records:
        print(f"{args.log}: no records", file=sys.stderr)
        return 1
    print(summarize(records))
    if args.compare is not None:
        baseline = load(args.compare)
        if not baseline:
            print(f"{args.compare}: no records", file=sys.stderr)
            return 1
        cmp = compare_runs(records, baseline, baseline_path=args.compare)
        print(render_compare(cmp))
        records.append(cmp)  # --max_regression_pct reads it like any row
    rc = 0
    for dest, _metavar, check, _help in GATES:
        threshold = getattr(args, dest)
        if threshold is None:
            continue
        ok, msg = check(records, threshold)
        print(msg, file=sys.stdout if ok else sys.stderr)
        rc = rc if ok else 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
