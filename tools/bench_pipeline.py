#!/usr/bin/env python
"""GPipe vs 1F1B schedule step time (VERDICT r4 #5).

Times one full train step (fwd + bwd + AdamW) for both pipeline schedules
at 2 and 4 stages. Multi-chip TPU hardware is unavailable in this
environment (one real chip), so the comparison runs on the virtual CPU
mesh — schedule-overhead-relative numbers: the 1F1B premium measured here
is an UPPER bound on TPU, where the remat recompute rides the MXU and the
per-tick ppermutes ride ICI instead of host memcpy. docs/DESIGN.md records
the table.

    TPUKIT_CPU_DEVICES=8 python tools/bench_pipeline.py
"""

from __future__ import annotations

import argparse
import json
import os
import time

os.environ.setdefault("TPUKIT_CPU_DEVICES", "8")

import jax
import jax.numpy as jnp
import numpy as np


def bench(schedule: str, stages: int, micro_mult: int = 4, steps: int = 4,
          windows: int = 3, dim: int = 128, layers: int = 8, seq: int = 128):
    from tpukit.mesh import create_mesh
    from tpukit.model import GPTConfig
    from tpukit.pipeline import Pipeline, Pipeline1F1B
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    cls = {"gpipe": Pipeline, "1f1b": Pipeline1F1B}[schedule]
    strat = cls(create_mesh({"stage": stages}), num_microbatches=micro_mult * stages)
    cfg = GPTConfig(
        dim=dim, head_dim=dim // 4, heads=4, num_layers=layers,
        vocab_size=8192, max_position_embeddings=seq,
        compute_dtype=jnp.bfloat16, scan_layers=True,
    )
    opt = make_optimizer(1e-4)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strat)
    step, _, sh = make_step_fns(cfg, opt, strat, jax.eval_shape(lambda: state))
    state = jax.device_put(state, sh)

    batch_rows = micro_mult * stages  # one row per micro-batch
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(batch_rows, seq)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(seq, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)

    for _ in range(2):
        state, loss = step(state, batch, targets)
    float(loss)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, batch, targets)
        float(loss)
        best = min(best, time.perf_counter() - t0)
    return best / steps * 1e3  # ms/step


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=4)
    args = p.parse_args()
    for stages in (2, 4):
        row = {"stages": stages, "microbatches": 4 * stages}
        for schedule in ("gpipe", "1f1b"):
            row[f"{schedule}_ms"] = round(bench(schedule, stages, steps=args.steps), 1)
        row["ratio_1f1b_over_gpipe"] = round(row["1f1b_ms"] / row["gpipe_ms"], 3)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
