"""Long-context component split via full-train-step ablations (the only
reliable timing on the tunneled backend is a chained step loop + float()
sync). Varies num_layers and sequence length at constant token count to
separate head vs trunk vs attention-S^2 time.

    PYTHONPATH=. python tools/ablate_long_context.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def step_time_ms(cfg, batch, seq, fused=True, iters=8):
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    strategy = SingleDevice()
    strategy.fused_head = fused
    optimizer = make_optimizer(1e-4)
    state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
    shapes = jax.eval_shape(lambda: state)
    step, _, sh = make_step_fns(cfg, optimizer, strategy, shapes)
    state = jax.device_put(state, sh)
    ids = jnp.zeros((batch, seq - 1), jnp.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": jnp.broadcast_to(jnp.arange(seq - 1, dtype=jnp.int32), ids.shape),
        "mask": jnp.zeros(ids.shape, bool),
    }
    targets = jnp.zeros(ids.shape, jnp.int32)
    for _ in range(2):
        state, l = step(state, model_batch, targets)
    float(l)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, l = step(state, model_batch, targets)
        float(l)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def main():
    from tpukit.model import GPTConfig

    base = dict(
        dim=256, head_dim=32, heads=8, vocab_size=50257,
        compute_dtype=jnp.bfloat16,
    )
    tok = 16 * 2048  # constant token budget

    rows = []
    for tag, layers, seq, batch, fused in [
        ("L8 S2048 fused", 8, 2048, 16, True),
        ("L8 S2048 unfused", 8, 2048, 16, False),
        ("L4 S2048 fused", 4, 2048, 16, True),
        ("L8 S1024 fused (b32)", 8, 1024, 32, True),
        ("L8 S512 fused (b64)", 8, 512, 64, True),
    ]:
        cfg = GPTConfig(num_layers=layers, max_position_embeddings=seq, **base)
        ms = step_time_ms(cfg, batch, seq, fused)
        tps = batch * (seq - 1) / (ms / 1e3)
        rows.append((tag, ms, tps))
        print(f"{tag:24s}: {ms:7.1f} ms  ({tps:,.0f} tok/s)", flush=True)

    by = {t: m for t, m, _ in rows}
    t8, t4 = by["L8 S2048 fused"], by["L4 S2048 fused"]
    per_layer = (t8 - t4) / 4
    head_plus = t8 - 8 * per_layer  # head + embeddings + optimizer + overhead
    print(f"\nper-layer (trunk+attn @S=2048): {per_layer:.1f} ms")
    print(f"head+emb+opt+overhead:          {head_plus:.1f} ms")
    # attention S^2 share: halving S at constant tokens halves S^2 work
    t1k = by["L8 S1024 fused (b32)"]
    print(f"S2048 -> S1024 delta (≈ half the attn-S^2 cost): {t8 - t1k:.1f} ms")


if __name__ == "__main__":
    main()
