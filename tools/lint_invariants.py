#!/usr/bin/env python
"""lint_invariants — AST-level repo lint for hard-won host-side rules.

Three one-spelling rules, each earned by a real incident, each cheap to
re-break in review because the broken form LOOKS idiomatic:

  atomic-publish     Every tmp+rename file publish goes through
                     `fsio.atomic_write_text` (historically reached as
                     `checkpoint._atomic_write_text`, now a delegate) —
                     one tmp-naming scheme, one rename rule. A
                     hand-rolled `write_text` + rename pair re-opens the
                     torn-read/tmp-collision class the round-9 review
                     closed (recovery._atomic_write_json was delegated
                     for exactly this). Flags all three spellings:
                     `os.replace`/`os.rename`, the bare names when
                     `from os import replace/rename` is in scope, and
                     pathlib's one-argument `.replace(target)` /
                     `.rename(target)` method calls (str.replace takes
                     two arguments, so the single-operand form is the
                     Path publish idiom) — anywhere outside
                     `atomic_write_text` itself.
  retry-io           Checkpoint blob/shard/manifest I/O is wrapped in
                     `retry.retry_io`: the raw helpers (`_read_blob`,
                     `_write_blob`, `_write_shard`, `_write_shard_digest`)
                     may be passed TO retry_io but never called directly —
                     a direct call silently opts that path out of the
                     round-9 transient-fault budget. Round 24 puts the
                     request-ledger helpers (`_write_rec`, `_read_rec`,
                     tpukit/serve/ledger.py) under the same rule: fleet
                     serving's durable records share the transient-fault
                     budget, and the chaos harness's ledger_io_fail
                     injections must always land inside a retry.
  sampling-spelling  No new `fold_in`-based sampling math outside
                     `sampling._sample_next`: flags
                     `jax.random.categorical` calls anywhere else. The
                     round-14 review collapsed three copies of the
                     temperature/top-k/fold_in math into that one
                     function BECAUSE the triplication was the
                     token-parity guarantee's weak point.
  collective-spelling The wire-collective launches (`lax.all_to_all`,
                     `lax.all_gather`, `lax.psum_scatter` — each lowers
                     to an async start/done pair on TPU) live in
                     `tpukit/ops/quant_comm.py`, the bucket scheduler's
                     home (round 18): a raw launch anywhere else
                     bypasses the packed-payload/closed-form-byte/
                     overlap-declaration machinery the audits gate, the
                     way sampling math outside `_sample_next` bypassed
                     the parity guarantee. ring_attention's ulysses
                     head-repartition a2a + pad-mask gather carry
                     reasoned waivers (activation re-layout inside the
                     attention schedule, audited by CP's comm_ops — not
                     a grad/dispatch wire).
  online-softmax-spelling
                     The flash-attention running-max/renormalize update
                     has ONE spelling: `pallas_attention.
                     online_softmax_update`, shared by the training
                     kernels and the paged decode kernel (round 21). A
                     re-derived copy in a new kernel is exactly how the
                     max/exp/correction order drifts and the paged
                     kernel's token-parity bar silently moves — the
                     degenerate-to-plain-softmax exactness argument
                     holds for the owner's spelling, not for "a"
                     spelling. Flags `maximum(..., max(...))` — nested,
                     or through a name assigned from a `.max(...)` call
                     in the same function — inside tpukit/ops/ outside
                     the owner. fused_head_ce's online LOGSUMEXP carries
                     a reasoned waiver (it streams lse + argmax
                     tie-break state, a different contract than the
                     owner's `(m, l, correction, p)`).
  stdlib-only        `tpukit/obs/trace.py` and `tpukit/obs/metrics.py`
                     import NOTHING heavier than the stdlib — no jax,
                     no numpy, no tpukit (round 22; trace.py pioneered
                     the discipline, metrics.py is the second owner).
                     The post-mortem tools (traceview.py, top.py,
                     report.py) load them by file path on machines the
                     logs were merely copied to, and `import tpukit`
                     transitively pulls jax; one convenience import
                     silently breaks every offline consumer. Flags any
                     `import`/`from ... import` of jax/numpy/tpukit (or
                     a submodule) in those two files.

Waivers: a site that is legitimately outside a rule carries an inline
comment on the flagged line —

    os.replace(path, dest)  # lint: allow(atomic-publish): quarantine rename, not a publish

The rule name must match and a reason is REQUIRED (a bare allow is
itself a violation). Zero violations on the current tree; CI runs this
next to tools/hlolint.py.

Usage:
    python tools/lint_invariants.py            # lint the repo
    python tools/lint_invariants.py --root DIR # lint another tree
Exit 1 on any violation.
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from dataclasses import dataclass
from pathlib import Path

# Scanned relative to the root: production host-side code. tests/ are
# excluded — they plant broken spellings on purpose.
SCAN_GLOBS = (
    "tpukit/**/*.py",
    "tools/*.py",
    "main-*.py",
    "bench.py",
    "__graft_entry__.py",
)

RULES = ("atomic-publish", "retry-io", "sampling-spelling",
         "collective-spelling", "online-softmax-spelling", "stdlib-only")

# Module roots banned in the stdlib-only files: anything that would make
# a by-file-path load pull an accelerator stack (tpukit/__init__ imports
# jax via tpukit.model).
_HEAVY_ROOTS = frozenset({"jax", "jaxlib", "numpy", "np", "tpukit",
                          "flax", "optax"})

# The raw checkpoint I/O helpers that must ride retry_io.
_RAW_IO_HELPERS = frozenset({
    "_read_blob", "_write_blob", "_write_shard", "_write_shard_digest",
})

# The raw request-ledger I/O helpers (tpukit/serve/ledger.py, round 24)
# under the same discipline: every call site outside their home file
# wraps them in retry_io so fleet serving survives transient filesystem
# errors — and so the chaos harness's ledger_io_fail injections always
# land inside a retry budget.
_LEDGER_IO_HELPERS = frozenset({"_write_rec", "_read_rec"})

# The wire-collective primitives quant_comm.py owns (collective-spelling):
# the async-start spellings of the grad/dispatch wire. lax.psum/ppermute
# stay unrestricted — scalar reductions and ring hops are not the bucket
# scheduler's payload ops.
_WIRE_COLLECTIVES = frozenset({"all_to_all", "all_gather", "psum_scatter"})

_WAIVER_RE = re.compile(r"#\s*lint:\s*allow\(([\w\-]+)\)\s*:?\s*(.*)")


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _is_max_call(node: ast.AST) -> bool:
    """True for a `<mod>.max(...)` call (jnp.max / np.max / lax.max —
    any attribute spelling of a row-max reduction)."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "max"
    )


def _waiver_on(lines: list[str], lineno: int) -> tuple[str, str] | None:
    """(rule, reason) of a waiver comment on the given 1-based line."""
    if 1 <= lineno <= len(lines):
        m = _WAIVER_RE.search(lines[lineno - 1])
        if m:
            return m.group(1), m.group(2).strip()
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: Path, rel: str, lines: list[str],
                 owner_funcs: frozenset[str],
                 wire_collective_owner: bool = False,
                 ops_kernel_file: bool = False,
                 stdlib_only_file: bool = False):
        self.path = path
        self.rel = rel
        self.lines = lines
        # function names whose bodies this FILE may legitimately contain
        # (the one-spelling owners); a same-named function in any other
        # file must not self-exempt
        self.owner_funcs = owner_funcs
        # True only for tpukit/ops/quant_comm.py: the one file allowed to
        # launch the wire collectives directly (collective-spelling)
        self.wire_collective_owner = wire_collective_owner
        # True for files under tpukit/ops/: the only tree where the
        # online-softmax-spelling rule applies (kernel code)
        self.ops_kernel_file = ops_kernel_file
        # True for tpukit/obs/{trace,metrics}.py: the by-file-path
        # loadable modules that must stay jax/numpy/tpukit-free
        self.stdlib_only_file = stdlib_only_file
        self.out: list[Violation] = []
        self.func_stack: list[str] = []
        # names bound by `from os import replace/rename` in this file
        self.os_fn_aliases: set[str] = set()
        # per-scope names assigned from a `.max(...)` call — the
        # spelled-out form of the online-softmax running max
        # (`row_max = jnp.max(s); maximum(m, row_max)`); [0] is module
        # scope, one frame pushed per function
        self._max_names: list[set[str]] = [set()]

    # -- helpers -----------------------------------------------------------

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        waiver = _waiver_on(self.lines, node.lineno)
        if waiver is not None:
            wrule, reason = waiver
            if wrule == rule:
                if not reason:
                    self.out.append(Violation(
                        rule, self.rel, node.lineno,
                        f"waiver without a reason — `# lint: "
                        f"allow({rule}): <why>` must say why",
                    ))
                return
        self.out.append(Violation(rule, self.rel, node.lineno, message))

    def _in_function(self, name: str) -> bool:
        return name in self.owner_funcs and name in self.func_stack

    # -- traversal ---------------------------------------------------------

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self._max_names.append(set())
        self.generic_visit(node)
        self._max_names.pop()
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign):
        if _is_max_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._max_names[-1].add(t.id)
        self.generic_visit(node)

    def _check_stdlib_only(self, node: ast.AST, module: str) -> None:
        if not self.stdlib_only_file:
            return
        root = module.split(".")[0]
        if root in _HEAVY_ROOTS:
            self._flag(
                "stdlib-only", node,
                f"import of {module} in a stdlib-only module — "
                f"traceview.py/top.py/report.py load this file by path on "
                f"machines without jax; keep it importable bare (round-22 "
                f"discipline, tests assert it too)",
            )

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self._check_stdlib_only(node, a.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "os":
            for a in node.names:
                if a.name in ("replace", "rename"):
                    self.os_fn_aliases.add(a.asname or a.name)
        if node.module and node.level == 0:
            self._check_stdlib_only(node, node.module)
        self.generic_visit(node)

    def _is_rename_call(self, node: ast.Call) -> str | None:
        """Spelling of a file-rename call, or None: `os.replace(...)`,
        a bare `replace(...)` bound by `from os import replace`, or
        pathlib's one-positional-argument `p.replace(target)` (str.replace
        needs two operands, so the single-operand method form is the Path
        publish idiom)."""
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("replace", "rename"):
            if isinstance(fn.value, ast.Name) and fn.value.id == "os":
                return f"os.{fn.attr}"
            if len(node.args) == 1 and not node.keywords:
                return f"Path.{fn.attr}"
        if (
            isinstance(fn, ast.Name)
            and fn.id in self.os_fn_aliases
        ):
            return f"os.{fn.id} (imported bare)"
        return None

    def visit_Call(self, node: ast.Call):
        fn = node.func
        # atomic-publish: any rename spelling outside atomic_write_text
        rename = self._is_rename_call(node)
        if rename is not None and not (
            self._in_function("atomic_write_text")
            or self._in_function("atomic_write_bytes")
        ):
            self._flag(
                "atomic-publish", node,
                f"{rename}() outside fsio.atomic_write_text — file "
                f"publishes go through the one atomic-write spelling (or "
                f"carry a waiver naming why this is a rename, not a "
                f"publish)",
            )
        # retry-io: direct call of a raw checkpoint/ledger I/O helper
        if (
            isinstance(fn, ast.Name)
            and fn.id in (_RAW_IO_HELPERS | _LEDGER_IO_HELPERS)
            and not self._in_function(fn.id)
        ):
            what = ("checkpoint blob/manifest"
                    if fn.id in _RAW_IO_HELPERS else "request-ledger")
            self._flag(
                "retry-io", node,
                f"direct call of {fn.id}() — {what} I/O "
                f"must be wrapped: retry_io({fn.id}, ...) keeps it inside "
                f"the transient-fault budget",
            )
        # sampling-spelling: jax.random.categorical outside _sample_next
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "categorical"
            and not self._in_function("_sample_next")
        ):
            self._flag(
                "sampling-spelling", node,
                "categorical() sampling outside sampling._sample_next — "
                "every decode path shares ONE fold_in/temperature/top-k "
                "spelling (the round-14 parity guarantee); route through "
                "_sample_next",
            )
        # online-softmax-spelling: a hand-rolled flash running-max update
        # (`maximum(m, max(s))`, nested or via an assigned row-max name)
        # in kernel code outside online_softmax_update
        if (
            self.ops_kernel_file
            and isinstance(fn, ast.Attribute)
            and fn.attr == "maximum"
            and not self._in_function("online_softmax_update")
            and any(
                _is_max_call(a)
                or (isinstance(a, ast.Name) and a.id in self._max_names[-1])
                for a in node.args
            )
        ):
            self._flag(
                "online-softmax-spelling", node,
                "hand-rolled online-softmax running-max update — the "
                "flash max/renormalize step has ONE spelling, "
                "pallas_attention.online_softmax_update, so the training "
                "and paged-decode kernels cannot drift (round 21); call "
                "the owner (or carry a waiver naming why this "
                "maximum-of-max is not an online softmax)",
            )
        # collective-spelling: a raw wire-collective launch (the async
        # start/done ops of the grad/dispatch wire) outside quant_comm.py
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _WIRE_COLLECTIVES
            and not self.wire_collective_owner
        ):
            self._flag(
                "collective-spelling", node,
                f"lax.{fn.attr}() outside tpukit/ops/quant_comm.py — the "
                f"wire collectives live in the bucket scheduler's home so "
                f"every launch carries the packed payload, closed-form "
                f"byte audit and overlap declaration (round 18); route "
                f"through the quant_comm wrappers (or carry a waiver "
                f"naming why this launch is not a grad/dispatch wire)",
            )
        self.generic_visit(node)


def lint_file(path: Path, rel: str | None = None) -> list[Violation]:
    """Lint one file; unparseable files report as a violation rather than
    crashing the sweep."""
    rel = rel or str(path)
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError) as e:
        return [Violation("parse", rel, getattr(e, "lineno", 0) or 0,
                          f"could not parse: {e}")]
    # one-spelling owner functions, honored only in their home file — a
    # same-named function anywhere else must not self-exempt
    norm = rel.replace("\\", "/")
    owners = set()
    if norm.endswith("tpukit/fsio.py"):
        # THE rename sites (text + binary twins)
        owners.update(("atomic_write_text", "atomic_write_bytes"))
    if norm.endswith("tpukit/checkpoint.py"):
        owners.update(_RAW_IO_HELPERS)  # a helper may recurse on itself
    if norm.endswith("tpukit/serve/ledger.py"):
        owners.update(_LEDGER_IO_HELPERS)  # the ledger defines its helpers
    if norm.endswith("tpukit/sampling.py"):
        owners.add("_sample_next")
    if norm.endswith("tpukit/ops/pallas_attention.py"):
        owners.add("online_softmax_update")  # THE flash max/renorm update
    v = _Visitor(
        path, rel, source.splitlines(), frozenset(owners),
        wire_collective_owner=norm.endswith("tpukit/ops/quant_comm.py"),
        ops_kernel_file="tpukit/ops/" in norm,
        stdlib_only_file=(norm.endswith("tpukit/obs/trace.py")
                          or norm.endswith("tpukit/obs/metrics.py")),
    )
    v.visit(tree)
    return v.out


def lint_tree(root: Path) -> list[Violation]:
    out: list[Violation] = []
    seen: set[Path] = set()
    for pattern in SCAN_GLOBS:
        for path in sorted(root.glob(pattern)):
            if path in seen or not path.is_file():
                continue
            seen.add(path)
            out.extend(lint_file(path, str(path.relative_to(root))))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--root", default=str(Path(__file__).resolve().parent.parent),
                    help="tree to lint (default: this repo)")
    ap.add_argument("paths", nargs="*",
                    help="specific files to lint instead of the tree sweep")
    args = ap.parse_args(argv)

    if args.paths:
        violations = []
        for p in args.paths:
            violations.extend(lint_file(Path(p)))
    else:
        violations = lint_tree(Path(args.root))

    for v in violations:
        print(v)
    if violations:
        print(f"lint_invariants: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("lint_invariants: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
