"""Component-level timing of the S=2048 train step on the real TPU: full
step (fused vs unfused head), forward-only, and isolated kernel
microbenches (fused head+CE, flash attention fwd+bwd). Prints ms per step
and the implied per-component MFU so the optimization target is obvious.

    python tools/profile_long_context.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=8, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree.map(
        lambda x: jax.block_until_ready(x) if hasattr(x, "block_until_ready") else x,
        out,
    )
    _sync(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3  # ms


def _sync(out):
    leaves = jax.tree.leaves(out)
    if leaves:
        np.asarray(jax.device_get(leaves[0])).ravel()[:1]


def main():
    from tpukit.model import GPTConfig
    from tpukit.obs import peak_flops_per_chip
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    seq, batch = 2048, 16
    cfg = GPTConfig(
        dim=256, head_dim=32, heads=8, num_layers=8, vocab_size=50257,
        max_position_embeddings=seq, compute_dtype=jnp.bfloat16,
    )
    tokens = batch * (seq - 1)
    peak = peak_flops_per_chip()

    optimizer = make_optimizer(1e-4)
    ids = jnp.zeros((batch, seq - 1), jnp.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": jnp.broadcast_to(
            jnp.arange(seq - 1, dtype=jnp.int32), ids.shape
        ),
        "mask": jnp.zeros(ids.shape, bool),
    }
    targets = jnp.zeros(ids.shape, jnp.int32)

    for fused in (True, False):
        strategy = SingleDevice()
        strategy.fused_head = fused
        state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
        shapes = jax.eval_shape(lambda: state)
        step, _, sh = make_step_fns(cfg, optimizer, strategy, shapes)
        state = jax.device_put(state, sh)

        def run(state):
            s, l = step(state, model_batch, targets)
            return l

        # NOTE: step donates state; re-create per timing loop iteration is
        # wrong, so time via a fori-style python loop carrying state
        def loop(state, n=8):
            for _ in range(n):
                state, l = step(state, model_batch, targets)
            return state, l

        for _ in range(2):
            state, l = step(state, model_batch, targets)
        float(l)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            state, l = loop(state)
            float(l)
            best = min(best, (time.perf_counter() - t0) / 8)
        print(f"train step ({'fused' if fused else 'unfused'} head): "
              f"{best*1e3:7.1f} ms  ({tokens/best:,.0f} tok/s)")

    # --- isolated fused head+CE fwd+bwd at the train shape
    from tpukit.ops.fused_head_ce import fused_head_ce

    n, dim, vpad = tokens, cfg.dim, cfg.padded_vocab_size
    h = jnp.zeros((n, dim), jnp.bfloat16)
    w = jnp.zeros((dim, vpad), jnp.bfloat16)
    tg = jnp.zeros((n,), jnp.int32)

    def head_loss(h, w):
        s, c, _ = fused_head_ce(h, w, tg, cfg.vocab_size)
        return s / jnp.maximum(c, 1.0)

    head_fwd = jax.jit(head_loss)
    head_bwd = jax.jit(jax.grad(head_loss, argnums=(0, 1)))
    ms_f = timeit(head_fwd, h, w)
    ms_b = timeit(head_bwd, h, w)
    flops_f = 2 * n * dim * vpad
    flops_b = 3 * flops_f
    print(f"fused head+CE fwd: {ms_f:7.1f} ms  ({flops_f/ms_f/1e9*1e3/peak*100:5.1f}% MFU)")
    print(f"fused head+CE fwd+bwd: {ms_b:7.1f} ms  ({(flops_f+flops_b)/ms_b/1e9*1e3/peak*100:5.1f}% MFU)")

    # --- isolated flash attention fwd+bwd at the train shape
    from tpukit.ops.pallas_attention import flash_causal_attention

    bh_b, heads, s_len, hd = batch, cfg.heads, seq - 1, cfg.head_dim
    q = jnp.zeros((bh_b, heads, s_len, hd), jnp.bfloat16)

    def attn_loss(q, k, v):
        return jnp.sum(
            flash_causal_attention(q, k, v, scale=hd**-0.5).astype(jnp.float32)
        )

    attn_fwd = jax.jit(lambda q, k, v: flash_causal_attention(q, k, v, scale=hd**-0.5))
    attn_bwd = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
    ms_af = timeit(attn_fwd, q, q, q)
    ms_ab = timeit(attn_bwd, q, q, q)
    # causal: ~half the S^2 work is live
    flops_af = 2 * 2 * bh_b * heads * s_len * s_len * hd / 2
    flops_ab = flops_af * 3.5
    print(f"flash attn fwd  (x8 layers: {8*ms_af:6.1f} ms): {ms_af:6.1f} ms ({flops_af/ms_af/1e9*1e3/peak*100:5.1f}% MFU)")
    print(f"flash attn fwd+bwd (x8: {8*ms_ab:6.1f} ms): {ms_ab:6.1f} ms ({(flops_af+flops_ab)/ms_ab/1e9*1e3/peak*100:5.1f}% MFU)")


if __name__ == "__main__":
    main()
