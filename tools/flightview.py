#!/usr/bin/env python
"""Render a tpukit diagnostics bundle into a human-readable post-mortem.

The hang watchdog / sentinel path (tpukit/obs/watchdog.py) dumps one JSON
bundle per event into `--debug_dir`: every Python thread's stack, the
flight-recorder ring (the loop's last-N records), live HBM gauges, the
heartbeat snapshot across processes, in-flight async-checkpoint/prefetch
state, and the run config. This tool turns that JSON into the page an
operator actually reads at 3am: what fired, what every thread was doing,
what the trainer did in the minutes before, and which process looks wrong.
Serving-era rings (round 20) get their own headline — serve/fleet window
records, fleet events and summaries — before the raw ring tail; the
per-request story lives in the metrics JSONL (tools/traceview.py).

Like tools/report.py it needs NOTHING but the file — no jax import — so it
runs anywhere the bundle was copied to.

Usage:
  python tools/flightview.py debug/bundle-step*-hang-*.json
  python tools/flightview.py debug/            # newest bundle in the dir
  python tools/flightview.py bundle.json --ring 50 --full-stacks
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def resolve_bundle(path: str) -> Path:
    """A file renders itself; a directory renders its newest bundle."""
    p = Path(path)
    if p.is_dir():
        bundles = sorted(p.glob("bundle-*.json"))
        if not bundles:
            raise FileNotFoundError(f"{p}: no bundle-*.json files")
        return bundles[-1]
    return p


def _ts(t) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(float(t)))
    except (TypeError, ValueError):
        return str(t)


def _interesting(stack: list[str]) -> list[str]:
    """Trim a thread stack to the frames an operator reads first: drop the
    interpreter/threading boilerplate prefix, keep everything from the
    first non-runtime frame down (the blocked call is the LAST line)."""
    boring = ("/threading.py", "/concurrent/", "bootstrap")
    start = 0
    for idx, line in enumerate(stack):
        if line.strip().startswith("File") and not any(b in line for b in boring):
            start = idx
            break
    return stack[start:]


def render(bundle: dict, ring_tail: int = 25, full_stacks: bool = False) -> str:
    out: list[str] = []
    w = out.append

    w("== diagnostics bundle ==")
    w(f"  reason: {bundle.get('reason', '?')}   step: {bundle.get('step', '?')}"
      f"   at {_ts(bundle.get('time'))}")
    proc = bundle.get("process") or {}
    if proc and "error" not in proc:
        w(f"  process {proc.get('index', '?')}/{proc.get('count', '?')}   "
          f"device: {proc.get('device_kind', '?')}   jax {proc.get('jax', '?')}")
    if bundle.get("stuck_for_s") is not None:
        w(f"  stuck for: {bundle['stuck_for_s']}s past the deadline")

    inflight = bundle.get("inflight") or {}
    if inflight:
        w("== in-flight state ==")
        for k, v in inflight.items():
            w(f"  {k}: {v}")

    mem = bundle.get("memory")
    if isinstance(mem, dict) and "error" not in mem:
        w("== device memory ==")
        for k, v in mem.items():
            w(f"  {k}: {v:,}" if isinstance(v, int) else f"  {k}: {v}")

    beats = bundle.get("heartbeats")
    if isinstance(beats, dict) and "error" not in beats:
        w("== heartbeats ==")
        now = bundle.get("time")
        for k in sorted(beats, key=lambda x: int(x) if str(x).isdigit() else 0):
            rec = beats[k]
            age = ""
            if now is not None and isinstance(rec, dict) and "time" in rec:
                age = f"   age {now - rec['time']:.1f}s"
            step = rec.get("step", "?") if isinstance(rec, dict) else "?"
            cs = (
                f"   checksum {rec['checksum']} @ step {rec.get('checksum_step', '?')}"
                if isinstance(rec, dict) and rec.get("checksum")
                else ""
            )
            w(f"  p{k}: step {step}{age}{cs}")

    for key in ("stragglers", "mismatches"):
        if bundle.get(key):
            w(f"== {key} ==")
            for item in bundle[key]:
                w(f"  {item}")

    # Round-9 recovery events in the ring deserve a headline before the raw
    # tail: a bundle from a run that already rolled back / retried I/O /
    # fired injected faults reads differently from a first failure.
    recov = [
        r for r in (bundle.get("ring") or [])
        if r.get("kind") in ("rollback", "preempt", "retry", "chaos")
    ]
    if recov:
        w("== recovery events (from the ring) ==")
        counts: dict[str, int] = {}
        for r in recov:
            counts[r["kind"]] = counts.get(r["kind"], 0) + 1
        w("  " + "  ".join(f"{k} x{v}" for k, v in sorted(counts.items())))
        for r in recov:
            if r["kind"] == "rollback":
                w(f"  rollback #{r.get('seq', '?')} [{r.get('reason', '?')}] "
                  f"anomaly step {r.get('anomaly_step', '?')} -> restored "
                  f"step {r.get('target_step', '?')} "
                  f"({r.get('steps_lost', '?')} steps lost)")
            elif r["kind"] == "preempt":
                w(f"  preempt {r.get('signal', '?')} at step {r.get('step', '?')}")

    # Round-20 serving observability: a bundle dumped mid-serve (or
    # post-kill) carries the engine/router ring records — headline them
    # like the recovery events so the serving shape of the run (windows,
    # occupancy, fleet kills/scales) reads before the raw ring tail.
    serve_ring = [
        r for r in (bundle.get("ring") or [])
        if r.get("kind") in ("serve", "serve_summary", "fleet",
                             "fleet_event", "fleet_summary")
    ]
    if serve_ring:
        w("== serving events (from the ring) ==")
        counts = {}
        for r in serve_ring:
            counts[r["kind"]] = counts.get(r["kind"], 0) + 1
        w("  " + "  ".join(f"{k} x{v}" for k, v in sorted(counts.items())))
        wins = [r for r in serve_ring if r["kind"] in ("serve", "fleet")]
        if wins:
            last = wins[-1]
            occ = last.get("occupancy")
            w(f"  last {last['kind']} window #{last.get('window', '?')}: "
              f"{last.get('new_tokens', '?')} tokens"
              + (f", occupancy {100 * occ:.0f}%" if occ is not None else "")
              + (f", {last['replicas']} replica(s)"
                 if last.get("replicas") is not None else ""))
        for r in serve_ring:
            if r["kind"] == "fleet_event":
                extra = " ".join(f"{k}={v}" for k, v in r.items()
                                 if k not in ("kind", "t", "event"))
                w(f"  fleet_event {r.get('event', '?')}"
                  + (f" ({extra})" if extra else ""))
            elif r["kind"] == "serve_summary":
                w(f"  serve_summary: {r.get('requests', '?')} requests, "
                  f"{r.get('tokens_per_sec', 0):.1f} tokens/s, occupancy "
                  f"{100 * (r.get('mean_occupancy') or 0):.0f}%")
            elif r["kind"] == "fleet_summary":
                w(f"  fleet_summary: {r.get('requests', '?')} requests, "
                  f"{r.get('tokens_per_sec', 0):.1f} tokens/s, "
                  f"{r.get('requeued', 0)} requeued / {r.get('kills', 0)} "
                  f"kill(s)")

    stacks = bundle.get("stacks") or {}
    if stacks:
        w(f"== thread stacks ({len(stacks)}) ==")
        # MainThread first: that is the (possibly hung) training thread
        order = sorted(stacks, key=lambda n: (not n.startswith("MainThread"), n))
        for name in order:
            frames = stacks[name]
            if not full_stacks:
                frames = _interesting(frames)
            w(f"  -- {name} --")
            for line in frames:
                for sub in line.splitlines():
                    w(f"    {sub}")

    ring = bundle.get("ring")
    if ring is not None:
        total = bundle.get("ring_total_recorded", len(ring))
        tail = ring[-ring_tail:]
        first = total - len(ring) + (len(ring) - len(tail)) + 1
        w(f"== flight recorder (last {len(tail)} of {total} records) ==")
        for idx, rec in enumerate(tail):
            fields = " ".join(
                f"{k}={v}" for k, v in rec.items() if k not in ("t", "kind")
            )
            w(f"  [{first + idx:>6}] {_ts(rec.get('t'))}  "
              f"{rec.get('kind', '?'):<18} {fields}")

    cfg = bundle.get("config")
    if cfg:
        w("== run config (non-default flags are the interesting ones) ==")
        w("  " + "  ".join(f"{k}={v}" for k, v in sorted(cfg.items())))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "bundle", help="bundle JSON (or a --debug_dir: newest bundle wins)"
    )
    ap.add_argument(
        "--ring", type=int, default=25, metavar="N",
        help="how many trailing flight-recorder records to show (default 25)",
    )
    ap.add_argument(
        "--full-stacks", action="store_true",
        help="show every stack frame incl. interpreter/threading boilerplate",
    )
    args = ap.parse_args(argv)
    try:
        path = resolve_bundle(args.bundle)
    except FileNotFoundError as exc:
        print(exc, file=sys.stderr)
        return 1
    try:
        bundle = json.loads(path.read_text())
    except OSError as exc:
        print(exc, file=sys.stderr)
        return 1
    except ValueError as exc:
        print(f"{path}: not a JSON bundle ({exc})", file=sys.stderr)
        return 1
    try:
        print(f"[{path}]")
        print(render(bundle, ring_tail=args.ring, full_stacks=args.full_stacks))
    except BrokenPipeError:  # `flightview ... | head` closed the pipe
        sys.stderr.close()  # suppress the interpreter's EPIPE complaint
    return 0


if __name__ == "__main__":
    sys.exit(main())
