#!/usr/bin/env python
"""Live terminal dashboard over a run's `--metrics_dir` (round 22).

Tails the atomic per-process snapshot files the metrics plane publishes
every window (tpukit/obs/metrics.py), merges them locally by bucket-wise
sum — the same merge process 0 performs, so what this tool shows IS the
fleet view — and redraws a compact panel: tokens/s, occupancy, queue
depth, page-pool pressure, per-series p50/p99 latencies with a bucket
sparkline of each distribution's shape, recovery counters, and (with
`--log run.jsonl`) the declared SLO targets' cumulative compliance and
burn plus a tokens/s-over-windows sparkline.

Like report.py and traceview.py this tool imports NO jax (or numpy):
`tpukit/obs/metrics.py` is deliberately stdlib-only and is loaded by
file path below, bypassing `tpukit/__init__` (which imports jax). It
therefore runs on a machine the snapshot dir was merely rsync'd to.

Usage:
    python tools/top.py /path/to/metrics_dir            # live, 2s redraw
    python tools/top.py metrics_dir --log run.jsonl     # + SLO panel
    python tools/top.py metrics_dir --once              # one frame (CI)
Exit codes: 0 rendered, 1 no snapshots in the directory.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import time
from pathlib import Path

SPARK = "▁▂▃▄▅▆▇█"


def _load_metrics_lib():
    """Import tpukit/obs/metrics.py by path — `import tpukit` would pull
    in jax, which this dashboard must not require."""
    path = Path(__file__).resolve().parent.parent / "tpukit" / "obs" / "metrics.py"
    spec = importlib.util.spec_from_file_location("tpukit_obs_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_log(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn final line from a live writer
    return records


def sparkline(values: list[float], width: int = 24) -> str:
    """Map a series onto SPARK glyphs, resampled to `width` cells."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        # mean-pool into width cells so old history compresses, not drops
        step = len(vals) / width
        vals = [
            sum(chunk) / len(chunk)
            for i in range(width)
            if (chunk := vals[int(i * step):max(int((i + 1) * step), int(i * step) + 1)])
        ]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(SPARK[int((v - lo) / span * (len(SPARK) - 1))] for v in vals)


def hist_sparkline(h, width: int = 24) -> str:
    """The distribution's shape: bucket counts over the occupied bucket
    range (log-spaced x axis for free — the edges are log-spaced;
    h.buckets is the sparse {index: count} map)."""
    if not h.buckets:
        return ""
    lo, hi = min(h.buckets), max(h.buckets) + 1
    return sparkline([float(h.buckets.get(i, 0)) for i in range(lo, hi)], width)


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def _fmt_count(n) -> str:
    n = float(n)
    for scale, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= scale:
            return f"{n / scale:.2f}{suffix}"
    return f"{n:.0f}" if n == int(n) else f"{n:.2f}"


def render(merged, meta: dict, metrics_lib, records: list[dict]) -> str:
    out: list[str] = []
    w = out.append
    snap = merged.summary()
    gauges = {(g["name"], tuple(sorted(g["labels"].items()))): g["value"]
              for g in snap["gauges"]}

    stale = f", {meta['stale']} stale" if meta.get("stale") else ""
    torn = f", {meta['skipped']} torn" if meta.get("skipped") else ""
    w(f"tpukit top — {meta.get('files', 0)} snapshot(s) merged{stale}{torn}"
      f"   {time.strftime('%H:%M:%S')}")

    # headline gauges: last-writer per label set; show the per-label rows
    # when a fleet's replicas each set one
    for name, label in (("fleet_tokens_per_sec", "fleet tokens/s"),
                        ("serve_tokens_per_sec", "serve tokens/s"),
                        ("train_tokens_per_sec", "train tokens/s")):
        rows = [(dict(lk), v) for (n, lk), v in gauges.items() if n == name]
        if rows:
            cells = "  ".join(
                (f"r{lab['replica']}=" if "replica" in lab else "")
                + _fmt_count(v)
                for lab, v in sorted(rows, key=lambda r: str(r[0])))
            w(f"  {label:<16} {cells}")
    occ_rows = []
    for name, label in (("fleet_occupancy", "fleet occ"),
                        ("serve_occupancy", "occupancy"),
                        ("serve_page_occupancy", "page occ"),
                        ("fleet_queue_depth", "queue"),
                        ("serve_queue_depth", "queue"),
                        ("fleet_replicas", "replicas")):
        rows = [(dict(lk), v) for (n, lk), v in gauges.items() if n == name]
        if not rows:
            continue
        cells = "  ".join(
            (f"r{lab['replica']}=" if "replica" in lab else "")
            + (f"{100 * v:.0f}%" if "occ" in name else _fmt_count(v))
            for lab, v in sorted(rows, key=lambda r: str(r[0])))
        occ_rows.append(f"{label} {cells}")
    if occ_rows:
        w("  " + "   ".join(occ_rows))

    counters: dict[str, float] = {}
    for c in snap["counters"]:
        counters[c["name"]] = counters.get(c["name"], 0.0) + c["value"]
    if counters:
        w("  " + "  ".join(f"{n}={_fmt_count(v)}"
                           for n, v in sorted(counters.items())))

    names = merged.hist_names()
    if names:
        w(f"  {'histogram':<26} {'count':>7} {'p50':>9} {'p99':>9}  shape")
        for name in names:
            h = merged.aggregate_hist(name)
            if h.count == 0:
                continue
            fmt = _fmt_s if name.endswith("_s") else _fmt_count
            w(f"  {name:<26} {_fmt_count(h.count):>7} "
              f"{fmt(h.quantile(0.5)):>9} {fmt(h.quantile(0.99)):>9}  "
              f"{hist_sparkline(h)}")

    # --log panels: SLO compliance/burn from the last kind="slo" row and
    # a tokens/s-over-windows sparkline from the window records
    if records:
        slo_rows = [r for r in records if r.get("kind") == "slo"]
        if slo_rows:
            last = slo_rows[-1]
            oc = last.get("overall_compliance")
            w(f"  slo ({len(slo_rows)} windows): overall "
              + (f"{100 * oc:.2f}%" if oc is not None else "no samples"))
            for t in last.get("targets") or []:
                cc, cb = t.get("cum_compliance"), t.get("cum_burn")
                if cc is None:
                    w(f"    {t.get('slo', '?'):<20} no samples")
                    continue
                w(f"    {t.get('slo', '?'):<20} {100 * cc:.2f}% "
                  f"burn {cb:.2f}x"
                  + ("" if cc >= (t.get("q") or 0) else "  <- VIOLATED"))
        for kind in ("fleet", "serve", "train"):
            tps = [r.get("tokens_per_sec") for r in records
                   if r.get("kind") == kind and r.get("tokens_per_sec")]
            if tps:
                w(f"  {kind} tokens/s over windows: {sparkline(tps)} "
                  f"(last {_fmt_count(tps[-1])})")
                break
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="--metrics_dir of a live or finished run")
    ap.add_argument("--log", default="",
                    help="the run's --metrics_log JSONL: adds the SLO "
                         "panel and the tokens/s-over-windows sparkline")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="redraw period in seconds (live mode)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit — the CI mode")
    args = ap.parse_args(argv)

    metrics_lib = _load_metrics_lib()
    while True:
        merged, meta = metrics_lib.merge_snapshot_dir(args.dir)
        if not meta.get("files"):
            print(f"{args.dir}: no metric snapshots (is the run started "
                  f"with --metrics_dir, and not --no_metrics?)",
                  file=sys.stderr)
            return 1
        records = load_log(args.log) if args.log else []
        frame = render(merged, meta, metrics_lib, records)
        if args.once:
            print(frame)
            return 0
        # full clear + home, then the frame: flicker-free enough at 2s
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
