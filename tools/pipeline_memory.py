"""Measure pipeline live-activation memory vs micro-batch count (VERDICT
r3 #8): XLA's compiled memory analysis of the REAL pipeline train step on a
virtual stage mesh, with and without --remat.

The GPipe schedule scans num_micro + num_stages - 1 steps and autodiff
saves residuals for every step, so temp memory grows linearly with the
micro-batch count; per-layer remat trades that slope for recompute. This
tool prints the measured slope so ladder configs (BASELINE.json GPT-large/
XL) can size micro-batch counts; docs/DESIGN.md records the numbers.

    TPUKIT_CPU_DEVICES=8 python tools/pipeline_memory.py [--ladder]
"""

from __future__ import annotations

import argparse
import os

os.environ.setdefault("TPUKIT_CPU_DEVICES", "8")

import jax
import jax.numpy as jnp
import numpy as np


def temp_bytes(cfg, strat, micro_rows: int):
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    opt = make_optimizer(1e-4)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy=strat)
    shapes = jax.eval_shape(lambda: state)
    step, _, sh = make_step_fns(cfg, opt, strat, shapes)
    state = jax.device_put(state, sh)
    seq = cfg.max_position_embeddings - 1
    ids = np.zeros((micro_rows, seq), np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.zeros_like(ids),
        "mask": np.zeros(ids.shape, bool),
    }
    ma = step.lower(state, batch, np.zeros_like(ids)).compile().memory_analysis()
    return ma.temp_size_in_bytes


def sweep(cfg, stages: int, micros, rows_per_micro: int = 1):
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline, Pipeline1F1B

    mesh = create_mesh({"stage": stages})
    rows = [
        ("plain", Pipeline, False),
        ("remat", Pipeline, True),
        ("1f1b", Pipeline1F1B, False),
    ]
    for tag, cls, remat in rows:
        c = cfg.replace(remat_layers=remat)
        sizes = []
        for m in micros:
            strat = cls(mesh, num_microbatches=m)
            sizes.append(temp_bytes(c, strat, m * rows_per_micro))
        slope = (sizes[-1] - sizes[0]) / (micros[-1] - micros[0])
        print(
            f"  {tag:>5}: "
            + ", ".join(f"M={m}: {s/2**20:7.2f} MiB" for m, s in zip(micros, sizes))
            + f"   slope {slope/2**20:.3f} MiB/micro"
        )


def main():
    from tpukit.model import GPTConfig

    ap = argparse.ArgumentParser()
    ap.add_argument("--ladder", action="store_true", help="include GPT-large/XL shapes")
    args = ap.parse_args()

    base = dict(vocab_size=512, compute_dtype=jnp.bfloat16, scan_layers=True)

    print("GPT-tiny dim64 L8 seq64, 8 stages:")
    sweep(
        GPTConfig(dim=64, head_dim=16, heads=4, num_layers=8,
                  max_position_embeddings=64, **base),
        stages=8, micros=(8, 16, 32),
    )

    if args.ladder:
        # BASELINE.json configs 4-5 shapes (GPT-large/XL class); small vocab
        # keeps CPU compile time sane — embeddings do not affect the per-
        # micro activation slope, which is what this tool measures.
        print("GPT-large-class dim1280 L16(of 36) seq512, 4 stages:")
        sweep(
            GPTConfig(dim=1280, head_dim=64, heads=20, num_layers=16,
                      max_position_embeddings=512, **base),
            stages=4, micros=(4, 8, 16),
        )
        print("GPT-XL-class dim1600 L16(of 48) seq512, 8 stages:")
        sweep(
            GPTConfig(dim=1600, head_dim=64, heads=25, num_layers=16,
                      max_position_embeddings=512, **base),
            stages=8, micros=(8, 16),
        )


if __name__ == "__main__":
    main()
