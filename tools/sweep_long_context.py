"""On-device sweep of the long-context (S=2048) train-step throughput over
the Pallas tile knobs and batch size. Run on the real TPU:

    python tools/sweep_long_context.py [--quick]

Prints one line per configuration (tok/s/chip, best-of-3 windows) and a
final ranking. Knobs swept via env are read at import time by the kernels,
so each config runs in a subprocess.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

CHILD = r"""
import time, sys, json
import jax, jax.numpy as jnp
from tpukit.model import GPTConfig
from tpukit.train import create_train_state, make_optimizer, make_step_fns
import tpukit.shardings as sh

batch = int(sys.argv[1])
seq = 2048
cfg = GPTConfig(
    dim=256, head_dim=32, heads=8, num_layers=8, vocab_size=50257,
    max_position_embeddings=seq, compute_dtype=jnp.bfloat16,
)
strategy = sh.SingleDevice()
optimizer = make_optimizer(1e-4)
state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
shapes = jax.eval_shape(lambda: state)
train_step, _, state_sharding = make_step_fns(cfg, optimizer, strategy, shapes)
state = jax.device_put(state, state_sharding)
ids = jnp.zeros((batch, seq - 1), jnp.int32)
model_batch = {
    "input_ids": ids,
    "position_ids": jnp.broadcast_to(jnp.arange(seq - 1, dtype=jnp.int32), ids.shape),
    "mask": jnp.zeros(ids.shape, bool),
}
targets = jnp.zeros(ids.shape, jnp.int32)
for _ in range(3):
    state, loss = train_step(state, model_batch, targets)
float(loss)
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    for _ in range(6):
        state, loss = train_step(state, model_batch, targets)
    float(loss)
    best = min(best, time.perf_counter() - t0)
print(json.dumps({"tps": 6 * batch * seq / best}))
"""


def run(env_extra: dict, batch: int) -> float | None:
    env = dict(os.environ, **{k: str(v) for k, v in env_extra.items()})
    try:
        out = subprocess.run(
            [sys.executable, "-c", CHILD, str(batch)],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        line = out.stdout.strip().splitlines()[-1]
        return json.loads(line)["tps"]
    except Exception as exc:  # OOM / compile failure: report and move on
        tail = (out.stderr if "out" in dir() else "")[-300:]
        print(f"  failed: {exc!r} {tail}", file=sys.stderr)
        return None


def main():
    ints = lambda s: tuple(int(x) for x in s.split(","))
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="fewer configs")
    ap.add_argument("--flash", type=ints, default=None)
    ap.add_argument("--tblk", type=ints, default=None)
    ap.add_argument("--vblk", type=ints, default=None)
    ap.add_argument("--batch", type=ints, default=None)
    args = ap.parse_args()

    configs = []
    for fb in args.flash or ((1024, 2048) if args.quick else (512, 1024, 2048)):
        for tb in args.tblk or ((1024,) if args.quick else (512, 1024, 2048)):
            for vb in args.vblk or ((2048,) if args.quick else (1024, 2048, 4096)):
                for batch in args.batch or ((16,) if args.quick else (16, 24, 32)):
                    configs.append({
                        "TPUKIT_FLASH_BLOCK": fb,
                        "TPUKIT_CE_T_BLOCK": tb,
                        "TPUKIT_CE_V_BLOCK": vb,
                        "_batch": batch,
                    })

    results = []
    for c in configs:
        batch = c.pop("_batch")
        tps = run(c, batch)
        tag = f"flash={c['TPUKIT_FLASH_BLOCK']} t={c['TPUKIT_CE_T_BLOCK']} v={c['TPUKIT_CE_V_BLOCK']} b={batch}"
        print(f"{tag}: {tps and round(tps):,}".replace(",", "_") if tps else f"{tag}: FAIL", flush=True)
        if tps:
            results.append((tps, tag))

    results.sort(reverse=True)
    print("\ntop 5:")
    for tps, tag in results[:5]:
        print(f"  {round(tps):>9,} tok/s/chip  {tag}")


if __name__ == "__main__":
    main()
