"""Train-and-checkpoint a tiny induction target for the spec serve-smoke.

Speculation is an optimization exactly when the target's next tokens are
predictable; a random-init target accepts ~nothing and the
`--min_accept_rate` CI gate would be unpassable (or vacuous). This tool
puts a checkpoint in the regime structured/templated serving traffic
puts a real model in: it trains the SAME tiled-phrase rows the
`repetitive` stream profile generates (`bench._induction_train` — one
spelling shared with the `spec_decode` bench record) and saves a
standard tpukit checkpoint that `main-serve.py --checkpoint` restores
params-only, so the CI lane exercises the real cold-start path:

    python tools/train_induction.py --dim 64 --num_layers 2 \
        --steps 400 --out ckpt_induction
    python main-serve.py --dim 64 --num_layers 2 \
        --checkpoint "$(ls -d ckpt_induction/checkpoint-step*)" \
        --draft ngram --stream_profile repetitive ...

Shape flags MUST match the serving invocation's (the params-only reader
verifies structure); `--row_len` must cover the serving position range
(largest bucket + max_new_tokens + spec_k — the bench docstring's
lesson: positions beyond the trained range decode noise and acceptance
collapses).
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--head_dim", type=int, default=16)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--num_layers", type=int, default=2)
    ap.add_argument("--sequence_length", type=int, default=128,
                    help="position-table size; must match the serving "
                    "--sequence_length")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--row_len", type=int, default=40,
                    help="training row length — cover largest bucket + "
                    "max_new_tokens + spec_k of the serving run")
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", type=str, default="ckpt_induction")
    flags = ap.parse_args(argv)

    import jax.numpy as jnp

    from bench import _induction_train
    from tpukit import checkpoint as ckpt_lib
    from tpukit.data import get_tokenizer
    from tpukit.model import GPTConfig

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = GPTConfig(
        dim=flags.dim, head_dim=flags.head_dim, heads=flags.heads,
        num_layers=flags.num_layers, vocab_size=tokenizer.vocab_size,
        max_position_embeddings=flags.sequence_length,
        compute_dtype=jnp.float32,
    )
    state, loss = _induction_train(
        cfg, tokenizer, flags.steps, flags.row_len, lr=flags.lr,
        seed=flags.seed,
    )
    path = ckpt_lib.save_auto(state, flags.out)
    print(f"induction target: loss {loss:.4f} after {flags.steps} steps "
          f"-> {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
