#!/usr/bin/env python
"""Render a serving run's request traces (round 20) from the metrics
JSONL: per-request span-tree post-mortem in the terminal, plus a
Chrome-trace / Perfetto export (`--out trace.json`, open in
chrome://tracing or ui.perfetto.dev).

Reads the `kind="trace_event"` rows the engine/fleet tracer flushes
(tpukit/obs/trace.py module docstring has the event vocabulary) and
re-derives the span trees locally — the terminal table therefore works
on a log copied off the machine, and disagreements between it and the
run's own `kind="trace"` rows would indicate a torn flush.

Like report.py and flightview.py this tool imports NO jax (or numpy):
`tpukit/obs/trace.py` is deliberately stdlib-only and is loaded by file
path below, bypassing `tpukit/__init__` (which imports jax).

Usage:
    python tools/traceview.py run.jsonl                  # terminal table
    python tools/traceview.py run.jsonl --out trace.json # Perfetto JSON
    python tools/traceview.py run.jsonl --rid 17         # one request
Exit codes: 0 rendered, 1 no trace events in the file.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path


def _load_trace_lib():
    """Import tpukit/obs/trace.py by path — `import tpukit` would pull in
    jax, which this post-mortem tool must not require."""
    path = Path(__file__).resolve().parent.parent / "tpukit" / "obs" / "trace.py"
    spec = importlib.util.spec_from_file_location("tpukit_obs_trace", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn final line from a killed run
    return records


def _ms(s) -> str:
    return f"{1e3 * s:8.1f}" if s is not None else "       -"


def render(trees: list[dict], trace_lib) -> str:
    out: list[str] = []
    w = out.append
    w("== request traces ==")
    w(f"{'trace':>6} {'rid':>5} {'att':>3} {'quanta':>6} "
      f"{'queue':>8} {'prefill':>8} {'handoff':>8} {'decode':>8} "
      f"{'sync':>8} {'other':>8} {'e2e_ms':>8}  ok reason     replicas")
    for t in trees:
        ph = t["phases"]
        w(f"{t['trace']:>6} {t['rid']:>5} {t['attempts']:>3} "
          f"{t['quanta']:>6} {_ms(ph['queue_wait'])} {_ms(ph['prefill'])} "
          f"{_ms(ph['handoff'])} {_ms(ph['decode'])} "
          f"{_ms(ph['sync_stall'])} {_ms(ph['other'])} {_ms(t['e2e_s'])}  "
          f"{'ok' if t['complete'] else ('OPEN' if not t['closed'] else 'SUM!')}"
          f" {str(t['reason'] or '-'):<10} {','.join(t['replicas']) or '-'}")
    comp = trace_lib.completeness(trees)
    closed = sum(1 for t in trees if t["closed"])
    w(f"{len(trees)} trace(s): {closed} closed, "
      f"{100 * comp:.0f}% complete" if comp is not None else "no traces")
    p50, p99 = trace_lib.phase_stats(trees)
    if trees:
        w("phase walls (ms)   " + "  ".join(
            f"{k} p50={1e3 * p50[k]:.1f}/p99={1e3 * p99[k]:.1f}"
            for k in trace_lib.PHASES if p50.get(k) is not None))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="metrics JSONL from a --metrics_log run")
    ap.add_argument("--out", default="",
                    help="write Chrome-trace JSON here (chrome://tracing "
                         "or ui.perfetto.dev)")
    ap.add_argument("--rid", type=int, default=None,
                    help="only the request with this rid")
    args = ap.parse_args(argv)

    trace_lib = _load_trace_lib()
    records = load(args.log)
    events = [
        {k: v for k, v in r.items() if k not in ("kind", "time")}
        for r in records if r.get("kind") == "trace_event"
    ]
    if not events:
        print(f"{args.log}: no trace_event rows (run with tracing on — "
              f"it is the default; check --no_trace was not passed)",
              file=sys.stderr)
        return 1

    trees = trace_lib.build_trees(events)
    if args.rid is not None:
        keep = {t["trace"] for t in trees if t["rid"] == args.rid}
        trees = [t for t in trees if t["trace"] in keep]
        events = [e for e in events
                  if e.get("trace") in keep or (
                      e.get("ev") == "quantum"
                      and keep & set(e.get("lanes") or ()))]
        if not trees:
            print(f"rid {args.rid}: no trace", file=sys.stderr)
            return 1

    print(render(trees, trace_lib))
    if args.out:
        chrome = trace_lib.to_chrome(events)
        with open(args.out, "w") as f:
            json.dump(chrome, f)
        print(f"wrote {len(chrome['traceEvents'])} Chrome-trace events -> "
              f"{args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
