"""Capture a jax.profiler trace of the S=2048 train step and print the
top device ops by total duration — the op-level breakdown that drives the
round-4 MFU work (VERDICT r3 #1).

    python tools/trace_step.py [--seq 2048] [--batch 16] [--top 25]
"""

from __future__ import annotations

import argparse
import glob
import gzip
import os
import tempfile
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from tpukit.model import GPTConfig
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()
    seq = args.seq - 1

    cfg = GPTConfig(
        dim=256, head_dim=32, heads=8, num_layers=8, vocab_size=50257,
        max_position_embeddings=args.seq, compute_dtype=jnp.bfloat16,
    )
    optimizer = make_optimizer(1e-4)
    strategy = SingleDevice()
    state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
    shapes = jax.eval_shape(lambda: state)
    step, _, sh = make_step_fns(cfg, optimizer, strategy, shapes)
    state = jax.device_put(state, sh)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(args.batch, seq)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(seq, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)

    for _ in range(3):
        state, loss = step(state, batch, targets)
    float(loss)

    tmp = tempfile.mkdtemp(prefix="tpukit_trace_")
    with jax.profiler.trace(tmp):
        for _ in range(3):
            state, loss = step(state, batch, targets)
        float(loss)

    paths = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise SystemExit(f"no xplane.pb under {tmp}")
    raw = open(paths[0], "rb").read()
    data = jax.profiler.ProfileData.from_serialized_xspace(raw)

    import re

    per_op = defaultdict(float)
    for plane in data.planes:
        if "TPU" not in plane.name and "tpu" not in plane.name.lower():
            continue
        for line in plane.lines:
            for ev in line.events:
                name = ev.name
                # skip wrapper spans and async copy spans (their duration
                # includes the wait, overlapping real compute)
                if name.startswith("jit_") or "copy-start" in name or name in ("0", "1", "2", "3"):
                    continue
                dur = (ev.end_ns - ev.start_ns) / 1e6
                # group: collapse %op.123 suffixes and shape strings
                g = re.split(r"\s*=", name)[0].strip()
                g = re.sub(r"\.\d+$", "", g)
                per_op[g] += dur
    total = sum(per_op.values())
    print(f"op-sum: {total:.1f} ms over 3 steps ({total/3:.1f}/step)")
    for name, ms in sorted(per_op.items(), key=lambda kv: -kv[1])[: args.top]:
        print(f"{ms/3:8.2f} ms/step  {ms/total*100:5.1f}%  {name[:100]}")


if __name__ == "__main__":
    main()
