"""Round-4 long-context ablation: time the REAL S=2048 train step under
config variants to find the MFU lever (VERDICT r3 #1). Every timing is the
full donated train step (fwd+bwd+AdamW) with a float(loss) host sync per
window, best-of-3 windows of 8 steps.

    python tools/ablate_r4.py [--seq 2048] [--variants baseline,remat,...]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def time_step(cfg, batch_size, seq, strategy=None, steps=8, windows=3):
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    strategy = strategy or SingleDevice()
    optimizer = make_optimizer(1e-4)
    state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
    shapes = jax.eval_shape(lambda: state)
    step, _, sh = make_step_fns(cfg, optimizer, strategy, shapes)
    state = jax.device_put(state, sh)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(batch_size, seq)).astype(np.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(seq, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)

    for _ in range(2):
        state, loss = step(state, model_batch, targets)
    float(loss)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, model_batch, targets)
        float(loss)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def main():
    from tpukit.model import GPTConfig
    from tpukit.obs import peak_flops_per_chip, train_flops_per_token

    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--variants", type=str, default="")
    ap.add_argument(
        "--batches", type=str, default="",
        help="comma list: time the base config at these batch sizes instead "
        "of the named variants",
    )
    args = ap.parse_args()
    seq = args.seq

    base = dict(
        dim=256, head_dim=32, heads=8, num_layers=8, vocab_size=50257,
        max_position_embeddings=seq, compute_dtype=jnp.bfloat16,
    )
    variants = [
        ("baseline b16", GPTConfig(**base), 16),
        ("remat b16", GPTConfig(**base, remat_layers=True), 16),
        ("remat b32", GPTConfig(**base, remat_layers=True), 32),
        ("remat b64", GPTConfig(**base, remat_layers=True), 64),
        ("b32", GPTConfig(**base), 32),
        ("hd128 h2 b16", GPTConfig(**{**base, "head_dim": 128, "heads": 2}), 16),
        ("scan b16", GPTConfig(**base, scan_layers=True), 16),
        # head-cost isolation: tiny vocab removes ~all head FLOPs
        ("vocab2k b16", GPTConfig(**{**base, "vocab_size": 2048}), 16),
        # trunk-cost isolation: 1 layer
        ("L1 b16", GPTConfig(**{**base, "num_layers": 1}), 16),
        # (short-sequence comparisons: use --seq 256 --batches ..., which
        # sizes the whole run consistently)
    ]
    if args.batches:
        variants = []
        for b in args.batches.split(","):
            variants.append((f"b{b}", GPTConfig(**base), int(b)))
            variants.append(
                (f"b{b}+flash", GPTConfig(**base, attention_impl="flash"), int(b))
            )
    elif args.variants:
        keep = args.variants.split(",")
        variants = [v for v in variants if any(k in v[0] for k in keep)]
        if not variants:
            raise SystemExit(f"--variants {args.variants!r} matched nothing")
    if args.batches and args.variants:
        raise SystemExit("--batches and --variants are mutually exclusive")

    peak = peak_flops_per_chip()
    for name, cfg, b in variants:
        try:
            dt = time_step(cfg, b, seq - 1)
        except Exception as exc:
            print(f"{name:>16}: FAILED {type(exc).__name__}: {str(exc)[:120]}")
            continue
        toks = b * (seq - 1) / dt
        fpt = train_flops_per_token(cfg, seq - 1)
        mfu = toks * fpt / peak * 100 if peak else float("nan")
        print(f"{name:>16}: {dt*1e3:7.1f} ms  {toks:10,.0f} tok/s  MFU {mfu:5.1f}%")


if __name__ == "__main__":
    main()
