#!/usr/bin/env python
"""Single-chip ladder benchmark: BASELINE configs 2-5 shapes (VERDICT r4 #1).

BASELINE.md names GPT-small/medium/large/XL on v4-8/16/32 pods; pod hardware
is unavailable here, so this measures the per-chip slice of each ladder rung
on the one real chip — GPT-small and GPT-medium in full (they fit), and the
16-layer stage slices of GPT-large/XL that docs/DESIGN.md §2 memory-profiles
(what one pipeline stage of the 4/8-stage recipe would execute). All rungs
use head_dim >= 64, the regime where the MXU contraction is not structurally
capped (DESIGN.md §5: head_dim=32 pins attention matmuls at ~25% of peak).

Usage: python tools/bench_ladder.py [--only NAME] [--batch N] [--steps N]
Prints one JSON line per shape; `python bench.py` imports `run_ladder`
(and the shared `make_batch`/`time_windows` harness) from here and embeds
the same measurements in the driver-facing JSON.
"""

import argparse
import json
import sys
import time

import numpy as np

LADDER = [
    # name, dim, heads, head_dim, layers, seq, batch, remat, scan
    # ("slice" = the 16-layer pipeline-stage slice DESIGN.md §2 profiles;
    #  full GPT-large/XL state does not fit one 16 GB chip at f32+Adam).
    # batch sizes + layer-stack execution swept on the real chip
    # 2026-07-30: the largest fitting batch won every rung (remat keeps
    # temp flat, so bigger batches just amortize the weight traffic
    # better); unrolled blocks beat the scanned stack on medium/large
    # (+~1% MFU) while the xl slice measured better scanned.
    ("gpt-small-dim768", 768, 12, 64, 12, 512, 64, False, False),
    ("gpt-medium-dim1024", 1024, 16, 64, 24, 512, 32, True, False),
    ("gpt-large-slice-dim1280", 1280, 20, 64, 16, 512, 32, True, False),
    ("gpt-xl-slice-dim1600", 1600, 25, 64, 16, 512, 32, True, True),
]


def make_batch(rng, vocab: int, batch: int, seq: int):
    """Synthetic (model_batch, targets) in the trainer's input format —
    the ONE batch builder every bench/probe in bench.py and this tool
    shares."""
    ids = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(seq, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    return model_batch, np.roll(ids, -1, axis=1).astype(np.int32)


def setup_step(cfg, strategy=None, lr=1e-4, seed=0):
    """State init + jitted step fns + sharded placement — the setup block
    every bench/probe repeats (bench.py's headline/long-context/offload/MoE
    probes and every ladder rung). Returns
    `(train_step, state, state_shapes, state_sharding)` ready for
    `time_windows`; warmup/compile happens there."""
    import jax

    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    strategy = strategy if strategy is not None else SingleDevice()
    optimizer = make_optimizer(lr)
    state = create_train_state(
        jax.random.PRNGKey(seed), cfg, optimizer, strategy=strategy
    )
    shapes = jax.eval_shape(lambda: state)
    train_step, _, state_sharding = make_step_fns(cfg, optimizer, strategy, shapes)
    state = jax.device_put(state, state_sharding)
    return train_step, state, shapes, state_sharding


def time_windows(step_fn, state, model_batch, targets, steps: int,
                 windows: int, warmup: int = 3):
    """Warm up (compile), then time `windows` windows of `steps` steps.
    Returns (window_times, state, last_loss). The shared/tunneled chip
    shows double-digit run-to-run variance, so callers report min(times)
    as steady-state and may report the spread as the noise band. float()
    forces a real host sync — block_until_ready is insufficient on
    tunneled PJRT backends."""
    last = None  # warmup=0 support (ADVICE r5 #5): no sync before the loops
    for _ in range(warmup):
        state, loss = step_fn(state, model_batch, targets)
    if warmup:
        last = float(loss)  # one sync: compile + warmup finish before timing
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step_fn(state, model_batch, targets)
        last = float(loss)
        times.append(time.perf_counter() - t0)
    return times, state, last


def bench_shape(name, dim, heads, head_dim, layers, seq, batch, remat, scan,
                steps=8, windows=3):
    import jax.numpy as jnp

    from tpukit.model import GPTConfig
    from tpukit.obs import peak_flops_per_chip, train_flops_per_token

    cfg = GPTConfig(
        dim=dim,
        head_dim=head_dim,
        heads=heads,
        num_layers=layers,
        vocab_size=50257,
        max_position_embeddings=seq,
        compute_dtype=jnp.bfloat16,
        remat_layers=remat,
        scan_layers=scan,
    )
    train_step, state, _, _ = setup_step(cfg)

    model_batch, targets = make_batch(np.random.RandomState(0), cfg.vocab_size, batch, seq)
    times, state, _ = time_windows(
        train_step, state, model_batch, targets, steps, windows, warmup=2
    )
    best = min(times)

    tps = steps * batch * seq / best
    fpt = train_flops_per_token(cfg, seq)
    peak = peak_flops_per_chip()
    mfu = tps * fpt / peak if peak else None
    del state
    return {
        "shape": name,
        "config": f"dim{dim} hd{head_dim}x{heads} L{layers} seq{seq} b{batch}"
                  + (" remat" if remat else "")
                  + (" scanned" if scan else " unrolled"),
        "tokens_per_sec_per_chip": round(tps, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "step_ms": round(best / steps * 1e3, 2),
    }


def run_ladder(steps=8, windows=3, only=None, batch=None):
    """Run every rung, never raising: failures land in the record as
    `error` (VERDICT r4 #8 — silent nulls hide regressions)."""
    out = []
    for name, dim, heads, hd, layers, seq, b, remat, scan in LADDER:
        if only and only not in name:
            continue
        try:
            out.append(bench_shape(name, dim, heads, hd, layers, seq,
                                   batch or b, remat, scan, steps, windows))
        except Exception as exc:
            out.append({"shape": name, "error": repr(exc)})
    return out


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--only", default=None)
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--windows", type=int, default=3)
    args = p.parse_args()
    for rec in run_ladder(args.steps, args.windows, args.only, args.batch):
        print(json.dumps(rec))
        sys.stdout.flush()
