#!/usr/bin/env python
"""hlolint — lint compiled HLO against the repo's comm plans and rules.

Two modes:

  - `--world N` (the CI lane): compile the audited worlds on N virtual
    CPU devices — the dryrun's strategy set (DDP/FSDP f32+int8, the EP
    a2a dispatch f32+int8, the round-18 overlapped DDP/FSDP/EP bucket
    schedules) plus the serving decode steps (TP ring, paged, and the
    round-21 fused-kernel step + on-device scheduler while-loop) — and run
    the full rule engine (tpukit/analysis/rules.py) over each: CommPlan
    diff, involuntary-remat, s32-index-plumbing, wire-upcast,
    donation-dropped, overlap (GATING on the *_overlap worlds — their
    plans declare the bucket schedule). Any "error" finding exits 1.
  - `--hlo FILE [FILE...]`: lint saved HLO text (plain or .gz — the
    golden fixtures under tests/fixtures/hlo/). When a fixture's JSON
    sidecar sits next to the file, its recorded CommPlan and donation
    expectation are restored so the saved text gets the same audit the
    live world does; a bare dump lints rules-only. `--stderr FILE`
    supplies a captured compiler log for the involuntary-remat rule.

Findings are emitted as `kind="hlolint"` JSONL (stdout, or `--out`),
the schema tools/report.py renders in its `== xla ==` section
(DESIGN.md §6/§15).

`--save-hlo DIR` (with `--world`) regenerates the golden fixtures:
gzipped module text + a JSON sidecar recording the world name, comm
dtype, donated-leaf count, measured collectives and the compiler-stderr
remat count — the provenance tests/test_analysis.py checks against.

The world registry here is importable (`from tools.hlolint import
WORLDS, build_world`) so the fixture tests and this CLI share ONE
spelling of each audited world.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from pathlib import Path

# runnable as `python tools/hlolint.py` from anywhere: the repo root (one
# up from tools/) must be importable for tpukit.analysis
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _ensure_env(n_devices: int) -> None:
    """Force a CPU platform with n virtual devices BEFORE jax imports —
    tools run standalone, outside conftest."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        )


# -- the audited worlds -----------------------------------------------------
# One spelling: the CLI lane, the fixture capture and the fixture tests all
# build these through build_world(). Shapes are the multichip dryrun's
# (__graft_entry__.py) for the train worlds and the serve HLO-audit tests'
# for the decode worlds.

WORLDS = (
    "ddp_f32", "ddp_int8", "fsdp_f32", "fsdp_int8",
    "ep_a2a", "ep_int8", "tp_decode", "paged_decode", "spec_verify",
    # round 18 (--grad_buckets): int8 + 4-bucket layer-reversed grad
    # wire; the sidecar plan carries the overlap declaration so the
    # promoted `overlap` rule gates the async/bucket schedule offline
    "ddp_overlap", "fsdp_overlap", "ep_overlap",
    # round 19 (fleet serving): the per-replica decode program compiled
    # on a NON-LEADING device subset (a fleet replica's grid) — the
    # router adds ZERO collectives, so the plan is the standalone decode
    # closed form unchanged (analysis.plan.fleet_decode_comm_plan)
    "fleet_decode",
    # round 21 (--fused_decode): the paged decode step with the fused
    # paged-attention pallas kernel (shard_map, zero body collectives —
    # the plan is paged_decode's closed form UNCHANGED), and the whole
    # on-device scheduler window as one while_loop program (the body's
    # collectives must be attributed ONCE by the body-membership parser,
    # so the per-step plan gates any window size)
    "paged_fused", "sched_loop",
    # round 22 (--virtual_stages): the interleaved 1F1B machine (V=2
    # chunks per device) on a data x stage grid — unrolled static ticks,
    # so the plan's collective-permute count is EXACT; pipe_moe runs the
    # meshless pallas dispatch inside the chunks and its plan pins
    # all-to-all to ZERO (the a2a-free guard)
    "pipe_interleave", "pipe_moe",
)

# the golden-fixture subset checked into tests/fixtures/hlo/ (ISSUE 12);
# ep_int8/ep_overlap compile the most expensive world again for little
# fixture value
FIXTURE_WORLDS = (
    "ddp_f32", "ddp_int8", "fsdp_f32", "fsdp_int8",
    "ep_a2a", "tp_decode", "paged_decode",
    "ddp_overlap", "fsdp_overlap",
    "paged_fused", "sched_loop",
    "pipe_interleave", "pipe_moe",
)


def _dryrun_cfg(comm_dtype="f32", num_experts=0, grad_buckets=0):
    import jax.numpy as jnp

    from tpukit.model import GPTConfig

    return GPTConfig(
        dim=64, head_dim=16, heads=8, num_layers=4, vocab_size=128,
        max_position_embeddings=32, compute_dtype=jnp.float32,
        comm_dtype=comm_dtype, num_experts=num_experts,
        grad_buckets=grad_buckets,
    )


def _train_world(name: str, n_devices: int) -> dict:
    import numpy as np

    import jax

    from tpukit.analysis import train_comm_plan
    from tpukit.mesh import create_mesh
    from tpukit.obs.xla import capture_compiler_stderr
    from tpukit.shardings import FSDP, DataParallel, ExpertParallel
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    devices = jax.devices()[:n_devices]
    inner = next((s for s in (4, 2) if n_devices % s == 0), 1)
    # *_overlap worlds: the round-18 bucket schedule — int8 wire + 4
    # layer-reversed grad buckets (EP: per-layer exchange, audit declared)
    overlap = name.endswith("overlap")
    comm = "f32" if name.endswith("f32") or name == "ep_a2a" else "int8"
    if name.startswith("pipe"):
        # round 22: interleaved 1F1B — V=2 virtual chunks per device on a
        # (data, stage) grid, 8 layers so each chunk holds exactly one.
        # The machine is UNROLLED (no scan), so the compiled module's
        # collective-permute population must equal the schedule's ship
        # count (Pipeline1F1B.pipe_comm) — the plan diff is exact, not a
        # bound. pipe_moe swaps in 4 experts through the meshless pallas
        # dispatch; its plan also pins all-to-all to ZERO so any buffer
        # dispatch leaking in trips the a2a-free guard.
        from tpukit.pipeline import Pipeline1F1B

        if n_devices % 4:
            raise SystemExit(f"world {name} needs a multiple of 4 devices")
        cfg = _dryrun_cfg(
            num_experts=4 if name == "pipe_moe" else 0,
        ).replace(num_layers=8, virtual_stages=2)
        if name == "pipe_moe":
            # STAGE-ONLY mesh: with a data axis GSPMD reshards the batch
            # ingest through tiny s32/pred all-to-alls, which would drown
            # the guard; on stages alone, all-to-all x0 is exact.
            strategy = Pipeline1F1B(
                create_mesh({"stage": 4}, devices[:4]),
                num_microbatches=4, moe_dispatch="pallas",
            )
        else:
            strategy = Pipeline1F1B(
                create_mesh({"data": n_devices // 4, "stage": 4}, devices),
                num_microbatches=4,
            )
    elif name.startswith("ep"):
        if inner <= 1:
            raise SystemExit(f"world {name} needs a composite device count")
        cfg = _dryrun_cfg(
            comm_dtype=comm,
            num_experts=2 * inner,
            grad_buckets=4 if overlap else 0,
        )
        strategy = ExpertParallel(
            create_mesh({"data": n_devices // inner, "expert": inner}, devices)
        )
    else:
        cfg = _dryrun_cfg(comm_dtype=comm, grad_buckets=4 if overlap else 0)
        cls = DataParallel if name.startswith("ddp") else FSDP
        strategy = cls(create_mesh({"data": n_devices}, devices))

    optimizer = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer, strategy)
    shapes = jax.eval_shape(lambda: state)
    train_step, _, _ = make_step_fns(cfg, optimizer, strategy, shapes)

    seq = 16 if 16 % n_devices == 0 else n_devices
    divisor = strategy.batch_divisor
    batch_n = -(-8 // divisor) * divisor
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(batch_n, seq)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(seq, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros((batch_n, seq), dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    struct = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
        np.asarray(x).shape, np.asarray(x).dtype
    )
    with capture_compiler_stderr() as cap:
        compiled = train_step.lower(
            shapes, jax.tree.map(struct, batch), struct(targets)
        ).compile()
    return {
        "name": name,
        "text": compiled.as_text(),
        "stderr": cap["text"],
        "plan": train_comm_plan(
            strategy, cfg, param_shapes=shapes.params,
            global_batch=batch_n, seq=seq, backend=jax.default_backend(),
        ),
        # train_step donates the whole state (make_step_fns
        # donate_argnums=(0,)): every leaf must appear in the alias table
        "expect_donated": len(jax.tree_util.tree_leaves(shapes)),
        "comm_dtype": cfg.comm_dtype,
    }


def _decode_world(name: str, n_devices: int) -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpukit.analysis import decode_comm_plan, fleet_decode_comm_plan
    from tpukit.mesh import create_mesh
    from tpukit.model import GPTConfig, init_params
    from tpukit.model import gpt
    from tpukit.obs.xla import capture_compiler_stderr
    from tpukit.serve import paged as paged_lib
    from tpukit.serve.decode import decode_step
    from tpukit.shardings import TensorParallel

    # round 21: paged_fused / sched_loop share paged_decode's state but
    # flip cfg.fused_decode — the whole point of their audit is that the
    # fused kernel (and the while-loop window around it) changes ZERO
    # bytes of the comm plan vs the unfused paged_decode world
    fused = name in ("paged_fused", "sched_loop")
    paged = name == "paged_decode" or fused
    spec = name == "spec_verify"
    fleet = name == "fleet_decode"
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=160,
        max_position_embeddings=64, compute_dtype=jnp.float32,
        fused_decode=fused,
    )
    if fleet:
        # a fleet replica's grid: model-parallel over a NON-LEADING device
        # subset (the second replica of a 2 x 4-device fleet) — same
        # program, same plan, different devices; a router that leaked
        # state into the compiled step would show up as surplus
        # collectives or resharding here
        devs = jax.devices()
        if len(devs) < 8:
            raise SystemExit(
                "world fleet_decode needs 8 devices (it compiles on the "
                "subset devices[4:8])"
            )
        mesh = create_mesh({"data": 1, "model": 4}, devices=devs[4:8])
    else:
        mesh = create_mesh({"model": 4} if paged else {"data": 2, "model": 4})
    slots, width, page, mp = 4, 24, 8, 3
    spec_k = 3  # the spec_verify world's draft width (verify window = 4)
    strat = TensorParallel(mesh)
    params = init_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree.map(
        jax.device_put, params, strat.state_sharding(jax.eval_shape(lambda: params))
    )
    sh = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    da = "data" if "data" in mesh.axis_names else None
    if paged:
        tree = paged_lib.init_paged_cache(
            cfg, slots * mp + 1, page, mp, slots, "f32"
        )
        specs = {"k": P(None, None, "model", None, None),
                 "v": P(None, None, "model", None, None),
                 "ks": P(None, None, "model", None),
                 "vs": P(None, None, "model", None), "bt": P()}
        cache = {k: jax.device_put(np.asarray(v), sh(specs[k]))
                 for k, v in tree.items()}
        cache["bt"] = jax.device_put(
            np.arange(1, slots * mp + 1, dtype=np.int32).reshape(slots, mp),
            sh(P()),
        )
        width = mp * page
    else:
        # spec_verify over-allocates the verify scratch tail (spec.py)
        cache = jax.tree.map(
            lambda c: jax.device_put(c, sh(P(None, da, "model", None, None))),
            gpt.init_kv_cache(cfg, slots, width + (spec_k if spec else 0)),
        )
    buf = jax.device_put(np.zeros((slots, width), np.int32), sh(P(da, None)))
    cursors = jax.device_put(np.full((slots,), 5, np.int32), sh(P(da)))
    active = jax.device_put(np.ones((slots,), bool), sh(P(da)))
    limits = jax.device_put(np.full((slots,), 12, np.int32), sh(P(da)))
    keys = jax.device_put(np.zeros((slots, 2), np.uint32), sh(P(da, None)))
    with capture_compiler_stderr() as cap:
        if spec:
            # the FUSED self-speculation program (on-device n-gram
            # proposal + verify) — the one production dispatches
            from tpukit.serve.spec import spec_ngram_step

            compiled = spec_ngram_step.lower(
                params, cfg, buf, cache, cursors, active, limits, keys,
                1, 0.0, 0, k=spec_k, max_ngram=3, mesh=mesh,
            ).compile()
        elif name == "sched_loop":
            # the on-device scheduler window: decode_quantum steps as ONE
            # while_loop program. max_ticks / stop_when_freed are traced
            # i32 scalars, so this very executable serves EVERY window
            # size — and the body's collectives must be attributed once
            # (body membership) for the per-step closed form to gate it.
            from tpukit.serve.decode import decode_loop_window

            ph = jax.device_put(
                np.full((slots,), mp, np.int32), sh(P(None))
            )
            compiled = decode_loop_window.lower(
                params, cfg, buf, cache, cursors, active, limits, keys,
                ph, jnp.asarray(8, jnp.int32),
                jnp.asarray(1 << 30, jnp.int32), 3, 0.0, 0, mesh,
            ).compile()
        else:
            compiled = decode_step.lower(
                params, cfg, buf, cache, cursors, active, limits, keys,
                1, 0.0, 0, mesh,
            ).compile()
    plan = (fleet_decode_comm_plan(cfg, mesh, slots, top_k=0)
            if fleet else
            decode_comm_plan(cfg, mesh, slots, top_k=0, paged=paged,
                             verify_tokens=spec_k + 1 if spec else 1))
    return {
        "name": name,
        "text": compiled.as_text(),
        "stderr": cap["text"],
        "plan": plan,
        # the serve jits deliberately do NOT donate (jaxlib deserialized-
        # executable mis-alias, serve/decode.py) — nothing to expect
        "expect_donated": None,
        "comm_dtype": "f32",
    }


def build_world(name: str, n_devices: int) -> dict:
    """Compile one audited world and return its lint context:
    {name, text, stderr, plan, expect_donated, comm_dtype}."""
    if name not in WORLDS:
        raise SystemExit(f"unknown world {name!r} — known: {', '.join(WORLDS)}")
    if name in ("tp_decode", "paged_decode", "spec_verify", "fleet_decode",
                "paged_fused", "sched_loop"):
        return _decode_world(name, n_devices)
    return _train_world(name, n_devices)


def lint_world(ctx: dict, waive: tuple[str, ...] = ()) -> list:
    """Run the rule engine over one built world's context."""
    import jax

    from tpukit.analysis import lint_text

    return lint_text(
        ctx["text"],
        plan=ctx["plan"],
        compiler_stderr=ctx["stderr"],
        backend=jax.default_backend(),
        expect_donated=ctx["expect_donated"],
        waive=waive,
    )


# -- fixtures ---------------------------------------------------------------

def fixture_paths(directory: Path, name: str) -> tuple[Path, Path]:
    return directory / f"{name}.hlo.txt.gz", directory / f"{name}.json"


def sidecar_of(hlo_path: Path) -> Path:
    """The JSON sidecar path next to a fixture's module text."""
    name = hlo_path.name
    for suffix in (".hlo.txt.gz", ".hlo.txt"):
        if name.endswith(suffix):
            return hlo_path.with_name(name[: -len(suffix)] + ".json")
    return hlo_path.with_suffix(".json")


def plan_from_meta(meta: dict):
    """Rebuild the CommPlan a fixture sidecar recorded at capture time
    (the one spelling tests/test_analysis.py uses too)."""
    from tpukit.analysis import CommPlan

    p = meta.get("plan")
    if p is None:
        return None
    return CommPlan(
        label=meta.get("world", "fixture"), ops=p["ops"], wire=p["wire"],
        exhaustive=p["exhaustive"], comm_dtype=meta.get("comm_dtype", "f32"),
        # round 18: the overlap declaration rides the sidecar so the
        # promoted gate audits saved text like the live world (absent in
        # pre-round-18 sidecars -> None -> reporting-only, as captured)
        overlap=p.get("overlap"),
    )


def read_fixture(path: Path) -> str:
    """Module text of a fixture (gz or plain)."""
    if str(path).endswith(".gz"):
        return gzip.decompress(path.read_bytes()).decode("utf-8")
    return path.read_text()


def save_fixture(directory: Path, ctx: dict) -> None:
    import jax

    from tpukit.analysis import count_involuntary_remat, parse_hlo
    from tpukit.analysis.hlo_ir import collective_summary

    directory.mkdir(parents=True, exist_ok=True)
    hlo_path, meta_path = fixture_paths(directory, ctx["name"])
    hlo_path.write_bytes(
        gzip.compress(ctx["text"].encode("utf-8"), compresslevel=9)
    )
    module = parse_hlo(ctx["text"])
    plan = ctx["plan"]
    meta = {
        "world": ctx["name"],
        "comm_dtype": ctx["comm_dtype"],
        # the capture backend decides wire-upcast severity (XLA:CPU's
        # bf16->f32 normalization warns instead of erroring) — without it
        # a saved bf16-wire dump would flip from clean to violation
        "backend": jax.default_backend(),
        "expect_donated": ctx["expect_donated"],
        "collectives": collective_summary(module),
        "plan": None if plan is None else {
            "ops": plan.ops, "wire": plan.wire, "exhaustive": plan.exhaustive,
            "overlap": plan.overlap,
        },
        "remat_warnings": count_involuntary_remat(ctx["stderr"]),
        "jax_version": jax.__version__,
        "regenerate": (
            f"python tools/hlolint.py --world 8 --save-hlo "
            f"tests/fixtures/hlo --worlds {ctx['name']}"
        ),
    }
    meta_path.write_text(json.dumps(meta, indent=1, sort_keys=True) + "\n")


# -- CLI --------------------------------------------------------------------

def _emit(findings, common: dict, out, human: bool) -> None:
    for f in findings:
        rec = f.to_record(**common)
        out.write(json.dumps(rec) + "\n")
    if human:
        for f in findings:
            print(f"  [{f.severity:<5}] {f.rule}: {f.message}",
                  file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("--world", type=int, default=0, metavar="N",
                    help="compile + lint the audited worlds on N virtual devices")
    ap.add_argument("--worlds", default=",".join(WORLDS),
                    help=f"comma list to restrict --world (default: all of "
                         f"{', '.join(WORLDS)})")
    ap.add_argument("--hlo", nargs="*", default=[],
                    help="saved HLO text file(s) (.gz ok) to lint rules-only")
    ap.add_argument("--stderr", default=None,
                    help="captured compiler stderr for --hlo (remat rule)")
    ap.add_argument("--expect-donated", type=int, default=None,
                    help="donated-leaf count for --hlo (donation rule)")
    ap.add_argument("--backend", default=None,
                    help="capture backend for --hlo (wire-upcast severity; "
                         "a fixture sidecar records it)")
    ap.add_argument("--waive", default="",
                    help="comma list of rules to skip (prints what it waived)")
    ap.add_argument("--out", default=None,
                    help="write findings JSONL here instead of stdout")
    ap.add_argument("--save-hlo", default=None, metavar="DIR",
                    help="with --world: write golden fixtures (gz + sidecar)")
    args = ap.parse_args(argv)

    if not args.world and not args.hlo:
        ap.error("nothing to lint: pass --world N and/or --hlo FILE")

    waive = tuple(w for w in args.waive.split(",") if w)
    if waive:
        print(f"hlolint: waiving rule(s): {', '.join(waive)}", file=sys.stderr)

    out = open(args.out, "w") if args.out else sys.stdout
    human = out is not sys.stdout
    errors = 0
    try:
        for path in args.hlo:
            p = Path(path)
            text = read_fixture(p)
            stderr_text = Path(args.stderr).read_text() if args.stderr else ""
            from tpukit.analysis import lint_text, summarize

            # a fixture's JSON sidecar restores the capture-time plan,
            # donation expectation and backend, so linting the saved text
            # runs the SAME audit the live world did; explicit flags win
            plan, donated, backend = None, args.expect_donated, args.backend
            side = sidecar_of(p)
            if side.exists():
                meta = json.loads(side.read_text())
                plan = plan_from_meta(meta)
                if donated is None:
                    donated = meta.get("expect_donated")
                if backend is None:
                    backend = meta.get("backend")
            findings = lint_text(
                text, plan=plan, compiler_stderr=stderr_text,
                backend=backend, expect_donated=donated, waive=waive,
            )
            s = summarize(findings)
            print(f"hlolint {p.name}: "
                  f"{'clean' if s['clean'] else s['violations']}"
                  f" ({s['errors']} errors, {s['warnings']} warnings)"
                  + (" [sidecar plan]" if plan is not None else ""),
                  file=sys.stderr)
            _emit(findings, {"source": str(p)}, out, human)
            errors += s["errors"]

        if args.world:
            _ensure_env(args.world)
            names = tuple(w for w in args.worlds.split(",") if w)
            save_dir = Path(args.save_hlo) if args.save_hlo else None
            if save_dir is not None and args.worlds == ",".join(WORLDS):
                # fixture capture defaults to the golden subset (ep_int8
                # re-compiles the most expensive world for no fixture
                # value); an explicit --worlds list always wins
                names = FIXTURE_WORLDS
            from tpukit.analysis import summarize

            for name in names:
                ctx = build_world(name, args.world)
                findings = lint_world(ctx, waive=waive)
                s = summarize(findings)
                plan = ctx["plan"]
                planned = (
                    " planned:" + ",".join(
                        f"{op}x{rec['count']}@{rec['bytes']}B"
                        for op, rec in sorted(plan.ops.items())
                    ) if plan is not None and plan.ops else ""
                )
                print(f"hlolint world {name}: "
                      f"{'clean' if s['clean'] else s['violations']}"
                      f" ({s['errors']} errors, {s['warnings']} warnings)"
                      + planned,
                      file=sys.stderr)
                _emit(findings, {"world": name}, out, human)
                errors += s["errors"]
                if save_dir is not None:
                    save_fixture(save_dir, ctx)
    finally:
        if out is not sys.stdout:
            out.close()

    if errors:
        print(f"hlolint: {errors} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
