"""Microbench: ring-attention schedule, optimized vs naive (VERDICT r3 #4).

Compares tpukit.ring_attention.ring_causal_attention (hop-skipping +
input-dtype MXU matmuls + permute/compute overlap) against the r3 naive
schedule (dense f32 einsum on every hop) at long-context shapes, inside the
same shard_map the ContextParallel strategy uses.

A ring needs >= 2 devices; on this machine that means the virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu) —
which is also where hop-skipping shows up directly in wall-clock, since one
host executes every device's compute serially. On real multi-chip TPU the
skip cuts total FLOPs/energy the same way, while the critical path (the
last device computes on every hop) is shortened by the bf16 MXU matmuls and
the transfer/compute overlap.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python tools/bench_ring.py [--seq 8192] [--batch 1] [--grad]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from tpukit.compat import axis_size as compat_axis_size, shard_map
from jax.sharding import PartitionSpec as P

from tpukit.mesh import create_mesh
from tpukit.ops.attention import NEG_INF
from tpukit.ring_attention import ring_causal_attention, zigzag_order


def naive_ring_attention(q, k, v, *, scale, axis_name, pad_mask=None):
    """The round-3 schedule: full f32 dense einsum on EVERY hop (including
    the entirely-masked ones), kept verbatim as the comparison baseline."""
    ring = compat_axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, _, s_local, _ = q.shape
    if pad_mask is None:
        pad_mask = jnp.zeros((batch, s_local), dtype=jnp.bool_)

    rows = my_index * s_local + jnp.arange(s_local)
    qf = q.astype(jnp.float32)
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, _):
        m, l, acc, k_c, v_c, mask_c, src = carry
        cols = src * s_local + jnp.arange(s_local)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_c.astype(jnp.float32)) * scale
        s = s + jnp.where(cols[None, :] <= rows[:, None], 0.0, NEG_INF)
        s = jnp.where(mask_c[:, None, None, :], jnp.finfo(jnp.float32).min, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32)
        )
        k_next = jax.lax.ppermute(k_c, axis_name, perm)
        v_next = jax.lax.ppermute(v_c, axis_name, perm)
        mask_next = jax.lax.ppermute(mask_c, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next, mask_next, (src - 1) % ring), None

    init = (
        jnp.full(q.shape[:3], -jnp.inf, jnp.float32),
        jnp.zeros(q.shape[:3], jnp.float32),
        jnp.zeros(qf.shape, jnp.float32),
        k, v, pad_mask, my_index,
    )
    (m, l, acc, *_), _ = jax.lax.scan(step, init, None, length=ring)
    return (acc / l[..., None]).astype(v.dtype)


def timed(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head_dim", type=int, default=32)
    ap.add_argument("--grad", action="store_true", help="time fwd+bwd instead of fwd")
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    n = len(jax.devices())
    if n < 2:
        raise SystemExit(
            "ring needs >=2 devices; run with XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu"
        )
    mesh = create_mesh({"seq": n})
    scale = args.head_dim**-0.5
    dtype = jnp.bfloat16

    rng = np.random.RandomState(0)
    shape = (args.batch, args.heads, args.seq, args.head_dim)
    q, k, v = (jnp.asarray(rng.randn(*shape), dtype) for _ in range(3))
    mask = jnp.zeros((args.batch, args.seq), jnp.bool_)

    def on_mesh(impl, layout="contiguous"):
        def local(q, k, v, m):
            if impl is naive_ring_attention:
                return impl(q, k, v, scale=scale, axis_name="seq", pad_mask=m)
            return impl(q, k, v, scale=scale, axis_name="seq", pad_mask=m, layout=layout)

        f = shard_map(
            local, mesh=mesh,
            in_specs=(P(None, None, "seq"),) * 3 + (P(None, "seq"),),
            out_specs=P(None, None, "seq"),
            check_vma=False,
        )
        if args.grad:
            loss = lambda q, k, v, m: jnp.sum(f(q, k, v, m).astype(jnp.float32) ** 2)
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return jax.jit(f)

    t_old = timed(on_mesh(naive_ring_attention), q, k, v, mask, iters=args.iters)
    t_new = timed(on_mesh(ring_causal_attention), q, k, v, mask, iters=args.iters)
    # zigzag operates on the permuted layout (ContextParallel permutes once
    # per step on [B,S] int arrays — negligible; excluded here)
    order = zigzag_order(args.seq, n)
    qz, kz, vz = (t[:, :, order] for t in (q, k, v))
    t_zz = timed(on_mesh(ring_causal_attention, "zigzag"), qz, kz, vz, mask[:, order], iters=args.iters)

    label = "fwd+bwd" if args.grad else "fwd"
    print(
        f"ring {label} S={args.seq} B={args.batch} h={args.heads} "
        f"d={args.head_dim} P={n} ({jax.devices()[0].device_kind}):"
    )
    print(f"  naive (r3)     : {t_old*1e3:8.2f} ms")
    print(f"  skip+bf16      : {t_new*1e3:8.2f} ms   speedup {t_old/t_new:.2f}x")
    print(f"  zigzag balanced: {t_zz*1e3:8.2f} ms   speedup {t_old/t_zz:.2f}x")


if __name__ == "__main__":
    main()
