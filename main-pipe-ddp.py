#!/usr/bin/env python
"""Recipe 5: 2-D pipeline x data-parallel hybrid training.

The reference ships only a stub for this recipe — `main-pipe-ddp.py` is a
single shebang line (main-pipe-ddp.py:1) — so this implements the intent
(per the filename and SURVEY §2.4): data-parallel replicas of a pipeline.

TPU-natively that is just the pipeline strategy on a 2-D `(data, stage)`
mesh: micro-batches shard over `data`, stacked layer params shard over
`stage` and replicate over `data`; XLA adds the data-axis gradient
all-reduce on top of the stage-axis collective-permutes. No new code beyond
choosing the mesh — which is the point of expressing parallelism as
shardings.

Run: `python main-pipe-ddp.py --batch_size 64 ...` — the device grid is
split with stages innermost (ICI-adjacent) and the data axis across the
remaining devices, e.g. 8 devices -> (data=2, stage=4).
"""

import jax

from tpukit.flags import parse_flags
from tpukit.mesh import create_mesh
from tpukit.pipeline import Pipeline, Pipeline1F1B
from tpukit.train import fit


def pick_grid(n_devices: int, num_layers: int) -> dict:
    """Largest stage count <= 4 that divides both the device count and the
    layer count; remaining devices become data-parallel replicas."""
    for stage in (4, 2, 1):
        if n_devices % stage == 0 and num_layers % stage == 0:
            return {"data": n_devices // stage, "stage": stage}
    return {"data": n_devices, "stage": 1}


def main(argv=None):
    flags = parse_flags(
        argv, pipeline_schedule=True, num_experts=True, default_experts=0
    )
    cls = Pipeline1F1B if flags.pipeline_schedule == "1f1b" else Pipeline
    grid = pick_grid(len(jax.devices()), flags.num_layers)
    return fit(
        flags,
        cls(
            create_mesh(grid),
            num_microbatches=flags.microbatches or "4x",
            moe_dispatch=flags.moe_dispatch if flags.num_experts else None,
        ),
    )


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
