#!/usr/bin/env python
"""Benchmark: GPT training throughput on the available chip(s).

Trains the cookbook's GPT (reference default shape: dim 256, 8x32 heads,
8 layers, seq 256, GPT-2 vocab — main-single.py:156-162) with the full jitted
train step (fwd + bwd + AdamW) in bf16 on synthetic data, and reports
tokens/sec/chip and MFU. The reference publishes no numbers (BASELINE.md), so
`vs_baseline` is measured MFU / the driver's 35% MFU north-star.

Every probe (headline, long-context, offload, MoE, ladder rungs) shares ONE
setup helper (`tools.bench_ladder.setup_step`) and the persistent XLA
compilation cache (`--compilation_cache_dir`, default `.jax_cache`), so a
repeat bench run skips recompiles; hit/miss counts land in the JSON. The
`host_pipeline` record measures the round-7 prefetch path: the same loader
schedule + train step run synchronously and with `--prefetch`-style
depth-2 overlap, reporting the input-share both ways and loss parity. The
`obs_overhead` record measures the round-8 failure-observability layer
(flight-recorder ring + periodic in-jit divergence checksum) against the
bare loop, with the same loss-parity proof. The `moe_ep_comm` record
(round 10) audits the ExpertParallel a2a dispatch: expected-vs-measured
all-to-all bytes, involuntary-remat warning count, a2a-path throughput.
The `moe_dispatch_ladder` record (round 11, ROADMAP #3) measures the
three MoE dataflows — xla buffers, a2a exchange, pallas grouped GEMM — at
e8 top-1/top-2 with active-FLOPs-normalized MFU; `--moe_dispatch pallas`
flips the headline moe_e8 probe onto the kernel path. The `quant_comm`
record (round 12, ROADMAP #2) measures `--comm_dtype` f32 vs bf16 vs int8
per strategy rung (ddp/fsdp/ep): expected+measured bytes-on-the-wire (the
~4x int8 cut is the headline), tokens/s/chip, and the final-loss delta vs
f32 — the tolerance-gate number. The `elastic_restore` record (round 13,
ROADMAP #5) measures the reshard-on-restore pass: a sharded FSDP
checkpoint landing on a half-size world — wall-clock, bytes read, host
RSS high-water delta, and the byte-parity bit vs a direct restore. The
`serving` record (round 14, ROADMAP #1) measures the continuous-batching
engine (tpukit/serve) against serial per-request cached decode on the
same seeded synthetic stream: tokens/s (>= 2x is the acceptance bar),
p50/p99 end-to-end and per-token latency, slot occupancy. The
`spec_decode` record (round 17, ROADMAP #3) measures speculative
decoding — induction-trained target, self-spec (fused on-device n-gram)
and draft-model proposers — vs the vanilla engine on the repetitive
stream at temperature 0 and 0.8: tokens/s (self-spec t=0 >= 1.3x is the
bar), acceptance rate, and the appended-tokens/verify histogram. The
`fleet_serving` record (round 19, ROADMAP #1) measures the fleet router
(tpukit/serve/fleet) at 1 vs 2 vs 4 replicas on the same stream at equal
total devices — fleet tokens/s scaling (>1.5x at 2 replicas is the bar),
p99 under load, per-request token parity across rungs, and
disaggregated-vs-colocated prefill admit latency — with an honest
CPU-loopback caveat in-record. Round 20 adds the
`serve_dispatch_attribution` record (per-quantum dispatch-vs-device wall
split from the request tracer's quantum spans) and a `serving` rung
inside `obs_overhead` (the trace recorder on vs off on the same seeded
stream: tokens/s delta under the 1% bar, bit-identical output tokens).
The `decode_fused` record (round 21, ROADMAP #2/#4) isolates the two
`--fused_decode` wins: unfused-gather vs fused-kernel at decode_quantum=1
(the kernel delta; interpret-mode CPU states its inversion honestly) and
fused q=1 vs the on-device while-loop window (the dispatch-amortization
delta, which transfers — the kernel cost cancels), with three-way token
parity and per-quantum dispatch/device walls.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import argparse
import json
import os
import sys

import numpy as np


def bench_host_pipeline(cfg, strategy, batch, depth=2, steps=24):
    """Prefetch-vs-sync host input pipeline on the headline config.

    Runs the REAL input path (DataLoader -> prepare_batch -> global-batch
    assembly -> jitted train step) over an identical batch schedule twice,
    from identical initial states: once synchronous (the data+h2d spans),
    once through a depth-N HostPrefetcher (the prefetch_stall span).
    Returns the window share of each, the buffer occupancy, and whether the
    final losses are bit-identical (they must be: same batches, same order,
    same step function — the prefetcher only moves WHEN host work runs).
    """
    from tpukit.batching import prepare_batch
    from tpukit.data import ArrayDataset
    from tpukit.loader import DataLoader
    from tpukit.obs import SpanTimeline
    from tpukit.prefetch import HostPrefetcher
    from tpukit.train import make_global_batch
    from tools.bench_ladder import make_batch, setup_step

    seq = cfg.max_position_embeddings
    pad_id = 2
    rng = np.random.RandomState(7)
    # raw [B, S] rows; prepare_batch shifts to the model's S-1, matching
    # the headline step's compiled shape
    ids = rng.randint(3, cfg.vocab_size, size=(steps * batch, seq)).astype(np.int32)
    ds = ArrayDataset(ids, np.ones_like(ids))
    batch_sh = strategy.batch_sharding()

    def pipeline(raw):
        b, t = prepare_batch(raw, pad_id)
        return make_global_batch(batch_sh, b, t, place=True)

    def run(prefetched: bool):
        train_step, state, _, _ = setup_step(cfg, strategy)
        # compile + warm outside the measured window
        wb, wt = make_batch(np.random.RandomState(0), cfg.vocab_size, batch, seq - 1)
        state, _ = train_step(state, wb, wt)
        spans = SpanTimeline()
        loader = DataLoader(ds, batch)
        occupancy = None
        spans.epoch()  # reset the clock to the loop start
        if prefetched:
            pf = HostPrefetcher(loader, pipeline, depth=depth)
            try:
                while True:
                    with spans.span("prefetch_stall"):
                        try:
                            b, t = next(pf)
                        except StopIteration:
                            break
                    with spans.span("step"):
                        state, loss = train_step(state, b, t)
            finally:
                occupancy = pf.window_stats()["occupancy"]
                pf.close()
        else:
            # loader next() INSIDE the data span, mirroring fit()'s sync
            # accounting — batch assembly is real host input work and must
            # land in the share being compared against prefetch_stall
            it = iter(loader)
            while True:
                with spans.span("data"):
                    try:
                        raw = next(it)
                    except StopIteration:
                        break
                    b, t = prepare_batch(raw, pad_id)
                with spans.span("h2d"):
                    b, t = make_global_batch(batch_sh, b, t)
                with spans.span("step"):
                    state, loss = train_step(state, b, t)
        with spans.span("sync"):
            final = float(loss)
        win = spans.epoch()
        del state
        return final, win, occupancy

    loss_sync, win_sync, _ = run(prefetched=False)
    loss_pf, win_pf, occupancy = run(prefetched=True)
    frac_s, frac_p = win_sync["fractions"], win_pf["fractions"]
    return {
        "depth": depth,
        "steps": steps,
        "sync_input_share": round(
            frac_s.get("data", 0.0) + frac_s.get("h2d", 0.0), 4
        ),
        "prefetch_stall_share": round(frac_p.get("prefetch_stall", 0.0), 4),
        "prefetch_occupancy": round(occupancy, 3) if occupancy is not None else None,
        "sync_wall_s": round(win_sync["total_s"], 4),
        "prefetch_wall_s": round(win_pf["total_s"], 4),
        "loss_bit_identical": loss_sync == loss_pf,
        "final_loss": round(loss_pf, 6),
    }


def bench_obs_overhead(cfg, strategy, batch, steps=48, checksum_every=8):
    """Flight-recorder + divergence-checksum overhead on the headline step.

    Runs the same compiled train step over the same batch for `steps`
    iterations twice, from identical initial states: once bare, once with
    the round-8 observability layer active — a FlightRecorder record per
    step plus an in-jit state checksum (with its D2H sync) every
    `checksum_every` steps, the exact per-step work fit() adds with
    `--divergence_check_freq`. Reports both walls, the overhead fraction
    (the <1% claim docs/DESIGN.md makes, now measured per run), and
    whether the final losses are bit-identical (they must be: the
    recorder only observes, and the checksum is a separate jitted
    program that never touches the training state).
    """
    import time as _time

    import jax

    from tools.bench_ladder import make_batch, setup_step
    from tpukit.obs import FlightRecorder, format_checksum, make_state_checksum

    seq = cfg.max_position_embeddings
    rng = np.random.RandomState(3)
    b, t = make_batch(rng, cfg.vocab_size, batch, seq - 1)

    def run(instrumented: bool):
        train_step, state, _, _ = setup_step(cfg, strategy)
        state, loss = train_step(state, b, t)  # compile + warm, untimed
        jax.block_until_ready(loss)
        rec = FlightRecorder() if instrumented else None
        checksum_fn = make_state_checksum() if instrumented else None
        if checksum_fn is not None:
            # compile the checksum program outside the timed window, the
            # same one-off cost fit() pays at its first check step
            jax.block_until_ready(checksum_fn(state)["params"])
        last_ck = pending = None
        t0 = _time.perf_counter()
        for i in range(1, steps + 1):
            state, loss = train_step(state, b, t)
            if rec is not None:
                rec.record("step", step=i)
                if i % checksum_every == 0:
                    pending = (i, checksum_fn(state))  # async dispatch
            if i % checksum_every == 0:
                float(loss)  # the PRINT_FREQ window sync BOTH paths pay
                if pending is not None:
                    # fit's deferred D2H read at the window boundary
                    last_ck = format_checksum(pending[1])
                    rec.record("divergence_check", step=pending[0], checksum=last_ck)
                    pending = None
        final = float(loss)  # drains the dispatch pipeline inside the timing
        wall = _time.perf_counter() - t0
        del state
        return final, wall, last_ck

    loss_off, wall_off, _ = run(False)
    loss_on, wall_on, last_ck = run(True)
    return {
        "steps": steps,
        "checksum_every": checksum_every,
        "baseline_wall_s": round(wall_off, 4),
        "instrumented_wall_s": round(wall_on, 4),
        "overhead_frac": round((wall_on - wall_off) / wall_off, 4),
        "loss_bit_identical": loss_off == loss_on,
        "final_loss": round(loss_on, 6),
        "last_checksum": last_ck,
    }


def bench_moe_ep_comm(cfg, n_dev, num_experts=8, steps=8):
    """Expert-parallel a2a dispatch audit + throughput on the available
    chips (round 10).

    Builds the moe_e8 shape on an ExpertParallel `(data, expert)` mesh with
    the explicit all_to_all dispatch, compiles the train step under a
    compiler-stderr capture, and reports:
      - expected vs measured per-device all-to-all payload (the closed-form
        `ExpertParallel.dispatch_comm` number against the optimized HLO) —
        hand-scheduling a collective means being able to predict its bytes;
      - the count of `[SPMD] Involuntary full rematerialization` warnings
        (zero is the bar — the round-5 einsum dispatch emitted a wall of
        them; meaningful on cold compiles, a cache hit emits none);
      - tokens/sec/chip through the a2a path, next to the xla-dispatch
        `moe_e8` headline so the two spellings stay comparable.
    On one chip the expert axis is 1 and no traffic crosses devices —
    expected == measured == 0 keeps the record honest rather than faked.
    """
    import math

    import jax

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.mesh import create_mesh
    from tpukit.obs import capture_compiler_stderr, collective_bytes
    from tpukit.shardings import ExpertParallel

    expert = math.gcd(n_dev, num_experts)
    grid = {"data": n_dev // expert, "expert": expert}
    strat = ExpertParallel(create_mesh(grid), dispatch="a2a")
    cfg_m = cfg.replace(num_experts=num_experts)
    seq = cfg.max_position_embeddings
    batch = 32 * n_dev
    b, t = make_batch(np.random.RandomState(5), cfg.vocab_size, batch, seq - 1)
    with capture_compiler_stderr() as cap:
        step, state, shapes, _ = setup_step(cfg_m, strat)
        struct = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
        compiled = step.lower(
            shapes, jax.tree.map(struct, b), struct(t)
        ).compile()
    measured = collective_bytes(compiled.as_text()).get(
        "all-to-all", {"count": 0, "bytes": 0}
    )
    backend = jax.default_backend()
    # dtype-aware expectation (round 12): the closed form prices in the
    # backend's wire dtype (XLA:CPU upcasts bf16 payloads to f32), so the
    # byte comparison is EXACT on every backend — the old cpu 2x allowance
    # is gone, a drift is a drift.
    expected = strat.dispatch_comm(
        cfg_m, global_batch=batch, seq=seq - 1, backend=backend
    )["train"]
    # time the COMPILED executable: on jax 0.4.x the AOT path does not
    # populate the jit call cache, so timing `step` would recompile
    times, state, loss = time_windows(
        compiled, state, b, t, steps=steps, windows=3, warmup=2
    )
    del state
    bytes_match = (
        measured["count"] == expected["count"]
        and measured["bytes"] == expected["bytes"]
    )
    return {
        "mesh": grid,
        "dispatch": "a2a",
        "backend": backend,
        "expected_a2a": {"count": expected["count"], "bytes": expected["bytes"]},
        "measured_a2a": measured,
        "bytes_match": bytes_match,
        "involuntary_remat_warnings": cap["involuntary_remat"],
        "tokens_per_sec_per_chip": round(steps * batch * (seq - 1) / min(times) / n_dev, 1),
        "final_loss": round(loss, 6),
    }


def bench_moe_dispatch_ladder(cfg, n_dev, num_experts=8, steps=8):
    """FLOP-normalized MoE dispatch ladder (ROADMAP #3, round 11): xla vs
    a2a vs pallas at the e8 shape, top-1 AND top-2. Each rung reports
    tokens/s/chip and an MFU normalized by ACTIVE FLOPs
    (`obs.moe_active_flops_per_token`: top_k routed experts + router per
    token — the dropless convention), so a dataflow that burns MXU cycles
    on capacity padding or one-hot dispatch einsums shows as LOST MFU at
    equal tokens/s instead of hiding inside a bigger FLOP count. "xla" and
    "pallas" run meshless (the single-chip spellings); "a2a" runs through
    ExpertParallel, whose 1-way expert axis on one chip keeps the same
    capacity-buffer dataflow without collectives. Per-rung failures land
    as {"dispatch", "top_k", "error"} entries — a broken rung cannot hide
    behind a clean rc=0."""
    import math

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.mesh import create_mesh
    from tpukit.obs import moe_active_flops_per_token, peak_flops_per_chip
    from tpukit.shardings import DataParallel, ExpertParallel, SingleDevice

    seq = cfg.max_position_embeddings
    batch = 32 * n_dev
    peak = peak_flops_per_chip()
    rows = []
    for top_k in (1, 2):
        for dispatch in ("xla", "a2a", "pallas"):
            cfg_m = cfg.replace(num_experts=num_experts, router_top_k=top_k)
            try:
                if dispatch == "a2a":
                    expert = math.gcd(n_dev, num_experts)
                    strat = ExpertParallel(
                        create_mesh(
                            {"data": n_dev // expert, "expert": expert}
                        ),
                        dispatch="a2a",
                    )
                else:
                    cfg_m = cfg_m.replace(moe_dispatch=dispatch)
                    strat = DataParallel() if n_dev > 1 else SingleDevice()
                step, state, _, _ = setup_step(cfg_m, strat)
                b, t = make_batch(
                    np.random.RandomState(5), cfg.vocab_size, batch, seq - 1
                )
                times, state, loss = time_windows(
                    step, state, b, t, steps=steps, windows=3, warmup=2
                )
                del state
                tps_chip = steps * batch * (seq - 1) / min(times) / n_dev
                flops = moe_active_flops_per_token(cfg_m, seq - 1)
                rows.append({
                    "dispatch": dispatch,
                    "top_k": top_k,
                    "tokens_per_sec_per_chip": round(tps_chip, 1),
                    "active_flops_per_token": flops,
                    "mfu_active": (
                        round(tps_chip * flops / peak, 4) if peak else None
                    ),
                    "final_loss": round(loss, 6),
                })
            except Exception as exc:
                rows.append(
                    {"dispatch": dispatch, "top_k": top_k, "error": repr(exc)}
                )
                print(
                    f"moe ladder rung {dispatch}/top{top_k} failed: {exc!r}",
                    file=sys.stderr,
                )
    return rows


def bench_elastic_restore(cfg, n_dev):
    """Elastic restore probe (round 13, ROADMAP #5): save a sharded FSDP
    checkpoint over all chips, then restore it two ways — direct (same
    world) and RESHARDED onto a half-size mesh (tpukit/reshard.py) — and
    record what an elastic relaunch costs:

      - restore+reshard wall-clock and bytes/blocks read (the streaming
        reader should read each byte once);
      - peak host RSS delta across the reshard (ru_maxrss high-water),
        plus `rss_overhead_bytes` = delta minus the state's own bytes:
        on CPU backends the restored arrays themselves live in process
        heap, so the DELTA is ~state_bytes on every healthy run — the
        OVERHEAD is the signal. The streaming pass bounds scratch memory
        by one leaf's blocks, so overhead near zero is healthy and
        overhead near +state_bytes means a second full copy was
        materialized (the regression this probe exists to catch);
      - a parity bit: the resharded state's leaves must be BYTE-identical
        to the direct restore's (resharding moves data, never math).

    Needs >= 2 chips to have a smaller world to land on; on one chip the
    record carries an honest error instead of a faked number."""
    import resource
    import shutil
    import tempfile

    import jax

    from tools.bench_ladder import setup_step
    from tpukit import checkpoint as ckpt_lib
    from tpukit import reshard as reshard_lib
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP

    if n_dev < 2:
        return {"error": "needs >= 2 chips (no smaller world to reshard onto)"}
    src = FSDP(create_mesh({"data": n_dev}))
    _, state, shapes, _ = setup_step(cfg, src)
    state_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(state)
    )
    ckdir = tempfile.mkdtemp(prefix="tpukit-bench-resize-")
    try:
        path = ckpt_lib.save_sharded(
            state, ckdir, meta={"world": reshard_lib.current_world(src)}
        )
        tgt = FSDP(create_mesh({"data": n_dev // 2}, jax.devices()[: n_dev // 2]))
        t_sharding = tgt.state_sharding(shapes)
        # reshard FIRST, bracketed by the RSS high-water reads, so the
        # direct (parity-reference) restore's allocations cannot inflate
        # the delta attributed to the streaming pass
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        resized, info = reshard_lib.reshard_restore(path, shapes, t_sharding)
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        direct, _ = reshard_lib.reshard_restore(
            path, shapes, src.state_sharding(shapes)
        )
        parity = all(
            np.asarray(jax.device_get(a)).tobytes()
            == np.asarray(jax.device_get(b)).tobytes()
            for a, b in zip(
                jax.tree_util.tree_leaves(resized),
                jax.tree_util.tree_leaves(direct),
            )
        )
        del state, direct, resized
        # ru_maxrss is KiB on Linux, bytes on macOS; normalize to bytes
        rss_delta = int(
            (rss1 - rss0) * (1 if sys.platform == "darwin" else 1024)
        )
        return {
            "from_world": {"strategy": "fsdp", "devices": n_dev},
            "to_world": {"strategy": "fsdp", "devices": n_dev // 2},
            "state_bytes": int(state_bytes),
            "restore_wall_s": round(info["wall_s"], 4),
            "bytes_read": int(info["bytes_read"]),
            "blocks_read": int(info["blocks_read"]),
            "peak_rss_delta_bytes": rss_delta,
            # the signal: scratch above the restored state's own residency
            # (on CPU the restored arrays ARE host RAM; on TPU they are
            # not, and overhead simply reads lower — still comparable
            # across rounds on the same backend)
            "rss_overhead_bytes": rss_delta - int(state_bytes),
            "parity_ok": bool(parity),
        }
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)


def bench_serving(cfg, n_dev, requests=32, slots=8, max_new=16):
    """Continuous batching vs serial per-request `generate` on the SAME
    seeded synthetic stream (round 14, ROADMAP #1 — the >= 2x bar).

    All sides serve identical requests from identical params: the engine
    admits into `slots` KV-ring lanes mid-decode (batched bucketed
    prefills, quantum cached decode steps); the baselines decode one
    request at a time, each waiting for every request before it — the
    pre-round-14 serving story. TWO serial baselines are reported so the
    headline can't hide behind baseline choice:

      - "serial": per-request `generate` AS SHIPPED — its use_cache
        auto-resolve picks the naive full-re-forward loop at these
        buffer widths (the v5e-tuned threshold), exactly what serving
        through the training-era API costs.
      - "serial_cached": the STRONGEST serial spelling — the fused
        single-sequence KV-cached while_loop (`use_cache=True`), zero
        host round-trips per token.

    Each side runs twice (warm-up absorbs compiles — the stream's prompt
    lengths are drawn from a fixed set so the serial paths' per-length
    compiles are bounded); the measured run reports tokens/s, end-to-end
    p50/p99 (arrivals all at t=0, so serial queue wait IS the latency
    story), per-token p50/p99 and slot occupancy. `speedup` is
    continuous vs "serial" (the acceptance bar's baseline);
    `speedup_vs_cached` is the honest harder ratio."""
    import time

    import jax
    import jax.numpy as jnp

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.sampling import _decode_loop, _decode_loop_cached
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
    params = init_params(jax.random.PRNGKey(0), cfg)
    # buckets == the drawn length set: prompts prefill at their exact
    # length, so the comparison shows scheduling wins, not padding losses
    buckets = lengths = (8, 16, 24, 32)
    eos = int(tokenizer.eos_token_id)
    stream = synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    )
    serve = ServeConfig(slots=slots, buckets=buckets, max_new_tokens=max_new,
                        window_steps=10**9)  # no window records in the bench

    def run_continuous():
        eng = ServeEngine(params, cfg, serve, eos_id=eos)
        t0 = time.perf_counter()
        comps = eng.run(list(stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        e2e = np.asarray([c.e2e_s for c in comps])
        tok = np.asarray([c.per_token_s for c in comps])
        s = eng.last_summary
        return dict(
            tokens_per_sec=round(gen / wall, 1), wall_s=round(wall, 3),
            generated_tokens=gen,
            p50_e2e_s=round(float(np.percentile(e2e, 50)), 4),
            p99_e2e_s=round(float(np.percentile(e2e, 99)), 4),
            p50_token_s=round(float(np.percentile(tok, 50)), 5),
            p99_token_s=round(float(np.percentile(tok, 99)), 5),
            mean_occupancy=round(s["mean_occupancy"], 3),
            prefill_s=round(s["prefill_s"], 3),
            decode_s=round(s["decode_s"], 3),
        )

    def run_serial(decode_fn):
        t0 = time.perf_counter()
        gen, finish = 0, []
        for r in stream:
            ids = np.asarray(r.ids, np.int32)
            buf = np.zeros((1, len(ids) + max_new), np.int32)
            buf[0, : len(ids)] = ids
            out, length = decode_fn(
                params, cfg, jnp.asarray(buf), len(ids), max_new, eos
            )
            gen += int(length) - len(ids)
            finish.append(time.perf_counter() - t0)
        wall = time.perf_counter() - t0
        e2e = np.asarray(finish)  # arrivals at t=0: wait-in-line included
        return dict(
            tokens_per_sec=round(gen / wall, 1), wall_s=round(wall, 3),
            generated_tokens=gen,
            p50_e2e_s=round(float(np.percentile(e2e, 50)), 4),
            p99_e2e_s=round(float(np.percentile(e2e, 99)), 4),
        )

    run_continuous()  # warm: bucket prefills + the decode step compile
    cont = run_continuous()
    run_serial(_decode_loop)  # warm: one compile per distinct prompt length
    ser = run_serial(_decode_loop)
    run_serial(_decode_loop_cached)
    ser_cached = run_serial(_decode_loop_cached)
    return {
        "requests": requests, "slots": slots, "buckets": list(buckets),
        "max_new_tokens": max_new,
        "generated_tokens": cont["generated_tokens"],
        "decode_quantum": serve.decode_quantum,
        "continuous": cont, "serial": ser, "serial_cached": ser_cached,
        "speedup": round(cont["tokens_per_sec"] / ser["tokens_per_sec"], 2)
        if ser["tokens_per_sec"] else None,
        "speedup_vs_cached": round(
            cont["tokens_per_sec"] / ser_cached["tokens_per_sec"], 2
        ) if ser_cached["tokens_per_sec"] else None,
    }


def bench_serve_trace_overhead(cfg, n_dev, requests=32, slots=8, max_new=16):
    """Request-trace recorder overhead on the serving engine (round 20):
    the SAME seeded stream served twice, tracer off then on, after a warm
    pass that absorbs compiles. The tracer is host-side only — a dict +
    deque append per span event — so the acceptance bar is a tokens/s
    delta under 1% AND bit-identical output tokens per request (the
    recorder observes, it never schedules). Also reports the event count
    and ring drops so capacity sizing stays honest."""
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.obs import TraceRecorder
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
    params = init_params(jax.random.PRNGKey(0), cfg)
    buckets = lengths = (8, 16, 24, 32)
    eos = int(tokenizer.eos_token_id)
    stream = list(synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    ))
    serve = ServeConfig(slots=slots, buckets=buckets, max_new_tokens=max_new,
                        window_steps=10**9)

    def run(traced: bool):
        tracer = TraceRecorder() if traced else None
        eng = ServeEngine(params, cfg, serve, eos_id=eos, tracer=tracer)
        t0 = time.perf_counter()
        comps = eng.run(list(stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        toks = {c.rid: [int(x) for x in np.asarray(c.ids)] for c in comps}
        return gen / wall, toks, tracer

    run(False)  # warm: bucket prefills + the decode step compile
    tps_off, toks_off, _ = run(False)
    tps_on, toks_on, tracer = run(True)
    return {
        "requests": requests, "slots": slots, "max_new_tokens": max_new,
        "tokens_per_sec_off": round(tps_off, 1),
        "tokens_per_sec_on": round(tps_on, 1),
        "overhead_frac": round((tps_off - tps_on) / tps_off, 4)
        if tps_off else None,
        "tokens_bit_identical": toks_off == toks_on,
        "events_emitted": tracer.total_emitted,
        "events_dropped": tracer.dropped,
    }


def bench_metrics_overhead(cfg, n_dev, requests=32, slots=8, max_new=16):
    """Metrics-plane overhead on the serving engine (round 22): the SAME
    seeded stream served twice, registry off (--no_metrics) then on,
    after a warm pass that absorbs compiles. The metrics plane is a pure
    observer — counters/gauges/histograms DERIVED from completions the
    engine computes anyway — so the acceptance bar is the round-20
    discipline verbatim: tokens/s delta under 1% AND bit-identical
    output tokens per request. The atomic snapshot publish + merge (the
    only new I/O) is timed separately so dir-publish cost can't hide
    inside the throughput delta."""
    import tempfile
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.obs import MetricRegistry, merge_snapshot_dir, publish_snapshot
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
    params = init_params(jax.random.PRNGKey(0), cfg)
    buckets = lengths = (8, 16, 24, 32)
    eos = int(tokenizer.eos_token_id)
    stream = list(synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    ))
    serve = ServeConfig(slots=slots, buckets=buckets, max_new_tokens=max_new,
                        window_steps=10**9)

    def run(with_metrics: bool):
        metrics = MetricRegistry() if with_metrics else None
        eng = ServeEngine(params, cfg, serve, eos_id=eos, metrics=metrics)
        t0 = time.perf_counter()
        comps = eng.run(list(stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        toks = {c.rid: [int(x) for x in np.asarray(c.ids)] for c in comps}
        return gen / wall, toks, metrics

    run(False)  # warm: bucket prefills + the decode step compile
    tps_off, toks_off, _ = run(False)
    tps_on, toks_on, metrics = run(True)
    snap = metrics.snapshot()
    series = (len(snap["counters"]) + len(snap["gauges"])
              + len(snap["hists"]))
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        publish_snapshot(d, 0, metrics, time_s=time.time())
        merge_snapshot_dir(d)
        publish_s = time.perf_counter() - t0
    return {
        "requests": requests, "slots": slots, "max_new_tokens": max_new,
        "tokens_per_sec_off": round(tps_off, 1),
        "tokens_per_sec_on": round(tps_on, 1),
        "overhead_frac": round((tps_off - tps_on) / tps_off, 4)
        if tps_off else None,
        "tokens_bit_identical": toks_off == toks_on,
        "series": series,
        "snapshot_publish_s": round(publish_s, 6),
    }


def bench_serve_dispatch_attribution(cfg, n_dev, requests=32, slots=8,
                                     max_new=16):
    """Per-quantum dispatch-vs-device attribution on a traced serving run
    (round 20): where does a decode quantum's wall actually go — the
    host-side async-dispatch loop (`dispatch_overhead_s`, the [t0,t1]
    walls of the trace's quantum events) or waiting for the device at the
    per-quantum sync (`device_s`, the [s0,s1] walls)? Derived from spans
    the engine times anyway, so the record costs nothing beyond the
    traced run itself. On CPU loopback the "device" is the host too, so
    the split reads as loop-vs-XLA-compute; the per-quantum means are the
    transferable numbers."""
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.obs import TraceRecorder, build_trees, completeness
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size)
    params = init_params(jax.random.PRNGKey(0), cfg)
    buckets = lengths = (8, 16, 24, 32)
    eos = int(tokenizer.eos_token_id)
    stream = list(synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    ))
    serve = ServeConfig(slots=slots, buckets=buckets, max_new_tokens=max_new,
                        window_steps=10**9)

    def run():
        tracer = TraceRecorder()
        eng = ServeEngine(params, cfg, serve, eos_id=eos, tracer=tracer)
        t0 = time.perf_counter()
        comps = eng.run(list(stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        return eng, tracer, comps, wall

    run()  # warm: absorbs compiles so the split reflects steady state
    eng, tracer, comps, wall = run()
    s = eng.last_summary or {}
    quanta = [e for e in tracer.snapshot() if e.get("ev") == "quantum"]
    disp = sum(q["t1"] - q["t0"] for q in quanta)
    dev = sum(q["s1"] - q["s0"] for q in quanta if "s1" in q)
    tot = disp + dev
    trees = build_trees(tracer.snapshot())
    return {
        "requests": requests, "slots": slots, "max_new_tokens": max_new,
        "decode_quantum": serve.decode_quantum,
        "quanta": len(quanta),
        "wall_s": round(wall, 3),
        "dispatch_overhead_s": round(disp, 4),
        "device_s": round(dev, 4),
        "dispatch_frac": round(disp / tot, 4) if tot else None,
        "mean_dispatch_ms_per_quantum": round(1e3 * disp / len(quanta), 3)
        if quanta else None,
        "mean_device_ms_per_quantum": round(1e3 * dev / len(quanta), 3)
        if quanta else None,
        # the summary's span-derived split must agree with the trace's
        "summary_dispatch_overhead_s": round(s.get("dispatch_overhead_s", 0.0), 4),
        "summary_device_s": round(s.get("device_s", 0.0), 4),
        "trace_complete": completeness(trees),
        "completed": len(comps),
    }


def bench_decode_fused(cfg, n_dev, requests=24, slots=4, max_new=12,
                       window=8):
    """Fused-decode ladder (round 21, ROADMAP #2/#4): the two wins behind
    `--fused_decode`, measured SEPARATELY so neither can hide behind the
    other:

      - "unfused_q1" vs "fused_q1" (both at decode_quantum=1): the pure
        KERNEL delta — the per-layer XLA gather+attend against the fused
        paged-attention pallas_call, with the host dispatch cadence held
        identical. On a real TPU this is the no-materialized-view win; on
        CPU loopback the kernel runs in pallas INTERPRET mode (a scan
        over the grid) and is honestly SLOWER — the ratio still lands in
        the record because hiding it would defeat the point.
      - "fused_q1" vs "fused_loop" (decode_quantum=window): the
        DISPATCH-AMORTIZATION delta — the same kernel, but the scheduler
        state machine lives on device and one `while_loop` dispatch
        covers the whole window. The round-20 attribution priced the
        per-quantum host overhead at ~0.3 ms against ~0.7 ms device
        work; this ratio is that attribution cashed in, and because the
        kernel cost is IDENTICAL in numerator and denominator the
        interpret-mode slowness cancels — the amortization number
        transfers from this container.

    Every rung reruns the round-20 trace plumbing (quantum spans carry
    the device-reported tick count for the loop rung), so the record
    cross-checks mean per-quantum dispatch/device walls against the
    `serve_dispatch_attribution` record, and `parity_ok` pins all three
    rungs token-identical per request."""
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.obs import TraceRecorder, build_trees, completeness
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    import jax.numpy as jnp

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    # f32 compute: the parity bit across rungs is exact-token equality
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size,
                      compute_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    buckets = lengths = (8, 16)
    page = 8
    eos = int(tokenizer.eos_token_id)
    stream = list(synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    ))
    pages = slots * (-(-(max(buckets) + max_new) // page)) + 1

    def run(fused, quantum):
        serve = ServeConfig(
            slots=slots, buckets=buckets, max_new_tokens=max_new,
            window_steps=10**9, page_size=page, num_pages=pages,
            fused_decode=fused, decode_quantum=quantum,
        )
        ServeEngine(params, cfg, serve, eos_id=eos).run(
            list(stream), max_wall_s=900)  # warm: absorbs compiles
        tracer = TraceRecorder()
        eng = ServeEngine(params, cfg, serve, eos_id=eos, tracer=tracer)
        t0 = time.perf_counter()
        comps = eng.run(list(stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        quanta = [e for e in tracer.snapshot() if e.get("ev") == "quantum"]
        disp = sum(q["t1"] - q["t0"] for q in quanta)
        dev = sum(q["s1"] - q["s0"] for q in quanta if "s1" in q)
        rec = {
            "tokens_per_sec": round(gen / wall, 1),
            "wall_s": round(wall, 3),
            "generated_tokens": gen,
            "quanta": len(quanta),
            "decode_steps": eng.steps,
            "mean_dispatch_ms_per_quantum": round(1e3 * disp / len(quanta), 3)
            if quanta else None,
            "mean_device_ms_per_quantum": round(1e3 * dev / len(quanta), 3)
            if quanta else None,
            "trace_complete": completeness(build_trees(tracer.snapshot())),
        }
        return rec, {c.rid: list(map(int, c.ids)) for c in comps}

    unfused, toks_u = run(False, 1)
    fused_q1, toks_f1 = run(True, 1)
    fused_loop, toks_fl = run(True, window)
    return {
        "requests": requests, "slots": slots, "max_new_tokens": max_new,
        "page_size": page, "window_quanta": window,
        "unfused_q1": unfused, "fused_q1": fused_q1,
        "fused_loop": fused_loop,
        "parity_ok": bool(toks_u == toks_f1 == toks_fl),
        # the kernel win (interpret-mode CPU: expect < 1, stated honestly)
        "kernel_speedup": round(
            fused_q1["tokens_per_sec"] / unfused["tokens_per_sec"], 3)
        if unfused["tokens_per_sec"] else None,
        # the dispatch-amortization win (kernel cost cancels: transfers)
        "amortization_speedup": round(
            fused_loop["tokens_per_sec"] / fused_q1["tokens_per_sec"], 3)
        if fused_q1["tokens_per_sec"] else None,
    }


def bench_paged_kv(cfg, n_dev, requests=24, max_new=12, slots=4):
    """Paged-KV ladder (round 15, ROADMAP #2): ring vs paged vs paged+int8
    at EQUAL KV HBM, on the same seeded stream.

    The ring rung is the round-14 engine (per-slot full-width KV). The
    paged rungs get a page pool sized to the ring's exact byte budget
    (`serve.paged.pool_bytes`), so every difference is layout, not a
    bigger memory grant:

      - "paged" (f32 pages, same slot count): the parity rung — tokens
        must be identical to the ring rung per request (`parity_ok`, the
        acceptance bar's exactness bit) at ~equal throughput.
      - "paged_int8": pages cost ~1/4 the bytes (int8 payload + packed
        f32 block scales), so the same HBM holds ~4x pages; lanes are
        raised to 4x the ring slots and `max_live_slots` measures how
        many requests actually decode CONCURRENTLY — the >= 2x
        slots-at-equal-HBM acceptance bar, with `int8_token_agreement`
        (mean per-request match vs the exact paged rung) as the honest
        quality sidecar.

    The prefix rung re-serves the paged config on a stream whose requests
    share one system prompt: admissions that hit the prefix registry skip
    the shared prefill chunks, and the record carries measured
    hit-vs-cold admit latency plus the hit count."""
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream
    from tpukit.serve import paged as paged_lib

    import jax.numpy as jnp

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    # f32 compute for the whole ladder: the ring stores the COMPUTE dtype
    # while pages store kv_dtype, so a bf16 ring against f32 pages would
    # dtype-confound the equal-HBM sizing (half the token capacity for
    # the parity rung, ~2x instead of ~4x pages for int8) — at f32 the
    # ring and the f32-page rung are byte-comparable and the int8 ratio
    # is the honest payload win.
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size,
                      compute_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    buckets = lengths = (8, 16)
    page = 8  # page * head_dim is a 256 multiple at the ladder head_dim=32
    eos = int(tokenizer.eos_token_id)
    stream = synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    )

    def run(serve, reqs):
        ServeEngine(params, cfg, serve, eos_id=eos).run(list(reqs), max_wall_s=900)
        eng = ServeEngine(params, cfg, serve, eos_id=eos)  # measured: warm jits
        t0 = time.perf_counter()
        comps = eng.run(list(reqs), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        s = eng.last_summary
        rec = dict(
            tokens_per_sec=round(gen / wall, 1), wall_s=round(wall, 3),
            generated_tokens=gen, slots=serve.slots,
            max_live_slots=s["max_live_slots"], kv_bytes=s["kv_bytes"],
        )
        return rec, {c.rid: list(map(int, c.ids)) for c in comps}, s

    ring_cfg = ServeConfig(slots=slots, buckets=buckets,
                           max_new_tokens=max_new, window_steps=10**9)
    ring, ring_toks, _ = run(ring_cfg, stream)

    per_page_f32 = paged_lib.pool_bytes(cfg, 1, page, "f32")
    per_page_int8 = paged_lib.pool_bytes(cfg, 1, page, "int8")
    min_pages = -(-(max(buckets) + max_new) // page) + 1  # one request + null
    paged_cfg = ServeConfig(
        slots=slots, buckets=buckets, max_new_tokens=max_new,
        window_steps=10**9, page_size=page,
        num_pages=max(ring["kv_bytes"] // per_page_f32, min_pages),
    )
    paged, paged_toks, _ = run(paged_cfg, stream)
    parity = ring_toks == paged_toks

    int8_cfg = ServeConfig(
        slots=4 * slots, buckets=buckets, max_new_tokens=max_new,
        window_steps=10**9, page_size=page, kv_dtype="int8",
        num_pages=max(ring["kv_bytes"] // per_page_int8, min_pages),
    )
    int8, int8_toks, _ = run(int8_cfg, stream)
    agree = [
        float(np.mean(np.asarray(int8_toks[r][:m]) == np.asarray(paged_toks[r][:m])))
        for r in paged_toks
        for m in [min(len(int8_toks[r]), len(paged_toks[r]))]
        if m
    ]

    shared = synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths, shared_prefix=page,
    )
    _, _, psum = run(paged_cfg, shared)
    return {
        "requests": requests, "buckets": list(buckets), "page_size": page,
        "max_new_tokens": max_new,
        "ring": ring, "paged": paged, "paged_int8": int8,
        "parity_ok": bool(parity),
        "int8_token_agreement": round(float(np.mean(agree)), 4) if agree else None,
        "slots_at_equal_hbm_ratio": round(
            int8["max_live_slots"] / max(ring["max_live_slots"], 1), 2
        ),
        "prefix": {
            "hits": psum.get("prefix_hits"),
            "hit_rate": psum.get("prefix_hit_rate"),
            "pages_reused": psum.get("prefix_pages_reused"),
            "admit_latency_hit_s": psum.get("admit_latency_hit_s"),
            "admit_latency_cold_s": psum.get("admit_latency_cold_s"),
        },
    }


def bench_fleet_serving(cfg, n_dev, requests=32, slots=4, max_new=12):
    """Fleet scaling curve (round 19, ROADMAP #1): 1 vs 2 vs 4 engine
    replicas on the SAME seeded stream at EQUAL total devices — the
    router's capacity story. Each rung carves the device list into
    disjoint per-replica subsets (8 devices = 1x8, 2x4, 4x2; grids from
    `fleet.pick_serve_grid`), serves the identical stream, and reports
    fleet tokens/s, p99 e2e under load, and per-request token parity vs
    the 1-replica rung (the fleet bar: routing must never change a
    token). The 2-replica rung is the acceptance rung (>1.5x the
    1-replica tokens/s at equal total devices).

    The second half measures DISAGGREGATED vs COLOCATED prefill on the
    2-replica paged configuration over a shared-system-prompt stream:
    mean admit latency (slot-assignment to decode-ready — what moving
    prefill off the decode replicas buys them) plus handoff/prefix-hit
    counts.

    HONEST CPU CAVEAT (in-record as `caveat`, the comm_overlap
    discipline): on virtual CPU devices the per-replica "grids" share
    host cores and collectives are loopback memcpys, so the scaling
    curve measures the ROUTER (scheduling, admission, dispatch overlap
    across subsets), not interconnect physics; on real chips the
    per-replica model-parallel speedup stacks on top. With fewer than 4
    devices the rungs run meshless replicas (router identical, grids
    trivial)."""
    import time

    import jax

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.serve import (
        FleetConfig,
        FleetRouter,
        ServeConfig,
        synthetic_request_stream,
    )

    import jax.numpy as jnp

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    cfg = cfg.replace(vocab_size=tokenizer.vocab_size,
                      compute_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    buckets = lengths = (8, 16)
    eos = int(tokenizer.eos_token_id)
    stream = synthetic_request_stream(
        tokenizer, requests, seed=0, max_new_tokens=max_new,
        buckets=buckets, lengths=lengths,
    )
    serve = ServeConfig(slots=slots, buckets=buckets, max_new_tokens=max_new,
                        window_steps=10**9)
    meshed = n_dev >= 4

    def run_fleet(n_replicas, fleet_kw=None, serve_cfg=None, reqs=None):
        fc = FleetConfig(
            replicas=n_replicas,
            devices_per_replica=(n_dev // n_replicas) if meshed else 0,
            window_steps=10**9, **(fleet_kw or {}),
        )
        sv = serve_cfg or serve
        FleetRouter(host, cfg, sv, fc, eos_id=eos).run(
            list(reqs or stream), max_wall_s=900)  # warm compiles
        router = FleetRouter(host, cfg, sv, fc, eos_id=eos)
        t0 = time.perf_counter()
        comps = router.run(list(reqs or stream), max_wall_s=900)
        wall = time.perf_counter() - t0
        gen = sum(c.generated for c in comps)
        e2e = np.asarray([c.e2e_s for c in comps])
        admit = [c.admit_latency_s for c in comps]
        return dict(
            replicas=n_replicas,
            devices_per_replica=fc.devices_per_replica,
            tokens_per_sec=round(gen / wall, 1), wall_s=round(wall, 3),
            generated_tokens=gen,
            p50_e2e_s=round(float(np.percentile(e2e, 50)), 4),
            p99_e2e_s=round(float(np.percentile(e2e, 99)), 4),
            mean_admit_latency_s=round(float(np.mean(admit)), 5),
        ), {c.rid: list(map(int, c.ids)) for c in comps}, router.last_summary

    rungs, toks = [], {}
    for n_replicas in (1, 2, 4):
        if n_replicas > max(requests, 1):
            continue
        try:
            rec, t, _ = run_fleet(n_replicas)
            rungs.append(rec)
            toks[n_replicas] = t
        except Exception as exc:  # per-rung failures land in-record
            rungs.append({"replicas": n_replicas, "error": repr(exc)})
    parity = (1 in toks) and all(toks[n] == toks[1] for n in toks)
    by_n = {r["replicas"]: r for r in rungs if "error" not in r}
    scaling = (
        round(by_n[2]["tokens_per_sec"] / by_n[1]["tokens_per_sec"], 2)
        if 1 in by_n and 2 in by_n and by_n[1]["tokens_per_sec"] else None
    )

    # disaggregated vs colocated prefill: 2 replicas, paged pools, one
    # shared system prompt — what a dedicated prefill worker buys the
    # decode replicas' admit latency
    disagg = None
    try:
        page = 8
        paged_cfg = ServeConfig(
            slots=slots, buckets=buckets, max_new_tokens=max_new,
            window_steps=10**9, page_size=page,
        )
        shared = synthetic_request_stream(
            tokenizer, requests, seed=0, max_new_tokens=max_new,
            buckets=buckets, lengths=lengths, shared_prefix=page,
        )
        colo, _, _ = run_fleet(2, serve_cfg=paged_cfg, reqs=shared)
        dis, _, dsum = run_fleet(
            2, fleet_kw=dict(disagg_prefill=True), serve_cfg=paged_cfg,
            reqs=shared,
        )
        dp = (dsum or {}).get("disagg_prefill") or {}
        disagg = dict(
            colocated_admit_latency_s=colo["mean_admit_latency_s"],
            disagg_admit_latency_s=dis["mean_admit_latency_s"],
            colocated_tokens_per_sec=colo["tokens_per_sec"],
            disagg_tokens_per_sec=dis["tokens_per_sec"],
            handoffs=dp.get("handoffs"),
            worker_prefix_hits=dp.get("worker_prefix_hits"),
        )
    except Exception as exc:
        disagg = {"error": repr(exc)}

    return {
        "requests": requests, "slots_per_replica": slots,
        "buckets": list(buckets), "max_new_tokens": max_new,
        "total_devices": n_dev, "meshed": meshed,
        "rungs": rungs,
        "parity_ok": bool(parity),
        "scaling_2x_vs_1": scaling,
        "disagg_prefill": disagg,
        "caveat": (
            "CPU virtual devices: per-replica grids share host cores and "
            "collectives are loopback memcpys — the curve measures router "
            "scheduling + dispatch overlap, not interconnect physics"
            + ("" if meshed else "; <4 devices, so rungs ran MESHLESS "
               "replicas (trivial grids)")
        ),
    }


def _induction_train(cfg, tokenizer, steps, row_len, lr=3e-3, seed=7,
                     batch=8):
    """Train `cfg` on tiled-phrase rows — the `repetitive` stream profile
    as training data — so greedy decode learns induction (continue the
    repetition). Three details are load-bearing, all measured in
    round 17: (1) 2+ layers are the induction-head minimum; (2) `row_len`
    must cover the SERVING position range (prompt + decode budget +
    verify scratch) — position embeddings beyond the trained range are
    noise, and greedy continuations wander exactly there (acceptance
    0.34 vs 0.85 with the range covered); (3) the phrases must come from
    the DISTRIBUTION the serving stream tiles — short heads of the
    corpus stories, the templated-traffic family — not uniform random
    tokens: the acceptance rate is 0.30 (speedup 0.76x, speculation
    loses) with random-token phrases vs 0.99 (2.1x) in-domain, because
    greedy continuation of a repetition the model has never seen the
    token statistics of is exactly where it wanders. The training draws
    use their own seed, not the stream's — in-domain, not
    memorize-the-eval. Returns (state, final_loss) — the full train
    state so `tools/train_induction.py` can checkpoint it for the CI
    spec serve-smoke; bench rungs read `state.params`."""
    import optax

    from tools.bench_ladder import setup_step
    from tpukit.data import synthetic_stories

    # cosine decay to ~0: at a constant lr the greedy loops this probe
    # depends on stay fragile — the loss bounces around 0.1 and the
    # acceptance rate with it (measured 0.54..0.85 across retrains); a
    # decayed finish converges the induction behavior reproducibly
    step_fn, state, _, _ = setup_step(
        cfg, lr=optax.cosine_decay_schedule(lr, steps)
    )
    rng0 = np.random.RandomState(seed)
    enc = tokenizer(synthetic_stories(128), truncation=True,
                    max_length=8)["input_ids"]
    rows = []
    while len(rows) < 512:
        head = enc[rng0.randint(len(enc))]
        plen = min(int(rng0.randint(2, 5)), len(head))
        if plen < 2:
            continue
        phrase = np.asarray(head[:plen], np.int32)
        rows.append(np.tile(phrase, -(-(row_len + 1) // plen))[: row_len + 1])
    data = np.asarray(rows, np.int32)
    pos = np.ascontiguousarray(np.broadcast_to(
        np.arange(row_len, dtype=np.int32), (batch, row_len)))
    rng = np.random.RandomState(0)
    for _ in range(steps):
        idx = rng.randint(0, len(data), size=batch)
        mb = {"input_ids": data[idx, :row_len], "position_ids": pos,
              "mask": np.zeros((batch, row_len), dtype=bool)}
        state, loss = step_fn(state, mb, data[idx, 1 : row_len + 1])
    return state, float(loss)


def bench_spec_decode(cfg, n_dev, requests=24, slots=4, max_new=48, k=10):
    """Speculative decoding vs the vanilla engine (round 17, ROADMAP #3),
    end to end on the SAME seeded `repetitive` synthetic stream.

    Speculation is an optimization exactly when the target's next tokens
    are predictable, so the probe first makes them predictable the honest
    way: it TRAINS the target (and a smaller draft) into the regime
    templated/structured serving traffic puts a real model in — greedy
    loops that prompt-lookup drafting predicts (`_induction_train`). A
    random-init target accepts ~nothing and speculation rightly LOSES;
    that regime is visible in the CI serve smoke, not benched here.

    Rungs at temperature 0 and 0.8, each proposer vs the vanilla engine
    (all warm — engines constructed twice, second run measured, the
    round-14 serving-bench pattern): end-to-end tokens/s, acceptance
    rate, the appended-tokens-per-verify histogram, and the draft/verify
    wall split. `speedup` per rung is vs the SAME-temperature vanilla
    run. The acceptance bar is self-spec (ngram) at temperature 0
    >= 1.3x: the fused on-device proposal (spec.spec_ngram_step) keeps
    the host rhythm of one dispatch + one sync per quantum, so the win
    is k+1 tokens of emission capacity per target forward.

    k=10 because the verify dispatch is FIXED-COST dominated at bench
    shape on this backend (measured: 4.5 ms at k=8 vs 4.9 ms at k=12,
    vs 0.9 ms per one-token decode dispatch and the vanilla engine's
    decode_quantum=4 amortization) — a narrow window (k=6) caps the
    arithmetic at ~1.1x however high acceptance goes, while the
    induction-trained target's ~0.97 per-token greedy-match rate keeps
    the accepted prefix long enough for a wide window to pay."""
    import time

    import jax
    import jax.numpy as jnp

    from tpukit.data import get_tokenizer
    from tpukit.model import init_params
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2
    buckets = (16, 32)
    # serving positions: bucket 32 + 48 new + k scratch = 86
    row_len = max(buckets) + max_new + k + 2
    tgt_cfg = cfg.replace(
        dim=128, head_dim=32, heads=4, num_layers=4,
        vocab_size=tokenizer.vocab_size, max_position_embeddings=128,
        compute_dtype=jnp.float32, num_experts=0,
    )
    draft_cfg = tgt_cfg.replace(dim=32, head_dim=16, heads=2, num_layers=2)
    t0 = time.perf_counter()
    tgt_state, tgt_loss = _induction_train(tgt_cfg, tokenizer, 900, row_len)
    params = tgt_state.params
    draft_state, draft_loss = _induction_train(
        draft_cfg, tokenizer, 1500, row_len
    )
    draft_params = draft_state.params
    train_s = time.perf_counter() - t0
    eos = int(tokenizer.eos_token_id)
    stream = synthetic_request_stream(
        tokenizer, requests, seed=3, max_new_tokens=max_new,
        buckets=buckets, stream_profile="repetitive",
    )

    def run(draft, temperature):
        serve = ServeConfig(
            slots=slots, buckets=buckets, max_new_tokens=max_new,
            temperature=temperature, window_steps=10**9,
            draft=draft, spec_k=k,
        )
        kw = (dict(draft_params=draft_params, draft_cfg=draft_cfg)
              if draft == "model" else {})
        ServeEngine(params, tgt_cfg, serve, eos_id=eos, **kw).run(
            list(stream), max_wall_s=900)  # warm: compiles absorbed
        # steady state = best of 3 measured runs (the time_windows
        # min-of-windows convention — this shared CPU shows double-digit
        # run-to-run variance, and a ratio of two noisy walls is noisier
        # still); token streams are seed-deterministic, so every run
        # generates the identical tokens and only the wall moves
        walls = []
        for _ in range(3):
            eng = ServeEngine(params, tgt_cfg, serve, eos_id=eos, **kw)
            t0 = time.perf_counter()
            comps = eng.run(list(stream), max_wall_s=900)
            walls.append(time.perf_counter() - t0)
        wall = min(walls)
        gen = sum(c.generated for c in comps)
        out = dict(tokens_per_sec=round(gen / wall, 1),
                   wall_s=round(wall, 3),
                   wall_spread_s=round(max(walls) - wall, 3),
                   generated_tokens=gen, verify_steps=eng.steps)
        if draft:
            s = (eng.last_summary or {}).get("spec") or {}
            out.update(
                accept_rate=round(s["accept_rate"], 4)
                if s.get("accept_rate") is not None else None,
                proposed=s.get("proposed"), accepted=s.get("accepted"),
                accepted_hist=s.get("accepted_hist"),
                draft_s=round((eng.last_summary or {}).get("draft_s", 0.0), 3),
                verify_s=round((eng.last_summary or {}).get("verify_s", 0.0), 3),
            )
        return out

    rec = {
        "requests": requests, "slots": slots, "spec_k": k,
        "max_new_tokens": max_new, "buckets": list(buckets),
        "stream_profile": "repetitive",
        "train": {
            "target_loss": round(tgt_loss, 4),
            "draft_loss": round(draft_loss, 4),
            "train_s": round(train_s, 1),
        },
    }
    for label, temp in (("t0", 0.0), ("t0.8", 0.8)):
        van = run("", temp)
        rung = {"vanilla": van}
        for d in ("ngram", "model"):
            r = run(d, temp)
            r["speedup"] = (round(r["tokens_per_sec"] / van["tokens_per_sec"], 2)
                            if van["tokens_per_sec"] else None)
            rung[d] = r
        rec[label] = rung
    rec["speedup_ngram_t0"] = rec["t0"]["ngram"]["speedup"]
    return rec


def bench_quant_comm(cfg, n_dev, num_experts=8, steps=8):
    """Quantized-collective ladder (round 12, ROADMAP #2): f32 vs bf16 vs
    int8 `--comm_dtype` on each strategy with hand-wired quantized
    collectives — ddp (grad all-reduce), fsdp (grad reduce-scatter), ep
    (a2a dispatch payload). Each rung compiles the train step under a
    compiler-stderr capture and reports:

      - expected vs measured quantized payload bytes (the closed-form
        `grad_comm`/`dispatch_comm` numbers against the optimized HLO) and
        whether they match exactly;
      - ring-model bytes-on-the-wire (`obs.wire_bytes` — result payloads
        are not comparable across op KINDS, an all-reduce moves ~2x its
        result) plus the ratio vs the rung's f32 baseline: the ~4x cut is
        THE headline this record exists to publish;
      - involuntary-remat warning count (zero = the schedule did not
        change, only the payload — meaningful on cold compiles);
      - tokens/s/chip and the final-loss delta vs the f32 rung after
        `steps` identical steps — the tolerance-gate number (bit parity is
        impossible by construction; a small bounded delta is the
        correctness contract).

    On one chip the data/expert axes are 1-way: the wrappers keep the
    quantize/dequantize numerics but skip the collectives, so expected
    bytes are honestly zero rather than faked."""
    import math

    import jax

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.mesh import create_mesh
    from tpukit.obs import (
        capture_compiler_stderr,
        collective_bytes,
        wire_bytes,
    )
    from tpukit.shardings import DataParallel, ExpertParallel, FSDP

    seq = cfg.max_position_embeddings
    batch = 32 * n_dev
    expert = math.gcd(n_dev, num_experts)
    backend = jax.default_backend()
    struct = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731

    rungs = [
        ("ddp", lambda: DataParallel(create_mesh({"data": n_dev})),
         lambda dt: cfg.replace(comm_dtype=dt), n_dev),
        ("fsdp", lambda: FSDP(create_mesh({"data": n_dev})),
         lambda dt: cfg.replace(comm_dtype=dt), n_dev),
        ("ep", lambda: ExpertParallel(
            create_mesh({"data": n_dev // expert, "expert": expert}),
            dispatch="a2a"),
         lambda dt: cfg.replace(comm_dtype=dt, num_experts=num_experts),
         expert),
    ]
    rows = []
    for name, strat_fn, cfg_fn, world in rungs:
        f32_loss = f32_wire = None
        for dtype in ("f32", "bf16", "int8"):
            try:
                c = cfg_fn(dtype)
                strat = strat_fn()
                strat.validate_config(c)
                b, t = make_batch(
                    np.random.RandomState(5), cfg.vocab_size, batch, seq - 1
                )
                with capture_compiler_stderr() as cap:
                    step, state, shapes, _ = setup_step(c, strat)
                    compiled = step.lower(
                        shapes, jax.tree.map(struct, b), struct(t)
                    ).compile()
                coll = collective_bytes(compiled.as_text())
                if name == "ep":
                    # the EP rung's wire number AND its expectation isolate
                    # the a2a dispatch payload: the trunk's FSDP comm is
                    # identical across rungs (full precision by design) and
                    # would bury the dispatch cut in a shared constant
                    wire = wire_bytes(
                        {"all-to-all": coll.get("all-to-all")
                         or {"count": 0, "bytes": 0}},
                        world,
                    )
                    audit = strat.dispatch_comm(
                        c, global_batch=batch, seq=seq - 1, backend=backend
                    )
                    expected = (
                        {"all-to-all": {
                            "count": audit["train"]["count"],
                            "bytes": audit["train"]["bytes"],
                        }}
                        if audit
                        else None
                    )
                else:
                    wire = wire_bytes(coll, world)
                    expected = strat.grad_comm(c, shapes.params, backend=backend)
                exact = None
                if expected:
                    exact = all(
                        (coll.get(op) or {"count": 0, "bytes": 0}) == rec
                        for op, rec in expected.items()
                    )
                times, state, loss = time_windows(
                    compiled, state, b, t, steps=steps, windows=3, warmup=2
                )
                del state
                row = {
                    "strategy": name,
                    "comm_dtype": dtype,
                    "wire_bytes": wire,
                    "expected": expected,
                    "measured": {
                        op: coll.get(op)
                        for op in (expected or {})
                        if coll.get(op)
                    } or None,
                    "bytes_match": exact,
                    "involuntary_remat_warnings": cap["involuntary_remat"],
                    "tokens_per_sec_per_chip": round(
                        steps * batch * (seq - 1) / min(times) / n_dev, 1
                    ),
                    "final_loss": round(loss, 6),
                }
                if dtype == "f32":
                    f32_loss, f32_wire = loss, wire
                else:
                    row["loss_delta_vs_f32"] = (
                        round(loss - f32_loss, 6) if f32_loss is not None else None
                    )
                    row["wire_ratio_vs_f32"] = (
                        round(wire / f32_wire, 4) if f32_wire else None
                    )
                rows.append(row)
            except Exception as exc:
                rows.append(
                    {"strategy": name, "comm_dtype": dtype, "error": repr(exc)}
                )
                print(
                    f"quant comm rung {name}/{dtype} failed: {exc!r}",
                    file=sys.stderr,
                )
    return rows


def bench_comm_overlap(cfg, n_dev, num_experts=8, steps=8):
    """Overlap-scheduled collectives ladder (round 18, ROADMAP #5):
    step-time at f32 (serial) vs int8 (serial — the round-12 wire cut)
    vs int8 + --grad_buckets 4 (the overlap schedule) on the DDP, FSDP
    and EP worlds, so the wire cut and the overlap win are SEPARATELY
    visible. Each rung compiles cold under a compiler-stderr capture and
    reports:

      - step_time_s (best window / steps) and tokens/s/chip — the
        wall-clock observable. NOTE the honest caveat: on CPU virtual
        devices the collectives are loopback memcpys, so the overlap
        rung's wall win is noise-bounded; the schedule PROPERTY is the
        gated signal (below), the times are the observable a real
        multi-chip run compares;
      - the promoted hlolint `overlap` verdict on the overlap rung:
        declared vs overlappable bucket wires and `overlap_frac` =
        overlappable/declared (1.0 = every bucket wire independently
        schedulable) — the number tools/report.py's --min_overlap_frac
        gate checks;
      - bytes_match: measured collectives == the per-bucket closed form;
      - involuntary-remat warnings (zero = schedule intact, cold only);
      - final loss + delta vs the rung's f32 serial baseline (the
        round-12 tolerance-gate number; the f32 bucket schedule itself
        is bit-identical across bucket counts, tests/test_overlap.py).
    """
    import math

    import jax

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.analysis import (
        collective_summary, lint_module, parse_hlo, summarize,
        train_comm_plan,
    )
    from tpukit.mesh import create_mesh
    from tpukit.obs import capture_compiler_stderr
    from tpukit.shardings import DataParallel, ExpertParallel, FSDP

    seq = cfg.max_position_embeddings
    batch = 32 * n_dev
    expert = math.gcd(n_dev, num_experts)
    backend = jax.default_backend()
    struct = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731

    rungs = [
        ("ddp", lambda: DataParallel(create_mesh({"data": n_dev}))),
        ("fsdp", lambda: FSDP(create_mesh({"data": n_dev}))),
        ("ep", lambda: ExpertParallel(
            create_mesh({"data": n_dev // expert, "expert": expert}),
            dispatch="a2a")),
    ]
    rows = []
    for name, strat_fn in rungs:
        f32_loss = f32_step = None
        for dtype, buckets in (("f32", 0), ("int8", 0), ("int8", 4)):
            try:
                c = cfg.replace(
                    comm_dtype=dtype, grad_buckets=buckets,
                    num_experts=num_experts if name == "ep" else 0,
                )
                strat = strat_fn()
                strat.validate_config(c)
                b, t = make_batch(
                    np.random.RandomState(5), cfg.vocab_size, batch, seq - 1
                )
                with capture_compiler_stderr() as cap:
                    step, state, shapes, _ = setup_step(c, strat)
                    compiled = step.lower(
                        shapes, jax.tree.map(struct, b), struct(t)
                    ).compile()
                # render + parse ONCE (the round-16 discipline): the byte
                # audit and the lint share one module
                module = parse_hlo(compiled.as_text())
                coll = collective_summary(module)
                plan = train_comm_plan(
                    strat, c, param_shapes=shapes.params,
                    global_batch=batch, seq=seq - 1, backend=backend,
                )
                exact = None
                if plan is not None and plan.ops:
                    exact = all(
                        (coll.get(op) or {"count": 0, "bytes": 0}) == rec
                        for op, rec in plan.ops.items()
                    )
                overlap = None
                if plan is not None and plan.overlap:
                    verdict = summarize(lint_module(
                        module, plan=plan,
                        compiler_stderr=cap["text"], backend=backend,
                    ))
                    gate = verdict.get("overlap_gate") or {}
                    declared = gate.get("declared") or 0
                    overlap = {
                        "declared": declared,
                        "overlappable": gate.get("overlappable", 0),
                        # capped at 1.0: EP measures MORE overlappable
                        # wires than its (backward-hops-only) declaration
                        "overlap_frac": (
                            round(min(
                                1.0, gate.get("overlappable", 0) / declared
                            ), 4)
                            if declared else None
                        ),
                        "gate_ok": gate.get("ok"),
                        "clean": verdict["clean"],
                    }
                times, state, loss = time_windows(
                    compiled, state, b, t, steps=steps, windows=3, warmup=2
                )
                del state
                step_time = min(times) / steps
                row = {
                    "strategy": name,
                    "comm_dtype": dtype,
                    "grad_buckets": buckets,
                    "step_time_s": round(step_time, 6),
                    "tokens_per_sec_per_chip": round(
                        batch * (seq - 1) / step_time / n_dev, 1
                    ),
                    "bytes_match": exact,
                    "overlap": overlap,
                    "involuntary_remat_warnings": cap["involuntary_remat"],
                    "final_loss": round(loss, 6),
                }
                if dtype == "f32" and buckets == 0:
                    f32_loss, f32_step = loss, step_time
                else:
                    row["loss_delta_vs_f32"] = (
                        round(loss - f32_loss, 6)
                        if f32_loss is not None else None
                    )
                    row["step_time_vs_f32"] = (
                        round(step_time / f32_step, 4) if f32_step else None
                    )
                rows.append(row)
            except Exception as exc:
                rows.append({
                    "strategy": name, "comm_dtype": dtype,
                    "grad_buckets": buckets, "error": repr(exc),
                })
                print(
                    f"comm overlap rung {name}/{dtype}/b{buckets} failed: "
                    f"{exc!r}",
                    file=sys.stderr,
                )
    return rows


def bench_pipe_interleave(n_dev, steps=3, micro=8):
    """Interleaved-1F1B ladder (round 25, --virtual_stages): the flat
    1F1B tick machine vs V=2 and V=4 virtual chunks per device at EQUAL
    micro-batch count. Two kinds of numbers, kept apart on purpose:

      - `bubble_table` + per-rung `bubble_frac`: weighted idle-phase
        accounting straight off the tick table (pipeline_schedule.py,
        backward at 2x forward cost; the V=1 row is the closed form
        (2S-2)/(M+2S-2)). Deterministic, backend-free — the numbers
        tools/report.py's --min_bubble_gain gate pins, because on CPU
        virtual devices wall-clock is loopback noise (the
        --min_overlap_frac discipline).
      - per-rung step time / tokens/s/chip and `wall_ratio_vs_flat` vs
        `predicted_ratio_vs_flat` (schedule cost in forward-units, a
        chunk being 1/V of a flat stage pass): the wall cross-check a
        real multi-chip run compares. On CPU the unrolled machine's
        per-tick dispatch overhead dilutes the predicted win.

    Rungs that fail land as {"virtual_stages": V, "error": ...} so a
    machine that stops compiling cannot hide behind the pure-math table
    (the gate fails on errored rungs)."""
    import jax.numpy as jnp

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.mesh import create_mesh
    from tpukit.model import GPTConfig
    from tpukit.pipeline import Pipeline1F1B
    from tpukit.pipeline_schedule import (
        bubble_table, cached_schedule, flat_1f1b_bubble,
    )

    stages = 4 if n_dev >= 4 else 2
    layers = 4 * stages  # V=4 needs S*V chunks <= layers
    seq = 128
    cfg_p = GPTConfig(
        dim=128, head_dim=32, heads=4, num_layers=layers, vocab_size=8192,
        max_position_embeddings=seq, compute_dtype=jnp.bfloat16,
    )
    batch = 2 * micro  # two rows per micro-batch
    record = {
        "stages": stages,
        "microbatches": micro,
        "layers": layers,
        # the measured-bubble grid the gate checks: V x M, tick-table
        # accounting (V=1 rows are the closed form)
        "bubble_table": bubble_table(stages),
        "rungs": [],
        "caveat": (
            "CPU loopback: per-tick dispatch overhead dilutes the "
            "schedule win; bubble_frac/predicted_ratio are the "
            "backend-transferable numbers"
        ),
    }
    # schedule cost in forward-units: flat runs fwd+bwd EVERY tick (its
    # idle ticks still compute garbage), interleaved only on live phases
    # at 1/V the per-tick work
    flat_cost = 3.0 * (micro + 2 * stages - 2)
    flat_step = None
    for v in (1, 2, 4):
        try:
            if v == 1:
                bubble = flat_1f1b_bubble(stages, micro)
                cost = flat_cost
            else:
                st = cached_schedule(stages, v, micro).stats
                bubble = st["bubble_frac"]
                cost = (st["fwd_phase_ticks"]
                        + 2.0 * st["bwd_phase_ticks"]) / v
            strat = Pipeline1F1B(
                create_mesh({"stage": stages}), num_microbatches=micro
            )
            c = cfg_p.replace(virtual_stages=v)
            strat.validate_config(c)
            b, t = make_batch(np.random.RandomState(5), c.vocab_size,
                              batch, seq)
            step, state, _, _ = setup_step(c, strat)
            times, state, loss = time_windows(
                step, state, b, t, steps=steps, windows=3, warmup=2
            )
            del state
            step_time = min(times) / steps
            row = {
                "virtual_stages": v,
                "bubble_frac": round(bubble, 4),
                "sched_cost_units": round(cost, 2),
                "predicted_ratio_vs_flat": round(cost / flat_cost, 4),
                "step_time_s": round(step_time, 6),
                "tokens_per_sec_per_chip": round(
                    batch * seq / step_time / stages, 1
                ),
                "final_loss": round(loss, 6),
            }
            if v == 1:
                flat_step = step_time
            else:
                row["wall_ratio_vs_flat"] = (
                    round(step_time / flat_step, 4) if flat_step else None
                )
            record["rungs"].append(row)
        except Exception as exc:
            record["rungs"].append(
                {"virtual_stages": v, "error": repr(exc)}
            )
            print(f"pipe interleave rung V={v} failed: {exc!r}",
                  file=sys.stderr)
    return record


def bench_pipe_moe(n_dev, micro=4, steps=3):
    """Pipeline x MoE composition rung (round 25): the interleaved 1F1B
    machine with 8 experts through the meshless dropless pallas dispatch
    — the ONE legal pipeline MoE dataflow — against the single-device
    run of the identical per-micro objective (CE + aux, f32). The
    parity bit is the record's point; tokens/s/chip rides along as the
    observable. A buffer dispatch leaking in shows up as an hlolint
    a2a-free violation (pipe_moe world), not here."""
    import jax.numpy as jnp

    from tools.bench_ladder import make_batch, setup_step, time_windows
    from tpukit.mesh import create_mesh
    from tpukit.model import GPTConfig
    from tpukit.pipeline import Pipeline1F1B
    from tpukit.shardings import SingleDevice

    stages = 2
    if n_dev < stages:
        raise ValueError("pipe_moe rung needs >= 2 devices")
    seq = 64
    cfg_m = GPTConfig(
        dim=64, head_dim=16, heads=4, num_layers=8, vocab_size=1024,
        max_position_embeddings=seq, compute_dtype=jnp.float32,
        num_experts=8, moe_dispatch="pallas", virtual_stages=2,
    )
    batch = 2 * micro
    b, t = make_batch(np.random.RandomState(5), cfg_m.vocab_size, batch, seq)

    # single-device reference: same params (same init key), same
    # objective — the pipeline's per-micro CE+aux at f32 must match to
    # float tolerance
    step_ref, state_ref, _, _ = setup_step(
        cfg_m.replace(virtual_stages=1), SingleDevice()
    )
    state_ref, ref_loss = step_ref(state_ref, b, t)
    ref_loss = float(ref_loss)
    del state_ref

    strat = Pipeline1F1B(
        create_mesh({"stage": stages}), num_microbatches=micro,
        moe_dispatch="pallas",
    )
    step, state, _, _ = setup_step(cfg_m, strat)
    state, loss = step(state, b, t)
    loss = float(loss)
    times, state, _ = time_windows(
        step, state, b, t, steps=steps, windows=2, warmup=1
    )
    del state
    delta = abs(loss - ref_loss)
    return {
        "stages": stages,
        "virtual_stages": 2,
        "microbatches": micro,
        "num_experts": 8,
        "dispatch": "pallas",
        "loss": round(loss, 6),
        "ref_loss": round(ref_loss, 6),
        "loss_delta": round(delta, 8),
        "parity_ok": bool(delta < 1e-4),
        "tokens_per_sec_per_chip": round(
            steps * batch * seq / min(times) / stages, 1
        ),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--compilation_cache_dir",
        default=os.environ.get("TPUKIT_COMPILE_CACHE_DIR", ".jax_cache"),
        help="persistent XLA compile cache ('' disables); repeat runs skip "
        "recompiles and the JSON reports hits/misses",
    )
    ap.add_argument(
        "--moe_dispatch",
        choices=("xla", "pallas"),
        default="xla",
        help="dataflow for the headline moe_e8 probe (default xla so the "
        "number stays comparable across rounds; the moe_dispatch_ladder "
        "record always measures xla, a2a and pallas side by side)",
    )
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from tools.bench_ladder import make_batch, run_ladder, setup_step, time_windows
    from tpukit.model import GPTConfig
    from tpukit.obs import peak_flops_per_chip, train_flops_per_token
    from tpukit.shardings import DataParallel, SingleDevice

    cache_stats = None
    if args.compilation_cache_dir:
        from tpukit.cache import enable_compilation_cache

        cache_stats = enable_compilation_cache(args.compilation_cache_dir)

    n_dev = len(jax.devices())
    strategy = DataParallel() if n_dev > 1 else SingleDevice()

    seq = 256
    per_chip_batch = 64
    batch = per_chip_batch * n_dev
    cfg = GPTConfig(
        dim=256,
        head_dim=32,
        heads=8,
        num_layers=8,
        vocab_size=50257,
        max_position_embeddings=seq,
        compute_dtype=jnp.bfloat16,
    )

    train_step, state, shapes, _ = setup_step(cfg, strategy)

    rng = np.random.RandomState(0)
    model_batch, targets = make_batch(rng, cfg.vocab_size, batch, seq - 1)

    # XLA static analysis of the exact executable the timing loop runs
    # (tpukit.obs round 6): the AOT lower/compile shares the jit caches, so
    # this is not a second compile; FLOPs/bytes come from cost_analysis and
    # comm bytes are parsed from the compiled HLO's collectives.
    from tpukit.obs import compiled_stats

    struct = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    xla_stats = compiled_stats(
        train_step, shapes, jax.tree.map(struct, model_batch), struct(targets)
    )

    # Best of four timing windows: the shared/tunneled chip shows double-
    # digit run-to-run variance from external load; the fastest window is
    # the honest steady-state throughput of THIS program. All window times
    # are kept so the JSON can report the spread (VERDICT r4: a headline
    # that sits on the target bar needs its noise band stated).
    steps = 12
    windows, state, final_loss = time_windows(
        train_step, state, model_batch, targets, steps=steps, windows=4
    )
    best = min(windows)

    tokens = steps * batch * (seq - 1)
    tps = tokens / best
    tps_chip = tps / n_dev
    flops_per_token = train_flops_per_token(cfg, seq - 1)
    peak = peak_flops_per_chip()
    mfu = (tps_chip * flops_per_token / peak) if peak else None

    # Secondary: long-context throughput (S=2048) through the Pallas flash
    # attention kernel — a regime where the materialized-mask attention the
    # reference uses (models/gpt.py:83-88) stops being viable.
    long_tps, long_err = None, None
    try:
        # batch 16/chip measured best on v5e with the fused head+CE path
        # (8 underfills the chip; 64 OOMs on trunk activations even with
        # no logits buffer — remat didn't pay for itself at 32/64)
        long_seq, long_batch = 2048, 16 * n_dev
        cfg_long = cfg.replace(max_position_embeddings=long_seq)
        train_step_l, state, _, _ = setup_step(cfg_long, strategy)
        long_b, long_t = make_batch(rng, cfg.vocab_size, long_batch, long_seq)
        # best-of-4 windows of 8: the shared chip's variance needs the shots
        times_l, state, _ = time_windows(
            train_step_l, state, long_b, long_t, steps=8, windows=4, warmup=2
        )
        long_tps = 8 * long_batch * long_seq / min(times_l) / n_dev
    except Exception as exc:  # stdout is reserved for the JSON line; the
        # error ALSO lands in the JSON so a kernel regression cannot hide
        # behind a clean rc=0 with null fields (VERDICT r4 #8)
        long_err = repr(exc)
        print(f"long-context bench failed: {exc!r}", file=sys.stderr)

    # FSDP --cpu_offload proof (VERDICT r3 #6): run the donated train step
    # with params/opt state pinned to HOST memory on the real chip and
    # record that the state is still host-pinned afterwards — the positive
    # path that CPU tests can only fake (they assert the degrade warning).
    offload_ok, offload_tps, offload_err = None, None, None
    try:
        from tpukit.mesh import create_mesh
        from tpukit.shardings import FSDP

        strat_o = FSDP(mesh=create_mesh({"data": n_dev}), cpu_offload=True)
        if strat_o._offload_supported():
            step_o, state_o, _, _ = setup_step(cfg, strat_o)
            kinds = {l.sharding.memory_kind for l in jax.tree.leaves(state_o.params)}
            assert kinds == {"pinned_host"}, kinds
            times_o, state_o, _ = time_windows(
                step_o, state_o, model_batch, targets, steps=6, windows=1, warmup=2
            )
            kinds = {l.sharding.memory_kind for l in jax.tree.leaves(state_o.params)}
            assert kinds == {"pinned_host"}, kinds
            offload_ok = True
            offload_tps = 6 * batch * (seq - 1) / times_o[0] / n_dev
            del state_o
    except Exception as exc:
        offload_ok = False
        offload_err = repr(exc)
        print(f"fsdp cpu_offload probe failed: {exc!r}", file=sys.stderr)

    # MoE probe (round 5): the Switch-style expert path on the real chip —
    # reference shape with 8 experts, full train step (routing + dispatch
    # einsums + aux loss + AdamW).
    moe_tps, moe_err = None, None
    try:
        cfg_moe = cfg.replace(num_experts=8, moe_dispatch=args.moe_dispatch)
        step_m, state_m, _, _ = setup_step(cfg_moe, strategy)
        moe_batch = 32 * n_dev
        b_m, t_m = make_batch(rng, cfg.vocab_size, moe_batch, seq - 1)
        times_m, state_m, _ = time_windows(
            step_m, state_m, b_m, t_m, steps=8, windows=3, warmup=2
        )
        moe_tps = 8 * moe_batch * (seq - 1) / min(times_m) / n_dev
        del state_m
    except Exception as exc:
        moe_err = repr(exc)
        print(f"moe probe failed: {exc!r}", file=sys.stderr)

    # EP a2a dispatch audit (round 10): expected-vs-measured all-to-all
    # payload + remat-warning count + a2a-path throughput. The xla-dispatch
    # moe probe above is untouched, so moe_e8_tokens_per_sec_per_chip stays
    # comparable across rounds.
    moe_ep_comm, moe_ep_comm_err = None, None
    try:
        moe_ep_comm = bench_moe_ep_comm(cfg, n_dev)
    except Exception as exc:
        moe_ep_comm_err = repr(exc)
        print(f"moe ep comm probe failed: {exc!r}", file=sys.stderr)

    # MoE dispatch ladder (round 11, ROADMAP #3): xla vs a2a vs pallas at
    # e8 top-1/top-2, tokens/s/chip + active-FLOPs-normalized MFU. Per-rung
    # errors land inside the record itself.
    moe_dispatch_ladder = None
    try:
        moe_dispatch_ladder = bench_moe_dispatch_ladder(cfg, n_dev)
    except Exception as exc:
        moe_dispatch_ladder = [{"dispatch": "ladder", "error": repr(exc)}]
        print(f"moe dispatch ladder failed: {exc!r}", file=sys.stderr)

    # Quantized collectives (round 12, ROADMAP #2): f32 vs bf16 vs int8
    # --comm_dtype per strategy rung — expected+measured bytes on the wire,
    # tokens/s/chip, final-loss delta vs f32. Per-rung errors land inside
    # the record itself.
    quant_comm_rec = None
    try:
        quant_comm_rec = bench_quant_comm(cfg, n_dev)
    except Exception as exc:
        quant_comm_rec = [{"strategy": "quant_comm", "error": repr(exc)}]
        print(f"quant comm ladder failed: {exc!r}", file=sys.stderr)

    # Overlap-scheduled collectives (round 18, ROADMAP #5): f32 vs int8
    # vs int8 + --grad_buckets 4 per strategy — step time, the promoted
    # overlap-gate verdict (overlap_frac), per-bucket byte match.
    comm_overlap_rec = None
    try:
        comm_overlap_rec = bench_comm_overlap(cfg, n_dev)
    except Exception as exc:
        comm_overlap_rec = [{"strategy": "comm_overlap", "error": repr(exc)}]
        print(f"comm overlap ladder failed: {exc!r}", file=sys.stderr)

    # Interleaved pipeline (round 25, --virtual_stages): flat 1F1B vs
    # V=2/V=4 at equal micro count — the tick-table bubble grid (the
    # --min_bubble_gain gated numbers) plus wall cross-checks; and the
    # pipeline x MoE pallas-dispatch parity rung.
    pipe_interleave_rec = None
    try:
        pipe_interleave_rec = bench_pipe_interleave(n_dev)
    except Exception as exc:
        pipe_interleave_rec = {"error": repr(exc)}
        print(f"pipe interleave ladder failed: {exc!r}", file=sys.stderr)
    pipe_moe_rec = None
    try:
        pipe_moe_rec = bench_pipe_moe(n_dev)
    except Exception as exc:
        pipe_moe_rec = {"error": repr(exc)}
        print(f"pipe moe probe failed: {exc!r}", file=sys.stderr)

    # Elastic restore (round 13, ROADMAP #5): restore+reshard wall-clock,
    # bytes read, RSS high-water delta and the parity bit for a sharded
    # checkpoint landing on a half-size world.
    elastic_restore = None
    try:
        elastic_restore = bench_elastic_restore(cfg, n_dev)
    except Exception as exc:
        elastic_restore = {"error": repr(exc)}
        print(f"elastic restore probe failed: {exc!r}", file=sys.stderr)

    # Serving (round 14, ROADMAP #1): continuous batching vs serial
    # per-request decode on the same seeded stream — tokens/s (the >= 2x
    # bar), p50/p99 end-to-end + per-token latency, slot occupancy.
    serving_rec = None
    try:
        serving_rec = bench_serving(cfg, n_dev)
    except Exception as exc:
        serving_rec = {"error": repr(exc)}
        print(f"serving probe failed: {exc!r}", file=sys.stderr)

    # Paged KV (round 15, ROADMAP #2): ring vs paged vs paged+int8 at
    # equal KV HBM — tokens/s, measured max concurrent slots (the >= 2x
    # bar with int8 pages), the exact-parity bit, and prefix-hit vs cold
    # admit latency on a shared-system-prompt stream.
    paged_kv_rec = None
    try:
        paged_kv_rec = bench_paged_kv(cfg, n_dev)
    except Exception as exc:
        paged_kv_rec = {"error": repr(exc)}
        print(f"paged kv probe failed: {exc!r}", file=sys.stderr)

    # Speculative decoding (round 17, ROADMAP #3): draft-and-verify vs
    # the vanilla engine on the repetitive stream — tokens/s (>= 1.3x
    # self-spec at temperature 0 is the bar), acceptance rate, the
    # appended-tokens/verify histogram, at temperature 0 and 0.8.
    spec_decode_rec = None
    try:
        spec_decode_rec = bench_spec_decode(cfg, n_dev)
    except Exception as exc:
        spec_decode_rec = {"error": repr(exc)}
        print(f"spec decode probe failed: {exc!r}", file=sys.stderr)

    # Dispatch-vs-device attribution (round 20): where a decode quantum's
    # wall goes — host async-dispatch loop vs waiting at the per-quantum
    # sync — from the request tracer's quantum spans on a traced run.
    serve_dispatch_rec = None
    try:
        serve_dispatch_rec = bench_serve_dispatch_attribution(cfg, n_dev)
    except Exception as exc:
        serve_dispatch_rec = {"error": repr(exc)}
        print(f"serve dispatch attribution probe failed: {exc!r}",
              file=sys.stderr)

    # Fused decode (round 21, ROADMAP #2/#4): the kernel win (unfused vs
    # fused at quantum=1) and the dispatch-amortization win (fused q=1 vs
    # the on-device while-loop window) measured separately, with parity
    # and per-quantum dispatch/device walls cross-checking the round-20
    # attribution record.
    decode_fused_rec = None
    try:
        decode_fused_rec = bench_decode_fused(cfg, n_dev)
    except Exception as exc:
        decode_fused_rec = {"error": repr(exc)}
        print(f"fused decode probe failed: {exc!r}", file=sys.stderr)

    # Fleet serving (round 19, ROADMAP #1): 1 vs 2 vs 4 replicas on the
    # same stream at equal total devices — fleet tokens/s scaling (>1.5x
    # at 2 replicas is the bar), p99 under load, per-request parity, and
    # disaggregated-vs-colocated prefill admit latency.
    fleet_serving_rec = None
    try:
        fleet_serving_rec = bench_fleet_serving(cfg, n_dev)
    except Exception as exc:
        fleet_serving_rec = {"error": repr(exc)}
        print(f"fleet serving probe failed: {exc!r}", file=sys.stderr)

    # Host input pipeline (round 7): sync data+h2d share vs the depth-2
    # prefetcher's residual stall share, with loss-parity proof.
    host_pipeline, host_pipeline_err = None, None
    try:
        host_pipeline = bench_host_pipeline(cfg, strategy, batch)
    except Exception as exc:
        host_pipeline_err = repr(exc)
        print(f"host pipeline probe failed: {exc!r}", file=sys.stderr)

    # Failure-observability overhead (round 8): recorder + periodic
    # checksum cost vs the bare loop, with loss-parity proof.
    obs_overhead, obs_overhead_err = None, None
    try:
        obs_overhead = bench_obs_overhead(cfg, strategy, batch)
    except Exception as exc:
        obs_overhead_err = repr(exc)
        print(f"obs overhead probe failed: {exc!r}", file=sys.stderr)

    # Round-20 serving rung of the obs-overhead story: the request-trace
    # recorder on vs off on the same seeded stream — tokens/s delta
    # (<1% bar) and bit-identical output tokens.
    try:
        serving_rung = bench_serve_trace_overhead(cfg, n_dev)
    except Exception as exc:
        serving_rung = {"error": repr(exc)}
        print(f"serve trace overhead probe failed: {exc!r}", file=sys.stderr)
    if obs_overhead is None:
        obs_overhead = {}
    obs_overhead["serving"] = serving_rung

    # Round-22 metrics-plane rung of the same story: the registry on vs
    # --no_metrics on the same seeded stream — tokens/s delta (<1% bar),
    # bit-identical tokens, and the snapshot-publish wall timed apart.
    try:
        metrics_overhead_rec = bench_metrics_overhead(cfg, n_dev)
    except Exception as exc:
        metrics_overhead_rec = {"error": repr(exc)}
        print(f"metrics overhead probe failed: {exc!r}", file=sys.stderr)

    # Ladder rungs (VERDICT r4 #1): single-chip measurements of the
    # BASELINE configs 2-5 shapes at head_dim=64 — GPT-small/medium full,
    # GPT-large/XL as the 16-layer stage slices DESIGN.md §2 profiles.
    # Per-rung failures land as {"shape": ..., "error": ...} entries.
    ladder = None
    if n_dev == 1:  # rung batch sizes are tuned per chip
        try:
            ladder = run_ladder(steps=6, windows=3)
        except Exception as exc:
            ladder = [{"shape": "ladder", "error": repr(exc)}]

    result = {
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if mfu is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # spread across the four timing windows on this shared chip: the
        # slowest window's MFU (lower bound seen THIS run) vs the reported
        # best — the honest noise band around the headline number
        "mfu_window_min": (
            round(mfu * best / max(windows), 4) if mfu is not None else None
        ),
        "tokens_per_sec_total": round(tps, 1),
        "long_context_s2048_tokens_per_sec_per_chip": round(long_tps, 1) if long_tps else None,
        "long_context_error": long_err,
        "fsdp_cpu_offload_ok": offload_ok,
        "fsdp_cpu_offload_tokens_per_sec_per_chip": round(offload_tps, 1) if offload_tps else None,
        "fsdp_cpu_offload_error": offload_err,
        "moe_e8_tokens_per_sec_per_chip": round(moe_tps, 1) if moe_tps else None,
        "moe_e8_dispatch": args.moe_dispatch,
        "moe_error": moe_err,
        "moe_ep_comm": moe_ep_comm,
        "moe_ep_comm_error": moe_ep_comm_err,
        "moe_dispatch_ladder": moe_dispatch_ladder,
        "quant_comm": quant_comm_rec,
        "comm_overlap": comm_overlap_rec,
        "pipe_interleave": pipe_interleave_rec,
        "pipe_moe": pipe_moe_rec,
        "elastic_restore": elastic_restore,
        "serving": serving_rec,
        "paged_kv": paged_kv_rec,
        "spec_decode": spec_decode_rec,
        "serve_dispatch_attribution": serve_dispatch_rec,
        "decode_fused": decode_fused_rec,
        "fleet_serving": fleet_serving_rec,
        "host_pipeline": host_pipeline,
        "host_pipeline_error": host_pipeline_err,
        "obs_overhead": obs_overhead,
        "obs_overhead_error": obs_overhead_err,
        "metrics_overhead": metrics_overhead_rec,
        "ladder": ladder,
        "chips": n_dev,
        "device": jax.devices()[0].device_kind,
        "config": f"GPT-20M dim256 L8 seq256 bf16 batch{batch}, fused train step",
        "final_loss": round(final_loss, 4),
        # roofline + comm-volume telemetry for the headline step (tpukit.obs)
        "xla_train_step": xla_stats,
        "compile_cache": cache_stats.stats() if cache_stats else None,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
