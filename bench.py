#!/usr/bin/env python
"""Benchmark: GPT training throughput on the available chip(s).

Trains the cookbook's GPT (reference default shape: dim 256, 8x32 heads,
8 layers, seq 256, GPT-2 vocab — main-single.py:156-162) with the full jitted
train step (fwd + bwd + AdamW) in bf16 on synthetic data, and reports
tokens/sec/chip and MFU. The reference publishes no numbers (BASELINE.md), so
`vs_baseline` is measured MFU / the driver's 35% MFU north-star.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import json
import sys

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from tools.bench_ladder import make_batch, run_ladder, time_windows
    from tpukit.model import GPTConfig
    from tpukit.obs import peak_flops_per_chip, train_flops_per_token
    from tpukit.shardings import DataParallel, SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    n_dev = len(jax.devices())
    strategy = DataParallel() if n_dev > 1 else SingleDevice()

    seq = 256
    per_chip_batch = 64
    batch = per_chip_batch * n_dev
    cfg = GPTConfig(
        dim=256,
        head_dim=32,
        heads=8,
        num_layers=8,
        vocab_size=50257,
        max_position_embeddings=seq,
        compute_dtype=jnp.bfloat16,
    )

    optimizer = make_optimizer(1e-4)
    state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
    shapes = jax.eval_shape(lambda: state)
    train_step, _, state_sharding = make_step_fns(cfg, optimizer, strategy, shapes)
    state = jax.device_put(state, state_sharding)

    rng = np.random.RandomState(0)
    model_batch, targets = make_batch(rng, cfg.vocab_size, batch, seq - 1)

    # XLA static analysis of the exact executable the timing loop runs
    # (tpukit.obs round 6): the AOT lower/compile shares the jit caches, so
    # this is not a second compile; FLOPs/bytes come from cost_analysis and
    # comm bytes are parsed from the compiled HLO's collectives.
    from tpukit.obs import compiled_stats

    struct = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    xla_stats = compiled_stats(
        train_step, shapes, jax.tree.map(struct, model_batch), struct(targets)
    )

    # Best of four timing windows: the shared/tunneled chip shows double-
    # digit run-to-run variance from external load; the fastest window is
    # the honest steady-state throughput of THIS program. All window times
    # are kept so the JSON can report the spread (VERDICT r4: a headline
    # that sits on the target bar needs its noise band stated).
    steps = 12
    windows, state, final_loss = time_windows(
        train_step, state, model_batch, targets, steps=steps, windows=4
    )
    best = min(windows)

    tokens = steps * batch * (seq - 1)
    tps = tokens / best
    tps_chip = tps / n_dev
    flops_per_token = train_flops_per_token(cfg, seq - 1)
    peak = peak_flops_per_chip()
    mfu = (tps_chip * flops_per_token / peak) if peak else None

    # Secondary: long-context throughput (S=2048) through the Pallas flash
    # attention kernel — a regime where the materialized-mask attention the
    # reference uses (models/gpt.py:83-88) stops being viable.
    long_tps, long_err = None, None
    try:
        # batch 16/chip measured best on v5e with the fused head+CE path
        # (8 underfills the chip; 64 OOMs on trunk activations even with
        # no logits buffer — remat didn't pay for itself at 32/64)
        long_seq, long_batch = 2048, 16 * n_dev
        cfg_long = cfg.replace(max_position_embeddings=long_seq)
        state = create_train_state(jax.random.PRNGKey(0), cfg_long, optimizer)
        shapes = jax.eval_shape(lambda: state)
        train_step_l, _, sharding_l = make_step_fns(cfg_long, optimizer, strategy, shapes)
        state = jax.device_put(state, sharding_l)
        long_b, long_t = make_batch(rng, cfg.vocab_size, long_batch, long_seq)
        # best-of-4 windows of 8: the shared chip's variance needs the shots
        times_l, state, _ = time_windows(
            train_step_l, state, long_b, long_t, steps=8, windows=4, warmup=2
        )
        long_tps = 8 * long_batch * long_seq / min(times_l) / n_dev
    except Exception as exc:  # stdout is reserved for the JSON line; the
        # error ALSO lands in the JSON so a kernel regression cannot hide
        # behind a clean rc=0 with null fields (VERDICT r4 #8)
        long_err = repr(exc)
        print(f"long-context bench failed: {exc!r}", file=sys.stderr)

    # FSDP --cpu_offload proof (VERDICT r3 #6): run the donated train step
    # with params/opt state pinned to HOST memory on the real chip and
    # record that the state is still host-pinned afterwards — the positive
    # path that CPU tests can only fake (they assert the degrade warning).
    offload_ok, offload_tps, offload_err = None, None, None
    try:
        from tpukit.mesh import create_mesh
        from tpukit.shardings import FSDP

        strat_o = FSDP(mesh=create_mesh({"data": n_dev}), cpu_offload=True)
        if strat_o._offload_supported():
            state_o = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
            shapes_o = jax.eval_shape(lambda: state_o)
            step_o, _, sh_o = make_step_fns(cfg, optimizer, strat_o, shapes_o)
            state_o = jax.device_put(state_o, sh_o)
            kinds = {l.sharding.memory_kind for l in jax.tree.leaves(state_o.params)}
            assert kinds == {"pinned_host"}, kinds
            times_o, state_o, _ = time_windows(
                step_o, state_o, model_batch, targets, steps=6, windows=1, warmup=2
            )
            kinds = {l.sharding.memory_kind for l in jax.tree.leaves(state_o.params)}
            assert kinds == {"pinned_host"}, kinds
            offload_ok = True
            offload_tps = 6 * batch * (seq - 1) / times_o[0] / n_dev
            del state_o
    except Exception as exc:
        offload_ok = False
        offload_err = repr(exc)
        print(f"fsdp cpu_offload probe failed: {exc!r}", file=sys.stderr)

    # MoE probe (round 5): the Switch-style expert path on the real chip —
    # reference shape with 8 experts, full train step (routing + dispatch
    # einsums + aux loss + AdamW).
    moe_tps, moe_err = None, None
    try:
        cfg_moe = cfg.replace(num_experts=8)
        state_m = create_train_state(jax.random.PRNGKey(0), cfg_moe, optimizer)
        shapes_m = jax.eval_shape(lambda: state_m)
        step_m, _, sh_m = make_step_fns(cfg_moe, optimizer, strategy, shapes_m)
        state_m = jax.device_put(state_m, sh_m)
        moe_batch = 32 * n_dev
        b_m, t_m = make_batch(rng, cfg.vocab_size, moe_batch, seq - 1)
        times_m, state_m, _ = time_windows(
            step_m, state_m, b_m, t_m, steps=8, windows=3, warmup=2
        )
        moe_tps = 8 * moe_batch * (seq - 1) / min(times_m) / n_dev
        del state_m
    except Exception as exc:
        moe_err = repr(exc)
        print(f"moe probe failed: {exc!r}", file=sys.stderr)

    # Ladder rungs (VERDICT r4 #1): single-chip measurements of the
    # BASELINE configs 2-5 shapes at head_dim=64 — GPT-small/medium full,
    # GPT-large/XL as the 16-layer stage slices DESIGN.md §2 profiles.
    # Per-rung failures land as {"shape": ..., "error": ...} entries.
    ladder = None
    if n_dev == 1:  # rung batch sizes are tuned per chip
        try:
            ladder = run_ladder(steps=6, windows=3)
        except Exception as exc:
            ladder = [{"shape": "ladder", "error": repr(exc)}]

    result = {
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if mfu is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # spread across the four timing windows on this shared chip: the
        # slowest window's MFU (lower bound seen THIS run) vs the reported
        # best — the honest noise band around the headline number
        "mfu_window_min": (
            round(mfu * best / max(windows), 4) if mfu is not None else None
        ),
        "tokens_per_sec_total": round(tps, 1),
        "long_context_s2048_tokens_per_sec_per_chip": round(long_tps, 1) if long_tps else None,
        "long_context_error": long_err,
        "fsdp_cpu_offload_ok": offload_ok,
        "fsdp_cpu_offload_tokens_per_sec_per_chip": round(offload_tps, 1) if offload_tps else None,
        "fsdp_cpu_offload_error": offload_err,
        "moe_e8_tokens_per_sec_per_chip": round(moe_tps, 1) if moe_tps else None,
        "moe_error": moe_err,
        "ladder": ladder,
        "chips": n_dev,
        "device": jax.devices()[0].device_kind,
        "config": f"GPT-20M dim256 L8 seq256 bf16 batch{batch}, fused train step",
        "final_loss": round(final_loss, 4),
        # roofline + comm-volume telemetry for the headline step (tpukit.obs)
        "xla_train_step": xla_stats,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
