#!/usr/bin/env python
"""Recipe 8 (tpukit extension): mixture-of-experts training with expert
parallelism.

The reference cookbook has no MoE and no expert parallelism (SURVEY §2.4
marks the EP row "not required"); this recipe closes that row anyway, the
TPU way. `--num_experts N` replaces every layer's FFN with a Switch-style
top-1 routed expert bank (fixed-capacity dispatch — static shapes — and
the Switch load-balance aux loss; see tpukit/model/gpt.py _apply_moe_ffn).
The ExpertParallel strategy shards the expert bank over an `expert` mesh
axis, the dense trunk + its Adam moments FSDP-style over `data`, and —
with the default `--moe_dispatch a2a` — moves tokens through hand-placed
`lax.all_to_all` pairs inside shard_map (tpukit/ops/moe_dispatch.py), the
collectives GPU MoE frameworks hand-write with NCCL, in both the forward
and the backward. `--moe_dispatch pallas` keeps that exchange but runs
the expert FFN through the fused grouped-expert segment GEMM
(tpukit/ops/moe_gemm.py) — and on a single chip it is the dropless
sorted dataflow with no capacity buffer at all. `--moe_dispatch xla`
restores the round-5 einsum-and-GSPMD dispatch for comparison (its
backward degrades to a replicate-repartition; see tpukit/shardings.py
ExpertParallel).

The device grid puts `expert` innermost (its all_to_alls ride the fastest
ICI links) with remaining devices data-parallel, e.g. 8 devices and 8
experts -> (data=1, expert=8); 8 devices and 4 experts -> (data=2,
expert=4).

Run: `python main-moe.py --num_experts 8 --batch_size 64 ...`
(batch_size is per data shard, as in the per-rank reference loader).
"""

import math

import jax

from tpukit.flags import parse_flags
from tpukit.mesh import create_mesh
from tpukit.shardings import ExpertParallel
from tpukit.train import fit


def pick_grid(n_devices: int, num_experts: int) -> dict:
    """Largest expert-parallel degree that divides both the device count
    and the expert count — their gcd; remaining devices are data-parallel."""
    expert = math.gcd(n_devices, num_experts)
    return {"data": n_devices // expert, "expert": expert}


def main(argv=None):
    flags = parse_flags(argv, num_experts=True)
    grid = pick_grid(len(jax.devices()), flags.num_experts)
    return fit(flags, ExpertParallel(create_mesh(grid), dispatch=flags.moe_dispatch))


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
