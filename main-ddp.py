#!/usr/bin/env python
"""Recipe 2: data-parallel training.

TPU-native twin of reference `main-ddp.py`. The reference wraps the model in
`DistributedDataParallel` (main-ddp.py:55) under torchrun + NCCL
(main-ddp.py:1-6,26); gradients are all-reduced by DDP's autograd hooks
during backward (main-ddp.py:124) and eval metrics are explicitly
all-reduced (main-ddp.py:159-160). Here the same capability is a 1-D `data`
mesh with the batch sharded across it and parameters replicated: XLA emits
the gradient all-reduce over ICI from the sharding specs — no process
groups, no launcher, no hooks. Per-rank data sharding (DistributedSampler,
main-ddp.py:83-84) becomes "feed the global batch, shard on the data axis";
process-0 gating of tqdm/generate/checkpoint (main-ddp.py:106,170,180) is
preserved for multi-host runs.

Run on any number of chips: `python main-ddp.py --batch_size 64 ...`
(batch_size is per data-shard, as in the per-rank reference loader).
"""

from tpukit.flags import parse_flags
from tpukit.shardings import DataParallel
from tpukit.train import fit


def main(argv=None):
    flags = parse_flags(argv)
    return fit(flags, DataParallel())


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
