#!/usr/bin/env python
"""Recipe 7 (tpukit extension): long-context training with ring attention.

The reference cookbook has no long-context story — its attention
materializes the full S x S score tensor on one device and sequence length
caps at 256/512 (reference models/gpt.py:83-88, data.py:18; SURVEY §5).
This recipe shards the *sequence* dimension over a `seq` mesh axis and
computes exact causal attention with a `lax.ppermute` ring (K/V blocks
rotate over ICI while each device keeps its query shard and online-softmax
state) — see tpukit/ring_attention.py and the ContextParallel strategy.

Use it when one chip can't hold the sequence:
  python main-ring.py --sequence_length 8192 --batch_size 4 ...
(sequence_length - 1 must divide by the number of sequence shards; on an
8-device mesh the default grid is seq=8.)

`--cp_attention ulysses` swaps the ring for all-to-all sequence
parallelism (DeepSpeed-Ulysses style): two all_to_alls re-partition heads
over the seq axis and each device runs full-sequence flash attention on
its head subset — fewer collectives per layer, requires heads divisible
by the shard count.
"""

from tpukit.flags import parse_flags
from tpukit.shardings import ContextParallel
from tpukit.train import fit


def main(argv=None):
    flags = parse_flags(argv, cp_attention=True)
    # host_permute: fit() applies the zigzag layout permutation on the host
    # numpy batch (strategy.host_batch_fn) instead of an in-jit gather that
    # GSPMD turns into a per-step cross-shard reshard (ADVICE r4).
    return fit(
        flags, ContextParallel(attention=flags.cp_attention, host_permute=True)
    )


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
