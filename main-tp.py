#!/usr/bin/env python
"""Recipe 6 (tpukit extension): tensor-parallel training (beyond-reference;
SURVEY §2.4 stretch). The extension ladder is 6 = TP, 7 = ring/CP
(main-ring.py), 8 = MoE/EP (main-moe.py), after the reference's five.

The reference has no tensor-parallel recipe — its parallelism ladder stops
at pipeline (SURVEY §2.4). On TPU, Megatron-style TP is pure shardings: q/k/v
and the ffn up-projection shard their output dimension (column parallel), the
attention out-projection and ffn down-projection shard their input dimension
(row parallel), so XLA inserts exactly one all-reduce after attention and one
after the MLP — see tpukit.shardings.TensorParallel. The lm_head and token
embedding shard their vocab dimension.

The device grid follows the classic layout: `model` (TP) innermost so its
per-layer all-reduces ride the fastest ICI links, the remaining devices
data-parallel, e.g. 8 devices -> (data=2, model=4).

Run: `python main-tp.py --batch_size 64 ...` (batch_size is per data shard,
as in the per-rank reference loader).
"""

import jax

from tpukit.flags import parse_flags
from tpukit.mesh import create_mesh
from tpukit.shardings import TensorParallel
from tpukit.train import fit


def pick_grid(n_devices: int, heads: int) -> dict:
    """Largest model-parallel degree <= 4 that divides the device count and
    the head count (column-parallel q/k/v shard the head dimension);
    remaining devices become data-parallel replicas."""
    for model in (4, 2, 1):
        if n_devices % model == 0 and heads % model == 0:
            return {"data": n_devices // model, "model": model}
    return {"data": n_devices, "model": 1}


def main(argv=None):
    flags = parse_flags(argv)
    grid = pick_grid(len(jax.devices()), flags.heads)
    return fit(flags, TensorParallel(create_mesh(grid)))


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
