#!/usr/bin/env python
"""Recipe 9 (tpukit extension): continuous-batching inference serving.

The extension ladder after the reference's five recipes is 6 = TP
(main-tp.py), 7 = ring/CP (main-ring.py), 8 = MoE/EP (main-moe.py),
9 = serving — the "millions of users" half of the north star (ROADMAP #1).
Everything upstream of this recipe decodes as a training-loop side effect;
this is the standalone serving path: restore ANY checkpoint the training
recipes saved (reshard-on-restore handles a different world — round 13),
shard it over a (data x model) serving mesh with params at their
TensorParallel training shardings and the per-slot KV ring sharded heads
over `model` / slots over `data`, and drive a seeded synthetic request
stream through the continuous-batching engine (tpukit/serve): requests
admit into free slots mid-decode at bucketed prompt lengths (the whole
compile budget is the declared bucket set), evict on EOS/length, and the
`kind="serve"` JSONL windows — tokens/s, p50/p99 per-token and end-to-end
latency, slot occupancy, prefill/decode wall split — flow through the same
StepLogger/flight-recorder/report stack that covers training
(`python tools/report.py serve.jsonl`, with `--min_serve_tps` as the CI
throughput gate).

Round 15 (ROADMAP #2): `--page_size P` swaps the per-slot ring for the
PAGED KV cache (tpukit/serve/paged.py) — fixed-size pages + per-slot
block tables, request-granular allocation, shared-prefix reuse
(admissions hitting the page-granular prefix registry skip the shared
prefill entirely; `--shared_prefix N` gives the synthetic stream one
system prompt), chunked prefill (`--prefill_chunk`), and int8 page
payloads (`--kv_dtype int8`, ~4x pages per HBM byte, tolerance-gated).
Paged serving picks a model-only grid (the page pool replicates over
`data`); the checkpoint restore is params-ONLY either way
(`checkpoint.restore_params`: the Adam moments — ~2/3 of the bytes —
are never read, and any saved world lands at the serving shardings).

Round 17 (ROADMAP #3): `--draft {ngram,model}` turns on SPECULATIVE
DECODING (tpukit/serve/spec.py) — a proposer guesses `--spec_k` tokens
per slot per quantum and the target scores all k+1 positions in ONE
batched forward, rejection sampling keeping the output distribution
EXACT (greedy output token-identical to vanilla decode). "ngram" is
self-speculation: on-device prompt-lookup drafting fused into the
verify program, no second model — near-free, and a big win on
repetitive/templated traffic (`--stream_profile repetitive`). "model"
runs a small tpukit GPT draft (`--draft_checkpoint` + `--draft_*` shape
flags, params-only restore with its own ledger line) with its own
replicated KV ring. Speculation needs the ring cache (page_size 0).

Round 19 (ROADMAP #1, tpukit/serve/fleet.py): `--replicas N` routes the
stream through a FLEET — N engine replicas, each on its own disjoint
device subset (`--devices_per_replica`, model-parallel grid per
replica), behind one least-loaded router. The checkpoint is read ONCE
(host-side params-only restore) and placed per replica; fleet output is
token-identical to a single engine on the same stream, including when
`--fleet_kill replica_kill@R[:idx]` chaos-kills a replica mid-stream
(in-flight requests re-queue onto survivors, exactly-once output).
`--disagg_prefill` dedicates a prefill worker that hands finished
prefixes to decode replicas as pages; `--scale_up_occupancy` /
`--scale_down_occupancy` autoscale the replica count between fleet
windows. `kind="fleet"` telemetry renders via tools/report.py
"== fleet ==" with `--min_fleet_tps` as the CI gate.

Round 21 (ROADMAP #2/#4): `--fused_decode` chases the decode hardware
ceiling on two axes at once. Per step, paged attention runs as ONE
fused Pallas kernel (tpukit/ops/paged_attention.py): the block table is
scalar-prefetched and dereferenced INSIDE the kernel — no per-layer XLA
gather materializing a [slots, window] contiguous KV view — and int8
pages dequantize tile-by-tile in VMEM on the quant_comm block layout.
Per quantum, the scheduler inner state (cursors, EOS flags, length
limits, freed-page account) lives on device and `--decode_quantum` steps
run as one `lax.while_loop` (decode.decode_loop_window), so the ~0.3 ms
host dispatch the round-20 traces measured per step is paid once per
quantum instead of once per step; the host syncs only at window
boundaries (or early, when EOS activity frees enough pages for the
head-of-queue admit). Token streams are exactly those of the unfused
engine (greedy and seeded sampling; kernel math is op-for-op identical,
~1-ULP dot reassociation only); bench.py's `decode_fused` record
measures the kernel and amortization wins separately and
`tools/report.py --min_decode_speedup` gates the latter. Needs the
paged cache (`--page_size`).

Round 24 (tpukit/serve/ledger.py): CRASH-TOLERANT fleet serving. With
`--fleet_dir` the request lifecycle is durable — write-ahead lease
records before dispatch, exactly-once completion records after, full
stream replay on router restart (a restarted router serves only the
not-yet-completed frontier; `duplicate_completions` stays 0 across
process death). Replicas publish heartbeat files; `--replica_timeout`
declares silent replicas dead and requeues their leases on survivors
under the `--request_retries` budget with jittered backoff.
`--fleet_procs` runs each replica as a real worker PROCESS (this recipe
re-exec'd with `--fleet_worker i`) so `--fleet_kill
replica_sigkill@R` chaos delivers a real SIGKILL; the serving chaos
grammar also takes slow_replica@R:ms (heartbeat stall — slowness the
liveness check must NOT confuse with death), stuck_request@N (pair with
`--deadline_ms`), and ledger_io_fail@k:c (transient IOError on ledger
I/O, absorbed by retry_io). `--deadline_ms` evicts over-deadline lanes
with their partial tokens as reason="deadline" (kind="deadline_miss"
records, gated by report.py --max_deadline_miss_pct);
`--max_queue_depth` sheds over-depth arrivals lowest-priority-first as
named request_rejected events.

Run examples:
  python main-serve.py --requests 64 --slots 8 --metrics_log serve.jsonl
  python main-serve.py --checkpoint latest --temperature 0.8 --top_k 40
  python main-serve.py --checkpoint checkpoints/step-200.msgpack \\
      --num_experts 8 --moe_dispatch pallas   # dropless MoE: exact cached
  python main-serve.py --page_size 8 --shared_prefix 16 --requests 128 \\
      --kv_dtype int8 --metrics_log serve.jsonl   # paged + prefix + int8
  python main-serve.py --draft ngram --spec_k 6 \\
      --stream_profile repetitive --metrics_log serve.jsonl  # self-spec
  python main-serve.py --draft model \\
      --draft_checkpoint ckpts_draft/checkpoint-step000002000.msgpack \\
      --draft_dim 64 --draft_num_layers 2   # draft-model speculation
  python main-serve.py --replicas 2 --devices_per_replica 4 \\
      --fleet_kill replica_kill@40:1 \\
      --metrics_log fleet.jsonl   # fleet router + chaos replica kill
  python main-serve.py --replicas 2 --fleet_procs --fleet_dir /tmp/fleet \\
      --replica_timeout 3 --fleet_kill replica_sigkill@6:1 \\
      --metrics_log fleet.jsonl   # real worker procs + real SIGKILL
"""

import argparse
import sys
import time
from functools import partial

import numpy as np


def parse_serve_flags(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    # model shape — must match the checkpoint being served
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--head_dim", type=int, default=32)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--num_layers", type=int, default=8)
    ap.add_argument("--sequence_length", type=int, default=256,
                    help="position table size; the KV ring (max bucket + "
                    "max_new_tokens) must fit inside it")
    ap.add_argument("--disable_amp", action="store_true")
    ap.add_argument("--num_experts", type=int, default=0)
    ap.add_argument("--moe_top_k", type=int, default=1)
    ap.add_argument("--moe_dispatch", choices=("xla", "pallas"), default="xla",
                    help="meshless decode dataflow for MoE checkpoints; "
                    "'pallas' (dropless) makes the cached decode exact")
    # checkpoint
    ap.add_argument("--checkpoint", type=str, default="",
                    help="path or 'latest'; empty serves fresh seeded params "
                    "(smoke/bench mode)")
    ap.add_argument("--seed", type=int, default=0)
    # engine shape (shared with bench.py via tpukit.flags.add_serve_flags)
    from tpukit.flags import add_fleet_flags, add_serve_flags

    add_serve_flags(ap)
    # fleet router (round 19): --replicas N routes the stream over N
    # engine replicas on disjoint device subsets; 0 = single engine
    add_fleet_flags(ap)
    # stream
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--qps", type=float, default=0.0,
                    help="0 = offered up front (saturation); >0 = seeded "
                    "exponential arrivals at this rate")
    ap.add_argument("--shared_prefix", type=int, default=0,
                    help="prepend the SAME n-token system prompt to every "
                    "request (the shared-prefix-reuse shape; with "
                    "--page_size the engine skips the shared prefill on "
                    "prefix hits)")
    ap.add_argument("--stream_profile",
                    choices=("uniform", "repetitive", "shared_prefix"),
                    default="uniform",
                    help="synthetic-stream workload shape: 'repetitive' "
                    "tiles a short phrase per prompt (where "
                    "self-speculation wins), 'shared_prefix' gives every "
                    "request one system prompt (the paged prefix-reuse "
                    "shape)")
    # draft model (--draft model): restored params-only like the target,
    # with its own shape flags — a draft checkpoint is just a smaller
    # tpukit training run sharing the target's tokenizer
    ap.add_argument("--draft_checkpoint", type=str, default="",
                    help="checkpoint PATH for the --draft model proposer "
                    "(no 'latest' — it would resolve the same shared "
                    "directory as --checkpoint latest); empty with "
                    "--draft model serves fresh seeded draft params "
                    "(smoke/bench mode)")
    ap.add_argument("--draft_dim", type=int, default=64)
    ap.add_argument("--draft_head_dim", type=int, default=16)
    ap.add_argument("--draft_heads", type=int, default=4)
    ap.add_argument("--draft_num_layers", type=int, default=2)
    # telemetry
    ap.add_argument("--metrics_log", type=str, default="")
    ap.add_argument("--compilation_cache_dir", type=str, default="")
    return ap.parse_args(argv)


def pick_serve_grid(n_devices: int, heads: int, slots: int,
                    paged: bool = False) -> dict:
    """The grid picker moved to tpukit/serve/fleet.py in round 19 (the
    fleet builds one grid PER REPLICA over each replica's device subset,
    so it is shared infrastructure now); this thin delegate keeps the
    name callers and docs know, and the lazy import keeps this module's
    import side-effect-free like the rest of the recipe CLI."""
    from tpukit.serve.fleet import pick_serve_grid as _pick

    return _pick(n_devices, heads, slots, paged=paged)


def main(argv=None):
    flags = parse_serve_flags(argv)
    import jax
    import jax.numpy as jnp

    from tpukit import checkpoint as ckpt_lib
    from tpukit import reshard as reshard_lib
    from tpukit.data import get_tokenizer
    from tpukit.mesh import create_mesh, initialize_runtime, is_process_zero
    from tpukit.model import GPTConfig
    from tpukit.obs import (
        FlightRecorder,
        MetricRegistry,
        StepLogger,
        TraceRecorder,
        parse_slo,
    )
    from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream
    from tpukit.shardings import DataParallel, SingleDevice, TensorParallel
    from tpukit.train import TrainState, create_train_state, make_optimizer

    initialize_runtime()
    if flags.compilation_cache_dir:
        from tpukit.cache import enable_compilation_cache

        enable_compilation_cache(flags.compilation_cache_dir)

    tokenizer = get_tokenizer()
    tokenizer.pad_token_id = 2  # every recipe pins pad to 2 (main-single.py:23)
    cfg = GPTConfig(
        dim=flags.dim,
        head_dim=flags.head_dim,
        heads=flags.heads,
        num_layers=flags.num_layers,
        vocab_size=tokenizer.vocab_size,
        max_position_embeddings=flags.sequence_length,
        compute_dtype=jnp.float32 if flags.disable_amp else jnp.bfloat16,
        num_experts=flags.num_experts,
        router_top_k=flags.moe_top_k,
        moe_dispatch=flags.moe_dispatch if flags.num_experts > 0 else "xla",
    )
    buckets = tuple(sorted({int(b) for b in flags.buckets.split(",") if b}))

    # ---- fleet mode (round 19, --replicas >= 1) --------------------------
    if flags.replicas > 0:
        return _run_fleet(flags, cfg, tokenizer, buckets)

    # ---- serving mesh + params at their training shardings ---------------
    # Dense models serve TensorParallel (heads over `model`); MoE
    # checkpoints serve replicated over a data-only grid — the Megatron
    # rules don't cover expert banks, and the meshless MoE decode dataflow
    # (xla buffers / dropless pallas) needs no expert axis.
    n_dev = len(jax.devices())
    if flags.num_experts > 0:
        data = n_dev
        while data > 1 and flags.slots % data:
            data -= 1
        mesh = create_mesh({"data": data})
        strategy = DataParallel(mesh) if data > 1 else SingleDevice()
    else:
        mesh = create_mesh(pick_serve_grid(n_dev, flags.heads, flags.slots,
                                           paged=flags.page_size > 0))
        strategy = TensorParallel(mesh)
    strategy.validate_config(cfg)

    # Shapes only — serving never steps, so only the params subtree of the
    # TrainState is ever materialized (the optimizer here exists solely to
    # derive the state's tree structure for the sharding specs).
    optimizer = make_optimizer(1e-4)
    init_fn = partial(create_train_state, cfg=cfg, optimizer=optimizer,
                      strategy=strategy)
    state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(flags.seed))
    state_sharding = strategy.state_sharding(state_shapes)

    logger = StepLogger(flags.metrics_log)
    recorder = FlightRecorder()
    p0 = is_process_zero()

    if flags.checkpoint:
        path = (ckpt_lib.latest_any() if flags.checkpoint == "latest"
                else flags.checkpoint)
        if path is None:
            raise FileNotFoundError("--checkpoint latest: no checkpoint found")
        ok, detail = ckpt_lib.verify_checkpoint(path)
        if not ok:
            raise RuntimeError(f"--checkpoint {path}: failed integrity "
                               f"verification ({detail})")
        saved_w = reshard_lib.saved_world(path)
        run_world = reshard_lib.current_world(strategy)
        mismatch = reshard_lib.describe_mismatch(saved_w, run_world)
        # Round 15: params-ONLY restore — the full-TrainState restore read
        # params + both Adam moments (~3x the params bytes; the documented
        # round-14 future optimization). `restore_params` filters the
        # sharded manifest to the `.params` leaves from npy headers alone
        # and places them straight at the serving shardings; because
        # leaves are assembled whole and placed at the TARGET shardings, a
        # training world that differs from the serving grid needs no
        # reshard pass for a params-only read.
        try:
            params, rs_info = ckpt_lib.restore_params(
                path, state_shapes.params, state_sharding.params
            )
        except ValueError as exc:
            # flax's structure mismatch is deep and unnamed — say what
            # it almost always means at this surface
            raise ValueError(
                f"--checkpoint {path}: state structure does not match "
                f"the model flags (--dim/--heads/--num_layers/"
                f"--num_experts... must equal the training run's). "
                f"Original error: {exc}"
            ) from exc
        rec = dict(kind="ckpt_restore", params_only=True,
                   checkpoint=str(path), mismatch=mismatch or "",
                   world=run_world, **rs_info)
        logger.log(**rec)
        recorder.record("ckpt_restore", params_only=True,
                        mismatch=mismatch or "")
        if p0:
            step = ckpt_lib._step_of(ckpt_lib.Path(path))
            skipped = rs_info.get("bytes_skipped", 0)
            print(f"serving checkpoint {path} ("
                  + (f"step {step}, " if step >= 0 else "")
                  + f"params-only restore: {rs_info['bytes_read']} B read"
                  + (f", {skipped} B of opt state skipped" if skipped else "")
                  + (f"; cross-world: {mismatch}" if mismatch else "") + ")")
    else:
        # smoke/bench mode: fresh seeded params directly at the shardings
        params = jax.jit(
            lambda r: init_fn(r).params, out_shardings=state_sharding.params
        )(jax.random.PRNGKey(flags.seed))
        if p0:
            print("serving fresh seeded params (no --checkpoint)")

    # ---- the draft model (--draft model, round 17) -----------------------
    # The draft is restored by the SAME params-only reader as the target,
    # replicated (its forward is not the audited program — replication
    # keeps any head count legal whatever the model axis), with its own
    # kind="ckpt_restore" ledger so the report's restore accounting sees
    # both reads.
    draft_params = draft_cfg = None
    if flags.draft == "model":
        from jax.sharding import NamedSharding, PartitionSpec
        from tpukit.model.gpt import init_params as gpt_init_params

        draft_cfg = GPTConfig(
            dim=flags.draft_dim, head_dim=flags.draft_head_dim,
            heads=flags.draft_heads, num_layers=flags.draft_num_layers,
            vocab_size=tokenizer.vocab_size,
            max_position_embeddings=flags.sequence_length,
            compute_dtype=cfg.compute_dtype,
        )
        d_shapes = jax.eval_shape(
            partial(gpt_init_params, cfg=draft_cfg),
            jax.random.PRNGKey(flags.seed),
        )
        repl = NamedSharding(mesh, PartitionSpec())
        d_sharding = jax.tree.map(lambda _: repl, d_shapes)
        if flags.draft_checkpoint:
            # path-only, deliberately NO "latest": latest_any() scans one
            # shared directory, so "latest" here and on --checkpoint would
            # always resolve to the SAME (newest) save — there is no way
            # to say "latest draft" vs "latest target" from one ledger
            d_path = flags.draft_checkpoint
            if d_path == "latest":
                raise ValueError(
                    "--draft_checkpoint takes an explicit path: 'latest' "
                    "would resolve through the same checkpoint directory "
                    "as --checkpoint latest and pick the identical "
                    "(newest) save for both models"
                )
            ok, detail = ckpt_lib.verify_checkpoint(d_path)
            if not ok:
                raise RuntimeError(
                    f"--draft_checkpoint {d_path}: failed integrity "
                    f"verification ({detail})")
            try:
                draft_params, d_info = ckpt_lib.restore_params(
                    d_path, d_shapes, d_sharding
                )
            except ValueError as exc:
                raise ValueError(
                    f"--draft_checkpoint {d_path}: state structure does "
                    f"not match the draft shape flags (--draft_dim/"
                    f"--draft_heads/--draft_num_layers... must equal the "
                    f"draft training run's). Original error: {exc}"
                ) from exc
            rec = dict(kind="ckpt_restore", params_only=True, draft=True,
                       checkpoint=str(d_path), **d_info)
            logger.log(**rec)
            recorder.record("ckpt_restore", params_only=True, draft=True)
            if p0:
                print(f"draft model {d_path} (params-only restore: "
                      f"{d_info['bytes_read']} B read)")
        else:
            draft_params = jax.jit(
                partial(gpt_init_params, cfg=draft_cfg),
                out_shardings=d_sharding,
            )(jax.random.PRNGKey(flags.seed + 1))
            if p0:
                print("draft model: fresh seeded params "
                      "(no --draft_checkpoint)")

    # ---- the engine + the stream -----------------------------------------
    serve = ServeConfig(
        slots=flags.slots, buckets=buckets,
        max_new_tokens=flags.max_new_tokens,
        temperature=flags.temperature, top_k=flags.top_k,
        window_steps=flags.window_steps,
        decode_quantum=flags.decode_quantum,
        page_size=flags.page_size, num_pages=flags.num_pages,
        kv_dtype=flags.kv_dtype, prefill_chunk=flags.prefill_chunk,
        draft=flags.draft, spec_k=flags.spec_k, ngram_max=flags.ngram_max,
        fused_decode=flags.fused_decode,
    )
    # Request-scoped tracing (round 20): on by default — the recorder is a
    # bounded ring of host-side span events, asserted <1% overhead and
    # token-bit-identical on/off by tests/test_trace.py.
    tracer = (None if flags.no_trace
              else TraceRecorder(capacity=flags.trace_capacity))
    # Metrics plane (round 22): on by default; --slo parses NOW so a
    # typo'd objective fails the launch, not silently never gates
    # (chaos-grammar discipline; SloSpecError is a clean startup error).
    metrics = None if flags.no_metrics else MetricRegistry()
    slo = parse_slo(flags.slo) if flags.slo else None
    engine = ServeEngine(params, cfg, serve, eos_id=int(tokenizer.eos_token_id),
                         mesh=mesh, logger=logger, recorder=recorder,
                         tracer=tracer, metrics=metrics, slo=slo,
                         metrics_dir=flags.metrics_dir or None,
                         draft_params=draft_params, draft_cfg=draft_cfg)
    requests = synthetic_request_stream(
        tokenizer, flags.requests, seed=flags.seed,
        max_new_tokens=flags.max_new_tokens, buckets=buckets, qps=flags.qps,
        shared_prefix=flags.shared_prefix,
        stream_profile=flags.stream_profile,
    )
    t0 = time.perf_counter()
    completions = engine.run(requests)
    wall = time.perf_counter() - t0

    if p0:
        gen = sum(c.generated for c in completions)
        e2e = sorted(c.e2e_s for c in completions)
        occ = (engine.last_summary or {}).get("mean_occupancy") or 0.0
        print(f"served {len(completions)} requests / {gen} tokens in "
              f"{wall:.2f}s ({gen / wall:.1f} tokens/s, occupancy "
              f"{100 * occ:.0f}%)")
        if serve.paged:
            s = engine.last_summary or {}
            print(f"paged KV: {s.get('num_pages')} pages x "
                  f"{s.get('page_size')} tokens ({s.get('kv_dtype')}), "
                  f"prefix hits {s.get('prefix_hits', 0)}/"
                  f"{s.get('admitted', 0)} admissions, "
                  f"{s.get('prefix_pages_reused', 0)} pages of prefill "
                  f"skipped")
        if serve.draft:
            sp = (engine.last_summary or {}).get("spec") or {}
            rate = sp.get("accept_rate")
            print(f"speculative decoding ({serve.draft}, k={serve.spec_k}): "
                  f"accepted {sp.get('accepted', 0)}/{sp.get('proposed', 0)} "
                  f"draft tokens"
                  + (f" ({100 * rate:.0f}%)" if rate is not None else "")
                  + f", appended/verify histogram "
                  f"{sp.get('accepted_hist', [])}")
        if e2e:
            print(f"e2e latency p50 {1e3 * e2e[len(e2e) // 2]:.1f} ms  "
                  f"p99 {1e3 * e2e[min(len(e2e) - 1, int(len(e2e) * 0.99))]:.1f} ms")
        s = engine.last_summary or {}
        if s.get("trace_complete") is not None:
            p50p = s.get("phase_p50") or {}
            print(f"traces: {100 * s['trace_complete']:.0f}% complete span "
                  f"trees; phase p50 (ms) "
                  + "  ".join(f"{k} {1e3 * v:.1f}"
                              for k, v in p50p.items() if v)
                  + (f" (view: python tools/traceview.py {flags.metrics_log})"
                     if flags.metrics_log else ""))
        if s.get("trace_dropped"):
            print(f"WARNING: {s['trace_dropped']} trace events evicted "
                  f"(ring saturated) — phase aggregates above are built "
                  f"from an incomplete history; grow --trace_capacity")
        if s.get("slo_overall_compliance") is not None:
            print(f"SLO compliance {100 * s['slo_overall_compliance']:.2f}% "
                  f"(worst target, cumulative) for --slo {flags.slo!r}")
        if flags.metrics_dir:
            print(f"metric snapshots -> {flags.metrics_dir} "
                  f"(live: python tools/top.py {flags.metrics_log or '-'} "
                  f"--metrics_dir {flags.metrics_dir})")
        for c in completions[:3]:
            print(f"  [{c.rid}] " + tokenizer.decode(
                np.asarray(c.ids), skip_special_tokens=True))
        if flags.metrics_log:
            print(f"serve telemetry -> {flags.metrics_log} "
                  f"(render: python tools/report.py {flags.metrics_log})")
    logger.close()
    return 0


def _apply_request_knobs(requests, flags):
    """Apply the stream-wide request robustness knobs (round 24):
    `--deadline_ms` stamps every synthetic request with a completion
    deadline (the engine evicts over-deadline lanes with their partial
    tokens as reason=\"deadline\")."""
    if not flags.deadline_ms:
        return requests
    import dataclasses

    return [dataclasses.replace(r, deadline_ms=flags.deadline_ms)
            for r in requests]


def _run_fleet_worker(flags, cfg, tokenizer, buckets) -> int:
    """INTERNAL (`--fleet_worker N`, set by the --fleet_procs supervisor
    re-execing this recipe): run ONE replica engine as a real process
    driven entirely through the durable ledger under `--fleet_dir` —
    claim leases addressed to this replica, decode, publish exactly-once
    completion records, beat the heartbeat file, exit on the
    supervisor's stop record. The worker does its OWN params cold start
    (processes share no memory; the ledger directory is the only
    channel) and never writes the supervisor's JSONL."""
    import jax
    from functools import partial

    from tpukit import checkpoint as ckpt_lib
    from tpukit.serve import ServeConfig, ServeEngine, serve_from_ledger
    from tpukit.serve.fleet import place_replica_params
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer

    serve = ServeConfig(
        slots=flags.slots, buckets=buckets,
        max_new_tokens=flags.max_new_tokens,
        temperature=flags.temperature, top_k=flags.top_k,
        window_steps=flags.window_steps,
        decode_quantum=flags.decode_quantum,
        page_size=flags.page_size, num_pages=flags.num_pages,
        kv_dtype=flags.kv_dtype, prefill_chunk=flags.prefill_chunk,
        draft=flags.draft, spec_k=flags.spec_k, ngram_max=flags.ngram_max,
        fused_decode=flags.fused_decode,
    )
    optimizer = make_optimizer(1e-4)
    init_fn = partial(create_train_state, cfg=cfg, optimizer=optimizer,
                      strategy=SingleDevice())
    state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(flags.seed))
    if flags.checkpoint:
        path = (ckpt_lib.latest_any() if flags.checkpoint == "latest"
                else flags.checkpoint)
        if path is None:
            raise FileNotFoundError("--checkpoint latest: no checkpoint found")
        params_host, _ = ckpt_lib.restore_params(
            path, state_shapes.params, None
        )
        params = place_replica_params(params_host, None)
    else:
        params = jax.jit(lambda r: init_fn(r).params)(
            jax.random.PRNGKey(flags.seed)
        )
    engine = ServeEngine(
        params, cfg, serve, eos_id=int(tokenizer.eos_token_id), mesh=None,
        logger=None, recorder=None, replica=flags.fleet_worker,
    )
    comps = serve_from_ledger(engine, flags.fleet_dir, flags.fleet_worker)
    print(f"fleet worker {flags.fleet_worker}: {len(comps)} completion(s) "
          f"published")
    return 0


def _run_fleet_procs(flags, cfg, tokenizer, buckets) -> int:
    """Process fleet (`--fleet_procs`, round 24): each replica is a real
    worker PROCESS (this recipe re-exec'd with `--fleet_worker i`)
    coordinated only through the durable ledger under `--fleet_dir`.
    `--fleet_kill replica_sigkill@R` delivers a REAL SIGKILL mid-stream;
    liveness (process exit + heartbeat age) revokes the victim's leases
    and requeues its in-flight requests on survivors with the
    `--request_retries` budget — the crash-consistency claim the
    in-process router can only simulate."""
    import os
    import subprocess

    from tpukit import chaos as chaos_lib
    from tpukit.obs import FlightRecorder, StepLogger
    from tpukit.serve import ProcessFleet, synthetic_request_stream

    if not flags.fleet_dir:
        raise ValueError(
            "--fleet_procs requires --fleet_dir: the ledger directory is "
            "the only channel between supervisor and worker processes"
        )
    logger = StepLogger(flags.metrics_log)
    recorder = FlightRecorder()

    def spawn(idx):
        argv = ([sys.executable, sys.argv[0]] + list(sys.argv[1:])
                + ["--fleet_worker", str(idx)])
        return subprocess.Popen(argv, env=dict(os.environ))

    requests = _apply_request_knobs(
        synthetic_request_stream(
            tokenizer, flags.requests, seed=flags.seed,
            max_new_tokens=flags.max_new_tokens, buckets=buckets,
            qps=flags.qps, shared_prefix=flags.shared_prefix,
            stream_profile=flags.stream_profile,
        ),
        flags,
    )
    pf = ProcessFleet(
        flags.fleet_dir, spawn=spawn, replicas=flags.replicas,
        replica_timeout=flags.replica_timeout or 5.0,
        request_retries=flags.request_retries,
        chaos=chaos_lib.ServingChaos(flags.fleet_kill),
        logger=logger, recorder=recorder,
    )
    rec = pf.run(requests)
    print(f"process fleet served {rec['requests']} requests / "
          f"{rec['generated_tokens']} tokens in {rec['wall_s']:.2f}s over "
          f"{flags.replicas} worker process(es)")
    if rec["replicas_dead"] or rec["kills"]:
        print(f"  failures: {rec['kills']} SIGKILL(s), "
              f"{rec['replicas_dead']} replica death(s), "
              f"{rec['leases_revoked']} lease(s) revoked, "
              f"{rec['requeued']} request(s) re-queued, "
              f"{rec['duplicate_completions']} duplicate completion(s)")
    if rec["request_failures"] or rec["deadline_misses"]:
        print(f"  requests: {rec['request_failures']} terminal failure(s), "
              f"{rec['deadline_misses']} deadline miss(es)")
    if rec["retry_total"]:
        print(f"  {rec['retry_total']} transient I/O error(s) retried")
    if flags.metrics_log:
        print(f"fleet telemetry -> {flags.metrics_log} "
              f"(render: python tools/report.py {flags.metrics_log})")
    logger.close()
    return 0


def _run_fleet(flags, cfg, tokenizer, buckets) -> int:
    """Fleet serving (round 19, ROADMAP #1): route the stream over
    `--replicas` ServeEngine replicas on disjoint device subsets via
    `tpukit/serve/fleet.FleetRouter`. The checkpoint cold start is SHARED:
    `checkpoint.restore_params(..., sharding_tree=None)` reads the bytes
    ONCE into host arrays, and every replica placement is a device_put of
    that one copy — the `kind="ckpt_restore"` ledger records bytes_read
    once with the placement count alongside, so N replicas never imply
    N checkpoint reads. Round 24 adds the crash-tolerance plane: worker
    (`--fleet_worker`) and process-fleet (`--fleet_procs`) modes dispatch
    before the in-process router below."""
    if flags.fleet_worker >= 0:
        return _run_fleet_worker(flags, cfg, tokenizer, buckets)
    if flags.fleet_procs:
        return _run_fleet_procs(flags, cfg, tokenizer, buckets)
    import time
    from functools import partial

    import jax
    import numpy as np

    from tpukit import checkpoint as ckpt_lib
    from tpukit.mesh import is_process_zero
    from tpukit.obs import (
        FlightRecorder,
        MetricRegistry,
        StepLogger,
        TraceRecorder,
        parse_slo,
    )
    from tpukit.serve import (
        FleetConfig,
        FleetRouter,
        ServeConfig,
        synthetic_request_stream,
    )
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer

    if flags.draft == "model":
        raise ValueError(
            "--replicas with --draft model is a future round (the draft "
            "params would need their own per-replica placement); "
            "--draft ngram (self-speculation, no second model) runs per "
            "replica today"
        )
    serve = ServeConfig(
        slots=flags.slots, buckets=buckets,
        max_new_tokens=flags.max_new_tokens,
        temperature=flags.temperature, top_k=flags.top_k,
        window_steps=flags.window_steps,
        decode_quantum=flags.decode_quantum,
        page_size=flags.page_size, num_pages=flags.num_pages,
        kv_dtype=flags.kv_dtype, prefill_chunk=flags.prefill_chunk,
        draft=flags.draft, spec_k=flags.spec_k, ngram_max=flags.ngram_max,
        fused_decode=flags.fused_decode,
    )
    fleet = FleetConfig(
        replicas=flags.replicas,
        devices_per_replica=flags.devices_per_replica,
        min_replicas=flags.min_replicas, max_replicas=flags.max_replicas,
        scale_up_occupancy=flags.scale_up_occupancy,
        scale_down_occupancy=flags.scale_down_occupancy,
        window_steps=flags.fleet_window_steps,
        disagg_prefill=flags.disagg_prefill,
        prefill_slots=flags.prefill_slots, prefill_pages=flags.prefill_pages,
        kill_spec=flags.fleet_kill,
        fleet_dir=flags.fleet_dir,
        replica_timeout=flags.replica_timeout,
        request_retries=flags.request_retries,
        max_queue_depth=flags.max_queue_depth,
    )
    logger = StepLogger(flags.metrics_log)
    recorder = FlightRecorder()
    p0 = is_process_zero()

    # Shapes only (strategy-independent): the template for the params-only
    # host read. Nothing is materialized here.
    optimizer = make_optimizer(1e-4)
    init_fn = partial(create_train_state, cfg=cfg, optimizer=optimizer,
                      strategy=SingleDevice())
    state_shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(flags.seed))

    path = rs_info = None
    if flags.checkpoint:
        path = (ckpt_lib.latest_any() if flags.checkpoint == "latest"
                else flags.checkpoint)
        if path is None:
            raise FileNotFoundError("--checkpoint latest: no checkpoint found")
        ok, detail = ckpt_lib.verify_checkpoint(path)
        if not ok:
            raise RuntimeError(f"--checkpoint {path}: failed integrity "
                               f"verification ({detail})")
        try:
            # sharding_tree=None keeps the leaves on HOST — the one read
            params_host, rs_info = ckpt_lib.restore_params(
                path, state_shapes.params, None
            )
        except ValueError as exc:
            raise ValueError(
                f"--checkpoint {path}: state structure does not match "
                f"the model flags (--dim/--heads/--num_layers/"
                f"--num_experts... must equal the training run's). "
                f"Original error: {exc}"
            ) from exc
    else:
        params_host = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)),
            jax.jit(lambda r: init_fn(r).params)(jax.random.PRNGKey(flags.seed)),
        )
        if p0:
            print("serving fresh seeded params (no --checkpoint)")

    # One shared TraceRecorder across router + replicas + prefill worker:
    # span events land in per-replica rings and merge into one event stream.
    tracer = (None if flags.no_trace
              else TraceRecorder(capacity=flags.trace_capacity))
    # One shared MetricRegistry too (round 22): replica engines observe
    # replica-labeled series into it; the router accounts the declared
    # --slo fleet-wide and owns the --metrics_dir snapshot publish/merge.
    metrics = None if flags.no_metrics else MetricRegistry()
    slo = parse_slo(flags.slo) if flags.slo else None
    router = FleetRouter(params_host, cfg, serve, fleet,
                         eos_id=int(tokenizer.eos_token_id),
                         logger=logger, recorder=recorder, tracer=tracer,
                         metrics=metrics, slo=slo,
                         metrics_dir=flags.metrics_dir or None)
    if path is not None:
        rec = dict(kind="ckpt_restore", params_only=True, fleet=True,
                   checkpoint=str(path), replicas=flags.replicas,
                   placements=router.placements, **rs_info)
        logger.log(**rec)
        recorder.record("ckpt_restore", params_only=True, fleet=True,
                        placements=router.placements)
        if p0:
            print(f"fleet cold start from {path}: "
                  f"{rs_info['bytes_read']} B read ONCE, "
                  f"{router.placements} placement(s) for "
                  f"{flags.replicas} replica(s)"
                  + (" + prefill worker" if fleet.disagg_prefill else ""))

    requests = _apply_request_knobs(
        synthetic_request_stream(
            tokenizer, flags.requests, seed=flags.seed,
            max_new_tokens=flags.max_new_tokens, buckets=buckets,
            qps=flags.qps, shared_prefix=flags.shared_prefix,
            stream_profile=flags.stream_profile,
        ),
        flags,
    )
    t0 = time.perf_counter()
    completions = router.run(requests)
    wall = time.perf_counter() - t0

    if p0:
        s = router.last_summary or {}
        gen = sum(c.generated for c in completions)
        print(f"fleet served {len(completions)} requests / {gen} tokens in "
              f"{wall:.2f}s ({gen / wall:.1f} tokens/s) over "
              f"{s.get('replicas_final', '?')} replica(s) "
              f"(peak {s.get('replicas_peak', '?')})")
        if s.get("kills") or s.get("requeued"):
            print(f"  failures: {s.get('kills', 0)} replica kill(s) "
                  f"({s.get('replicas_dead', 0)} by liveness), "
                  f"{s.get('leases_revoked', 0)} lease(s) revoked, "
                  f"{s.get('requeued', 0)} request(s) re-queued, "
                  f"{s.get('duplicate_completions', 0)} duplicate "
                  f"completion(s)")
        if (s.get("deadline_misses") or s.get("rejected")
                or s.get("request_failures")):
            print(f"  requests: {s.get('deadline_misses', 0)} deadline "
                  f"miss(es), {s.get('rejected', 0)} shed by backpressure, "
                  f"{s.get('request_failures', 0)} terminal failure(s)")
        if s.get("ledger"):
            led = s["ledger"]
            print(f"  ledger: {led.get('completed', 0)} durable completion "
                  f"record(s), {led.get('replayed', 0)} replayed, "
                  f"{led.get('duplicates', 0)} duplicate(s) "
                  f"-> {flags.fleet_dir}")
        if s.get("scale_ups") or s.get("scale_downs"):
            print(f"  autoscale: {s.get('scale_ups', 0)} up / "
                  f"{s.get('scale_downs', 0)} down")
        if fleet.disagg_prefill:
            d = s.get("disagg_prefill") or {}
            print(f"  disaggregated prefill: {d.get('handoffs', 0)} "
                  f"handoffs, {d.get('worker_prefix_hits', 0)} worker "
                  f"prefix hits, {d.get('worker_pages_reused', 0)} pages "
                  f"of prefill skipped")
        p50, p99 = s.get("p50_e2e_s"), s.get("p99_e2e_s")
        if p50 is not None:
            print(f"  e2e latency p50 {1e3 * p50:.1f} ms  "
                  f"p99 {1e3 * p99:.1f} ms")
        if s.get("trace_complete") is not None:
            p50p = s.get("phase_p50") or {}
            print(f"  traces: {100 * s['trace_complete']:.0f}% complete "
                  f"span trees; phase p50 (ms) "
                  + "  ".join(f"{k} {1e3 * v:.1f}"
                              for k, v in p50p.items() if v))
        if s.get("trace_dropped"):
            print(f"  WARNING: {s['trace_dropped']} trace events evicted "
                  f"(per replica {s.get('trace_dropped_by_replica')}) — "
                  f"grow --trace_capacity")
        if s.get("slo_overall_compliance") is not None:
            print(f"  SLO compliance "
                  f"{100 * s['slo_overall_compliance']:.2f}% (worst "
                  f"target, cumulative) for --slo {flags.slo!r}")
        if flags.metrics_log:
            print(f"fleet telemetry -> {flags.metrics_log} "
                  f"(render: python tools/report.py {flags.metrics_log})")
    logger.close()
    return 0


if __name__ == "__main__":
    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
