#!/usr/bin/env python
"""Recipe 3: fully-sharded (ZeRO-3-style) training.

TPU-native twin of reference `main-fsdp.py`. The reference wraps the model
in `FullyShardedDataParallel` with `size_based_auto_wrap_policy(
min_num_params=100)` (main-fsdp.py:60-69), sharding params and re-gathering
them per-module in forward/backward, with grads reduce-scattered; optional
`CPUOffload(offload_params=True)` behind `--cpu_offload` (main-fsdp.py:68,
219). Here the same capability is GSPMD sharding: every parameter, gradient
and optimizer-state tensor above the size threshold is sharded along the
`data` mesh axis; XLA inserts the all-gathers and reduce-scatters. The
consolidated end-of-training checkpoint (full state_dict gathered, rank-0
saves, main-fsdp.py:193-200) is the default tpukit checkpoint behavior.

Run: `python main-fsdp.py --batch_size 64 [--cpu_offload] ...`
"""

from tpukit.flags import parse_flags
from tpukit.shardings import FSDP
from tpukit.train import fit


def main(argv=None):
    flags = parse_flags(argv, cpu_offload=True)
    return fit(flags, FSDP(cpu_offload=flags.cpu_offload))


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
