"""Request-scoped serving traces (tpukit/obs/trace, round 20).

Contracts pinned here:
  - COMPLETENESS INVARIANT: on a traced meshless serve run, every
    completed request has a CLOSED span tree (enqueue, >=1 admit,
    exactly one finish) whose named phase walls sum to its e2e latency
    within 1e-3 s — end-to-end, not on crafted events;
  - a requeue-after-replica_kill links BOTH attempts under ONE trace id
    (attempts == 2, one finish) and exactly-once delivery is checkable
    from the trace alone (every trace has exactly one finish event);
  - tracing is an OBSERVER: output tokens are bit-identical with the
    tracer on vs off, and `TraceRecorder.emit` is cheap (bounded ring,
    O(1) append — the <1% serving-overhead budget bench.py measures);
  - the serve/fleet summaries carry per-phase p50/p99, trace_complete
    and the dispatch-vs-device split, and the window/summary wall split
    surfaces its residual as an explicit `other_s` >= 0;
  - `kind="trace_event"`/`kind="trace"` rows land in the metrics JSONL,
    `tools/report.py --min_trace_complete` gates on them (failing on
    trace-less logs — anti-vacuous), and `tools/traceview.py` renders
    the post-mortem + a parseable Chrome-trace export with one closed
    tree per completed request;
  - `tpukit/obs/trace.py` stays stdlib-only (no jax/numpy import), the
    property that lets traceview run anywhere the log was copied to.
"""

import importlib
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.obs import StepLogger, TraceRecorder
from tpukit.obs import trace as trace_lib
from tpukit.serve import (
    FleetConfig,
    FleetRouter,
    ServeConfig,
    ServeEngine,
    synthetic_request_stream,
)

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


@pytest.fixture(scope="module")
def host_params(params):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)


def _run_traced(params, cfg, tok, n=8, logger=None, **serve_kw):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=4, **serve_kw)
    reqs = synthetic_request_stream(tok, n, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    tracer = TraceRecorder()
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      tracer=tracer, logger=logger)
    comps = eng.run(list(reqs), max_wall_s=300)
    return eng, tracer, comps


# ---------------------------------------------------------------------------
# The completeness invariant, end-to-end on a real engine run.
# ---------------------------------------------------------------------------


def test_every_completion_has_a_complete_tree(tok, cfg, params):
    eng, tracer, comps = _run_traced(params, cfg, tok)
    trees = trace_lib.build_trees(tracer.snapshot())
    by_rid = {t["rid"]: t for t in trees}
    assert len(comps) == 8
    for c in comps:
        t = by_rid[c.rid]
        assert t["closed"], f"rid {c.rid}: open tree"
        assert t["complete"], (
            f"rid {c.rid}: named walls overran e2e by {t['residual_s']:.6f}s"
        )
        named = sum(v for k, v in t["phases"].items() if k != "other")
        assert named <= t["e2e_s"] + trace_lib.SUM_TOL_S
        # the walls + the residual `other` reconstruct e2e exactly
        assert sum(t["phases"].values()) == pytest.approx(t["e2e_s"], abs=1e-6)
        assert t["quanta"] > 0 and t["attempts"] == 1
        assert t["reason"] in ("eos", "length")
    assert trace_lib.completeness(trees) == 1.0
    assert tracer.dropped == 0


def test_summary_carries_phase_stats_and_attribution(tok, cfg, params):
    eng, tracer, comps = _run_traced(params, cfg, tok)
    s = eng.last_summary
    assert s["trace_complete"] == 1.0
    for key in ("phase_p50", "phase_p99"):
        assert set(s[key]) == set(trace_lib.PHASES)
    assert s["phase_p99"]["decode"] >= s["phase_p50"]["decode"] > 0
    # satellite: the wall split surfaces its residual explicitly
    assert s["other_s"] >= 0.0
    named = s["prefill_s"] + s["decode_s"] + s["sync_s"] + s["other_s"]
    assert named == pytest.approx(s["wall_s"], rel=0.05)
    # dispatch-vs-device attribution present and sane
    assert s["dispatch_overhead_s"] > 0 and s["device_s"] >= 0
    assert s["device_s"] == s["sync_s"]


def test_window_records_carry_attribution(tok, cfg, params, tmp_path):
    log = tmp_path / "serve.jsonl"
    logger = StepLogger(str(log))
    _run_traced(params, cfg, tok, logger=logger)
    logger.close()
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    wins = [r for r in recs if r["kind"] == "serve"]
    assert wins
    for w in wins:
        assert w["other_s"] >= 0.0
        assert w["dispatch_overhead_s"] >= 0.0
        assert w["device_s"] == pytest.approx(w["seconds"].get("sync", 0.0))


# ---------------------------------------------------------------------------
# Observer discipline: bit-identical tokens, bounded + cheap ring.
# ---------------------------------------------------------------------------


def test_tokens_bit_identical_tracer_on_off(tok, cfg, params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=4, temperature=0.9, top_k=5)
    reqs = list(synthetic_request_stream(tok, 6, seed=5,
                                         max_new_tokens=MAX_NEW,
                                         buckets=(8, 16)))
    def run(tracer):
        eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                          tracer=tracer)
        return {c.rid: list(map(int, c.ids))
                for c in eng.run(list(reqs), max_wall_s=300)}

    assert run(None) == run(TraceRecorder())


def test_recorder_ring_bounded_and_cheap():
    import time

    tr = TraceRecorder(capacity=256)
    t0 = time.perf_counter()
    for i in range(20_000):
        tr.emit("quantum", -1, t0=0.0, t1=1.0, s0=1.0, s1=2.0,
                steps=4, lanes=[i], replica=i % 2)
    wall = time.perf_counter() - t0
    assert wall < 1.0  # 20k emits: O(1) dict+deque appends under a lock
    assert len(tr) == 2 * 256  # bounded per ring
    assert tr.total_emitted == 20_000
    assert tr.dropped == 20_000 - 2 * 256
    with pytest.raises(ValueError):
        TraceRecorder(capacity=0)


def test_trace_module_is_stdlib_only():
    import ast

    tree = ast.parse(Path(trace_lib.__file__).read_text())
    imported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            imported |= {a.name.split(".")[0] for a in node.names}
        elif isinstance(node, ast.ImportFrom) and node.module:
            imported.add(node.module.split(".")[0])
    assert not imported & {"jax", "numpy", "tpukit"}, (
        f"trace.py must stay stdlib-only (traceview loads it by path with "
        f"no jax installed); imports {sorted(imported)}"
    )


# ---------------------------------------------------------------------------
# Fleet: requeue-after-kill links both attempts under ONE trace id, and
# exactly-once is checkable from the trace alone.
# ---------------------------------------------------------------------------


def test_kill_requeue_links_attempts_under_one_trace(tok, cfg, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    tracer = TraceRecorder()
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4, kill_spec="replica_kill@1:1"),
        eos_id=int(tok.eos_token_id), tracer=tracer)
    comps = router.run(list(reqs), max_wall_s=300)
    s = router.last_summary
    assert s["kills"] == 1 and s["requeued"] >= 1
    assert len(comps) == 8

    events = tracer.snapshot()
    trees = trace_lib.build_trees(events)
    by_rid = {t["rid"]: t for t in trees}
    # every completion: a closed tree, one finish, complete walls
    assert trace_lib.completeness(trees) == 1.0
    for c in comps:
        assert by_rid[c.rid]["closed"]
    # exactly-once, FROM THE TRACE ALONE: one finish event per trace id
    fins: dict = {}
    for e in events:
        if e["ev"] == "finish":
            fins[e["trace"]] = fins.get(e["trace"], 0) + 1
    assert len(fins) == 8 and set(fins.values()) == {1}
    assert s["duplicate_completions"] == 0
    # the requeued victims: BOTH attempts live under one trace id — a
    # requeue event, two admits, still exactly one finish
    requeued_traces = {e["trace"] for e in events if e["ev"] == "requeue"}
    assert len(requeued_traces) == s["requeued"]
    for t in trees:
        if t["trace"] in requeued_traces:
            assert t["attempts"] == 2, (
                f"trace {t['trace']}: requeued but {t['attempts']} attempt(s)"
            )
            assert len(t["replicas"]) >= 1 and t["complete"]
            # its queue_wait includes the second wait-in-line
            assert t["phases"]["queue_wait"] > 0
    # the fleet summary carries the fleet-wide phase view
    assert s["trace_complete"] == 1.0
    assert set(s["phase_p50"]) == set(trace_lib.PHASES)


# ---------------------------------------------------------------------------
# Tree building on crafted events (unit-level edge cases).
# ---------------------------------------------------------------------------


def test_build_trees_requeue_accounting():
    evs = [
        dict(ev="enqueue", trace=7, rid=7, t=0.0, replica=None),
        dict(ev="admit", trace=7, rid=7, t=1.0, slot=0, replica=0),
        dict(ev="prefill_done", trace=7, rid=7, t=1.5, replica=0),
        dict(ev="quantum", trace=-1, t0=1.5, t1=1.6, s0=1.6, s1=1.8,
             steps=4, lanes=[7], replica=0),
        dict(ev="requeue", trace=7, rid=7, t=2.0, from_replica=0,
             replica="router"),
        dict(ev="admit", trace=7, rid=7, t=3.0, slot=1, replica=1),
        dict(ev="prefill_done", trace=7, rid=7, t=3.25, replica=1),
        dict(ev="quantum", trace=-1, t0=3.25, t1=3.3, s0=3.3, s1=3.5,
             steps=4, lanes=[7], replica=1),
        dict(ev="finish", trace=7, rid=7, t=3.5, reason="eos", generated=8,
             replica=1),
    ]
    (t,) = trace_lib.build_trees(evs)
    assert t["closed"] and t["complete"] and t["attempts"] == 2
    ph = t["phases"]
    assert ph["queue_wait"] == pytest.approx(1.0 + 1.0)  # both waits
    assert ph["prefill"] == pytest.approx(0.5 + 0.25)
    assert ph["decode"] == pytest.approx(0.1 + 0.05)
    assert ph["sync_stall"] == pytest.approx(0.2 + 0.2)
    assert t["e2e_s"] == pytest.approx(3.5)
    assert t["replicas"] == ["0", "1"]
    assert t["quanta"] == 2 and t["generated"] == 8


def test_build_trees_open_and_overrun_trees():
    # no finish -> open, not complete
    open_evs = [
        dict(ev="enqueue", trace=1, rid=1, t=0.0),
        dict(ev="admit", trace=1, rid=1, t=0.5, slot=0),
    ]
    (t,) = trace_lib.build_trees(open_evs)
    assert not t["closed"] and not t["complete"]
    # named walls overrunning e2e -> closed but NOT complete
    bad = [
        dict(ev="enqueue", trace=2, rid=2, t=0.0),
        dict(ev="admit", trace=2, rid=2, t=0.5, slot=0),
        dict(ev="prefill_done", trace=2, rid=2, t=0.6),
        dict(ev="quantum", trace=-1, t0=0.0, t1=5.0, s0=5.0, s1=5.0,
             steps=1, lanes=[2]),
        dict(ev="finish", trace=2, rid=2, t=1.0, reason="eos", generated=1),
    ]
    (t,) = trace_lib.build_trees(bad)
    assert t["closed"] and not t["complete"] and t["residual_s"] > 1.0


def test_percentile_matches_numpy():
    vals = [3.0, 1.0, 4.0, 1.5, 9.0, 2.6, 5.3]
    for q in (0, 25, 50, 99, 100):
        assert trace_lib.percentile(vals, q) == pytest.approx(
            float(np.percentile(vals, q))
        )
    assert trace_lib.percentile([], 50) is None
    assert trace_lib.percentile([2.0], 99) == 2.0


# ---------------------------------------------------------------------------
# Persistence + tools: JSONL rows, the report gate, traceview + export.
# ---------------------------------------------------------------------------


def _traced_log(tok, cfg, params, tmp_path):
    log = tmp_path / "run.jsonl"
    logger = StepLogger(str(log))
    eng, tracer, comps = _run_traced(params, cfg, tok, logger=logger)
    logger.close()
    return log, comps


def test_jsonl_rows_and_report_gate(tok, cfg, params, tmp_path):
    log, comps = _traced_log(tok, cfg, params, tmp_path)
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    events = [r for r in recs if r["kind"] == "trace_event"]
    trees = [r for r in recs if r["kind"] == "trace"]
    assert events and len(trees) == len(comps)
    assert all(t["complete"] for t in trees)

    report = importlib.import_module("tools.report")
    ok, msg = report.check_min_trace_complete(recs, 1.0)
    assert ok and "OK" in msg
    # anti-vacuous: a trace-less log FAILS the gate
    ok, msg = report.check_min_trace_complete(
        [r for r in recs if r["kind"] != "trace"], 1.0)
    assert not ok
    # the rendered summary carries the phase + completeness lines
    text = report.summarize(recs)
    assert "request phases p50/p99" in text
    assert "100% complete span trees" in text
    assert "dispatch vs device" in text
    # exit-2 wiring
    assert report.main([str(log), "--min_trace_complete", "1.0"]) == 0
    assert report.main([str(log), "--min_trace_complete", "1.1"]) == 2


def test_traceview_renders_and_exports(tok, cfg, params, tmp_path, capsys):
    log, comps = _traced_log(tok, cfg, params, tmp_path)
    traceview = importlib.import_module("tools.traceview")
    out = tmp_path / "trace.json"
    assert traceview.main([str(log), "--out", str(out)]) == 0
    text = capsys.readouterr().out
    assert "== request traces ==" in text and "100% complete" in text
    chrome = json.loads(out.read_text())
    assert chrome["traceEvents"]
    # one closed phase-bar set per completed request in the export
    phase_rows = {e["tid"] for e in chrome["traceEvents"]
                  if e.get("cat") == "phase"}
    assert len(phase_rows) == len(comps)
    # --rid filter narrows to one request
    rid = comps[0].rid
    assert traceview.main([str(log), "--rid", str(rid)]) == 0
    # a log with no trace events exits nonzero
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps({"kind": "train", "step": 1}) + "\n")
    assert traceview.main([str(bare)]) == 1
