"""Fused paged decode (round 21, ROADMAP #2/#4): the Pallas
paged-attention kernel (tpukit/ops/paged_attention.py) and the on-device
scheduler window (decode.decode_loop_window), both behind
`--fused_decode`.

Contracts pinned here:
  - the kernel is the gathered-view reference (`paged.gather_view` +
    `_attend_over_cache` math) op-for-op: logits agree to the ~1-ULP dot
    reassociation of the backend (interpret mode *scans* the grid, so
    kernel dots compile inside a loop body and XLA:CPU picks a different
    accumulation order than the eager einsum — measured max ~5e-7 f32 at
    test shapes, and NOT reducible by barriers), while TOKEN streams are
    exactly identical — greedy and fixed-seed sampled, at the forward,
    decode_step, and full-engine levels;
  - a one-position window degenerates to the fresh token exactly, and
    positions beyond the cursor never contribute: null/garbage/recycled
    page ids behind the cursor are annihilated bit-for-bit (the ragged
    block-table story);
  - int8 pages dequantize in-kernel on the quant_comm block layout to
    the same values the gather path dequantizes — token agreement >= 90%
    is the gate (in practice 100% at test scale; int8 is lossy vs f32,
    never vs the unfused int8 path);
  - decode_loop_window == repeated decode_step for ANY window schedule,
    including early exit on the freed-page account — ticks/freed report
    what actually ran, and resuming after an early exit lands on the
    same stream;
  - under the model-only TP mesh the fused step and the whole while-loop
    window move EXACTLY `decode_step_comm(paged=True)` — the kernel adds
    no comm (shard_map, zero body collectives) and the loop body's
    collectives appear ONCE regardless of window size — with zero
    involuntary-remat warnings;
  - bad layouts fail with NAMED errors (VMEM budget, int8 quant-block
    tiling, fused without the paged cache), never Mosaic/XLA shape
    errors;
  - the fused engine's traces stay complete (1.0) with window-granular
    quantum spans whose `steps` is the device-reported tick count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.model import gpt
from tpukit.ops import quant_comm
from tpukit.ops import paged_attention as pa
from tpukit.ops.pallas_attention import online_softmax_update
from tpukit.sampling import _decode_loop_cached
from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream
from tpukit.serve import decode as sd
from tpukit.serve import paged as paged_lib

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=96, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


def _serial(params, cfg, ids, max_new=MAX_NEW, eos_id=None, temperature=0.0,
            top_k=0, seed=0):
    ids = np.asarray(ids, np.int32)
    buf = np.zeros((1, len(ids) + max_new), np.int32)
    buf[0, : len(ids)] = ids
    out, length = _decode_loop_cached(
        params, cfg, jnp.asarray(buf), len(ids), max_new, int(eos_id),
        temperature=float(temperature),
        top_k=min(int(top_k), cfg.padded_vocab_size),
        rng=jnp.asarray(np.asarray(jax.random.PRNGKey(seed)))
        if temperature > 0.0
        else None,
    )
    return np.asarray(out)[0, : int(length)]


def _ref_attend(pool_k, pool_v, scale_k, scale_v, bt, start, q, kn, vn):
    """The unfused spelling of the kernel's contract: gather_view, insert
    the fresh K/V at the cursor with the ring path's dynamic-update-slice,
    then `_attend_over_cache`'s math verbatim (pre-projection)."""
    cdt = q.dtype
    view_k = paged_lib.gather_view(pool_k, scale_k, bt, cdt)
    view_v = paged_lib.gather_view(pool_v, scale_v, bt, cdt)
    upd = lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (0, s, 0))
    view_k = jax.vmap(upd)(view_k, kn[:, :, None, :], start)
    view_v = jax.vmap(upd)(view_v, vn[:, :, None, :], start)
    d = q.shape[-1]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q[:, :, None, :], view_k
    ) * (1.0 / d**0.5)
    q_pos = (start[:, None] + jnp.arange(1))[:, None, :, None]
    key_pos = jnp.arange(view_k.shape[2])[None, None, None, :]
    scores = jnp.where(key_pos <= q_pos, scores, jnp.asarray(-1e9, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(view_v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, view_v)[:, :, 0, :]


def _rand_kernel_operands(dtype=jnp.float32, h=4, p=8, d=8, mp=3, n=4,
                          seed=0):
    np_pages = n * mp + 1
    rng = np.random.RandomState(seed)
    pool_k = jnp.asarray(rng.randn(np_pages, h, p, d), dtype)
    pool_v = jnp.asarray(rng.randn(np_pages, h, p, d), dtype)
    bt = jnp.asarray(np.arange(1, n * mp + 1).reshape(n, mp), jnp.int32)
    start = jnp.asarray([5, 0, 17, 23], jnp.int32)[:n]
    q = jnp.asarray(rng.randn(n, h, d), dtype)
    kn = jnp.asarray(rng.randn(n, h, d), dtype)
    vn = jnp.asarray(rng.randn(n, h, d), dtype)
    return pool_k, pool_v, bt, start, q, kn, vn


# ---------------------------------------------------------------------------
# The owner helper's exactness argument: one call == plain softmax, bit
# for bit. This degeneracy is what lets the one-block kernel claim the
# reference's math rather than "a flash approximation of it".
# ---------------------------------------------------------------------------


def test_online_softmax_single_call_is_plain_softmax():
    s = jnp.asarray(np.random.RandomState(0).randn(4, 24) * 3, jnp.float32)
    m0 = jnp.full((4, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((4, 1), jnp.float32)
    m, l, corr, p = online_softmax_update(m0, l0, s)
    ref = jax.nn.softmax(s, axis=-1)
    np.testing.assert_array_equal(np.asarray(p / l), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(m), np.asarray(jnp.max(s, -1, keepdims=True)))


# ---------------------------------------------------------------------------
# Kernel vs the gathered reference.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-5),
                                        (jnp.bfloat16, 5e-2)],
                         ids=["f32", "bf16"])
def test_paged_attend_matches_gathered_reference(dtype, atol):
    ops = _rand_kernel_operands(dtype)
    out = pa.paged_attend(ops[0], ops[1], None, None, *ops[2:])
    ref = _ref_attend(ops[0], ops[1], None, None, *ops[2:])
    assert out.dtype == ref.dtype == dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=atol, rtol=0)


def test_paged_attend_ragged_block_tables():
    """The block-table edge cases the engine actually produces: a cursor
    at 0 (fresh token only — the softmax over ONE position must return
    v_new exactly), a partially filled last page, page ids recycled
    across rows, and garbage pages behind the cursor (a freed page
    re-issued full of another request's K/V must be annihilated — the
    output may not depend on what the masked tail points at)."""
    pool_k, pool_v, bt, start, q, kn, vn = _rand_kernel_operands()
    # cursor 0: only the fresh token is in-window -> exact passthrough
    out = pa.paged_attend(pool_k, pool_v, None, None, bt, start, q, kn, vn)
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(vn[1]))
    # start=17 (row 2) is a partially filled last page; all rows match
    # the gathered reference
    ref = _ref_attend(pool_k, pool_v, None, None, bt, start, q, kn, vn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=0)
    # masked-tail independence: rows 0/1 sit early in their windows, so
    # repoint their tail pages at garbage (large finite values, and page
    # ids RECYCLED from other rows' tables) — output must not move a bit
    poison_k = pool_k.at[5].set(1e3).at[9].set(-1e3)
    poison_v = pool_v.at[5].set(1e3).at[9].set(-1e3)
    bt2 = np.asarray(bt).copy()
    bt2[0, 1:] = (5, 9)   # row 0 tail -> poisoned pages
    bt2[1, :] = (9, 5, 9)  # row 1 (cursor 0): EVERY page garbage + repeated
    out2 = pa.paged_attend(poison_k, poison_v, None, None,
                           jnp.asarray(bt2), start, q, kn, vn)
    np.testing.assert_array_equal(np.asarray(out2[:2]), np.asarray(out[:2]))


def test_paged_attend_int8_matches_gather_dequant():
    """int8 pools dequantize INSIDE the kernel tile-by-tile on the
    quant_comm block layout; the gather path dequantizes after the
    gather. Same blocks, same scales — the values must agree to the same
    ~1-ULP reassociation bar as f32."""
    h, p, d, mp, n = 4, 8, 32, 3, 4  # page*head_dim == 256 == quant block
    np_pages = n * mp + 1
    rng = np.random.RandomState(3)
    raw_k = jnp.asarray(rng.randn(np_pages, h, p * d), jnp.float32) * 0.3
    raw_v = jnp.asarray(rng.randn(np_pages, h, p * d), jnp.float32) * 0.3
    qk, sk = quant_comm.quantize_blocks(raw_k)
    qv, sv = quant_comm.quantize_blocks(raw_v)
    pool_k = qk.reshape(np_pages, h, p, d)
    pool_v = qv.reshape(np_pages, h, p, d)
    bt = jnp.asarray(np.arange(1, n * mp + 1).reshape(n, mp), jnp.int32)
    start = jnp.asarray([5, 0, 17, 23], jnp.int32)
    q = jnp.asarray(rng.randn(n, h, d), jnp.float32)
    kn = jnp.asarray(rng.randn(n, h, d), jnp.float32)
    vn = jnp.asarray(rng.randn(n, h, d), jnp.float32)
    out = pa.paged_attend(pool_k, pool_v, sk, sv, bt, start, q, kn, vn)
    ref = _ref_attend(pool_k, pool_v, sk, sv, bt, start, q, kn, vn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=0)


def test_paged_attend_named_errors(monkeypatch):
    ops = _rand_kernel_operands()
    monkeypatch.setattr(pa, "_PAGED_VMEM_BYTES", 1024)
    with pytest.raises(ValueError, match="VMEM"):
        pa.paged_attend(ops[0], ops[1], None, None, *ops[2:])
    monkeypatch.undo()
    # int8 with page*head_dim == 64: does not tile into 256-elem blocks
    pool8 = jnp.zeros(ops[0].shape, jnp.int8)
    scales = jnp.ones(ops[0].shape[:2] + (1,), jnp.float32)
    with pytest.raises(ValueError, match="quant blocks"):
        pa.paged_attend(pool8, pool8, scales, scales, *ops[2:])


# ---------------------------------------------------------------------------
# forward_cached with fused_decode: same logits (~1 ULP), same tokens
# (exactly), same write-back (bit-for-bit — the pool write is the SHARED
# path, only the read is fused).
# ---------------------------------------------------------------------------


def _fresh_cache(cfg, slots=4, page=8, mp=3, kv="f32", fill_seed=None):
    num_pages = slots * mp + 1
    cache = paged_lib.init_paged_cache(cfg, num_pages, page, mp, slots, kv)
    cache["bt"] = jnp.asarray(
        np.arange(1, slots * mp + 1).reshape(slots, mp), jnp.int32)
    if fill_seed is not None:
        cache = dict(
            cache,
            k=jax.random.normal(jax.random.PRNGKey(fill_seed),
                                cache["k"].shape, jnp.float32) * 0.3,
            v=jax.random.normal(jax.random.PRNGKey(fill_seed + 1),
                                cache["v"].shape, jnp.float32) * 0.3,
        )
    return cache


def test_fused_forward_cached_parity(cfg, params):
    slots = 4
    cache = _fresh_cache(cfg, slots, fill_seed=1)
    tok_ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (slots, 1)),
        jnp.int32)
    start = jnp.asarray([5, 1, 17, 23], jnp.int32)
    wm = jnp.asarray([True, True, True, False])  # one frozen lane
    lu, cu = gpt.forward_cached(params, cfg, tok_ids, start[:, None],
                                dict(cache), start, write_mask=wm)
    lf, cf = gpt.forward_cached(params, cfg.replace(fused_decode=True),
                                tok_ids, start[:, None], dict(cache), start,
                                write_mask=wm)
    assert float(jnp.max(jnp.abs(lu - lf))) < 1e-5
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(lu[:, -1], -1)),
        np.asarray(jnp.argmax(lf[:, -1], -1)))
    # write-back is the SHARED path: layer 0 (same activations in) lands
    # bit-identically; deeper layers' K/V projections see the previous
    # layer's ~1-ULP attention wobble, so they agree to the same bar as
    # the logits
    for key in ("k", "v"):
        np.testing.assert_array_equal(np.asarray(cu[key][0]),
                                      np.asarray(cf[key][0]),
                                      err_msg=f"cache[{key}] layer 0")
        np.testing.assert_allclose(np.asarray(cu[key]), np.asarray(cf[key]),
                                   atol=1e-5, rtol=0,
                                   err_msg=f"cache[{key}]")
    np.testing.assert_array_equal(np.asarray(cu["bt"]), np.asarray(cf["bt"]))


def test_fused_forward_int8_token_agreement(cfg, params):
    """The issue's int8 gate: >= 90% greedy token agreement between the
    fused kernel (in-kernel dequant) and the unfused gather-then-dequant
    path, over the SAME quantized pools."""
    cfg8 = cfg.replace(head_dim=32)  # page*head_dim == 256
    params8 = init_params(jax.random.PRNGKey(1), cfg8)
    slots, page, mp = 4, 8, 3
    cache = _fresh_cache(cfg8, slots, page, mp, kv="int8")
    rng = np.random.RandomState(3)
    for nm, snm in (("k", "ks"), ("v", "vs")):
        raw = jnp.asarray(
            rng.randn(cfg8.num_layers, slots * mp + 1, cfg8.heads,
                      page * cfg8.head_dim), jnp.float32) * 0.3
        q8, s8 = quant_comm.quantize_blocks(raw)
        cache[nm] = q8.reshape(cfg8.num_layers, slots * mp + 1, cfg8.heads,
                               page, cfg8.head_dim)
        cache[snm] = s8
    tok_ids = jnp.asarray(rng.randint(0, cfg8.vocab_size, (slots, 1)),
                          jnp.int32)
    start = jnp.asarray([5, 1, 17, 23], jnp.int32)
    wm = jnp.ones((slots,), bool)
    lu, _ = gpt.forward_cached(params8, cfg8, tok_ids, start[:, None],
                               dict(cache), start, write_mask=wm)
    lf, _ = gpt.forward_cached(params8, cfg8.replace(fused_decode=True),
                               tok_ids, start[:, None], dict(cache), start,
                               write_mask=wm)
    agree = jnp.mean(jnp.argmax(lu[:, -1], -1) == jnp.argmax(lf[:, -1], -1))
    assert float(agree) >= 0.9


@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.8, 5)],
                         ids=["greedy", "sampled_topk"])
def test_fused_decode_steps_token_parity(cfg, params, temperature, top_k):
    """12 decode ticks from a shared prompt state: the fused and unfused
    buffers (and cursors) must be IDENTICAL — greedy and fixed-seed
    sampled. Sampling folds each lane's own cursor, so ~1-ULP logit
    wobble may only flip a token if it flips the argmax/top-k order —
    pinning exact equality here is the real parity bar."""
    slots, page, mp = 4, 8, 3
    tok_ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (slots,))
    buf = jnp.zeros((slots, mp * page), jnp.int32).at[:, 0].set(tok_ids)
    cursors = jnp.ones((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    limits = jnp.full((slots,), 20, jnp.int32)
    keys = jnp.stack([jax.random.PRNGKey(100 + i)
                      for i in range(slots)]).astype(jnp.uint32)
    outs = {}
    for fused in (False, True):
        c = cfg.replace(fused_decode=fused)
        st = (buf, _fresh_cache(cfg, slots, page, mp), cursors, active)
        for _ in range(12):
            st = sd.decode_step(params, c, st[0], st[1], st[2], st[3],
                                limits, keys, 3, temperature, top_k, None,
                                steps=1)
        outs[fused] = (np.asarray(st[0]), np.asarray(st[2]))
    np.testing.assert_array_equal(outs[False][0], outs[True][0])
    np.testing.assert_array_equal(outs[False][1], outs[True][1])


# ---------------------------------------------------------------------------
# The on-device scheduler window.
# ---------------------------------------------------------------------------


def _loop_state(cfg, slots=4, page=8, mp=3):
    tok_ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (slots,))
    buf = jnp.zeros((slots, mp * page), jnp.int32).at[:, 0].set(tok_ids)
    keys = jnp.stack([jax.random.PRNGKey(100 + i)
                      for i in range(slots)]).astype(jnp.uint32)
    return (buf, _fresh_cache(cfg, slots, page, mp),
            jnp.ones((slots,), jnp.int32), jnp.ones((slots,), bool), keys)


def test_decode_loop_window_equals_repeated_steps(cfg, params):
    cfgf = cfg.replace(fused_decode=True)
    buf, cache, cursors, active, keys = _loop_state(cfg)
    limits = jnp.full((4,), 10, jnp.int32)
    ph = jnp.full((4,), 3, jnp.int32)
    st = (buf, dict(cache), cursors, active)
    for _ in range(8):
        st = sd.decode_step(params, cfgf, st[0], st[1], st[2], st[3],
                            limits, keys, 3, 0.0, 0, None, steps=1)
    b2, c2, cur2, act2, ticks, freed = sd.decode_loop_window(
        params, cfgf, buf, dict(cache), cursors, active, limits, keys,
        ph, jnp.asarray(8, jnp.int32), jnp.asarray(1 << 30, jnp.int32),
        3, 0.0, 0, None)
    assert int(ticks) == 8 and int(freed) == 0
    np.testing.assert_array_equal(np.asarray(b2), np.asarray(st[0]))
    np.testing.assert_array_equal(np.asarray(cur2), np.asarray(st[2]))
    np.testing.assert_array_equal(np.asarray(act2), np.asarray(st[3]))
    for key in c2:
        np.testing.assert_array_equal(np.asarray(c2[key]),
                                      np.asarray(st[1][key]))


def test_decode_loop_window_early_exit_resumes_on_stream(cfg, params):
    """Lane 0's limit trips on tick 2, releasing its 3 pages >= the
    stop_when_freed target: the loop must hand control back EARLY
    (ticks=2, freed=3) — and resuming for the remaining ticks must land
    bit-for-bit on the same stream as the uninterrupted window (the
    schedule-invariance that makes early exit free)."""
    cfgf = cfg.replace(fused_decode=True)
    buf, cache, cursors, active, keys = _loop_state(cfg)
    limits = jnp.asarray([3, 10, 10, 10], jnp.int32)
    ph = jnp.full((4,), 3, jnp.int32)
    full = sd.decode_loop_window(
        params, cfgf, buf, dict(cache), cursors, active, limits, keys,
        ph, jnp.asarray(8, jnp.int32), jnp.asarray(1 << 30, jnp.int32),
        3, 0.0, 0, None)
    b1, c1, cur1, act1, t1, f1 = sd.decode_loop_window(
        params, cfgf, buf, dict(cache), cursors, active, limits, keys,
        ph, jnp.asarray(8, jnp.int32), jnp.asarray(3, jnp.int32),
        3, 0.0, 0, None)
    assert int(t1) == 2 and int(f1) == 3
    assert not bool(act1[0]) and bool(act1[1])
    b2, c2, cur2, act2, t2, _ = sd.decode_loop_window(
        params, cfgf, b1, c1, cur1, act1, limits, keys,
        ph, jnp.asarray(8 - int(t1), jnp.int32),
        jnp.asarray(1 << 30, jnp.int32), 3, 0.0, 0, None)
    assert int(t1) + int(t2) == int(full[4]) == 8
    np.testing.assert_array_equal(np.asarray(b2), np.asarray(full[0]))
    np.testing.assert_array_equal(np.asarray(cur2), np.asarray(full[2]))
    np.testing.assert_array_equal(np.asarray(act2), np.asarray(full[3]))


# ---------------------------------------------------------------------------
# TP comm audits: the fused step and the whole window both move exactly
# decode_step_comm(paged=True) — the kernel adds no collectives and the
# while body is compiled (and counted) once at any window size.
# ---------------------------------------------------------------------------


def _tp_paged_state(cfg, mesh, slots, kv_dtype="f32", page=8, mp=3):
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpukit.shardings import TensorParallel

    strat = TensorParallel(mesh)
    params = init_params(jax.random.PRNGKey(0), cfg)
    psh = strat.state_sharding(jax.eval_shape(lambda: params))
    params = jax.tree.map(jax.device_put, params, psh)
    sh = lambda spec: NamedSharding(mesh, spec)
    num_pages = slots * mp + 1
    tree = paged_lib.init_paged_cache(cfg, num_pages, page, mp, slots, kv_dtype)
    specs = {"k": P(None, None, "model", None, None),
             "v": P(None, None, "model", None, None),
             "ks": P(None, None, "model", None),
             "vs": P(None, None, "model", None), "bt": P()}
    cache = {k: jax.device_put(np.asarray(v), sh(specs[k]))
             for k, v in tree.items()}
    bt = np.arange(1, slots * mp + 1, dtype=np.int32).reshape(slots, mp)
    cache["bt"] = jax.device_put(bt, sh(P()))
    w = mp * page
    buf = jax.device_put(np.zeros((slots, w), np.int32), sh(P(None, None)))
    cursors = jax.device_put(np.full((slots,), 5, np.int32), sh(P(None)))
    active = jax.device_put(np.ones((slots,), bool), sh(P(None)))
    limits = jax.device_put(np.full((slots,), 12, np.int32), sh(P(None)))
    keys = jax.device_put(np.zeros((slots, 2), np.uint32), sh(P(None, None)))
    return params, buf, cache, cursors, active, limits, keys


@pytest.mark.parametrize(
    "kv_dtype,temperature,top_k",
    [("f32", 0.0, 0), ("f32", 0.9, 5), ("int8", 0.0, 0)],
    ids=["f32_greedy", "f32_topk", "int8_greedy"],
)
def test_tp_fused_decode_step_hlo_comm_audit(kv_dtype, temperature, top_k):
    from tpukit.mesh import create_mesh
    from tpukit.obs.xla import capture_compiler_stderr, collective_bytes

    head_dim = 32 if kv_dtype == "int8" else 8
    cfg = GPTConfig(
        dim=32, head_dim=head_dim, heads=4, num_layers=2, vocab_size=160,
        max_position_embeddings=64, compute_dtype=jnp.float32,
        fused_decode=True,
    )
    mesh = create_mesh({"model": 4})
    slots = 4
    state = _tp_paged_state(cfg, mesh, slots, kv_dtype)
    params, buf, cache, cursors, active, limits, keys = state
    with capture_compiler_stderr(check=True):
        compiled = sd.decode_step.lower(
            params, cfg, buf, cache, cursors, active, limits, keys,
            1, temperature, top_k, mesh,
        ).compile()
    measured = collective_bytes(compiled.as_text())
    expected = sd.decode_step_comm(cfg, mesh, slots, top_k=top_k, paged=True)
    assert measured == expected, (measured, expected)


def test_tp_sched_loop_hlo_comm_audit():
    """The whole fused window lowered as one program: collective_bytes
    over the compiled HLO must STILL equal the per-step closed form —
    the while body's collectives appear once, so the audit is window-
    size-invariant (max_ticks/stop_when_freed are traced scalars; the
    same executable serves every window)."""
    from jax.sharding import PartitionSpec as P
    from jax.sharding import NamedSharding

    from tpukit.mesh import create_mesh
    from tpukit.obs.xla import capture_compiler_stderr, collective_bytes

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=160,
        max_position_embeddings=64, compute_dtype=jnp.float32,
        fused_decode=True,
    )
    mesh = create_mesh({"model": 4})
    slots = 4
    state = _tp_paged_state(cfg, mesh, slots, "f32")
    params, buf, cache, cursors, active, limits, keys = state
    ph = jax.device_put(np.full((slots,), 3, np.int32),
                        NamedSharding(mesh, P(None)))
    with capture_compiler_stderr(check=True):
        compiled = sd.decode_loop_window.lower(
            params, cfg, buf, cache, cursors, active, limits, keys,
            ph, jnp.asarray(8, jnp.int32), jnp.asarray(1 << 30, jnp.int32),
            3, 0.0, 0, mesh,
        ).compile()
    measured = collective_bytes(compiled.as_text())
    expected = sd.decode_step_comm(cfg, mesh, slots, top_k=0, paged=True)
    assert measured == expected, (measured, expected)


# ---------------------------------------------------------------------------
# The full engine behind --fused_decode: same streams as the unfused
# engine (which is itself serial-exact) on the round-15 tight pool, with
# correct device-reported step accounting and complete traces.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,stream_seed",
    [(0.0, 0, 3), (0.9, 5, 11)],
    ids=["greedy", "sampled_topk"],
)
def test_fused_engine_tight_pool_parity(tok, cfg, params, temperature, top_k,
                                        stream_seed):
    serve_kw = dict(slots=3, buckets=(8, 16), max_new_tokens=MAX_NEW,
                    temperature=temperature, top_k=top_k, window_steps=8,
                    page_size=4, num_pages=12)
    reqs = synthetic_request_stream(
        tok, 8, seed=stream_seed, max_new_tokens=MAX_NEW, buckets=(8, 16),
        qps=50.0 if temperature else 0.0,
    )
    outs = {}
    for fused in (False, True):
        eng = ServeEngine(params, cfg,
                          ServeConfig(**serve_kw, fused_decode=fused),
                          eos_id=int(tok.eos_token_id))
        outs[fused] = {c.rid: c
                       for c in eng.run(list(reqs), max_wall_s=300)}
        if fused:
            assert not eng._lanes and len(eng._free) == 3
            assert eng.allocator.live_pages == 0
            assert eng.steps > 0  # device-reported ticks landed
    assert outs[True].keys() == outs[False].keys() == {r.rid for r in reqs}
    for rid, c in outs[True].items():
        np.testing.assert_array_equal(c.ids, outs[False][rid].ids,
                                      err_msg=f"rid {rid} vs unfused")
        want = _serial(params, cfg, c.ids[: c.prompt_len], MAX_NEW,
                       tok.eos_token_id, temperature, top_k,
                       seed=stream_seed + rid)
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {rid}")


def test_fused_engine_trace_complete_with_window_quanta(tok, cfg, params):
    from tpukit.obs import TraceRecorder
    from tpukit.obs import trace as trace_lib

    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=4, page_size=4, fused_decode=True,
                        decode_quantum=4)
    reqs = synthetic_request_stream(tok, 6, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    tracer = TraceRecorder()
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      tracer=tracer)
    comps = eng.run(list(reqs), max_wall_s=300)
    assert len(comps) == 6
    trees = trace_lib.build_trees(tracer.snapshot())
    assert trace_lib.completeness(trees) == 1.0
    quanta = [e for e in tracer.snapshot() if e.get("ev") == "quantum"]
    assert quanta
    # window-granular spans: `steps` is the DEVICE-reported tick count —
    # at least one tick each, never more than the window, and summing to
    # the engine's step account
    assert all(1 <= e["steps"] <= serve.decode_quantum for e in quanta)
    assert sum(e["steps"] for e in quanta) == eng.steps


def test_fused_engine_requires_paged_cache():
    with pytest.raises(ValueError, match="fused_decode"):
        ServeConfig(slots=2, buckets=(8, 16), fused_decode=True)
