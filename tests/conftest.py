"""Test harness configuration.

Distributed-without-a-cluster (SURVEY §4): force the CPU platform with 8
virtual devices so every mesh strategy (DP, FSDP sharding, pipeline ppermute,
2-D pipe x DP) is testable on one process with bit-level assertions. Must run
before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# Belt and braces: if a pytest plugin imported jax before this conftest, the
# env var alone is too late, but the config flag still wins as long as no
# backend has been initialized yet.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices config option; the XLA_FLAGS
    # host-platform device count set above covers those versions.
    pass
jax.config.update("jax_threefry_partitionable", True)

# Persistent compile cache: repeat test runs skip recompilation.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from tpukit.model import GPTConfig, init_params  # noqa: E402


@pytest.fixture(scope="session")
def tiny_config():
    """GPT-tiny in float32 for exact-math tests."""
    import jax.numpy as jnp

    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=97,
        max_position_embeddings=64,
        compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="session")
def tiny_params(tiny_config):
    return init_params(jax.random.PRNGKey(0), tiny_config)


@pytest.fixture()
def rng():
    return np.random.RandomState(1234)
