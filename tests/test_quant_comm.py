"""Block-scaled int8 quantized collectives (`--comm_dtype`, round 12).

Four proof obligations, mirroring tpukit/ops/quant_comm.py's contract:

  1. the quantizer itself: per-block round-trip error bound, exact zeros,
     stochastic-rounding behavior, pack/unpack inverses;
  2. the wrappers at f32: bit-exact passthrough vs the raw lax collectives
     (compression must be opt-in, never a silent numerics change);
  3. the loss-trajectory tolerance gate per strategy (ddp / fsdp / ep on
     the 8-virtual-device mesh): bit parity is impossible by construction,
     so a bounded quantized-vs-f32 loss delta IS the correctness contract;
  4. the HLO byte audit: the compiled programs move EXACTLY the closed-form
     payload+sidecar bytes (`grad_comm` / `dispatch_comm`), at unchanged op
     schedules (zero involuntary-remat warnings), and the int8 wire cost is
     <= 30% of the f32 baseline for the DDP grad all-reduce and the EP a2a
     dispatch — the acceptance bar.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpukit.compat import shard_map
from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig
from tpukit.obs.xla import (
    capture_compiler_stderr,
    collective_bytes,
    wire_bytes,
)
from tpukit.ops import quant_comm as qc
from tpukit.shardings import DataParallel, ExpertParallel, FSDP
from tpukit.train import create_train_state, make_optimizer, make_step_fns

BATCH = 16
SEQ = 32
STEPS = 6  # trajectory-gate horizon (cheap: compiled once, stepped N times)

# Tolerance gates (the correctness contract): int8 grad/dispatch payloads
# perturb each update by ~0.4% relative per block; over the 6-step fixture
# horizon the trajectories measured within ~1e-4 of f32 — the gates leave
# an order of magnitude of headroom without ever allowing a divergent run.
FIRST_STEP_TOL = 1e-3  # step 1's loss predates any quantized update
FINAL_LOSS_TOL = 2e-2


def _base_cfg(**kw):
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=211,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
        **kw,
    )


def _batch():
    rng = np.random.RandomState(11)
    ids = rng.randint(3, 211, size=(BATCH, SEQ)).astype(np.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros((BATCH, SEQ), dtype=bool),
    }
    return model_batch, np.roll(ids, -1, axis=1).astype(np.int32)


def _make_world(kind: str, comm_dtype: str):
    if kind == "ddp":
        return DataParallel(create_mesh({"data": 8})), _base_cfg(
            comm_dtype=comm_dtype
        )
    if kind == "fsdp":
        return FSDP(create_mesh({"data": 8})), _base_cfg(comm_dtype=comm_dtype)
    return (
        ExpertParallel(create_mesh({"data": 2, "expert": 4}), dispatch="a2a"),
        _base_cfg(comm_dtype=comm_dtype, num_experts=4),
    )


# One compiled world per (strategy, comm_dtype), shared by the trajectory
# gates AND the HLO audits — each extra compile on the 8-device mesh costs
# real tier-1 seconds.
_WORLDS: dict = {}


def _world(kind: str, comm_dtype: str) -> dict:
    key = (kind, comm_dtype)
    if key in _WORLDS:
        return _WORLDS[key]
    strategy, cfg = _make_world(kind, comm_dtype)
    strategy.validate_config(cfg)
    model_batch, targets = _batch()
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    struct = lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)  # noqa: E731
    b_structs = jax.tree.map(struct, model_batch)
    with capture_compiler_stderr() as cap:
        train_step, eval_step, sharding = make_step_fns(cfg, opt, strategy, shapes)
        compiled = train_step.lower(shapes, b_structs, struct(targets)).compile()
        ecompiled = eval_step.lower(shapes, b_structs, struct(targets)).compile()
    state = jax.device_put(state, sharding)
    losses = []
    for _ in range(STEPS):
        state, loss = compiled(state, model_batch, targets)
        losses.append(float(loss))
    del state
    _WORLDS[key] = {
        "strategy": strategy,
        "cfg": cfg,
        "shapes": shapes,
        "losses": losses,
        "coll": collective_bytes(compiled.as_text()),
        "ecoll": collective_bytes(ecompiled.as_text()),
        "warns": cap["involuntary_remat"],
    }
    return _WORLDS[key]


# -- 1. the quantizer ------------------------------------------------------


@pytest.mark.parametrize("block", [64, 256])
def test_roundtrip_error_bound(block):
    """Per-block max-abs scaling bounds the round-trip error by half a
    quantization step — scale/2 = max|block| / 254 — element-wise, for any
    block size; zero blocks round-trip exactly."""
    rng = np.random.RandomState(0)
    x = jnp.asarray((rng.randn(5 * block + 17) * rng.uniform(0.01, 10)).astype(np.float32))
    q, scales = qc.quantize_blockwise(x, block=block)
    assert q.dtype == jnp.int8 and scales.dtype == jnp.float32
    back = qc.dequantize_blockwise(q, scales, x.shape, block=block)
    n_pad = -(-x.size // block) * block
    padded = np.pad(np.asarray(x), (0, n_pad - x.size)).reshape(-1, block)
    bound = np.repeat(np.abs(padded).max(axis=1) / 253.9, block)[: x.size]
    assert (np.abs(np.asarray(back - x)) <= bound).all()

    zeros = jnp.zeros((2 * block,), jnp.float32)
    qz, sz = qc.quantize_blockwise(zeros, block=block)
    np.testing.assert_array_equal(np.asarray(qz), 0)
    np.testing.assert_array_equal(
        np.asarray(qc.dequantize_blockwise(qz, sz, zeros.shape, block=block)), 0.0
    )


def test_pack_unpack_inverse():
    """pack_quantized's wire row is exactly packed_bytes() long and
    unpack_dequantized inverts it — including the bitcast f32 scale
    sidecar — for ragged (non-block-multiple) row widths."""
    rng = np.random.RandomState(3)
    parts = jnp.asarray(rng.randn(4, 700).astype(np.float32))
    packed = qc.pack_quantized(parts)
    assert packed.dtype == jnp.int8
    assert packed.shape == (4, qc.packed_bytes(700))
    back = qc.unpack_dequantized(packed, 700)
    assert back.shape == parts.shape
    bound = np.abs(np.asarray(parts)).max() / 120  # loose: per-row blocks
    assert np.abs(np.asarray(back - parts)).max() <= bound


def test_stochastic_rounding_unbiased():
    """Stochastic rounding lands on one of the two adjacent quantization
    levels and is unbiased: the mean over many keys converges to the true
    value (round-to-nearest's systematic bias does not)."""
    x = jnp.full((1, 256), 0.3217, jnp.float32)
    q, s = qc.quantize_blocks(x)  # deterministic
    det = qc.dequantize_blocks(q, s)
    acc = np.zeros((1, 256), np.float64)
    draws = 200
    for i in range(draws):
        qi, si = qc.quantize_blocks(x, rng=jax.random.PRNGKey(i))
        back = np.asarray(qc.dequantize_blocks(qi, si))
        step = float(s[0, 0])
        assert (np.abs(back - np.asarray(x)) < step + 1e-7).all()
        acc += back
    mean_err = abs(acc.mean() / draws - 0.3217)
    det_err = abs(float(det.mean()) - 0.3217)
    assert mean_err < det_err or mean_err < 1e-4


# -- 2. wrapper-vs-lax parity at f32 ---------------------------------------


def test_wrappers_f32_passthrough_parity():
    """dtype="f32" is a bit-exact passthrough to the raw lax collective for
    every wrapper — compression is opt-in, never a silent numerics change."""
    mesh = create_mesh({"data": 8})
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 16, 4).astype(np.float32))
    e = jnp.asarray(rng.randn(8 * 8, 4, 2, 6).astype(np.float32))

    def blk(v, buf):
        ar = qc.quantized_all_reduce(v, "data", 8, "f32")
        ar_ref = jax.lax.psum(v, "data")
        rs = qc.quantized_reduce_scatter(v, "data", 8, dim=1, dtype="f32")
        rs_ref = jax.lax.psum_scatter(v, "data", scatter_dimension=1, tiled=True)
        ag = qc.quantized_all_gather(v, "data", 8, dim=0, dtype="f32")
        ag_ref = jax.lax.all_gather(v, "data", axis=0, tiled=True)
        d = qc.exchange_all_to_all(buf, "data", 8, "dispatch", dtype="f32")
        d_ref = jax.lax.all_to_all(buf, "data", split_axis=0, concat_axis=1, tiled=True)
        gq = qc.all_gather_qgrad(v, "data", 8, 0, "f32", qc.DEFAULT_BLOCK, False)
        return ar, ar_ref, rs, rs_ref, ag, ag_ref, d, d_ref, gq

    sp = P("data", None, None)
    sp4 = P("data", None, None, None)
    out = shard_map(
        blk, mesh=mesh,
        in_specs=(sp, sp4),
        # ar/ag results are replicated (each device holds the full array);
        # rs keeps dim-1 sharded; the exchange keeps dim-0 sharded
        out_specs=(P(), P(), P(None, "data", None), P(None, "data", None),
                   P(), P(), sp4, sp4, P()),
        check_vma=False,
    )(x, e)
    ar, ar_ref, rs, rs_ref, ag, ag_ref, d, d_ref, gq = out
    np.testing.assert_array_equal(np.asarray(ar), np.asarray(ar_ref))
    np.testing.assert_array_equal(np.asarray(rs), np.asarray(rs_ref))
    np.testing.assert_array_equal(np.asarray(ag), np.asarray(ag_ref))
    np.testing.assert_array_equal(np.asarray(d), np.asarray(d_ref))
    np.testing.assert_array_equal(np.asarray(gq), np.asarray(ag_ref))


def test_quantized_collectives_error_bounded():
    """int8/bf16 all-reduce, reduce-scatter and all-gather land within a
    small relative error of the exact lax collective (f32 accumulation,
    only the wire is compressed), and the all_gather_qgrad backward equals
    the quantized reduce-scatter of the cotangent — the FSDP grad wire."""
    mesh = create_mesh({"data": 8})
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(8, 320, 2).astype(np.float32))

    def blk(v):
        exact = jax.lax.psum(v, "data")
        i8 = qc.quantized_all_reduce(v, "data", 8, "int8")
        b16 = qc.quantized_all_reduce(v, "data", 8, "bf16")
        rs_ref = jax.lax.psum_scatter(v, "data", scatter_dimension=1, tiled=True)
        rs_i8 = qc.quantized_reduce_scatter(v, "data", 8, dim=1, dtype="int8")
        ag_ref = jax.lax.all_gather(v, "data", axis=0, tiled=True)
        ag_i8 = qc.quantized_all_gather(v, "data", 8, dim=0, dtype="int8")
        ag_b16 = qc.quantized_all_gather(v, "data", 8, dim=0, dtype="bf16")
        return exact, i8, b16, rs_ref, rs_i8, ag_ref, ag_i8, ag_b16

    sp = P("data", None, None)
    rsp = P(None, "data", None)
    out = shard_map(
        blk, mesh=mesh, in_specs=(sp,),
        out_specs=(P(), P(), P(), rsp, rsp, P(), P(), P()),
        check_vma=False,
    )(x)
    exact, i8, b16, rs_ref, rs_i8, ag_ref, ag_i8, ag_b16 = out
    scale = float(jnp.max(jnp.abs(exact)))
    assert float(jnp.max(jnp.abs(i8 - exact))) / scale < 0.03
    assert float(jnp.max(jnp.abs(b16 - exact))) / scale < 0.03
    rs_scale = float(jnp.max(jnp.abs(rs_ref)))
    assert float(jnp.max(jnp.abs(rs_i8 - rs_ref))) / rs_scale < 0.03
    ag_scale = float(jnp.max(jnp.abs(ag_ref)))
    assert float(jnp.max(jnp.abs(ag_i8 - ag_ref))) / ag_scale < 0.02
    assert float(jnp.max(jnp.abs(ag_b16 - ag_ref))) / ag_scale < 0.01

    # backward of the full-precision gather is the quantized reduce-scatter
    shard = jnp.asarray(rng.randn(8, 2, 16).astype(np.float32))

    def gather_loss(v, cot):
        def inner(s, c):
            full = qc.all_gather_qgrad(s, "data", 8, 0, "int8", qc.DEFAULT_BLOCK, False)
            return jnp.sum(full * c)
        return shard_map(
            inner, mesh=mesh,
            in_specs=(P("data", None, None), P(None, None, None)),
            out_specs=P(), check_vma=False,
        )(v, cot)

    cot = jnp.asarray(rng.randn(8, 2, 16).astype(np.float32))
    g = jax.grad(gather_loss)(shard, cot)
    # exact reference: globally the loss is sum(gather(shard) * cot) =
    # sum(shard * cot), so d/d shard = cot — delivered physically through
    # the quantized reduce-scatter of the per-device cotangents
    ref = cot
    assert g.shape == shard.shape
    rel = float(jnp.max(jnp.abs(g - ref))) / float(jnp.max(jnp.abs(ref)))
    assert rel < 0.02


# -- 3. loss-trajectory tolerance gates ------------------------------------


@pytest.mark.parametrize("kind", ["ddp", "fsdp", "ep"])
def test_loss_trajectory_gate(kind):
    """THE correctness contract: --comm_dtype int8 must track the f32 loss
    trajectory within tolerance on every wired strategy. Step 1 predates
    any quantized update (the forward is full precision — for EP the
    payload quantizes AFTER routing, perturbing activations but never the
    discrete routing), so its gate is tight; the final-step gate bounds the
    accumulated drift of STEPS quantized gradient applications."""
    ref = _world(kind, "f32")
    quant = _world(kind, "int8")
    assert all(np.isfinite(quant["losses"]))
    first_tol = FIRST_STEP_TOL if kind != "ep" else 1e-2  # int8 activations
    assert abs(quant["losses"][0] - ref["losses"][0]) < first_tol, (
        quant["losses"][0], ref["losses"][0],
    )
    assert abs(quant["losses"][-1] - ref["losses"][-1]) < FINAL_LOSS_TOL, (
        quant["losses"], ref["losses"],
    )
    # the trajectory is monotone-ish on this fixture: training still works
    assert quant["losses"][-1] < quant["losses"][0]


@pytest.mark.parametrize("kind", ["ddp"])
def test_loss_trajectory_gate_bf16(kind):
    """The bf16 rung of the same gate (cheaper payload cut, tighter
    numerics): one strategy suffices — the wrappers share one code path."""
    ref = _world(kind, "f32")
    quant = _world(kind, "bf16")
    assert abs(quant["losses"][-1] - ref["losses"][-1]) < FINAL_LOSS_TOL


# -- 4. HLO byte audits -----------------------------------------------------


def test_ddp_int8_hlo_audit():
    """The compiled DDP int8 step moves EXACTLY the closed-form two-shot
    payload (one packed a2a + one packed all-gather), emits zero
    involuntary-remat warnings, and its grad wire costs <= 30% of the f32
    baseline's all-reduce (ring model, payload+scales counted) — the
    acceptance bar."""
    w = _world("ddp", "int8")
    assert w["warns"] == 0
    expected = w["strategy"].grad_comm(
        w["cfg"], w["shapes"].params, backend=jax.default_backend()
    )
    for op, rec in expected.items():
        got = w["coll"].get(op)
        assert got == rec, (op, got, rec)
    # <= 30% of f32 wire: quantized ops vs the baseline grad all-reduce
    base = _world("ddp", "f32")
    quant_wire = wire_bytes(
        {op: w["coll"][op] for op in expected}, 8
    )
    base_wire = wire_bytes(base["coll"], 8)
    assert base_wire > 0
    ratio = quant_wire / base_wire
    assert ratio <= 0.30, ratio


def test_fsdp_int8_hlo_audit():
    """FSDP int8: one packed grad-reduce-scatter a2a per sharded leaf at
    exact closed-form bytes, forward param all-gathers full-precision at
    exact bytes (grads-only first), zero remat warnings."""
    w = _world("fsdp", "int8")
    assert w["warns"] == 0
    expected = w["strategy"].grad_comm(
        w["cfg"], w["shapes"].params, backend=jax.default_backend()
    )
    assert expected["all-to-all"]["count"] > 1  # per-leaf wires, really many
    for op, rec in expected.items():
        got = w["coll"].get(op)
        assert got == rec, (op, got, rec)


def test_ep_int8_hlo_audit():
    """EP int8: the a2a op SCHEDULE is unchanged (same 4L train / 2L eval
    counts as f32) while every op moves the packed block-scaled buffer at
    exact closed-form bytes — train AND eval, <= 30% of the f32 payload."""
    w = _world("ep", "int8")
    base = _world("ep", "f32")
    assert w["warns"] == 0
    cfg = w["cfg"]
    expect = w["strategy"].dispatch_comm(
        cfg, global_batch=BATCH, seq=SEQ, backend=jax.default_backend()
    )
    a2a = w["coll"].get("all-to-all")
    base_a2a = base["coll"].get("all-to-all")
    assert a2a["count"] == base_a2a["count"] == expect["train"]["count"]
    assert a2a["bytes"] == expect["train"]["bytes"]
    assert a2a["bytes"] <= 0.30 * base_a2a["bytes"]
    ea2a = w["ecoll"].get("all-to-all")
    assert ea2a["count"] == expect["eval"]["count"]
    assert ea2a["bytes"] == expect["eval"]["bytes"]


def test_eval_bytes_audit_exact_on_cpu():
    """Satellite hardening (PR 5 flagged this 'softly'): the EVAL-step
    expected-bytes formula is dtype-aware — backend="cpu" prices the bf16
    eval autocast's f32 upcast into the expectation, so the f32-comm EP
    eval window audits EXACTLY on CPU too (bytes, not just op counts)."""
    w = _world("ep", "f32")
    expect = w["strategy"].dispatch_comm(
        w["cfg"], global_batch=BATCH, seq=SEQ, backend=jax.default_backend()
    )
    ea2a = w["ecoll"].get("all-to-all")
    assert ea2a["count"] == expect["eval"]["count"]
    assert ea2a["bytes"] == expect["eval"]["bytes"]
    assert expect["eval"].get("wire") is not None  # dtype-aware marker
    # the nominal (backend-less) expectation differs on CPU — the exact
    # match above is the hardening, not an accident of equal numbers
    nominal = w["strategy"].dispatch_comm(w["cfg"], global_batch=BATCH, seq=SEQ)
    if jax.default_backend() == "cpu":
        assert nominal["eval"]["bytes"] != expect["eval"]["bytes"]


# -- flag validation --------------------------------------------------------


def test_comm_dtype_validation():
    """--comm_dtype int8 is rejected everywhere it is not actually wired:
    bogus values at config construction, strategies without quantized
    collectives, MoE under DP/FSDP (no aux psum in the manual block), and
    the GSPMD xla dispatch under EP."""
    from tpukit.pipeline import Pipeline
    from tpukit.shardings import ContextParallel, SingleDevice, TensorParallel

    with pytest.raises(ValueError, match="comm_dtype"):
        GPTConfig(comm_dtype="int4")
    cfg = _base_cfg(comm_dtype="int8")
    for strategy in (
        SingleDevice(),
        ContextParallel(create_mesh({"seq": 8})),
        TensorParallel(create_mesh({"model": 4})),
        Pipeline(create_mesh({"stage": 4})),
    ):
        with pytest.raises(ValueError, match="comm_dtype"):
            strategy.validate_config(cfg)
    moe_int8 = _base_cfg(comm_dtype="int8", num_experts=4)
    with pytest.raises(ValueError, match="ExpertParallel"):
        DataParallel(create_mesh({"data": 8})).validate_config(moe_int8)
    with pytest.raises(ValueError, match="ExpertParallel"):
        FSDP(create_mesh({"data": 8})).validate_config(moe_int8)
    with pytest.raises(ValueError, match="moe_dispatch"):
        ExpertParallel(
            create_mesh({"data": 2, "expert": 4}), dispatch="xla"
        ).validate_config(moe_int8)
    # the wired combinations pass
    DataParallel(create_mesh({"data": 8})).validate_config(cfg)
    FSDP(create_mesh({"data": 8})).validate_config(cfg)
    ExpertParallel(create_mesh({"data": 2, "expert": 4})).validate_config(moe_int8)

    # comm_ops_for is a pure function of cfg — validating/auditing an int8
    # config must never widen the instance's f32 expected-op set (the
    # surprise-collective audit depends on it staying tight)
    dp = DataParallel(create_mesh({"data": 8}))
    dp.validate_config(cfg)
    assert "all-to-all" in dp.comm_ops_for(cfg)
    assert dp.comm_ops == ("all-reduce",)
    assert dp.comm_ops_for(_base_cfg()) == ("all-reduce",)


def test_comm_dtype_flag_plumbing():
    """--comm_dtype/--quant_stochastic parse on every recipe, default to
    the unchanged path, and reach GPTConfig through TrainFlags."""
    from tpukit.flags import TrainFlags, parse_flags

    assert TrainFlags().comm_dtype == "f32"
    assert TrainFlags().quant_stochastic is False
    flags = parse_flags([])
    assert flags.comm_dtype == "f32" and flags.quant_stochastic is False
    flags = parse_flags(["--comm_dtype", "int8", "--quant_stochastic"])
    assert flags.comm_dtype == "int8" and flags.quant_stochastic is True
    flags = parse_flags(["--comm_dtype", "bf16"], num_experts=True)
    assert flags.comm_dtype == "bf16"
    with pytest.raises(SystemExit):
        parse_flags(["--comm_dtype", "int4"])
