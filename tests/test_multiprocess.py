"""Real multi-PROCESS execution of the multi-host code paths (VERDICT r3 #2).

Spawns two `jax.distributed`-initialized CPU processes on localhost (4
virtual devices each -> an 8-device global mesh, 2 "hosts") and runs the
UNMODIFIED recipe CLIs end-to-end through fit(). This executes, for real,
every `jax.process_count() > 1` branch the single-process suite can only
reason about:

  - `initialize_runtime`'s explicit-coordinator rendezvous (tpukit/mesh.py),
  - per-rank DistributedSampler-style loading + `make_global_batch`'s
    process-local assembly (tpukit/train.py),
  - cross-process sharded checkpoint save/publish/restore with its
    sync-barrier choreography (tpukit/checkpoint.py),
  - collective generation (every process computes, process 0 prints).

Loss parity vs the in-process single-world run holds because each global
batch is the same row SET (rank sharding is a permutation) and the masked
CE mean is order-invariant up to f32 reduction order.

The reference's counterpart capability is torchrun multi-node DDP/FSDP
(main-ddp.py:1-6); there it is never tested — here it is.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "multiproc_worker.py"

TINY_ARGS = [
    "--batch_size", "8",
    "--epochs", "1",
    "--sequence_length", "33",
    "--dim", "32",
    "--head_dim", "8",
    "--heads", "4",
    "--num_layers", "4",
    "--learning_rate", "1e-3",
    "--dataset_slice", "64",
    "--num_workers", "0",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_world(recipe, workdir, extra=(), nprocs=2, local_devices=4, timeout=900):
    """Run `recipe` in an nprocs-process world; returns per-rank result dicts."""
    port = _free_port()
    procs, outs = [], []
    for rank in range(nprocs):
        out_path = Path(workdir) / f"out_{rank}.json"
        outs.append(out_path)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the pytest process's 8-device flag
        env.update(
            TPUKIT_CPU_DEVICES=str(local_devices),
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES=str(nprocs),
            JAX_PROCESS_ID=str(rank),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(WORKER), recipe, str(workdir), str(out_path)]
                + TINY_ARGS + list(extra),
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    try:
        logs = [p.communicate(timeout=timeout)[0] for p in procs]
        for rank, (p, log) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{log[-4000:]}"
    finally:
        # one rank hanging (e.g. a failed rendezvous) must not orphan the
        # others — they hold the coordinator port for later tests
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    results = [json.loads(o.read_text()) for o in outs]
    for rank, r in enumerate(results):
        assert r["rank"] == rank and r["world"] == nprocs
        assert r["global_devices"] == nprocs * local_devices
    return results


def _single_world_loss(recipe, workdir, extra=()):
    """The same recipe in THIS process's single-process 8-device world."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        recipe.replace("-", "_").replace(".py", ""), REPO / recipe
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        result = mod.main(TINY_ARGS + list(extra))
    finally:
        os.chdir(cwd)
    return float(result.metrics["eval"]["loss"])


@pytest.mark.slow
def test_fsdp_two_process_world_matches_single(tmp_path):
    """FSDP across 2 processes: rank-sharded input feeding, cross-process
    ZeRO-3 sharding, collective generation — eval loss must agree across
    ranks exactly (it is a psum'd global mean) and match the single-process
    world closely (same row sets per batch, f32 reduction-order slop plus
    the per-host eval-weight approximation on ragged final batches)."""
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    results = _launch_world("main-fsdp.py", mp_dir)
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    ref = _single_world_loss("main-fsdp.py", single_dir)
    assert abs(results[0]["eval_loss"] - ref) < 5e-2


@pytest.mark.slow
def test_fsdp_two_process_sharded_checkpoint_resume(tmp_path):
    """Cross-process sharded save -> cross-process restore: the multi-process
    branches of save_sharded/restore_sharded (per-host shard files, sync
    barriers, atomic publish) execute for real, and training resumes."""
    results = _launch_world(
        "main-fsdp.py", tmp_path,
        extra=["--checkpoint_format", "sharded"],
    )
    ckpt = Path(results[0]["checkpoint"])
    assert ckpt.is_dir() and ckpt.name.endswith(".sharded")
    assert (ckpt / "manifest.json").exists()
    first_step = results[0]["step"]
    assert first_step > 0

    resumed = _launch_world(
        "main-fsdp.py", tmp_path,
        extra=["--checkpoint_format", "sharded", "--resume", "latest"],
    )
    assert resumed[0]["step"] == 2 * first_step
    assert abs(resumed[0]["eval_loss"] - resumed[1]["eval_loss"]) < 1e-5


@pytest.mark.slow
def test_ddp_two_process_world_matches_single(tmp_path):
    """DDP across 2 processes: the multi-host branch of DataParallel — each
    process feeds its addressable rank shard, XLA's grad all-reduce crosses
    the process boundary. Eval loss agrees across ranks exactly and matches
    the single-process 8-device world (same global row sets)."""
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    results = _launch_world("main-ddp.py", mp_dir)
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    ref = _single_world_loss("main-ddp.py", single_dir)
    assert abs(results[0]["eval_loss"] - ref) < 5e-2


@pytest.mark.slow
def test_ddp_two_process_ragged_token_meter_exact(tmp_path):
    """VERDICT r4 #6: with 253 rows over 2 ranks (global batch 64 = 8 x 8
    data shards) the final batches carry different real-row counts (31 vs
    30); the throughput meter's global token count must be the exact
    cross-process sum — identical on every rank and equal to the dataset's
    real rows (minus the clock-starting first batch) x model seq. The old
    `* num_hosts` approximation disagrees across ranks (190 vs 188 rows)."""
    results = _launch_world(
        "main-ddp.py", tmp_path, extra=["--dataset_slice", "253"]
    )
    seq = 33 - 1  # model seq after the LM shift
    expected = (253 - 64) * seq  # first global batch (64 rows) starts the clock
    assert results[0]["train_tokens"] == expected
    assert results[1]["train_tokens"] == expected


@pytest.mark.slow
def test_tp_two_process_world_matches_single(tmp_path):
    """Tensor parallel across 2 processes: the (data=2, model=4) grid spans
    the host boundary, so the per-layer Megatron all-reduces (after
    attention and after the MLP) cross processes, as do the vocab-sharded
    embedding/head gathers."""
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    results = _launch_world("main-tp.py", mp_dir)
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    ref = _single_world_loss("main-tp.py", single_dir)
    assert abs(results[0]["eval_loss"] - ref) < 5e-2


@pytest.mark.slow
@pytest.mark.parametrize(
    "cp_args",
    [[], ["--cp_attention", "ulysses", "--heads", "8"]],
    ids=["ring", "ulysses"],
)
def test_cp_two_process_world_matches_single(tmp_path, cp_args):
    """Context parallelism across 2 processes: the seq=8 mesh axis spans the
    host boundary, so the ring's K/V ppermute hops (or Ulysses' two
    all_to_alls) run over the cross-process transport. Ulysses needs
    heads % 8 == 0, hence the head override (head count changes the model,
    so its single-world reference uses the same override)."""
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    results = _launch_world("main-ring.py", mp_dir, extra=cp_args)
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    ref = _single_world_loss("main-ring.py", single_dir, extra=cp_args)
    assert abs(results[0]["eval_loss"] - ref) < 5e-2


@pytest.mark.slow
def test_moe_two_process_world_matches_single(tmp_path):
    """Expert parallelism across 2 processes: the 8-way expert mesh axis
    spans the host boundary, so the MoE dispatch/combine all_to_alls and
    the expert-grad reductions cross processes."""
    mp_dir = tmp_path / "mp"
    mp_dir.mkdir()
    results = _launch_world("main-moe.py", mp_dir, extra=["--num_experts", "8"])
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    ref = _single_world_loss("main-moe.py", single_dir, extra=["--num_experts", "8"])
    assert abs(results[0]["eval_loss"] - ref) < 5e-2


@pytest.mark.slow
@pytest.mark.parametrize(
    "writer_args", [[], ["--async_checkpoint"]], ids=["sync", "async"]
)
def test_fsdp_kill_midrun_resume(tmp_path, writer_args):
    """VERDICT r4 #3: the failure-recovery path, for real. Train a
    2-process FSDP world with periodic sharded checkpointing, SIGKILL both
    processes mid-epoch (right after the first atomic publish), plant a
    torn checkpoint directory (no manifest) plus a stale .tmp staging dir,
    relaunch with --resume latest — training must continue from the last
    PUBLISHED step (asserted via exact step arithmetic; picking either
    decoy would break it or crash the restore).

    The async variant (round 7) runs the SAME scenario through the
    background writer: snapshots on the training thread, file-based
    cross-process rendezvous, atomic publish — SIGKILL mid-save must still
    leave only fully-published checkpoints ('async checkpoint never
    tears')."""
    run_args = [
        "--dataset_slice", "2048",  # 32 steps/epoch at global batch 64
        "--checkpoint_every", "2",
        "--checkpoint_format", "sharded",
    ] + writer_args
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(
            TPUKIT_CPU_DEVICES="4",
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(WORKER), "main-fsdp.py", str(tmp_path),
                 str(tmp_path / f"killed_{rank}.json")] + TINY_ARGS + run_args,
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    ckdir = tmp_path / "checkpoints"
    try:
        deadline = time.time() + 600
        published = []
        while time.time() < deadline:
            if ckdir.is_dir():
                published = [
                    p for p in ckdir.glob("*.sharded")
                    if (p / "manifest.json").exists()
                ]
                if published:
                    break
            ended = [p for p in procs if p.poll() is not None]
            assert not ended, (
                "worker exited before any checkpoint published:\n"
                + ended[0].communicate()[0][-3000:]
            )
            time.sleep(0.1)
        assert published, "no checkpoint published within the deadline"
    finally:
        for p in procs:
            p.kill()  # SIGKILL: no atexit, no final save — a real crash
        for p in procs:
            p.communicate()

    import tpukit.checkpoint as ckpt_lib

    published = [
        p for p in ckdir.glob("*.sharded") if (p / "manifest.json").exists()
    ]
    ckpt_step = max(ckpt_lib._step_of(p) for p in published)
    assert ckpt_step >= 2

    # decoys a broken resume could pick up: a torn directory that never got
    # its manifest (simulated crash between shard write and publish), and a
    # stale .tmp staging dir from a save that died mid-write
    torn = ckdir / "checkpoint-step000099999.sharded"
    torn.mkdir()
    (torn / "shard-00000.npz").write_bytes(b"garbage")
    stale = ckdir / "checkpoint-step000088888.sharded.tmp"
    stale.mkdir()
    (stale / "manifest.json").write_text("{}")

    resumed = _launch_world(
        "main-fsdp.py", tmp_path, extra=run_args + ["--resume", "latest"]
    )
    steps_per_epoch = 2048 // 64  # fresh run trains exactly one epoch
    assert resumed[0]["step"] == ckpt_step + steps_per_epoch
    assert abs(resumed[0]["eval_loss"] - resumed[1]["eval_loss"]) < 1e-5
    assert np.isfinite(resumed[0]["eval_loss"])


def _wait_for_checkpoint(proc, ckdir: Path, pattern: str, timeout_s: float = 300):
    """Block until the run publishes its first periodic checkpoint (the
    signal that training is genuinely mid-epoch) or the process exits."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if ckdir.is_dir() and list(ckdir.glob(pattern)):
            return
        if proc.poll() is not None:
            out = proc.communicate()[0]
            raise AssertionError(
                f"run exited rc={proc.returncode} before any checkpoint:\n"
                + out[-3000:]
            )
        time.sleep(0.02)
    raise AssertionError("no checkpoint published within the deadline")


def test_sigterm_midrun_graceful_checkpoint_and_bitexact_resume(tmp_path):
    """Round-9 preemption, through the REAL CLI: SIGTERM a mid-epoch
    `main-single.py`, assert the documented exit-code contract (75 =
    preempted-and-checkpointed, tpukit/recovery.py), then `--resume
    latest` must reproduce the uninterrupted run's final checkpoint
    BIT-exact — the same parity methodology as the kill-midrun harness,
    with a graceful signal instead of SIGKILL. (Single-process tier-1
    twin of the 2-process slow-tier variant below.)"""
    import signal as signal_mod

    run_args = [
        "--dataset_slice", "400",  # 50 steps: SIGTERM lands mid-epoch
        "--checkpoint_every", "2",
        "--compilation_cache_dir", str(REPO / ".jax_cache"),
    ]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def _launch(workdir, extra=()):
        return subprocess.Popen(
            [sys.executable, str(REPO / "main-single.py")]
            + TINY_ARGS + run_args + list(extra),
            cwd=workdir, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    control = tmp_path / "control"
    control.mkdir()
    proc = _launch(control)
    out = proc.communicate(timeout=600)[0]
    assert proc.returncode == 0, out[-3000:]  # exit-code contract: clean

    victim = tmp_path / "victim"
    victim.mkdir()
    proc = _launch(victim)
    try:
        _wait_for_checkpoint(
            proc, victim / "checkpoints", "checkpoint-*.msgpack"
        )
        proc.send_signal(signal_mod.SIGTERM)
        out = proc.communicate(timeout=600)[0]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    # exit-code contract: preempted AND checkpointed — 75 (EX_TEMPFAIL),
    # the code a babysitter keys "relaunch with --resume latest" on
    assert proc.returncode == 75, f"rc={proc.returncode}\n{out[-3000:]}"
    assert "preempted by SIGTERM" in out

    import tpukit.checkpoint as ckpt_lib

    newest = ckpt_lib.latest(victim / "checkpoints")
    meta = ckpt_lib.read_meta(newest)
    assert meta is not None and meta["preempted"] and meta["signal"] == "SIGTERM"

    resume = _launch(victim, extra=["--resume", "latest"])
    out = resume.communicate(timeout=600)[0]
    assert resume.returncode == 0, out[-3000:]

    final = "checkpoint-step000000050.msgpack"
    a = (control / "checkpoints" / final).read_bytes()
    b = (victim / "checkpoints" / final).read_bytes()
    assert a == b  # bit-exact: the preemption lost nothing


@pytest.mark.slow
def test_fsdp_two_process_sigterm_graceful_resume(tmp_path):
    """2-process variant: SIGTERM both ranks mid-epoch. Host loops poll
    their signal flags at independent wall-clocks, so the graceful save is
    collectivized through `--heartbeat_dir` (recovery.PreemptCoordinator:
    p0 publishes a decision naming a window boundary every rank's
    deterministic host-step counter passes through) — the step-keyed
    sharded save then matches on all ranks; both exit 75; the relaunched
    world continues from the preemption step."""
    import signal as signal_mod

    run_args = [
        "--dataset_slice", "2048",  # 32 steps/epoch at global batch 64
        "--checkpoint_every", "2",
        "--checkpoint_format", "sharded",
        "--heartbeat_dir", str(tmp_path / "hb"),
    ]
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(
            TPUKIT_CPU_DEVICES="4",
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(WORKER), "main-fsdp.py", str(tmp_path),
                 str(tmp_path / f"sigterm_{rank}.json")] + TINY_ARGS + run_args,
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    ckdir = tmp_path / "checkpoints"
    try:
        _wait_for_checkpoint(procs[0], ckdir, "*.sharded")
        for p in procs:
            p.send_signal(signal_mod.SIGTERM)
        logs = [p.communicate(timeout=600)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (p, log) in enumerate(zip(procs, logs)):
        assert p.returncode == 75, f"rank {rank} rc={p.returncode}:\n{log[-3000:]}"

    import tpukit.checkpoint as ckpt_lib

    preempt_step = ckpt_lib._step_of(ckpt_lib.latest_sharded(ckdir))
    assert preempt_step >= 2

    resumed = _launch_world(
        "main-fsdp.py", tmp_path, extra=run_args + ["--resume", "latest"]
    )
    steps_per_epoch = 2048 // 64
    # mid-epoch resume: the world finishes exactly the interrupted epoch
    assert resumed[0]["step"] == steps_per_epoch
    assert abs(resumed[0]["eval_loss"] - resumed[1]["eval_loss"]) < 1e-5
    assert np.isfinite(resumed[0]["eval_loss"])


@pytest.mark.slow
@pytest.mark.parametrize(
    "schedule_args", [[], ["--schedule", "1f1b"]], ids=["gpipe", "1f1b"]
)
def test_pipeline_two_process_world(tmp_path, schedule_args):
    """Pipeline over 8 stages spanning 2 processes: batch rows are
    process-REPLICATED (make_global_batch's callback branch) while layer
    shards and the ppermute schedule cross the host boundary. The 1f1b
    case additionally runs the BACKWARD ppermute chain and the explicit
    per-stage vjp gradients across the boundary."""
    results = _launch_world(
        "main-pipe.py", tmp_path,
        extra=["--num_layers", "8", "--microbatches", "8"] + schedule_args,
    )
    assert abs(results[0]["eval_loss"] - results[1]["eval_loss"]) < 1e-5
    assert np.isfinite(results[0]["eval_loss"])
    assert results[0]["checkpoint_exists"]
