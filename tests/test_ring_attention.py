"""Ring attention / context parallelism on the 8-fake-device mesh:
ring == dense attention bit-near, and the ContextParallel strategy
reproduces the single-device train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tpukit.compat import shard_map
from jax.sharding import PartitionSpec as P

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig
from tpukit.ops.attention import causal_attention
from tpukit.ring_attention import (
    ring_causal_attention,
    ulysses_attention,
    zigzag_order,
)
from tpukit.shardings import ContextParallel, SingleDevice
from tpukit.train import create_train_state, make_optimizer, make_step_fns

B, H, S, D = 2, 4, 64, 8
SCALE = D**-0.5


def _ring_on_mesh(q, k, v, mask, seq_shards):
    mesh = create_mesh({"seq": seq_shards})

    def local(q, k, v, m):
        return ring_causal_attention(q, k, v, scale=SCALE, axis_name="seq", pad_mask=m)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"), P(None, None, "seq"), P(None, "seq")),
        out_specs=P(None, None, "seq"),
        check_vma=False,
    )(q, k, v, mask)


@pytest.fixture(scope="module")
def qkvm():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    mask = np.zeros((B, S), dtype=bool)
    mask[0, 50:] = True
    return mk(), mk(), mk(), jnp.asarray(mask)


@pytest.mark.parametrize("seq_shards", [2, 4, 8])
def test_ring_matches_dense(qkvm, seq_shards):
    q, k, v, mask = qkvm
    ours = _ring_on_mesh(q, k, v, mask, seq_shards)
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    valid = ~np.asarray(mask)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(ours)[b, :, valid[b]],
            np.asarray(ref)[b, :, valid[b]],
            atol=1e-5,
            rtol=1e-4,
        )


def test_ring_grads_match_dense(qkvm):
    q, k, v, mask = qkvm

    def loss_ring(q, k, v):
        out = _ring_on_mesh(q, k, v, mask, 4)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    def loss_dense(q, k, v):
        out = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=1e-4, rtol=1e-3,
            err_msg=f"d{name}",
        )


def _zigzag_on_mesh(q, k, v, mask, seq_shards):
    """Permute to the zigzag layout, run the balanced ring, unpermute."""
    order = zigzag_order(S, seq_shards)
    inv = np.argsort(order)
    mesh = create_mesh({"seq": seq_shards})

    def local(q, k, v, m):
        return ring_causal_attention(
            q, k, v, scale=SCALE, axis_name="seq", pad_mask=m, layout="zigzag"
        )

    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"), P(None, None, "seq"), P(None, "seq")),
        out_specs=P(None, None, "seq"),
        check_vma=False,
    )(q[:, :, order], k[:, :, order], v[:, :, order], mask[:, order])
    return out[:, :, inv]


@pytest.mark.parametrize("seq_shards", [2, 4, 8])
def test_zigzag_matches_dense(qkvm, seq_shards):
    q, k, v, mask = qkvm
    ours = _zigzag_on_mesh(q, k, v, mask, seq_shards)
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    valid = ~np.asarray(mask)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(ours)[b, :, valid[b]],
            np.asarray(ref)[b, :, valid[b]],
            atol=1e-5,
            rtol=1e-4,
        )


def test_zigzag_grads_match_dense(qkvm):
    q, k, v, mask = qkvm

    def loss_zz(q, k, v):
        out = _zigzag_on_mesh(q, k, v, mask, 4)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    def loss_dense(q, k, v):
        out = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_zz, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=1e-4, rtol=1e-3,
            err_msg=f"d{name}",
        )


def _ulysses_on_mesh(q, k, v, mask, seq_shards):
    mesh = create_mesh({"seq": seq_shards})

    def local(q, k, v, m):
        return ulysses_attention(q, k, v, scale=SCALE, axis_name="seq", pad_mask=m)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"), P(None, None, "seq"), P(None, "seq")),
        out_specs=P(None, None, "seq"),
        check_vma=False,
    )(q, k, v, mask)


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_ulysses_matches_dense(qkvm, seq_shards):
    q, k, v, mask = qkvm
    ours = _ulysses_on_mesh(q, k, v, mask, seq_shards)
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    valid = ~np.asarray(mask)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(ours)[b, :, valid[b]],
            np.asarray(ref)[b, :, valid[b]],
            atol=1e-5,
            rtol=1e-4,
        )


def test_ulysses_grads_match_dense(qkvm):
    q, k, v, mask = qkvm

    def loss_uly(q, k, v):
        out = _ulysses_on_mesh(q, k, v, mask, 4)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    def loss_dense(q, k, v):
        out = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
        return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_uly, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=1e-4, rtol=1e-3,
            err_msg=f"d{name}",
        )


def test_ulysses_rejects_undividable_heads(qkvm):
    q, k, v, mask = qkvm  # H=4 heads, 8 shards -> 4 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        _ulysses_on_mesh(q, k, v, mask, 8)


# ---- strategy-level parity (same scheme as tests/test_strategies.py) ------

CFG = dict(dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=151)
SEQ = 32
BATCH = 8


@pytest.fixture(scope="module")
def cfg():
    return GPTConfig(max_position_embeddings=SEQ, compute_dtype=jnp.float32, **CFG)


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.RandomState(5)
    ids = rng.randint(3, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    mask = np.zeros((BATCH, SEQ), dtype=bool)
    mask[0, 28:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }
    return model_batch, targets


def _one_step(strategy, cfg, batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, batch, targets)
    eval_loss, eval_acc = eval_step(new_state, batch, targets)
    return jax.device_get(new_state.params), float(loss), float(eval_loss), float(eval_acc)


def test_cp_matches_single(cfg, batch):
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    cp = _one_step(ContextParallel(create_mesh({"seq": 8})), cfg, model_batch, targets)
    assert abs(cp[1] - ref[1]) < 1e-5
    assert abs(cp[2] - ref[2]) < 1e-2
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        cp[0],
        ref[0],
    )


def test_cp_host_permuted_matches_injit(cfg, batch):
    """ADVICE r4: the zigzag permutation applied host-side (host_batch_fn,
    what fit() does — no per-step reshard collective) must produce exactly
    the in-jit permute's loss and parameter update."""
    model_batch, targets = batch
    # fit()'s convention: the model consumes sequence_length - 1 tokens
    cfg33 = cfg.replace(max_position_embeddings=SEQ + 1)

    injit = _one_step(
        ContextParallel(create_mesh({"seq": 8})), cfg33, model_batch, targets
    )

    host_strategy = ContextParallel(create_mesh({"seq": 8}), host_permute=True)
    permute = host_strategy.host_batch_fn(cfg33)
    assert permute is not None  # 32 % (2*8) == 0 -> zigzag active
    # without the explicit opt-in, no permute fn and loss_fn permutes in-jit
    assert ContextParallel(create_mesh({"seq": 8})).host_batch_fn(cfg33) is None
    h_batch, h_targets = permute(model_batch, targets)
    hosted = _one_step(host_strategy, cfg33, h_batch, h_targets)

    assert abs(hosted[1] - injit[1]) < 1e-6
    assert abs(hosted[2] - injit[2]) < 1e-6
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
        hosted[0], injit[0],
    )


def test_cp_ulysses_matches_single(cfg, batch):
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    # 4 shards: heads=4 divides, exercising the all_to_all schedule
    cp = _one_step(
        ContextParallel(create_mesh({"seq": 4}), attention="ulysses"),
        cfg, model_batch, targets,
    )
    assert abs(cp[1] - ref[1]) < 1e-5
    assert abs(cp[2] - ref[2]) < 1e-2
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        cp[0],
        ref[0],
    )


def test_cp_ulysses_rejects_undividable_heads(cfg):
    strategy = ContextParallel(create_mesh({"seq": 8}), attention="ulysses")
    # sequence divides (33 - 1 = 32 over 8) so the HEADS check is what fires
    with pytest.raises(ValueError, match="heads"):
        strategy.validate_config(cfg.replace(max_position_embeddings=33))


def test_cp_data_hybrid_matches_single(cfg, batch):
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    cp = _one_step(
        ContextParallel(create_mesh({"data": 2, "seq": 4})), cfg, model_batch, targets
    )
    assert abs(cp[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        cp[0],
        ref[0],
    )


def test_cp_rejects_undividable_seq(cfg, batch):
    model_batch, targets = batch
    strategy = ContextParallel(create_mesh({"seq": 5}))
    with pytest.raises(ValueError, match="divide"):
        strategy.loss_fn(None, cfg, model_batch, targets)
