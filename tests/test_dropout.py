"""Dropout is real in the train path (VERDICT r2 #6): a step rng threads
through every strategy's loss, changes the loss when dropout > 0, and never
touches the eval path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, gpt
from tpukit.pipeline import Pipeline
from tpukit.shardings import ContextParallel, SingleDevice, TensorParallel


def _cfg(dropout, **kw):
    base = dict(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=33, compute_dtype=jnp.float32, dropout=dropout,
    )
    base.update(kw)
    return GPTConfig(**base)


def _batch(cfg, batch=8, seq=32, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    batch_d = {
        "input_ids": jnp.asarray(ids),
        "position_ids": jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32), (batch, seq)),
        "mask": jnp.zeros((batch, seq), bool),
    }
    targets = jnp.asarray(r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    return batch_d, targets


STRATEGIES = [
    ("single", lambda: SingleDevice(), {}),
    ("pipe", lambda: Pipeline(create_mesh({"stage": 2}), num_microbatches=2), {}),
    ("cp", lambda: ContextParallel(create_mesh({"seq": 2})), {}),
    ("tp", lambda: TensorParallel(create_mesh({"model": 2})), {}),
]


@pytest.mark.parametrize("name,make,kw", STRATEGIES, ids=[s[0] for s in STRATEGIES])
def test_dropout_changes_train_loss(name, make, kw):
    strategy = make()
    cfg = _cfg(0.5)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    batch, targets = _batch(cfg)
    rng = jax.random.PRNGKey(7)

    base, _ = jax.jit(lambda p: strategy.loss_fn(p, cfg, batch, targets))(params)
    dropped, _ = jax.jit(lambda p, r: strategy.loss_fn(p, cfg, batch, targets, rng=r))(
        params, rng
    )
    # No rng -> deterministic: dropout is inert even at rate 0.5 (eval path).
    no_drop_cfg = _cfg(0.0)
    base0, _ = jax.jit(lambda p: strategy.loss_fn(p, no_drop_cfg, batch, targets))(params)
    np.testing.assert_allclose(float(base), float(base0), rtol=1e-6)
    # With rng the loss must move.
    assert abs(float(dropped) - float(base)) > 1e-4

    # Different step keys -> different masks -> different losses.
    dropped2, _ = jax.jit(lambda p, r: strategy.loss_fn(p, cfg, batch, targets, rng=r))(
        params, jax.random.PRNGKey(8)
    )
    assert abs(float(dropped2) - float(dropped)) > 1e-6


def test_train_step_threads_step_rng():
    """make_step_fns folds state.step into the key: consecutive steps from
    the same state produce different dropout masks, and eval is untouched."""
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    cfg = _cfg(0.5)
    strategy = SingleDevice()
    optimizer = make_optimizer(1e-3)
    state_shapes = jax.eval_shape(
        lambda r: create_train_state(r, cfg, optimizer), jax.random.PRNGKey(0)
    )
    train_step, eval_step, sharding = make_step_fns(
        cfg, optimizer, strategy, state_shapes, seed=0
    )
    state = jax.jit(
        lambda r: create_train_state(r, cfg, optimizer), out_shardings=sharding
    )(jax.random.PRNGKey(0))
    batch, targets = _batch(cfg)

    state1, loss1 = train_step(state, batch, targets)
    # same params would give the same loss without dropout; with step-keyed
    # dropout the second step (step=1) sees a different mask. Compare the
    # second step's loss against re-running step 0's computation on the
    # updated params WITHOUT dropout.
    eval_loss, _ = eval_step(state1, batch, targets)
    # eval twice is bit-identical (no rng anywhere in the eval path)
    eval_loss2, _ = eval_step(state1, batch, targets)
    assert float(eval_loss) == float(eval_loss2)
    # dropout active in train: the step's loss differs from the same params'
    # deterministic loss (same cfg/dtype, no rng)
    plain, _ = jax.jit(lambda p: strategy.loss_fn(p, cfg, batch, targets))(state1.params)
    _, loss2 = train_step(state1, batch, targets)  # donates state1
    assert abs(float(loss2) - float(plain)) > 1e-4
