"""Strategy equivalence tests on the 8-fake-device CPU mesh (SURVEY §4):
every distributed strategy must reproduce the single-device loss and the
single-device parameter update bit-for-bit (fp32, same global batch) —
DP-on-8 == single with 8x batch, FSDP == single, pipeline == single,
2-D pipe x DP == single."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig
from tpukit.pipeline import Pipeline
from tpukit.shardings import DataParallel, FSDP, SingleDevice
from tpukit.train import create_train_state, make_optimizer, make_step_fns

BATCH = 16
SEQ = 32


@pytest.fixture(scope="module")
def cfg():
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=4,
        vocab_size=211,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.RandomState(7)
    ids = rng.randint(3, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    mask = np.zeros((BATCH, SEQ), dtype=bool)
    # give some rows trailing padding
    for row in range(0, BATCH, 3):
        pad_from = rng.randint(SEQ // 2, SEQ)
        mask[row, pad_from:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }
    return model_batch, targets


def _one_step(strategy, cfg, batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, batch, targets)
    eval_loss, eval_acc = eval_step(new_state, batch, targets)
    return (
        jax.device_get(new_state.params),
        float(loss),
        float(eval_loss),
        float(eval_acc),
    )


@pytest.fixture(scope="module")
def reference_step(cfg, batch):
    model_batch, targets = batch
    return _one_step(SingleDevice(), cfg, model_batch, targets)


def _assert_matches_reference(result, reference, loss_tol=1e-5, param_tol=5e-5):
    params, loss, eval_loss, eval_acc = result
    ref_params, ref_loss, ref_eval_loss, ref_eval_acc = reference
    assert abs(loss - ref_loss) < loss_tol
    assert abs(eval_loss - ref_eval_loss) < 1e-2  # eval runs in bf16
    assert abs(eval_acc - ref_eval_acc) < 1.0
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=param_tol, rtol=1e-4),
        params,
        ref_params,
    )


def test_dp_matches_single(cfg, batch, reference_step):
    model_batch, targets = batch
    strategy = DataParallel(create_mesh({"data": 8}))
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_fsdp_matches_single(cfg, batch, reference_step):
    model_batch, targets = batch
    strategy = FSDP(create_mesh({"data": 8}))
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_fsdp_actually_shards(cfg):
    strategy = FSDP(create_mesh({"data": 8}))
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    sh = strategy.state_sharding(shapes)
    # the token embedding [211, 32] has no dim divisible by 8 -> replicated;
    # the ffn up kernel [L, 32, 128] shards its 128 dim
    up = sh.params["layers"]["ffn"]["up"]["kernel"]
    assert up.spec == jax.sharding.PartitionSpec(None, None, "data")
    # norm_out scale is [32]: 32 elements < min_shard_size 100 -> replicated,
    # the twin of size_based_auto_wrap_policy(min_num_params=100)
    # (main-fsdp.py:62)
    assert sh.params["norm_out"]["scale"].spec == jax.sharding.PartitionSpec()
    # optimizer state mirrors the param sharding (ZeRO-3)
    adam_mu = sh.opt_state[0].mu["layers"]["ffn"]["up"]["kernel"]
    assert adam_mu.spec == jax.sharding.PartitionSpec(None, None, "data")


def test_pipeline_matches_single(cfg, batch, reference_step):
    model_batch, targets = batch
    strategy = Pipeline(create_mesh({"stage": 4}))
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_pipeline_more_microbatches(cfg, batch, reference_step):
    """micro-batch count independent of stage count (chunks flag)."""
    model_batch, targets = batch
    strategy = Pipeline(create_mesh({"stage": 4}), num_microbatches=8)
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_pipe_dp_matches_single(cfg, batch, reference_step):
    model_batch, targets = batch
    strategy = Pipeline(create_mesh({"data": 2, "stage": 4}))
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_pipeline_rejects_unpadded_params(cfg, batch):
    """Uneven layer counts are supported, but only through the identity-
    padded init path — feeding raw unpadded params must fail loudly."""
    from tpukit.model import init_params

    model_batch, targets = batch
    strategy = Pipeline(create_mesh({"stage": 3}), num_microbatches=4)
    raw_params = init_params(jax.random.PRNGKey(0), cfg)  # 4 layers, not 6
    with pytest.raises(ValueError, match="identity-padded"):
        strategy.loss_fn(raw_params, cfg, model_batch, targets)


def test_pipeline_uneven_layers_matches_single(cfg, batch, reference_step):
    """VERDICT r2 #5: 4 layers on 3 stages (the reference's uneven-stage
    arithmetic, main-pipe.py:52-68) trains and matches single-device exactly;
    the identity-padding slots stay exactly zero through the update."""
    model_batch, targets = batch
    strategy = Pipeline(create_mesh({"stage": 3}), num_microbatches=4)
    params, loss, eval_loss, eval_acc = _one_step(strategy, cfg, model_batch, targets)
    ref_params, ref_loss, ref_eval_loss, ref_eval_acc = reference_step
    assert abs(loss - ref_loss) < 1e-5
    assert abs(eval_loss - ref_eval_loss) < 1e-2
    assert abs(eval_acc - ref_eval_acc) < 1.0
    # real layers (slots [:L]) take the single-device update
    real = jax.tree.map(lambda t: t[: cfg.num_layers], params["layers"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        real, ref_params["layers"],
    )
    # padding slots received zero gradient and zero decay: still exactly 0
    pad = jax.tree.map(lambda t: t[cfg.num_layers :], params["layers"])
    assert all((np.asarray(x) == 0).all() for x in jax.tree.leaves(pad))
    # embeddings / head / final norm match too
    for key in ("embeddings", "norm_out", "lm_head"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
            params[key], ref_params[key],
        )


def test_dp_batch_sharding_spec():
    strategy = DataParallel(create_mesh({"data": 8}))
    assert strategy.batch_spec() == jax.sharding.PartitionSpec("data")
    assert strategy.param_spec((64, 64)) == jax.sharding.PartitionSpec()


def test_fsdp_cpu_offload_degrades_on_cpu(cfg, batch):
    """VERDICT r1 W3: --cpu_offload needs TPU host memory spaces; on the CPU
    test backend it must warn and fall back to plain FSDP shardings (and the
    train step must still run)."""
    import warnings

    model_batch, targets = batch
    strategy = FSDP(create_mesh({"data": 8}), cpu_offload=True)
    assert strategy.name == "fsdp-offload"
    assert not strategy._offload_supported()

    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sharding = strategy.state_sharding(shapes)
    assert any("cpu_offload" in str(w.message) for w in caught)
    # degraded shardings have no host memory kind
    kinds = {s.memory_kind for s in jax.tree.leaves(sharding)}
    assert "pinned_host" not in kinds

    train_step, _, state_sharding = make_step_fns(cfg, opt, strategy, shapes)
    state = jax.device_put(state, state_sharding)
    new_state, loss = train_step(state, model_batch, targets)
    assert np.isfinite(float(loss))


def _backend_knows_pinned_host() -> bool:
    """Newer jax CPU backends expose a pinned_host memory space; older ones
    reject the kind at NamedSharding validation, so the faked-support rule
    test below cannot even construct its shardings there."""
    try:
        return any(
            m.kind == "pinned_host" for m in jax.devices()[0].addressable_memories()
        )
    except Exception:
        return False


@pytest.mark.skipif(
    not _backend_knows_pinned_host(),
    reason="backend has no pinned_host memory space (jax < 0.5 CPU); the "
    "real offload path runs in the TPU dryrun/bench",
)
def test_fsdp_offload_memory_kind_rule(cfg):
    """On TPU-like backends the offload shardings pin params to host memory;
    assert the rule by faking backend support (the real pinned_host path runs
    in the TPU dryrun/bench)."""
    strategy = FSDP(create_mesh({"data": 8}), cpu_offload=True)
    strategy._offload_supported = lambda: True
    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg, opt)
    )
    sharding = strategy.state_sharding(shapes)
    kinds = {s.memory_kind for s in jax.tree.leaves(sharding)}
    assert kinds == {"pinned_host"}


def test_pipeline_param_memory(cfg):
    """VERDICT r2 #3: embeddings/head are placed, not replicated — with 4
    stages no device holds more than (layers/4 + max(emb, head)) parameter
    bytes, and the vocab tables + their Adam state shard over `stage`."""
    from jax.sharding import PartitionSpec as P

    strategy = Pipeline(create_mesh({"stage": 4}))
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    sharding = jax.eval_shape(lambda: state)
    sharding = strategy.state_sharding(sharding)
    assert sharding.params["embeddings"]["token"].spec == P("stage", None)
    assert sharding.params["lm_head"]["kernel"].spec == P(None, "stage")
    assert sharding.params["embeddings"]["position"].spec == P()
    # Adam state follows the same placement (mu/nu mirror the param paths)
    assert sharding.opt_state[0].mu["embeddings"]["token"].spec == P("stage", None)
    assert sharding.opt_state[0].nu["lm_head"]["kernel"].spec == P(None, "stage")

    placed = jax.tree.map(jax.device_put, state.params, sharding.params)
    per_device = {}
    for leaf in jax.tree.leaves(placed):
        for shard in leaf.addressable_shards:
            per_device[shard.device] = per_device.get(shard.device, 0) + shard.data.nbytes
    layers_bytes = sum(l.nbytes for l in jax.tree.leaves(state.params["layers"]))
    emb = state.params["embeddings"]["token"].nbytes
    head = state.params["lm_head"]["kernel"].nbytes
    bound = layers_bytes / 4 + max(emb, head)
    assert max(per_device.values()) < bound, (per_device, bound)


def test_pipeline_activation_memory_scaling_and_remat():
    """VERDICT r3 #8: the GPipe scan's live-activation (temp) memory grows
    linearly with the micro-batch count, and per-layer remat cuts the slope
    (measured via XLA's compiled memory analysis, the same numbers
    tools/pipeline_memory.py records in docs/DESIGN.md)."""
    import numpy as np

    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=8, vocab_size=256,
        max_position_embeddings=33, compute_dtype=jnp.bfloat16,
        scan_layers=True,
    )
    mesh = create_mesh({"stage": 8})

    def temp_bytes(c, micro):
        strat = Pipeline(mesh, num_microbatches=micro)
        opt = make_optimizer(1e-4)
        state = create_train_state(jax.random.PRNGKey(0), c, opt, strategy=strat)
        step, _, sh = make_step_fns(c, opt, strat, jax.eval_shape(lambda: state))
        state = jax.device_put(state, sh)
        ids = np.zeros((micro, 32), np.int32)
        batch = {
            "input_ids": ids,
            "position_ids": np.zeros_like(ids),
            "mask": np.zeros(ids.shape, bool),
        }
        ma = step.lower(state, batch, np.zeros_like(ids)).compile().memory_analysis()
        return ma.temp_size_in_bytes

    plain8, plain32 = temp_bytes(cfg, 8), temp_bytes(cfg, 32)
    assert plain32 > plain8  # activation memory scales with micro count
    remat8 = temp_bytes(cfg.replace(remat_layers=True), 8)
    remat32 = temp_bytes(cfg.replace(remat_layers=True), 32)
    # remat must cut the per-micro slope by at least 2x
    assert (remat32 - remat8) < (plain32 - plain8) / 2


# ---------------------------------------------------------------------------
# 1F1B pipeline schedule (round 4): explicit per-stage vjps, activation
# memory bounded by the stage count. Must clear the same parity bar as the
# GPipe schedule.
# ---------------------------------------------------------------------------

from tpukit.pipeline import Pipeline1F1B


def test_pipeline_1f1b_matches_single(cfg, batch, reference_step):
    """One full train step (fwd + explicit vjp bwd + AdamW) through the
    1F1B schedule equals the single-device step to 1e-5."""
    model_batch, targets = batch
    strategy = Pipeline1F1B(create_mesh({"stage": 4}), num_microbatches=8)
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_pipeline_1f1b_data_hybrid_matches_single(cfg, batch, reference_step):
    model_batch, targets = batch
    strategy = Pipeline1F1B(create_mesh({"data": 2, "stage": 4}), num_microbatches=4)
    _assert_matches_reference(_one_step(strategy, cfg, model_batch, targets), reference_step)


def test_pipeline_1f1b_uneven_layers(cfg, batch, reference_step):
    """4 layers on 3 stages (same case as the GPipe uneven test): identity
    padding + active-slot gating flow through the explicit-vjp schedule —
    real layer slots take the single-device update, padded slots get
    exactly zero gradient."""
    model_batch, targets = batch
    strategy = Pipeline1F1B(create_mesh({"stage": 3}), num_microbatches=4)
    params, loss, eval_loss, _ = _one_step(strategy, cfg, model_batch, targets)
    ref_params, ref_loss, ref_eval_loss, _ = reference_step
    assert abs(loss - ref_loss) < 1e-5
    assert abs(eval_loss - ref_eval_loss) < 1e-2
    real = jax.tree.map(lambda t: t[: cfg.num_layers], params["layers"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        real, ref_params["layers"],
    )
    pad = jax.tree.map(lambda t: t[cfg.num_layers :], params["layers"])
    assert all((np.asarray(x) == 0).all() for x in jax.tree.leaves(pad))
    for key in ("embeddings", "norm_out", "lm_head"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
            params[key], ref_params[key],
        )


def test_pipeline_1f1b_param_memory(cfg):
    """VERDICT r4 #4: the 1F1B schedule shards the vocab tables over
    `stage` exactly like the GPipe schedule — same per-device parameter
    bound as test_pipeline_param_memory, with the explicit-vjp schedule."""
    from jax.sharding import PartitionSpec as P

    strategy = Pipeline1F1B(create_mesh({"stage": 4}), num_microbatches=8)
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    sharding = strategy.state_sharding(jax.eval_shape(lambda: state))
    assert sharding.params["embeddings"]["token"].spec == P("stage", None)
    assert sharding.params["lm_head"]["kernel"].spec == P(None, "stage")
    assert sharding.opt_state[0].mu["embeddings"]["token"].spec == P("stage", None)

    placed = jax.tree.map(jax.device_put, state.params, sharding.params)
    per_device = {}
    for leaf in jax.tree.leaves(placed):
        for shard in leaf.addressable_shards:
            per_device[shard.device] = per_device.get(shard.device, 0) + shard.data.nbytes
    layers_bytes = sum(l.nbytes for l in jax.tree.leaves(state.params["layers"]))
    emb = state.params["embeddings"]["token"].nbytes
    head = state.params["lm_head"]["kernel"].nbytes
    bound = layers_bytes / 4 + max(emb, head)
    assert max(per_device.values()) < bound, (per_device, bound)


def test_pipeline_1f1b_memory_flat_in_micro_count():
    """The point of 1F1B: temp memory must NOT grow with the micro-batch
    count (the GPipe schedule's grows linearly — see
    test_pipeline_activation_memory_scaling_and_remat)."""
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    mcfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=8, vocab_size=256,
        max_position_embeddings=33, compute_dtype=jnp.bfloat16,
        scan_layers=True,
    )
    mesh = create_mesh({"stage": 8})

    def temp_bytes(m):
        strat = Pipeline1F1B(mesh, num_microbatches=m)
        opt = make_optimizer(1e-4)
        state = create_train_state(jax.random.PRNGKey(0), mcfg, opt, strat)
        step, _, sh = make_step_fns(mcfg, opt, strat, jax.eval_shape(lambda: state))
        state = jax.device_put(state, sh)
        ids = np.zeros((m, 32), np.int32)
        b = {"input_ids": ids, "position_ids": np.zeros_like(ids), "mask": np.zeros(ids.shape, bool)}
        ma = step.lower(state, b, np.zeros_like(ids)).compile().memory_analysis()
        return ma.temp_size_in_bytes

    t8, t32 = temp_bytes(8), temp_bytes(32)
    assert t32 <= t8 * 1.1, (t8, t32)  # flat, not linear
