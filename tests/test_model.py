"""Model-math unit tests (SURVEY §4 plan): causal masking, padding mask,
reference quirks, parameter shapes/counts."""

import jax
import jax.numpy as jnp
import numpy as np

from tpukit.model import GPTConfig, TransformerDecoderLM, forward, init_params
from tpukit.model.gpt import param_count


def _random_batch(rng, cfg, batch=2, seq=16):
    input_ids = rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    position_ids = np.broadcast_to(np.arange(seq, dtype=np.int32), (batch, seq))
    return jnp.asarray(input_ids), jnp.asarray(position_ids)


def test_forward_shape_dtype(tiny_config, tiny_params, rng):
    ids, pos = _random_batch(rng, tiny_config)
    logits = forward(tiny_params, tiny_config, ids, pos)
    assert logits.shape == (2, 16, tiny_config.padded_vocab_size)
    assert logits.dtype == tiny_config.compute_dtype
    # pad columns are forced to -1e9 so no consumer can select them
    assert (np.asarray(logits)[..., tiny_config.vocab_size:] == -1e9).all()


def test_causality(tiny_config, tiny_params, rng):
    """Changing a future token must not change logits at earlier positions."""
    ids, pos = _random_batch(rng, tiny_config, batch=1, seq=12)
    logits_a = forward(tiny_params, tiny_config, ids, pos)
    ids_b = ids.at[0, 8].set((ids[0, 8] + 1) % tiny_config.vocab_size)
    logits_b = forward(tiny_params, tiny_config, ids_b, pos)
    np.testing.assert_allclose(logits_a[0, :8], logits_b[0, :8], atol=1e-6)
    assert not np.allclose(logits_a[0, 8:], logits_b[0, 8:])


def test_padding_mask_blocks_keys(tiny_config, tiny_params, rng):
    """With the last positions marked as padding (True = masked, the inverted
    convention of reference utils.py:36), changing those token ids must not
    affect logits at earlier query positions."""
    ids, pos = _random_batch(rng, tiny_config, batch=1, seq=12)
    mask = jnp.zeros((1, 12), dtype=bool).at[0, 9:].set(True)
    logits_a = forward(tiny_params, tiny_config, ids, pos, mask)
    ids_b = ids.at[0, 10].set((ids[0, 10] + 3) % tiny_config.vocab_size)
    logits_b = forward(tiny_params, tiny_config, ids_b, pos, mask)
    np.testing.assert_allclose(logits_a[0, :9], logits_b[0, :9], atol=1e-6)


def test_double_activation_quirk(tiny_config, tiny_params, rng):
    """The reference applies the activation after down_proj too
    (models/gpt.py:37-38), so the FFN output is non-negative."""
    from tpukit.model.gpt import _apply_feed_forward

    layer0 = jax.tree.map(lambda p: p[0], tiny_params["layers"])
    x = jnp.asarray(rng.randn(2, 8, tiny_config.dim).astype(np.float32))
    out = _apply_feed_forward(layer0, tiny_config, x, None, True)
    assert (np.asarray(out) >= 0).all()


def test_param_shapes_and_count(tiny_config, tiny_params):
    cfg = tiny_config
    p = tiny_params
    assert p["embeddings"]["token"].shape == (cfg.padded_vocab_size, cfg.dim)
    assert p["embeddings"]["position"].shape == (cfg.max_position_embeddings, cfg.dim)
    assert p["layers"]["attn"]["q"]["kernel"].shape == (cfg.num_layers, cfg.dim, cfg.inner_dim)
    assert "bias" not in p["layers"]["attn"]["q"]  # qkv_bias=False (gpt.py:50)
    assert "bias" in p["layers"]["attn"]["out"]  # to_out has bias (gpt.py:64)
    assert p["lm_head"]["kernel"].shape == (cfg.dim, cfg.padded_vocab_size)
    assert "bias" not in p["lm_head"]  # untied, bias=False (gpt.py:219)

    d, hd, h, L, v, pe, m = (
        cfg.dim, cfg.head_dim, cfg.heads, cfg.num_layers, cfg.padded_vocab_size,
        cfg.max_position_embeddings, cfg.ffn_mult,
    )
    inner = hd * h
    per_layer = (
        2 * d  # norm1
        + 3 * d * inner  # qkv
        + inner * d + d  # out proj
        + 2 * d  # norm2
        + d * (d * m) + d * m  # up
        + (d * m) * d + d  # down
    )
    expected = v * d + pe * d + L * per_layer + 2 * d + d * v
    assert param_count(p) == expected


def test_oo_veneer_matches_functional(tiny_config, tiny_params, rng):
    model = TransformerDecoderLM(
        dim=tiny_config.dim,
        head_dim=tiny_config.head_dim,
        heads=tiny_config.heads,
        num_layers=tiny_config.num_layers,
        vocab_size=tiny_config.vocab_size,
        max_position_embeddings=tiny_config.max_position_embeddings,
        compute_dtype=jnp.float32,
    )
    ids, pos = _random_batch(rng, tiny_config)
    np.testing.assert_allclose(
        model(tiny_params, ids, pos),
        forward(tiny_params, tiny_config, ids, pos),
        atol=0,
    )


def test_scan_matches_unrolled(tiny_config, tiny_params, rng):
    """All three trunk execution modes (unrolled — the default, lax.scan,
    and unrolled+remat) must produce identical logits."""
    ids, pos = _random_batch(rng, tiny_config, batch=1, seq=10)
    unrolled = forward(tiny_params, tiny_config, ids, pos)
    scanned = forward(
        tiny_params, tiny_config.replace(scan_layers=True), ids, pos
    )
    remat = forward(
        tiny_params, tiny_config.replace(remat_layers=True), ids, pos
    )
    np.testing.assert_allclose(unrolled, scanned, atol=1e-5)
    np.testing.assert_allclose(unrolled, remat, atol=1e-5)


def test_remat_grads_match(tiny_config, tiny_params, rng):
    """remat recomputes the forward in backward; grads must be unchanged."""
    from tpukit.ops.layers import cross_entropy_loss

    ids, pos = _random_batch(rng, tiny_config, batch=2, seq=12)
    targets = jnp.asarray(
        np.roll(np.asarray(ids), -1, axis=1).astype(np.int32)
    )

    def loss(p, cfg):
        return cross_entropy_loss(forward(p, cfg, ids, pos), targets)

    g_plain = jax.grad(loss)(tiny_params, tiny_config)
    g_remat = jax.grad(loss)(tiny_params, tiny_config.replace(remat_layers=True))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        g_plain,
        g_remat,
    )
