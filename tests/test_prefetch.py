"""Round-7 host-overlap tests: the depth-N input prefetcher and the async
checkpoint writer (ISSUE 2).

The load-bearing guarantees:
  - prefetch changes WHEN host work runs, never WHAT runs: the loss
    trajectory is bit-identical to the synchronous path, and depth only
    affects timing (depth-1 == depth-4 item streams);
  - worker failures surface on the training thread at the position the
    failed batch would have appeared — never swallowed;
  - epoch boundaries flush cleanly (no cross-epoch buffering);
  - an async save snapshots the state the moment `save_auto` is called and
    publishes bytes IDENTICAL to the sync writer's, with the same atomic
    tmp+rename durability (the kill-midrun half lives in
    tests/test_multiprocess.py, slow tier).
"""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpukit import checkpoint as ckpt_lib
from tpukit.flags import TrainFlags
from tpukit.model import GPTConfig
from tpukit.prefetch import HostPrefetcher
from tpukit.shardings import SingleDevice
from tpukit.train import create_train_state, fit, make_optimizer


# ---------------------------------------------------------------------------
# HostPrefetcher unit contract
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_prefetcher_preserves_order_and_values(depth):
    items = [{"i": i} for i in range(17)]
    out = list(HostPrefetcher(items, lambda r: r["i"] * 3, depth=depth))
    assert out == [i * 3 for i in range(17)]


def test_prefetcher_depth_equivalence():
    """Depth changes timing only — the streams are identical element-wise."""
    items = list(range(23))
    d1 = list(HostPrefetcher(items, depth=1))
    d4 = list(HostPrefetcher(items, depth=4))
    assert d1 == d4 == items


def test_prefetcher_propagates_worker_exception_in_iterable():
    def gen():
        yield 1
        yield 2
        raise ValueError("loader blew up")

    pf = HostPrefetcher(gen(), depth=2)
    got = []
    with pytest.raises(ValueError, match="loader blew up"):
        for x in pf:
            got.append(x)
    # the good items BEFORE the failure were delivered in order first
    assert got == [1, 2]
    assert not pf._thread.is_alive()


def test_prefetcher_propagates_worker_exception_in_process_fn():
    def boom(x):
        if x == 3:
            raise RuntimeError("prepare failed")
        return x

    with pytest.raises(RuntimeError, match="prepare failed"):
        list(HostPrefetcher(range(10), boom, depth=4))


def test_prefetcher_epoch_boundary_flush():
    """One prefetcher per epoch: each epoch's iterator yields exactly that
    epoch's batches (reshuffled via set_epoch), nothing buffered across."""
    from tpukit.data import ArrayDataset
    from tpukit.loader import DataLoader

    ids = np.arange(64 * 4, dtype=np.int32).reshape(64, 4)
    loader = DataLoader(ArrayDataset(ids, np.ones_like(ids)), 8, shuffle=True)

    def epoch_rows(epoch):
        loader.set_epoch(epoch)
        pf = HostPrefetcher(loader, depth=2)
        batches = list(pf)
        assert not pf._thread.is_alive()  # flushed + joined at exhaustion
        return [tuple(b["input_ids"][:, 0]) for b in batches]

    e0, e1 = epoch_rows(0), epoch_rows(1)
    assert len(e0) == len(e1) == 8  # exactly one epoch each, no leakage
    assert e0 != e1  # set_epoch reshuffled
    # same epoch again -> identical schedule (determinism through the thread)
    assert epoch_rows(0) == e0


def test_prefetcher_close_mid_epoch_releases_worker():
    import itertools

    pf = HostPrefetcher(itertools.count(), depth=2)  # infinite producer
    assert next(pf) == 0
    pf.close()
    assert not pf._thread.is_alive()
    assert list(pf) == []  # closed iterates as exhausted, never hangs
    pf.close()  # idempotent


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        HostPrefetcher([], depth=0)


def test_prefetcher_window_stats_reset():
    pf = HostPrefetcher(list(range(6)), depth=2)
    list(pf)
    first = pf.window_stats()
    assert 0.0 <= first["occupancy"] <= 2.0
    again = pf.window_stats()
    assert again["occupancy"] == 0.0


def test_prefetcher_occupancy_excludes_done_sentinel():
    """A 1-item epoch at depth 2: nothing was ever prefetched ahead, so the
    gauge must read 0 — the terminal sentinel is not a buffered batch."""
    import time

    pf = HostPrefetcher([42], depth=2)
    time.sleep(0.2)  # let the worker enqueue the item AND the sentinel
    assert list(pf) == [42]
    assert pf.window_stats()["occupancy"] == 0.0


# ---------------------------------------------------------------------------
# fit(): prefetch on/off parity + telemetry fields
# ---------------------------------------------------------------------------


def _tiny_flags(**kw):
    defaults = dict(
        batch_size=8, epochs=1, sequence_length=33, dim=32, head_dim=8,
        heads=4, num_layers=2, learning_rate=1e-3, dataset_slice="96",
        num_workers=0, disable_amp=True, seed=0,
    )
    defaults.update(kw)
    return TrainFlags(**defaults)


def _run_fit(workdir, **kw):
    log = workdir / "run.jsonl"
    cwd = os.getcwd()
    workdir.mkdir(parents=True, exist_ok=True)
    os.chdir(workdir)
    try:
        result = fit(_tiny_flags(metrics_log=str(log), **kw), SingleDevice())
    finally:
        os.chdir(cwd)
    records = [json.loads(line) for line in log.read_text().splitlines()]
    return result, records


@pytest.fixture(scope="module")
def prefetch_pair(tmp_path_factory):
    """ONE deterministic run, two configurations: synchronous input + sync
    checkpoint writer vs prefetch-2 input + async writer. Every comparison
    test reads from this pair — losses must match bitwise AND the periodic
    checkpoints must publish identical bytes (checkpointing never perturbs
    the trajectory, and the async writer is exact)."""
    tmp = tmp_path_factory.mktemp("prefetch")
    sync = _run_fit(
        tmp / "sync", prefetch=0, checkpoint_every=4, async_checkpoint=False
    )
    pf = _run_fit(
        tmp / "pf", prefetch=2, checkpoint_every=4, async_checkpoint=True
    )
    return tmp, sync, pf


def test_fit_prefetch_loss_trajectory_bit_identical(prefetch_pair):
    """The acceptance bar: --prefetch 2 vs --prefetch 0 produce EXACTLY the
    same training losses and eval metrics — the prefetcher only moves host
    work earlier, it never changes batches, order, or numerics."""
    _, (r_sync, recs_sync), (r_pf, recs_pf) = prefetch_pair
    l_sync = [r["loss"] for r in recs_sync if r["kind"] == "train"]
    l_pf = [r["loss"] for r in recs_pf if r["kind"] == "train"]
    assert l_sync and l_sync == l_pf
    assert r_sync.metrics["eval"]["loss"] == r_pf.metrics["eval"]["loss"]
    assert r_sync.metrics["eval"]["accuracy"] == r_pf.metrics["eval"]["accuracy"]
    assert r_sync.metrics["train_tokens"] == r_pf.metrics["train_tokens"]


def test_fit_prefetch_emits_stall_span_and_gauges(prefetch_pair):
    """Prefetch runs replace the data/h2d spans with prefetch_stall and add
    the buffer gauges to every train window (docs/DESIGN.md §6 schema)."""
    _, (_, recs_sync), (_, recs_pf) = prefetch_pair
    sync_win = [r for r in recs_sync if r["kind"] == "train"]
    pf_win = [r for r in recs_pf if r["kind"] == "train"]
    assert all("data" in r["spans"] for r in sync_win)
    assert all("prefetch_stall_s" not in r for r in sync_win)
    for r in pf_win:
        assert "prefetch_stall" in r["spans"]
        assert "data" not in r["spans"] and "h2d" not in r["spans"]
        assert r["prefetch_stall_s"] >= 0.0
        assert 0.0 <= r["prefetch_occupancy"] <= 2.0
        # spans still sum to the window (prefetch_stall is a first-class
        # phase in the goodput accounting)
        assert abs(sum(r["spans"].values()) - 1.0) < 1e-6


def test_fit_rejects_negative_prefetch(tmp_path):
    with pytest.raises(ValueError, match="prefetch"):
        fit(_tiny_flags(prefetch=-1), SingleDevice(), num_epochs=0)


def test_prefetch_flag_parsing():
    from tpukit.flags import parse_flags

    assert parse_flags([]).prefetch == 2  # overlap is the default
    assert parse_flags(["--prefetch", "0"]).prefetch == 0
    assert parse_flags(["--async_checkpoint"]).async_checkpoint is True
    assert parse_flags([]).async_checkpoint is False
    assert parse_flags(
        ["--compilation_cache_dir", "/tmp/x"]
    ).compilation_cache_dir == "/tmp/x"


# ---------------------------------------------------------------------------
# AsyncCheckpointer
# ---------------------------------------------------------------------------


def _tiny_state():
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    return create_train_state(jax.random.PRNGKey(0), cfg, make_optimizer(1e-3))


def test_async_consolidated_bytes_match_sync_writer(tmp_path):
    state = _tiny_state()
    saver = ckpt_lib.AsyncCheckpointer()
    p_async = saver.save_auto(state, tmp_path, name="a", format="consolidated")
    saver.wait()
    assert not saver.in_flight
    p_sync = ckpt_lib.save(state, tmp_path, name="b")
    assert p_async.read_bytes() == p_sync.read_bytes()


def test_async_sharded_restores_identically(tmp_path):
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP

    state = _tiny_state()
    fsdp = FSDP(create_mesh({"data": 8}))
    shapes = jax.eval_shape(lambda: state)
    sharding = fsdp.state_sharding(shapes)
    state = jax.device_put(state, sharding)

    saver = ckpt_lib.AsyncCheckpointer()
    path = saver.save_auto(state, tmp_path, name="async_sh", format="sharded")
    saver.wait()
    assert path.is_dir() and (path / "manifest.json").exists()
    restored = ckpt_lib.restore_sharded(path, shapes, sharding)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        jax.device_get(restored),
    )
    # and it is the SAME on-disk layout the sync writer produces
    sync_path = ckpt_lib.save_sharded(state, tmp_path, name="sync_sh")
    sync_restored = ckpt_lib.restore_sharded(sync_path, shapes, sharding)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(restored),
        jax.device_get(sync_restored),
    )


def test_async_snapshot_taken_at_save_time(tmp_path):
    """The snapshot must capture the state AT the save call — mutating the
    'live' state afterwards (the next donated train step, here simulated
    with a replace) must not leak into the published bytes."""
    state = _tiny_state()
    saver = ckpt_lib.AsyncCheckpointer()
    expected = ckpt_lib.save(state, tmp_path, name="truth")
    path = saver.save_auto(state, tmp_path, name="snap", format="consolidated")
    state = state.replace(step=jnp.int32(999))  # "training moved on"
    saver.wait()
    assert path.read_bytes() == expected.read_bytes()


def test_async_error_surfaces_at_next_barrier(tmp_path):
    state = _tiny_state()
    blocker = tmp_path / "notadir"
    blocker.write_text("x")  # file where the writer needs a directory
    saver = ckpt_lib.AsyncCheckpointer()
    saver.save_auto(state, blocker, name="x", format="sharded")
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        saver.wait()
    # the barrier clears the error: the writer is reusable afterwards
    ok = saver.save_auto(state, tmp_path, name="ok", format="consolidated")
    saver.wait()
    assert ok.exists()


def test_async_join_barrier_single_write_in_flight(tmp_path):
    """Back-to-back saves: the second save joins the first before starting —
    at most one background write exists, and both publish correctly."""
    state = _tiny_state()
    saver = ckpt_lib.AsyncCheckpointer()
    p1 = saver.save_auto(state, tmp_path, name="s1", format="consolidated")
    p2 = saver.save_auto(state, tmp_path, name="s2", format="consolidated")
    saver.wait()
    assert p1.read_bytes() == p2.read_bytes()


def test_fit_async_checkpoints_identical_to_sync_writer(prefetch_pair, tmp_path):
    """Mid-epoch async saves publish exactly what the sync writer publishes:
    same deterministic run, same step-keyed names, byte-identical files
    (the ISSUE acceptance: a save landing mid-epoch restores identically)."""
    base, _, _ = prefetch_pair
    a = sorted((base / "pf" / "checkpoints").glob("*.msgpack"))
    s = sorted((base / "sync" / "checkpoints").glob("*.msgpack"))
    assert [p.name for p in a] == [p.name for p in s] and len(a) >= 3
    for pa, ps in zip(a, s):
        assert pa.read_bytes() == ps.read_bytes(), pa.name
    # and a mid-epoch async checkpoint actually resumes
    mid = a[0]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        resumed = fit(
            _tiny_flags(resume=str(mid), checkpoint_every=0),
            SingleDevice(),
            num_epochs=0,
        )
    finally:
        os.chdir(cwd)
    assert int(resumed.state.step) == 4
