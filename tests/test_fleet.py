"""Fleet serving: the request router over N engine replicas
(tpukit/serve/fleet, round 19, ROADMAP #1).

Contracts pinned here:
  - fleet output is TOKEN-IDENTICAL to a single engine consuming the same
    seeded stream — greedy and fixed-seed sampled, all-at-once and under
    staggered `--qps` arrivals — because per-request seeds ride the
    Request and every replica is the proven round-14 engine;
  - a chaos-killed replica's in-flight requests re-queue onto survivors
    (prompt reconstructed from the Request — completion-carries-prompt)
    and every request's tokens are emitted EXACTLY once, still
    token-identical to the un-killed run;
  - N replicas x model-parallel grids coexist on disjoint device subsets
    of the one process, one params placement per subset from ONE host
    copy (the shared-cold-start ledger);
  - disaggregated prefill: decode replicas never run a prefill program
    (compile budget shrinks to decode + the adopt arm), the handoff's
    decode-side registry claims survive prefill-pool pressure (refcounted
    pages are never reclaimed under a reader), and parity holds;
  - occupancy-driven autoscale grows under load and drains when idle,
    with parity throughout;
  - `kind="fleet"`/`fleet_summary` JSONL lands, `tools/report.py` renders
    the "== fleet ==" section, and the `--min_fleet_tps` gate fails on
    fleet-less logs, sub-threshold throughput, and exactly-once
    violations.
"""

import dataclasses
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit import chaos as chaos_lib
from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.serve import (
    FleetConfig,
    FleetRouter,
    Request,
    ServeConfig,
    ServeEngine,
    synthetic_request_stream,
)
from tpukit.serve import decode as serve_decode
from tpukit.serve.paged import PageAllocator

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


@pytest.fixture(scope="module")
def host_params(params):
    """ONE host-side copy — what `restore_params(..., None)` hands the
    router in production; every replica placement is a device_put of it."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)


def _tokens(comps):
    return {c.rid: list(map(int, c.ids)) for c in comps}


def _single_engine_tokens(params, cfg, tok, serve, reqs):
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    return _tokens(eng.run(list(reqs), max_wall_s=300))


# ---------------------------------------------------------------------------
# Parity: fleet == single engine on the same stream, greedy and sampled,
# all-at-once and under staggered arrivals.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,qps",
    [(0.0, 0, 0.0), (0.9, 5, 0.0), (0.9, 5, 50.0), (0.0, 0, 50.0)],
    ids=["greedy", "sampled", "sampled_qps", "greedy_qps"],
)
def test_fleet_matches_single_engine(tok, cfg, params, host_params,
                                     temperature, top_k, qps):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=temperature, top_k=top_k, window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16), qps=qps)
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4),
                         eos_id=int(tok.eos_token_id))
    got = _tokens(router.run(list(reqs), max_wall_s=300))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["requests"] == 8 and s["duplicate_completions"] == 0
    assert s["kills"] == 0 and s["requeued"] == 0


# ---------------------------------------------------------------------------
# Replica failure: killed mid-stream, in-flight requests re-queue onto the
# survivor, exactly-once output, tokens unchanged.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.9, 5)],
                         ids=["greedy", "sampled"])
def test_fleet_kill_requeues_exactly_once(tok, cfg, params, host_params,
                                          temperature, top_k):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=temperature, top_k=top_k, window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    kill_spec="replica_kill@1:1"),
        eos_id=int(tok.eos_token_id))
    comps = router.run(list(reqs), max_wall_s=300)
    got = _tokens(comps)
    # exactly once: 8 completions, 8 distinct rids
    assert len(comps) == 8 and got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["kills"] == 1 and s["requeued"] >= 1
    assert s["duplicate_completions"] == 0
    assert s["per_replica"][1]["fate"] == "killed"


def test_fleet_never_kills_last_replica(tok, cfg, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 4, seed=2, max_new_tokens=8,
                                    buckets=(8, 16))
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    kill_spec="replica_kill@0:1,replica_kill@1:0"),
        eos_id=int(tok.eos_token_id))
    comps = router.run(list(reqs), max_wall_s=300)
    # the second kill targets the ONLY survivor and must be refused
    assert len(comps) == 4
    assert router.last_summary["kills"] == 1


# ---------------------------------------------------------------------------
# Device subsets: N replicas x model-parallel grids in one process, one
# placement per subset from one host copy.
# ---------------------------------------------------------------------------


def test_fleet_subset_meshes_coexist(tok, cfg, params, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 6, seed=5, max_new_tokens=6,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, devices_per_replica=2,
                                     window_steps=4),
                         eos_id=int(tok.eos_token_id))
    # disjoint subsets, model-parallel grid per replica
    devs = [tuple(d.id for d in np.ravel(e.mesh.devices))
            for e in router._replicas.values()]
    assert devs[0] != devs[1] and not (set(devs[0]) & set(devs[1]))
    for e in router._replicas.values():
        assert e.mesh.shape["model"] == 2
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    # one placement per subset, from ONE shared host copy
    assert router.last_summary["params_placements"] == 2


def test_fleet_cold_start_ledger(tok, cfg, tmp_path):
    """The shared cold start: the checkpoint is read ONCE into host
    arrays, and N replicas cost N placements (meshless replicas share a
    single committed copy — placements == 1) — never N reads."""
    from tpukit import checkpoint as ck
    from tpukit.train import create_train_state, make_optimizer

    state = create_train_state(jax.random.PRNGKey(0), cfg,
                               make_optimizer(1e-4))
    path = ck.save_auto(state, tmp_path, "checkpoint-step5",
                        format="sharded")
    template = jax.eval_shape(lambda: state).params
    # ONE read (no sharding tree): this is the fleet path — the bytes are
    # paid here and never again; every replica placement below is a pure
    # device_put of this copy
    host, info = ck.restore_params(path, template, None)
    assert info["bytes_read"] > 0 and info["bytes_skipped"] > info["bytes_read"]
    serve = ServeConfig(slots=2, buckets=(8,), max_new_tokens=4,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 3, seed=1, max_new_tokens=4,
                                    buckets=(8,))
    # meshless: all replicas SHARE one committed copy — N-1 placements free
    router = FleetRouter(host, cfg, serve, FleetConfig(replicas=3),
                         eos_id=int(tok.eos_token_id))
    assert router.placements == 1
    comps = router.run(list(reqs), max_wall_s=300)
    assert len(comps) == 3
    assert router.last_summary["params_placements"] == 1
    # meshed: one placement per subset
    router2 = FleetRouter(host, cfg, serve,
                          FleetConfig(replicas=2, devices_per_replica=2),
                          eos_id=int(tok.eos_token_id))
    assert router2.placements == 2


# ---------------------------------------------------------------------------
# Disaggregated prefill: handoff parity, the shrunk decode compile budget,
# and the write-safety of decode-side claims under pool pressure.
# ---------------------------------------------------------------------------


def test_disagg_prefill_parity_and_compile_budget(tok, cfg, params,
                                                  host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8, page_size=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16), shared_prefix=8)
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    adopt0 = serve_decode.adopt_slot._cache_size()
    chunk0 = serve_decode.prefill_chunk_paged._cache_size()
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4,
                                     disagg_prefill=True),
                         eos_id=int(tok.eos_token_id))
    replicas = list(router._replicas.values())
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    dp = s["disagg_prefill"]
    assert dp["handoffs"] == 8 and dp["worker_admitted"] == 8
    assert dp["worker_prefix_hits"] > 0  # the shared system prompt hit
    # decode replicas NEVER ran a prefill: their compile budget is the
    # decode program + the adopt arm. The worker owns every chunk program.
    for eng in replicas:
        assert eng.spans.epoch()["seconds"].get("prefill", 0.0) == 0.0
    assert serve_decode.adopt_slot._cache_size() - adopt0 <= 1
    # chunk compiles bounded by the WORKER's power-of-two admit sizes
    worker_sizes = (router.prefill.serve.slots - 1).bit_length() + 1
    assert (serve_decode.prefill_chunk_paged._cache_size() - chunk0
            <= worker_sizes)


def test_disagg_claims_survive_prefill_pool_pressure(tok, cfg, params,
                                                     host_params):
    """The handoff safety invariant: decode-side pages backing live lanes
    are refcounted (claimed/owned) and can never be reclaimed, however
    hard the PREFILL pool is pressed — a tiny worker pool that must
    reclaim its retained prefix pages between admissions still produces
    token-exact completions on the decode side."""
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8, page_size=8)
    # worker pool: exactly one worst-case request + null page, so UNIQUE
    # prompts interleaved with the shared-prefix ones force the worker's
    # retained prefix pages out between admissions (reclaim pressure) —
    # while the decode side keeps claiming its own registered copies
    min_pages = -(-(16 + MAX_NEW) // 8) + 1
    shared = synthetic_request_stream(tok, 6, seed=3, max_new_tokens=MAX_NEW,
                                      buckets=(8, 16), shared_prefix=8)
    unique = synthetic_request_stream(tok, 4, seed=11, max_new_tokens=MAX_NEW,
                                      buckets=(8, 16))
    reqs = list(shared)
    for i, r in enumerate(unique):
        reqs.insert(2 * i + 1, Request(rid=100 + i, ids=r.ids,
                                       max_new_tokens=MAX_NEW, seed=11 + i))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4,
                                     disagg_prefill=True,
                                     prefill_pages=min_pages),
                         eos_id=int(tok.eos_token_id))
    replicas = list(router._replicas.values())
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    # pressure actually happened on the worker pool...
    assert router.prefill.allocator.stats.reclaimed > 0
    # ...and decode-side claims still fired (registered copies survive the
    # worker's reclaims — the refcounted-reader invariant, pool-for-pool)
    assert sum(e.allocator.stats.prefix_hits for e in replicas) > 0


def test_claimed_pages_never_reclaimed_unit():
    """Allocator-level spelling of the same invariant: a claimed
    (refcount >= 1) registered page is not in the retained LRU, so pool
    pressure can only reclaim unreferenced pages — a doomed allocation
    returns None rather than stealing from a reader."""
    alloc = PageAllocator(num_pages=6, page_size=4)
    ids = tuple(range(8))
    own = alloc.alloc(2)
    alloc.register(ids, own)          # published prefix chain
    alloc.claim(own)                  # a decode-side reader claims it
    alloc.release(own)                # the writer lane evicts
    # reader still holds refcount 1 -> pages are NOT retained/reclaimable
    assert alloc.refcount[own[0]] == 1
    got = alloc.alloc(4)              # pool has 3 free pages left
    assert got is None                # refuses rather than stealing
    assert alloc.lookup_prefix(ids, 2) == own  # registry intact
    alloc.release(own)                # reader done -> retained now
    assert alloc.alloc(4) is not None  # pressure may NOW reclaim them


# ---------------------------------------------------------------------------
# Autoscale: grow under load, drain when idle, parity throughout.
# ---------------------------------------------------------------------------


def test_fleet_autoscale_up_and_down(tok, cfg, params, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=8)
    burst = synthetic_request_stream(tok, 10, seed=7, max_new_tokens=8,
                                     buckets=(8, 16))
    # a trickle arrives after the burst drains: low occupancy, empty queue
    trickle = [
        Request(rid=100 + i, ids=burst[i].ids, max_new_tokens=8,
                seed=7 + i, arrival_s=1.5 + 0.4 * i)
        for i in range(4)
    ]
    reqs = burst + trickle
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=1, max_replicas=2, window_steps=2,
                    scale_up_occupancy=0.9, scale_down_occupancy=0.45),
        eos_id=int(tok.eos_token_id))
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["scale_ups"] >= 1, s
    assert s["scale_downs"] >= 1, s
    assert s["replicas_peak"] == 2
    assert s["duplicate_completions"] == 0


# ---------------------------------------------------------------------------
# Telemetry: fleet JSONL + report render + the --min_fleet_tps gate.
# ---------------------------------------------------------------------------


def test_fleet_jsonl_and_report_gate(tok, cfg, host_params, tmp_path):
    import importlib

    from tpukit.obs import FlightRecorder, StepLogger

    report = importlib.import_module("tools.report")
    log = tmp_path / "fleet.jsonl"
    logger = StepLogger(str(log))
    recorder = FlightRecorder(capacity=64)
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=4)
    reqs = synthetic_request_stream(tok, 8, seed=8, max_new_tokens=8,
                                    buckets=(8, 16))
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=2,
                                     kill_spec="replica_kill@1:1"),
                         eos_id=int(tok.eos_token_id), logger=logger,
                         recorder=recorder)
    router.run(list(reqs), max_wall_s=300)
    logger.close()

    recs = [json.loads(l) for l in log.read_text().splitlines()]
    fleet_wins = [r for r in recs if r["kind"] == "fleet"]
    fleet_sums = [r for r in recs if r["kind"] == "fleet_summary"]
    events = [r for r in recs if r["kind"] == "fleet_event"]
    serve_wins = [r for r in recs if r["kind"] == "serve"]
    serve_sums = [r for r in recs if r["kind"] == "serve_summary"]
    assert fleet_wins and len(fleet_sums) == 1
    assert any(e["event"] == "replica_kill" for e in events)
    # replica-tagged serve telemetry: every window/summary names its engine
    assert serve_wins and all("replica" in r for r in serve_wins)
    assert serve_sums and all("replica" in r for r in serve_sums)
    s = fleet_sums[0]
    assert s["requests"] == 8 and s["tokens_per_sec"] > 0
    assert s["requeued"] >= 1 and s["duplicate_completions"] == 0
    assert s["p99_e2e_s"] >= s["p50_e2e_s"]
    # the flight recorder saw the fleet records too
    ring = [r for r in recorder.snapshot() if r["kind"] == "fleet_summary"]
    assert len(ring) == 1

    text = report.summarize(recs)
    assert "== fleet ==" in text
    assert "fleet tokens/s" in text and "re-queued" in text
    assert "per-replica occupancy" in text

    ok, msg = report.check_min_fleet_tps(recs, 1.0)
    assert ok, msg
    ok, msg = report.check_min_fleet_tps(recs, 1e9)
    assert not ok and "FAIL" in msg
    # no fleet records at all -> fail, never a vacuous pass
    ok, msg = report.check_min_fleet_tps(
        [r for r in recs if r["kind"] != "fleet_summary"], 1.0)
    assert not ok and "no fleet_summary" in msg
    # an exactly-once violation fails the gate even above threshold
    forged = [dict(s, duplicate_completions=1)]
    ok, msg = report.check_min_fleet_tps(forged, 1.0)
    assert not ok and "duplicate" in msg


# ---------------------------------------------------------------------------
# Validation: named construction errors, fleet-scoped chaos grammar.
# ---------------------------------------------------------------------------


def test_fleet_config_validation(tok, cfg, host_params):
    with pytest.raises(ValueError, match="replicas"):
        FleetConfig(replicas=0)
    with pytest.raises(ValueError, match="min_replicas"):
        FleetConfig(replicas=2, min_replicas=3)
    with pytest.raises(ValueError, match="max_replicas"):
        FleetConfig(replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="oscillate"):
        FleetConfig(scale_up_occupancy=0.5, scale_down_occupancy=0.5)
    with pytest.raises(ValueError, match="prefill worker"):
        FleetConfig(prefill_slots=4)
    with pytest.raises(chaos_lib.ChaosSpecError, match="replica_kill"):
        FleetConfig(kill_spec="nan_loss@5")
    with pytest.raises(chaos_lib.ChaosSpecError, match="integer replica id"):
        chaos_lib.parse_spec("replica_kill@5:-1")
    # the training harness rejects fleet-scoped faults by name
    with pytest.raises(chaos_lib.ChaosSpecError, match="fleet-scoped"):
        chaos_lib.ChaosEngine("replica_kill@5")
    serve_ring = ServeConfig(slots=2, buckets=(8,), max_new_tokens=4)
    with pytest.raises(ValueError, match="paged cache"):
        FleetRouter(host_params, cfg, serve_ring,
                    FleetConfig(replicas=2, disagg_prefill=True), eos_id=1)
    with pytest.raises(ValueError, match="needs 16 devices"):
        FleetRouter(host_params, cfg, serve_ring,
                    FleetConfig(replicas=2, devices_per_replica=8), eos_id=1)
    moe = cfg.replace(num_experts=2, moe_dispatch="pallas")
    with pytest.raises(ValueError, match="meshless"):
        FleetRouter(host_params, moe, serve_ring,
                    FleetConfig(replicas=2, devices_per_replica=2), eos_id=1)


# ---------------------------------------------------------------------------
# Crash tolerance (round 24): durable ledger + real-process SIGKILL,
# slow-vs-dead liveness discrimination, request deadlines, backpressure,
# ledger replay, and the serving chaos grammar.
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parent.parent


def test_process_fleet_sigkill_requeues_and_parity(tok, cfg, params,
                                                   tmp_path):
    """THE round-24 acceptance: a real worker process SIGKILLed mid-stream
    loses nothing — its leases revoke, its requests requeue onto the
    survivor, and the durable completion set is token-identical to an
    unkilled single engine with ZERO duplicate completions across real
    process death."""
    from tpukit.obs import StepLogger
    from tpukit.serve.ledger import ProcessFleet

    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    log = tmp_path / "procs.jsonl"
    logger = StepLogger(str(log))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def spawn(idx):
        return subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "fleet_worker.py"),
             str(tmp_path / "fleet"), str(idx)],
            cwd=str(REPO), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    fleet = ProcessFleet(tmp_path / "fleet", spawn=spawn, replicas=2,
                         replica_timeout=60.0, request_retries=3,
                         chaos=chaos_lib.ServingChaos("replica_sigkill@3:1"),
                         logger=logger)
    s = fleet.run(list(reqs), max_wall_s=240.0)
    logger.close()
    got = {rid: list(map(int, rec["ids"]))
           for rid, rec in fleet.ledger.completions().items()}
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    assert s["kills"] == 1 and s["replicas_dead"] >= 1
    assert s["requeued"] >= 1 and s["leases_revoked"] >= 1
    assert s["duplicate_completions"] == 0
    assert s["ledger"]["duplicates"] == 0
    assert s["request_failures"] == 0
    # the death was a REAL SIGKILL: the worker's wait status says so
    assert any(d["reason"] == "exit" and d.get("code") == -9
               for d in s["deaths"])
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    events = {r["event"] for r in recs if r["kind"] == "fleet_event"}
    assert "replica_sigkill" in events and "replica_dead" in events
    assert any(r["kind"] == "lease_requeue" for r in recs)
    assert any(r["kind"] == "chaos" and r.get("fault") == "replica_sigkill"
               for r in recs)


def test_liveness_discriminates_slow_from_dead(tok, cfg, params, host_params,
                                               tmp_path):
    """slow_replica@R:ms against --replica_timeout: a stall shorter than
    the timeout is a straggler and must NOT be declared dead; the SAME
    fault outliving the timeout IS death — leases revoke, work requeues
    onto the survivor, and parity holds either way."""
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    base = synthetic_request_stream(tok, 16, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, base)
    slow = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    fleet_dir=str(tmp_path / "slow"), replica_timeout=5.0,
                    kill_spec="slow_replica@2:30"),
        eos_id=int(tok.eos_token_id))
    got = _tokens(slow.run(list(base), max_wall_s=300))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = slow.last_summary
    assert s["replicas_dead"] == 0 and s["kills"] == 0
    assert s["requeued"] == 0
    # the dead case must not ride on wall-clock racing a warm (fast) run:
    # rid 1 lands on replica 1 (least-loaded round-robin) and is PINNED
    # stuck there, so the stalled replica provably holds a lease when its
    # heartbeat age crosses the timeout; its deadline is the run's escape
    # hatch once the request requeues (still stuck) onto the survivor
    reqs = [dataclasses.replace(r, deadline_ms=800.0) if r.rid == 1 else r
            for r in base]
    dead = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    fleet_dir=str(tmp_path / "dead"), replica_timeout=0.15,
                    kill_spec="slow_replica@2:60000,stuck_request@1"),
        eos_id=int(tok.eos_token_id))
    comps = dead.run(list(reqs), max_wall_s=300)
    got = _tokens(comps)
    assert got.keys() == want.keys()
    for rid in want:
        if rid != 1:
            np.testing.assert_array_equal(got[rid], want[rid],
                                          err_msg=f"rid {rid}")
    assert {c.rid: c for c in comps}[1].reason == "deadline"
    s = dead.last_summary
    assert s["replicas_dead"] == 1 and s["requeued"] >= 1
    assert s["leases_revoked"] >= 1
    assert s["duplicate_completions"] == 0
    assert s["per_replica"][1]["fate"] == "dead"
    assert s["ledger"]["duplicates"] == 0
    assert s["deadline_misses"] == 1


def test_deadline_evicts_stuck_request(tok, cfg, params, host_params,
                                       tmp_path):
    """stuck_request@RID + deadline_ms: the pinned request is evicted at
    its deadline as a reason="deadline" completion with partial output,
    every OTHER request's tokens are untouched, and the miss lands in the
    summary, the JSONL, and the --max_deadline_miss_pct gate."""
    import importlib

    from tpukit.obs import StepLogger

    report = importlib.import_module("tools.report")
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    base = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, base)
    stuck_rid = base[2].rid
    reqs = [dataclasses.replace(r, deadline_ms=600.0) if r.rid == stuck_rid
            else r for r in base]
    log = tmp_path / "deadline.jsonl"
    logger = StepLogger(str(log))
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    kill_spec=f"stuck_request@{stuck_rid}"),
        eos_id=int(tok.eos_token_id), logger=logger)
    comps = router.run(list(reqs), max_wall_s=120)
    logger.close()
    got = _tokens(comps)
    assert got.keys() == want.keys()
    by_rid = {c.rid: c for c in comps}
    assert by_rid[stuck_rid].reason == "deadline"
    for rid in want:
        if rid != stuck_rid:
            np.testing.assert_array_equal(got[rid], want[rid],
                                          err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["deadline_misses"] == 1
    assert s["duplicate_completions"] == 0

    recs = [json.loads(l) for l in log.read_text().splitlines()]
    misses = [r for r in recs if r["kind"] == "deadline_miss"]
    assert len(misses) == 1 and misses[0]["rid"] == stuck_rid
    assert misses[0]["over_ms"] > 0
    text = report.summarize(recs)
    assert "fleet recovery" in text and "deadline miss" in text
    # the gate: 1/8 = 12.5% — passes a 50% threshold, fails 5%
    ok, msg = report.check_max_deadline_miss_pct(recs, 50.0)
    assert ok, msg
    ok, msg = report.check_max_deadline_miss_pct(recs, 5.0)
    assert not ok and "FAIL" in msg
    # no fleet summary at all -> fail, never a vacuous pass
    ok, msg = report.check_max_deadline_miss_pct(
        [r for r in recs if r["kind"] != "fleet_summary"], 50.0)
    assert not ok and "no fleet_summary" in msg
    # a pre-round-24 summary (no deadline_misses field) fails too
    forged = [{k: v for k, v in s.items() if k != "deadline_misses"}]
    ok, msg = report.check_max_deadline_miss_pct(forged, 50.0)
    assert not ok and "deadline_misses" in msg


def test_backpressure_sheds_lowest_priority(tok, cfg, params, host_params,
                                            tmp_path):
    """max_queue_depth backpressure: over-depth arrivals shed lowest
    priority first, each as a NAMED request_rejected event and a terminal
    backpressure ledger record; the admitted survivors stay token-exact."""
    from tpukit.obs import StepLogger

    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    base = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, base)
    keep = {base[0].rid, base[5].rid}
    reqs = [dataclasses.replace(r, priority=1) if r.rid in keep else r
            for r in base]
    log = tmp_path / "shed.jsonl"
    logger = StepLogger(str(log))
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4, max_queue_depth=2,
                    fleet_dir=str(tmp_path / "fleet")),
        eos_id=int(tok.eos_token_id), logger=logger)
    comps = router.run(list(reqs), max_wall_s=120)
    logger.close()
    got = _tokens(comps)
    assert got.keys() == keep
    for rid in keep:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["rejected"] == 6 and s["requests"] == 2
    fails = router.ledger.failures()
    assert set(fails) == {r.rid for r in base} - keep
    assert all(f["reason"] == "backpressure" for f in fails.values())
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    rej = [r for r in recs if r["kind"] == "fleet_event"
           and r["event"] == "request_rejected"]
    assert len(rej) == 6
    assert all(r["reason"] == "backpressure" for r in rej)


def test_ledger_replay_resumes_at_frontier(tok, cfg, params, host_params,
                                           tmp_path):
    """A router crashing mid-stream (a ledger I/O fault outliving the
    retry budget) leaves its completed frontier durable; a restarted
    router over the SAME directory replays it and serves only the
    remainder — the union is token-exact with zero duplicates."""
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    fdir = str(tmp_path / "fleet")
    crashed = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4, fleet_dir=fdir,
                    # 9 consecutive failures of the 7th ledger operation:
                    # past the default retry budget -> fatal, mid-stream
                    kill_spec="ledger_io_fail@7:9"),
        eos_id=int(tok.eos_token_id))
    with pytest.raises(IOError, match="chaos: injected"):
        crashed.run(list(reqs), max_wall_s=300)
    durable = crashed.ledger.completions()
    assert 1 <= len(durable) < 8
    restarted = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4, fleet_dir=fdir),
        eos_id=int(tok.eos_token_id))
    comps = restarted.run(list(reqs), max_wall_s=300)
    # the restarted router served ONLY the not-yet-completed frontier...
    assert {c.rid for c in comps} == set(want) - set(durable)
    s = restarted.last_summary
    assert s["ledger"]["replayed"] == len(durable)
    assert s["ledger"]["completed"] == 8
    assert s["ledger"]["duplicates"] == 0
    # ...and the durable union is the full stream, token-exact
    got = {rid: list(map(int, rec["ids"]))
           for rid, rec in restarted.ledger.completions().items()}
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")


def test_ledger_io_fault_absorbed_by_retry(tok, cfg, params, host_params,
                                           tmp_path):
    """ledger_io_fail within the retry budget is absorbed: the run
    completes token-exact and the injected faults surface as
    kind="chaos" records, not failures."""
    from tpukit.obs import StepLogger

    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    log = tmp_path / "iofault.jsonl"
    logger = StepLogger(str(log))
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    fleet_dir=str(tmp_path / "fleet"),
                    kill_spec="ledger_io_fail@2:2"),
        eos_id=int(tok.eos_token_id), logger=logger)
    got = _tokens(router.run(list(reqs), max_wall_s=300))
    logger.close()
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["duplicate_completions"] == 0 and s["ledger"]["duplicates"] == 0
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    chaos_recs = [r for r in recs if r["kind"] == "chaos"]
    assert sum(1 for r in chaos_recs if r.get("fault") == "ledger_io") == 2


def test_serving_chaos_grammar_one_path():
    """ONE grammar: every fleet fault kind parses through
    validate_fleet_spec (shared with --chaos_spec's parse_spec), malformed
    entries fail by name, and the round-24 FleetConfig knobs validate."""
    entries = chaos_lib.validate_fleet_spec(
        "replica_kill@3,replica_sigkill@4:1,slow_replica@2:50,"
        "stuck_request@7,ledger_io_fail@2:3")
    assert [e["kind"] for e in entries] == [
        "replica_kill", "replica_sigkill", "slow_replica",
        "stuck_request", "ledger_io_fail"]
    ch = chaos_lib.ServingChaos(
        "replica_sigkill@4:1,slow_replica@2:50,stuck_request@7,"
        "ledger_io_fail@2:3")
    assert ch.sigkills == {4: [1]}
    assert ch.stalls == {2: [0.05]}
    assert ch.stuck == {7}
    # FleetConfig.kill_spec rides the same path
    FleetConfig(replicas=2, kill_spec="slow_replica@2:50")
    with pytest.raises(chaos_lib.ChaosSpecError, match="stall"):
        FleetConfig(replicas=2, kill_spec="slow_replica@2")
    with pytest.raises(chaos_lib.ChaosSpecError, match="takes no param"):
        chaos_lib.validate_fleet_spec("stuck_request@7:1")
    with pytest.raises(chaos_lib.ChaosSpecError, match="1-based"):
        chaos_lib.validate_fleet_spec("ledger_io_fail@0")
    with pytest.raises(chaos_lib.ChaosSpecError, match="integer replica id"):
        chaos_lib.validate_fleet_spec("replica_sigkill@5:-1")
    # round-24 robustness knobs: named construction errors
    with pytest.raises(ValueError, match="replica_timeout"):
        FleetConfig(replicas=2, replica_timeout=-1.0)
    with pytest.raises(ValueError, match="needs fleet_dir"):
        FleetConfig(replicas=2, replica_timeout=1.0)
    with pytest.raises(ValueError, match="request_retries"):
        FleetConfig(replicas=2, request_retries=-1)
    with pytest.raises(ValueError, match="max_queue_depth"):
        FleetConfig(replicas=2, max_queue_depth=-1)


def test_serving_chaos_io_fault_occurrence_semantics():
    """A scheduled count of c fails the first c ATTEMPTS of that
    occurrence (retries re-enter without advancing the index), then the
    occurrence completes; foreign sites pass through untouched."""
    ch = chaos_lib.ServingChaos("ledger_io_fail@2:2")
    ch.io_fault("ledger")                       # occurrence 1 passes
    with pytest.raises(IOError, match="occurrence 2"):
        ch.io_fault("ledger")                   # occurrence 2, attempt 1
    with pytest.raises(IOError, match="occurrence 2"):
        ch.io_fault("ledger")                   # occurrence 2, attempt 2
    ch.io_fault("ledger")                       # attempt 3 succeeds
    ch.io_fault("ledger")                       # occurrence 3 passes
    fired = ch.drain_fired()
    assert len(fired) == 2
    assert all(f["fault"] == "ledger_io" for f in fired)
    ch2 = chaos_lib.ServingChaos("ledger_io_fail@1:1")
    ch2.io_fault("checkpoint")                  # not this plan's site


def test_fleet_decode_plan_is_standalone_plan():
    """The router adds ZERO collectives: the per-replica plan is the
    standalone decode closed form, byte for byte, on a subset mesh."""
    from tpukit.analysis import decode_comm_plan, fleet_decode_comm_plan
    from tpukit.mesh import create_mesh

    cfg = GPTConfig(dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=160,
                    max_position_embeddings=64, compute_dtype=jnp.float32)
    mesh = create_mesh({"data": 1, "model": 4},
                       devices=jax.devices()[4:8])
    base = decode_comm_plan(cfg, mesh, 4)
    fleet = fleet_decode_comm_plan(cfg, mesh, 4)
    assert fleet.ops == base.ops and fleet.exhaustive
    assert fleet.label.startswith("fleet replica")
