"""Fleet serving: the request router over N engine replicas
(tpukit/serve/fleet, round 19, ROADMAP #1).

Contracts pinned here:
  - fleet output is TOKEN-IDENTICAL to a single engine consuming the same
    seeded stream — greedy and fixed-seed sampled, all-at-once and under
    staggered `--qps` arrivals — because per-request seeds ride the
    Request and every replica is the proven round-14 engine;
  - a chaos-killed replica's in-flight requests re-queue onto survivors
    (prompt reconstructed from the Request — completion-carries-prompt)
    and every request's tokens are emitted EXACTLY once, still
    token-identical to the un-killed run;
  - N replicas x model-parallel grids coexist on disjoint device subsets
    of the one process, one params placement per subset from ONE host
    copy (the shared-cold-start ledger);
  - disaggregated prefill: decode replicas never run a prefill program
    (compile budget shrinks to decode + the adopt arm), the handoff's
    decode-side registry claims survive prefill-pool pressure (refcounted
    pages are never reclaimed under a reader), and parity holds;
  - occupancy-driven autoscale grows under load and drains when idle,
    with parity throughout;
  - `kind="fleet"`/`fleet_summary` JSONL lands, `tools/report.py` renders
    the "== fleet ==" section, and the `--min_fleet_tps` gate fails on
    fleet-less logs, sub-threshold throughput, and exactly-once
    violations.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit import chaos as chaos_lib
from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.serve import (
    FleetConfig,
    FleetRouter,
    Request,
    ServeConfig,
    ServeEngine,
    synthetic_request_stream,
)
from tpukit.serve import decode as serve_decode
from tpukit.serve.paged import PageAllocator

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


@pytest.fixture(scope="module")
def host_params(params):
    """ONE host-side copy — what `restore_params(..., None)` hands the
    router in production; every replica placement is a device_put of it."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)


def _tokens(comps):
    return {c.rid: list(map(int, c.ids)) for c in comps}


def _single_engine_tokens(params, cfg, tok, serve, reqs):
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    return _tokens(eng.run(list(reqs), max_wall_s=300))


# ---------------------------------------------------------------------------
# Parity: fleet == single engine on the same stream, greedy and sampled,
# all-at-once and under staggered arrivals.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,qps",
    [(0.0, 0, 0.0), (0.9, 5, 0.0), (0.9, 5, 50.0), (0.0, 0, 50.0)],
    ids=["greedy", "sampled", "sampled_qps", "greedy_qps"],
)
def test_fleet_matches_single_engine(tok, cfg, params, host_params,
                                     temperature, top_k, qps):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=temperature, top_k=top_k, window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16), qps=qps)
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4),
                         eos_id=int(tok.eos_token_id))
    got = _tokens(router.run(list(reqs), max_wall_s=300))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["requests"] == 8 and s["duplicate_completions"] == 0
    assert s["kills"] == 0 and s["requeued"] == 0


# ---------------------------------------------------------------------------
# Replica failure: killed mid-stream, in-flight requests re-queue onto the
# survivor, exactly-once output, tokens unchanged.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.9, 5)],
                         ids=["greedy", "sampled"])
def test_fleet_kill_requeues_exactly_once(tok, cfg, params, host_params,
                                          temperature, top_k):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=temperature, top_k=top_k, window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    kill_spec="replica_kill@1:1"),
        eos_id=int(tok.eos_token_id))
    comps = router.run(list(reqs), max_wall_s=300)
    got = _tokens(comps)
    # exactly once: 8 completions, 8 distinct rids
    assert len(comps) == 8 and got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["kills"] == 1 and s["requeued"] >= 1
    assert s["duplicate_completions"] == 0
    assert s["per_replica"][1]["fate"] == "killed"


def test_fleet_never_kills_last_replica(tok, cfg, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 4, seed=2, max_new_tokens=8,
                                    buckets=(8, 16))
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=2, window_steps=4,
                    kill_spec="replica_kill@0:1,replica_kill@1:0"),
        eos_id=int(tok.eos_token_id))
    comps = router.run(list(reqs), max_wall_s=300)
    # the second kill targets the ONLY survivor and must be refused
    assert len(comps) == 4
    assert router.last_summary["kills"] == 1


# ---------------------------------------------------------------------------
# Device subsets: N replicas x model-parallel grids in one process, one
# placement per subset from one host copy.
# ---------------------------------------------------------------------------


def test_fleet_subset_meshes_coexist(tok, cfg, params, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 6, seed=5, max_new_tokens=6,
                                    buckets=(8, 16))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, devices_per_replica=2,
                                     window_steps=4),
                         eos_id=int(tok.eos_token_id))
    # disjoint subsets, model-parallel grid per replica
    devs = [tuple(d.id for d in np.ravel(e.mesh.devices))
            for e in router._replicas.values()]
    assert devs[0] != devs[1] and not (set(devs[0]) & set(devs[1]))
    for e in router._replicas.values():
        assert e.mesh.shape["model"] == 2
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    # one placement per subset, from ONE shared host copy
    assert router.last_summary["params_placements"] == 2


def test_fleet_cold_start_ledger(tok, cfg, tmp_path):
    """The shared cold start: the checkpoint is read ONCE into host
    arrays, and N replicas cost N placements (meshless replicas share a
    single committed copy — placements == 1) — never N reads."""
    from tpukit import checkpoint as ck
    from tpukit.train import create_train_state, make_optimizer

    state = create_train_state(jax.random.PRNGKey(0), cfg,
                               make_optimizer(1e-4))
    path = ck.save_auto(state, tmp_path, "checkpoint-step5",
                        format="sharded")
    template = jax.eval_shape(lambda: state).params
    # ONE read (no sharding tree): this is the fleet path — the bytes are
    # paid here and never again; every replica placement below is a pure
    # device_put of this copy
    host, info = ck.restore_params(path, template, None)
    assert info["bytes_read"] > 0 and info["bytes_skipped"] > info["bytes_read"]
    serve = ServeConfig(slots=2, buckets=(8,), max_new_tokens=4,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 3, seed=1, max_new_tokens=4,
                                    buckets=(8,))
    # meshless: all replicas SHARE one committed copy — N-1 placements free
    router = FleetRouter(host, cfg, serve, FleetConfig(replicas=3),
                         eos_id=int(tok.eos_token_id))
    assert router.placements == 1
    comps = router.run(list(reqs), max_wall_s=300)
    assert len(comps) == 3
    assert router.last_summary["params_placements"] == 1
    # meshed: one placement per subset
    router2 = FleetRouter(host, cfg, serve,
                          FleetConfig(replicas=2, devices_per_replica=2),
                          eos_id=int(tok.eos_token_id))
    assert router2.placements == 2


# ---------------------------------------------------------------------------
# Disaggregated prefill: handoff parity, the shrunk decode compile budget,
# and the write-safety of decode-side claims under pool pressure.
# ---------------------------------------------------------------------------


def test_disagg_prefill_parity_and_compile_budget(tok, cfg, params,
                                                  host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8, page_size=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16), shared_prefix=8)
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    adopt0 = serve_decode.adopt_slot._cache_size()
    chunk0 = serve_decode.prefill_chunk_paged._cache_size()
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4,
                                     disagg_prefill=True),
                         eos_id=int(tok.eos_token_id))
    replicas = list(router._replicas.values())
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    dp = s["disagg_prefill"]
    assert dp["handoffs"] == 8 and dp["worker_admitted"] == 8
    assert dp["worker_prefix_hits"] > 0  # the shared system prompt hit
    # decode replicas NEVER ran a prefill: their compile budget is the
    # decode program + the adopt arm. The worker owns every chunk program.
    for eng in replicas:
        assert eng.spans.epoch()["seconds"].get("prefill", 0.0) == 0.0
    assert serve_decode.adopt_slot._cache_size() - adopt0 <= 1
    # chunk compiles bounded by the WORKER's power-of-two admit sizes
    worker_sizes = (router.prefill.serve.slots - 1).bit_length() + 1
    assert (serve_decode.prefill_chunk_paged._cache_size() - chunk0
            <= worker_sizes)


def test_disagg_claims_survive_prefill_pool_pressure(tok, cfg, params,
                                                     host_params):
    """The handoff safety invariant: decode-side pages backing live lanes
    are refcounted (claimed/owned) and can never be reclaimed, however
    hard the PREFILL pool is pressed — a tiny worker pool that must
    reclaim its retained prefix pages between admissions still produces
    token-exact completions on the decode side."""
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8, page_size=8)
    # worker pool: exactly one worst-case request + null page, so UNIQUE
    # prompts interleaved with the shared-prefix ones force the worker's
    # retained prefix pages out between admissions (reclaim pressure) —
    # while the decode side keeps claiming its own registered copies
    min_pages = -(-(16 + MAX_NEW) // 8) + 1
    shared = synthetic_request_stream(tok, 6, seed=3, max_new_tokens=MAX_NEW,
                                      buckets=(8, 16), shared_prefix=8)
    unique = synthetic_request_stream(tok, 4, seed=11, max_new_tokens=MAX_NEW,
                                      buckets=(8, 16))
    reqs = list(shared)
    for i, r in enumerate(unique):
        reqs.insert(2 * i + 1, Request(rid=100 + i, ids=r.ids,
                                       max_new_tokens=MAX_NEW, seed=11 + i))
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4,
                                     disagg_prefill=True,
                                     prefill_pages=min_pages),
                         eos_id=int(tok.eos_token_id))
    replicas = list(router._replicas.values())
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    assert got.keys() == want.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    # pressure actually happened on the worker pool...
    assert router.prefill.allocator.stats.reclaimed > 0
    # ...and decode-side claims still fired (registered copies survive the
    # worker's reclaims — the refcounted-reader invariant, pool-for-pool)
    assert sum(e.allocator.stats.prefix_hits for e in replicas) > 0


def test_claimed_pages_never_reclaimed_unit():
    """Allocator-level spelling of the same invariant: a claimed
    (refcount >= 1) registered page is not in the retained LRU, so pool
    pressure can only reclaim unreferenced pages — a doomed allocation
    returns None rather than stealing from a reader."""
    alloc = PageAllocator(num_pages=6, page_size=4)
    ids = tuple(range(8))
    own = alloc.alloc(2)
    alloc.register(ids, own)          # published prefix chain
    alloc.claim(own)                  # a decode-side reader claims it
    alloc.release(own)                # the writer lane evicts
    # reader still holds refcount 1 -> pages are NOT retained/reclaimable
    assert alloc.refcount[own[0]] == 1
    got = alloc.alloc(4)              # pool has 3 free pages left
    assert got is None                # refuses rather than stealing
    assert alloc.lookup_prefix(ids, 2) == own  # registry intact
    alloc.release(own)                # reader done -> retained now
    assert alloc.alloc(4) is not None  # pressure may NOW reclaim them


# ---------------------------------------------------------------------------
# Autoscale: grow under load, drain when idle, parity throughout.
# ---------------------------------------------------------------------------


def test_fleet_autoscale_up_and_down(tok, cfg, params, host_params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=8)
    burst = synthetic_request_stream(tok, 10, seed=7, max_new_tokens=8,
                                     buckets=(8, 16))
    # a trickle arrives after the burst drains: low occupancy, empty queue
    trickle = [
        Request(rid=100 + i, ids=burst[i].ids, max_new_tokens=8,
                seed=7 + i, arrival_s=1.5 + 0.4 * i)
        for i in range(4)
    ]
    reqs = burst + trickle
    want = _single_engine_tokens(params, cfg, tok, serve, reqs)
    router = FleetRouter(
        host_params, cfg, serve,
        FleetConfig(replicas=1, max_replicas=2, window_steps=2,
                    scale_up_occupancy=0.9, scale_down_occupancy=0.45),
        eos_id=int(tok.eos_token_id))
    got = _tokens(router.run(list(reqs), max_wall_s=600))
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid], err_msg=f"rid {rid}")
    s = router.last_summary
    assert s["scale_ups"] >= 1, s
    assert s["scale_downs"] >= 1, s
    assert s["replicas_peak"] == 2
    assert s["duplicate_completions"] == 0


# ---------------------------------------------------------------------------
# Telemetry: fleet JSONL + report render + the --min_fleet_tps gate.
# ---------------------------------------------------------------------------


def test_fleet_jsonl_and_report_gate(tok, cfg, host_params, tmp_path):
    import importlib

    from tpukit.obs import FlightRecorder, StepLogger

    report = importlib.import_module("tools.report")
    log = tmp_path / "fleet.jsonl"
    logger = StepLogger(str(log))
    recorder = FlightRecorder(capacity=64)
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=4)
    reqs = synthetic_request_stream(tok, 8, seed=8, max_new_tokens=8,
                                    buckets=(8, 16))
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=2,
                                     kill_spec="replica_kill@1:1"),
                         eos_id=int(tok.eos_token_id), logger=logger,
                         recorder=recorder)
    router.run(list(reqs), max_wall_s=300)
    logger.close()

    recs = [json.loads(l) for l in log.read_text().splitlines()]
    fleet_wins = [r for r in recs if r["kind"] == "fleet"]
    fleet_sums = [r for r in recs if r["kind"] == "fleet_summary"]
    events = [r for r in recs if r["kind"] == "fleet_event"]
    serve_wins = [r for r in recs if r["kind"] == "serve"]
    serve_sums = [r for r in recs if r["kind"] == "serve_summary"]
    assert fleet_wins and len(fleet_sums) == 1
    assert any(e["event"] == "replica_kill" for e in events)
    # replica-tagged serve telemetry: every window/summary names its engine
    assert serve_wins and all("replica" in r for r in serve_wins)
    assert serve_sums and all("replica" in r for r in serve_sums)
    s = fleet_sums[0]
    assert s["requests"] == 8 and s["tokens_per_sec"] > 0
    assert s["requeued"] >= 1 and s["duplicate_completions"] == 0
    assert s["p99_e2e_s"] >= s["p50_e2e_s"]
    # the flight recorder saw the fleet records too
    ring = [r for r in recorder.snapshot() if r["kind"] == "fleet_summary"]
    assert len(ring) == 1

    text = report.summarize(recs)
    assert "== fleet ==" in text
    assert "fleet tokens/s" in text and "re-queued" in text
    assert "per-replica occupancy" in text

    ok, msg = report.check_min_fleet_tps(recs, 1.0)
    assert ok, msg
    ok, msg = report.check_min_fleet_tps(recs, 1e9)
    assert not ok and "FAIL" in msg
    # no fleet records at all -> fail, never a vacuous pass
    ok, msg = report.check_min_fleet_tps(
        [r for r in recs if r["kind"] != "fleet_summary"], 1.0)
    assert not ok and "no fleet_summary" in msg
    # an exactly-once violation fails the gate even above threshold
    forged = [dict(s, duplicate_completions=1)]
    ok, msg = report.check_min_fleet_tps(forged, 1.0)
    assert not ok and "duplicate" in msg


# ---------------------------------------------------------------------------
# Validation: named construction errors, fleet-scoped chaos grammar.
# ---------------------------------------------------------------------------


def test_fleet_config_validation(tok, cfg, host_params):
    with pytest.raises(ValueError, match="replicas"):
        FleetConfig(replicas=0)
    with pytest.raises(ValueError, match="min_replicas"):
        FleetConfig(replicas=2, min_replicas=3)
    with pytest.raises(ValueError, match="max_replicas"):
        FleetConfig(replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="oscillate"):
        FleetConfig(scale_up_occupancy=0.5, scale_down_occupancy=0.5)
    with pytest.raises(ValueError, match="prefill worker"):
        FleetConfig(prefill_slots=4)
    with pytest.raises(chaos_lib.ChaosSpecError, match="replica_kill"):
        FleetConfig(kill_spec="nan_loss@5")
    with pytest.raises(chaos_lib.ChaosSpecError, match="integer replica id"):
        chaos_lib.parse_spec("replica_kill@5:-1")
    # the training harness rejects fleet-scoped faults by name
    with pytest.raises(chaos_lib.ChaosSpecError, match="fleet-scoped"):
        chaos_lib.ChaosEngine("replica_kill@5")
    serve_ring = ServeConfig(slots=2, buckets=(8,), max_new_tokens=4)
    with pytest.raises(ValueError, match="paged cache"):
        FleetRouter(host_params, cfg, serve_ring,
                    FleetConfig(replicas=2, disagg_prefill=True), eos_id=1)
    with pytest.raises(ValueError, match="needs 16 devices"):
        FleetRouter(host_params, cfg, serve_ring,
                    FleetConfig(replicas=2, devices_per_replica=8), eos_id=1)
    moe = cfg.replace(num_experts=2, moe_dispatch="pallas")
    with pytest.raises(ValueError, match="meshless"):
        FleetRouter(host_params, moe, serve_ring,
                    FleetConfig(replicas=2, devices_per_replica=2), eos_id=1)


def test_fleet_decode_plan_is_standalone_plan():
    """The router adds ZERO collectives: the per-replica plan is the
    standalone decode closed form, byte for byte, on a subset mesh."""
    from tpukit.analysis import decode_comm_plan, fleet_decode_comm_plan
    from tpukit.mesh import create_mesh

    cfg = GPTConfig(dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=160,
                    max_position_embeddings=64, compute_dtype=jnp.float32)
    mesh = create_mesh({"data": 1, "model": 4},
                       devices=jax.devices()[4:8])
    base = decode_comm_plan(cfg, mesh, 4)
    fleet = fleet_decode_comm_plan(cfg, mesh, 4)
    assert fleet.ops == base.ops and fleet.exhaustive
    assert fleet.label.startswith("fleet replica")
