"""Bucketed, overlap-scheduled gradient collectives (`--grad_buckets`,
round 18 — ROADMAP #5).

Four proof obligations, mirroring the quant_comm bucket scheduler's
contract:

  1. the partition itself: layer-reversed (backward-completion) order,
     ~equal bytes, every leaf exactly once, the FSDP include-filter
     (replicated sub-threshold leaves never enter a bucket);
  2. f32 BIT parity: bucketing is a pure repartition of independent
     fixed-order reductions, so the loss trajectory at grad_buckets=4 is
     bit-identical to the serial one-bucket schedule (DDP and FSDP) —
     and the serial hand-placed schedule itself tracks the GSPMD f32
     path within the dense tolerance;
  3. int8+overlap within the round-12 loss-trajectory tolerance of f32
     (the wire cut and the overlap win stack without new numerics);
  4. the HLO audit: per-BUCKET closed-form bytes exact, op counts exact
     (B a2as + B gathers for DDP, B backward a2as for FSDP with forward
     param gathers unchanged), zero involuntary-remat warnings, and the
     promoted hlolint `overlap` gate clean — every declared bucket wire
     independently schedulable.

Plus the validation matrix: strategies without a hand-placed grad wire
reject --grad_buckets at startup.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, init_params
from tpukit.obs.xla import capture_compiler_stderr, collective_bytes
from tpukit.ops import quant_comm as qc
from tpukit.shardings import DataParallel, ExpertParallel, FSDP
from tpukit.train import create_train_state, make_optimizer, make_step_fns

BATCH = 16
SEQ = 32
STEPS = 6
FINAL_LOSS_TOL = 2e-2  # the round-12 quantized-trajectory gate
DENSE_TOL = 5e-4  # hand-placed f32 block vs GSPMD (reduction order only)


def _base_cfg(**kw):
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=211,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
        **kw,
    )


def _batch():
    rng = np.random.RandomState(11)
    ids = rng.randint(3, 211, size=(BATCH, SEQ)).astype(np.int32)
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": np.zeros((BATCH, SEQ), dtype=bool),
    }
    return model_batch, np.roll(ids, -1, axis=1).astype(np.int32)


def _make_world(kind: str, comm_dtype: str, buckets: int):
    cfg = _base_cfg(comm_dtype=comm_dtype, grad_buckets=buckets)
    if kind == "ddp":
        return DataParallel(create_mesh({"data": 8})), cfg
    return FSDP(create_mesh({"data": 8})), cfg


# One compiled world per (strategy, comm_dtype, buckets), shared by the
# parity gates AND the HLO audits — the 8-device compiles dominate.
_WORLDS: dict = {}


def _world(kind: str, comm_dtype: str, buckets: int) -> dict:
    key = (kind, comm_dtype, buckets)
    if key in _WORLDS:
        return _WORLDS[key]
    strategy, cfg = _make_world(kind, comm_dtype, buckets)
    strategy.validate_config(cfg)
    model_batch, targets = _batch()
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    struct = lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)  # noqa: E731
    with capture_compiler_stderr() as cap:
        train_step, _, sharding = make_step_fns(cfg, opt, strategy, shapes)
        compiled = train_step.lower(
            shapes, jax.tree.map(struct, model_batch), struct(targets)
        ).compile()
    state = jax.device_put(state, sharding)
    losses = []
    for _ in range(STEPS):
        state, loss = compiled(state, model_batch, targets)
        losses.append(float(loss))
    del state
    _WORLDS[key] = {
        "strategy": strategy,
        "cfg": cfg,
        "shapes": shapes,
        "losses": losses,
        "coll": collective_bytes(compiled.as_text()),
        "text": compiled.as_text(),
        "warns": cap["involuntary_remat"],
    }
    return _WORLDS[key]


# -- 1. the partition -------------------------------------------------------


def _param_tree():
    return init_params(jax.random.PRNGKey(0), _base_cfg())


def test_bucket_plan_layer_reversed_order():
    """Buckets are contiguous runs of backward-completion order: head and
    norm_out leaves land in the FIRST bucket, embeddings in the LAST (the
    real tree's layer leaves are STACKED along a leading num_layers axis,
    so within `layers` the completion granularity is the leaf — see
    DESIGN.md §17); on a list-structured tree a deeper (higher-index)
    layer's leaves always precede a shallower layer's."""
    params = _param_tree()
    paths = jax.tree_util.tree_flatten_with_path(params)[0]

    def top_name(i):
        return next(
            k.key for k in paths[i][0]
            if isinstance(k, jax.tree_util.DictKey)
        )

    buckets = qc.grad_bucket_plan(params, 3)
    assert len(buckets) == 3
    first = {top_name(i) for i in buckets[0]}
    assert "lm_head" in first or "norm_out" in first
    assert "embeddings" in {top_name(i) for i in buckets[-1]}

    # the SequenceKey (per-layer list) spelling: reversed layer order
    listed = {
        "embeddings": np.zeros((8, 4), np.float32),
        "layers": [
            {"w": np.zeros((4, 4), np.float32)} for _ in range(3)
        ],
        "lm_head": np.zeros((4, 8), np.float32),
    }
    lpaths = jax.tree_util.tree_flatten_with_path(listed)[0]
    order = [i for b in qc.grad_bucket_plan(listed, 100) for i in b]
    layer_seq = [
        next(k.idx for k in lpaths[i][0]
             if isinstance(k, jax.tree_util.SequenceKey))
        for i in order
        if any(getattr(k, "key", None) == "layers" for k in lpaths[i][0])
    ]
    assert layer_seq == sorted(layer_seq, reverse=True)
    assert any(getattr(k, "key", None) == "lm_head"
               for k in lpaths[order[0]][0])
    assert any(getattr(k, "key", None) == "embeddings"
               for k in lpaths[order[-1]][0])


def test_bucket_plan_equal_bytes_and_exhaustive():
    """Every leaf appears exactly once; bucket byte totals are balanced
    (no bucket above 2x the ideal share once its largest leaf fits)."""
    params = _param_tree()
    leaves = jax.tree_util.tree_leaves(params)
    sizes = [leaf.size for leaf in leaves]
    for n_buckets in (1, 2, 4, 100):
        buckets = qc.grad_bucket_plan(params, n_buckets)
        flat = [i for b in buckets for i in b]
        assert sorted(flat) == list(range(len(leaves)))
        assert len(buckets) == min(n_buckets, len(leaves))
        assert all(b for b in buckets)  # never an empty bucket
        if n_buckets in (2, 4):
            total = sum(sizes)
            biggest_leaf = max(sizes)
            for b in buckets:
                share = sum(sizes[i] for i in b)
                assert share <= max(2 * total / n_buckets, biggest_leaf + 1)


def test_bucket_plan_include_filter():
    """The FSDP restriction: only the included (sharded) indices are
    partitioned — replicated sub-threshold leaves stay outside every
    bucket (they ride the f32 psum path)."""
    params = _param_tree()
    leaves = jax.tree_util.tree_leaves(params)
    include = {i for i, leaf in enumerate(leaves) if leaf.size >= 100}
    assert include and len(include) < len(leaves)
    buckets = qc.grad_bucket_plan(params, 4, include=include)
    flat = [i for b in buckets for i in b]
    assert sorted(flat) == sorted(include)
    assert qc.grad_bucket_plan(params, 4, include=set()) == []
    with pytest.raises(ValueError, match="n_buckets"):
        qc.grad_bucket_plan(params, 0)


def test_bucket_all_reduce_partition_invariant():
    """The two-shot f32 bucket reduction is a fixed-device-order
    elementwise sum: splitting one payload into two buckets yields
    BIT-identical results (the parity bar's mechanism, unit-scale)."""
    from jax.sharding import PartitionSpec as P

    from tpukit.compat import shard_map

    mesh = create_mesh({"data": 8})
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 1000).astype(np.float32))

    def blk(v):
        whole = qc.bucket_all_reduce(v, "data", 8, "f32")
        left = qc.bucket_all_reduce(v[:, :300], "data", 8, "f32")
        right = qc.bucket_all_reduce(v[:, 300:], "data", 8, "f32")
        exact = jax.lax.psum(v, "data")
        return whole, jnp.concatenate([left, right], axis=1), exact

    whole, split, exact = shard_map(
        blk, mesh=mesh, in_specs=(P("data", None),),
        out_specs=(P(), P(), P()), check_vma=False,
    )(x)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(split))
    # f32 two-shot vs psum: same values within reduction-order ulps
    np.testing.assert_allclose(
        np.asarray(whole), np.asarray(exact), rtol=1e-6, atol=1e-5
    )


# -- 2/3. trajectory parity gates -------------------------------------------


@pytest.mark.parametrize("kind", ["ddp", "fsdp"])
def test_f32_bucketed_bit_parity(kind):
    """THE f32 contract: grad_buckets=4 vs the serial one-bucket schedule
    is BIT-identical — bucketing only repartitions independent fixed-
    order reductions. (grad_buckets=1 IS the serial schedule expressed in
    the bucket machinery: one payload, one two-shot pair.)"""
    serial = _world(kind, "f32", 1)
    bucketed = _world(kind, "f32", 4)
    assert bucketed["losses"] == serial["losses"], (
        bucketed["losses"], serial["losses"],
    )


@pytest.mark.parametrize("kind", ["ddp", "fsdp"])
def test_f32_bucketed_tracks_gspmd(kind):
    """The hand-placed f32 bucket block vs the default GSPMD path
    (grad_buckets=0): same math, different reduction structure — dense
    tolerance, not bit parity (local-mean-then-psum vs global mean)."""
    gspmd = _world(kind, "f32", 0)
    bucketed = _world(kind, "f32", 4)
    drift = max(
        abs(a - b) for a, b in zip(bucketed["losses"], gspmd["losses"])
    )
    assert drift <= DENSE_TOL, (bucketed["losses"], gspmd["losses"])


@pytest.mark.parametrize("kind", ["ddp", "fsdp"])
def test_int8_bucketed_trajectory_gate(kind):
    """int8 + overlap stays inside the round-12 tolerance gate vs f32:
    the bucket schedule adds reordering, never new quantization error
    classes (per-bucket block boundaries shift, the error bound per
    block does not)."""
    ref = _world(kind, "f32", 1)
    quant = _world(kind, "int8", 4)
    assert all(np.isfinite(quant["losses"]))
    assert abs(quant["losses"][-1] - ref["losses"][-1]) < FINAL_LOSS_TOL, (
        quant["losses"], ref["losses"],
    )
    assert quant["losses"][-1] < quant["losses"][0]  # still trains


# -- 4. HLO audits ----------------------------------------------------------


@pytest.mark.parametrize("kind,comm", [
    ("ddp", "f32"), ("ddp", "int8"), ("fsdp", "int8"),
])
def test_bucketed_hlo_audit(kind, comm):
    """The compiled bucketed step moves EXACTLY the per-bucket closed
    form: B a2as + B gathers for DDP (B a2as + unchanged per-leaf f32
    param gathers for FSDP), zero involuntary-remat warnings, and the
    promoted overlap gate clean with every declared wire hidden."""
    from tpukit.analysis import (
        lint_module, parse_hlo, summarize, train_comm_plan,
    )

    w = _world(kind, comm, 4)
    assert w["warns"] == 0
    expected = w["strategy"].grad_comm(
        w["cfg"], w["shapes"].params, backend=jax.default_backend()
    )
    assert expected["all-to-all"]["count"] == 4
    if kind == "ddp":
        assert expected["all-gather"]["count"] == 4
    for op, rec in expected.items():
        got = w["coll"].get(op)
        assert got == rec, (op, got, rec)
    plan = train_comm_plan(
        w["strategy"], w["cfg"], param_shapes=w["shapes"].params,
        global_batch=BATCH, seq=SEQ, backend=jax.default_backend(),
    )
    assert plan.overlap is not None
    findings = lint_module(parse_hlo(w["text"]), plan=plan,
                           backend=jax.default_backend())
    assert [f for f in findings if f.severity == "error"] == []
    s = summarize(findings)
    gate = s["overlap_gate"]
    assert gate["ok"] and gate["overlappable"] >= gate["declared"]


def test_fsdp_replicated_leaves_stay_f32_psum():
    """Sub-threshold replicated leaves never enter a bucket: the bucket
    plan covers exactly the sharded subset, and their grads ride the
    full-precision psum (visible as the per-replicated-leaf all-reduces
    the serial path has always emitted)."""
    w = _world("fsdp", "int8", 4)
    strategy, shapes = w["strategy"], w["shapes"]
    leaves = jax.tree_util.tree_leaves(shapes.params)
    sharded = {
        i for i, leaf in enumerate(leaves)
        if any(ax == "data" for ax in strategy.param_spec(leaf.shape))
    }
    buckets = qc.grad_bucket_plan(shapes.params, 4, include=sharded)
    assert sorted(i for b in buckets for i in b) == sorted(sharded)
    n_replicated = len(leaves) - len(sharded)
    assert n_replicated > 0
    # each replicated PARAM leaf grad psums in f32; the compiled step's
    # all-reduce count must cover at least those (plus loss/count scalars)
    assert w["coll"]["all-reduce"]["count"] >= n_replicated


def test_serial_default_unchanged():
    """grad_buckets=0 (the default) leaves the serial schedules exactly
    as round 17 shipped them: int8 = ONE flattened two-shot pair."""
    w = _world("ddp", "int8", 0)
    assert w["coll"]["all-to-all"]["count"] == 1
    assert w["coll"]["all-gather"]["count"] == 1
    expected = w["strategy"].grad_comm(
        w["cfg"], w["shapes"].params, backend=jax.default_backend()
    )
    for op, rec in expected.items():
        assert w["coll"].get(op) == rec, op
    # and no overlap declaration exists to gate
    assert w["strategy"].overlap_comm(w["cfg"], w["shapes"].params) is None


# -- validation matrix + flags ----------------------------------------------


def test_grad_buckets_validation_matrix():
    """--grad_buckets is rejected everywhere there is no hand-placed grad
    wire to bucket: negative at config construction; single/CP/TP/
    pipeline strategies; MoE under DDP/FSDP (no aux psum in the manual
    block); EP's xla dispatch. The wired combinations validate."""
    from tpukit.pipeline import Pipeline
    from tpukit.shardings import ContextParallel, SingleDevice, TensorParallel

    with pytest.raises(ValueError, match="grad_buckets"):
        GPTConfig(grad_buckets=-1)
    cfg = _base_cfg(grad_buckets=4)
    for strategy in (
        SingleDevice(),
        ContextParallel(create_mesh({"seq": 8})),
        TensorParallel(create_mesh({"model": 4})),
        Pipeline(create_mesh({"stage": 4})),
    ):
        with pytest.raises(ValueError, match="grad_buckets"):
            strategy.validate_config(cfg)
    moe_buckets = _base_cfg(grad_buckets=4, num_experts=4)
    with pytest.raises(ValueError, match="ExpertParallel"):
        DataParallel(create_mesh({"data": 8})).validate_config(moe_buckets)
    with pytest.raises(ValueError, match="ExpertParallel"):
        FSDP(create_mesh({"data": 8})).validate_config(moe_buckets)
    with pytest.raises(ValueError, match="grad_buckets"):
        ExpertParallel(
            create_mesh({"data": 2, "expert": 4}), dispatch="xla"
        ).validate_config(moe_buckets)
    # the wired combinations pass, f32 and int8 alike
    DataParallel(create_mesh({"data": 8})).validate_config(cfg)
    FSDP(create_mesh({"data": 8})).validate_config(
        _base_cfg(grad_buckets=4, comm_dtype="int8")
    )
    ExpertParallel(create_mesh({"data": 2, "expert": 4})).validate_config(
        moe_buckets
    )


def test_ep_overlap_declaration():
    """EP + grad_buckets declares the per-layer overlap audit (2L
    backward a2a hops) without changing the dataflow; without buckets
    (or on a 1-way expert axis) nothing is declared."""
    ep = ExpertParallel(create_mesh({"data": 2, "expert": 4}))
    cfg = _base_cfg(num_experts=4, grad_buckets=4)
    assert ep.overlap_comm(cfg, None) == {"all-to-all": 2 * cfg.num_layers}
    assert ep.overlap_comm(_base_cfg(num_experts=4), None) is None


def test_fit_xla_verdict_carries_overlap_gate(tmp_path):
    """The promoted gate rides fit()'s kind="xla" verdict: a --grad_buckets
    int8 DDP run's train_step record carries hlolint.overlap_gate with
    every declared bucket wire hidden (and stays clean) — the production
    enforcement surface next to the dryrun and the CI lane."""
    import json
    import os

    from tpukit.flags import TrainFlags
    from tpukit.train import fit

    log = tmp_path / "run.jsonl"
    flags = TrainFlags(
        batch_size=2, epochs=1, sequence_length=33, dim=32, head_dim=8,
        heads=4, num_layers=2, learning_rate=1e-3, dataset_slice="32",
        num_workers=0, disable_amp=True, seed=0, metrics_log=str(log),
        comm_dtype="int8", grad_buckets=4,
    )
    cwd = os.getcwd()
    os.chdir(tmp_path)  # checkpoints/ lands in tmp
    try:
        fit(flags, DataParallel(create_mesh({"data": 8})))
    finally:
        os.chdir(cwd)
    records = [json.loads(line) for line in log.read_text().splitlines()]
    train_rec = next(
        r for r in records if r["kind"] == "xla" and r["fn"] == "train_step"
    )
    verdict = train_rec["hlolint"]
    assert verdict["clean"] is True, verdict
    gate = verdict["overlap_gate"]
    assert gate["ok"] is True
    assert gate["overlappable"] >= gate["declared"] == 8  # 4 a2a + 4 ag
    # the eval step has no grad wire: no overlap gate to declare
    eval_rec = next(
        r for r in records if r["kind"] == "xla" and r["fn"] == "eval_step"
    )
    assert "overlap_gate" not in (eval_rec.get("hlolint") or {})


def test_report_overlap_record_and_gate(tmp_path):
    """tools/report.py renders the comm_overlap bench record and the
    --min_overlap_frac gate exits 2 below threshold — or when the log
    has no bucketed rung at all (no vacuous pass)."""
    import json

    from tools.report import check_min_overlap_frac, main as report_main

    rec = {"comm_overlap": [
        {"strategy": "ddp", "comm_dtype": "f32", "grad_buckets": 0,
         "step_time_s": 0.01, "tokens_per_sec_per_chip": 1000.0,
         "bytes_match": None, "overlap": None,
         "involuntary_remat_warnings": 0, "final_loss": 5.0},
        {"strategy": "ddp", "comm_dtype": "int8", "grad_buckets": 4,
         "step_time_s": 0.009, "tokens_per_sec_per_chip": 1100.0,
         "bytes_match": True,
         "overlap": {"declared": 8, "overlappable": 8,
                     "overlap_frac": 1.0, "gate_ok": True, "clean": True},
         "involuntary_remat_warnings": 0, "final_loss": 5.0,
         "loss_delta_vs_f32": 1e-6, "step_time_vs_f32": 0.9},
    ]}
    log = tmp_path / "bench.jsonl"
    log.write_text(json.dumps(rec) + "\n")
    assert report_main([str(log), "--min_overlap_frac", "0.9"]) == 0
    assert report_main([str(log), "--min_overlap_frac", "1.01"]) == 2
    ok, msg = check_min_overlap_frac([rec], 0.9)
    assert ok and "1.000" in msg
    # a log with no bucketed rung fails the gate rather than passing
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"metric": "x"}) + "\n")
    assert report_main([str(empty), "--min_overlap_frac", "0.5"]) == 2
    # an ERRORED bucketed rung fails the gate even if the others pass —
    # a crashed strategy must not vanish from the verdict
    with_err = dict(rec)
    with_err["comm_overlap"] = rec["comm_overlap"] + [
        {"strategy": "fsdp", "comm_dtype": "int8", "grad_buckets": 4,
         "error": "RuntimeError('boom')"},
    ]
    ok, msg = check_min_overlap_frac([with_err], 0.5)
    assert not ok and "fsdp/b4" in msg
    # and a rung whose own hlolint gate failed is a failure regardless of
    # the summed fraction
    with_gate_fail = json.loads(json.dumps(rec))
    with_gate_fail["comm_overlap"][1]["overlap"]["gate_ok"] = False
    ok, msg = check_min_overlap_frac([with_gate_fail], 0.5)
    assert not ok and "gate FAIL" in msg
    # and the renderer names the gate verdict in the summary text
    from tools.report import summarize as render

    text = render([rec])
    assert "overlap-scheduled collectives" in text
    assert "8/8 wires hidden OK" in text


def test_grad_buckets_flag_plumbing():
    """--grad_buckets parses on every recipe, defaults to the unchanged
    serial path, and reaches GPTConfig through TrainFlags."""
    from tpukit.flags import TrainFlags, parse_flags

    assert TrainFlags().grad_buckets == 0
    assert parse_flags([]).grad_buckets == 0
    flags = parse_flags(["--grad_buckets", "4", "--comm_dtype", "int8"])
    assert flags.grad_buckets == 4 and flags.comm_dtype == "int8"
    flags = parse_flags(["--grad_buckets", "2"], num_experts=True)
    assert flags.grad_buckets == 2
