"""Round-8 failure-observability tests: flight recorder, hang watchdog +
diagnostics bundles, trace-on-anomaly, and cross-replica divergence
detection (tpukit/obs/{recorder,watchdog,divergence}.py; docs/DESIGN.md §8).

The acceptance bar from the issue: a hung step must produce a bundle on
disk (with all-thread stacks, ring records, heartbeat snapshot) within
--hang_timeout and tools/flightview.py must render it; the divergence
checksum must be bit-stable across identical replicas, flip on a single
perturbed element, leave the train step's HLO byte-identical when off,
and the recorder ring must bound memory.
"""

import json
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.obs import (
    AnomalyTracer,
    FlightRecorder,
    HangWatchdog,
    Heartbeat,
    format_checksum,
    make_state_checksum,
)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_recorder_ring_bounds_memory():
    """The ring evicts oldest records at capacity — a long run holds
    exactly `capacity` records, whatever was recorded."""
    rec = FlightRecorder(capacity=16)
    for i in range(100):
        rec.record("step", step=i)
    assert len(rec) == 16
    assert rec.total_recorded == 100
    snap = rec.snapshot()
    assert [r["step"] for r in snap] == list(range(84, 100))  # newest 16
    assert all(r["kind"] == "step" and "t" in r for r in snap)
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=0)


def test_recorder_snapshot_safe_under_concurrent_records():
    """snapshot() (the watchdog thread) must never see a torn ring while
    the training thread keeps appending — deque iteration during append
    raises without the lock."""
    rec = FlightRecorder(capacity=64)
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            rec.record("step", step=i)
            i += 1

    def reader():
        try:
            for _ in range(200):
                snap = rec.snapshot()
                # records are well-formed and in order
                steps = [r["step"] for r in snap]
                assert steps == sorted(steps)
        except Exception as exc:  # pragma: no cover - the failure path
            errors.append(exc)

    t = threading.Thread(target=writer)
    t.start()
    try:
        reader()
    finally:
        stop.set()
        t.join()
    assert not errors


# ---------------------------------------------------------------------------
# hang watchdog + bundles
# ---------------------------------------------------------------------------


def _wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_watchdog_fires_on_hung_step_and_bundle_is_complete(tmp_path):
    """Armed + overrun -> a bundle lands within ~the timeout, holding
    all-thread stacks, the ring, the heartbeat snapshot, probes, and the
    config; tools/flightview.py renders it without error."""
    rec = FlightRecorder()
    for i in range(5):
        rec.record("step", step=i)
    hb = Heartbeat(tmp_path / "hb", process_index=0, process_count=1)
    hb.beat(4, checksum="aa:bb", checksum_step=4)
    wd = HangWatchdog(
        tmp_path / "dbg", timeout_s=0.3, recorder=rec, heartbeat=hb,
        probes={
            "ok": lambda: {"buffered": 2},
            "broken": lambda: (_ for _ in ()).throw(RuntimeError("probe boom")),
        },
        config={"hang_timeout": 0.3, "debug_dir": str(tmp_path / "dbg")},
    )
    try:
        t0 = time.monotonic()
        wd.arm(5)
        assert _wait_for(lambda: wd.dumps, timeout=5.0)
        elapsed = time.monotonic() - t0
        # fires within the timeout plus one poll tick (not, say, 10x late)
        assert elapsed < 0.3 * 3 + 1.0
        assert wd.hang_count == 1
    finally:
        wd.close()

    bundle = json.loads(wd.dumps[0].read_text())
    assert bundle["reason"] == "hang" and bundle["step"] == 5
    assert bundle["stuck_for_s"] >= 0.3
    # all-thread stacks: this (main) thread + the watchdog's own monitor
    names = list(bundle["stacks"])
    assert any(n.startswith("MainThread") for n in names)
    assert any("tpukit-watchdog" in n for n in names)
    assert all(isinstance(f, list) and f for f in bundle["stacks"].values())
    # ring contents rode along
    assert [r["step"] for r in bundle["ring"]] == list(range(5))
    assert bundle["ring_total_recorded"] == 5
    # heartbeat snapshot with the divergence checksum fields
    assert bundle["heartbeats"]["0"]["step"] == 4
    assert bundle["heartbeats"]["0"]["checksum"] == "aa:bb"
    # probes: values captured, errors stringified (never aborting the dump)
    assert bundle["inflight"]["ok"] == {"buffered": 2}
    assert "probe boom" in bundle["inflight"]["broken"]
    assert bundle["config"]["hang_timeout"] == 0.3

    # the renderer consumes it end to end
    from tools import flightview

    assert flightview.main([str(wd.dumps[0])]) == 0
    text = flightview.render(bundle)
    for needle in ("hang", "MainThread", "flight recorder", "heartbeats"):
        assert needle in text
    # directory mode resolves to the newest bundle
    assert flightview.main([str(tmp_path / "dbg")]) == 0


def test_watchdog_disarm_and_rearm_protocol(tmp_path):
    """disarm() before the deadline prevents the dump; every arm() resets
    the clock, so a loop of healthy steps re-arming never fires."""
    wd = HangWatchdog(tmp_path / "dbg", timeout_s=0.25)
    try:
        wd.arm(1)
        wd.disarm()
        time.sleep(0.5)
        assert not wd.dumps
        # healthy cadence: re-arm faster than the timeout
        for step in range(8):
            wd.arm(step)
            time.sleep(0.05)
        wd.disarm()
        assert not wd.dumps and wd.hang_count == 0
    finally:
        wd.close()


def test_watchdog_trigger_and_dump_budget(tmp_path):
    """trigger() dumps synchronously (the sentinel path); the shared
    max_dumps budget bounds a flapping sentinel."""
    rec = FlightRecorder()
    wd = HangWatchdog(tmp_path / "dbg", timeout_s=0.0, recorder=rec, max_dumps=2)
    try:
        p1 = wd.trigger("spike", step=10, loss=9.5)
        p2 = wd.trigger("divergence", step=11)
        p3 = wd.trigger("spike", step=12)
        assert p1 is not None and p2 is not None
        assert p3 is None  # budget spent
        assert len(wd.dumps) == 2
        b = json.loads(p1.read_text())
        assert b["reason"] == "spike" and b["loss"] == 9.5
        # timeout 0: no monitor thread was started
        assert wd._thread is None
    finally:
        wd.close()
    with pytest.raises(ValueError, match="timeout"):
        HangWatchdog(tmp_path / "dbg2", timeout_s=-1)


# ---------------------------------------------------------------------------
# trace-on-anomaly
# ---------------------------------------------------------------------------


def test_anomaly_tracer_arms_exactly_once(tmp_path):
    tr = AnomalyTracer(tmp_path / "tr", steps=2)
    assert not tr.maybe_start()  # not armed yet: no-op
    assert tr.trigger("spike") is True
    assert tr.trigger("nan") is False  # second anomaly: already armed
    assert tr.reason == "spike"
    assert tr.maybe_start() is True
    assert tr.tracing
    assert tr.maybe_start() is False  # already tracing
    assert tr.step() is False  # 1 of 2
    assert tr.step() is True  # 2 of 2 -> stopped
    assert tr.done and not tr.tracing
    # a one-shot: nothing re-arms it
    assert tr.trigger("spike") is False
    assert not tr.maybe_start()
    # the capture actually wrote profiler artifacts
    assert any((tmp_path / "tr").rglob("*"))
    with pytest.raises(ValueError, match="step count"):
        AnomalyTracer(tmp_path, steps=0)


# ---------------------------------------------------------------------------
# divergence checksums
# ---------------------------------------------------------------------------


def _tiny_state(tiny_config, seed=0):
    from tpukit.train import create_train_state, make_optimizer

    return create_train_state(
        jax.random.PRNGKey(seed), tiny_config, make_optimizer(1e-3)
    )


def test_checksum_bit_stable_across_identical_replicas(tiny_config):
    """Two replicas built the same way (the DP contract: replicated state)
    must produce the SAME checksum — and recomputing it must too."""
    fn = make_state_checksum()
    a = format_checksum(fn(_tiny_state(tiny_config)))
    b = format_checksum(fn(_tiny_state(tiny_config)))
    assert a == b
    assert format_checksum(fn(_tiny_state(tiny_config))) == a  # idempotent
    # and it actually depends on the values, not just the structure
    c = format_checksum(fn(_tiny_state(tiny_config, seed=1)))
    assert c != a


def test_checksum_fires_on_single_element_perturbation(tiny_config):
    """One element nudged anywhere — params or opt state, even by 1 ulp —
    flips the corresponding checksum half (XOR of bit patterns: no
    float-sum cancellation)."""
    fn = make_state_checksum()
    state = _tiny_state(tiny_config)
    base = fn(state)

    k = state.params["layers"]["attn"]["q"]["kernel"]
    new_layers = jax.tree_util.tree_map(lambda x: x, state.params["layers"])
    new_layers["attn"]["q"]["kernel"] = k.at[0, 1, 2].set(
        jnp.nextafter(k[0, 1, 2], jnp.float32(1e9))
    )
    perturbed = state.replace(params={**state.params, "layers": new_layers})
    got = fn(perturbed)
    assert int(got["params"]) != int(base["params"])
    assert int(got["opt_state"]) == int(base["opt_state"])  # untouched half

    mu = state.opt_state[0].mu
    new_mu = jax.tree_util.tree_map(lambda x: x, mu)
    new_mu["lm_head"]["kernel"] = new_mu["lm_head"]["kernel"].at[3, 4].add(1e-6)
    new_inner = state.opt_state[0]._replace(mu=new_mu)
    got2 = fn(state.replace(opt_state=(new_inner,) + tuple(state.opt_state[1:])))
    assert int(got2["opt_state"]) != int(base["opt_state"])
    assert int(got2["params"]) == int(base["params"])


def test_divergence_check_leaves_train_step_hlo_byte_identical(tiny_config):
    """The --log_grad_norms discipline, re-verified for the checksum: it is
    a separate jitted program, so compiling the train step before vs after
    building+running the checksum yields byte-identical optimized HLO (and
    the same output arity)."""
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), tiny_config, opt)
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((4, 16), np.int32),
        "position_ids": jax.ShapeDtypeStruct((4, 16), np.int32),
        "mask": jax.ShapeDtypeStruct((4, 16), np.bool_),
    }
    targets = jax.ShapeDtypeStruct((4, 16), np.int32)

    step_off, _, _ = make_step_fns(tiny_config, opt, SingleDevice(), shapes)
    hlo_off = step_off.lower(shapes, batch, targets).compile().as_text()

    # build AND run the checksum program (divergence "on"), then compile
    # the train step again: byte-identical
    fn = make_state_checksum()
    format_checksum(fn(_tiny_state(tiny_config)))
    step_on, _, _ = make_step_fns(tiny_config, opt, SingleDevice(), shapes)
    hlo_on = step_on.lower(shapes, batch, targets).compile().as_text()
    assert hlo_on == hlo_off
    out_off = jax.eval_shape(step_off, shapes, batch, targets)
    assert len(out_off) == 2  # arity untouched — no smuggled outputs


def test_heartbeat_divergence_detection_across_replicas(tmp_path, tiny_config):
    """The cross-replica wire: each process publishes its checksum through
    its beat file; process 0 names the minority at any step where the
    checksums disagree — and skewed steps are never compared."""
    fn = make_state_checksum()
    healthy = format_checksum(fn(_tiny_state(tiny_config)))
    state = _tiny_state(tiny_config)
    new_layers = jax.tree_util.tree_map(lambda x: x, state.params["layers"])
    new_layers["norm1"]["scale"] = new_layers["norm1"]["scale"].at[0, 0].add(1e-3)
    diverged = format_checksum(
        fn(state.replace(params={**state.params, "layers": new_layers}))
    )
    assert diverged != healthy

    hbs = [
        Heartbeat(tmp_path, process_index=i, process_count=3, timeout_s=60)
        for i in range(3)
    ]
    # all agree at step 8: quiet
    for hb in hbs:
        hb.beat(8, checksum=healthy, checksum_step=8)
    assert hbs[0].check_divergence() == []
    # replica 2 diverges at step 16
    hbs[0].beat(16, checksum=healthy, checksum_step=16)
    hbs[1].beat(16, checksum=healthy, checksum_step=16)
    hbs[2].beat(16, checksum=diverged, checksum_step=16)
    got = hbs[0].check_divergence()
    assert got == [{
        "process": 2, "checksum_step": 16,
        "checksum": diverged, "expected": healthy,
    }]
    # skew: replica 2 still reporting step 16 while others moved to 24 —
    # different steps are not comparable, so no (false) mismatch either way
    hbs[0].beat(24, checksum=healthy, checksum_step=24)
    hbs[1].beat(24, checksum=healthy, checksum_step=24)
    got = hbs[0].check_divergence()
    assert got == []


# ---------------------------------------------------------------------------
# fit() end to end: hung step -> watchdog -> bundle -> flightview; and the
# injected-divergence path through the heartbeat files
# ---------------------------------------------------------------------------


class _Loader:
    """Minimal make_loaders-contract loader over fixed raw batches, with an
    optional hang: iteration `hang_at` blocks until a hang bundle appears
    in `debug_dir` (i.e. until the watchdog has demonstrably fired), then
    the remaining batches stream normally so fit() finishes its epoch."""

    def __init__(self, batches, hang_at=None, debug_dir=None, timeout_s=60.0):
        self.batches = batches
        self.hang_at = hang_at
        self.debug_dir = Path(debug_dir) if debug_dir else None
        self.timeout_s = timeout_s
        self.hung_for: float | None = None

    def set_epoch(self, epoch):
        pass

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        for i, b in enumerate(self.batches):
            if i == self.hang_at:
                t0 = time.monotonic()
                deadline = t0 + self.timeout_s
                while time.monotonic() < deadline and not list(
                    self.debug_dir.glob("bundle-*-hang-*.json")
                ):
                    time.sleep(0.05)
                self.hung_for = time.monotonic() - t0
            yield b


def _raw_batches(n, batch, seq, vocab, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = rng.randint(3, vocab, size=(batch, seq)).astype(np.int32)
        out.append(
            {"input_ids": ids, "attention_mask": np.ones_like(ids)}
        )
    return out


def _obs_flags(tmp, **kw):
    from tpukit.flags import TrainFlags

    defaults = dict(
        batch_size=8, epochs=1, sequence_length=33, dim=32, head_dim=8,
        heads=4, num_layers=2, learning_rate=1e-3, dataset_slice="64",
        num_workers=0, disable_amp=True, seed=0, prefetch=0,
        metrics_log=str(tmp / "run.jsonl"),
        heartbeat_dir=str(tmp / "hb"), debug_dir=str(tmp / "dbg"),
    )
    defaults.update(kw)
    return TrainFlags(**defaults)


@pytest.fixture(scope="module")
def hang_run(tmp_path_factory):
    """One fit() whose 3rd training iteration hangs until the watchdog
    fires, then recovers and finishes — exercising hang detection, bundle
    dump, hang-surfacing in the JSONL, and trace-on-anomaly (the hang
    recovery is the first anomaly) in a single run."""
    import os

    from tpukit.train import fit
    from tpukit.shardings import SingleDevice

    tmp = tmp_path_factory.mktemp("hang")
    flags = _obs_flags(tmp, hang_timeout=1.0, trace_on_anomaly=2)
    loaders = {}

    def make_loaders(fl, tokenizer, strategy):
        train = _Loader(
            _raw_batches(12, fl.batch_size, fl.sequence_length, tokenizer.vocab_size),
            hang_at=2, debug_dir=flags.debug_dir,
        )
        val = _Loader(
            _raw_batches(2, fl.batch_size, fl.sequence_length, tokenizer.vocab_size, seed=1)
        )
        loaders["train"] = train
        return train, val

    cwd = os.getcwd()
    os.chdir(tmp)  # checkpoints/ lands in tmp
    try:
        result = fit(flags, SingleDevice(), make_loaders=make_loaders)
    finally:
        os.chdir(cwd)
    records = [
        json.loads(line)
        for line in (tmp / "run.jsonl").read_text().splitlines()
    ]
    return flags, result, records, tmp, loaders["train"]


def test_fit_hung_step_dumps_bundle_within_timeout(hang_run):
    flags, _, _, tmp, train_loader = hang_run
    bundles = sorted((tmp / "dbg").glob("bundle-*-hang-*.json"))
    assert bundles, "watchdog never fired on the hung step"
    # the loader unblocked BECAUSE the bundle appeared — i.e. the watchdog
    # fired while the step was actually hung, within timeout + poll slack
    assert train_loader.hung_for is not None
    assert train_loader.hung_for < flags.hang_timeout * 3 + 2.0

    bundle = json.loads(bundles[0].read_text())
    assert bundle["reason"] == "hang"
    # all-thread stacks, with the training thread blocked in the loader
    main = next(
        frames for name, frames in bundle["stacks"].items()
        if name.startswith("MainThread")
    )
    assert any("__iter__" in line or "_Loader" in str(line) for line in main)
    assert any("tpukit-watchdog" in n for n in bundle["stacks"])
    # ring holds the pre-hang step records
    kinds = [r["kind"] for r in bundle["ring"]]
    assert "step" in kinds
    # heartbeat snapshot (the beat written before the first compile)
    assert "0" in bundle["heartbeats"]
    # config + in-flight probes made it in
    assert bundle["config"]["hang_timeout"] == flags.hang_timeout
    assert "async_checkpoint_in_flight" in bundle["inflight"]


def test_fit_hang_surfaces_in_jsonl_and_arms_trace(hang_run):
    _, _, records, _, _ = hang_run
    wd = [r for r in records if r["kind"] == "watchdog"]
    assert any(r.get("event") == "hang" for r in wd)
    hang = next(r for r in wd if r.get("event") == "hang")
    assert hang["hangs"] >= 1 and hang["bundles"]
    # the hang recovery was the run's first anomaly: trace armed once,
    # started, and stopped after trace_on_anomaly steps
    tr = [r for r in records if r["kind"] == "anomaly_trace"]
    events = [r["event"] for r in tr]
    assert events.count("armed") == 1
    assert events.count("started") == 1
    assert events.count("stopped") == 1
    started = next(r for r in tr if r["event"] == "started")
    stopped = next(r for r in tr if r["event"] == "stopped")
    assert stopped["step"] - started["step"] + 1 == 2  # K=2 traced steps


def test_fit_hang_run_renders_in_tools(hang_run):
    from tools import flightview
    from tools.report import load, summarize

    flags, _, _, tmp, _ = hang_run
    # flightview renders the bundle (newest-in-dir mode) without error
    assert flightview.main([str(tmp / "dbg")]) == 0
    text = summarize(load(str(tmp / "run.jsonl")))
    assert "watchdog" in text and "HANG" in text
    assert "anomaly trace" in text


def test_fit_trains_to_completion_after_hang(hang_run):
    """The watchdog is advisory: the recovered run finishes its epoch and
    the final state/checkpoint are intact."""
    _, result, records, _, _ = hang_run
    assert int(result.state.step) == 12
    assert any(r["kind"] == "validation" for r in records)


@pytest.fixture(scope="module")
def divergence_run(tmp_path_factory):
    """fit() with --divergence_check_freq on, plus a planted beat file
    from a fake process 1 whose checksum at step 8 disagrees — the
    process-0 window check must flag it, log it, and dump a bundle."""
    import os

    from tpukit.train import fit
    from tpukit.shardings import SingleDevice

    tmp = tmp_path_factory.mktemp("div")
    # 24 steps -> windows at 8 and 16: the stale planted mismatch is still
    # on disk at the second window, which must NOT re-report it (dedupe)
    flags = _obs_flags(
        tmp, divergence_check_freq=4, dataset_slice="192", batch_size=8,
    )
    hb_dir = Path(flags.heartbeat_dir)
    hb_dir.mkdir(parents=True, exist_ok=True)
    # the first window closes at host_step 8 with checksum_step 8 (freq 4
    # divides 8); the imposter claims a different state at that exact step
    (hb_dir / "heartbeat-p00001.json").write_text(json.dumps({
        "process": 1, "step": 8, "time": time.time() + 3600,
        "checksum": "deadbeef:deadbeef", "checksum_step": 8,
    }))
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        result = fit(flags, SingleDevice())
    finally:
        os.chdir(cwd)
    records = [
        json.loads(line)
        for line in (tmp / "run.jsonl").read_text().splitlines()
    ]
    return flags, result, records, tmp


def test_fit_divergence_check_records_and_detection(divergence_run):
    flags, _, records, tmp = divergence_run
    checks = [r for r in records if r["kind"] == "divergence_check"]
    assert checks, "no periodic checksum records"
    assert all(r["step"] % flags.divergence_check_freq == 0 for r in checks)
    # every checksum is the replicated-state format
    assert all(
        len(r["checksum"]) == 17 and ":" in r["checksum"] for r in checks
    )
    div = [r for r in records if r["kind"] == "divergence"]
    assert div, "planted mismatching replica was not detected"
    m = div[0]["mismatches"][0]
    # two processes, one planted mismatch: with no majority the tie breaks
    # deterministically by checksum string, so either side may be named —
    # what matters is that the disagreeing PAIR at step 8 was flagged
    assert m["checksum_step"] == 8
    assert m["process"] in (0, 1)
    assert "deadbeef:deadbeef" in (m["checksum"], m["expected"])
    assert m["checksum"] != m["expected"]
    # the SAME mismatch is still on disk at the next window (beats
    # republish their latest checksum) but is reported exactly once
    assert len(div) == 1
    # and the bundle budget was charged once, not once per window
    assert len(list((tmp / "dbg").glob("bundle-*-divergence-*.json"))) == 1
    # a bundle was dumped for the divergence
    assert list((tmp / "dbg").glob("bundle-*-divergence-*.json"))
    # and the run's own beat file carries its checksum for peers to read
    beat = json.loads(
        (Path(flags.heartbeat_dir) / "heartbeat-p00000.json").read_text()
    )
    assert beat.get("checksum") and beat.get("checksum_step") is not None


def test_fit_divergence_report_renders(divergence_run):
    from tools.report import load, summarize

    flags, _, _, tmp = divergence_run
    text = summarize(load(str(tmp / "run.jsonl")))
    assert "DIVERGENCE" in text
    assert "divergence checks" in text
    assert "deadbeef" in text
