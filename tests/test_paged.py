"""Paged KV cache subsystem (tpukit/serve/paged.py, round 15, ROADMAP #2).

Contracts pinned here:
  - paged decode is TOKEN-FOR-TOKEN the serial cached decode (and the
    round-14 ring engine) for the exact (f32-at-compute-dtype) page
    storage — greedy and fixed-seed sampling, under admit/evict
    interleaving with a pool tight enough to force mid-stream page reuse
    and retained-prefix reclaim;
  - shared-prefix reuse: prefix-hit admissions skip the shared prefill,
    and a shared page's WRITER evicting leaves its readers valid
    (refcounts), with the retained-LRU keeping a popular prefix hot;
  - chunked prefill (one page per dispatch) is equivalent to one-shot
    prefill (chunk == bucket);
  - int8 page payloads are gated by a token-level tolerance (they are
    lossy by construction — never claimed exact) at ~4x pages per HBM
    byte;
  - the decode step's per-step collectives under a model-only TP mesh
    match `decode_step_comm(..., paged=True)` EXACTLY with zero
    involuntary-remat warnings — the paged gather/write-back adds NO
    comm (the round-10/12 audit discipline extended to paging);
  - ServeConfig/engine reject bad page layouts with NAMED errors at
    construction (page size vs buckets, int8 vs the 256-element quant
    block, paged vs a data-sharded mesh), never opaque XLA shape errors;
  - the page allocator's registry can never match stale content after a
    page is reclaimed and re-issued (parent-chain purge);
  - `checkpoint.restore_params` restores the params subtree only —
    equal values to the full restore, opt_state bytes skipped (sharded),
    named errors for non-TrainState checkpoints and flag mismatches.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit import checkpoint as ck
from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.sampling import _decode_loop_cached
from tpukit.serve import (
    Request,
    ServeConfig,
    ServeEngine,
    decode_step,
    decode_step_comm,
    synthetic_request_stream,
)
from tpukit.serve import paged as paged_lib

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=96, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


def _serial(params, cfg, ids, max_new=MAX_NEW, eos_id=None, temperature=0.0,
            top_k=0, seed=0):
    ids = np.asarray(ids, np.int32)
    buf = np.zeros((1, len(ids) + max_new), np.int32)
    buf[0, : len(ids)] = ids
    out, length = _decode_loop_cached(
        params, cfg, jnp.asarray(buf), len(ids), max_new, int(eos_id),
        temperature=float(temperature),
        top_k=min(int(top_k), cfg.padded_vocab_size),
        rng=jnp.asarray(np.asarray(jax.random.PRNGKey(seed)))
        if temperature > 0.0
        else None,
    )
    return np.asarray(out)[0, : int(length)]


# ---------------------------------------------------------------------------
# Parity: paged engine == ring engine == serial cached decode, including a
# pool tight enough to recycle pages mid-stream.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,stream_seed",
    [(0.0, 0, 3), (0.9, 5, 11)],
    ids=["greedy", "sampled_topk"],
)
def test_paged_engine_parity_tight_pool(tok, cfg, params, temperature, top_k,
                                        stream_seed):
    """8 requests through 3 slots and a pool barely larger than one
    worst-case request set: forces mid-decode eviction, slot reuse AND
    page recycling (freed/retained pages re-issued with old garbage in
    them) while other slots are mid-sequence. Every completion must still
    be token-for-token the serial cached decode of its own prompt, and
    the ring engine must agree per request."""
    serve_kw = dict(slots=3, buckets=(8, 16), max_new_tokens=MAX_NEW,
                    temperature=temperature, top_k=top_k, window_steps=8)
    reqs = synthetic_request_stream(
        tok, 8, seed=stream_seed, max_new_tokens=MAX_NEW, buckets=(8, 16),
        qps=50.0 if temperature else 0.0,
    )
    ring = ServeEngine(params, cfg, ServeConfig(**serve_kw),
                       eos_id=int(tok.eos_token_id))
    ring_out = {c.rid: c for c in ring.run(list(reqs), max_wall_s=300)}
    # pages: width 26 -> ceil(26/4)=7 pages/slot; 11 usable pages < 3 slots'
    # worst case (21) -> admission control + recycling both exercised
    eng = ServeEngine(
        params, cfg,
        ServeConfig(**serve_kw, page_size=4, num_pages=12),
        eos_id=int(tok.eos_token_id),
    )
    comps = {c.rid: c for c in eng.run(list(reqs), max_wall_s=300)}
    assert comps.keys() == ring_out.keys() == {r.rid for r in reqs}
    assert not eng._lanes and len(eng._free) == 3
    assert eng.allocator.live_pages == 0  # every reference released
    for rid, c in comps.items():
        want = _serial(params, cfg, c.ids[: c.prompt_len], MAX_NEW,
                       tok.eos_token_id, temperature, top_k,
                       seed=stream_seed + rid)
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {rid}")
        np.testing.assert_array_equal(c.ids, ring_out[rid].ids,
                                      err_msg=f"rid {rid} vs ring")


def test_paged_bf16_kv_parity_at_bf16_compute(tok, cfg, params):
    """bf16 pages at bf16 compute store exactly what the ring stores
    (the storage dtype == compute dtype rule): token-for-token parity
    with the serial cached decode at the same compute dtype."""
    bcfg = cfg.replace(compute_dtype=jnp.bfloat16)
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8, page_size=4, kv_dtype="bf16")
    reqs = synthetic_request_stream(tok, 4, seed=6, max_new_tokens=6,
                                    buckets=(8, 16))
    eng = ServeEngine(params, bcfg, serve, eos_id=int(tok.eos_token_id))
    for c in eng.run(list(reqs), max_wall_s=300):
        want = _serial(params, bcfg, c.ids[: c.prompt_len], 6,
                       tok.eos_token_id)
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {c.rid}")


def test_chunked_prefill_equals_one_shot(tok, cfg, params):
    """Chunked prefill (one page per dispatch) and one-shot prefill
    (chunk == bucket) must produce identical tokens — causal attention
    makes a chunk's K/V independent of how later positions arrive."""
    reqs = synthetic_request_stream(tok, 6, seed=7, max_new_tokens=MAX_NEW,
                                    buckets=(16,))
    outs = []
    for chunk in (4, 16):
        serve = ServeConfig(slots=2, buckets=(16,), max_new_tokens=MAX_NEW,
                            window_steps=8, page_size=4, prefill_chunk=chunk)
        eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
        outs.append({c.rid: list(map(int, c.ids))
                     for c in eng.run(list(reqs), max_wall_s=300)})
    assert outs[0] == outs[1]


def test_paged_completion_carries_prompt_on_prefix_hit(tok, cfg, params):
    """A prefix-hit admission skips its shared chunks, so the token buffer
    never holds the shared prompt segment — the completion must still
    carry the FULL prompt (regression: completions returned zeros for the
    shared prefix). Two runs on one engine: the registry (and the
    retained pages) survive between runs, so the second admission is a
    guaranteed hit."""
    ids = tuple(tok(["One day, the big cat sat"], truncation=True,
                    max_length=8)["input_ids"][0])
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=4,
                        window_steps=8, page_size=4)
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    comps = {}
    for rid in (0, 1):
        for c in eng.run([Request(rid=rid, ids=ids, max_new_tokens=4)],
                         max_wall_s=300):
            comps[c.rid] = c
    assert eng.allocator.stats.prefix_hits >= 1
    assert comps[1].prefix_pages > 0
    for c in comps.values():
        np.testing.assert_array_equal(c.ids[: c.prompt_len], ids)
        want = _serial(params, cfg, ids, 4, tok.eos_token_id)
        np.testing.assert_array_equal(c.ids, want)


# ---------------------------------------------------------------------------
# Shared-prefix reuse: hits skip prefill; a writer's eviction never
# invalidates its readers (refcounts); retained pages serve later arrivals.
# ---------------------------------------------------------------------------


def test_prefix_reader_survives_writer_eviction(tok, cfg, params):
    """Writer A prefills + registers prompt X's pages, completes, and
    evicts — its pages retire into the retained LRU, NOT the free list.
    Readers B and C then admit the same prompt as prefix hits sharing
    those pages (refcount 2); B finishes first and releases while C is
    still mid-decode — the refcount must keep the shared pages valid for
    C, whose completion stays serial-exact."""
    ids = tuple(tok(["The big brown cat sat on a mat and then"],
                    truncation=True, max_length=16)["input_ids"][0])
    assert len(ids) == 16
    serve = ServeConfig(slots=3, buckets=(16,), max_new_tokens=MAX_NEW,
                        window_steps=8, page_size=4)
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    budgets = {0: 2, 1: 1, 2: MAX_NEW}
    # run 1: the writer alone (registers pages 0..2 of the prompt);
    # run 2: B (evicts after 1 token, releasing its shared refs early)
    # and C (decodes on) share the writer's retained pages
    comps = {c.rid: c for c in eng.run(
        [Request(rid=0, ids=ids, max_new_tokens=budgets[0])], max_wall_s=300)}
    assert eng.allocator.registered_pages() >= 3  # writer evicted; retained
    assert eng.allocator.live_pages == 0
    for c in eng.run(
        [Request(rid=1, ids=ids, max_new_tokens=budgets[1]),
         Request(rid=2, ids=ids, max_new_tokens=budgets[2], seed=2)],
        max_wall_s=300,
    ):
        comps[c.rid] = c
    assert len(comps) == 3
    # (plen-1)//P = 3 shareable pages; both readers hit all of them
    assert eng.allocator.stats.prefix_hits >= 2
    assert comps[1].prefix_pages == 3 and comps[2].prefix_pages == 3
    for rid, c in comps.items():
        want = _serial(params, cfg, ids, budgets[rid], tok.eos_token_id)
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {rid}")
    # all references released again; prefix pages stay RETAINED for the
    # next arrival instead of returning to the free list
    assert eng.allocator.live_pages == 0
    assert eng.allocator.registered_pages() >= 3
    assert eng.allocator.free_pages < eng.num_pages - 1
    # prefix hits deleted admission work: hit admit latency < cold
    s = eng.last_summary
    assert s["prefix_hits"] >= 1
    assert s["admit_latency_hit_s"] < s["admit_latency_cold_s"]


def test_page_allocator_refcounts_and_stale_parent_purge():
    """Allocator unit contracts: refcounted sharing, retained-LRU reuse,
    and — the correctness-critical one — a reclaimed page's registry
    subtree is purged with it, so a re-issued page id can NEVER be
    matched under its old content (stale-parent hazard)."""
    al = paged_lib.PageAllocator(num_pages=6, page_size=2)  # pages 1..5
    ids = (7, 8, 9, 10)
    pages = al.alloc(2)
    assert pages == [1, 2] and al.live_pages == 2
    al.register(ids, pages)
    assert al.lookup_prefix(ids, 2) == [1, 2]
    assert al.lookup_prefix((7, 8, 99, 100), 2) == [1]  # chain is content-exact
    # a reader shares, the writer releases: pages stay live
    al.claim(pages)
    al.release(pages)
    assert al.refcount[1] == al.refcount[2] == 1
    # last release retires REGISTERED pages into the retained LRU
    al.release(pages)
    assert al.live_pages == 0 and al.free_pages == 3
    assert al.lookup_prefix(ids, 2) == [1, 2]  # still matchable (retained)
    al.claim([1, 2])  # a hit rescues them
    assert al.refcount[1] == 1
    al.release([1, 2])
    # pool pressure reclaims the retained chain root -> whole subtree
    # purged and freed; the old registration must be gone even though the
    # page ids return to circulation
    got = al.alloc(5)
    assert sorted(got) == [1, 2, 3, 4, 5]
    assert al.lookup_prefix(ids, 2) == []
    assert al.registered_pages() == 0
    # a LIVE child whose parent is purged keeps its page but loses its
    # registration (it can only be reached through the parent)
    al2 = paged_lib.PageAllocator(num_pages=6, page_size=2)
    p = al2.alloc(2)
    al2.register(ids, p)
    al2.claim([p[1]])          # child read by someone
    al2.release([p[0], p[1]])  # writer gone: parent retained, child live
    assert al2.alloc(4) is not None  # reclaims the retained parent
    assert al2.lookup_prefix(ids, 2) == []
    al2.release([p[1]])        # last reader: unregistered -> plain free
    assert al2.refcount[p[1]] == 0
    with pytest.raises(AssertionError, match="negative"):
        al2.release([p[1]])
    # a DOOMED allocation must not purge the retained registry on its
    # way to failing: the caller retries the same admission later, and
    # every prefix hit it would have had would be gone
    al3 = paged_lib.PageAllocator(num_pages=4, page_size=2)  # pages 1..3
    p = al3.alloc(2)
    al3.register(ids, p)
    al3.release(p)  # both retained
    assert al3.alloc(4) is None  # free(1) + retained(2) < 4: infeasible
    assert al3.lookup_prefix(ids, 2) == p  # registry untouched
    assert al3.stats.reclaimed == 0


# ---------------------------------------------------------------------------
# int8 pages: tolerance-gated (lossy by construction), ~4x HBM win.
# ---------------------------------------------------------------------------


def test_int8_kv_token_tolerance_gate(tok, params):
    """The token-level tolerance gate for quantized pages (mirroring the
    round-12 loss-trajectory gate): int8 page storage must agree with the
    exact engine on >= 90% of tokens over the stream, at ~1/4 the page
    bytes. Bit parity is impossible by construction — the gate pins the
    quantizer's quality, not exactness."""
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=96, compute_dtype=jnp.float32,
    )
    # head_dim 8 -> page 32 makes each (page, head) row exactly one
    # 256-element quant block
    reqs = synthetic_request_stream(tok, 6, seed=4, max_new_tokens=MAX_NEW,
                                    buckets=(32,))
    outs = {}
    for dt in ("f32", "int8"):
        serve = ServeConfig(slots=2, buckets=(32,), max_new_tokens=MAX_NEW,
                            window_steps=8, page_size=32, kv_dtype=dt)
        eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
        outs[dt] = {c.rid: np.asarray(c.ids)
                    for c in eng.run(list(reqs), max_wall_s=300)}
        if dt == "int8":
            bytes_int8 = eng.kv_bytes
        else:
            bytes_f32 = eng.kv_bytes
    assert outs["f32"].keys() == outs["int8"].keys()
    agree = []
    for rid in outs["f32"]:
        a, b = outs["f32"][rid], outs["int8"][rid]
        m = min(len(a), len(b))
        agree.append(float(np.mean(a[:m] == b[:m])))
    assert np.mean(agree) >= 0.9, agree
    # packed int8 pages cost ~(1 + 4/256)/4 of f32 pages
    assert bytes_int8 < bytes_f32 / 3.5


def test_pool_bytes_closed_form(cfg):
    """`pool_bytes` must equal the actual device pytree footprint."""
    for dt in ("f32", "bf16", "int8"):
        page = 32 if dt == "int8" else 4
        tree = paged_lib.init_paged_cache(cfg, 7, page, 3, 2, dt)
        measured = sum(
            int(np.prod(v.shape)) * v.dtype.itemsize
            for k, v in tree.items() if k != "bt"
        )
        assert paged_lib.pool_bytes(cfg, 7, page, dt) == measured, dt


# ---------------------------------------------------------------------------
# Validation: named errors at construction, never XLA shape errors.
# ---------------------------------------------------------------------------


def test_serve_config_paged_validation(tok, cfg, params):
    with pytest.raises(ValueError, match="divide every bucket"):
        ServeConfig(buckets=(8, 12), page_size=8)
    with pytest.raises(ValueError, match="requires the paged cache"):
        ServeConfig(kv_dtype="int8")
    with pytest.raises(ValueError, match="requires the paged cache"):
        ServeConfig(num_pages=16)
    with pytest.raises(ValueError, match="multiple of.*page_size"):
        ServeConfig(buckets=(16,), page_size=4, prefill_chunk=6)
    with pytest.raises(ValueError, match="divide every bucket"):
        ServeConfig(buckets=(16, 32), page_size=4, prefill_chunk=12)
    with pytest.raises(ValueError, match="one of"):
        ServeConfig(buckets=(16,), page_size=4, kv_dtype="fp8")
    with pytest.raises(ValueError, match="cannot hold even one"):
        ServeConfig(buckets=(16,), max_new_tokens=16, page_size=4, num_pages=8)
    # int8 quant-block mismatch: page 4 x head_dim 8 = 32 elements/head,
    # not a 256 multiple — NAMED at engine construction
    with pytest.raises(ValueError, match="256-element"):
        ServeEngine(params, cfg,
                    ServeConfig(buckets=(16,), page_size=4, kv_dtype="int8"),
                    eos_id=1)
    # the same check is importable stand-alone
    with pytest.raises(ValueError, match="256-element"):
        paged_lib.validate_kv_layout(cfg, 4, "int8")
    paged_lib.validate_kv_layout(cfg, 32, "int8")  # 32*8=256: fine


def test_paged_rejects_data_sharded_mesh(cfg, params):
    from tpukit.mesh import create_mesh

    mesh = create_mesh({"data": 2, "model": 4})
    with pytest.raises(ValueError, match="model-only grid"):
        ServeEngine(params, cfg, ServeConfig(slots=4, buckets=(8,), page_size=4),
                    eos_id=1, mesh=mesh)
    with pytest.raises(ValueError, match="model-only grid"):
        decode_step_comm(cfg, mesh, 4, paged=True)


# ---------------------------------------------------------------------------
# Compile budget: chunked prefill compiles per admit size only (one chunk
# width), plus one decode program.
# ---------------------------------------------------------------------------


def test_paged_compile_budget(tok, cfg, params):
    from tpukit.serve import prefill_chunk_paged

    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8, page_size=4)
    assert serve.compile_budget == 3  # 1 decode + admit sizes {1, 2}
    chunk0 = prefill_chunk_paged._cache_size()
    decode0 = decode_step._cache_size()
    reqs = synthetic_request_stream(tok, 10, seed=2, max_new_tokens=6,
                                    buckets=(8, 16))
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    assert len(eng.run(list(reqs), max_wall_s=300)) == 10
    added = (prefill_chunk_paged._cache_size() - chunk0
             + decode_step._cache_size() - decode0)
    assert added <= serve.compile_budget
    # a second engine over the same shape adds ZERO compiles
    c1, d1 = prefill_chunk_paged._cache_size(), decode_step._cache_size()
    ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id)).run(
        synthetic_request_stream(tok, 4, seed=9, max_new_tokens=6,
                                 buckets=(8, 16)), max_wall_s=300)
    assert prefill_chunk_paged._cache_size() == c1
    assert decode_step._cache_size() == d1


# ---------------------------------------------------------------------------
# Sharded serving: the paged gather must add ZERO collectives — compiled
# HLO matches decode_step_comm(paged=True) exactly, no involuntary remat.
# ---------------------------------------------------------------------------


def _tp_paged_state(cfg, mesh, slots, kv_dtype="f32", page=8, mp=3):
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpukit.shardings import TensorParallel

    strat = TensorParallel(mesh)
    params = init_params(jax.random.PRNGKey(0), cfg)
    psh = strat.state_sharding(jax.eval_shape(lambda: params))
    params = jax.tree.map(jax.device_put, params, psh)
    sh = lambda spec: NamedSharding(mesh, spec)
    num_pages = slots * mp + 1
    tree = paged_lib.init_paged_cache(cfg, num_pages, page, mp, slots, kv_dtype)
    specs = {"k": P(None, None, "model", None, None),
             "v": P(None, None, "model", None, None),
             "ks": P(None, None, "model", None),
             "vs": P(None, None, "model", None), "bt": P()}
    cache = {k: jax.device_put(np.asarray(v), sh(specs[k]))
             for k, v in tree.items()}
    bt = np.arange(1, slots * mp + 1, dtype=np.int32).reshape(slots, mp)
    cache["bt"] = jax.device_put(bt, sh(P()))
    w = mp * page
    buf = jax.device_put(np.zeros((slots, w), np.int32), sh(P(None, None)))
    cursors = jax.device_put(np.full((slots,), 5, np.int32), sh(P(None)))
    active = jax.device_put(np.ones((slots,), bool), sh(P(None)))
    limits = jax.device_put(np.full((slots,), 12, np.int32), sh(P(None)))
    keys = jax.device_put(np.zeros((slots, 2), np.uint32), sh(P(None, None)))
    return params, buf, cache, cursors, active, limits, keys


@pytest.mark.parametrize(
    "kv_dtype,temperature,top_k",
    [("f32", 0.0, 0), ("f32", 0.9, 5), ("int8", 0.0, 0)],
    ids=["f32_greedy", "f32_topk", "int8_greedy"],
)
def test_tp_paged_decode_step_hlo_comm_audit(kv_dtype, temperature, top_k):
    """Under the model-only serving grid the paged decode step must move
    EXACTLY the ring path's closed-form collectives — the Megatron pair
    per layer + embedding psum + the one logits all-gather — with the
    page gather, the pool write-back scatter, and (int8) the
    quantize/dequantize all COMM-FREE, and zero GSPMD involuntary-remat
    fallbacks. f32 compute so byte counts are exact on the CPU wire."""
    from tpukit.mesh import create_mesh
    from tpukit.obs.xla import capture_compiler_stderr, collective_bytes

    head_dim = 32 if kv_dtype == "int8" else 8  # int8: page*head_dim == 256
    cfg = GPTConfig(
        dim=32, head_dim=head_dim, heads=4, num_layers=2, vocab_size=160,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )
    mesh = create_mesh({"model": 4})
    slots = 4
    state = _tp_paged_state(cfg, mesh, slots, kv_dtype)
    params, buf, cache, cursors, active, limits, keys = state
    # check=True raises on any involuntary-remat warning at capture exit
    with capture_compiler_stderr(check=True):
        compiled = decode_step.lower(
            params, cfg, buf, cache, cursors, active, limits, keys,
            1, temperature, top_k, mesh,
        ).compile()
    measured = collective_bytes(compiled.as_text())
    expected = decode_step_comm(cfg, mesh, slots, top_k=top_k, paged=True)
    assert measured == expected, (measured, expected)


def test_tp_paged_engine_decode_parity(tok, cfg, params):
    """Value check on top of the byte audit: the paged engine under the
    model-only TP mesh decodes the same tokens as the meshless paged
    engine (which is itself serial-exact)."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import TensorParallel

    mesh = create_mesh({"model": 4})
    strat = TensorParallel(mesh)
    tp_params = jax.tree.map(
        jax.device_put, params,
        strat.state_sharding(jax.eval_shape(lambda: params)),
    )
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8, page_size=4)
    reqs = synthetic_request_stream(tok, 4, seed=4, max_new_tokens=6,
                                    buckets=(8, 16))
    eng_tp = ServeEngine(tp_params, cfg, serve, eos_id=int(tok.eos_token_id),
                         mesh=mesh)
    comps_tp = {c.rid: c for c in eng_tp.run(list(reqs), max_wall_s=300)}
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    comps = {c.rid: c for c in eng.run(list(reqs), max_wall_s=300)}
    assert comps_tp.keys() == comps.keys()
    for rid in comps:
        np.testing.assert_array_equal(comps_tp[rid].ids, comps[rid].ids)


# ---------------------------------------------------------------------------
# Telemetry: paged fields land in the JSONL windows/summary and report.py
# renders them.
# ---------------------------------------------------------------------------


def test_paged_jsonl_windows_and_report(tok, cfg, params, tmp_path):
    from tpukit.obs import StepLogger

    log = tmp_path / "serve.jsonl"
    logger = StepLogger(str(log))
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=4, page_size=4)
    reqs = synthetic_request_stream(tok, 6, seed=8, max_new_tokens=8,
                                    buckets=(8, 16), shared_prefix=8)
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      logger=logger)
    eng.run(reqs, max_wall_s=300)
    logger.close()
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    windows = [r for r in recs if r["kind"] == "serve"]
    (summary,) = [r for r in recs if r["kind"] == "serve_summary"]
    assert windows
    for w in windows:
        assert 0.0 <= w["page_occupancy"] <= 1.0
        assert w["prefix_hit_rate"] is None or 0.0 <= w["prefix_hit_rate"] <= 1.0
    assert summary["page_size"] == 4 and summary["kv_dtype"] == "f32"
    assert summary["prefix_hits"] > 0  # the shared system prompt hit
    assert summary["prefix_pages_reused"] > 0
    assert summary["pages_per_request"] > 0
    assert summary["kv_bytes"] == eng.kv_bytes
    assert summary["max_live_slots"] <= serve.slots

    import importlib

    report = importlib.import_module("tools.report")
    text = report.summarize(recs)
    assert "paged KV:" in text and "prefix hits" in text


# ---------------------------------------------------------------------------
# Satellite: params-only checkpoint restore (serve cold start).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def train_state():
    from tpukit.train import create_train_state, make_optimizer

    cfg = GPTConfig(dim=16, head_dim=4, heads=4, num_layers=2, vocab_size=64,
                    max_position_embeddings=32, compute_dtype=jnp.float32)
    return create_train_state(jax.random.PRNGKey(0), cfg, make_optimizer(1e-4))


@pytest.mark.parametrize("fmt", ["consolidated", "sharded"])
def test_restore_params_matches_full_restore(train_state, tmp_path, fmt):
    state = train_state
    path = ck.save_auto(state, tmp_path, "checkpoint-step7", format=fmt)
    template = jax.eval_shape(lambda: state).params
    params, info = ck.restore_params(path, template)
    got = jax.tree_util.tree_leaves(params)
    want = jax.tree_util.tree_leaves(state.params)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    assert info["format"] == fmt
    assert info["leaves_read"] == len(want)
    assert info["leaves_skipped"] > 0  # opt_state + step never decoded
    if fmt == "sharded":
        # the 3x win: the Adam moments' blocks are never read
        assert info["bytes_skipped"] > info["bytes_read"]


def test_restore_params_named_errors(train_state, tmp_path):
    from flax import serialization

    state = train_state
    sharded = ck.save_auto(state, tmp_path, "checkpoint-step8", format="sharded")
    # template from different model flags: leaf-count mismatch, named
    cfg_big = GPTConfig(dim=16, head_dim=4, heads=4, num_layers=2,
                        vocab_size=64, max_position_embeddings=32,
                        compute_dtype=jnp.float32, num_experts=2)
    bad_template = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg_big)
    )
    with pytest.raises(ValueError, match="model flags"):
        ck.restore_params(sharded, bad_template)
    # a non-TrainState consolidated blob: named, not a KeyError
    raw = tmp_path / "raw.msgpack"
    raw.write_bytes(serialization.to_bytes(state.params))
    with pytest.raises(ValueError, match="no 'params' subtree"):
        ck.restore_params(raw, jax.eval_shape(lambda: state.params))


def test_restore_params_places_at_shardings(train_state, tmp_path):
    """With a sharding tree, leaves land directly at the target shardings
    — the serving cold-start path (any saved world, no reshard pass)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpukit.mesh import create_mesh

    state = train_state
    path = ck.save_auto(state, tmp_path, "checkpoint-step9", format="sharded")
    mesh = create_mesh({"model": 4})
    template = jax.eval_shape(lambda: state).params
    shardings = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), template
    )
    params, _ = ck.restore_params(path, template, shardings)
    for leaf, want in zip(jax.tree_util.tree_leaves(params),
                          jax.tree_util.tree_leaves(state.params)):
        assert leaf.sharding.mesh.shape == mesh.shape
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(want))
