"""Interleaved-1F1B virtual stages (round 25, `--virtual_stages V`).

Four layers under test, one table of truth (tpukit/pipeline_schedule.py):

1. the schedule AUTHORITY itself — every (chunk, micro) job exactly once,
   dependency-ordered, ship counts consistent, bubble strictly shrinking
   on the gate grid;
2. the tick MACHINE (Pipeline1F1B._interleaved_value_and_grad) — loss,
   eval loss and parameter updates match the single-device reference at
   V∈{2,4}, on ragged micro counts, uneven layer counts and a 2-D
   data x stage mesh; V=1 dense lowers BYTE-IDENTICAL to the original
   flat scan (the do-no-harm bar);
3. the pipeline x MoE composition — the meshless dropless "pallas"
   dispatch inside stage chunks reproduces the per-micro Switch
   objective's loss AND grads exactly, top-1 and top-2, 1F1B and GPipe,
   while "xla"/"a2a" stay rejected by name;
4. the plumbing — flags, comm plan (pipe_comm feeding train_comm_plan),
   the param layout round-trip, and the report gate
   (`--min_bubble_gain`, tools/report.py) that keeps the bench record
   honest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, gpt
from tpukit.model.gpt import init_params
from tpukit.ops.layers import cross_entropy_sum
from tpukit.pipeline import Pipeline, Pipeline1F1B
from tpukit.pipeline_schedule import (
    bubble_table,
    build_schedule,
    cached_schedule,
    flat_1f1b_bubble,
)
from tpukit.shardings import SingleDevice
from tpukit.train import create_train_state, make_optimizer, make_step_fns

SEQ = 32


# ---------------------------------------------------------------- helpers


def make_batch(cfg, batch_size, seed=7):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, SEQ)).astype(np.int32)
    mask = np.zeros((batch_size, SEQ), dtype=bool)
    for row in range(0, batch_size, 3):
        pad_from = rng.randint(SEQ // 2, SEQ)
        mask[row, pad_from:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    return {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }, targets


def one_step(strategy, cfg, model_batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, model_batch, targets)
    eval_loss, eval_acc = eval_step(new_state, model_batch, targets)
    return new_state.params, float(loss), float(eval_loss), float(eval_acc)


def assert_interleave_matches_single(cfg, v, micro, batch_size,
                                     stages=2, data=1):
    """One optimizer step on the interleaved machine == single device:
    same loss (1e-5), same updated params (after undoing the chunk
    permutation and slicing off identity padding)."""
    mb, tg = make_batch(cfg, batch_size)
    ref_params, ref_loss, ref_eval, ref_acc = one_step(
        SingleDevice(), cfg, mb, tg
    )
    c2 = cfg.replace(virtual_stages=v)
    axes = {"stage": stages} if data == 1 else {"data": data, "stage": stages}
    strat = Pipeline1F1B(create_mesh(axes), num_microbatches=micro)
    params, loss, eval_loss, eval_acc = one_step(strat, c2, mb, tg)
    params = strat.inference_params(jax.device_get(params), c2)
    params = {
        **params,
        "layers": jax.tree.map(lambda l: l[: cfg.num_layers], params["layers"]),
    }
    assert abs(loss - ref_loss) < 1e-5, (v, micro, loss, ref_loss)
    assert abs(eval_loss - ref_eval) < 1e-2
    assert abs(eval_acc - ref_acc) < 1.0
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        params, jax.device_get(ref_params),
    )


@pytest.fixture(scope="module")
def cfg4():
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=4, vocab_size=211,
        max_position_embeddings=SEQ, compute_dtype=jnp.float32,
    )


# ------------------------------------------- 1. the schedule authority


@pytest.mark.parametrize(
    "s,v,m",
    [(2, 2, 4), (2, 4, 8), (4, 2, 4), (4, 4, 16), (2, 2, 3), (4, 3, 5)],
)
def test_schedule_complete_and_ordered(s, v, m):
    """Every (global chunk, micro) runs forward exactly once and backward
    exactly once, in dependency order, and the ship-tick stats match the
    per-tick flags (they are the comm plan's collective-permute count)."""
    sched = build_schedule(s, v, m)
    g_total = s * v
    f_tick, b_tick = {}, {}
    for t, tk in enumerate(sched.ticks):
        for d in range(s):
            if tk.fwd[d] is not None:
                c, mi, _slot = tk.fwd[d]
                g = c * s + d
                assert (g, mi) not in f_tick, "forward ran twice"
                f_tick[(g, mi)] = t
            if tk.bwd[d] is not None:
                c, mi, _slot = tk.bwd[d]
                g = c * s + d
                assert (g, mi) not in b_tick, "backward ran twice"
                b_tick[(g, mi)] = t
    assert len(f_tick) == g_total * m
    assert len(b_tick) == g_total * m
    for (g, mi), t in f_tick.items():
        if g > 0:
            assert f_tick[(g - 1, mi)] < t, "forward ran before its input"
        # the last chunk's backward is self-triggered the same tick (the
        # head+CE vjp); every other backward waits for the cotangent hop
        bt = b_tick[(g, mi)]
        assert bt >= t
        if g < g_total - 1:
            assert b_tick[(g + 1, mi)] < bt
    assert sched.stats["ship_fwd_ticks"] == sum(
        1 for tk in sched.ticks if tk.ship_fwd
    )
    assert sched.stats["ship_bwd_ticks"] == sum(
        1 for tk in sched.ticks if tk.ship_bwd
    )
    assert sched.stats["ticks"] == len(sched.ticks)


def test_schedule_forward_only():
    """include_backward=False is the eval program: complete forwards, no
    backward jobs, no backward shipping, NaN bubble (not priced)."""
    sched = build_schedule(4, 2, 8, include_backward=False)
    assert all(all(j is None for j in tk.bwd) for tk in sched.ticks)
    assert sched.stats["ship_bwd_ticks"] == 0
    n_fwd = sum(
        1 for tk in sched.ticks for j in tk.fwd if j is not None
    )
    assert n_fwd == 4 * 2 * 8


def test_flat_bubble_closed_form():
    assert flat_1f1b_bubble(4, 8) == pytest.approx((2 * 4 - 2) / (8 + 2 * 4 - 2))
    assert flat_1f1b_bubble(2, 4) == pytest.approx(2 / 6)


def test_bubble_strictly_decreases_on_gate_grid():
    """The gate grid (S=4, M in {4,8,16}, V 1->2->4): interleaving must
    strictly cut the idle-work fraction at every micro count — the exact
    monotonicity `report.py --min_bubble_gain` enforces on bench logs."""
    for m in (4, 8, 16):
        flat = flat_1f1b_bubble(4, m)
        b2 = build_schedule(4, 2, m).stats["bubble_frac"]
        b4 = build_schedule(4, 4, m).stats["bubble_frac"]
        assert flat > b2 > b4, (m, flat, b2, b4)
        # and the headline cut is large: >= 50% relative at M=4..16
        assert 1.0 - b4 / flat >= 0.5


def test_bubble_table_shape():
    rows = bubble_table(4)
    assert len(rows) == 9  # 3 micros x 3 virtuals
    for row in rows:
        assert 0.0 < row["bubble_frac"] < 1.0
        if row["virtual_stages"] > 1:
            assert row["depth"] >= 1


# ------------------------------------------------- 2. the tick machine


def test_v1_dense_hlo_byte_identical(cfg4):
    """`--virtual_stages 1` on a dense config must cost NOTHING: the
    public value_and_grad lowers to byte-for-byte the same HLO as the
    original flat tick scan it dispatches to."""
    strat = Pipeline1F1B(create_mesh({"stage": 2}), num_microbatches=4)
    params = strat.prepare_params(init_params(jax.random.PRNGKey(0), cfg4), cfg4)
    mb, tg = make_batch(cfg4, 8)

    def lower(fn):
        return jax.jit(
            lambda p: fn(p, cfg4, mb, tg)
        ).lower(params).as_text()

    assert lower(strat.value_and_grad) == lower(strat._flat_value_and_grad)


def test_interleave_v2_ragged_micro(cfg4):
    # M=3 does not divide S*V — the warm-up/cool-down is ragged
    assert_interleave_matches_single(cfg4, v=2, micro=3, batch_size=12)


@pytest.mark.slow
def test_interleave_v4(cfg4):
    assert_interleave_matches_single(
        cfg4.replace(num_layers=8), v=4, micro=4, batch_size=16
    )


@pytest.mark.slow
def test_interleave_uneven_layers(cfg4):
    # L=5 on 2 stages x V=2 -> padded to 8, three identity chunks
    assert_interleave_matches_single(
        cfg4.replace(num_layers=5), v=2, micro=4, batch_size=16
    )


def test_interleave_data_stage_mesh(cfg4):
    # 2-D data x stage: each micro splits over the data axis too
    assert_interleave_matches_single(
        cfg4, v=2, micro=4, batch_size=16, stages=2, data=2
    )


def test_param_layout_round_trip(cfg4):
    """prepare_params permutes the stacked layers into interleaved chunk
    order (device-major); inference_params is its exact inverse."""
    cfg = cfg4.replace(num_layers=8, virtual_stages=4)
    strat = Pipeline1F1B(create_mesh({"stage": 2}), num_microbatches=4)
    params = init_params(jax.random.PRNGKey(1), cfg)
    packed = strat.prepare_params(params, cfg)
    restored = strat.inference_params(jax.device_get(packed), cfg)
    jax.tree.map(
        np.testing.assert_array_equal,
        jax.device_get(params), restored,
    )


# --------------------------------------------- 3. pipeline x MoE parity


def moe_reference_value_and_grad(params, cfg, batch, targets, num_micro):
    """Single-device reference of the pipeline's per-micro MoE objective:
    CE over the full batch + aux_weight * sum_m aux_m / M. Exact parity
    holds because the stage-only mesh keeps one dispatch group per micro
    (the Switch balance loss is nonlinear in dispatch grouping)."""
    c = cfg.replace(moe_dispatch="pallas", virtual_stages=1)
    batch_size = batch["input_ids"].shape[0]
    micro = batch_size // num_micro

    def total(p):
        ce_sum = jnp.float32(0)
        cnt = jnp.float32(0)
        aux_tot = jnp.float32(0)
        for m in range(num_micro):
            sl = slice(m * micro, (m + 1) * micro)
            al = []
            logits = gpt.forward(
                p, c, batch["input_ids"][sl], batch["position_ids"][sl],
                batch["mask"][sl], aux_out=al,
            )
            ls, cn = cross_entropy_sum(logits, targets[sl])
            ce_sum += ls
            cnt += cn
            aux_tot += al[0]
        ce = ce_sum / jnp.maximum(cnt, 1.0)
        return ce + c.moe_aux_weight * aux_tot / num_micro, ce

    (_, ce), grads = jax.value_and_grad(total, has_aux=True)(params)
    return ce, grads


# Tier-1 keeps ONE MoE composition gate (1f1b V=2, the headline case);
# the full matrix is slow-tiered and runs in the pipeline-interleave CI
# lane, whose parity step includes the slow tier (compile-heavy worlds —
# the 870s tier-1 budget is the binding constraint, see ci.yml).
@pytest.mark.parametrize(
    "schedule,v,top_k",
    [
        pytest.param("1f1b", 1, 1, marks=pytest.mark.slow),
        ("1f1b", 2, 1),
        pytest.param("1f1b", 2, 2, marks=pytest.mark.slow),
        pytest.param("gpipe", 1, 1, marks=pytest.mark.slow),
    ],
    ids=["1f1b-v1", "1f1b-v2", "1f1b-v2-top2", "gpipe"],
)
def test_moe_pipeline_parity(cfg4, schedule, v, top_k):
    """MoE inside stage chunks (--num_experts N --moe_dispatch pallas):
    loss and every grad leaf match the per-micro reference exactly."""
    cfg = cfg4.replace(num_experts=4, router_top_k=top_k, virtual_stages=v)
    mb, tg = make_batch(cfg, 8)
    cls = Pipeline1F1B if schedule == "1f1b" else Pipeline
    strat = cls(
        create_mesh({"stage": 2}), num_microbatches=4, moe_dispatch="pallas"
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    ref_loss, ref_grads = moe_reference_value_and_grad(params, cfg, mb, tg, 4)
    packed = strat.prepare_params(params, cfg)
    loss, grads = jax.jit(lambda p: strat.value_and_grad(p, cfg, mb, tg))(packed)
    grads = strat.inference_params(jax.device_get(grads), cfg)
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        grads, jax.device_get(ref_grads),
    )


# ------------------------------------------------ validation matrix


def test_rejects_too_many_virtual_stages(cfg4):
    strat = Pipeline1F1B(create_mesh({"stage": 2}), num_microbatches=4)
    with pytest.raises(ValueError, match="maximum virtual_stages here is 2"):
        strat.validate_config(cfg4.replace(virtual_stages=4))


def test_gpipe_rejects_interleave(cfg4):
    strat = Pipeline(create_mesh({"stage": 2}), num_microbatches=4)
    with pytest.raises(ValueError, match="1f1b"):
        strat.validate_config(cfg4.replace(virtual_stages=2))


@pytest.mark.parametrize("dispatch", ["xla", "a2a"])
def test_rejects_buffer_moe_dispatch(cfg4, dispatch):
    strat = Pipeline1F1B(
        create_mesh({"stage": 2}), num_microbatches=4, moe_dispatch=dispatch
    )
    cfg = cfg4.replace(num_experts=4, virtual_stages=2)
    with pytest.raises(ValueError, match="pallas") as exc:
        strat.validate_config(cfg)
    assert "ExpertParallel" in str(exc.value)
    # and the strategy-call entry points fail just as loudly
    with pytest.raises(ValueError, match="pallas"):
        strat.value_and_grad(None, cfg, None, None)


# --------------------------------------------------- 4. the plumbing


def test_flag_plumbing():
    from tpukit.flags import parse_flags

    flags = parse_flags(
        ["--schedule", "1f1b", "--virtual_stages", "2",
         "--num_experts", "8", "--moe_dispatch", "pallas"],
        pipeline_schedule=True, num_experts=True, default_experts=0,
    )
    assert flags.pipeline_schedule == "1f1b"
    assert flags.virtual_stages == 2
    assert flags.num_experts == 8
    assert flags.moe_dispatch == "pallas"
    defaults = parse_flags(
        [], pipeline_schedule=True, num_experts=True, default_experts=0
    )
    # the pipeline recipes stay the dense flat reference by default
    assert defaults.virtual_stages == 1
    assert defaults.num_experts == 0


def test_pipe_comm_plan(cfg4):
    """pipe_comm: None for the flat dense scan (its hops live inside the
    scan body); for V>1 the exact collective-permute count/bytes of the
    unrolled program, folded into train_comm_plan; MoE on a stage-only
    mesh additionally pins all-to-all to ZERO (pallas is collective-free)."""
    from tpukit.analysis.plan import train_comm_plan

    strat = Pipeline1F1B(create_mesh({"stage": 2}), num_microbatches=4)
    assert strat.pipe_comm(cfg4, global_batch=8, seq=SEQ) is None
    assert train_comm_plan(strat, cfg4, global_batch=8, seq=SEQ) is None

    c2 = cfg4.replace(virtual_stages=2)
    sched = cached_schedule(2, 2, 4)
    n_ship = sched.stats["ship_fwd_ticks"] + sched.stats["ship_bwd_ticks"]
    payload = (8 // 4) * SEQ * c2.dim * 4  # micro x seq x dim x f32
    ops = strat.pipe_comm(c2, global_batch=8, seq=SEQ)
    assert ops["collective-permute"] == {
        "count": n_ship, "bytes": n_ship * payload
    }
    plan = train_comm_plan(strat, c2, global_batch=8, seq=SEQ)
    assert plan.ops["collective-permute"]["count"] == n_ship
    # eval plan prices the forward-only program (fewer shipping ticks)
    ev = cached_schedule(2, 2, 4, include_backward=False)
    eplan = train_comm_plan(strat, c2, global_batch=8, seq=SEQ, phase="eval")
    assert eplan.ops["collective-permute"]["count"] == ev.stats["ship_fwd_ticks"]

    moe = Pipeline1F1B(
        create_mesh({"stage": 2}), num_microbatches=4, moe_dispatch="pallas"
    )
    mops = moe.pipe_comm(
        c2.replace(num_experts=4), global_batch=8, seq=SEQ
    )
    assert mops["all-to-all"] == {"count": 0, "bytes": 0}
    # with a data axis GSPMD reshards the batch ingest through tiny
    # all-to-alls that are not ours to pin — the guard must not appear
    moe2 = Pipeline1F1B(
        create_mesh({"data": 2, "stage": 2}), num_microbatches=4,
        moe_dispatch="pallas",
    )
    assert "all-to-all" not in moe2.pipe_comm(
        c2.replace(num_experts=4), global_batch=8, seq=SEQ
    )


def _gain_records():
    rungs = [
        {"virtual_stages": 1, "bubble_frac": 0.43},
        {"virtual_stages": 2, "bubble_frac": 0.16},
        {"virtual_stages": 4, "bubble_frac": 0.09},
    ]
    return [{"pipe_interleave": {
        "stages": 4, "bubble_table": bubble_table(4), "rungs": rungs,
    }}]


def test_min_bubble_gain_gate():
    from tools.report import check_min_bubble_gain

    ok, msg = check_min_bubble_gain(_gain_records(), 0.5)
    assert ok, msg
    # threshold above the real cut -> FAIL with the worst M named
    ok, msg = check_min_bubble_gain(_gain_records(), 0.99)
    assert not ok and "min relative bubble cut" in msg
    # no record -> FAIL (anti-vacuous)
    ok, msg = check_min_bubble_gain([{"kind": "metric"}], 0.1)
    assert not ok and "no pipe_interleave record" in msg
    # an errored timed rung fails even though the grid math is fine
    recs = _gain_records()
    recs[0]["pipe_interleave"]["rungs"].append(
        {"virtual_stages": 4, "error": "XlaRuntimeError('boom')"}
    )
    ok, msg = check_min_bubble_gain(recs, 0.1)
    assert not ok and "errored timed rung" in msg
    # a non-monotone grid fails regardless of the headline cut
    recs = _gain_records()
    recs[0]["pipe_interleave"]["bubble_table"][1]["bubble_frac"] = 0.99
    ok, msg = check_min_bubble_gain(recs, 0.1)
    assert not ok and "strictly decreasing" in msg
