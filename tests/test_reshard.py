"""Round-13 elastic world resize: reshard-on-restore, tested end to end.

The recovery stack (rounds 8-9) assumed the world that comes back after a
failure is the world that left. This file tests the round-13 elastic
path:

  - world metadata (tpukit/reshard.py): every save records the saving
    world (nprocs, devices, strategy, mesh axes) in its meta sidecar;
    `describe_mismatch` names a topology change, legacy checkpoints never
    trigger a spurious reshard;
  - the streaming reshard pass: a checkpoint saved under one strategy and
    world restores BIT-identically onto another strategy's shardings at a
    different device count (shrink, grow, cross-strategy), reading only
    the blocks each target shard needs (planned from npz headers);
  - checkpoints saved by a LARGER multi-process world restore into a
    smaller one (`latest_good` resolves them, `restore_any` and the
    reshard pass read every recorded shard file regardless of the current
    process count) — satellite: today's undefined behavior is pinned;
  - `verify_checkpoint`'s world/geometry cross-check: a manifest paired
    with shard files from a different world fails with a named detail
    even when per-file checksums pass;
  - `--keep_checkpoints` retention: oldest published checkpoints pruned
    past K, quarantined timelines and the `latest_good` candidate never
    pruned;
  - the `resize@N:M` chaos spec: preempt-save at step N recording target
    world M; the relaunch must reshard to M (fit raises at any other
    world) — and fit() end to end: mesh-8 save -> mesh-4 elastic resume
    with a kind="resize" JSONL record, stale-incarnation sweep, and
    post-resume window losses matching an unresized control at the dense
    tolerance (global batch held constant across the resize).
"""

from __future__ import annotations

import json
import os
import shutil
import signal
from pathlib import Path

import jax
import numpy as np
import pytest

from tpukit import chaos as chaos_lib
from tpukit import checkpoint as ckpt_lib
from tpukit import reshard as reshard_lib
from tpukit.mesh import create_mesh
from tpukit.recovery import Preempted, RecoveryEngine
from tpukit.shardings import FSDP, DataParallel, SingleDevice
from tpukit.train import create_train_state, make_optimizer

# ---------------------------------------------------------------------------
# world metadata
# ---------------------------------------------------------------------------


def test_current_world_and_describe_mismatch():
    ddp8 = DataParallel(create_mesh({"data": 8}))
    ddp4 = DataParallel(create_mesh({"data": 4}, jax.devices()[:4]))
    w8 = reshard_lib.current_world(ddp8, global_batch=64)
    assert w8["device_count"] == 8 and w8["mesh_axes"] == {"data": 8}
    assert w8["strategy"] == "ddp" and w8["global_batch"] == 64
    w4 = reshard_lib.current_world(ddp4)
    assert reshard_lib.describe_mismatch(w4, w4) is None
    detail = reshard_lib.describe_mismatch(w8, w4)
    assert "device_count 8 -> 4" in detail and "mesh_axes" in detail
    # global_batch alone is NOT a topology change (plain restore handles it)
    assert reshard_lib.describe_mismatch({**w4, "global_batch": 16}, w4) is None
    # legacy checkpoints (no world record) never trigger a spurious reshard
    assert reshard_lib.describe_mismatch(None, w4) is None
    assert reshard_lib.describe_mismatch({}, w4) is None
    # cross-strategy is a named mismatch even at equal device counts
    fsdp4 = FSDP(create_mesh({"data": 4}, jax.devices()[:4]))
    assert "strategy" in reshard_lib.describe_mismatch(
        reshard_lib.current_world(fsdp4), w4
    )


def _tiny_state(tiny_config, seed=0):
    return create_train_state(
        jax.random.PRNGKey(seed), tiny_config, make_optimizer(1e-3)
    )


def test_saved_world_meta_and_manifest_fallback(tmp_path, tiny_config):
    state = _tiny_state(tiny_config)
    ddp = DataParallel(create_mesh({"data": 2}, jax.devices()[:2]))
    world = reshard_lib.current_world(ddp)
    path = ckpt_lib.save(state, tmp_path, meta={"world": world})
    assert reshard_lib.saved_world(path) == world
    # consolidated without meta: no world signal (and none needed)
    bare = ckpt_lib.save(state, tmp_path, name="bare")
    assert reshard_lib.saved_world(bare) is None
    # sharded without meta: the manifest's nprocs is the fallback signal
    sharded = ckpt_lib.save_sharded(state, tmp_path, name="noworld")
    assert reshard_lib.saved_world(sharded) == {"nprocs": 1}


def test_sweep_stale_world(tmp_path):
    stale = [
        "heartbeat-p00003.json", "heartbeat-p00007.json",
        "rollback-0001.json", "rollback-0001-ack-p00002.json",
        "rollback-final-drain.json", "preempt-request-p00001.json",
        "preempt-decision.json",
    ]
    for name in stale:
        (tmp_path / name).write_text("{}")
    (tmp_path / "unrelated.txt").write_text("keep me")
    removed = reshard_lib.sweep_stale_world(tmp_path)
    assert sorted(removed) == sorted(stale)
    assert (tmp_path / "unrelated.txt").exists()
    assert not list(tmp_path.glob("heartbeat-*"))
    # missing directory is inert (fresh run, no heartbeat dir yet)
    assert reshard_lib.sweep_stale_world(tmp_path / "nope") == []


def test_copy_overlap_and_overlaps_unit():
    dest = np.zeros((4, 4), np.float32)  # target block at global [2:6, 0:4]
    block = np.arange(12, dtype=np.float32).reshape(3, 4)  # at [4:7, 0:4]
    assert reshard_lib._overlaps([2, 0], [4, 4], [4, 0], [3, 4])
    n = reshard_lib._copy_overlap(dest, [2, 0], block, [4, 0])
    assert n == 8  # rows 4..5 of the global space
    np.testing.assert_array_equal(dest[2:4], block[:2])
    assert dest[:2].sum() == 0
    # disjoint: nothing copied
    assert not reshard_lib._overlaps([0, 0], [2, 4], [4, 0], [3, 4])
    assert reshard_lib._copy_overlap(dest[:2], [0, 0], block, [4, 0]) == 0
    # scalars
    d0 = np.zeros((), np.float32)
    assert reshard_lib._copy_overlap(d0, [], np.float32(7.0), []) == 1
    assert float(d0) == 7.0


# ---------------------------------------------------------------------------
# the reshard pass: shrink / grow / cross-strategy, both formats
# ---------------------------------------------------------------------------


def _assert_exact(restored, reference, sharding_tree=None):
    r = jax.tree_util.tree_leaves(restored)
    s = jax.tree_util.tree_leaves(reference)
    assert len(r) == len(s)
    for a, b in zip(r, s):
        assert tuple(a.shape) == tuple(np.asarray(b).shape)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
    if sharding_tree is not None:
        shardings = jax.tree_util.tree_leaves(
            sharding_tree, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding)
        )
        for a, sh in zip(r, shardings):
            assert a.sharding.is_equivalent_to(sh, a.ndim), (a.sharding, sh)


@pytest.fixture(scope="module")
def fsdp8_sharded_checkpoint(tmp_path_factory, tiny_config):
    """One FSDP@8 state saved in the sharded format — the shrink/grow/
    cross-strategy tests below all reshard from it."""
    tmp = tmp_path_factory.mktemp("reshard_src")
    src = FSDP(create_mesh({"data": 8}))
    state = create_train_state(
        jax.random.PRNGKey(3), tiny_config, make_optimizer(1e-3), src
    )
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(state, src.state_sharding(shapes))
    path = ckpt_lib.save_sharded(
        state, tmp, meta={"world": reshard_lib.current_world(src)}
    )
    return path, state, shapes


def test_reshard_sharded_shrink_cross_strategy(fsdp8_sharded_checkpoint):
    """FSDP@8 -> DDP@4: re-slice ZeRO-3 shards onto a replicated layout at
    half the world — exact values, target placement, streamed blocks."""
    path, state, shapes = fsdp8_sharded_checkpoint
    tgt = DataParallel(create_mesh({"data": 4}, jax.devices()[:4]))
    tsh = tgt.state_sharding(shapes)
    restored, info = reshard_lib.reshard_restore(path, shapes, tsh)
    _assert_exact(restored, state, tsh)
    assert info["format"] == "sharded"
    assert info["bytes_read"] > 0 and info["blocks_read"] > 0


def test_reshard_sharded_same_strategy_rechunk(fsdp8_sharded_checkpoint):
    """FSDP@8 -> FSDP@2: the ZeRO-3 chunking re-derives at the new world
    (min_shard_size + divisibility against 2, not 8) — exact values land
    in the re-derived layout."""
    path, state, shapes = fsdp8_sharded_checkpoint
    tgt = FSDP(create_mesh({"data": 2}, jax.devices()[:2]))
    tsh = tgt.state_sharding(shapes)
    restored, _ = reshard_lib.reshard_restore(path, shapes, tsh)
    _assert_exact(restored, state, tsh)


def test_reshard_consolidated_grow(tmp_path, tiny_config):
    """Consolidated DDP@2 save -> FSDP@8 restore (grow + cross-strategy):
    the world-agnostic msgpack lands sharded at the larger world."""
    src = DataParallel(create_mesh({"data": 2}, jax.devices()[:2]))
    state = create_train_state(
        jax.random.PRNGKey(5), tiny_config, make_optimizer(1e-3), src
    )
    shapes = jax.eval_shape(lambda: state)
    path = ckpt_lib.save(
        state, tmp_path, meta={"world": reshard_lib.current_world(src)}
    )
    tgt = FSDP(create_mesh({"data": 8}))
    tsh = tgt.state_sharding(shapes)
    restored, info = reshard_lib.reshard_restore(path, shapes, tsh)
    _assert_exact(restored, state, tsh)
    assert info["format"] == "consolidated" and info["bytes_read"] > 0


def _split_into_two_proc_checkpoint(src_dir: Path, dest: Path) -> None:
    """Rewrite a 1-process sharded checkpoint as the 2-process layout a
    larger world would have written: the single shard's blocks split
    across shard-00000/shard-00001 by leaf parity, manifest nprocs=2 with
    re-derived checksums. This is the on-disk shape multi-host saves
    produce — which this container cannot run natively (see the PR-2
    multiprocess note)."""
    import hashlib

    manifest = json.loads((src_dir / "manifest.json").read_text())
    blocks = dict(np.load(src_dir / "shard-00000.npz"))
    halves: list[dict] = [{}, {}]
    for key, arr in blocks.items():
        leaf = int(key.partition("|")[0])
        halves[leaf % 2][key] = arr
    dest.mkdir()
    manifest["nprocs"] = 2
    checksums = {}
    for pid, half in enumerate(halves):
        shard = dest / f"shard-{pid:05d}.npz"
        with open(shard, "wb") as f:
            np.savez(f, **half)
        checksums[shard.name] = hashlib.sha256(shard.read_bytes()).hexdigest()
    manifest["checksums"] = checksums
    (dest / "manifest.json").write_text(json.dumps(manifest))
    meta = src_dir / "resume.json"
    if meta.exists():
        rec = json.loads(meta.read_text())
        rec.setdefault("world", {})["nprocs"] = 2
        (dest / "resume.json").write_text(json.dumps(rec))


def test_restore_from_larger_world_nprocs(tmp_path, tiny_config):
    """Satellite: the newest checkpoint was saved by a LARGER world (more
    processes) than the current one. `latest_good` must resolve it (its
    integrity check reads the manifest's world, not the current one),
    `restore_any` must read every recorded shard file, and the reshard
    pass must land it exactly on the smaller world's shardings."""
    src = FSDP(create_mesh({"data": 8}))
    state = create_train_state(
        jax.random.PRNGKey(7), tiny_config, make_optimizer(1e-3), src
    )
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(
        state.replace(step=state.step * 0 + 12), src.state_sharding(shapes)
    )
    one_proc = ckpt_lib.save_sharded(state, tmp_path, name="tmp-oneproc")
    big = tmp_path / "checkpoint-step000000012.sharded"
    _split_into_two_proc_checkpoint(one_proc, big)
    shutil.rmtree(one_proc)
    assert json.loads((big / "manifest.json").read_text())["nprocs"] == 2
    assert ckpt_lib.verify_checkpoint(big) == (True, "verified")
    assert ckpt_lib.latest_good(tmp_path) == big
    assert reshard_lib.saved_world(big)["nprocs"] == 2

    tgt = DataParallel(create_mesh({"data": 4}, jax.devices()[:4]))
    tsh = tgt.state_sharding(shapes)
    restored, info = reshard_lib.reshard_restore(big, shapes, tsh)
    _assert_exact(restored, state, tsh)
    assert info["blocks_read"] > 0
    # restore_any (the pre-elastic reader) also reads every recorded shard
    via_any, was_sharded = ckpt_lib.restore_any(big, shapes, tsh)
    assert was_sharded
    _assert_exact(via_any, state)


def test_reshard_missing_block_fails_named(tmp_path, tiny_config):
    """A shard file whose blocks vanish must fail the assembly coverage
    check with a named leaf, not restore zeros silently."""
    state = _tiny_state(tiny_config, seed=9)
    path = ckpt_lib.save_sharded(state, tmp_path)
    blocks = dict(np.load(path / "shard-00000.npz"))
    dropped = next(iter(blocks))
    del blocks[dropped]
    with open(path / "shard-00000.npz", "wb") as f:
        np.savez(f, **blocks)
    shapes = jax.eval_shape(lambda: state)
    sd = SingleDevice()
    with pytest.raises(ValueError, match="assembled"):
        reshard_lib.reshard_restore(path, shapes, sd.state_sharding(shapes))


# ---------------------------------------------------------------------------
# verify_checkpoint: world/geometry cross-check (satellite)
# ---------------------------------------------------------------------------


def test_verify_geometry_catches_foreign_world_manifest(tmp_path, tiny_config):
    """A manifest paired with shard files from a DIFFERENT world must fail
    verification with a named detail even when nothing is bit-corrupt:
    the per-file checksums prove each shard is intact, the geometry check
    proves the set belongs to THIS manifest's world."""
    state = _tiny_state(tiny_config)
    path = ckpt_lib.save_sharded(state, tmp_path)
    manifest = json.loads((path / "manifest.json").read_text())
    # shrink one leaf's recorded global shape: the shards now describe a
    # bigger world than the manifest claims
    victim = next(
        i for i, l in enumerate(manifest["leaves"]) if len(l["shape"]) >= 1
        and l["shape"][0] > 1
    )
    manifest["leaves"][victim]["shape"][0] -= 1
    (path / "manifest.json").write_text(json.dumps(manifest))
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert not ok and "different world" in detail
    assert manifest["paths"][victim] in detail

    # legacy manifests (no checksums) get the same geometry protection
    del manifest["checksums"]
    (path / "manifest.json").write_text(json.dumps(manifest))
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert not ok and "different world" in detail


def test_verify_geometry_catches_missing_elements(tmp_path, tiny_config):
    """Coverage: a manifest claiming more processes than contributed
    blocks (a stale shard swap) fails with the per-leaf element count."""
    state = _tiny_state(tiny_config, seed=2)
    path = ckpt_lib.save_sharded(state, tmp_path)
    import hashlib

    # drop one block from the shard, refresh its checksum so only the
    # geometry check can notice
    blocks = dict(np.load(path / "shard-00000.npz"))
    del blocks[next(iter(blocks))]
    shard = path / "shard-00000.npz"
    with open(shard, "wb") as f:
        np.savez(f, **blocks)
    manifest = json.loads((path / "manifest.json").read_text())
    manifest["checksums"][shard.name] = hashlib.sha256(
        shard.read_bytes()
    ).hexdigest()
    (path / "manifest.json").write_text(json.dumps(manifest))
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert not ok and "elements" in detail and "different world" in detail


def test_verify_geometry_accepts_honest_checkpoints(tmp_path, tiny_config):
    state = _tiny_state(tiny_config, seed=4)
    path = ckpt_lib.save_sharded(state, tmp_path)
    assert ckpt_lib.verify_checkpoint(path) == (True, "verified")


def test_duplicate_blocks_rejected_by_verify_and_reshard(tmp_path, tiny_config):
    """A duplicate (leaf, starts) block across shard files could mask a
    missing block EXACTLY under element-count coverage (two same-size
    blocks: one duplicated, one absent) and would silently restore
    uninitialized memory — both the geometry check and the reshard pass
    must reject it by identity, not by count."""
    import hashlib

    state = _tiny_state(tiny_config, seed=6)
    path = ckpt_lib.save_sharded(state, tmp_path)
    manifest = json.loads((path / "manifest.json").read_text())
    blocks = dict(np.load(path / "shard-00000.npz"))
    keys = sorted(blocks)
    dup, drop = next(
        (a, b) for a in keys for b in keys
        if a != b and blocks[a].shape == blocks[b].shape
    )
    halves = [
        {k: v for k, v in blocks.items() if k != drop},  # `drop` missing
        {dup: blocks[dup]},  # ... masked by a same-size duplicate of `dup`
    ]
    manifest["nprocs"] = 2
    manifest["checksums"] = {}
    for pid, half in enumerate(halves):
        shard = path / f"shard-{pid:05d}.npz"
        with open(shard, "wb") as f:
            np.savez(f, **half)
        manifest["checksums"][shard.name] = hashlib.sha256(
            shard.read_bytes()
        ).hexdigest()
    (path / "manifest.json").write_text(json.dumps(manifest))
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert not ok and "duplicate block" in detail
    shapes = jax.eval_shape(lambda: state)
    sd = SingleDevice()
    with pytest.raises(ValueError, match="duplicate block"):
        reshard_lib.reshard_restore(path, shapes, sd.state_sharding(shapes))


# ---------------------------------------------------------------------------
# --keep_checkpoints retention (satellite)
# ---------------------------------------------------------------------------


def _fake_state(step: int):
    from flax import struct

    @struct.dataclass
    class S:
        step: int
        w: np.ndarray

    return S(step=step, w=np.arange(8, dtype=np.float32) + step)


def test_prune_checkpoints_keeps_newest_k(tmp_path):
    for step in (2, 4, 6, 8, 10):
        ckpt_lib.save(_fake_state(step), tmp_path, meta={"step": step})
    removed = ckpt_lib.prune_checkpoints(tmp_path, keep=2)
    assert sorted(removed) == [
        "checkpoint-step000000002.msgpack",
        "checkpoint-step000000004.msgpack",
        "checkpoint-step000000006.msgpack",
    ]
    steps = [ckpt_lib._step_of(p) for p in ckpt_lib.all_checkpoints(tmp_path)]
    assert steps == [8, 10]
    # sidecars went with their blobs
    assert not list(tmp_path.glob("checkpoint-step000000002.*"))
    # idempotent
    assert ckpt_lib.prune_checkpoints(tmp_path, keep=2) == []
    with pytest.raises(ValueError):
        ckpt_lib.prune_checkpoints(tmp_path, keep=0)


def test_prune_never_touches_quarantined_timelines(tmp_path):
    """The quarantine interaction: checkpoints renamed aside by a rollback
    are forensic evidence — retention must never delete them, and they
    must not count against the keep budget."""
    for step in (2, 4, 6, 8, 10):
        ckpt_lib.save(_fake_state(step), tmp_path)
    eng = RecoveryEngine(tmp_path, max_rollbacks=3)
    plan = eng.plan("nan", anomaly_step=11, window=4)  # target step 6
    quarantined = eng.quarantine(plan)  # steps 8, 10 renamed aside
    assert len(quarantined) == 2
    removed = ckpt_lib.prune_checkpoints(tmp_path, keep=1)
    # published world is now {2, 4, 6}: keep 6, drop 2 and 4
    assert sorted(removed) == [
        "checkpoint-step000000002.msgpack",
        "checkpoint-step000000004.msgpack",
    ]
    assert [ckpt_lib._step_of(p) for p in ckpt_lib.all_checkpoints(tmp_path)] == [6]
    # both quarantined checkpoints still on disk, untouched
    assert len(list(tmp_path.glob("*.quarantined-0001"))) >= 2


def test_prune_protects_latest_good_when_kept_are_corrupt(tmp_path):
    for step in (2, 4, 6, 8):
        ckpt_lib.save(_fake_state(step), tmp_path)
    # corrupt the two NEWEST (the keep window at keep=2): latest_good now
    # resolves to step 4, which must survive the prune
    for step in (6, 8):
        bad = tmp_path / f"checkpoint-step{step:09d}.msgpack"
        bad.write_bytes(b"bitrot" + bad.read_bytes()[6:])
    removed = ckpt_lib.prune_checkpoints(tmp_path, keep=2)
    assert removed == ["checkpoint-step000000002.msgpack"]
    with pytest.warns(UserWarning):
        assert ckpt_lib._step_of(ckpt_lib.latest_good(tmp_path)) == 4


# ---------------------------------------------------------------------------
# chaos resize@N:M grammar + engine
# ---------------------------------------------------------------------------


def test_chaos_resize_spec_parses_and_validates():
    entries = chaos_lib.parse_spec("resize@6:4")
    assert entries == [{"kind": "resize", "at": 6, "param": 4.0}]
    for bad in ("resize@6", "resize@6:0", "resize@6:2.5"):
        with pytest.raises(chaos_lib.ChaosSpecError, match="resize"):
            chaos_lib.parse_spec(bad)


def test_chaos_resize_fires_sigterm_and_records_target():
    import jax.numpy as jnp

    eng = chaos_lib.ChaosEngine("resize@5:4")
    assert eng.resize_target is None  # set when the fault FIRES
    caught = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: caught.append(s))
    try:
        state = {"w": jnp.zeros(3)}
        _, _, fired = eng.on_step(4, state, jnp.float32(1.0))
        assert not fired and not caught
        s, _, fired = eng.on_step(5, state, jnp.float32(1.0))
        assert s is state  # resize never mutates state in-process
        assert fired[0]["fault"] == "resize" and fired[0]["to"] == 4
        assert caught == [signal.SIGTERM]
        assert eng.resize_target == 4
        # fire-once, like every step-indexed fault
        _, _, fired = eng.on_step(5, state, jnp.float32(1.0))
        assert not fired and len(caught) == 1
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# heartbeat: stale larger-world beats never poison divergence
# ---------------------------------------------------------------------------


def test_divergence_ignores_beats_beyond_world(tmp_path):
    from tpukit.obs.heartbeat import Heartbeat

    h0 = Heartbeat(tmp_path, process_index=0, process_count=2)
    h1 = Heartbeat(tmp_path, process_index=1, process_count=2)
    h0.beat(8, checksum="aaaa", checksum_step=8)
    h1.beat(8, checksum="aaaa", checksum_step=8)
    # a stale beat from rank 7 of a previous 8-process incarnation, at the
    # same step with a different checksum — landed after the resize sweep
    (tmp_path / "heartbeat-p00007.json").write_text(
        json.dumps({"process": 7, "step": 8, "time": 0.0,
                    "checksum": "ffff", "checksum_step": 8})
    )
    assert h0.check_divergence() == []
    # the guard is scoped to real multi-process worlds: a single-process
    # reader keeps comparing every beat (the established fake-peer test
    # harness pattern, tests/test_flightrec.py divergence_run)
    solo = Heartbeat(tmp_path, process_index=0, process_count=1)
    assert solo.check_divergence() != []


# ---------------------------------------------------------------------------
# fit() end to end: resize@N:M -> preempt-save -> elastic resume
# ---------------------------------------------------------------------------

TINY = dict(
    epochs=1, sequence_length=33, dim=32, head_dim=8, heads=4, num_layers=2,
    learning_rate=1e-3, dataset_slice="200", num_workers=0, disable_amp=True,
    seed=0,
)
# 200 rows at global batch 8 = 25 steps; resize@6:4 preempt-saves at step 6.


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compile_cache():
    """Container jaxlib 0.4.37 workaround: deserializing persistent-cache
    executables for a SECOND mesh size in one process corrupts the heap —
    the next MLIR lowering segfaults. Reproduced WITHOUT any elastic code
    (a plain mesh-8 fit followed by a mesh-4 `--resume latest` fit, cache
    on: crash 3/3; cache off: clean 3/3), so this is the runtime, not the
    reshard pass. Real elastic relaunches are separate processes (the CI
    elastic-resize lane drives the recipe CLI twice, each with its own
    cache, and is unaffected) — only this in-process test harness ever
    runs two mesh sizes under one warm cache. Disable the cache for the
    module; restore the conftest setting after."""
    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()  # drop the once-per-process "cache used" latch
    except Exception:
        pass
    yield
    jax.config.update("jax_enable_compilation_cache", prev)
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def _run_fit(tmp, log_name, strategy_fn, **overrides):
    from tpukit.flags import TrainFlags
    from tpukit.train import fit

    flags = TrainFlags(**{**TINY, "metrics_log": str(tmp / log_name), **overrides})
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        result = fit(flags, strategy_fn())
    finally:
        os.chdir(cwd)
    records = [
        json.loads(line) for line in (tmp / log_name).read_text().splitlines()
    ]
    return result, records


@pytest.fixture(scope="module")
def elastic_resume_run(tmp_path_factory):
    """The acceptance scenario: resize@6:4 preempt-saves a mesh-8 DDP run
    at step 6 (exit 75 semantics); the relaunch at mesh-4 (same GLOBAL
    batch: batch_size doubles as shards halve) reshards and completes; an
    unresized mesh-8 control resumes the same checkpoint for parity."""
    tmp = tmp_path_factory.mktemp("elastic_fit")
    hb = tmp / "hb"
    hb.mkdir()
    (hb / "heartbeat-p00007.json").write_text(
        '{"process": 7, "step": 99, "time": 0}'
    )
    (hb / "rollback-0001.json").write_text('{"seq": 1}')
    with pytest.raises(Preempted):
        _run_fit(
            tmp, "run1.jsonl",
            lambda: DataParallel(create_mesh({"data": 8})),
            batch_size=1, chaos_spec="resize@6:4",
        )
    shutil.copytree(tmp / "checkpoints", tmp / "ck_saved")
    resized, rz_records = _run_fit(
        tmp, "run2.jsonl",
        lambda: DataParallel(create_mesh({"data": 4}, jax.devices()[:4])),
        batch_size=2, resume="latest", heartbeat_dir=str(hb),
    )
    control = tmp_path_factory.mktemp("elastic_fit_control")
    shutil.copytree(tmp / "ck_saved", control / "checkpoints")
    _, ctrl_records = _run_fit(
        control, "run.jsonl",
        lambda: DataParallel(create_mesh({"data": 8})),
        batch_size=1, resume="latest",
    )
    return tmp, resized, rz_records, ctrl_records


def test_elastic_resume_reshards_and_completes(elastic_resume_run):
    tmp, resized, records, _ = elastic_resume_run
    meta = ckpt_lib.read_meta(
        tmp / "ck_saved" / "checkpoint-step000000006.msgpack"
    )
    assert meta["preempted"] and meta["resize_to"] == 4
    assert meta["world"]["mesh_axes"] == {"data": 8}
    assert meta["world"]["global_batch"] == 8
    rz = [r for r in records if r["kind"] == "resize"]
    assert len(rz) == 1
    assert "device_count 8 -> 4" in rz[0]["mismatch"]
    assert rz[0]["world"]["mesh_axes"] == {"data": 4}
    assert rz[0]["bytes_read"] > 0
    assert sorted(rz[0]["swept"]) == [
        "heartbeat-p00007.json", "rollback-0001.json",
    ]
    assert not (tmp / "hb" / "heartbeat-p00007.json").exists()
    # the run COMPLETED at the resized world: full epoch, validation, the
    # same final step the unresized run would reach
    assert int(jax.device_get(resized.state.step)) == 25
    assert any(r["kind"] == "validation" for r in records)


def test_elastic_resume_loss_parity_with_unresized_control(elastic_resume_run):
    """Topology-change parity: post-resume window losses at mesh-4 track
    the unresized mesh-8 control within the dense tolerance (the global
    batch is held constant, so reduction order across the smaller mesh is
    the only difference)."""
    _, _, records, ctrl_records = elastic_resume_run
    resized = [r["loss"] for r in records if r["kind"] == "train"]
    control = [r["loss"] for r in ctrl_records if r["kind"] == "train"]
    assert resized and len(resized) == len(control)
    np.testing.assert_allclose(resized, control, rtol=0, atol=5e-4)


def test_wrong_world_relaunch_raises(elastic_resume_run, tmp_path):
    """The resize@N:M contract: coming back at any world other than M is
    the test harness NOT testing what it claims — fail loud."""
    src_tmp, _, _, _ = elastic_resume_run
    shutil.copytree(src_tmp / "ck_saved", tmp_path / "checkpoints")
    with pytest.raises(RuntimeError, match="expecting relaunch at 4"):
        _run_fit(
            tmp_path, "bad.jsonl",
            lambda: DataParallel(create_mesh({"data": 2}, jax.devices()[:2])),
            batch_size=4, resume="latest",
        )


def test_fit_rejects_negative_keep_checkpoints():
    from tpukit.flags import TrainFlags
    from tpukit.train import fit

    with pytest.raises(ValueError, match="keep_checkpoints"):
        fit(
            TrainFlags(**TINY, batch_size=8, keep_checkpoints=-1),
            SingleDevice(),
        )


def test_keep_checkpoints_retention_in_fit(tmp_path):
    """--keep_checkpoints 2 on a 25-step run with checkpoint_every=4:
    periodic saves at 4..24 plus the final save at 25 — only the newest
    two survive, and the JSONL carries the prune audit."""
    _, records = _run_fit(
        tmp_path, "run.jsonl", SingleDevice,
        batch_size=8, checkpoint_every=4, keep_checkpoints=2,
    )
    steps = [
        ckpt_lib._step_of(p)
        for p in ckpt_lib.all_checkpoints(tmp_path / "checkpoints")
    ]
    assert steps == [24, 25]
    prunes = [r for r in records if r["kind"] == "ckpt_prune"]
    assert prunes and prunes[0]["keep"] == 2
    assert sum(len(r["pruned"]) for r in prunes) == 5  # steps 4..20
