"""Native C++ tokenizer (tpukit/native): byte-identical to the Python
WordTokenizer encoder, across the piece classes the regex produces (words,
punctuation runs, leading spaces, whitespace, unknown/unicode fallback)."""

import numpy as np
import pytest

from tpukit import native
from tpukit.data import WordTokenizer, synthetic_stories

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="no C++ toolchain for tpukit/native"
)


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(256))


def _python_encode(tok, texts, max_len):
    ids, mask = [], []
    for t in texts:
        e = tok._encode_one(t)[:max_len]
        ids.append(e + [tok.pad_token_id] * (max_len - len(e)))
        mask.append([1] * len(e) + [0] * (max_len - len(e)))
    return np.asarray(ids, np.int32), np.asarray(mask, np.int32)


def test_native_matches_python_on_corpus(tok):
    texts = synthetic_stories(300, seed=7)
    enc = native.NativeEncoder(tok._id_to_token, tok.unk_token_id)
    ids, mask = enc.encode_batch(texts, 96, tok.pad_token_id)
    ref_ids, ref_mask = _python_encode(tok, texts, 96)
    np.testing.assert_array_equal(ids, ref_ids)
    np.testing.assert_array_equal(mask, ref_mask)


def test_native_edge_cases(tok):
    enc = native.NativeEncoder(tok._id_to_token, tok.unk_token_id)
    texts = [
        "",  # empty
        "   ",  # runs of spaces
        "Hello, world!! 'tis  a--test\nnewline",
        "unicode café — dash",  # multibyte fallback
        "x" * 500,  # truncation of a giant word-run
        'She said "What a big ball!"',
    ]
    ids, mask = enc.encode_batch(texts, 64, tok.pad_token_id, n_threads=2)
    ref_ids, ref_mask = _python_encode(tok, texts, 64)
    np.testing.assert_array_equal(ids, ref_ids)
    np.testing.assert_array_equal(mask, ref_mask)


def test_wordtokenizer_dispatches_to_native(tok):
    """Large padded+truncated batches take the native path and must agree
    with the Python path end-to-end (including decode round-trip)."""
    texts = synthetic_stories(128, seed=9)
    out = tok(texts, padding="max_length", max_length=80, truncation=True)
    assert isinstance(out["input_ids"], np.ndarray)  # native path returned arrays
    small = tok(texts[:2], padding="max_length", max_length=80, truncation=True)
    np.testing.assert_array_equal(np.asarray(out["input_ids"][:2]), np.asarray(small["input_ids"]))
    # decode round-trips through the same vocab
    row = np.asarray(out["input_ids"][0])
    assert tok.decode(row, skip_special_tokens=True) in texts[0]


def test_native_threads_deterministic(tok):
    texts = synthetic_stories(500, seed=11)
    enc = native.NativeEncoder(tok._id_to_token, tok.unk_token_id)
    a, _ = enc.encode_batch(texts, 64, tok.pad_token_id, n_threads=1)
    b, _ = enc.encode_batch(texts, 64, tok.pad_token_id, n_threads=8)
    np.testing.assert_array_equal(a, b)
