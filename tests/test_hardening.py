"""Hardening tests (ADVICE r2 / VERDICT r2 #9): loud multi-host init
failures, checkpoint shape-mismatch diagnostics, stable tokenizer output
types, and the flash-kernel sequence-sharding warning."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpukit import checkpoint as ckpt_lib
from tpukit import mesh as mesh_lib
from tpukit.model import GPTConfig, init_params


# ---------------------------------------------------------------------------
# initialize_runtime must not silently degrade (VERDICT r2 weak #8)
# ---------------------------------------------------------------------------


@pytest.fixture()
def fresh_runtime(monkeypatch):
    monkeypatch.setattr(mesh_lib, "_initialized", False)
    yield
    mesh_lib._initialized = True  # never re-run real init in later tests


def test_initialize_runtime_raises_on_explicit_coordinator(monkeypatch, fresh_runtime):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("connection refused")),
    )
    with pytest.raises(RuntimeError, match="JAX_COORDINATOR_ADDRESS"):
        mesh_lib.initialize_runtime()


def test_initialize_runtime_rejects_half_set_identity_pair(monkeypatch, fresh_runtime):
    """ADVICE r4: only one of JAX_NUM_PROCESSES / JAX_PROCESS_ID set must
    fail with an error NAMING the missing variable — not an opaque failure
    deep inside jax.distributed.initialize."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    called = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda *a, **k: called.append(1))
    with pytest.raises(RuntimeError, match="JAX_PROCESS_ID"):
        mesh_lib.initialize_runtime()
    assert not called  # rejected before touching jax.distributed


def test_initialize_runtime_tolerates_already_initialized(monkeypatch, fresh_runtime):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("distributed.initialize has already been called")
        ),
    )
    mesh_lib.initialize_runtime()  # must not raise
    assert mesh_lib._initialized


# ---------------------------------------------------------------------------
# Restore shape mismatches name vocab_pad_multiple (ADVICE r2 low #3)
# ---------------------------------------------------------------------------


def _params(pad_multiple):
    cfg = GPTConfig(
        dim=16, head_dim=8, heads=2, num_layers=1, vocab_size=97,
        max_position_embeddings=32, vocab_pad_multiple=pad_multiple,
    )
    return init_params(jax.random.PRNGKey(0), cfg)


def test_consolidated_restore_mismatch_names_vocab_padding(tmp_path):
    path = ckpt_lib.save(_params(128), directory=tmp_path, name="padded")
    with pytest.raises(ValueError, match="vocab_pad_multiple"):
        ckpt_lib.restore(_params(1), path)


def test_sharded_restore_mismatch_names_vocab_padding(tmp_path):
    path = ckpt_lib.save_sharded(_params(128), directory=tmp_path, name="padded")
    with pytest.raises(ValueError, match="vocab_pad_multiple"):
        ckpt_lib.restore_sharded(path, _params(1))


# ---------------------------------------------------------------------------
# Tokenizer output type is batch-size independent (ADVICE r2 low #4)
# ---------------------------------------------------------------------------


def test_tokenizer_padded_output_type_stable():
    from tpukit.data import get_tokenizer

    tok = get_tokenizer()
    small = tok(["a cat", "a dog"], padding="max_length", truncation=True, max_length=8)
    large = tok(["a cat sat"] * 80, padding="max_length", truncation=True, max_length=8)
    for enc, n in ((small, 2), (large, 80)):
        ids = np.asarray(enc["input_ids"])
        mask = np.asarray(enc["attention_mask"])
        assert isinstance(enc["input_ids"], np.ndarray)
        assert ids.dtype == np.int32 and ids.shape == (n, 8)
        assert mask.dtype == np.int32 and mask.shape == (n, 8)


# ---------------------------------------------------------------------------
# Flash kernel warns when a sharding would force a sequence all-gather
# (ADVICE r2 low #5)
# ---------------------------------------------------------------------------


def test_flash_batch_head_spec_warns_on_seq_sharding():
    from tpukit.ops.pallas_attention import _batch_head_spec

    mesh = mesh_lib.create_mesh({"seq": 8})
    seq_sharded = NamedSharding(mesh, P(None, None, "seq", None))
    with pytest.warns(UserWarning, match="ring"):
        spec = _batch_head_spec(seq_sharded, 4)
    assert spec == P(None, None, None, None)

    batch_sharded = NamedSharding(mesh, P("seq", None, None, None))
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        spec = _batch_head_spec(batch_sharded, 4)
    assert spec == P("seq", None, None, None)


# ---------------------------------------------------------------------------
# Sharded-save crash/re-save semantics (code-review r3)
# ---------------------------------------------------------------------------


def test_sharded_save_clears_stale_tmp(tmp_path):
    """A crashed save leaves a .tmp dir at the (deterministic) step name;
    the retry must not publish its leftover shard files."""
    stale = tmp_path / "padded.sharded.tmp"
    stale.mkdir(parents=True)
    np.savez(stale / "shard-00099.npz", **{"0|0,0": np.ones((4, 4))})
    params = _params(128)
    path = ckpt_lib.save_sharded(params, directory=tmp_path, name="padded")
    assert not (path / "shard-00099.npz").exists()
    restored = ckpt_lib.restore_sharded(path, params)
    np.testing.assert_array_equal(
        np.asarray(restored["embeddings"]["token"]),
        np.asarray(params["embeddings"]["token"]),
    )


def test_sharded_resave_replaces_contents(tmp_path):
    """Saving again under the same name must publish the NEW data, not
    silently keep the old directory."""
    v1 = _params(128)
    v2 = jax.tree.map(lambda x: x + 1.0, v1)
    ckpt_lib.save_sharded(v1, directory=tmp_path, name="same")
    path = ckpt_lib.save_sharded(v2, directory=tmp_path, name="same")
    restored = ckpt_lib.restore_sharded(path, v1)
    np.testing.assert_array_equal(
        np.asarray(restored["embeddings"]["token"]),
        np.asarray(v2["embeddings"]["token"]),
    )
    assert not path.with_name(path.name + ".tmp").exists()
    assert not path.with_name(path.name + ".old").exists()


def test_uneven_pipeline_checkpoint_cross_strategy_restore(tmp_path):
    """Identity-padded pipeline checkpoints restore into unpadded templates
    (padding sliced off) and vice versa (zero slots appended) — the
    pipe -> single contract survives uneven layer counts."""
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    cfg = GPTConfig(
        dim=16, head_dim=8, heads=2, num_layers=3, vocab_size=97,
        max_position_embeddings=32,
    )
    pipe = Pipeline(create_mesh({"stage": 2}), num_microbatches=2)
    padded = pipe.prepare_params(init_params(jax.random.PRNGKey(0), cfg), cfg)
    assert jax.tree.leaves(padded["layers"])[0].shape[0] == 4

    # padded (4 slots) -> unpadded template (3 layers): padding sliced off
    spath = ckpt_lib.save_sharded(padded, directory=tmp_path, name="padded-layers")
    template = init_params(jax.random.PRNGKey(1), cfg)
    restored = ckpt_lib.restore_sharded(spath, template)
    jax.tree.map(
        lambda r, p: np.testing.assert_array_equal(np.asarray(r), np.asarray(p)[:3]),
        restored["layers"], padded["layers"],
    )

    # unpadded (3 layers) -> padded template (4 slots): zero slots appended
    cpath = ckpt_lib.save(template, directory=tmp_path, name="unpadded")
    restored2 = ckpt_lib.restore(padded, cpath)
    for leaf, src in zip(
        jax.tree.leaves(restored2["layers"]), jax.tree.leaves(template["layers"])
    ):
        np.testing.assert_array_equal(np.asarray(leaf)[:3], np.asarray(src))
        assert (np.asarray(leaf)[3:] == 0).all()
