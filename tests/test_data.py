"""Data pipeline tests: offline fixture determinism, tokenizer round-trip,
transform semantics (pad to max_length, truncation), loader sharding math."""

import numpy as np

from tpukit.data import (
    ArrayDataset,
    WordTokenizer,
    get_dataset,
    get_tokenizer,
    synthetic_stories,
    transform_dataset,
)
from tpukit.loader import DataLoader, distributed_indices


def test_synthetic_corpus_deterministic():
    assert synthetic_stories(10, seed=0) == synthetic_stories(10, seed=0)
    assert synthetic_stories(10, seed=0) != synthetic_stories(10, seed=1)


def test_tokenizer_roundtrip():
    tok = get_tokenizer()
    text = 'One day, Lily went to the park. She said "What a big ball!"'
    out = tok([text])
    decoded = tok.decode(out["input_ids"][0])
    assert decoded == text


def test_tokenizer_unknown_chars_roundtrip():
    tok = WordTokenizer(["hello world"], model_max_length=64)
    text = "zzz qqq 123 !?"
    assert tok.decode(tok([text])["input_ids"][0]) == text


def test_tokenizer_padding_truncation():
    tok = get_tokenizer()
    tok.pad_token_id = 2  # every recipe does this (main-single.py:23)
    out = tok(["One day, Tom saw a cat."], padding="max_length", max_length=16, truncation=True)
    ids, mask = out["input_ids"][0], out["attention_mask"][0]
    assert len(ids) == 16 and len(mask) == 16
    n = sum(mask)
    assert all(i == 2 for i in ids[n:])
    long = tok(["word " * 100], padding="max_length", max_length=8, truncation=True)
    assert len(long["input_ids"][0]) == 8


def test_get_dataset_slicing():
    train, val = get_dataset(slice_size="50%")
    train_full, _ = get_dataset()
    assert len(train) == len(train_full) // 2
    train_n, _ = get_dataset(slice_size=100)
    assert len(train_n) == 100
    assert len(val) > 0


def test_transform_dataset():
    tok = get_tokenizer()
    tok.pad_token_id = 2
    train, _ = get_dataset(slice_size=32)
    ds = transform_dataset(train, tok, max_length=64)
    assert isinstance(ds, ArrayDataset)
    assert ds.input_ids.shape == (32, 64)
    assert ds.attention_mask.shape == (32, 64)
    row = ds[0]
    assert row["input_ids"].shape == (64,)


def test_distributed_indices_partition():
    """Twin of DistributedSampler: ranks partition (a padded copy of) the
    dataset; same seed+epoch -> same permutation across ranks."""
    n, world = 103, 8
    all_idx = [distributed_indices(n, world, r, shuffle=True, seed=7, epoch=3) for r in range(world)]
    lens = {len(a) for a in all_idx}
    assert lens == {13}  # ceil(103/8)
    flat = np.concatenate(all_idx)
    assert set(flat.tolist()) == set(range(n))  # covers everything (with wrap-padding)


def test_distributed_indices_epoch_reshuffle():
    a = distributed_indices(64, 4, 0, shuffle=True, seed=0, epoch=0)
    b = distributed_indices(64, 4, 0, shuffle=True, seed=0, epoch=1)
    assert not np.array_equal(a, b)


def test_dataloader_batching():
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3 == len(loader)
    assert batches[0]["input_ids"].shape == (4, 4)
    assert batches[2]["input_ids"].shape == (2, 4)  # drop_last=False default

    loader = DataLoader(ds, batch_size=4, shuffle=True, seed=0)
    loader.set_epoch(0)
    e0 = np.concatenate([b["input_ids"] for b in loader])
    loader.set_epoch(1)
    e1 = np.concatenate([b["input_ids"] for b in loader])
    assert not np.array_equal(e0, e1)
    assert set(map(tuple, e0)) == set(map(tuple, e1))
