"""Data pipeline tests: offline fixture determinism, tokenizer round-trip,
transform semantics (pad to max_length, truncation), loader sharding math."""

import numpy as np

from tpukit.data import (
    ArrayDataset,
    WordTokenizer,
    get_dataset,
    get_tokenizer,
    synthetic_stories,
    transform_dataset,
)
from tpukit.loader import DataLoader, distributed_indices


def test_synthetic_corpus_deterministic():
    assert synthetic_stories(10, seed=0) == synthetic_stories(10, seed=0)
    assert synthetic_stories(10, seed=0) != synthetic_stories(10, seed=1)


def test_tokenizer_roundtrip():
    tok = get_tokenizer()
    text = 'One day, Lily went to the park. She said "What a big ball!"'
    out = tok([text])
    decoded = tok.decode(out["input_ids"][0])
    assert decoded == text


def test_tokenizer_unknown_chars_roundtrip():
    tok = WordTokenizer(["hello world"], model_max_length=64)
    text = "zzz qqq 123 !?"
    assert tok.decode(tok([text])["input_ids"][0]) == text


def test_tokenizer_padding_truncation():
    tok = get_tokenizer()
    tok.pad_token_id = 2  # every recipe does this (main-single.py:23)
    out = tok(["One day, Tom saw a cat."], padding="max_length", max_length=16, truncation=True)
    ids, mask = out["input_ids"][0], out["attention_mask"][0]
    assert len(ids) == 16 and len(mask) == 16
    n = sum(mask)
    assert all(i == 2 for i in ids[n:])
    long = tok(["word " * 100], padding="max_length", max_length=8, truncation=True)
    assert len(long["input_ids"][0]) == 8


def test_get_dataset_slicing():
    train, val = get_dataset(slice_size="50%")
    train_full, _ = get_dataset()
    assert len(train) == len(train_full) // 2
    train_n, _ = get_dataset(slice_size=100)
    assert len(train_n) == 100
    assert len(val) > 0


def test_transform_dataset():
    tok = get_tokenizer()
    tok.pad_token_id = 2
    train, _ = get_dataset(slice_size=32)
    ds = transform_dataset(train, tok, max_length=64)
    assert isinstance(ds, ArrayDataset)
    assert ds.input_ids.shape == (32, 64)
    assert ds.attention_mask.shape == (32, 64)
    row = ds[0]
    assert row["input_ids"].shape == (64,)


def test_distributed_indices_partition():
    """Twin of DistributedSampler: ranks partition (a padded copy of) the
    dataset; same seed+epoch -> same permutation across ranks."""
    n, world = 103, 8
    all_idx = [distributed_indices(n, world, r, shuffle=True, seed=7, epoch=3) for r in range(world)]
    lens = {len(a) for a in all_idx}
    assert lens == {13}  # ceil(103/8)
    flat = np.concatenate(all_idx)
    assert set(flat.tolist()) == set(range(n))  # covers everything (with wrap-padding)


def test_distributed_indices_epoch_reshuffle():
    a = distributed_indices(64, 4, 0, shuffle=True, seed=0, epoch=0)
    b = distributed_indices(64, 4, 0, shuffle=True, seed=0, epoch=1)
    assert not np.array_equal(a, b)


def test_dataloader_global_real_row_counts():
    """The precomputed global schedule (throughput meter, VERDICT r4 #6)
    must equal the sum of every rank's per-batch real_rows, for ragged
    dataset sizes, any epoch shuffle, and both pad modes."""
    n = 61  # odd over 2 ranks: ranks end with differing real counts
    ds = ArrayDataset(
        np.arange(4 * n).reshape(n, 4).astype(np.int32),
        np.ones((n, 4), dtype=np.int32),
    )
    for pad_mode in ("wrap", "empty"):
        loaders = [
            DataLoader(
                ds, batch_size=8, shuffle=True, seed=3, num_replicas=2,
                rank=r, pad_to_batch=True, pad_mode=pad_mode,
            )
            for r in range(2)
        ]
        for epoch in (0, 2):
            for ld in loaders:
                ld.set_epoch(epoch)
            expected = None
            for ld in loaders:
                per = np.array([b["real_rows"] for b in ld])
                expected = per if expected is None else expected + per
            got = loaders[0].global_real_row_counts()
            np.testing.assert_array_equal(got, expected)
            assert int(got.sum()) == n  # every original row counted once


def test_dataloader_batching():
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3 == len(loader)
    assert batches[0]["input_ids"].shape == (4, 4)
    assert batches[2]["input_ids"].shape == (2, 4)  # drop_last=False default

    loader = DataLoader(ds, batch_size=4, shuffle=True, seed=0)
    loader.set_epoch(0)
    e0 = np.concatenate([b["input_ids"] for b in loader])
    loader.set_epoch(1)
    e1 = np.concatenate([b["input_ids"] for b in loader])
    assert not np.array_equal(e0, e1)
    assert set(map(tuple, e0)) == set(map(tuple, e1))


def test_dataloader_pad_to_batch_full_shapes():
    """ADVICE r1: with pad_to_batch every batch has the full static shape, so
    the jitted step never recompiles on a ragged final batch and Pipeline's
    micro-batch divisor always holds."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=False, pad_to_batch=True)
    batches = list(loader)
    assert len(batches) == 3
    assert all(b["input_ids"].shape == (4, 4) for b in batches)
    # padding wraps from the start of the index list
    np.testing.assert_array_equal(
        batches[2]["input_ids"][2:], ds.input_ids[:2]
    )


def test_dataloader_pad_smaller_than_batch():
    """np.resize tiling: a dataset smaller than the pad still fills the batch."""
    ds = ArrayDataset(
        np.arange(24).reshape(6, 4).astype(np.int32),
        np.ones((6, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=16, shuffle=False, pad_to_batch=True)
    (batch,) = list(loader)
    assert batch["input_ids"].shape == (16, 4)


def test_dataloader_pad_mode_empty():
    """Validation padding: all-ignore rows, not wrap-duplicates, so eval
    metrics are not skewed by repeated samples."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=False, pad_to_batch=True,
                        pad_mode="empty", pad_fill=2)
    batches = list(loader)
    assert all(b["input_ids"].shape == (4, 4) for b in batches)
    assert (batches[2]["input_ids"][2:] == 2).all()
    assert (batches[2]["attention_mask"][2:] == 0).all()

    from tpukit.batching import prepare_batch

    _, targets = prepare_batch(batches[2], pad_id=2)
    assert (targets[2:] == -100).all()


def test_dataloader_pad_distributed_path():
    """pad_to_batch applies after DistributedSampler-style sharding too."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=False, pad_to_batch=True,
                        num_replicas=2, rank=0)
    batches = list(loader)  # 5 rows for rank 0 -> pad to 8
    assert len(batches) == 2
    assert all(b["input_ids"].shape == (4, 4) for b in batches)


def test_dataloader_empty_pad_distributed_no_duplicates():
    """pad_mode='empty' with num_replicas>1 must not wrap-duplicate samples:
    the even-split padding uses all-ignore sentinel rows instead."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    seen = []
    for rank in range(4):
        loader = DataLoader(ds, batch_size=4, shuffle=False, pad_to_batch=True,
                            pad_mode="empty", pad_fill=2, num_replicas=4, rank=rank)
        for b in loader:
            assert b["input_ids"].shape == (4, 4)
            real = b["attention_mask"].any(axis=1)
            seen.extend(map(tuple, b["input_ids"][real]))
    assert len(seen) == 10  # every sample exactly once, no duplicates
    assert len(set(seen)) == 10


def test_loader_real_rows_ragged():
    """Honest token accounting (VERDICT r2 #8): wrap-padded rows are flagged,
    so an epoch's real_rows sum equals the dataset size, not the padded one."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    loader = DataLoader(ds, batch_size=4, shuffle=True, pad_to_batch=True)
    batches = list(loader)
    assert all(b["input_ids"].shape == (4, 4) for b in batches)  # still full
    assert sum(b["real_rows"] for b in batches) == 10  # not 12


def test_loader_real_rows_distributed():
    """Across ranks, wrap-duplicates from the even-split padding are not
    counted: the global real_rows sum is the dataset size."""
    ds = ArrayDataset(
        np.arange(40).reshape(10, 4).astype(np.int32),
        np.ones((10, 4), dtype=np.int32),
    )
    total = 0
    for rank in range(4):
        loader = DataLoader(ds, batch_size=3, shuffle=True, pad_to_batch=True,
                            num_replicas=4, rank=rank)
        total += sum(b["real_rows"] for b in loader)
    assert total == 10


# ---------------------------------------------------------------------------
# HuggingFace-branch tests (VERDICT r3 #5): `datasets` is installed as a test
# extra, so the HF code paths in transform_dataset/get_dataset — dead code in
# offline training runs — execute for real against hub-free local datasets.
# ---------------------------------------------------------------------------

import pytest

try:
    import datasets
except ImportError:  # offline/minimal env: fixture-path tests above still run
    datasets = None

requires_datasets = pytest.mark.skipif(
    datasets is None, reason="datasets package not installed"
)



@requires_datasets
def test_transform_dataset_hf_map_branch_matches_fixture():
    """The real `datasets.Dataset.map` branch (tpukit/data.py map+set_format,
    twin of reference data.py:23-36) must produce byte-identical arrays to
    the fixture branch on the same texts."""
    texts = synthetic_stories(24, seed=3)
    tok = get_tokenizer()
    hf_ds = datasets.Dataset.from_dict({"text": texts})
    assert hasattr(hf_ds, "map")

    via_hf = transform_dataset(hf_ds, tok, max_length=48, num_proc=1)
    via_fixture = transform_dataset(
        __import__("tpukit.data", fromlist=["ListDataset"]).ListDataset(texts),
        tok,
        max_length=48,
    )
    np.testing.assert_array_equal(via_hf.input_ids, via_fixture.input_ids)
    np.testing.assert_array_equal(via_hf.attention_mask, via_fixture.attention_mask)
    assert via_hf.input_ids.dtype == np.int32
    assert via_hf.input_ids.shape == (24, 48)


@requires_datasets
def test_transform_dataset_hf_map_multiproc():
    """num_proc > 1 forks dataset.map workers — the tokenizer must pickle
    and the ragged->dense conversion must survive the sharded map."""
    texts = synthetic_stories(32, seed=4)
    tok = get_tokenizer()
    hf_ds = datasets.Dataset.from_dict({"text": texts})
    out = transform_dataset(hf_ds, tok, max_length=32, num_proc=2)
    ref = transform_dataset(hf_ds, tok, max_length=32, num_proc=1)
    np.testing.assert_array_equal(out.input_ids, ref.input_ids)


@requires_datasets
def test_hf_slice_string_semantics_match_parse_slice(tmp_path):
    """tpukit builds `train[:{slice_size}]` split strings for load_dataset
    (twin of reference data.py:11) and mirrors them with _parse_slice on the
    fixture path; the two must agree with REAL datasets slicing — verified
    against a local json dataset, no hub."""
    import json as json_lib

    from tpukit.data import _parse_slice

    texts = synthetic_stories(40, seed=5)
    data_file = tmp_path / "train.json"
    data_file.write_text(
        "\n".join(json_lib.dumps({"text": t}) for t in texts)
    )

    for slice_size in ("25%", "50%", "10", "1000"):
        real = datasets.load_dataset(
            "json",
            data_files={"train": str(data_file)},
            split=f"train[:{slice_size}]",
        )
        assert len(real) == _parse_slice(len(texts), slice_size), slice_size


@requires_datasets
def test_get_dataset_hf_branch_with_local_cache(tmp_path, monkeypatch):
    """get_dataset's HF branch end-to-end: a dataset saved where
    load_dataset can find it loads WITHOUT the fixture fallback and honors
    the slice string."""
    texts = synthetic_stories(20, seed=6)
    ds = datasets.DatasetDict(
        {
            "train": datasets.Dataset.from_dict({"text": texts}),
            "validation": datasets.Dataset.from_dict({"text": texts[:5]}),
        }
    )
    local = tmp_path / "tinystories_local"
    ds.save_to_disk(str(local))

    import tpukit.data as data_mod

    real_load = datasets.load_dataset

    def fake_load(name, split=None, **kw):
        d = datasets.load_from_disk(str(local))
        if split is None:
            return d
        base, _, sl = split.partition("[")
        out = d[base]
        if sl:
            spec = sl.rstrip("]")[1:]  # ":N" or ":P%"
            out = out.select(range(_parse_slice(len(out), spec)))
        return out

    from tpukit.data import _parse_slice

    monkeypatch.setattr(datasets, "load_dataset", fake_load)
    try:
        train, validation = data_mod.get_dataset(name="local", slice_size=10)
        assert len(train) == 10 and len(validation) == 5
        assert hasattr(train, "map")  # HF object, not the fixture
        tok = get_tokenizer()
        arr = transform_dataset(train, tok, max_length=32, num_proc=1)
        assert arr.input_ids.shape == (10, 32)
    finally:
        monkeypatch.setattr(datasets, "load_dataset", real_load)
