"""Fused head+CE kernel equivalence vs the unfused apply_head +
cross_entropy_sum + masked_accuracy path (the reference semantics,
main-single.py:95-96,128-131). Runs in Pallas interpreter mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.model import GPTConfig, gpt
from tpukit.ops.fused_head_ce import fused_head_ce
from tpukit.ops.layers import cross_entropy_sum, masked_accuracy

N, DIM, VOCAB = 200, 32, 300  # N not a tile multiple; vocab pads 300 -> 384


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(N, DIM), jnp.float32)
    v_pad = -(-VOCAB // 128) * 128
    w = jnp.asarray(rng.randn(DIM, v_pad) * 0.1, jnp.float32)
    tgt = rng.randint(0, VOCAB, N).astype(np.int32)
    tgt[::7] = -100  # ignore rows
    return h, w, jnp.asarray(tgt)


def _unfused(h, w, tgt):
    logits = h @ w
    col = jax.lax.broadcasted_iota(jnp.int32, (w.shape[1],), 0)
    logits = jnp.where(col < VOCAB, logits, -1e9)
    loss_sum, count = cross_entropy_sum(logits, tgt)
    acc = masked_accuracy(logits, tgt)
    return logits, loss_sum, count, acc


def test_forward_matches_unfused(setup):
    h, w, tgt = setup
    logits, ref_sum, ref_count, ref_acc = _unfused(h, w, tgt)
    loss_sum, count, correct = fused_head_ce(h, w, tgt, VOCAB, with_accuracy=True)
    np.testing.assert_allclose(float(loss_sum), float(ref_sum), rtol=1e-5)
    assert float(count) == float(ref_count)
    valid = np.asarray(tgt) != -100
    ref_correct = ref_acc * valid.sum() / 100.0
    np.testing.assert_allclose(float(correct), float(ref_correct), atol=0.5)


def test_grads_match_unfused(setup):
    h, w, tgt = setup

    def fused_loss(h, w):
        s, c, _ = fused_head_ce(h, w, tgt, VOCAB)
        return s / jnp.maximum(c, 1.0)

    def unfused_loss(h, w):
        _, s, c, _ = _unfused(h, w, tgt)
        return s / jnp.maximum(c, 1.0)

    gf = jax.grad(fused_loss, argnums=(0, 1))(h, w)
    gu = jax.grad(unfused_loss, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gu[0]), atol=2e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gu[1]), atol=2e-6)
    # vocab-pad columns get zero gradient, exactly as the masked unfused head
    assert (np.asarray(gf[1])[:, VOCAB:] == 0).all()


def test_multi_tile_vocab_matches_unfused(monkeypatch):
    """vocab spanning several vocab tiles — the production shape (GPT-2
    vocab = ~25 tiles). Targets land in tiles >= 1, where a tile-relative/
    global index confusion in the one-hot select returns 0 instead of the
    target logit (caught by review; this test pins the fix)."""
    import tpukit.ops.fused_head_ce as m

    monkeypatch.setattr(m, "_V_BLK", 128)  # 300-vocab -> 3 tiles
    rng = np.random.RandomState(3)
    h = jnp.asarray(rng.randn(64, DIM), jnp.float32)
    v_pad = -(-VOCAB // 128) * 128
    w = jnp.asarray(rng.randn(DIM, v_pad) * 0.1, jnp.float32)
    tgt_np = rng.randint(130, VOCAB, 64).astype(np.int32)  # all in tiles >= 1
    tgt_np[::9] = -100
    tgt = jnp.asarray(tgt_np)

    logits, ref_sum, ref_count, _ = _unfused(h, w, tgt)
    loss_sum, count, correct = fused_head_ce(h, w, tgt, VOCAB, with_accuracy=True)
    np.testing.assert_allclose(float(loss_sum), float(ref_sum), rtol=1e-5)
    assert float(count) == float(ref_count)
    valid = tgt_np != -100
    ref_correct = (np.asarray(jnp.argmax(logits, -1))[valid] == tgt_np[valid]).sum()
    assert float(correct) == float(ref_correct)

    def fused_loss(h, w):
        s, c, _ = fused_head_ce(h, w, tgt, VOCAB)
        return s / jnp.maximum(c, 1.0)

    def unfused_loss(h, w):
        _, s, c, _ = _unfused(h, w, tgt)
        return s / jnp.maximum(c, 1.0)

    gf = jax.grad(fused_loss, argnums=(0, 1))(h, w)
    gu = jax.grad(unfused_loss, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gu[0]), atol=2e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gu[1]), atol=2e-6)


def test_gpt2_scale_vocab_target_logit():
    """Full-size check at a real multi-tile vocab (no monkeypatch): a
    target above _V_BLK must contribute its true logit to the loss."""
    dim, vocab = 16, 5000
    v_pad = -(-vocab // 128) * 128
    h = jnp.ones((8, dim), jnp.float32)
    w = jnp.zeros((dim, v_pad), jnp.float32).at[:, 3000].set(2.0)  # logit 32
    tgt = jnp.full((8,), 3000, jnp.int32)
    loss_sum, count, _ = fused_head_ce(h, w, tgt, vocab)
    # lse ~= log(exp(32) + 4999*exp(0)) ~= 32; loss = lse - 32 ~= 0
    assert float(loss_sum) / float(count) < 1e-3


def test_argmax_tie_break_first_index():
    h = jnp.zeros((8, DIM), jnp.float32)  # all logits equal -> argmax = 0
    w = jnp.zeros((DIM, 128), jnp.float32)
    tgt = jnp.zeros((8,), jnp.int32)
    _, _, correct = fused_head_ce(h, w, tgt, 100, with_accuracy=True)
    assert float(correct) == 8.0  # predicted index 0 == target 0 everywhere


def test_token_sharded_grads_match_unsharded(setup):
    """The custom_partitioning rules: with h/targets sharded over an
    8-device data axis (and w replicated), loss and both grads equal the
    unsharded result — the backward's dw psums local token partials."""
    import tpukit.mesh as mesh_lib
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device mesh")
    h, w, tgt = setup
    n8 = (N // 8) * 8
    h8, tgt8 = h[:n8], tgt[:n8]
    mesh = mesh_lib.create_mesh({"data": 8})

    def loss(h, w, t):
        s, c, _ = fused_head_ce(h, w, t, VOCAB)
        return s / jnp.maximum(c, 1.0)

    ref_l, ref_g = jax.value_and_grad(loss, argnums=(0, 1))(h8, w, tgt8)
    hs = jax.device_put(h8, NamedSharding(mesh, P("data", None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, None)))
    ts = jax.device_put(tgt8, NamedSharding(mesh, P("data")))
    sh_l, sh_g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(hs, ws, ts)
    np.testing.assert_allclose(float(sh_l), float(ref_l), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sh_g[0]), np.asarray(ref_g[0]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(sh_g[1]), np.asarray(ref_g[1]), atol=1e-6)


def test_overfit_multi_tile_vocab():
    """End-to-end semantic guard: a 2-layer model must overfit one repeated
    batch at a MULTI-TILE vocab (here forced via a small _V_BLK). An
    indexing bug anywhere in the fused loss (e.g. a tile-relative target
    select) leaves the loss near log(vocab) and fails this, even when
    per-op equivalence tests are green."""
    import tpukit.ops.fused_head_ce as m
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    orig = m._V_BLK
    m._V_BLK = 128  # vocab 300 -> 3 tiles
    try:
        cfg = GPTConfig(
            dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=300,
            max_position_embeddings=32, compute_dtype=jnp.float32,
        )
        strategy = SingleDevice()
        assert strategy.fused_head
        optimizer = make_optimizer(3e-3)
        state = create_train_state(jax.random.PRNGKey(0), cfg, optimizer)
        shapes = jax.eval_shape(lambda: state)
        step, _, sh = make_step_fns(cfg, optimizer, strategy, shapes)
        state = jax.device_put(state, sh)

        r = np.random.RandomState(0)
        ids = jnp.asarray(r.randint(130, 300, (4, 32)).astype(np.int32))
        batch = {
            "input_ids": ids,
            "position_ids": jnp.broadcast_to(
                jnp.arange(32, dtype=jnp.int32), (4, 32)
            ),
            "mask": jnp.zeros((4, 32), bool),
        }
        tgt = jnp.asarray(r.randint(130, 300, (4, 32)).astype(np.int32))
        first = None
        for _ in range(60):
            state, loss = step(state, batch, tgt)
            if first is None:
                first = float(loss)
        # random-chance loss is log(300) ~ 5.7; memorizing one batch must
        # cut it far below that
        assert first > 5.0
        assert float(loss) < 2.0, f"loss stuck at {float(loss)} (started {first})"
    finally:
        m._V_BLK = orig


def test_strategy_loss_fused_matches_unfused_path():
    """The default strategy loss (fused) equals the same computation through
    gpt.forward + cross_entropy_loss (unfused)."""
    from tpukit.shardings import SingleDevice

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.RandomState(1)
    ids = jnp.asarray(r.randint(0, 97, (4, 32)).astype(np.int32))
    batch = {
        "input_ids": ids,
        "position_ids": jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32), (4, 32)),
        "mask": jnp.zeros((4, 32), bool),
    }
    tgt = jnp.asarray(r.randint(0, 97, (4, 32)).astype(np.int32))

    strategy = SingleDevice()
    assert strategy.fused_head
    fused_loss, fused_acc = strategy.loss_fn(params, cfg, batch, tgt, with_accuracy=True)

    from tpukit.ops.layers import cross_entropy_loss

    logits = gpt.forward(params, cfg, ids, batch["position_ids"], batch["mask"])
    ref_loss = cross_entropy_loss(logits, tgt)
    ref_acc = masked_accuracy(logits, tgt)
    np.testing.assert_allclose(float(fused_loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(float(fused_acc), float(ref_acc), atol=1e-3)
