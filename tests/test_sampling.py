"""Greedy generation tests: determinism, EOS stop semantics, fixed-buffer
equivalence with a naive growing-sequence loop (the reference's algorithm,
utils.py:63-87)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpukit.data import get_tokenizer
from tpukit.model import forward
from tpukit.sampling import generate


def _naive_generate_ids(params, cfg, ids, max_new_tokens, eos_id):
    """Direct transcription of the reference loop: grow the sequence, full
    re-forward each step, break on EOS before appending."""
    ids = list(ids)
    for _ in range(max_new_tokens):
        arr = jnp.asarray(np.array(ids, dtype=np.int32))[None]
        pos = jnp.arange(arr.shape[1], dtype=jnp.int32)[None]
        logits = forward(params, cfg, arr, pos)
        new = int(jnp.argmax(logits[0, -1].astype(jnp.float32)))
        if new == eos_id:
            break
        ids.append(new)
    return ids


def test_generate_matches_naive_loop(tiny_config, tiny_params):
    tok = get_tokenizer()
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = __import__("tpukit.model", fromlist=["init_params"]).init_params(
        jax.random.PRNGKey(3), cfg
    )
    prompt = "One day, "
    out = generate(params, cfg, prompt, tok, max_new_tokens=4)

    ids = tok([prompt], truncation=True, max_length=256)["input_ids"][0]
    naive_ids = _naive_generate_ids(params, cfg, ids, 4, tok.eos_token_id)
    assert out == tok.decode(np.array(naive_ids), skip_special_tokens=True)


def test_generate_deterministic(tiny_config, tiny_params):
    tok = get_tokenizer()
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = __import__("tpukit.model", fromlist=["init_params"]).init_params(
        jax.random.PRNGKey(3), cfg
    )
    a = generate(params, cfg, "She said ", tok, max_new_tokens=6)
    b = generate(params, cfg, "She said ", tok, max_new_tokens=6)
    assert a == b
    assert a.startswith("She said ")


def test_generate_prompt_capped_to_position_table(tiny_config, tiny_params):
    """ADVICE r1: a prompt longer than the position table minus the decode
    budget must be truncated, not silently clamp position lookups."""
    from tpukit.data import WordTokenizer, synthetic_stories

    tok = WordTokenizer(synthetic_stories(64))
    long_prompt = " ".join(["the cat sat"] * 200)
    # max_position_embeddings=64, max_new_tokens=20 -> prompt capped at 44
    out = generate(tiny_params, tiny_config, long_prompt, tok, max_new_tokens=20)
    assert isinstance(out, str)

    import pytest

    with pytest.raises(ValueError, match="no room"):
        generate(tiny_params, tiny_config, "hi", tok, max_new_tokens=64)


def test_cached_decode_matches_naive(tiny_config, tiny_params):
    """The KV-cached decode must produce the exact token sequence of the
    naive full-re-forward loop, for several prompts."""
    from tpukit.data import WordTokenizer, synthetic_stories

    tok = WordTokenizer(synthetic_stories(64))
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    from tpukit.model import init_params

    params = init_params(jax.random.PRNGKey(1), cfg)
    for prompt in ["One day, ", "The big brown cat ", "She said "]:
        cached = generate(params, cfg, prompt, tok, max_new_tokens=12, use_cache=True)
        naive = generate(params, cfg, prompt, tok, max_new_tokens=12, use_cache=False)
        assert cached == naive


def test_cached_sampling_matches_uncached_same_seed(tiny_config):
    """Round 11 (ROADMAP #1 first rung; the cached loop raised on
    temperature>0 through round 10 — VERDICT r5 #5): the KV-cached decode
    samples with the SAME per-position key fold as the re-forward loop, so
    a fixed seed must produce the identical token sequence cached and
    uncached — with and without top-k truncation, across seeds."""
    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.model import init_params

    tok = WordTokenizer(synthetic_stories(64))
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(9), cfg)
    for prompt, temp, top_k, seed in [
        ("One day, ", 0.9, 0, 0),
        ("One day, ", 1.3, 5, 7),
        ("The big brown cat ", 0.7, 3, 2),
    ]:
        cached = generate(
            params, cfg, prompt, tok, max_new_tokens=10, use_cache=True,
            temperature=temp, top_k=top_k, seed=seed,
        )
        uncached = generate(
            params, cfg, prompt, tok, max_new_tokens=10, use_cache=False,
            temperature=temp, top_k=top_k, seed=seed,
        )
        assert cached == uncached, (prompt, temp, top_k, seed)

    # cached greedy is the temperature->0 limit of the same loop: the
    # sampling plumbing must not have disturbed it (r5 #4 regression bar)
    greedy_c = generate(params, cfg, "She said ", tok, max_new_tokens=8, use_cache=True)
    greedy_u = generate(params, cfg, "She said ", tok, max_new_tokens=8, use_cache=False)
    assert greedy_c == greedy_u


def test_generate_sampling_modes(tiny_config):
    """Beyond-parity sampling: temperature=0 stays the greedy reference
    path; top_k=1 sampling IS argmax (exact); temperature>0 is
    reproducible under a fixed seed."""
    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.model import init_params

    tok = WordTokenizer(synthetic_stories(64))
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(5), cfg)
    prompt = "One day, "

    greedy = generate(params, cfg, prompt, tok, max_new_tokens=8)
    top1 = generate(
        params, cfg, prompt, tok, max_new_tokens=8, temperature=0.7, top_k=1
    )
    assert top1 == greedy  # a 1-candidate distribution is argmax

    a = generate(params, cfg, prompt, tok, max_new_tokens=8, temperature=1.3, seed=7)
    b = generate(params, cfg, prompt, tok, max_new_tokens=8, temperature=1.3, seed=7)
    assert a == b and a.startswith(prompt)


def test_generate_batch_matches_serial(tiny_config):
    """VERDICT r4 #7: the batched decode (one jitted [N, W] call, per-row
    cursors/EOS) must produce token-for-token the serial per-prompt
    decode — including prompts of different tokenized lengths."""
    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.model import init_params
    from tpukit.sampling import generate_batch

    tok = WordTokenizer(synthetic_stories(64))
    cfg = tiny_config.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(1), cfg)
    prompts = ["One day, ", "The big brown cat sat on a mat ", "She said "]
    batched = generate_batch(params, cfg, prompts, tok, max_new_tokens=12)
    serial = [
        generate(params, cfg, p, tok, max_new_tokens=12, use_cache=False)
        for p in prompts
    ]
    assert batched == serial
    assert generate_batch(params, cfg, [], tok) == []


def test_generate_from_sharded_state(tiny_config):
    """VERDICT r2 #2: generation must work from FSDP- and Pipeline-sharded
    train state via the collective replication path (generate_samples), and
    produce the same text as single-device params."""
    from tpukit.data import get_tokenizer
    from tpukit.mesh import create_mesh
    from tpukit.model import init_params
    from tpukit.pipeline import Pipeline
    from tpukit.shardings import FSDP, SingleDevice
    from tpukit.train import TrainState, generate_samples, make_optimizer

    tok = get_tokenizer()
    cfg = tiny_config.replace(
        vocab_size=tok.vocab_size, max_position_embeddings=64, num_layers=3
    )
    opt = make_optimizer(1e-3)

    def state_for(strategy):
        params = strategy.prepare_params(init_params(jax.random.PRNGKey(3), cfg), cfg)
        sharding = strategy.state_sharding(
            TrainState(params=params, opt_state=opt.init(params), step=jnp.int32(0))
        )
        placed = jax.tree.map(jax.device_put, params, sharding.params)
        return TrainState(params=placed, opt_state=None, step=jnp.int32(0))

    reference = generate_samples(
        SingleDevice(), state_for(SingleDevice()), cfg, tok, max_new_tokens=4
    )
    # 3 layers on 2 stages: also exercises the identity-padded uneven layout
    for strategy in (FSDP(create_mesh({"data": 8})),
                     Pipeline(create_mesh({"stage": 2}))):
        texts = generate_samples(strategy, state_for(strategy), cfg, tok, max_new_tokens=4)
        assert texts == reference, strategy.name
