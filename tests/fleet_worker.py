"""Replica worker process for the crash-tolerance fleet tests (round 24).

Builds the SAME tiny engine as tests/test_fleet.py's fixtures — identical
tokenizer corpus, GPTConfig and PRNGKey(1) params — so a worker process is
token-identical to the in-test control engine, then serves leases from the
ledger directory until the supervisor publishes stop (or the wall budget
runs out: an orphaned worker must exit, not linger past the test).

Usage: python tests/fleet_worker.py FLEET_DIR REPLICA_IDX
"""

import sys
from pathlib import Path

# the script lives in tests/, so the interpreter puts tests/ (not the repo
# root) on sys.path — put tpukit back in reach however we were launched
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    directory, replica = sys.argv[1], int(sys.argv[2])

    import jax
    import jax.numpy as jnp

    # mirror tests/conftest.py's PRNG + cache config: the control engine's
    # params come from the SAME PRNGKey(1) stream, so the worker must draw
    # with the same threefry flavor or parity is dead on arrival
    jax.config.update("jax_threefry_partitionable", True)
    cache = Path(__file__).resolve().parent.parent / ".jax_cache"
    jax.config.update("jax_compilation_cache_dir", str(cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)

    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.model import GPTConfig, init_params
    from tpukit.serve import ServeConfig, ServeEngine
    from tpukit.serve.ledger import serve_from_ledger

    tok = WordTokenizer(synthetic_stories(64))
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=10,
                        window_steps=8)
    engine = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                         replica=replica)
    comps = serve_from_ledger(engine, directory, replica, max_wall_s=240.0)
    print(f"replica {replica}: served {len(comps)} completions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
