"""Round-9 recovery: the detect→recover loop, tested end to end.

Rounds 6-8 could DETECT every major failure class (NaN/spike sentinels,
hang watchdog, heartbeat stragglers, cross-replica divergence) but the only
response was checkpoint-then-abort. This file tests the round-9 response
machinery:

  - the chaos fault-injection harness (tpukit/chaos.py): spec grammar,
    exact-step firing, occurrence-indexed I/O faults, fire-once semantics;
  - jittered-exponential retry/backoff for transient host I/O
    (tpukit/retry.py): budget, fail-loud, never-retry-programming-errors,
    observer events;
  - checkpoint integrity (tpukit/checkpoint.py): sha256 sidecars /
    manifest checksums at save, corrupt/partial checkpoints skipped by
    `latest`/`latest_any`/`latest_good` with a warning, resume-metadata
    sidecars;
  - the recovery engine (tpukit/recovery.py): rollback planning against
    the budget, quarantine of the abandoned timeline, the collective-
    decision coordinator, the preemption guard, the exit-code contract;
  - fit() end to end: an injected NaN rolls the run back to the last good
    checkpoint and the post-recovery trajectory is BIT-EXACT with an
    uninjected control run restored at the same checkpoint (the chaos
    `skip@N` stream fast-forward reproduces the recovered run's input
    position); budget 0 escalates to the documented abort; an injected
    SIGTERM checkpoints gracefully and `--resume latest` continues to a
    bit-exact final state; injected transient I/O faults are absorbed by
    the backoff wrapper and leave `retry` records;
  - HLO invariance: the chaos flag off/on leaves the compiled train step
    byte-identical (all injection is host-side).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
from pathlib import Path

import jax
import numpy as np
import pytest

from tpukit import chaos as chaos_lib
from tpukit import checkpoint as ckpt_lib
from tpukit import retry as retry_lib
from tpukit.recovery import (
    EXIT_ANOMALY_ABORT,
    EXIT_CLEAN,
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
    AnomalyAbort,
    Preempted,
    PreemptionGuard,
    RecoveryEngine,
    RollbackBudgetExhausted,
    RollbackCoordinator,
    RollbackPlan,
    TrainingAborted,
    run_recipe,
)

# ---------------------------------------------------------------------------
# chaos: spec grammar + engine semantics
# ---------------------------------------------------------------------------


def test_chaos_spec_parses_all_kinds():
    entries = chaos_lib.parse_spec(
        "nan_loss@120, spike_loss@7:50, sigterm@300,hang@450:2.5,"
        "bitflip@10:1,ckpt_io_fail@2:3,loader_io_fail@1,skip@17"
    )
    by_kind = {e["kind"]: e for e in entries}
    assert by_kind["nan_loss"] == {"kind": "nan_loss", "at": 120, "param": None}
    assert by_kind["spike_loss"]["param"] == 50.0
    assert by_kind["hang"]["param"] == 2.5
    assert by_kind["ckpt_io_fail"] == {"kind": "ckpt_io_fail", "at": 2, "param": 3.0}
    assert by_kind["skip"]["at"] == 17


@pytest.mark.parametrize(
    "bad", ["nan_loss", "nan_loss@", "@12", "frobnicate@3", "nan_loss@x"]
)
def test_chaos_spec_rejects_typos_at_startup(bad):
    """A typo'd fault plan must fail loudly when parsed, not silently never
    fire mid-run."""
    with pytest.raises(chaos_lib.ChaosSpecError, match="chaos spec"):
        chaos_lib.parse_spec(bad)


@pytest.mark.parametrize(
    "bad",
    [
        "hang@10:-2",        # would crash mid-run in time.sleep
        "spike_loss@7:0",    # multiplier 0 is not a spike — never fires
        "ckpt_io_fail@0",    # occurrences are 1-based: @0 never fires
        "loader_io_fail@2:0",  # failure count 0 never fires
    ],
)
def test_chaos_spec_rejects_insane_params_at_startup(bad):
    """Param sanity is part of the fail-at-startup contract: a plan that
    parses but crashes mid-run or silently never fires means a CI chaos
    test can silently test nothing."""
    with pytest.raises(chaos_lib.ChaosSpecError, match="chaos spec"):
        chaos_lib.parse_spec(bad)


def test_chaos_bitflip_target_must_be_in_world():
    # a target process outside the world would silently never flip — the
    # divergence test downstream would then be testing nothing
    with pytest.raises(chaos_lib.ChaosSpecError, match="out of range"):
        chaos_lib.ChaosEngine("bitflip@5:9", process_count=4)
    eng = chaos_lib.ChaosEngine("bitflip@5:3", process_count=4)  # in range
    assert eng.mutates_state_at(5)


def test_chaos_step_fault_fires_exactly_once():
    import jax.numpy as jnp

    eng = chaos_lib.ChaosEngine("nan_loss@5")
    loss = jnp.asarray(2.5, dtype=jnp.float32)
    state = {"w": jnp.zeros(3)}
    s, l, fired = eng.on_step(4, state, loss)
    assert not fired and float(l) == 2.5
    s, l, fired = eng.on_step(5, state, loss)
    assert fired and np.isnan(float(l))
    assert s is state  # nan_loss poisons the OBSERVED loss, never the state
    # post-rollback the step counter repeats 5 — the fault must not re-fire
    s, l, fired = eng.on_step(5, state, loss)
    assert not fired and float(l) == 2.5


def test_chaos_spike_mult_and_bitflip_targeting():
    import jax.numpy as jnp

    eng = chaos_lib.ChaosEngine("spike_loss@3:100,bitflip@4:1", process_index=0,
                                process_count=2)
    loss = jnp.asarray(2.0, dtype=jnp.float32)
    _, l, _ = eng.on_step(3, {"w": jnp.ones(3)}, loss)
    assert float(l) == 200.0
    # bitflip targets process 1; process 0's state must be untouched
    state = {"w": jnp.ones(3, dtype=jnp.float32)}
    s, _, fired = eng.on_step(4, state, loss)
    assert fired[0]["process"] == 1 and "flipped" not in fired[0]
    assert s is state

    other = chaos_lib.ChaosEngine("bitflip@4:1", process_index=1, process_count=2)
    s2, _, fired2 = other.on_step(4, state, loss)
    assert fired2[0].get("flipped") is True
    changed = np.asarray(s2["w"]) != np.asarray(state["w"])
    assert changed.sum() == 1  # exactly one element, one mantissa bit
    assert np.isfinite(np.asarray(s2["w"])).all()


def test_chaos_io_fault_occurrence_and_consecutive_counting():
    """`ckpt_io_fail@2:2`: the 2nd ckpt_write OPERATION fails its first two
    attempts (retries re-enter without advancing the occurrence), then
    succeeds; other occurrences pass untouched."""
    eng = chaos_lib.ChaosEngine("ckpt_io_fail@2:2")
    eng.io_fault("ckpt_write")  # occurrence 1: clean
    with pytest.raises(IOError):
        eng.io_fault("ckpt_write")  # occurrence 2, attempt 1: injected
    with pytest.raises(IOError):
        eng.io_fault("ckpt_write")  # occurrence 2, attempt 2: injected
    eng.io_fault("ckpt_write")  # occurrence 2, attempt 3: recovers
    eng.io_fault("ckpt_write")  # occurrence 3: clean
    assert len(eng.fired) == 2
    # an unrelated site never sees the plan
    eng2 = chaos_lib.ChaosEngine("ckpt_io_fail@1")
    eng2.io_fault("loader_fetch")


def test_chaos_module_hooks_install_and_clear():
    assert chaos_lib.installed() is None
    chaos_lib.maybe_io_fault("ckpt_write")  # no harness: a no-op
    eng = chaos_lib.ChaosEngine("ckpt_io_fail@1")
    prev = chaos_lib.install(eng)
    try:
        assert prev is None and chaos_lib.installed() is eng
        with pytest.raises(IOError):
            chaos_lib.maybe_io_fault("ckpt_write")
    finally:
        chaos_lib.install(prev)
    assert chaos_lib.installed() is None


# ---------------------------------------------------------------------------
# retry: policy + wrapper semantics
# ---------------------------------------------------------------------------


def test_retry_policy_validates():
    with pytest.raises(ValueError):
        retry_lib.RetryPolicy(retries=-1)
    with pytest.raises(ValueError):
        retry_lib.RetryPolicy(jitter=1.0)
    with pytest.raises(ValueError):
        retry_lib.RetryPolicy(base_delay=-0.1)


def test_retry_delay_exponential_and_capped():
    import random

    pol = retry_lib.RetryPolicy(retries=8, base_delay=0.1, max_delay=1.0, jitter=0.0)
    rng = random.Random(0)
    delays = [pol.delay(k, rng) for k in range(1, 7)]
    assert delays[:4] == [0.1, 0.2, 0.4, 0.8]
    assert delays[4] == delays[5] == 1.0  # capped
    jittered = retry_lib.RetryPolicy(retries=3, base_delay=0.1, jitter=0.5)
    for k in (1, 2, 3):
        d = jittered.delay(k, rng)
        base = min(0.1 * 2 ** (k - 1), jittered.max_delay)
        assert 0.5 * base <= d <= 1.5 * base


def test_retry_io_recovers_within_budget_and_observes():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError(f"transient {calls['n']}")
        return "ok"

    events = []
    assert (
        retry_lib.retry_io(
            flaky, label="t", policy=retry_lib.RetryPolicy(retries=3),
            sleep=slept.append,
        )
        == "ok"
    )
    assert calls["n"] == 3 and len(slept) == 2
    # the observer path (fit installs a RetryLog)
    log = retry_lib.RetryLog()
    retry_lib.set_observer(log)
    try:
        calls["n"] = 0
        retry_lib.retry_io(
            flaky, label="obs", policy=retry_lib.RetryPolicy(retries=3),
            sleep=lambda s: None,
        )
    finally:
        retry_lib.set_observer(None)
    events = log.drain()
    assert [e["attempt"] for e in events] == [1, 2]
    assert all(e["label"] == "obs" for e in events)
    assert log.total == 2 and log.drain() == []  # total survives draining


def test_retry_io_fails_loud_after_budget():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise IOError("still down")

    with pytest.raises(IOError, match="still down"):
        retry_lib.retry_io(
            always, policy=retry_lib.RetryPolicy(retries=2),
            sleep=lambda s: None,
        )
    assert calls["n"] == 3  # 1 attempt + 2 retries, then the REAL error


def test_retry_io_never_retries_programming_errors():
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_lib.retry_io(buggy, sleep=lambda s: None)
    assert calls["n"] == 1  # retrying a bug just repeats it slower


def test_retry_zero_budget_is_one_attempt():
    def always():
        raise IOError("x")

    with pytest.raises(IOError):
        retry_lib.retry_io(
            always, policy=retry_lib.RetryPolicy(retries=0),
            sleep=lambda s: None,
        )


# ---------------------------------------------------------------------------
# checkpoint integrity: checksums at save, corrupt skipped at resolve
# ---------------------------------------------------------------------------


def _fake_state(step: int):
    """A minimal pytree with a .step — enough for the consolidated writer."""
    from flax import struct

    @struct.dataclass
    class S:
        step: int
        w: np.ndarray

    return S(step=step, w=np.arange(8, dtype=np.float32) + step)


def test_consolidated_save_writes_verifying_sidecar(tmp_path):
    path = ckpt_lib.save(_fake_state(3), tmp_path)
    side = ckpt_lib.checksum_sidecar(path)
    assert side.exists()
    assert side.read_text().strip() == hashlib.sha256(path.read_bytes()).hexdigest()
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert ok and detail == "verified"


def test_latest_skips_corrupt_checkpoint_with_warning(tmp_path):
    good = ckpt_lib.save(_fake_state(4), tmp_path)
    bad = ckpt_lib.save(_fake_state(8), tmp_path)
    bad.write_bytes(b"bitrot" + bad.read_bytes()[6:])  # same size, wrong bytes
    ok, detail = ckpt_lib.verify_checkpoint(bad)
    assert not ok and "mismatch" in detail
    with pytest.warns(UserWarning, match="skipping corrupt checkpoint"):
        assert ckpt_lib.latest(tmp_path) == good
    with pytest.warns(UserWarning):
        assert ckpt_lib.latest_any(tmp_path) == good
    assert ckpt_lib.latest(tmp_path, verify=False) == bad  # escape hatch


def test_missing_sidecar_is_legacy_not_corrupt(tmp_path):
    """Pre-round-9 checkpoints (no sidecar) must stay restorable."""
    path = ckpt_lib.save(_fake_state(5), tmp_path)
    ckpt_lib.checksum_sidecar(path).unlink()
    ok, detail = ckpt_lib.verify_checkpoint(path)
    assert ok and "legacy" in detail
    assert ckpt_lib.latest(tmp_path) == path


def test_latest_good_respects_max_step(tmp_path):
    for step in (2, 4, 6, 8):
        ckpt_lib.save(_fake_state(step), tmp_path)
    assert ckpt_lib._step_of(ckpt_lib.latest_good(tmp_path)) == 8
    assert ckpt_lib._step_of(ckpt_lib.latest_good(tmp_path, max_step=5)) == 4
    assert ckpt_lib._step_of(ckpt_lib.latest_good(tmp_path, max_step=4)) == 4
    assert ckpt_lib.latest_good(tmp_path, max_step=1) is None


def test_meta_sidecar_roundtrip(tmp_path):
    meta = {"step": 7, "epoch": 1, "batch_in_epoch": 3, "preempted": True}
    path = ckpt_lib.save(_fake_state(7), tmp_path, meta=meta)
    assert ckpt_lib.read_meta(path) == meta
    plain = ckpt_lib.save(_fake_state(9), tmp_path)
    assert ckpt_lib.read_meta(plain) is None


def test_sharded_manifest_records_checksums_and_verifies(tmp_path, tiny_config):
    """Single-process sharded save: the manifest must carry a sha256 per
    shard file; corrupting a shard or deleting it flips verification, and
    `latest_sharded` skips the corrupt directory for an older good one."""
    from tpukit.model import init_params
    from tpukit.train import create_train_state, make_optimizer

    state = create_train_state(
        jax.random.PRNGKey(0), tiny_config, make_optimizer(1e-3)
    )
    old = ckpt_lib.save_sharded(
        state.replace(step=state.step * 0 + 1), tmp_path, meta={"step": 1}
    )
    new = ckpt_lib.save_sharded(state.replace(step=state.step * 0 + 2), tmp_path)
    manifest = json.loads((new / "manifest.json").read_text())
    shard = new / "shard-00000.npz"
    assert manifest["checksums"][shard.name] == hashlib.sha256(
        shard.read_bytes()
    ).hexdigest()
    assert ckpt_lib.verify_checkpoint(new) == (True, "verified")
    assert ckpt_lib.read_meta(old) == {"step": 1}

    shard.write_bytes(shard.read_bytes()[:-4] + b"\x00\x00\x00\x00")
    ok, detail = ckpt_lib.verify_checkpoint(new)
    assert not ok and "mismatch" in detail
    with pytest.warns(UserWarning, match="skipping corrupt checkpoint"):
        assert ckpt_lib.latest_sharded(tmp_path) == old

    shard.unlink()
    ok, detail = ckpt_lib.verify_checkpoint(new)
    assert not ok and "missing shard" in detail


# ---------------------------------------------------------------------------
# recovery engine: budget, planning, quarantine, coordinator, exit codes
# ---------------------------------------------------------------------------


def test_exit_code_contract_values():
    """The documented contract (README): these are load-bearing for any
    babysitter script keying relaunch decisions on them — moving one is a
    breaking change."""
    assert EXIT_CLEAN == 0
    assert EXIT_PREEMPTED == 75  # EX_TEMPFAIL: relaunch with --resume latest
    assert EXIT_ANOMALY_ABORT == 76
    assert EXIT_ROLLBACK_EXHAUSTED == 77
    assert Preempted("x").exit_code == 75
    assert AnomalyAbort("x").exit_code == 76
    assert RollbackBudgetExhausted("x").exit_code == 77
    assert issubclass(RollbackBudgetExhausted, AnomalyAbort)


def test_run_recipe_maps_exceptions_to_exit_codes():
    assert run_recipe(lambda argv: None) == 0
    assert run_recipe(lambda argv: (_ for _ in ()).throw(Preempted("p"))) == 75
    assert run_recipe(lambda argv: (_ for _ in ()).throw(AnomalyAbort("a"))) == 76
    assert (
        run_recipe(
            lambda argv: (_ for _ in ()).throw(RollbackBudgetExhausted("r"))
        )
        == 77
    )
    with pytest.raises(KeyError):  # unexpected crashes keep their traceback
        run_recipe(lambda argv: (_ for _ in ()).throw(KeyError("boom")))


def test_recovery_plan_picks_newest_good_outside_window(tmp_path):
    for step in (2, 4, 6, 8):
        ckpt_lib.save(_fake_state(step), tmp_path)
    eng = RecoveryEngine(tmp_path, max_rollbacks=2)
    plan = eng.plan("nan", anomaly_step=9, window=4)
    assert plan.target_step == 4  # newest with step <= 9 - 4
    assert plan.steps_lost == 5 and plan.seq == 1
    eng.committed(plan)
    assert eng.count == 1 and eng.steps_lost == 5


def test_recovery_budget_exhaustion_and_no_candidate(tmp_path):
    eng = RecoveryEngine(tmp_path, max_rollbacks=0)
    assert eng.plan("nan", 10, window=0) is None and eng.exhausted
    ckpt_lib.save(_fake_state(6), tmp_path)
    eng2 = RecoveryEngine(tmp_path, max_rollbacks=3)
    # nothing restorable OLDER than the window -> same escalation
    assert eng2.plan("nan", 5, window=4) is None and eng2.exhausted
    with pytest.raises(ValueError):
        RecoveryEngine(tmp_path, max_rollbacks=-1)


def test_quarantine_renames_suspect_timeline_aside(tmp_path):
    for step in (2, 4, 6, 8):
        ckpt_lib.save(_fake_state(step), tmp_path, meta={"step": step})
    eng = RecoveryEngine(tmp_path, max_rollbacks=3)
    plan = eng.plan("spike", anomaly_step=9, window=4)  # target step 4
    names = eng.quarantine(plan)
    assert sorted(names) == [
        "checkpoint-step000000006.msgpack.quarantined-0001",
        "checkpoint-step000000008.msgpack.quarantined-0001",
    ]
    # the poisoned timeline is invisible to every resolution path now
    assert ckpt_lib._step_of(ckpt_lib.latest(tmp_path)) == 4
    assert ckpt_lib._step_of(ckpt_lib.latest_good(tmp_path)) == 4
    # sidecars went aside with their blobs (no orphan checksum/meta files)
    assert not ckpt_lib.checksum_sidecar(
        tmp_path / "checkpoint-step000000008.msgpack"
    ).exists()
    assert not ckpt_lib.meta_path(
        tmp_path / "checkpoint-step000000008.msgpack"
    ).exists()
    # non-process-0 never touches the shared filesystem
    assert eng.quarantine(plan, process_zero=False) == []


def test_coordinator_confirm_rejects_split_brain(tmp_path):
    plan = RollbackPlan(
        seq=1, reason="nan", anomaly_step=20, target_step=12,
        target_path="c", steps_lost=8,
    )
    p0 = RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    p1 = RollbackCoordinator(
        tmp_path, process_index=1, process_count=2, timeout_s=0.3
    )
    # timeout first: no decision file yet
    with pytest.raises(TrainingAborted, match="timed out"):
        p1.confirm(plan)
    p0.publish(plan)
    p1.confirm(plan)  # matching plan: returns quietly
    import dataclasses

    skewed = dataclasses.replace(plan, target_step=8)
    with pytest.raises(TrainingAborted, match="split-brain"):
        p1.confirm(skewed)
    p1.ack(1, 12)
    acks = list(Path(tmp_path).glob("rollback-0001-ack-*.json"))
    assert len(acks) == 1
    # deferred decisions carry their execution boundary
    p0.publish(plan, execute_after=28)
    assert p0.read(1)["execute_after"] == 28
    # single-process worlds never touch the filesystem
    solo = RollbackCoordinator(None)
    solo.publish(plan)
    solo.confirm(plan)
    assert solo.read(1) is None


def test_coordinator_publish_abort_round_trips(tmp_path):
    # budget exhausted on a p0-only anomaly (divergence): the abort is a
    # published decision every rank executes at the boundary, never a
    # lone-p0 abort (whose autopsy checkpoint collective would strand the
    # other ranks)
    p0 = RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    p0.publish_abort(1, "divergence", anomaly_step=40, execute_after=48)
    rec = p0.read(1)
    assert rec["action"] == "abort"
    assert rec["reason"] == "divergence"
    assert rec["anomaly_step"] == 40 and rec["execute_after"] == 48
    # single-process worlds never touch the filesystem
    solo = RollbackCoordinator(None)
    solo.publish_abort(1, "divergence", anomaly_step=40, execute_after=48)
    assert solo.read(1) is None


def test_coordinator_final_drain_rendezvous(tmp_path):
    # A deferred decision published during the LAST training window is
    # read at the end-of-epoch drain — but a fast rank's lone read can
    # land BEFORE slow p0's publish (p0 detects divergence inside its
    # boundary block: heartbeat reads + hashing). The drain is therefore
    # a rendezvous: ranks must not trust a None read until p0's marker
    # exists, and the marker is only written after everything p0 will
    # ever publish is on disk.
    p0 = RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    p1 = RollbackCoordinator(
        tmp_path, process_index=1, process_count=2, timeout_s=0.3
    )
    # no marker yet: the wait must time out LOUD, never silently proceed
    with pytest.raises(TrainingAborted, match="final-drain marker"):
        p1.wait_final_drain()
    plan = RollbackPlan(
        seq=1, reason="divergence", anomaly_step=20, target_step=12,
        target_path="c", steps_lost=8,
    )
    p0.publish(plan, execute_after=28)  # publish strictly before marker
    p0.publish_final_drain(24)
    p1.wait_final_drain()  # returns promptly now
    assert p1.read(1)["execute_after"] == 28
    # p0 itself never waits; non-p0 never publishes the marker
    p0.wait_final_drain()
    p1.publish_final_drain(24)
    # the marker lives in the rollback-*.json namespace, so a relaunched
    # incarnation's construction sweep clears it with the decisions
    RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    with pytest.raises(TrainingAborted, match="final-drain marker"):
        p1.wait_final_drain()
    # single-process worlds never touch the filesystem
    solo = RollbackCoordinator(None)
    solo.publish_final_drain(24)
    solo.wait_final_drain()


def test_verify_checkpoint_vanishing_file_skips_not_crashes(tmp_path, monkeypatch):
    # During a collective rollback every rank runs latest_good over the
    # shared directory while p0 concurrently quarantine-renames the
    # suspect checkpoints: a candidate can pass the exists() probes and
    # vanish before the hash opens it. The warn-and-skip contract demands
    # (False, detail) — an OSError escaping verify_checkpoint would crash
    # the rank unclassified and strand the others in the rollback
    # collectives.
    good = ckpt_lib.save(_fake_state(4), tmp_path)
    doomed = ckpt_lib.save(_fake_state(8), tmp_path)
    real = ckpt_lib._sha256_file

    def racing_sha256(path):
        if Path(path).name == doomed.name:
            raise FileNotFoundError(f"quarantine race: {path} renamed away")
        return real(path)

    monkeypatch.setattr(ckpt_lib, "_sha256_file", racing_sha256)
    ok, detail = ckpt_lib.verify_checkpoint(doomed)
    assert not ok and "unreadable" in detail
    with pytest.warns(UserWarning, match="skipping corrupt checkpoint"):
        assert ckpt_lib.latest_good(tmp_path) == good
    assert ckpt_lib.verify_checkpoint(good) == (True, "verified")


def test_chaos_mutates_state_at_flags_only_bitflips():
    # the trainer brackets on_step with a prefetcher quiesce exactly when
    # the fault will device_put into the state (bitflip) — loss poisoning
    # and signals never pay the quiesce
    eng = chaos_lib.ChaosEngine("nan_loss@3,bitflip@5,hang@7")
    assert not eng.mutates_state_at(3)
    assert eng.mutates_state_at(5)
    assert not eng.mutates_state_at(7)
    # fire-once: after the step executes, the quiesce is no longer needed
    import jax.numpy as jnp

    eng.on_step(5, {"w": jnp.zeros((2,))}, jnp.float32(1.0))
    assert not eng.mutates_state_at(5)


def test_preempt_coordinator_request_decide_protocol(tmp_path):
    from tpukit.recovery import PreemptCoordinator

    p0 = PreemptCoordinator(tmp_path, process_index=0, process_count=2)
    p1 = PreemptCoordinator(tmp_path, process_index=1, process_count=2)
    assert p0.any_request() is None and p0.read() is None
    # rank 1's signal lands first: it publishes a request (idempotent)
    p1.request("SIGTERM")
    p1.request("SIGTERM")
    reqs = list(Path(tmp_path).glob("preempt-request-p*.json"))
    assert len(reqs) == 1
    assert p0.any_request() == "SIGTERM"
    # p0 turns the first request into the decision; first decision wins
    dec = p0.publish("SIGTERM", execute_after=48)
    assert dec == {"signal": "SIGTERM", "execute_after": 48, "run_start": 0}
    assert p0.publish("SIGINT", execute_after=64) == dec  # idempotent
    assert p1.read() == dec
    # single-process worlds never construct one, but None-dir is inert
    solo = PreemptCoordinator(None)
    solo.request("SIGTERM")
    assert solo.any_request() is None and solo.read() is None


def test_preempt_coordinator_clears_stale_incarnation_state(tmp_path):
    # The decision/request files survive the incarnation that wrote them.
    # A relaunched run must NOT re-read them: its first poll would match
    # the stale decision and preempt again with no signal pending — every
    # relaunch exits 75 and the run never makes progress.
    from tpukit.recovery import PreemptCoordinator

    old0 = PreemptCoordinator(tmp_path, process_index=0, process_count=2)
    old1 = PreemptCoordinator(tmp_path, process_index=1, process_count=2)
    old1.request("SIGTERM")
    old0.publish("SIGTERM", execute_after=48)
    # relaunch: each rank clears its own request, p0 clears the decision
    new1 = PreemptCoordinator(tmp_path, process_index=1, process_count=2)
    new0 = PreemptCoordinator(tmp_path, process_index=0, process_count=2)
    assert new0.read() is None
    assert new0.any_request() is None
    assert new1.read() is None
    # ... and even when the cleanup LOSES the relaunch race (a fast rank
    # polls before a slow p0's init sweep), the incarnation tag rejects
    # the leftovers: the resumed run's start step (48 here — it restored
    # the preemption checkpoint saved at execute_after) differs from the
    # old incarnation's tag, so a surviving decision/request never matches.
    old0b = PreemptCoordinator(tmp_path, process_index=0, process_count=2)
    old1b = PreemptCoordinator(tmp_path, process_index=1, process_count=2)
    old1b.request("SIGTERM")
    old0b.publish("SIGTERM", execute_after=48)
    racer = PreemptCoordinator.__new__(PreemptCoordinator)  # no cleanup ran
    racer.directory = Path(tmp_path)
    racer.process_index = 1
    racer.process_count = 2
    racer._requested = False
    racer.run_start = 48
    assert racer.read() is None
    assert racer.any_request() is None
    # same incarnation tag on both sides round-trips normally
    old1b.run_start = 48
    old1b._requested = False
    old0b.run_start = 48
    old1b.request("SIGTERM")
    dec = old0b.publish("SIGTERM", execute_after=96)
    assert racer.read() == dec and racer.any_request() == "SIGTERM"


def test_rollback_coordinator_clears_stale_incarnation_state(tmp_path):
    # A new incarnation restarts its rollback seq at 1; a surviving
    # rollback-0001.json would either execute a spurious rollback at the
    # first boundary or, via the in-flight dedup, suppress every real
    # deferred rollback of the resumed run.
    plan = RollbackPlan(
        seq=1, reason="divergence", anomaly_step=20, target_step=12,
        target_path="c", steps_lost=8,
    )
    old0 = RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    old0.publish(plan, execute_after=28)
    RollbackCoordinator(tmp_path, process_index=1, process_count=2).ack(1, 12)
    assert old0.read(1) is not None
    new0 = RollbackCoordinator(tmp_path, process_index=0, process_count=2)
    assert new0.read(1) is None
    assert not list(Path(tmp_path).glob("rollback-*.json"))
    # non-p0 ranks never clear (p0 owns the channel); a rank constructed
    # before a straggling p0 must not see the old decision either once p0
    # arrives — but it must not delete p0's files itself
    old0.publish(plan, execute_after=28)
    RollbackCoordinator(tmp_path, process_index=1, process_count=2)
    assert old0.read(1) is not None


def test_preemption_guard_sets_flag_and_restores_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert guard.pending is None
        signal.raise_signal(signal.SIGTERM)
        assert guard.pending == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is prev


# ---------------------------------------------------------------------------
# fit() end to end: the detect→recover loop on a real run
# ---------------------------------------------------------------------------

TINY = dict(
    batch_size=8, epochs=1, sequence_length=33, dim=32, head_dim=8, heads=4,
    num_layers=2, learning_rate=1e-3, dataset_slice="200", num_workers=0,
    disable_amp=True, seed=0, checkpoint_every=4, spike_threshold=8.0,
)
# 200 rows / batch 8 = 25 steps; PRINT_FREQ=8 windows at batch index 8, 16,
# 24; nan_loss@12 poisons the window ending at step 17, whose newest
# checkpoint outside the window (17 - 8 = 9) is step 8.


def _run_fit(tmp, log_name, **overrides):
    from tpukit.flags import TrainFlags
    from tpukit.shardings import SingleDevice
    from tpukit.train import fit

    flags = TrainFlags(**{**TINY, "metrics_log": str(tmp / log_name), **overrides})
    cwd = os.getcwd()
    os.chdir(tmp)  # checkpoints/ lands in tmp
    try:
        result = fit(flags, SingleDevice())
    finally:
        os.chdir(cwd)
    records = [
        json.loads(line) for line in (tmp / log_name).read_text().splitlines()
    ]
    return result, records


@pytest.fixture(scope="module")
def chaos_rollback_run(tmp_path_factory):
    """The acceptance scenario: nan_loss@12 + --on_anomaly rollback. The
    run must detect at the window boundary (step 17), roll back to the
    step-8 checkpoint, keep the stream moving forward, and complete."""
    tmp = tmp_path_factory.mktemp("chaos_rollback")
    result, records = _run_fit(
        tmp, "run.jsonl", chaos_spec="nan_loss@12", on_anomaly="rollback",
        max_rollbacks=2,
    )
    return tmp, result, records


def test_chaos_rollback_completes_and_logs_the_loop(chaos_rollback_run):
    tmp, result, records = chaos_rollback_run
    kinds = [r["kind"] for r in records]
    assert "chaos" in kinds and "spike" in kinds and "rollback" in kinds
    rb = next(r for r in records if r["kind"] == "rollback")
    assert rb["reason"] == "nan"
    assert rb["anomaly_step"] == 17 and rb["target_step"] == 8
    assert rb["steps_lost"] == 9 and rb["timeline"] == 1
    assert len(rb["quarantined"]) == 2  # poisoned steps 12 and 16
    # the run COMPLETED: validation ran, the final state is healthy, and
    # the step counter reflects the replayed window (25 batches, 9 steps
    # lost to the rollback -> final step 16)
    assert any(r["kind"] == "validation" for r in records)
    assert int(jax.device_get(result.state.step)) == 16
    last_window = [r for r in records if r["kind"] == "train"][-1]
    assert np.isfinite(last_window["loss"])
    # quarantined names never resolve again
    assert ckpt_lib._step_of(ckpt_lib.latest(tmp / "checkpoints")) == 16


def test_chaos_rollback_trajectory_matches_restored_control(
    chaos_rollback_run, tmp_path_factory
):
    """THE acceptance criterion: the post-recovery trajectory equals an
    uninjected control run restored at the same checkpoint with the stream
    fast-forwarded to the same position (chaos `skip@17` — the recovered
    run had consumed batches 0..16 when it rolled back)."""
    tmp, result, records = chaos_rollback_run
    control = tmp_path_factory.mktemp("control")
    target = tmp / "checkpoints" / "checkpoint-step000000008.msgpack"
    ctrl_result, ctrl_records = _run_fit(
        control, "run.jsonl", resume=str(target), chaos_spec="skip@17"
    )
    # bit-exact final states: identical bytes on disk
    a = (tmp / "checkpoints" / "checkpoint-step000000016.msgpack").read_bytes()
    b = (control / "checkpoints" / "checkpoint-step000000016.msgpack").read_bytes()
    assert hashlib.sha256(a).hexdigest() == hashlib.sha256(b).hexdigest()
    # and the post-recovery window losses agree exactly, window by window
    rb_idx = next(i for i, r in enumerate(records) if r["kind"] == "rollback")
    post = [r["loss"] for r in records[rb_idx:] if r["kind"] == "train"]
    ctrl = [r["loss"] for r in ctrl_records if r["kind"] == "train"]
    assert post and post == ctrl


@pytest.fixture(scope="module")
def exhausted_abort_run(tmp_path_factory):
    """Budget 0 + transient I/O faults: the same injection must escalate to
    the round-8 bundle-dump-and-abort path with the documented exit code,
    while the inert I/O faults are absorbed by the retry wrapper."""
    from tpukit.recovery import RollbackBudgetExhausted

    tmp = tmp_path_factory.mktemp("chaos_abort")
    with pytest.raises(RollbackBudgetExhausted) as excinfo:
        _run_fit(
            tmp, "run.jsonl",
            chaos_spec="nan_loss@12,ckpt_io_fail@1:2,loader_io_fail@2",
            on_anomaly="rollback", max_rollbacks=0,
        )
    records = [
        json.loads(line) for line in (tmp / "run.jsonl").read_text().splitlines()
    ]
    return tmp, excinfo.value, records


def test_budget_zero_escalates_with_documented_exit_code(exhausted_abort_run):
    tmp, exc, records = exhausted_abort_run
    assert exc.exit_code == EXIT_ROLLBACK_EXHAUSTED
    assert "budget exhausted" in str(exc)
    # the blown-up state was checkpointed for autopsy (the round-8 tail)
    assert "checkpoint-step000000017" in str(exc)
    assert (tmp / "checkpoints" / "checkpoint-step000000017.msgpack").exists()
    assert not any(r["kind"] == "rollback" for r in records)


def test_transient_io_faults_retried_and_recorded(exhausted_abort_run):
    _, _, records = exhausted_abort_run
    retries = [r for r in records if r["kind"] == "retry"]
    labels = {r["label"] for r in retries}
    assert {"ckpt_write", "loader_fetch"} <= labels
    # 2 consecutive ckpt failures + 1 loader failure, all within the
    # default budget of 3: the run never saw an error
    assert len([r for r in retries if r["label"] == "ckpt_write"]) == 2
    for r in retries:
        assert r["retries"] == 3 and r["delay_s"] >= 0
        assert "chaos: injected transient" in r["error"]


@pytest.fixture(scope="module")
def preempted_run(tmp_path_factory):
    """Chaos-injected SIGTERM mid-epoch: graceful checkpoint with resume
    metadata, Preempted(exit 75), then `--resume latest` continues to the
    uninterrupted run's final state bit-exact."""
    tmp = tmp_path_factory.mktemp("preempt")
    with pytest.raises(Preempted) as excinfo:
        _run_fit(tmp, "run1.jsonl", chaos_spec="sigterm@13")
    records1 = [
        json.loads(line) for line in (tmp / "run1.jsonl").read_text().splitlines()
    ]
    result2, records2 = _run_fit(tmp, "run2.jsonl", resume="latest")
    control = tmp_path_factory.mktemp("preempt_control")
    _run_fit(control, "run.jsonl")
    return tmp, control, excinfo.value, records1, result2


def test_preemption_checkpoints_and_reports(preempted_run):
    tmp, _, exc, records1, _ = preempted_run
    assert exc.exit_code == EXIT_PREEMPTED
    assert exc.step == 13
    pre = next(r for r in records1 if r["kind"] == "preempt")
    assert pre["signal"] == "SIGTERM" and pre["step"] == 13
    assert pre["epoch"] == 0 and pre["batch_in_epoch"] == 13
    meta = ckpt_lib.read_meta(
        tmp / "checkpoints" / "checkpoint-step000000013.msgpack"
    )
    assert meta["preempted"] and meta["batch_in_epoch"] == 13


def test_preempted_resume_is_bit_exact_with_uninterrupted(preempted_run):
    tmp, control, _, _, result2 = preempted_run
    assert int(jax.device_get(result2.state.step)) == 25
    a = (tmp / "checkpoints" / "checkpoint-step000000025.msgpack").read_bytes()
    b = (control / "checkpoints" / "checkpoint-step000000025.msgpack").read_bytes()
    assert hashlib.sha256(a).hexdigest() == hashlib.sha256(b).hexdigest()


def test_fit_rejects_bad_recovery_flags(tmp_path):
    from tpukit.flags import TrainFlags
    from tpukit.shardings import SingleDevice
    from tpukit.train import fit

    with pytest.raises(ValueError, match="max_rollbacks"):
        fit(TrainFlags(**TINY, max_rollbacks=-1), SingleDevice())
    with pytest.raises(ValueError, match="io_retries"):
        fit(TrainFlags(**TINY, io_retries=-1), SingleDevice())
    with pytest.raises(chaos_lib.ChaosSpecError):
        fit(TrainFlags(**TINY, chaos_spec="frobnicate@3"), SingleDevice())


def test_fit_resume_rejects_corrupt_checkpoint(tmp_path):
    from tpukit.flags import TrainFlags
    from tpukit.shardings import SingleDevice
    from tpukit.train import fit

    ckdir = tmp_path / "checkpoints"
    ckdir.mkdir()
    bad = ckdir / "checkpoint-step000000004.msgpack"
    bad.write_bytes(b"garbage")
    ckpt_lib.checksum_sidecar(bad).write_text("0" * 64)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        with pytest.raises(ValueError, match="integrity"):
            fit(TrainFlags(**TINY, resume=str(bad)), SingleDevice())
    finally:
        os.chdir(cwd)


def test_chaos_flag_leaves_train_step_hlo_byte_identical(tiny_config):
    """Zero behavior change when no fault fires: all injection is host-side,
    so the compiled train step is byte-identical with the harness installed
    (the acceptance criterion's HLO check)."""
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), tiny_config, opt)
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((4, 16), np.int32),
        "position_ids": jax.ShapeDtypeStruct((4, 16), np.int32),
        "mask": jax.ShapeDtypeStruct((4, 16), np.bool_),
    }
    targets = jax.ShapeDtypeStruct((4, 16), np.int32)
    step_off, _, _ = make_step_fns(tiny_config, opt, SingleDevice(), shapes)
    hlo_off = step_off.lower(shapes, batch, targets).compile().as_text()
    prev = chaos_lib.install(chaos_lib.ChaosEngine("nan_loss@10,ckpt_io_fail@1"))
    try:
        step_on, _, _ = make_step_fns(tiny_config, opt, SingleDevice(), shapes)
        hlo_on = step_on.lower(shapes, batch, targets).compile().as_text()
    finally:
        chaos_lib.install(prev)
    assert hlo_on == hlo_off


# ---------------------------------------------------------------------------
# tools: report.py + flightview.py render the new kinds
# ---------------------------------------------------------------------------


def test_report_renders_recovery_section(chaos_rollback_run):
    from tools.report import summarize

    _, _, records = chaos_rollback_run
    text = summarize(records)
    assert "== recovery ==" in text
    assert "rollbacks: 1   total steps lost: 9" in text
    assert "restored step 8" in text
    assert "chaos faults fired" in text


def test_report_renders_preempt_and_retries():
    from tools.report import summarize

    records = [
        {"kind": "preempt", "step": 13, "signal": "SIGTERM",
         "epoch": 0, "batch_in_epoch": 13, "checkpoint": "c/ck.msgpack"},
        {"kind": "retry", "step": 9, "label": "ckpt_write", "attempt": 1,
         "retries": 3, "delay_s": 0.05, "error": "OSError: x"},
        {"kind": "retry", "step": 9, "label": "loader_fetch", "attempt": 1,
         "retries": 3, "delay_s": 0.05, "error": "OSError: x"},
    ]
    text = summarize(records)
    assert "preempted: SIGTERM at step 13" in text
    assert "io retries: 2" in text and "ckpt_write x1" in text


def test_flightview_headlines_recovery_ring_events():
    from tools.flightview import render

    bundle = {
        "reason": "nan", "step": 17, "time": 0.0,
        "ring": [
            {"t": 0.0, "kind": "step", "step": 16},
            {"t": 0.0, "kind": "rollback", "seq": 1, "reason": "nan",
             "anomaly_step": 17, "target_step": 8, "steps_lost": 9},
            {"t": 0.0, "kind": "retry", "label": "ckpt_write", "attempt": 1},
            {"t": 0.0, "kind": "preempt", "signal": "SIGTERM", "step": 20},
        ],
    }
    text = render(bundle)
    assert "== recovery events (from the ring) ==" in text
    assert "rollback #1 [nan] anomaly step 17 -> restored step 8" in text
    assert "preempt SIGTERM at step 20" in text
    assert "retry x1" in text
