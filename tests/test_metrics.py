"""Fleet metrics plane (tpukit/obs/metrics, round 22).

Contracts pinned here:
  - histograms share ONE log-spaced edge table, so merge is bucket-wise
    sum: EXACT, associative, commutative — shuffled shard orders and
    re-parenthesised merges produce identical bucket tables;
  - quantile estimates respect the proven relative-error bound
    `sqrt(GROWTH)-1 ~ 4.4%` against exact nearest-rank on adversarial
    distributions (bimodal, heavy-tail, single-bucket, log-uniform);
    underflow/overflow samples clamp to the exact tracked min/max;
  - registry snapshots round-trip losslessly; snapshot FILES follow the
    heartbeat discipline: atomic publish, torn files skip-and-count,
    stale incarnations (process >= process_count) are excluded;
  - `--slo` parsing fails fast on malformed specs (SloSpecError), and
    the accountant's compliance / error-budget burn arithmetic is exact;
    `overall_compliance` is the WORST sampled target (min, anti-vacuous
    None when nothing sampled);
  - metrics are an OBSERVER: output tokens are bit-identical with the
    registry on vs off (the --no_metrics contract);
  - a 2-replica fleet's merged snapshot dir equals a single engine's
    aggregate on the same seeded stream, bucket-for-bucket — the
    merged-fleet == single-engine acceptance proof;
  - `kind="slo"`/`kind="metrics"` rows land in the JSONL,
    `tools/report.py --min_slo_compliance` and
    `--compare/--max_regression_pct` gate on them with exit 2 (failing
    on slo-less / compare-less logs — anti-vacuous), and report/top
    render the slo + metrics panels;
  - `tpukit/obs/metrics.py` stays stdlib-only (no jax/numpy/tpukit
    import) — top.py and report.py load it by file path on machines
    without jax (lint_invariants' stdlib-only rule is the other owner).
"""

import importlib
import json
import math
import random
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.obs import StepLogger, TraceRecorder
from tpukit.obs import metrics as metrics_lib
from tpukit.obs.metrics import (
    EDGES,
    HI,
    LO,
    OVERFLOW,
    QUANTILE_REL_ERROR,
    UNDERFLOW,
    Histogram,
    MetricRegistry,
    SloAccountant,
    SloSpecError,
    bucket_index,
    parse_slo,
)
from tpukit.serve import (
    FleetConfig,
    FleetRouter,
    ServeConfig,
    ServeEngine,
    synthetic_request_stream,
)

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


@pytest.fixture(scope="module")
def host_params(params):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)


# ---------------------------------------------------------------------------
# Bucket placement + histogram edge cases.
# ---------------------------------------------------------------------------


def test_bucket_index_respects_edges():
    assert bucket_index(LO / 2) == UNDERFLOW
    assert bucket_index(0.0) == UNDERFLOW
    assert bucket_index(HI) == OVERFLOW
    assert bucket_index(HI * 10) == OVERFLOW
    # every finite bucket i holds exactly [EDGES[i-1], EDGES[i])
    for k in range(0, metrics_lib.N_BUCKETS, 17):
        i = bucket_index(EDGES[k])
        assert i == k + 1, f"edge {k}: landed in bucket {i}"
        assert EDGES[i - 1] <= EDGES[k] < EDGES[i]
    # values strictly inside a bucket stay there
    rng = random.Random(11)
    for _ in range(500):
        v = math.exp(rng.uniform(math.log(LO), math.log(HI * 0.999)))
        i = bucket_index(v)
        assert 1 <= i <= metrics_lib.N_BUCKETS
        assert EDGES[i - 1] <= v < EDGES[i]


def test_histogram_empty_and_one_sample():
    h = Histogram()
    assert h.quantile(0.5) is None and h.fraction_le(1.0) is None
    s = h.summary()
    assert s["count"] == 0 and s["min"] is None and s["p99"] is None
    h.observe(0.005)
    # one sample: every quantile clamps to the exact value (min == max)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == 0.005
    assert h.summary()["p50"] == 0.005
    with pytest.raises(ValueError):
        h.quantile(1.5)
    # n<=0 observes are dropped, not negative
    h.observe(1.0, n=0)
    assert h.count == 1


def test_underflow_overflow_clamp_to_exact_min_max():
    h = Histogram()
    h.observe(LO / 10)     # underflow
    h.observe(HI * 3)      # overflow
    h.observe(0.01)
    assert UNDERFLOW in h.buckets and OVERFLOW in h.buckets
    assert h.quantile(0.0) == LO / 10    # underflow rank -> exact min
    assert h.quantile(1.0) == HI * 3     # overflow rank -> exact max
    for q in (0.0, 0.25, 0.5, 0.99, 1.0):
        assert h.min <= h.quantile(q) <= h.max


def _exact_nearest_rank(vals, q):
    s = sorted(vals)
    return s[max(0, math.ceil(q * len(s)) - 1)]


@pytest.mark.parametrize("name,make", [
    # bimodal: two spikes five orders of magnitude apart
    ("bimodal", lambda rng: [1e-4 * rng.uniform(0.95, 1.05) for _ in range(400)]
                          + [1.0 * rng.uniform(0.95, 1.05) for _ in range(600)]),
    # heavy tail: pareto-ish, the p99 lives far from the median
    ("heavy_tail", lambda rng: [1e-3 * rng.paretovariate(1.2) for _ in range(1000)]),
    # single bucket: everything within one bucket's span
    ("single_bucket", lambda rng: [0.005 * rng.uniform(1.0, 1.04) for _ in range(200)]),
    # log-uniform across 6 octave-decades
    ("log_uniform", lambda rng: [math.exp(rng.uniform(math.log(1e-5), math.log(10.0)))
                                 for _ in range(1000)]),
])
def test_quantile_relative_error_bound(name, make):
    rng = random.Random(29)
    vals = make(rng)
    h = Histogram()
    for v in vals:
        h.observe(v)
    for q in (0.25, 0.5, 0.9, 0.99):
        exact = _exact_nearest_rank(vals, q)
        est = h.quantile(q)
        rel = abs(est - exact) / exact
        assert rel <= QUANTILE_REL_ERROR + 1e-9, (
            f"{name} p{100 * q:g}: est {est:.6g} vs exact {exact:.6g} "
            f"-> rel error {rel:.4f} > bound {QUANTILE_REL_ERROR:.4f}"
        )


def test_fraction_le_exact_on_edges():
    h = Histogram()
    for v in (0.001, 0.002, 0.004, 0.008):
        h.observe(v)
    # a bound above everything / below everything is exact
    assert h.fraction_le(1.0) == 1.0
    assert h.fraction_le(LO / 2) == 0.0
    # a bound on a bucket edge counts whole buckets exactly
    i = bucket_index(0.002)
    assert h.fraction_le(EDGES[i]) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# Merge: exact, associative, commutative — shuffled shard orders.
# ---------------------------------------------------------------------------


def test_merge_exact_associative_commutative():
    rng = random.Random(5)
    vals = [math.exp(rng.uniform(math.log(1e-5), math.log(100.0)))
            for _ in range(600)]
    whole = Histogram()
    for v in vals:
        whole.observe(v)
    shards = [Histogram() for _ in range(6)]
    for i, v in enumerate(vals):
        shards[i % 6].observe(v)

    def merged_in(order):
        out = Histogram()
        for j in order:
            out.merge(shards[j])
        return out

    for seed in range(4):  # commutativity: any shard order, same buckets
        order = list(range(6))
        random.Random(seed).shuffle(order)
        m = merged_in(order)
        assert m.buckets == whole.buckets
        assert m.count == whole.count
        assert m.min == whole.min and m.max == whole.max
        assert m.sum == pytest.approx(whole.sum)
        for q in (0.5, 0.99):  # identical buckets -> identical quantiles
            assert m.quantile(q) == whole.quantile(q)

    # associativity: (a+b)+c == a+(b+c), bucket-for-bucket
    left = Histogram()
    left.merge(shards[0]); left.merge(shards[1]); left.merge(shards[2])
    bc = Histogram()
    bc.merge(shards[1]); bc.merge(shards[2])
    right = Histogram()
    right.merge(shards[0]); right.merge(bc)
    assert left.buckets == right.buckets
    assert left.count == right.count


# ---------------------------------------------------------------------------
# Registry: labels, snapshot round-trip, merge semantics.
# ---------------------------------------------------------------------------


def _demo_registry():
    reg = MetricRegistry()
    reg.inc("reqs", 3, replica=0, reason="eos")
    reg.inc("reqs", 1, replica=1, reason="length")
    reg.gauge("occ", 0.5, replica=0)
    reg.gauge("occ", 0.75, replica=1)
    for v in (0.001, 0.01, 0.1):
        reg.observe("lat_s", v, replica=0)
    reg.observe("lat_s", 0.2, replica=1)
    return reg


def test_registry_snapshot_roundtrip_lossless():
    reg = _demo_registry()
    snap = reg.snapshot()
    back = MetricRegistry.from_snapshot(snap)
    assert back.snapshot() == snap
    assert back.counter_value("reqs", replica=0, reason="eos") == 3
    assert back.sum_counter("reqs") == 4
    assert back.hist("lat_s", replica=0).count == 3
    agg = back.aggregate_hist("lat_s")
    assert agg.count == 4 and agg.max == 0.2
    assert back.hist_names() == ["lat_s"]


def test_registry_merge_semantics():
    reg = _demo_registry()
    snap = _demo_registry().snapshot()
    reg.merge_snapshot(snap)
    # counters sum, histograms bucket-sum, gauges last-writer-wins
    assert reg.sum_counter("reqs") == 8
    assert reg.aggregate_hist("lat_s").count == 8
    assert reg.counter_value("reqs", replica=0, reason="eos") == 6
    g = {(r["name"], tuple(sorted(r["labels"].items()))): r["value"]
         for r in reg.snapshot()["gauges"]}
    assert g[("occ", (("replica", "0"),))] == 0.5


def test_registry_filter_splits_by_label():
    reg = _demo_registry()
    r0 = reg.filter(replica=0)
    assert r0.sum_counter("reqs") == 3
    assert r0.aggregate_hist("lat_s").count == 3
    assert r0.hist("lat_s", replica=1) is None
    # the filtered copy is independent of the parent
    r0.observe("lat_s", 9.0, replica=0)
    assert reg.aggregate_hist("lat_s").count == 4


# ---------------------------------------------------------------------------
# Snapshot files: atomic publish, torn-file skip, stale exclusion, merge.
# ---------------------------------------------------------------------------


def test_publish_read_merge_snapshot_dir(tmp_path):
    d = tmp_path / "metrics"
    for rep in (0, 1):
        metrics_lib.publish_snapshot(d, rep, _demo_registry().filter(replica=rep),
                                     process_count=2, time_s=float(rep))
    # a torn file: skipped and counted, never raised
    (d / "metrics-p00042.json").write_text('{"process": 42, "metr')
    # a stale incarnation from a larger world: excluded under
    # process_count=2, like heartbeat's straggler check
    metrics_lib.publish_snapshot(d, 7, _demo_registry(), process_count=8)

    merged, meta = metrics_lib.merge_snapshot_dir(d, process_count=2)
    assert meta == {"files": 4, "skipped": 1, "stale": 1, "merged": 2}
    assert merged.sum_counter("reqs") == 4
    assert merged.aggregate_hist("lat_s").count == 4
    # without a process_count the stale payload folds in too
    all_in, meta_all = metrics_lib.merge_snapshot_dir(d)
    assert meta_all["merged"] == 3 and all_in.sum_counter("reqs") == 8

    metrics_lib.write_merged(d, merged, meta=meta)
    assert (d / metrics_lib.MERGED_NAME).is_file()
    prom = (d / metrics_lib.OPENMETRICS_NAME).read_text()
    assert prom.rstrip().endswith("# EOF")
    assert "reqs_total" in prom and "lat_s_bucket" in prom
    # cumulative le series top out at the series count
    assert 'lat_s_count{replica="0"} 3' in prom


def test_read_snapshots_empty_dir(tmp_path):
    payloads, meta = metrics_lib.read_snapshots(tmp_path / "nope")
    assert payloads == [] and meta["files"] == 0


def test_openmetrics_cumulative_buckets():
    h = Histogram()
    for v in (0.001, 0.002, 0.004):
        h.observe(v)
    reg = MetricRegistry()
    for v in (0.001, 0.002, 0.004):
        reg.observe("w_s", v)
    text = metrics_lib.to_openmetrics(reg)
    counts = [int(l.rsplit(" ", 1)[1]) for l in text.splitlines()
              if l.startswith("w_s_bucket")]
    assert counts == sorted(counts) and counts[-1] == 3  # cumulative
    assert "# TYPE w_s histogram" in text


# ---------------------------------------------------------------------------
# SLO grammar + accounting.
# ---------------------------------------------------------------------------


def test_parse_slo_good_spec():
    targets = parse_slo("ttft<=250ms@p99; tpot<=40ms@p95;e2e<=2s@p99.9")
    assert [t.metric for t in targets] == ["ttft", "tpot", "e2e"]
    assert targets[0].bound_s == pytest.approx(0.250)
    assert targets[1].bound_s == pytest.approx(0.040)
    assert targets[2].q == pytest.approx(0.999)
    assert targets[1].budget == pytest.approx(0.05)
    assert "ttft" in repr(targets[0])


@pytest.mark.parametrize("bad", [
    "ttft<250ms@p99",            # wrong operator
    "ttft<=250@p99",             # missing unit
    "latency<=250ms@p99",        # unknown metric
    "ttft<=250ms@p0",            # quantile at the open boundary
    "ttft<=250ms@p100",          # quantile at the open boundary
    "ttft<=0ms@p99",             # zero bound
    "ttft<=1ms@p99;ttft<=2ms@p95",  # duplicate metric
    "",                          # empty spec
    ";;",                        # empty after splitting
])
def test_parse_slo_fails_fast(bad):
    with pytest.raises(SloSpecError):
        parse_slo(bad)


def test_slo_accounting_compliance_and_burn():
    acc = SloAccountant(parse_slo("ttft<=100ms@p90;e2e<=1s@p99"))
    # window 1: 10 ttft samples, 2 violations -> burning 2x budget
    rec = acc.evaluate({"ttft": [0.05] * 8 + [0.2, 0.3], "e2e": []})
    ttft, e2e = rec["targets"]
    assert ttft["n"] == 10 and ttft["violations"] == 2
    assert ttft["compliance"] == pytest.approx(0.8)
    assert ttft["met"] is False
    assert ttft["burn"] == pytest.approx(2.0)  # 20% violations / 10% budget
    assert e2e["n"] == 0 and e2e["compliance"] is None and e2e["burn"] is None
    # overall = worst SAMPLED target, e2e's emptiness doesn't vacuously pass
    assert rec["overall_compliance"] == pytest.approx(0.8)
    # window 2: clean -> cumulative recovers to 0.9, burn to exactly 1.0
    rec = acc.evaluate({"ttft": [0.05] * 10, "e2e": [0.5]})
    ttft, e2e = rec["targets"]
    assert ttft["cum_n"] == 20
    assert ttft["cum_compliance"] == pytest.approx(0.9)
    assert ttft["cum_burn"] == pytest.approx(1.0)
    assert ttft["met"] is True and ttft["burn"] == 0.0
    assert e2e["cum_compliance"] == 1.0
    assert rec["overall_compliance"] == pytest.approx(0.9)  # min across targets
    assert acc.windows == 2


def test_slo_overall_none_until_sampled():
    acc = SloAccountant(parse_slo("ttft<=100ms@p99"))
    assert acc.overall_compliance() is None
    acc.evaluate({"ttft": []})
    assert acc.overall_compliance() is None  # still no samples


# ---------------------------------------------------------------------------
# stdlib-only: metrics.py must stay loadable with no jax installed.
# ---------------------------------------------------------------------------


def test_metrics_module_is_stdlib_only():
    import ast

    tree = ast.parse(Path(metrics_lib.__file__).read_text())
    imported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            imported |= {a.name.split(".")[0] for a in node.names}
        elif isinstance(node, ast.ImportFrom) and node.module:
            imported.add(node.module.split(".")[0])
    assert not imported & {"jax", "numpy", "tpukit"}, (
        f"metrics.py must stay stdlib-only (top.py/report.py load it by "
        f"path with no jax installed); imports {sorted(imported)}"
    )


# ---------------------------------------------------------------------------
# Engine integration: observer discipline + the derived series.
# ---------------------------------------------------------------------------


def test_tokens_bit_identical_metrics_on_off(tok, cfg, params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=4, temperature=0.9, top_k=5)
    reqs = list(synthetic_request_stream(tok, 6, seed=5,
                                         max_new_tokens=MAX_NEW,
                                         buckets=(8, 16)))

    def run(metrics):
        eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                          metrics=metrics)
        return {c.rid: list(map(int, c.ids))
                for c in eng.run(list(reqs), max_wall_s=300)}

    assert run(None) == run(MetricRegistry())


@pytest.fixture(scope="module")
def metered_run(tok, cfg, params, tmp_path_factory):
    """One metered+traced serve run shared by the integration tests:
    generous SLOs (they pass), a metrics_dir, a JSONL log."""
    tmp = tmp_path_factory.mktemp("metered")
    log = tmp / "run.jsonl"
    logger = StepLogger(str(log))
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=4)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    metrics = MetricRegistry()
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      tracer=TraceRecorder(), logger=logger, metrics=metrics,
                      slo=parse_slo("ttft<=60s@p99;e2e<=120s@p99"),
                      metrics_dir=str(tmp / "snaps"))
    comps = eng.run(list(reqs), max_wall_s=300)
    logger.close()
    records = [json.loads(l) for l in log.read_text().splitlines()]
    return dict(eng=eng, metrics=metrics, comps=comps, log=log,
                records=records, snaps=tmp / "snaps")


def test_engine_derives_series_from_completions(metered_run):
    m, comps = metered_run["metrics"], metered_run["comps"]
    assert m.sum_counter("serve_requests") == len(comps) == 8
    assert m.sum_counter("serve_tokens") == sum(c.generated for c in comps)
    for name in ("serve_e2e_s", "serve_ttft_s", "serve_queue_wait_s",
                 "serve_tpot_s", "serve_tokens_per_request"):
        assert m.aggregate_hist(name).count == 8, name
    # phase walls derived from the span trees, dispatch/sync from quanta
    assert m.aggregate_hist("serve_phase_s").count > 0
    assert m.aggregate_hist("serve_dispatch_s").count > 0
    # e2e dominates ttft per construction
    assert m.aggregate_hist("serve_e2e_s").max >= m.aggregate_hist("serve_ttft_s").min


def test_slo_and_metrics_rows_land_in_jsonl(metered_run):
    records = metered_run["records"]
    slo_rows = [r for r in records if r["kind"] == "slo"]
    assert slo_rows
    last = slo_rows[-1]
    assert last["overall_compliance"] == 1.0  # generous bounds
    assert {t["metric"] for t in last["targets"]} == {"ttft", "e2e"}
    for t in last["targets"]:
        assert t["cum_burn"] == 0.0 and t["met"] in (True, None)
    (mrec,) = [r for r in records if r["kind"] == "metrics"]
    assert mrec["source"] == "serve" and mrec["hists"]
    (summ,) = [r for r in records if r["kind"] == "serve_summary"]
    assert summ["slo_overall_compliance"] == 1.0


def test_engine_publishes_and_merges_snapshots(metered_run):
    snaps = metered_run["snaps"]
    assert (snaps / metrics_lib.MERGED_NAME).is_file()
    assert (snaps / metrics_lib.OPENMETRICS_NAME).is_file()
    merged, meta = metrics_lib.merge_snapshot_dir(snaps)
    assert meta["merged"] >= 1 and meta["skipped"] == 0
    m = metered_run["metrics"]
    assert merged.sum_counter("serve_tokens") == m.sum_counter("serve_tokens")
    assert (merged.aggregate_hist("serve_e2e_s").buckets
            == m.aggregate_hist("serve_e2e_s").buckets)


# ---------------------------------------------------------------------------
# Fleet: merged snapshot dir == single engine aggregate, bucket-exact.
# ---------------------------------------------------------------------------


def test_fleet_merged_equals_single_engine(tok, cfg, params, host_params,
                                           tmp_path):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = list(synthetic_request_stream(tok, 8, seed=3,
                                         max_new_tokens=MAX_NEW,
                                         buckets=(8, 16)))
    m_single = MetricRegistry()
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      metrics=m_single)
    comps1 = eng.run(list(reqs), max_wall_s=300)

    snaps = tmp_path / "snaps"
    m_fleet = MetricRegistry()
    router = FleetRouter(host_params, cfg, serve,
                         FleetConfig(replicas=2, window_steps=4),
                         eos_id=int(tok.eos_token_id), metrics=m_fleet,
                         metrics_dir=str(snaps))
    comps2 = router.run(list(reqs), max_wall_s=300)

    # the premise: greedy decode makes per-request token counts a
    # deterministic function of the request, replica placement aside
    assert ({c.rid: c.generated for c in comps1}
            == {c.rid: c.generated for c in comps2})

    merged, meta = metrics_lib.merge_snapshot_dir(snaps)
    assert meta["merged"] == 2 and meta["skipped"] == 0  # one per replica
    # deterministic series merge bucket-exact equal to the single engine
    h1 = m_single.aggregate_hist("serve_tokens_per_request")
    h2 = merged.aggregate_hist("serve_tokens_per_request")
    assert h2.buckets == h1.buckets
    assert (h2.count, h2.min, h2.max) == (h1.count, h1.min, h1.max)
    assert h2.quantile(0.5) == h1.quantile(0.5)
    assert merged.sum_counter("serve_requests") == 8
    assert (merged.sum_counter("serve_tokens")
            == m_single.sum_counter("serve_tokens"))
    # ... and the shared in-memory fleet registry agrees with its own
    # published-files merge (publish -> read -> merge loses nothing)
    assert (m_fleet.aggregate_hist("serve_tokens_per_request").buckets
            == h2.buckets)


# ---------------------------------------------------------------------------
# Tools: the report gates, the slo/metrics panels, top.py.
# ---------------------------------------------------------------------------


def test_report_renders_slo_and_metrics_sections(metered_run):
    report = importlib.import_module("tools.report")
    text = report.summarize(metered_run["records"])
    assert "== slo ==" in text
    assert "== metrics (serve) ==" in text
    assert "slo: overall compliance 100.00%" in text


def test_report_surfaces_trace_ring_evictions(metered_run):
    report = importlib.import_module("tools.report")
    records = [dict(r) for r in metered_run["records"]]
    for r in records:
        if r["kind"] == "serve_summary":
            r["trace_dropped"] = 5
            r["trace_dropped_by_replica"] = {"0": 3, "1": 2}
    text = report.summarize(records)
    assert "DROPPED EVENTS" in text and "r0: 3, r1: 2" in text
    # the healthy log carries no eviction warning
    assert "DROPPED EVENTS" not in report.summarize(metered_run["records"])


def test_report_min_slo_compliance_gate(metered_run, tmp_path):
    report = importlib.import_module("tools.report")
    records, log = metered_run["records"], metered_run["log"]
    ok, msg = report.check_min_slo_compliance(records, 0.99)
    assert ok and "OK" in msg
    ok, msg = report.check_min_slo_compliance(records, 1.01)
    assert not ok
    # anti-vacuous: a log with no slo rows FAILS the gate
    ok, msg = report.check_min_slo_compliance(
        [r for r in records if r["kind"] != "slo"], 0.5)
    assert not ok and "--slo" in msg
    # exit-2 wiring
    assert report.main([str(log), "--min_slo_compliance", "0.99"]) == 0
    assert report.main([str(log), "--min_slo_compliance", "1.01"]) == 2
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps({"kind": "compile_cache", "hits": 0}) + "\n")
    assert report.main([str(bare), "--min_slo_compliance", "0.5"]) == 2


def test_report_compare_and_regression_gate(metered_run, tmp_path):
    report = importlib.import_module("tools.report")
    log = metered_run["log"]
    # self-compare: ~0% regression, the gate passes
    assert report.main([str(log), "--compare", str(log),
                        "--max_regression_pct", "5"]) == 0
    # a baseline whose latencies were 10x lower -> current is a huge
    # regression -> exit 2
    doctored = []
    for r in metered_run["records"]:
        r = dict(r)
        if r["kind"] == "metrics":
            r["hists"] = [
                {**h, "p50": (h["p50"] or 0) / 10, "p99": (h["p99"] or 0) / 10}
                for h in r["hists"]
            ]
        doctored.append(r)
    base = tmp_path / "baseline.jsonl"
    base.write_text("\n".join(json.dumps(r) for r in doctored) + "\n")
    assert report.main([str(log), "--compare", str(base),
                        "--max_regression_pct", "50"]) == 2
    # anti-vacuous: gating on regression without a baseline fails
    assert report.main([str(log), "--max_regression_pct", "50"]) == 2
    ok, msg = report.check_max_regression_pct(metered_run["records"], 50.0)
    assert not ok and "--compare" in msg


def test_top_renders_one_frame(metered_run, capsys):
    top = importlib.import_module("tools.top")
    rc = top.main([str(metered_run["snaps"]), "--once",
                   "--log", str(metered_run["log"])])
    assert rc == 0
    out = capsys.readouterr().out
    assert "tpukit top" in out
    assert "serve_e2e_s" in out
    assert "slo (" in out  # the SLO panel from --log


def test_top_exits_nonzero_without_snapshots(tmp_path, capsys):
    top = importlib.import_module("tools.top")
    assert top.main([str(tmp_path / "empty"), "--once"]) == 1
