"""Integration tests for the shared trainer (SURVEY §4 integration plan):
N steps on the sliced offline fixture, loss decreases, checkpoint
save/restore round-trips, resume continues from the saved step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpukit import checkpoint as ckpt_lib
from tpukit.flags import TrainFlags
from tpukit.shardings import SingleDevice
from tpukit.train import create_train_state, fit, make_optimizer, make_step_fns
from tpukit.model import GPTConfig


def _tiny_flags(tmp_path, **kw):
    defaults = dict(
        batch_size=16,
        epochs=1,
        sequence_length=64,
        dim=64,
        head_dim=16,
        heads=4,
        num_layers=2,
        learning_rate=1e-3,
        dataset_slice="128",
        num_workers=0,
        disable_amp=True,  # fp32 on CPU for determinism
        seed=0,
    )
    defaults.update(kw)
    return TrainFlags(**defaults)


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("train")
    import os

    cwd = os.getcwd()
    os.chdir(tmp)  # checkpoints/ lands in tmp
    try:
        flags = _tiny_flags(tmp)
        result = fit(flags, SingleDevice())
    finally:
        os.chdir(cwd)
    return flags, result


def test_fit_trains_and_checkpoints(fitted):
    flags, result = fitted
    assert result.metrics["eval"]["loss"] < 7.0
    assert result.checkpoint_path is not None and result.checkpoint_path.exists()
    assert int(result.state.step) == 8  # 128 rows / 16 batch x 1 epoch


def test_loss_decreases(fitted):
    """Train a fresh model a few steps by hand; loss at the end must beat
    loss at the start (the reference's de-facto correctness signal)."""
    _, result = fitted
    cfg = result.config
    opt = make_optimizer(1e-3)
    strategy = SingleDevice()
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    train_step, _, _ = make_step_fns(cfg, opt, strategy, shapes)

    rng = np.random.RandomState(0)
    ids = rng.randint(3, cfg.vocab_size, size=(16, 32)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.broadcast_to(np.arange(32, dtype=np.int32), ids.shape).copy(),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    first = None
    for _ in range(20):
        state, loss = train_step(state, batch, targets)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_checkpoint_roundtrip(fitted, tmp_path):
    _, result = fitted
    state = result.state
    path = ckpt_lib.save(state, tmp_path, name="roundtrip.msgpack")
    template = jax.device_get(state)
    restored = ckpt_lib.restore(template, path)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        restored,
    )


def test_resume_continues(fitted, tmp_path):
    """The restore path the reference lacks (SURVEY §2.8: checkpoints are
    write-only there)."""
    flags, result = fitted
    import os

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        resumed = fit(
            _tiny_flags(tmp_path, resume=str(result.checkpoint_path), epochs=1),
            SingleDevice(),
        )
    finally:
        os.chdir(cwd)
    assert int(resumed.state.step) == int(result.state.step) + 8


def test_latest_checkpoint(tmp_path):
    assert ckpt_lib.latest(tmp_path) is None
    (tmp_path / "checkpoint-2026-01-01_00-00-00.msgpack").write_bytes(b"a")
    (tmp_path / "checkpoint-2026-01-02_00-00-00.msgpack").write_bytes(b"b")
    assert ckpt_lib.latest(tmp_path).name.startswith("checkpoint-2026-01-02")


def test_batch_divisor_validation(tmp_path):
    """A global batch that cannot split into the pipeline's micro-batches x
    data shards must fail fast with a clear message, before any tracing."""
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    strategy = Pipeline(create_mesh({"stage": 2}), num_microbatches=3)
    with pytest.raises(ValueError, match="multiple of"):
        fit(_tiny_flags(tmp_path, batch_size=16), strategy, num_epochs=0)


def test_fit_pipeline_ragged_dataset(tmp_path):
    """ADVICE r1 (medium): a dataset length not divisible by the batch size
    under a pure stage mesh used to raise mid-epoch on the final partial
    batch; pad_to_batch now wraps it to full shape."""
    import os

    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        flags = _tiny_flags(tmp_path, batch_size=16, dataset_slice="40")
        result = fit(flags, Pipeline(create_mesh({"stage": 2})))
    finally:
        os.chdir(cwd)
    # 40 rows pad to 48 -> 3 full batches of 16
    assert int(result.state.step) == 3
    assert np.isfinite(result.metrics["eval"]["loss"])


def test_debug_nans_flag(tmp_path):
    """SURVEY §5 debug toolchain: --debug_nans flips jax_debug_nans inside
    the training scope and restores it afterwards (no process-global leak)."""
    import os

    from tpukit.flags import parse_flags
    from tpukit.train import _debug_nans_scope

    assert parse_flags([]).debug_nans is False
    assert parse_flags(["--debug_nans"]).debug_nans is True

    assert not jax.config.jax_debug_nans
    with _debug_nans_scope():
        assert jax.config.jax_debug_nans
        # NaNs inside jitted code now raise instead of propagating
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.float32(-1.0)).block_until_ready()
    assert not jax.config.jax_debug_nans

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        fit(_tiny_flags(tmp_path, debug_nans=True), SingleDevice(), num_epochs=0)
        assert not jax.config.jax_debug_nans  # restored after fit
    finally:
        os.chdir(cwd)


def test_multihost_input_assembly():
    """VERDICT r1 #3: the multi-host input path. Per-host DistributedSampler
    shards must partition each global batch, and the assembly into a sharded
    global array must place every host's rows at the right global offsets."""
    import jax.sharding as jsh

    from tpukit.data import ArrayDataset
    from tpukit.loader import DataLoader
    from tpukit.mesh import create_mesh
    from tpukit.train import make_global_batch

    ds = ArrayDataset(
        np.arange(128).reshape(32, 4).astype(np.int32),
        np.ones((32, 4), dtype=np.int32),
    )
    procs, per_host = 4, 4  # global batch 16
    shards = []
    for rank in range(procs):
        loader = DataLoader(
            ds, per_host, shuffle=True, seed=0, pad_to_batch=True,
            num_replicas=procs, rank=rank,
        )
        loader.set_epoch(0)
        shards.append(list(loader))
    # each global step's rank shards are disjoint; the epoch covers all rows
    seen = set()
    for step in range(len(shards[0])):
        rows = np.concatenate([shards[r][step]["input_ids"] for r in range(procs)])
        keys = set(map(tuple, rows))
        assert len(keys) == 16  # no overlap within the global batch
        seen |= keys
    assert len(seen) == 32

    # single-process identity path
    mesh = create_mesh({"data": 8})
    sh = jsh.NamedSharding(mesh, jsh.PartitionSpec("data"))
    mb = {"input_ids": np.zeros((16, 4), np.int32)}
    tg = np.zeros((16, 4), np.int32)
    out_mb, out_tg = make_global_batch(sh, mb, tg)
    assert out_mb["input_ids"] is mb["input_ids"]  # no copy when 1 process

    # assembly semantics (single process: local data == global data; the
    # same call on a pod assembles per-process shards at their offsets)
    arr = jax.make_array_from_process_local_data(sh, np.arange(16 * 4, dtype=np.int32).reshape(16, 4))
    assert arr.shape == (16, 4)
    assert arr.sharding.spec == jsh.PartitionSpec("data")
    np.testing.assert_array_equal(np.asarray(arr), np.arange(64, dtype=np.int32).reshape(16, 4))


def test_sharded_checkpoint_cross_strategy(tmp_path):
    """VERDICT r1 #7: sharded save under one strategy, restore into a
    DIFFERENT strategy's shardings, values identical. No host ever holds
    the full state during save."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP, DataParallel

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    opt = make_optimizer(1e-3)
    fsdp = FSDP(create_mesh({"data": 8}))
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(state, fsdp.state_sharding(shapes))

    path = ckpt_lib.save_sharded(state, tmp_path, name="xstrategy")
    assert (path / "manifest.json").exists()
    assert list(path.glob("shard-*.npz"))

    dp = DataParallel(create_mesh({"data": 8}))
    template = jax.eval_shape(lambda: state)
    restored = ckpt_lib.restore_sharded(
        path, template, dp.state_sharding(template)
    )
    # values identical to the FSDP-sharded original
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        jax.device_get(restored),
    )
    # and actually placed with the DP (replicated-param) shardings
    leaf = restored.params["lm_head"]["kernel"]
    assert leaf.sharding.is_fully_replicated

    assert ckpt_lib.latest_sharded(tmp_path) == path


def test_sharded_checkpoint_detects_missing_shards(tmp_path):
    """A lost shard-*.npz must fail restore loudly, never fill weights with
    uninitialized memory."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    opt = make_optimizer(1e-3)
    fsdp = FSDP(create_mesh({"data": 8}))
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(state, fsdp.state_sharding(shapes))
    path = ckpt_lib.save_sharded(state, tmp_path, name="lossy")

    # simulate a lost shard file by dropping every key of one leaf
    import numpy as np_mod

    f = next(path.glob("shard-*.npz"))
    ar = np_mod.load(f)
    kept = {k: ar[k] for k in ar.files if not k.startswith("4|")}
    np_mod.savez(f, **kept)

    with pytest.raises(ValueError, match="elements"):
        ckpt_lib.restore_sharded(path, jax.eval_shape(lambda: state),
                                 fsdp.state_sharding(shapes))


def test_pipeline_microbatch_validation():
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    with pytest.raises(ValueError, match="positive"):
        Pipeline(create_mesh({"stage": 2}), num_microbatches=-4)
    with pytest.raises(ValueError, match="positive"):
        Pipeline(create_mesh({"stage": 2}), num_microbatches="0x")
    assert Pipeline(create_mesh({"stage": 2}), num_microbatches="4x").num_microbatches == 8
