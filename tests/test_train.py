"""Integration tests for the shared trainer (SURVEY §4 integration plan):
N steps on the sliced offline fixture, loss decreases, checkpoint
save/restore round-trips, resume continues from the saved step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpukit import checkpoint as ckpt_lib
from tpukit.flags import TrainFlags
from tpukit.shardings import SingleDevice
from tpukit.train import create_train_state, fit, make_optimizer, make_step_fns
from tpukit.model import GPTConfig


def _tiny_flags(tmp_path, **kw):
    defaults = dict(
        batch_size=16,
        epochs=1,
        sequence_length=64,
        dim=64,
        head_dim=16,
        heads=4,
        num_layers=2,
        learning_rate=1e-3,
        dataset_slice="128",
        num_workers=0,
        disable_amp=True,  # fp32 on CPU for determinism
        seed=0,
    )
    defaults.update(kw)
    return TrainFlags(**defaults)


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("train")
    import os

    cwd = os.getcwd()
    os.chdir(tmp)  # checkpoints/ lands in tmp
    try:
        flags = _tiny_flags(tmp)
        result = fit(flags, SingleDevice())
    finally:
        os.chdir(cwd)
    return flags, result


def test_fit_trains_and_checkpoints(fitted):
    flags, result = fitted
    assert result.metrics["eval"]["loss"] < 7.0
    assert result.checkpoint_path is not None and result.checkpoint_path.exists()
    assert int(result.state.step) == 8  # 128 rows / 16 batch x 1 epoch


def test_loss_decreases(fitted):
    """Train a fresh model a few steps by hand; loss at the end must beat
    loss at the start (the reference's de-facto correctness signal)."""
    _, result = fitted
    cfg = result.config
    opt = make_optimizer(1e-3)
    strategy = SingleDevice()
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    train_step, _, _ = make_step_fns(cfg, opt, strategy, shapes)

    rng = np.random.RandomState(0)
    ids = rng.randint(3, cfg.vocab_size, size=(16, 32)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.broadcast_to(np.arange(32, dtype=np.int32), ids.shape).copy(),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    first = None
    for _ in range(20):
        state, loss = train_step(state, batch, targets)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_checkpoint_roundtrip(fitted, tmp_path):
    _, result = fitted
    state = result.state
    path = ckpt_lib.save(state, tmp_path, name="roundtrip.msgpack")
    template = jax.device_get(state)
    restored = ckpt_lib.restore(template, path)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        restored,
    )


def test_resume_continues(fitted, tmp_path):
    """The restore path the reference lacks (SURVEY §2.8: checkpoints are
    write-only there)."""
    flags, result = fitted
    import os

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        resumed = fit(
            _tiny_flags(tmp_path, resume=str(result.checkpoint_path), epochs=1),
            SingleDevice(),
        )
    finally:
        os.chdir(cwd)
    assert int(resumed.state.step) == int(result.state.step) + 8


def test_latest_checkpoint(tmp_path):
    assert ckpt_lib.latest(tmp_path) is None
    (tmp_path / "checkpoint-2026-01-01_00-00-00.msgpack").write_bytes(b"a")
    (tmp_path / "checkpoint-2026-01-02_00-00-00.msgpack").write_bytes(b"b")
    assert ckpt_lib.latest(tmp_path).name.startswith("checkpoint-2026-01-02")
    # step-keyed names win over legacy timestamped ones, and order by step
    (tmp_path / "checkpoint-step000000002.msgpack").write_bytes(b"c")
    (tmp_path / "checkpoint-step000000010.msgpack").write_bytes(b"d")
    assert ckpt_lib.latest(tmp_path).name == "checkpoint-step000000010.msgpack"


def test_step_keyed_checkpoint_names(fitted, tmp_path):
    """VERDICT r2 weak #7: saves are keyed by training step, so two saves in
    the same wall-clock second cannot collide, and resume-from-latest picks
    by step."""
    _, result = fitted
    state = result.state  # step == 8
    path = ckpt_lib.save(state, tmp_path)
    assert path.name == "checkpoint-step000000008.msgpack"
    spath = ckpt_lib.save_sharded(state, tmp_path)
    assert spath.name == "checkpoint-step000000008.sharded"
    # saving the same step twice is idempotent, not an error
    assert ckpt_lib.save_sharded(state, tmp_path) == spath


def test_save_auto_routing(fitted, tmp_path):
    """VERDICT r2 #1: the consolidated path must never be taken for state
    that spans hosts without replication; single-host state keeps the
    reference-parity consolidated format."""
    _, result = fitted
    state = result.state

    # single host: everything addressable -> consolidated
    assert not ckpt_lib.needs_sharded(state)
    path = ckpt_lib.save_auto(state, tmp_path)
    assert path.suffix == ".msgpack"

    # a leaf spanning hosts without replication -> sharded is mandatory
    class _CrossHostLeaf:
        is_fully_addressable = False
        is_fully_replicated = False

    assert ckpt_lib.needs_sharded({"w": _CrossHostLeaf()})
    # multi-host but fully replicated -> consolidated still fine (each host
    # holds a full copy; the reference's own gather-then-save regime)
    class _ReplicatedLeaf:
        is_fully_addressable = False
        is_fully_replicated = True

    assert not ckpt_lib.needs_sharded({"w": _ReplicatedLeaf()})

    # forced sharded writes a .sharded dir; restore_any handles both formats
    spath = ckpt_lib.save_auto(state, tmp_path, name="forced", format="sharded")
    assert spath.name == "forced.sharded" and spath.is_dir()
    shapes = jax.eval_shape(lambda: state)
    repl = jax.tree.map(lambda l: l.sharding, state)
    restored, was_sharded = ckpt_lib.restore_any(spath, shapes, repl)
    assert was_sharded
    np.testing.assert_array_equal(
        np.asarray(restored.params["norm_out"]["scale"]),
        np.asarray(state.params["norm_out"]["scale"]),
    )
    restored, was_sharded = ckpt_lib.restore_any(path, shapes)
    assert not was_sharded
    assert int(restored.step) == int(state.step)


def test_latest_any_across_formats(fitted, tmp_path):
    """Resume-from-latest compares both formats by step."""
    _, result = fitted
    state = result.state
    older = state.replace(step=jnp.int32(3))
    ckpt_lib.save_sharded(older, tmp_path)
    newer = ckpt_lib.save(state, tmp_path)  # step 8
    assert ckpt_lib.latest_any(tmp_path) == newer
    newest = ckpt_lib.save_sharded(state.replace(step=jnp.int32(11)), tmp_path)
    assert ckpt_lib.latest_any(tmp_path) == newest


def test_resume_from_sharded_latest(tmp_path):
    """--checkpoint_format sharded + --resume latest: fit writes the sharded
    dir under a sharded strategy and resumes from it (the multi-host-default
    path, exercised on the 8-device mesh)."""
    import os

    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        flags = _tiny_flags(tmp_path, checkpoint_format="sharded")
        result = fit(flags, FSDP(create_mesh({"data": 8})))
        assert result.checkpoint_path.name.endswith(".sharded")
        assert result.checkpoint_path.is_dir()
        resumed = fit(
            _tiny_flags(tmp_path, checkpoint_format="sharded", resume="latest"),
            FSDP(create_mesh({"data": 8})),
        )
    finally:
        os.chdir(cwd)
    # one more epoch on top of the restored step count (the FSDP global
    # batch is batch_size x 8 shards, so an epoch is dataset/128 steps)
    assert int(resumed.state.step) == 2 * int(result.state.step)


def test_save_auto_with_unwritable_consolidated_is_never_called(monkeypatch):
    """The guarantee VERDICT r2 #1 asks for: when the state needs sharding,
    save_auto must not touch the consolidated writer at all."""

    class _CrossHostLeaf:
        is_fully_addressable = False
        is_fully_replicated = False

    state = {"w": _CrossHostLeaf()}

    def boom(*a, **k):
        raise AssertionError("consolidated save called for cross-host state")

    monkeypatch.setattr(ckpt_lib, "save", boom)
    called = {}
    monkeypatch.setattr(
        ckpt_lib, "save_sharded",
        lambda s, d="checkpoints", n=None, meta=None: called.setdefault("ok", True),
    )
    assert ckpt_lib.save_auto(state) is True
    assert called["ok"]


def test_batch_divisor_validation(tmp_path):
    """A global batch that cannot split into the pipeline's micro-batches x
    data shards must fail fast with a clear message, before any tracing."""
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    strategy = Pipeline(create_mesh({"stage": 2}), num_microbatches=3)
    with pytest.raises(ValueError, match="multiple of"):
        fit(_tiny_flags(tmp_path, batch_size=16), strategy, num_epochs=0)


def test_fit_pipeline_ragged_dataset(tmp_path):
    """ADVICE r1 (medium): a dataset length not divisible by the batch size
    under a pure stage mesh used to raise mid-epoch on the final partial
    batch; pad_to_batch now wraps it to full shape."""
    import os

    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        flags = _tiny_flags(tmp_path, batch_size=16, dataset_slice="40")
        result = fit(flags, Pipeline(create_mesh({"stage": 2})))
    finally:
        os.chdir(cwd)
    # 40 rows pad to 48 -> 3 full batches of 16
    assert int(result.state.step) == 3
    assert np.isfinite(result.metrics["eval"]["loss"])


def test_debug_nans_flag(tmp_path):
    """SURVEY §5 debug toolchain: --debug_nans flips jax_debug_nans inside
    the training scope and restores it afterwards (no process-global leak)."""
    import os

    from tpukit.flags import parse_flags
    from tpukit.train import _debug_nans_scope

    assert parse_flags([]).debug_nans is False
    assert parse_flags(["--debug_nans"]).debug_nans is True

    assert not jax.config.jax_debug_nans
    with _debug_nans_scope():
        assert jax.config.jax_debug_nans
        # NaNs inside jitted code now raise instead of propagating
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.float32(-1.0)).block_until_ready()
    assert not jax.config.jax_debug_nans

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        fit(_tiny_flags(tmp_path, debug_nans=True), SingleDevice(), num_epochs=0)
        assert not jax.config.jax_debug_nans  # restored after fit
    finally:
        os.chdir(cwd)


def test_multihost_input_assembly():
    """VERDICT r1 #3: the multi-host input path. Per-host DistributedSampler
    shards must partition each global batch, and the assembly into a sharded
    global array must place every host's rows at the right global offsets."""
    import jax.sharding as jsh

    from tpukit.data import ArrayDataset
    from tpukit.loader import DataLoader
    from tpukit.mesh import create_mesh
    from tpukit.train import make_global_batch

    ds = ArrayDataset(
        np.arange(128).reshape(32, 4).astype(np.int32),
        np.ones((32, 4), dtype=np.int32),
    )
    procs, per_host = 4, 4  # global batch 16
    shards = []
    for rank in range(procs):
        loader = DataLoader(
            ds, per_host, shuffle=True, seed=0, pad_to_batch=True,
            num_replicas=procs, rank=rank,
        )
        loader.set_epoch(0)
        shards.append(list(loader))
    # each global step's rank shards are disjoint; the epoch covers all rows
    seen = set()
    for step in range(len(shards[0])):
        rows = np.concatenate([shards[r][step]["input_ids"] for r in range(procs)])
        keys = set(map(tuple, rows))
        assert len(keys) == 16  # no overlap within the global batch
        seen |= keys
    assert len(seen) == 32

    # single-process identity path
    mesh = create_mesh({"data": 8})
    sh = jsh.NamedSharding(mesh, jsh.PartitionSpec("data"))
    mb = {"input_ids": np.zeros((16, 4), np.int32)}
    tg = np.zeros((16, 4), np.int32)
    out_mb, out_tg = make_global_batch(sh, mb, tg)
    assert out_mb["input_ids"] is mb["input_ids"]  # no copy when 1 process

    # assembly semantics (single process: local data == global data; the
    # same call on a pod assembles per-process shards at their offsets)
    arr = jax.make_array_from_process_local_data(sh, np.arange(16 * 4, dtype=np.int32).reshape(16, 4))
    assert arr.shape == (16, 4)
    assert arr.sharding.spec == jsh.PartitionSpec("data")
    np.testing.assert_array_equal(np.asarray(arr), np.arange(64, dtype=np.int32).reshape(16, 4))


def test_sharded_checkpoint_cross_strategy(tmp_path):
    """VERDICT r1 #7: sharded save under one strategy, restore into a
    DIFFERENT strategy's shardings, values identical. No host ever holds
    the full state during save."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP, DataParallel

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    opt = make_optimizer(1e-3)
    fsdp = FSDP(create_mesh({"data": 8}))
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(state, fsdp.state_sharding(shapes))

    path = ckpt_lib.save_sharded(state, tmp_path, name="xstrategy")
    assert (path / "manifest.json").exists()
    assert list(path.glob("shard-*.npz"))

    dp = DataParallel(create_mesh({"data": 8}))
    template = jax.eval_shape(lambda: state)
    restored = ckpt_lib.restore_sharded(
        path, template, dp.state_sharding(template)
    )
    # values identical to the FSDP-sharded original
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        jax.device_get(restored),
    )
    # and actually placed with the DP (replicated-param) shardings
    leaf = restored.params["lm_head"]["kernel"]
    assert leaf.sharding.is_fully_replicated

    assert ckpt_lib.latest_sharded(tmp_path) == path


def test_sharded_checkpoint_detects_missing_shards(tmp_path):
    """A lost shard-*.npz must fail restore loudly, never fill weights with
    uninitialized memory."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import FSDP

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=97,
        max_position_embeddings=32, compute_dtype=jnp.float32,
    )
    opt = make_optimizer(1e-3)
    fsdp = FSDP(create_mesh({"data": 8}))
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    state = jax.device_put(state, fsdp.state_sharding(shapes))
    path = ckpt_lib.save_sharded(state, tmp_path, name="lossy")

    # simulate a lost shard file by dropping every key of one leaf
    import numpy as np_mod

    f = next(path.glob("shard-*.npz"))
    ar = np_mod.load(f)
    kept = {k: ar[k] for k in ar.files if not k.startswith("4|")}
    np_mod.savez(f, **kept)

    with pytest.raises(ValueError, match="elements"):
        ckpt_lib.restore_sharded(path, jax.eval_shape(lambda: state),
                                 fsdp.state_sharding(shapes))


def test_pipeline_microbatch_validation():
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline

    with pytest.raises(ValueError, match="positive"):
        Pipeline(create_mesh({"stage": 2}), num_microbatches=-4)
    with pytest.raises(ValueError, match="positive"):
        Pipeline(create_mesh({"stage": 2}), num_microbatches="0x")
    assert Pipeline(create_mesh({"stage": 2}), num_microbatches="4x").num_microbatches == 8
