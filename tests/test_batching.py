"""Goldens for prepare_batch (reference utils.py:5-39 is subtle: shift-by-one,
-100 masking, mask inversion + last-column drop) and the loss/accuracy ops,
cross-checked against torch where available (SURVEY §4)."""

import numpy as np
import jax.numpy as jnp

from tpukit.batching import prepare_batch
from tpukit.ops.layers import cross_entropy_loss, masked_accuracy

PAD = 2


def test_prepare_batch_golden():
    batch = {
        "input_ids": np.array([[5, 6, 7, PAD, PAD]], dtype=np.int64),
        "attention_mask": np.array([[1, 1, 1, 0, 0]], dtype=np.int64),
    }
    model_batch, targets = prepare_batch(batch, PAD)

    np.testing.assert_array_equal(model_batch["input_ids"], [[5, 6, 7, PAD]])
    # targets: shifted by one, pad -> -100 (utils.py:22,25)
    np.testing.assert_array_equal(targets, [[6, 7, -100, -100]])
    # position ids arange(S-1) (utils.py:28-30)
    np.testing.assert_array_equal(model_batch["position_ids"], [[0, 1, 2, 3]])
    # mask inverted (True = masked) with last column dropped (utils.py:17,36)
    np.testing.assert_array_equal(model_batch["mask"], [[False, False, False, True]])


def test_prepare_batch_no_padding():
    batch = {
        "input_ids": np.array([[1, 3, 4, 5]], dtype=np.int64),
        "attention_mask": np.ones((1, 4), dtype=np.int64),
    }
    model_batch, targets = prepare_batch(batch, PAD)
    np.testing.assert_array_equal(targets, [[3, 4, 5]])
    assert not model_batch["mask"].any()


def test_cross_entropy_matches_torch():
    torch = __import__("pytest").importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    logits = rng.randn(3, 7, 11).astype(np.float32)
    targets = rng.randint(0, 11, size=(3, 7))
    targets[0, -2:] = -100
    targets[2, 0] = -100

    ours = cross_entropy_loss(jnp.asarray(logits), jnp.asarray(targets))
    theirs = F.cross_entropy(
        torch.tensor(logits).view(-1, 11), torch.tensor(targets).view(-1), ignore_index=-100
    )
    np.testing.assert_allclose(float(ours), float(theirs), rtol=1e-5)


def test_cross_entropy_all_ignored_is_finite():
    logits = jnp.zeros((1, 3, 5))
    targets = jnp.full((1, 3), -100)
    assert float(cross_entropy_loss(logits, targets)) == 0.0


def test_masked_accuracy():
    logits = jnp.asarray(
        np.array([[[0.0, 2.0, 0.0], [5.0, 0.0, 0.0], [0.0, 0.0, 9.0]]], dtype=np.float32)
    )  # argmax: 1, 0, 2
    targets = jnp.asarray(np.array([[1, 1, -100]]))
    # valid positions: 2; correct: 1 -> 50%
    assert float(masked_accuracy(logits, targets)) == 50.0
