"""Speculative decoding (tpukit/serve/spec, round 17, ROADMAP #3).

Contracts pinned here:
  - `_accept_prefix` IS rejection sampling: bit-for-bit against a plain
    Python loop reference over random windows (greedy and temperature/
    top-k), including the k=0 degenerate (one vanilla target sample) and
    the all-reject window (one corrected token from the residual);
  - distribution EXACTNESS, the whole point: the marginal of the first
    emitted token equals the target distribution p — not the proposal —
    for both a smooth sampled proposal and a deterministic one-hot
    proposer, measured empirically over thousands of keys;
  - the host `NGramProposer` and the fused on-device `_ngram_propose_row`
    are the SAME proposer, bit-for-bit, over random and crafted periodic
    histories;
  - the ENGINE with speculation on is distribution-exact end to end:
    greedy spec-decode output is token-identical to the vanilla engine
    over ragged prompts and mid-stream admit/evict for BOTH proposers
    (incl. a draft==target run that accepts everything, exercising the
    multi-token append path), and fixed-seed sampled output at
    temperature 0.8 + top-k is token-identical to the serial
    `reference_spec_decode` spelling;
  - `ServeConfig`/engine construction rejects bad spec configs by NAME
    (draft+paged, vocab/tokenizer mismatch, missing draft params) instead
    of shape-erroring at the first verify;
  - `--stream_profile` reproduces the repetitive / shared-prefix workload
    shapes from one spelling;
  - spec telemetry lands in the serve JSONL windows + summary, report.py
    renders it, and `--min_accept_rate` gates on it (incl. the vacuous
    no-spec-log failure).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.sampling import _adjust_logits
from tpukit.serve import ServeConfig, ServeEngine, synthetic_request_stream
from tpukit.serve.spec import (
    _SALT_ACCEPT,
    _SALT_FIX,
    NGramProposer,
    _accept_prefix,
    _ngram_propose_row,
    reference_spec_decode,
    spec_ngram_step,
)

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


# ---------------------------------------------------------------------------
# _accept_prefix: bit-for-bit against a plain-loop rejection sampler.
# ---------------------------------------------------------------------------


def _ref_accept(logits, draft, q_probs, draft_len, key, cursor,
                temperature, top_k):
    """The obvious serial spelling of the acceptance pass — same draw
    streams as `_accept_prefix`, zero vectorization tricks: walk the
    draft left to right, accept d_i iff u_i < min(1, p(d_i)/q(d_i)),
    correct from the residual on the first rejection, bonus-sample from
    p when everything survives."""
    logits = np.asarray(logits, np.float64)
    k = len(draft)
    if temperature > 0.0:
        adj = np.asarray(
            _adjust_logits(jnp.asarray(logits, jnp.float32), temperature,
                           top_k), np.float64)
        p = np.exp(adj - adj.max(axis=-1, keepdims=True))
        p /= p.sum(axis=-1, keepdims=True)
        accepted = 0
        for i in range(int(draft_len)):
            u = float(jax.random.uniform(jax.random.fold_in(
                jax.random.fold_in(key, int(cursor) + i), _SALT_ACCEPT)))
            q_d = max(float(q_probs[i, draft[i]]), 1e-30)
            if u * q_d < p[i, draft[i]]:
                accepted += 1
            else:
                break
        rejected = accepted < int(draft_len)
        p_next = p[accepted]
        if rejected:
            resid = np.maximum(p_next - np.asarray(q_probs[accepted],
                                                   np.float64), 0.0)
            dist = resid / resid.sum() if resid.sum() > 0 else p_next
        else:
            dist = p_next
        logd = np.where(dist > 0.0, np.log(np.maximum(dist, 1e-30)), -np.inf)
        fix = int(jax.random.categorical(
            jax.random.fold_in(jax.random.fold_in(
                key, int(cursor) + accepted), _SALT_FIX),
            jnp.asarray(logd, jnp.float32)))
    else:
        am = np.argmax(logits, axis=-1)
        accepted = 0
        for i in range(int(draft_len)):
            if draft[i] == am[i]:
                accepted += 1
            else:
                break
        fix = int(am[accepted])
    return accepted, list(draft[:accepted]) + [fix]


@pytest.mark.parametrize(
    "temperature,top_k",
    [(0.0, 0), (0.8, 0), (0.8, 5)],
    ids=["greedy", "t0.8", "t0.8_topk"],
)
def test_accept_prefix_matches_loop_reference(temperature, top_k):
    """Random verify windows (random target logits, random smooth
    proposal, random draft tokens, every draft_len 0..k): the vectorized
    `_accept_prefix` must agree with the serial loop on the accepted
    length AND every emitted token — the accepted prefix plus the
    corrected/bonus sample."""
    rng = np.random.RandomState(0)
    k, v = 4, 12
    for trial in range(8):
        logits = rng.randn(k + 1, v).astype(np.float32) * 2.0
        q = rng.dirichlet(np.ones(v), size=k).astype(np.float32)
        draft = rng.randint(0, v, size=k).astype(np.int32)
        for dlen in range(k + 1):
            key = jax.random.PRNGKey(100 + trial)
            cursor = int(rng.randint(1, 30))
            acc, toks = _accept_prefix(
                jnp.asarray(logits), jnp.asarray(draft), jnp.asarray(q),
                jnp.int32(dlen), key, jnp.int32(cursor), temperature, top_k,
            )
            acc, toks = int(acc), np.asarray(toks)
            ref_acc, ref_toks = _ref_accept(
                logits, draft, q, dlen, key, cursor, temperature, top_k)
            assert acc == ref_acc, (trial, dlen)
            np.testing.assert_array_equal(
                toks[: acc + 1], ref_toks, err_msg=f"trial {trial} dlen {dlen}")


def test_accept_prefix_k0_degenerate():
    """draft_len == 0 (the proposer had nothing): exactly one target
    sample — greedy argmax at the first window position, or a p-sample
    through the correction stream — i.e. a vanilla decode step."""
    rng = np.random.RandomState(1)
    logits = rng.randn(4, 9).astype(np.float32)
    draft = np.zeros((3,), np.int32)
    q = np.full((3, 9), 1 / 9, np.float32)
    acc, toks = _accept_prefix(
        jnp.asarray(logits), jnp.asarray(draft), jnp.asarray(q),
        jnp.int32(0), jax.random.PRNGKey(0), jnp.int32(5), 0.0, 0)
    assert int(acc) == 0 and int(toks[0]) == int(np.argmax(logits[0]))
    # sampled: still exactly one token, drawn from p[0] (checked
    # distributionally in test_first_token_distribution_exact)
    acc, toks = _accept_prefix(
        jnp.asarray(logits), jnp.asarray(draft), jnp.asarray(q),
        jnp.int32(0), jax.random.PRNGKey(0), jnp.int32(5), 0.8, 0)
    assert int(acc) == 0 and 0 <= int(toks[0]) < 9


def test_accept_prefix_all_reject_residual():
    """A proposer that is always wrong: one-hot q at a token the target
    gives ZERO adjusted mass (outside top-k) rejects every position and
    emits exactly ONE corrected token — and because the residual
    max(p - q, 0) zeroes the proposed token, the correction can never
    re-emit it."""
    rng = np.random.RandomState(2)
    k, v = 3, 10
    logits = rng.randn(k + 1, v).astype(np.float32)
    bad = int(np.argmin(logits[0]))  # outside top_k=2 by construction
    draft = np.full((k,), bad, np.int32)
    q = np.asarray(jax.nn.one_hot(draft, v), np.float32)
    for seed in range(32):
        acc, toks = _accept_prefix(
            jnp.asarray(logits), jnp.asarray(draft), jnp.asarray(q),
            jnp.int32(k), jax.random.PRNGKey(seed), jnp.int32(7), 0.8, 2)
        assert int(acc) == 0
        assert int(toks[0]) != bad
        # with top_k=2 the correction must be one of the two survivors
        assert int(toks[0]) in np.argsort(logits[0] / 0.8)[-2:]


def test_first_token_distribution_exact():
    """THE exactness theorem, measured: over many keys, the marginal of
    the first emitted token equals the TARGET distribution p — for a
    smooth proposal sampled from q, and for the deterministic one-hot
    proposer (the n-gram case) — even though q is deliberately far from
    p. This is what licenses speculation as an optimization rather than
    a model change."""
    n, v, temperature = 20000, 8, 1.0
    rng = np.random.RandomState(3)
    logits = rng.randn(2, v).astype(np.float32) * 1.5
    p = np.asarray(jax.nn.softmax(jnp.asarray(logits[0]) / temperature))
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(9), i))(
        jnp.arange(n))
    cursors = jnp.full((n,), 11, jnp.int32)

    def first_token(draft, q):
        _, toks = jax.vmap(
            lambda key, cur, d: _accept_prefix(
                jnp.asarray(logits), d, jnp.asarray(q), jnp.int32(1), key,
                cur, temperature, 0)
        )(keys, cursors, draft)
        return np.asarray(toks[:, 0])

    # (a) smooth q, draft ~ q per trial (an independent stream)
    q = rng.dirichlet(np.ones(v)).astype(np.float32)[None, :]
    draft = jax.vmap(
        lambda i: jax.random.categorical(
            jax.random.fold_in(jax.random.PRNGKey(77), i),
            jnp.log(jnp.asarray(q[0])))
    )(jnp.arange(n)).astype(jnp.int32)[:, None]
    emp = np.bincount(first_token(draft, q), minlength=v) / n
    assert np.abs(emp - p).max() < 0.02, (emp, p)
    # (b) deterministic proposer: one-hot q at a fixed (wrong-ish) token
    d0 = int(np.argsort(p)[v // 2])
    q1 = np.asarray(jax.nn.one_hot([d0], v), np.float32)
    draft1 = jnp.full((n, 1), d0, jnp.int32)
    emp1 = np.bincount(first_token(draft1, q1), minlength=v) / n
    assert np.abs(emp1 - p).max() < 0.02, (emp1, p)
    # the test has power: q itself is far from p
    assert np.abs(np.asarray(q[0]) - p).max() > 0.05
    assert np.abs(np.asarray(q1[0]) - p).max() > 0.05


# ---------------------------------------------------------------------------
# The n-gram proposer: host and device are the SAME proposer.
# ---------------------------------------------------------------------------


def test_ngram_host_device_parity():
    """`_ngram_propose_row` (the fused on-device spelling) must propose
    bit-for-bit what the host `NGramProposer` proposes — random
    small-alphabet histories (recurrences likely), pure periodic tails,
    and recurrence-free histories (the dlen=0 degenerate). Entries at or
    beyond the cursor are garbage on purpose: the device match must
    never consult them (the engine's buffer rows carry pad there)."""
    k, max_ngram, w = 4, 3, 24
    prop = NGramProposer(k, max_ngram=max_ngram)
    rng = np.random.RandomState(4)
    cases = []
    for _ in range(12):
        h = rng.randint(0, 5, size=w).astype(np.int32)
        cases.append((h, int(rng.randint(3, w))))
    cases.append((np.tile([7, 8, 9], 8).astype(np.int32), 18))  # periodic
    cases.append((np.tile([3, 4], 12).astype(np.int32), 20))  # short period
    cases.append((np.arange(w).astype(np.int32), 15))  # no recurrence
    for h, cur in cases:
        want = prop.propose(h[:cur])
        dirty = h.copy()
        dirty[cur:] = rng.randint(0, 99, size=w - cur)  # provably unread
        draft, dlen = _ngram_propose_row(
            jnp.asarray(dirty), jnp.int32(cur), k=k, max_ngram=max_ngram)
        dlen = int(dlen)
        got = list(np.asarray(draft)[:dlen])
        assert got == want and dlen in (0, k), (h[:cur].tolist(), cur)


def test_ngram_proposer_periodic_wrap():
    """A period-p loop proposes the full k continuation tokens however
    small p is (the wrap rule): without it, a proposal could never
    exceed p tokens — and on repetitive streams that is the whole win."""
    prop = NGramProposer(6, max_ngram=3)
    assert prop.propose([5, 6, 5, 6, 5, 6]) == [5, 6, 5, 6, 5, 6]
    assert prop.propose([1, 2, 3, 1, 2, 3, 1]) == [2, 3, 1, 2, 3, 1]
    assert prop.propose([1, 2, 3, 4, 5]) == []  # nothing recurs
    with pytest.raises(ValueError, match="k >= 1"):
        NGramProposer(0)


# ---------------------------------------------------------------------------
# Engine end-to-end: greedy token-identical to vanilla, sampled identical
# to the serial reference — over ragged prompts and mid-stream admit/evict.
# ---------------------------------------------------------------------------


def _engine_run(params, cfg, tok, reqs, serve, **kw):
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id), **kw)
    comps = eng.run(list(reqs), max_wall_s=300)
    return eng, {c.rid: c for c in comps}


@pytest.mark.parametrize("draft", ["ngram", "model"])
def test_engine_greedy_spec_equals_vanilla(tok, cfg, params, draft):
    """Greedy spec-decode must be TOKEN-IDENTICAL to the vanilla engine
    on the same stream — 8 ragged requests through 3 slots forces
    mid-stream eviction + slot reuse + admissions while other slots are
    mid-verify. The repetitive profile gives the n-gram proposer real
    acceptances, so the multi-token append path is exercised, not just
    the reject-everything fallback."""
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16),
                                    stream_profile="repetitive")
    vanilla = ServeConfig(slots=3, buckets=(8, 16), max_new_tokens=MAX_NEW,
                          window_steps=8)
    _, want = _engine_run(params, cfg, tok, reqs, vanilla)
    spec = ServeConfig(slots=3, buckets=(8, 16), max_new_tokens=MAX_NEW,
                       window_steps=8, draft=draft, spec_k=4)
    kw = (dict(draft_params=params, draft_cfg=cfg) if draft == "model"
          else {})
    eng, got = _engine_run(params, cfg, tok, reqs, spec, **kw)
    assert want.keys() == got.keys()
    for rid in want:
        np.testing.assert_array_equal(got[rid].ids, want[rid].ids,
                                      err_msg=f"rid {rid}")
        assert got[rid].reason == want[rid].reason
    if draft == "model":
        # draft == target: every greedy proposal matches the argmax, so
        # the engine must accept ~everything (the bonus-token/full-append
        # path, k+1 tokens per verify, is what's being exercised)
        assert eng.spec_accepted == eng.spec_proposed > 0
        assert sum(eng.spec_hist[:2]) < sum(eng.spec_hist)


def test_engine_spec_compile_budget(tok, cfg, params):
    """Self-speculation compiles ONE fused verify program however many
    requests/buckets/occupancies the run sweeps — the serve-path
    compile-budget discipline extended to the spec quantum."""
    before = spec_ngram_step._cache_size()
    reqs = synthetic_request_stream(tok, 6, seed=5, max_new_tokens=6,
                                    buckets=(8, 16),
                                    stream_profile="repetitive")
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8, draft="ngram", spec_k=3)
    _engine_run(params, cfg, tok, reqs, serve)
    assert spec_ngram_step._cache_size() - before <= 1


@pytest.mark.parametrize("draft", ["ngram", "model"])
def test_engine_sampled_spec_matches_serial_reference(tok, cfg, params, draft):
    """Fixed-seed sampled parity at temperature 0.8 + top-k: the engine's
    batched spec decode must reproduce the serial one-request
    `reference_spec_decode` token-for-token per request — the draws are
    position-keyed off the request key, so batching, quantum boundaries,
    and mid-stream admit/evict (5 requests through 2 slots) must not
    change a single token."""
    k, t, topk = 3, 0.8, 5
    reqs = synthetic_request_stream(tok, 5, seed=13, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16),
                                    stream_profile="repetitive")
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=t, top_k=topk, window_steps=8,
                        draft=draft, spec_k=k)
    kw = (dict(draft_params=params, draft_cfg=cfg) if draft == "model"
          else {})
    _, got = _engine_run(params, cfg, tok, reqs, serve, **kw)
    assert len(got) == 5
    for req in reqs:
        want = reference_spec_decode(
            params, cfg, req.ids, MAX_NEW, int(tok.eos_token_id), k=k,
            draft=draft, draft_params=params if draft == "model" else None,
            draft_cfg=cfg if draft == "model" else None,
            temperature=t, top_k=topk, seed=req.seed)
        np.testing.assert_array_equal(got[req.rid].ids, want,
                                      err_msg=f"rid {req.rid}")


def test_reference_greedy_matches_vanilla_serial(tok, cfg, params):
    """The serial reference itself honors exactness: greedy
    `reference_spec_decode` equals the plain serial cached decode."""
    from tests.test_serve import _serial_cached

    ids = tok(["One day, "], truncation=True, max_length=8)["input_ids"][0]
    want = _serial_cached(params, cfg, ids, MAX_NEW, tok.eos_token_id)
    for draft in ("ngram", "model"):
        got = reference_spec_decode(
            params, cfg, ids, MAX_NEW, int(tok.eos_token_id), k=3,
            draft=draft, draft_params=params if draft == "model" else None,
            draft_cfg=cfg if draft == "model" else None)
        np.testing.assert_array_equal(got, want, err_msg=draft)


# ---------------------------------------------------------------------------
# Config validation: bad spec configs fail by NAME at construction.
# ---------------------------------------------------------------------------


def test_serve_config_spec_validation(tok, cfg, params):
    with pytest.raises(ValueError, match="draft="):
        ServeConfig(draft="nope")
    with pytest.raises(ValueError, match="spec_k"):
        ServeConfig(draft="ngram", spec_k=0)
    with pytest.raises(ValueError, match="ngram_max"):
        ServeConfig(draft="ngram", ngram_max=0)
    with pytest.raises(ValueError, match="ring cache"):
        ServeConfig(draft="ngram", page_size=8, num_pages=16)
    serve = ServeConfig(slots=2, buckets=(8,), max_new_tokens=4,
                        draft="model", spec_k=3)
    # the scratch tail is part of the physical ring width
    assert serve.kv_width == serve.padded_width + 3
    with pytest.raises(ValueError, match="draft_params and draft_cfg"):
        ServeEngine(params, cfg, serve, eos_id=1)
    with pytest.raises(ValueError, match="share one tokenizer"):
        bad = cfg.replace(vocab_size=cfg.vocab_size + 1)
        ServeEngine(params, cfg, serve, eos_id=1,
                    draft_params=init_params(jax.random.PRNGKey(0), bad),
                    draft_cfg=bad)
    with pytest.raises(ValueError, match="position table"):
        small = cfg.replace(max_position_embeddings=8)
        ServeEngine(params, cfg, serve, eos_id=1,
                    draft_params=init_params(jax.random.PRNGKey(0), small),
                    draft_cfg=small)
    with pytest.raises(ValueError, match="draft='model'"):
        ServeEngine(params, cfg,
                    ServeConfig(slots=2, buckets=(8,), max_new_tokens=4),
                    eos_id=1, draft_params=params, draft_cfg=cfg)


# ---------------------------------------------------------------------------
# Stream profiles: one spelling reproduces each workload shape.
# ---------------------------------------------------------------------------


def test_stream_profiles(tok):
    with pytest.raises(ValueError, match="stream_profile"):
        synthetic_request_stream(tok, 2, stream_profile="bogus")
    rep = synthetic_request_stream(tok, 6, seed=5, buckets=(8, 16),
                                   stream_profile="repetitive")
    for r in rep:
        ids = list(r.ids)
        # every repetitive prompt is a short phrase tiled to length
        period = next(p for p in range(2, 5)
                      if all(ids[i] == ids[i % p] for i in range(len(ids))))
        assert 2 <= period <= 4
    shared = synthetic_request_stream(tok, 6, seed=5, buckets=(8, 16),
                                      stream_profile="shared_prefix")
    # shared_prefix defaults the system prompt to half the largest bucket
    head = shared[0].ids[:8]
    assert all(r.ids[:8] == head for r in shared)
    # profiles are seed-deterministic and distinct from uniform
    again = synthetic_request_stream(tok, 6, seed=5, buckets=(8, 16),
                                     stream_profile="repetitive")
    assert [r.ids for r in rep] == [r.ids for r in again]
    uni = synthetic_request_stream(tok, 6, seed=5, buckets=(8, 16))
    assert [r.ids for r in uni] != [r.ids for r in rep]


# ---------------------------------------------------------------------------
# Telemetry: spec counters land in the JSONL, report renders + gates.
# ---------------------------------------------------------------------------


def test_spec_telemetry_jsonl_report_and_gate(tok, cfg, params, tmp_path):
    import importlib

    from tpukit.obs import StepLogger

    report = importlib.import_module("tools.report")
    log = tmp_path / "spec.jsonl"
    logger = StepLogger(str(log))
    reqs = synthetic_request_stream(tok, 5, seed=8, max_new_tokens=8,
                                    buckets=(8, 16),
                                    stream_profile="repetitive")
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=4, draft="ngram", spec_k=4)
    eng, _ = _engine_run(params, cfg, tok, reqs, serve, logger=logger)
    logger.close()
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    windows = [r for r in recs if r["kind"] == "serve"]
    summary = [r for r in recs if r["kind"] == "serve_summary"][-1]
    assert windows
    for w in windows:
        sp = w["spec"]
        assert sp["draft"] == "ngram" and sp["k"] == 4
        assert len(sp["accepted_hist"]) == 4 + 2
        assert sp["accepted"] <= sp["proposed"]
    sp = summary["spec"]
    assert sp["proposed"] == eng.spec_proposed
    assert sp["accepted"] == eng.spec_accepted
    # one histogram entry per live slot-verify, and no verify can append
    # more than its accepted draft + the corrected/bonus token
    assert sum(sp["accepted_hist"]) > 0
    appended = sum(i * h for i, h in enumerate(sp["accepted_hist"]))
    assert appended <= sp["accepted"] + sum(sp["accepted_hist"])
    assert summary["verify_s"] > 0
    text = report.summarize(recs)
    assert "speculative (ngram, k=4)" in text
    assert "appended/verify histogram" in text
    # the gate: passes at 0, fails above the measured rate, and fails
    # VACUOUSLY (not passes) on a log with no spec summary at all
    ok, _ = report.check_min_accept_rate(recs, 0.0)
    assert ok
    ok, msg = report.check_min_accept_rate(recs, 1.01)
    assert not ok and "FAIL" in msg
    ok, msg = report.check_min_accept_rate(
        [r for r in recs if r["kind"] != "serve_summary"], 0.0)
    assert not ok and "no serve_summary" in msg
