"""Flash-attention kernel parity vs the XLA attention path (the reference
semantics): forward logit parity on non-padded rows, gradient parity for
q/k/v, and end-to-end model parity with attention_impl='flash'. Kernels run
in Pallas interpreter mode on the CPU mesh — the same code path the TPU
compiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.model import GPTConfig, forward, init_params
from tpukit.ops.attention import causal_attention
from tpukit.ops.pallas_attention import flash_causal_attention

B, H, S, D = 2, 4, 48, 32  # short-sequence branch: one 48-wide block, no pad
SCALE = D**-0.5


@pytest.fixture(scope="module")
def qkv(request):
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def pad_mask():
    mask = np.zeros((B, S), dtype=bool)
    mask[0, 40:] = True  # row 0 has trailing padding
    return jnp.asarray(mask)


def test_forward_matches_xla_no_mask(qkv):
    q, k, v = qkv
    ours = flash_causal_attention(q, k, v, scale=SCALE)
    ref = causal_attention(q, k, v, scale=SCALE)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_forward_matches_xla_with_mask(qkv, pad_mask):
    q, k, v = qkv
    ours = flash_causal_attention(q, k, v, scale=SCALE, pad_mask=pad_mask)
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=pad_mask)
    valid = ~np.asarray(pad_mask)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(ours)[b, :, valid[b]],
            np.asarray(ref)[b, :, valid[b]],
            atol=2e-5,
            rtol=1e-4,
        )


def test_grads_match_xla(qkv, pad_mask):
    q, k, v = qkv

    def loss_flash(q, k, v):
        out = flash_causal_attention(q, k, v, scale=SCALE, pad_mask=pad_mask)
        return jnp.sum(jnp.where(~pad_mask[:, None, :, None], out, 0.0) ** 2)

    def loss_ref(q, k, v):
        out = causal_attention(q, k, v, scale=SCALE, pad_mask=pad_mask)
        return jnp.sum(jnp.where(~pad_mask[:, None, :, None], out, 0.0) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=5e-4, rtol=1e-3,
            err_msg=f"d{name} mismatch",
        )


def test_model_end_to_end_flash(tiny_config, tiny_params, rng):
    """forward() with attention_impl='flash' reproduces the XLA model."""
    cfg_flash = tiny_config.replace(attention_impl="flash")
    ids = jnp.asarray(rng.randint(0, tiny_config.vocab_size, size=(2, 24)).astype(np.int32))
    pos = jnp.broadcast_to(jnp.arange(24, dtype=jnp.int32), (2, 24))
    mask = jnp.zeros((2, 24), dtype=bool).at[1, 20:].set(True)

    ref_logits = forward(tiny_params, tiny_config, ids, pos, mask)
    flash_logits = forward(tiny_params, cfg_flash, ids, pos, mask)
    np.testing.assert_allclose(
        np.asarray(flash_logits)[:, :20], np.asarray(ref_logits)[:, :20],
        atol=1e-4, rtol=1e-4,
    )


def test_padded_sequence_path():
    """S=130 > 128 and not lane-aligned: exercises the wrapper's pad-to-block
    path (seq_pad=256, padded query rows sliced off, padded key columns
    causally unreachable) — the regime where misaligned blocks once crashed
    Mosaic lowering."""
    rng = np.random.RandomState(3)
    s = 130
    q = jnp.asarray(rng.randn(1, 2, s, D).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, s, D).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, s, D).astype(np.float32))
    mask = jnp.zeros((1, s), dtype=bool).at[0, 120:].set(True)
    ours = flash_causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    np.testing.assert_allclose(
        np.asarray(ours)[0, :, :120], np.asarray(ref)[0, :, :120], atol=2e-5, rtol=1e-4
    )


def test_block_plan_alignment():
    """Every (block, seq_pad) the wrapper can produce must satisfy Mosaic's
    lane alignment: 128-multiples for seq >= 128, and seq_pad % block == 0."""
    from tpukit.ops.pallas_attention import _plan

    for seq in (1, 16, 48, 127, 128, 130, 255, 256, 511, 512, 520, 639, 1024, 2048, 8191):
        block, seq_pad = _plan(seq)
        assert seq_pad >= seq
        assert seq_pad % block == 0
        if seq >= 128:
            assert block % 128 == 0 and seq_pad % 128 == 0
        else:
            assert block % 16 == 0 and block == seq_pad


def test_multiblock_fused_and_split_backward(monkeypatch):
    """Multi-block grads on BOTH backward variants: the fused dkv+dq-partials
    kernel (num_k <= _DQ_FUSED_MAX_NUM_K) and the split two-kernel path that
    takes over for long sequences (no S^2-scaled dq partials in HBM). Block
    size is pinned to 128 so a 384-token sequence spans 3 blocks."""
    import tpukit.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "_BLOCK", 128)
    rng = np.random.RandomState(7)
    s = 384
    q, k, v = (jnp.asarray(rng.randn(1, 2, s, D), jnp.float32) for _ in range(3))
    mask = jnp.zeros((1, s), dtype=bool).at[0, 370:].set(True)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v, scale=SCALE, pad_mask=mask)
            return jnp.sum(jnp.where(~mask[:, None, :, None], out, 0.0) ** 2)
        return f

    g_ref = jax.grad(loss(causal_attention), argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setattr(pa, "_DQ_FUSED_MAX_NUM_K", 3)  # 3 blocks ride fused
    g_fused = jax.grad(loss(flash_causal_attention), argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_fused, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=5e-4, rtol=1e-3,
            err_msg=f"fused d{name} mismatch",
        )

    monkeypatch.setattr(pa, "_DQ_FUSED_MAX_NUM_K", 1)  # force the split path
    g_split = jax.grad(loss(flash_causal_attention), argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_split, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=5e-4, rtol=1e-3,
            err_msg=f"split d{name} mismatch",
        )

    # the byte-budget gate alone must also route to the split path (and
    # still match): a large-batch long-sequence config whose dq-partials
    # exceed TPUKIT_FLASH_DQ_PARTIALS_MB never allocates them
    monkeypatch.setattr(pa, "_DQ_FUSED_MAX_NUM_K", 3)
    monkeypatch.setattr(pa, "_DQ_PARTIALS_BUDGET", 1)  # bytes
    g_budget = jax.grad(loss(flash_causal_attention), argnums=(0, 1, 2))(q, k, v)
    for ours, ref, name in zip(g_budget, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(ours), np.asarray(ref), atol=5e-4, rtol=1e-3,
            err_msg=f"budget-gated d{name} mismatch",
        )


def test_auto_dispatch_gspmd_safe():
    """Under GSPMD-sharded jit on a multi-device mesh, impl='auto' is
    sharded-correct (on the CPU test backend it picks the XLA path; on TPU
    it picks the flash kernel, whose custom_partitioning rules the
    test_flash_under_dp_mesh tests below exercise explicitly)."""
    import jax.sharding as jsh

    from tpukit.mesh import create_mesh

    mesh = create_mesh({"data": 8})
    rng = np.random.RandomState(0)
    q = rng.randn(8, 2, 16, D).astype(np.float32)
    fn = jax.jit(
        lambda q: causal_attention(q, q, q, scale=SCALE, impl="auto"),
        in_shardings=jsh.NamedSharding(mesh, jsh.PartitionSpec("data")),
    )
    out = fn(q)
    ref = causal_attention(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), scale=SCALE)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-4)


def test_bf16_forward(qkv):
    q, k, v = (t.astype(jnp.bfloat16) for t in qkv)
    ours = flash_causal_attention(q, k, v, scale=SCALE)
    ref = causal_attention(q, k, v, scale=SCALE)
    assert ours.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(ours, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


def _dp_mesh():
    from tpukit.mesh import create_mesh

    return create_mesh({"data": 8})


def test_flash_under_dp_mesh(qkv, pad_mask):
    """VERDICT r1 #2: the kernel must keep working when its operands are
    GSPMD-sharded over a data mesh — the custom_partitioning rules run it
    per-shard with no collectives and no all-gather."""
    import jax.sharding as jsh

    mesh = _dp_mesh()
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(8, H, S, D), jnp.float32) for _ in range(3))
    mask = np.zeros((8, S), dtype=bool)
    mask[::2, 40:] = True
    mask = jnp.asarray(mask)

    sh = jsh.NamedSharding(mesh, jsh.PartitionSpec("data"))
    fn = jax.jit(
        lambda q, k, v, m: flash_causal_attention(q, k, v, scale=SCALE, pad_mask=m),
        in_shardings=(sh, sh, sh, sh),
    )
    out = fn(q, k, v, mask)
    assert out.sharding.spec == jsh.PartitionSpec("data")
    ref = causal_attention(q, k, v, scale=SCALE, pad_mask=mask)
    valid = ~np.asarray(mask)
    for b in range(8):
        np.testing.assert_allclose(
            np.asarray(out)[b, :, valid[b]], np.asarray(ref)[b, :, valid[b]],
            atol=2e-5, rtol=1e-4,
        )
    # the partitioned kernel must not gather the sharded operands
    hlo = fn.lower(q, k, v, mask).compile().as_text()
    assert "all-gather" not in hlo


def test_flash_grads_under_dp_mesh(qkv):
    """Backward kernels partition too: sharded grads match unsharded."""
    import jax.sharding as jsh

    mesh = _dp_mesh()
    rng = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rng.randn(8, H, S, D), jnp.float32) for _ in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, scale=SCALE) ** 2)

    g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    sh = jsh.NamedSharding(mesh, jsh.PartitionSpec("data"))
    g_dp = jax.jit(jax.grad(loss, argnums=(0, 1, 2)), in_shardings=(sh, sh, sh))(q, k, v)
    for a, b in zip(g_ref, g_dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4)


def test_flash_inside_shard_map():
    """The pipeline recipes call attention inside a Manual shard_map region;
    the kernel must compose there as well."""
    import jax.sharding as jsh
    from tpukit.compat import shard_map

    mesh = _dp_mesh()
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.randn(8, H, S, D), jnp.float32) for _ in range(3))
    P = jsh.PartitionSpec

    sm = shard_map(
        lambda q, k, v: flash_causal_attention(q, k, v, scale=SCALE),
        mesh=mesh, in_specs=(P("data"),) * 3, out_specs=P("data"), check_vma=False,
    )
    out = jax.jit(sm)(q, k, v)
    ref = causal_attention(q, k, v, scale=SCALE)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)
