"""Recipe-level smoke tests (VERDICT r1 #6): every main-*.py CLI runs
end-to-end on the 8-virtual-device CPU mesh — tiny model, one epoch on the
offline fixture — and must produce a finite eval loss and a checkpoint.
This exercises flag plumbing + strategy construction + fit() per recipe,
the product surface the unit tests bypass."""

import importlib.util
import os
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

TINY_ARGS = [
    "--batch_size", "8",
    "--epochs", "1",
    "--sequence_length", "33",
    "--dim", "32",
    "--head_dim", "8",
    "--heads", "4",
    "--num_layers", "4",
    "--learning_rate", "1e-3",
    "--dataset_slice", "64",
    "--num_workers", "0",
]


def _run_recipe(name, tmp_path, extra=()):
    spec = importlib.util.spec_from_file_location(name.replace("-", "_"), REPO / name)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    cwd = os.getcwd()
    os.chdir(tmp_path)  # checkpoints/ lands in tmp
    try:
        result = mod.main(TINY_ARGS + list(extra))
    finally:
        os.chdir(cwd)
    assert np.isfinite(result.metrics["eval"]["loss"])
    assert result.checkpoint_path is not None and result.checkpoint_path.exists()
    return result


def test_recipe_single(tmp_path):
    _run_recipe("main-single.py", tmp_path)


def test_recipe_ddp(tmp_path):
    _run_recipe("main-ddp.py", tmp_path)


def test_recipe_fsdp(tmp_path):
    _run_recipe("main-fsdp.py", tmp_path)


def test_recipe_fsdp_cpu_offload(tmp_path):
    # degrades to plain FSDP on the CPU backend, with a warning
    with pytest.warns(UserWarning, match="cpu_offload"):
        _run_recipe("main-fsdp.py", tmp_path, extra=["--cpu_offload"])


def test_recipe_pipe(tmp_path):
    # 8 virtual devices -> 8 stages: layers must divide; keep microbatches
    # at the stage count so the tiny batch still divides
    _run_recipe(
        "main-pipe.py", tmp_path,
        extra=["--num_layers", "8", "--microbatches", "8"],
    )


def test_recipe_pipe_ddp(tmp_path):
    # grid picker -> (data=2, stage=4) on 8 devices
    _run_recipe("main-pipe-ddp.py", tmp_path, extra=["--microbatches", "4"])


def test_recipe_ring(tmp_path):
    _run_recipe("main-ring.py", tmp_path)


def test_recipe_pipe_uneven_layers(tmp_path):
    # 10 layers on 8 stages (VERDICT r2 #5): identity-padded to 16, trains
    # end-to-end through fit() including generation and checkpointing
    _run_recipe(
        "main-pipe.py", tmp_path,
        extra=["--num_layers", "10", "--microbatches", "8"],
    )


def test_recipe_tp(tmp_path):
    # grid picker -> (data=2, model=4) on 8 devices with 4 heads
    _run_recipe("main-tp.py", tmp_path)


def test_recipe_fsdp_sharded_checkpoint_and_resume(tmp_path):
    """VERDICT r2 #1 done-criterion: a sharded recipe with --checkpoint_every
    writes a step-keyed .sharded dir and --resume latest restores from it."""
    result = _run_recipe(
        "main-fsdp.py", tmp_path,
        extra=["--checkpoint_every", "4", "--checkpoint_format", "sharded"],
    )
    assert result.checkpoint_path.name.endswith(".sharded")
    assert result.checkpoint_path.is_dir()
    assert (result.checkpoint_path / "manifest.json").exists()
    resumed = _run_recipe(
        "main-fsdp.py", tmp_path,
        extra=["--checkpoint_format", "sharded", "--resume", "latest"],
    )
    assert int(resumed.state.step) == 2 * int(result.state.step)


def test_recipe_pipe_1f1b(tmp_path):
    # the explicit-vjp 1F1B schedule through the full recipe surface
    _run_recipe(
        "main-pipe.py", tmp_path,
        extra=["--num_layers", "8", "--microbatches", "8", "--schedule", "1f1b"],
    )


def test_recipe_moe(tmp_path):
    # grid picker -> (data=1, expert=8) on 8 devices with the default 8
    # experts; MoE routing + aux loss + EP shardings through fit()
    _run_recipe("main-moe.py", tmp_path)
