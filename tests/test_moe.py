"""Mixture-of-experts + ExpertParallel tests (beyond-reference: the cookbook
has no MoE — SURVEY §2.4 marks the EP row "not required"; tpukit closes it
anyway). Same bar as the other strategies: the EP-sharded step must match
the single-device MoE step bit-near, and the MoE machinery must hold its
own invariants (capacity drops, aux loss, row independence, decode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, init_params
from tpukit.model.gpt import _apply_moe_ffn
from tpukit.shardings import ExpertParallel, SingleDevice
from tpukit.train import create_train_state, make_optimizer, make_step_fns

BATCH = 16
SEQ = 32


@pytest.fixture(scope="module")
def cfg():
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=211,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
        num_experts=4,
    )


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.RandomState(11)
    ids = rng.randint(3, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    mask = np.zeros((BATCH, SEQ), dtype=bool)
    mask[0, 28:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }
    return model_batch, targets


def _one_step(strategy, cfg, batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, batch, targets)
    eval_loss, eval_acc = eval_step(new_state, batch, targets)
    return jax.device_get(new_state.params), float(loss), float(eval_loss), float(eval_acc)


def test_ep_matches_single(cfg, batch):
    """The whole point: expert-sharded execution is the same math. One full
    train step (fwd + bwd incl. the aux loss + AdamW) through the
    (data=2, expert=4) mesh must match the single-device MoE step."""
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    ep = _one_step(
        ExpertParallel(create_mesh({"data": 2, "expert": 4})), cfg, model_batch, targets
    )
    assert abs(ep[1] - ref[1]) < 1e-5
    assert abs(ep[2] - ref[2]) < 1e-2
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        ep[0], ref[0],
    )


def test_ep_top2_matches_single(cfg, batch):
    """GShard/Mixtral-style top-2 routing holds the same EP-vs-single
    parity bar as top-1 (distinct experts per token, per-expert gates)."""
    model_batch, targets = batch
    cfg2 = cfg.replace(router_top_k=2)
    ref = _one_step(SingleDevice(), cfg2, model_batch, targets)
    ep = _one_step(
        ExpertParallel(create_mesh({"data": 2, "expert": 4})), cfg2, model_batch, targets
    )
    assert abs(ep[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        ep[0], ref[0],
    )
    # top-2 must actually engage a second expert: its loss path differs
    # from top-1's on the same params/batch
    ref1 = _one_step(SingleDevice(), cfg, model_batch, targets)
    assert abs(ref[1] - ref1[1]) > 1e-7


def test_ep_param_memory(cfg):
    """Each device holds only its experts' parameters and Adam state: with
    a 4-way expert axis, per-device expert bytes must be a quarter of the
    bank (embeddings/attention stay replicated)."""
    from jax.sharding import PartitionSpec as P

    strategy = ExpertParallel(create_mesh({"data": 2, "expert": 4}))
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    sharding = strategy.state_sharding(jax.eval_shape(lambda: state))
    up_spec = sharding.params["layers"]["ffn"]["experts"]["up"]["kernel"].spec
    assert up_spec == P(None, "expert", None, None)
    assert sharding.opt_state[0].mu["layers"]["ffn"]["experts"]["down"]["kernel"].spec == P(
        None, "expert", None, None
    )
    assert sharding.params["layers"]["ffn"]["router"]["kernel"].spec == P()

    placed = jax.tree.map(
        jax.device_put, state.params["layers"]["ffn"]["experts"],
        sharding.params["layers"]["ffn"]["experts"],
    )
    total = sum(l.nbytes for l in jax.tree.leaves(placed))
    per_device = {}
    for leaf in jax.tree.leaves(placed):
        for shard in leaf.addressable_shards:
            per_device[shard.device] = per_device.get(shard.device, 0) + shard.data.nbytes
    assert max(per_device.values()) <= total // 4


def test_moe_aux_loss_trains_router(cfg, batch):
    """The load-balance aux loss must reach the router: its gradient is
    nonzero under the training objective, and the returned train loss is
    the PURE CE (aux excluded from the reported number)."""
    model_batch, targets = batch
    strategy = SingleDevice()
    params = init_params(jax.random.PRNGKey(0), cfg)
    loss, grads = strategy.value_and_grad(params, cfg, model_batch, targets)
    router_g = grads["layers"]["ffn"]["router"]["kernel"]
    assert float(jnp.max(jnp.abs(router_g))) > 0.0
    pure_ce, _ = strategy.loss_fn(params, cfg, model_batch, targets)
    assert abs(float(loss) - float(pure_ce)) < 1e-6

    # aux weight 0 must still train (CE reaches the router through the gate)
    loss0, grads0 = strategy.value_and_grad(
        params, cfg.replace(moe_aux_weight=0.0), model_batch, targets
    )
    assert np.isfinite(float(loss0))
    assert not np.allclose(
        np.asarray(router_g),
        np.asarray(grads0["layers"]["ffn"]["router"]["kernel"]),
    )


def test_moe_capacity_drop_is_residual_passthrough(cfg):
    """Tokens beyond an expert's per-row capacity take EXACTLY zero FFN
    output. With capacity clamped to 1 (factor ~0), only each row's FIRST
    token per expert may produce output; every later token routed to the
    same expert must be an exact zero — the residual-passthrough
    invariant."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, SEQ, cfg.dim).astype(np.float32))
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])

    tiny = cfg.replace(expert_capacity_factor=1e-9)  # capacity clamps to 1
    out_tiny, aux = _apply_moe_ffn(layer0, tiny, x, None, True)
    out_tiny = np.asarray(out_tiny)
    assert np.isfinite(out_tiny).all()
    assert np.isfinite(float(aux))

    # recompute the routing the kernel used
    router = np.asarray(layer0["ffn"]["router"]["kernel"], np.float32)
    choice = np.argmax(np.asarray(x, np.float32) @ router, axis=-1)  # [B, S]
    dropped = kept_any = 0
    for b in range(x.shape[0]):
        seen = set()
        for s in range(x.shape[1]):
            if choice[b, s] in seen:
                np.testing.assert_array_equal(out_tiny[b, s], 0.0)
                dropped += 1
            else:
                seen.add(int(choice[b, s]))
                kept_any += 1
    assert dropped > 0 and kept_any > 0  # the case actually exercises both

    # with ample capacity nothing drops: the per-row dispatch equals
    # running each row alone (row independence)
    ample = cfg.replace(expert_capacity_factor=float(cfg.num_experts))
    out_all, _ = _apply_moe_ffn(layer0, ample, x, None, True)
    row0, _ = _apply_moe_ffn(layer0, ample, x[:1], None, True)
    np.testing.assert_allclose(np.asarray(out_all[:1]), np.asarray(row0), atol=1e-6)

    # dispatch must not depend on the buffer width around a row: the same
    # prefix inside a wider zero-padded buffer yields the same outputs
    # (capacity derives from max_position_embeddings, not the call width)
    half = SEQ // 2
    out_half, _ = _apply_moe_ffn(layer0, cfg, x[:, :half], None, True)
    out_full, _ = _apply_moe_ffn(layer0, cfg, x, None, True)
    np.testing.assert_allclose(
        np.asarray(out_full[:, :half]), np.asarray(out_half), atol=1e-6
    )


def test_moe_aux_loss_masks_pad_positions(cfg):
    """ADVICE r5 #2: the load-balance statistics exclude pad positions and
    normalize by the real-token count, so a padded batch reports the SAME
    aux loss as the unpadded rows alone — pads can no longer dilute the
    balance signal. moe_aux_mask_pads=False restores the old any-position
    average for pre-masking curve comparisons."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(3, SEQ, cfg.dim).astype(np.float32))
    params = init_params(jax.random.PRNGKey(1), cfg)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])
    real = SEQ - 8
    pad_mask = jnp.zeros((3, SEQ), bool).at[:, real:].set(True)
    no_pads_wide = jnp.zeros((3, SEQ), bool)
    no_pads_trunc = jnp.zeros((3, real), bool)

    # unpadded batches: masked path == maskless path (bit-near)
    _, aux_masked = _apply_moe_ffn(layer0, cfg, x, None, True, pad_mask=no_pads_wide)
    _, aux_plain = _apply_moe_ffn(layer0, cfg, x, None, True)
    np.testing.assert_allclose(float(aux_masked), float(aux_plain), atol=1e-6)

    # padded batch == the same rows truncated to their real tokens (the
    # dispatch is width-invariant, so only the statistics are at stake)
    _, aux_pad = _apply_moe_ffn(layer0, cfg, x, None, True, pad_mask=pad_mask)
    _, aux_trunc = _apply_moe_ffn(
        layer0, cfg, x[:, :real], None, True, pad_mask=no_pads_trunc
    )
    np.testing.assert_allclose(float(aux_pad), float(aux_trunc), atol=1e-6)

    # the old behavior is preserved behind the config flag, and it really
    # is different under padding (the r5 #2 dilution this fixes)
    old = cfg.replace(moe_aux_mask_pads=False)
    _, aux_old = _apply_moe_ffn(layer0, old, x, None, True, pad_mask=pad_mask)
    _, aux_old_nomask = _apply_moe_ffn(layer0, old, x, None, True)
    np.testing.assert_allclose(float(aux_old), float(aux_old_nomask), atol=1e-7)
    assert abs(float(aux_old) - float(aux_pad)) > 1e-7

    # an all-pad row drops out of the batch mean instead of contributing a
    # spurious zero: aux over [row0, all-pad row] equals aux over [row0]
    two = jnp.stack([x[0], x[1]])
    mask_allpad = jnp.stack(
        [jnp.zeros((SEQ,), bool), jnp.ones((SEQ,), bool)]
    )
    _, aux_with_dead = _apply_moe_ffn(layer0, cfg, two, None, True, pad_mask=mask_allpad)
    _, aux_alone = _apply_moe_ffn(
        layer0, cfg, x[:1], None, True, pad_mask=jnp.zeros((1, SEQ), bool)
    )
    np.testing.assert_allclose(float(aux_with_dead), float(aux_alone), atol=1e-6)

    # the masked aux still trains the router end to end through fit's
    # objective (the gradient path survives the einsum rewrite)
    model_batch = {
        "input_ids": np.asarray(rng.randint(3, cfg.vocab_size, size=(4, SEQ)), np.int32),
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), (4, SEQ))
        ),
        "mask": np.asarray(np.arange(SEQ) >= real)[None].repeat(4, 0),
    }
    targets = np.roll(model_batch["input_ids"], -1, axis=1).astype(np.int32)
    targets[model_batch["mask"]] = -100
    loss, grads = SingleDevice().value_and_grad(params, cfg, model_batch, targets)
    assert np.isfinite(float(loss))
    assert float(jnp.max(jnp.abs(grads["layers"]["ffn"]["router"]["kernel"]))) > 0.0


def test_moe_generation_batched_matches_serial(cfg):
    """Row-independent dispatch keeps the batched decode token-for-token
    equal to the serial one for MoE models too."""
    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.sampling import generate, generate_batch

    tok = WordTokenizer(synthetic_stories(64))
    gcfg = cfg.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(2), gcfg)
    prompts = ["One day, ", "The big brown cat "]
    batched = generate_batch(params, gcfg, prompts, tok, max_new_tokens=8)
    serial = [
        generate(params, gcfg, p, tok, max_new_tokens=8, use_cache=False)
        for p in prompts
    ]
    assert batched == serial


def test_strategies_reject_moe(cfg):
    """Pipeline/CP/TP name ExpertParallel in their refusal; EP refuses
    dense configs and undividable expert counts."""
    from tpukit.pipeline import Pipeline
    from tpukit.shardings import ContextParallel, TensorParallel

    for strategy in (
        Pipeline(create_mesh({"stage": 4})),
        ContextParallel(create_mesh({"seq": 8})),
        TensorParallel(create_mesh({"model": 4})),
    ):
        with pytest.raises(ValueError, match="ExpertParallel"):
            strategy.validate_config(cfg)

    ep = ExpertParallel(create_mesh({"expert": 8}))
    with pytest.raises(ValueError, match="num_experts"):
        ep.validate_config(cfg.replace(num_experts=0))
    with pytest.raises(ValueError, match="divide"):
        ep.validate_config(cfg.replace(num_experts=4))  # 4 over 8-way axis
