"""Mixture-of-experts + ExpertParallel tests (beyond-reference: the cookbook
has no MoE — SURVEY §2.4 marks the EP row "not required"; tpukit closes it
anyway). Same bar as the other strategies: the EP-sharded step must match
the single-device MoE step bit-near, and the MoE machinery must hold its
own invariants (capacity drops, aux loss, row independence, decode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, init_params
from tpukit.model.gpt import _apply_moe_ffn
from tpukit.shardings import ExpertParallel, SingleDevice
from tpukit.train import create_train_state, make_optimizer, make_step_fns

BATCH = 16
SEQ = 32


@pytest.fixture(scope="module")
def cfg():
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=211,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
        num_experts=4,
    )


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.RandomState(11)
    ids = rng.randint(3, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    mask = np.zeros((BATCH, SEQ), dtype=bool)
    mask[0, 28:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }
    return model_batch, targets


def _one_step(strategy, cfg, batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, batch, targets)
    eval_loss, eval_acc = eval_step(new_state, batch, targets)
    return jax.device_get(new_state.params), float(loss), float(eval_loss), float(eval_acc)


@pytest.mark.parametrize("dispatch", ["xla", "a2a", "pallas"])
def test_ep_matches_single(cfg, batch, dispatch):
    """The whole point: expert-sharded execution is the same math. One full
    train step (fwd + bwd incl. the aux loss + AdamW) through the
    (data=2, expert=4) mesh must match the single-device MoE step — for
    ALL dispatch dataflows (the GSPMD einsums, the explicit shard_map
    all_to_all of tpukit/ops/moe_dispatch.py, and the a2a exchange with
    the Pallas grouped GEMM of tpukit/ops/moe_gemm.py)."""
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    ep = _one_step(
        ExpertParallel(create_mesh({"data": 2, "expert": 4}), dispatch=dispatch),
        cfg, model_batch, targets,
    )
    assert abs(ep[1] - ref[1]) < 1e-5
    assert abs(ep[2] - ref[2]) < 1e-2
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        ep[0], ref[0],
    )


@pytest.mark.parametrize("dispatch", ["xla", "a2a", "pallas"])
def test_ep_top2_matches_single(cfg, batch, dispatch):
    """GShard/Mixtral-style top-2 routing holds the same EP-vs-single
    parity bar as top-1 (distinct experts per token, per-expert gates),
    on all three dispatch dataflows."""
    model_batch, targets = batch
    cfg2 = cfg.replace(router_top_k=2)
    ref = _one_step(SingleDevice(), cfg2, model_batch, targets)
    ep = _one_step(
        ExpertParallel(create_mesh({"data": 2, "expert": 4}), dispatch=dispatch),
        cfg2, model_batch, targets,
    )
    assert abs(ep[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        ep[0], ref[0],
    )
    # top-2 must actually engage a second expert: its loss path differs
    # from top-1's on the same params/batch
    ref1 = _one_step(SingleDevice(), cfg, model_batch, targets)
    assert abs(ref[1] - ref1[1]) > 1e-7


@pytest.mark.parametrize("top_k", [1, 2])
def test_ep_a2a_capacity_drop_parity(cfg, batch, top_k):
    """A2a dispatch under real capacity pressure: with the capacity factor
    squeezed so tokens actually drop, the shard_map exchange must still
    match the single-device step exactly — dropped tokens ride the residual
    identically on both sides of the all_to_all."""
    model_batch, targets = batch
    tight = cfg.replace(expert_capacity_factor=0.25, router_top_k=top_k)
    # the squeeze really drops tokens: outputs differ from ample capacity
    from tpukit.model import init_params
    from tpukit.model.gpt import _apply_moe_ffn

    params = init_params(jax.random.PRNGKey(0), tight)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])
    x = jnp.asarray(np.random.RandomState(3).randn(2, SEQ, tight.dim), jnp.float32)
    out_tight, _ = _apply_moe_ffn(layer0, tight, x, None, True)
    out_ample, _ = _apply_moe_ffn(
        layer0, tight.replace(expert_capacity_factor=float(tight.num_experts)),
        x, None, True,
    )
    assert np.max(np.abs(np.asarray(out_tight) - np.asarray(out_ample))) > 1e-6

    ref = _one_step(SingleDevice(), tight, model_batch, targets)
    ep = _one_step(
        ExpertParallel(create_mesh({"data": 2, "expert": 4}), dispatch="a2a"),
        tight, model_batch, targets,
    )
    assert abs(ep[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        ep[0], ref[0],
    )


def test_ep_param_memory(cfg):
    """Each device holds only its experts' parameters and Adam state: with
    a 4-way expert axis, per-device expert bytes must be a quarter of the
    bank. Round 10: the dense trunk no longer stays replicated — it shards
    FSDP-style over the whole (data x expert) world (see
    test_ep_trunk_fsdp_memory)."""
    from jax.sharding import PartitionSpec as P

    strategy = ExpertParallel(create_mesh({"data": 2, "expert": 4}))
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    sharding = strategy.state_sharding(jax.eval_shape(lambda: state))
    up_spec = sharding.params["layers"]["ffn"]["experts"]["up"]["kernel"].spec
    assert up_spec == P(None, "expert", None, None)
    assert sharding.opt_state[0].mu["layers"]["ffn"]["experts"]["down"]["kernel"].spec == P(
        None, "expert", None, None
    )
    # the router is dense trunk now, but on this fixture no non-contraction
    # dim of [L=2, dim, E=4] divides the 8-way world — it stays replicated
    # (the contraction dim is never sharded: its partial-sum ulps would
    # flip routing; see ExpertParallel._spec_for)
    assert sharding.params["layers"]["ffn"]["router"]["kernel"].spec == P()

    placed = jax.tree.map(
        jax.device_put, state.params["layers"]["ffn"]["experts"],
        sharding.params["layers"]["ffn"]["experts"],
    )
    total = sum(l.nbytes for l in jax.tree.leaves(placed))
    per_device = {}
    for leaf in jax.tree.leaves(placed):
        for shard in leaf.addressable_shards:
            per_device[shard.device] = per_device.get(shard.device, 0) + shard.data.nbytes
    assert max(per_device.values()) <= total // 4


def _trunk_leaves_with_shardings(tree, shardings):
    """(leaf, sharding) pairs of the dense trunk — everything that is not
    the expert bank."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    flat_sh = jax.tree_util.tree_flatten_with_path(shardings)[0]
    out = []
    for (path, leaf), (_, sh) in zip(flat, flat_sh):
        names = tuple(
            k.key for k in path if isinstance(k, jax.tree_util.DictKey)
        )
        if "experts" not in names:
            out.append((leaf, sh))
    return out


def test_ep_trunk_fsdp_memory(cfg):
    """EPxFSDP memory proof (round 10): per-device dense-trunk param+Adam
    bytes shrink to ~1/world vs the round-5 EP layout, which replicated
    the whole trunk (per-device trunk bytes == total trunk bytes) on every
    device. Small leaves (norms, biases) stay replicated under the
    min-size threshold, hence the slack factor."""
    world = 8  # data=2 x expert=4
    strategy = ExpertParallel(create_mesh({"data": 2, "expert": 4}))
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    sharding = strategy.state_sharding(jax.eval_shape(lambda: state))

    # params + both Adam moments: the at-rest state bytes of the trunk
    pairs = []
    pairs += _trunk_leaves_with_shardings(state.params, sharding.params)
    pairs += _trunk_leaves_with_shardings(
        state.opt_state[0].mu, sharding.opt_state[0].mu
    )
    pairs += _trunk_leaves_with_shardings(
        state.opt_state[0].nu, sharding.opt_state[0].nu
    )
    total = sum(leaf.nbytes for leaf, _ in pairs)
    per_device: dict = {}
    for leaf, sh in pairs:
        placed = jax.device_put(leaf, sh)
        for shard in placed.addressable_shards:
            per_device[shard.device] = per_device.get(shard.device, 0) + shard.data.nbytes
    # round-5 EP: max(per_device) == total (full replication). Now: ~1/8
    # plus the small replicated residue.
    assert max(per_device.values()) <= total / world * 1.5, (
        max(per_device.values()), total,
    )
    # the big trunk tensors (vocab tables, attention kernels) really carry
    # a world-sharded spec, moments included
    from jax.sharding import PartitionSpec as P

    assert sharding.params["embeddings"]["token"].spec == P(("data", "expert"), None)
    assert sharding.params["lm_head"]["kernel"].spec == P(None, ("data", "expert"))
    assert sharding.params["layers"]["attn"]["q"]["kernel"].spec == P(
        None, None, ("data", "expert")
    )
    assert sharding.opt_state[0].mu["embeddings"]["token"].spec == P(
        ("data", "expert"), None
    )
    # norms are below the threshold: replicated, like dense FSDP
    assert sharding.params["norm_out"]["scale"].spec == P()


def test_moe_aux_loss_trains_router(cfg, batch):
    """The load-balance aux loss must reach the router: its gradient is
    nonzero under the training objective, and the returned train loss is
    the PURE CE (aux excluded from the reported number)."""
    model_batch, targets = batch
    strategy = SingleDevice()
    params = init_params(jax.random.PRNGKey(0), cfg)
    loss, grads = strategy.value_and_grad(params, cfg, model_batch, targets)
    router_g = grads["layers"]["ffn"]["router"]["kernel"]
    assert float(jnp.max(jnp.abs(router_g))) > 0.0
    pure_ce, _ = strategy.loss_fn(params, cfg, model_batch, targets)
    assert abs(float(loss) - float(pure_ce)) < 1e-6

    # aux weight 0 must still train (CE reaches the router through the gate)
    loss0, grads0 = strategy.value_and_grad(
        params, cfg.replace(moe_aux_weight=0.0), model_batch, targets
    )
    assert np.isfinite(float(loss0))
    assert not np.allclose(
        np.asarray(router_g),
        np.asarray(grads0["layers"]["ffn"]["router"]["kernel"]),
    )


def test_moe_capacity_drop_is_residual_passthrough(cfg):
    """Tokens beyond an expert's per-row capacity take EXACTLY zero FFN
    output. With capacity clamped to 1 (factor ~0), only each row's FIRST
    token per expert may produce output; every later token routed to the
    same expert must be an exact zero — the residual-passthrough
    invariant."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, SEQ, cfg.dim).astype(np.float32))
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])

    tiny = cfg.replace(expert_capacity_factor=1e-9)  # capacity clamps to 1
    out_tiny, aux = _apply_moe_ffn(layer0, tiny, x, None, True)
    out_tiny = np.asarray(out_tiny)
    assert np.isfinite(out_tiny).all()
    assert np.isfinite(float(aux))

    # recompute the routing the kernel used
    router = np.asarray(layer0["ffn"]["router"]["kernel"], np.float32)
    choice = np.argmax(np.asarray(x, np.float32) @ router, axis=-1)  # [B, S]
    dropped = kept_any = 0
    for b in range(x.shape[0]):
        seen = set()
        for s in range(x.shape[1]):
            if choice[b, s] in seen:
                np.testing.assert_array_equal(out_tiny[b, s], 0.0)
                dropped += 1
            else:
                seen.add(int(choice[b, s]))
                kept_any += 1
    assert dropped > 0 and kept_any > 0  # the case actually exercises both

    # with ample capacity nothing drops: the per-row dispatch equals
    # running each row alone (row independence)
    ample = cfg.replace(expert_capacity_factor=float(cfg.num_experts))
    out_all, _ = _apply_moe_ffn(layer0, ample, x, None, True)
    row0, _ = _apply_moe_ffn(layer0, ample, x[:1], None, True)
    np.testing.assert_allclose(np.asarray(out_all[:1]), np.asarray(row0), atol=1e-6)

    # dispatch must not depend on the buffer width around a row: the same
    # prefix inside a wider zero-padded buffer yields the same outputs
    # (capacity derives from max_position_embeddings, not the call width)
    half = SEQ // 2
    out_half, _ = _apply_moe_ffn(layer0, cfg, x[:, :half], None, True)
    out_full, _ = _apply_moe_ffn(layer0, cfg, x, None, True)
    np.testing.assert_allclose(
        np.asarray(out_full[:, :half]), np.asarray(out_half), atol=1e-6
    )


def test_moe_aux_loss_masks_pad_positions(cfg):
    """ADVICE r5 #2: the load-balance statistics exclude pad positions and
    normalize by the real-token count, so a padded batch reports the SAME
    aux loss as the unpadded rows alone — pads can no longer dilute the
    balance signal. moe_aux_mask_pads=False restores the old any-position
    average for pre-masking curve comparisons."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(3, SEQ, cfg.dim).astype(np.float32))
    params = init_params(jax.random.PRNGKey(1), cfg)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])
    real = SEQ - 8
    pad_mask = jnp.zeros((3, SEQ), bool).at[:, real:].set(True)
    no_pads_wide = jnp.zeros((3, SEQ), bool)
    no_pads_trunc = jnp.zeros((3, real), bool)

    # unpadded batches: masked path == maskless path (bit-near)
    _, aux_masked = _apply_moe_ffn(layer0, cfg, x, None, True, pad_mask=no_pads_wide)
    _, aux_plain = _apply_moe_ffn(layer0, cfg, x, None, True)
    np.testing.assert_allclose(float(aux_masked), float(aux_plain), atol=1e-6)

    # padded batch == the same rows truncated to their real tokens (the
    # dispatch is width-invariant, so only the statistics are at stake)
    _, aux_pad = _apply_moe_ffn(layer0, cfg, x, None, True, pad_mask=pad_mask)
    _, aux_trunc = _apply_moe_ffn(
        layer0, cfg, x[:, :real], None, True, pad_mask=no_pads_trunc
    )
    np.testing.assert_allclose(float(aux_pad), float(aux_trunc), atol=1e-6)

    # the old behavior is preserved behind the config flag, and it really
    # is different under padding (the r5 #2 dilution this fixes)
    old = cfg.replace(moe_aux_mask_pads=False)
    _, aux_old = _apply_moe_ffn(layer0, old, x, None, True, pad_mask=pad_mask)
    _, aux_old_nomask = _apply_moe_ffn(layer0, old, x, None, True)
    np.testing.assert_allclose(float(aux_old), float(aux_old_nomask), atol=1e-7)
    assert abs(float(aux_old) - float(aux_pad)) > 1e-7

    # an all-pad row drops out of the batch mean instead of contributing a
    # spurious zero: aux over [row0, all-pad row] equals aux over [row0]
    two = jnp.stack([x[0], x[1]])
    mask_allpad = jnp.stack(
        [jnp.zeros((SEQ,), bool), jnp.ones((SEQ,), bool)]
    )
    _, aux_with_dead = _apply_moe_ffn(layer0, cfg, two, None, True, pad_mask=mask_allpad)
    _, aux_alone = _apply_moe_ffn(
        layer0, cfg, x[:1], None, True, pad_mask=jnp.zeros((1, SEQ), bool)
    )
    np.testing.assert_allclose(float(aux_with_dead), float(aux_alone), atol=1e-6)

    # the masked aux still trains the router end to end through fit's
    # objective (the gradient path survives the einsum rewrite)
    model_batch = {
        "input_ids": np.asarray(rng.randint(3, cfg.vocab_size, size=(4, SEQ)), np.int32),
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), (4, SEQ))
        ),
        "mask": np.asarray(np.arange(SEQ) >= real)[None].repeat(4, 0),
    }
    targets = np.roll(model_batch["input_ids"], -1, axis=1).astype(np.int32)
    targets[model_batch["mask"]] = -100
    loss, grads = SingleDevice().value_and_grad(params, cfg, model_batch, targets)
    assert np.isfinite(float(loss))
    assert float(jnp.max(jnp.abs(grads["layers"]["ffn"]["router"]["kernel"]))) > 0.0


def test_moe_generation_batched_matches_serial(cfg):
    """Row-independent dispatch keeps the batched decode token-for-token
    equal to the serial one for MoE models too."""
    from tpukit.data import WordTokenizer, synthetic_stories
    from tpukit.sampling import generate, generate_batch

    tok = WordTokenizer(synthetic_stories(64))
    gcfg = cfg.replace(vocab_size=tok.vocab_size, max_position_embeddings=64)
    params = init_params(jax.random.PRNGKey(2), gcfg)
    prompts = ["One day, ", "The big brown cat "]
    batched = generate_batch(params, gcfg, prompts, tok, max_new_tokens=8)
    serial = [
        generate(params, gcfg, p, tok, max_new_tokens=8, use_cache=False)
        for p in prompts
    ]
    assert batched == serial


@pytest.mark.parametrize("dispatch", ["a2a", "pallas"])
def test_ep_a2a_hlo_audit(cfg, batch, dispatch):
    """The round-10/11 proof obligations, against the compiled artifact:
    the a2a- and pallas-dispatch EP train steps' optimized HLO contains
    the all-to-all dispatch/combine pair for every layer — in the BACKWARD
    too (count 4 x layers: fwd dispatch+combine and their transposes) — at
    exactly the closed-form byte count `ExpertParallel.dispatch_comm`
    predicts, and the compile emits ZERO `[SPMD] Involuntary full
    rematerialization` warnings (the round-5 einsum dispatch emitted them
    on every backward; MULTICHIP_r05.json). Running BOTH dispatches
    through one audit asserts the round-11 kernel path changed the
    on-device FFN spelling without touching the collective schedule — the
    "unchanged a2a byte audit" acceptance bar."""
    from tpukit.obs.xla import capture_compiler_stderr, collective_bytes

    model_batch, targets = batch
    strategy = ExpertParallel(create_mesh({"data": 2, "expert": 4}), dispatch=dispatch)
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt, strategy)
    shapes = jax.eval_shape(lambda: state)
    struct = lambda x: jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)  # noqa: E731
    b_structs = jax.tree.map(struct, model_batch)
    # check=True: the capture itself raises on any involuntary-remat
    # warning (one spelling of the capture-then-count pattern, round 16)
    with capture_compiler_stderr(check=True) as cap:
        train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
        compiled = train_step.lower(shapes, b_structs, struct(targets)).compile()
        ecompiled = eval_step.lower(shapes, b_structs, struct(targets)).compile()

    expect = strategy.dispatch_comm(cfg, global_batch=BATCH, seq=SEQ)
    a2a = collective_bytes(compiled.as_text()).get("all-to-all")
    assert a2a is not None, "EP train step HLO contains no all-to-all at all"
    assert a2a["count"] == expect["train"]["count"] == 4 * cfg.num_layers
    assert a2a["bytes"] == expect["train"]["bytes"]

    # eval (forward-only): the dispatch/combine pair per layer. Bytes are
    # asserted as a COUNT only: eval computes in bf16, which the CPU test
    # backend upcasts to f32 — on TPU the bytes match expect["eval"].
    ea2a = collective_bytes(ecompiled.as_text()).get("all-to-all")
    assert ea2a is not None and ea2a["count"] == expect["eval"]["count"] == 2 * cfg.num_layers


@pytest.mark.parametrize("top_k", [1, 2])
def test_pallas_matches_xla_loss_grad(cfg, batch, top_k):
    """Round-11 acceptance: loss AND gradient parity of the dropless
    pallas grouped-GEMM dataflow vs the xla buffers at dense tolerance,
    top-1 and top-2, on the CPU interpret path. `moe_capacity=SEQ` pins
    both sides to the same (no-drop) token set — the xla buffer can hold
    every assignment, the pallas path is dropless by construction — so the
    only difference left is the dataflow itself."""
    model_batch, targets = batch
    base = cfg.replace(router_top_k=top_k, moe_capacity=SEQ)
    strategy = SingleDevice()
    params = init_params(jax.random.PRNGKey(0), base)
    loss_x, grads_x = strategy.value_and_grad(params, base, model_batch, targets)
    loss_p, grads_p = strategy.value_and_grad(
        params, base.replace(moe_dispatch="pallas"), model_batch, targets
    )
    assert abs(float(loss_x) - float(loss_p)) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        ),
        grads_x, grads_p,
    )
    # the kernel path really ran: its gradient reaches every expert AND
    # the router (the custom VJP wires dW, dX and the gate path)
    assert float(jnp.max(jnp.abs(
        grads_p["layers"]["ffn"]["experts"]["up"]["kernel"]
    ))) > 0.0
    assert float(jnp.max(jnp.abs(
        grads_p["layers"]["ffn"]["router"]["kernel"]
    ))) > 0.0


@pytest.mark.parametrize("top_k", [1, 2])
def test_pallas_drop_semantics(cfg, top_k):
    """Satellite regression: with `moe_capacity` forcing drops, the pallas
    path drops EXACTLY the token set the xla buffers drop (bit-identical
    kept mask AND matching outputs), and in dropless mode (moe_capacity=0)
    it drops none — every routed assignment computes."""
    from tpukit.ops.moe_dispatch import _route
    from tpukit.ops.moe_gemm import pallas_kept_mask

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(3, SEQ, cfg.dim).astype(np.float32))
    tight = cfg.replace(router_top_k=top_k, moe_capacity=2)
    params = init_params(jax.random.PRNGKey(0), tight)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])
    router = layer0["ffn"]["router"]["kernel"]

    # the kept sets are the SAME mask, bit for bit
    _, dispatch, _, _, assign = _route(x, router, tight)
    kept_xla = np.asarray(jnp.sum(dispatch, axis=-1))  # [B, S, E] 0/1
    kept_pal = np.asarray(pallas_kept_mask(tight, x, router))
    np.testing.assert_array_equal(kept_pal, kept_xla)
    assert kept_xla.sum() < np.asarray(assign).sum(), (
        "fixture must actually force drops"
    )

    # and the outputs agree under that shared drop set
    out_x, _ = _apply_moe_ffn(layer0, tight, x, None, True)
    out_p, _ = _apply_moe_ffn(
        layer0, tight.replace(moe_dispatch="pallas"), x, None, True
    )
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_p), atol=1e-5
    )
    if top_k == 1:
        # top-1 dropped tokens are exact-zero rows (residual passthrough)
        # on BOTH paths — the zero patterns must coincide
        zx = np.all(np.asarray(out_x) == 0.0, axis=-1)
        zp = np.all(np.asarray(out_p) == 0.0, axis=-1)
        np.testing.assert_array_equal(zx, zp)
        assert zx.any()

    # dropless mode: every routed assignment is kept, and the output
    # equals the xla path given a buffer big enough to never drop
    free = cfg.replace(router_top_k=top_k, moe_dispatch="pallas")
    kept_free = np.asarray(pallas_kept_mask(free, x, router))
    np.testing.assert_array_equal(kept_free, np.asarray(assign))
    out_free, _ = _apply_moe_ffn(layer0, free, x, None, True)
    out_ample, _ = _apply_moe_ffn(
        layer0, cfg.replace(router_top_k=top_k, moe_capacity=SEQ), x, None, True
    )
    np.testing.assert_allclose(
        np.asarray(out_free), np.asarray(out_ample), atol=1e-5
    )


def test_grouped_ffn_kernel_unit():
    """The segment-GEMM kernel against a per-segment jnp reference —
    forward values and all five cotangents through the custom VJP — on an
    adversarial segment layout: uneven sizes, an empty expert, a segment
    spanning a block boundary, and a sort-padding tail folded into the
    last segment (whose cotangent must stay exactly zero)."""
    from tpukit.ops import moe_gemm
    from tpukit.ops.moe_gemm import grouped_ffn

    e, d, f, n = 4, 32, 64, 250
    bt, m = moe_gemm._plan_rows(n)
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(m, d).astype(np.float32))
    wu = jnp.asarray(rng.randn(e, d, f).astype(np.float32) * 0.1)
    bu = jnp.asarray(rng.randn(e, f).astype(np.float32) * 0.1)
    wd = jnp.asarray(rng.randn(e, f, d).astype(np.float32) * 0.1)
    bd = jnp.asarray(rng.randn(e, d).astype(np.float32) * 0.1)
    sizes = [50, 3, 0, n - 53]
    offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
    offs[-1] = m  # padding tail rides the last segment
    offsets = jnp.asarray(offs)
    cot = jnp.asarray(rng.randn(m, d).astype(np.float32))
    cot = cot.at[n:].set(0.0)  # padding rows never receive cotangent

    def ref(xs, wu, bu, wd, bd):
        outs = []
        bounds = [0] + list(np.cumsum(sizes))
        for i in range(e):
            s, t = bounds[i], bounds[i + 1]
            h = jnp.maximum(xs[s:t] @ wu[i] + bu[i], 0.0)
            outs.append(jnp.maximum(h @ wd[i] + bd[i], 0.0))
        outs.append(xs[n:] * 0.0)  # padding rows: ignored either way
        return jnp.concatenate(outs, axis=0)

    y = grouped_ffn(xs, wu, bu, wd, bd, offsets)
    y_ref = ref(xs, wu, bu, wd, bd)
    np.testing.assert_allclose(
        np.asarray(y)[:n], np.asarray(y_ref)[:n], atol=1e-5
    )

    loss_k = lambda *a: jnp.sum(grouped_ffn(*a, offsets) * cot)  # noqa: E731
    loss_r = lambda *a: jnp.sum(ref(*a) * cot)  # noqa: E731
    gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 4))(xs, wu, bu, wd, bd)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(xs, wu, bu, wd, bd)
    for name, a, b in zip(("dx", "dwu", "dbu", "dwd", "dbd"), gk, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
        )
    # padding-row dx is exactly zero: the tail contributes nothing to dW
    # and receives nothing back
    np.testing.assert_array_equal(np.asarray(gk[0][n:]), 0.0)


def test_count_involuntary_remat():
    """The detector recognizes the real round-5 warning text (verbatim from
    MULTICHIP_r05.json) and stays quiet on a clean log."""
    from tpukit.obs.xla import count_involuntary_remat

    warning = (
        "W0730 21:58:30.205580 5801 spmd_partitioner.cc:652] [SPMD] "
        "Involuntary full rematerialization. The compiler cannot go from "
        "sharding {devices=[1,8,1,1]<=[8]} to {devices=[4,1,1,1,2]<=[2,4]"
        "T(1,0) last_tile_dim_replicate} efficiently for HLO operation "
        "%transpose.9 = f32[8,1,5,64]{2,0,3,1} transpose(%dot), "
        'metadata={op_name="jit(train_step)/jvp(bsec,bsd->ebcd)/transpose"}.'
    )
    assert count_involuntary_remat(warning) == 1
    assert count_involuntary_remat(warning * 3) == 3
    assert count_involuntary_remat("dryrun_multichip ok: ep over mesh") == 0


def test_ep_dispatch_validation(cfg):
    """Typos fail at construction, and the a2a impl refuses to run without
    a mesh instead of silently computing something else."""
    from tpukit.model import GPTConfig
    from tpukit.ops.moe_dispatch import moe_ffn_a2a

    with pytest.raises(ValueError, match="dispatch"):
        ExpertParallel(create_mesh({"expert": 4}), dispatch="nccl")
    with pytest.raises(ValueError, match="moe_dispatch"):
        GPTConfig(num_experts=4, moe_dispatch="bogus")
    # the round-11 kernel dispatch is a first-class citizen of both gates
    assert GPTConfig(num_experts=4, moe_dispatch="pallas").moe_dispatch == "pallas"
    assert ExpertParallel(
        create_mesh({"expert": 4}), dispatch="pallas"
    ).dispatch == "pallas"

    params = init_params(jax.random.PRNGKey(0), cfg)
    layer0 = jax.tree.map(lambda t: t[0], params["layers"])
    x = jnp.zeros((2, SEQ, cfg.dim), jnp.float32)
    with pytest.raises(ValueError, match="moe_mesh"):
        moe_ffn_a2a(layer0, cfg.replace(moe_dispatch="a2a"), x)


def test_moe_dispatch_flag_plumbing():
    """--moe_dispatch parses on MoE recipes, defaults to a2a, and stays
    a2a-by-default for code paths that construct TrainFlags directly."""
    from tpukit.flags import TrainFlags, parse_flags

    assert TrainFlags().moe_dispatch == "a2a"
    flags = parse_flags(["--num_experts", "4"], num_experts=True)
    assert flags.moe_dispatch == "a2a"
    flags = parse_flags(["--moe_dispatch", "xla"], num_experts=True)
    assert flags.moe_dispatch == "xla"
    flags = parse_flags(["--moe_dispatch", "pallas"], num_experts=True)
    assert flags.moe_dispatch == "pallas"
    # non-MoE recipes don't grow the flag but keep the dataclass default
    assert parse_flags([]).moe_dispatch == "a2a"


def test_strategies_reject_moe(cfg):
    """Pipeline/CP/TP name ExpertParallel in their refusal; EP refuses
    dense configs and undividable expert counts."""
    from tpukit.pipeline import Pipeline
    from tpukit.shardings import ContextParallel, TensorParallel

    for strategy in (
        Pipeline(create_mesh({"stage": 4})),
        ContextParallel(create_mesh({"seq": 8})),
        TensorParallel(create_mesh({"model": 4})),
    ):
        with pytest.raises(ValueError, match="ExpertParallel"):
            strategy.validate_config(cfg)

    ep = ExpertParallel(create_mesh({"expert": 8}))
    with pytest.raises(ValueError, match="num_experts"):
        ep.validate_config(cfg.replace(num_experts=0))
    with pytest.raises(ValueError, match="divide"):
        ep.validate_config(cfg.replace(num_experts=4))  # 4 over 8-way axis
