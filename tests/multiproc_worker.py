"""Worker process for tests/test_multiprocess.py.

Launched N times (once per simulated host) with:
  TPUKIT_CPU_DEVICES=<local devices>  JAX_COORDINATOR_ADDRESS=localhost:<p>
  JAX_NUM_PROCESSES=<N>  JAX_PROCESS_ID=<rank>

Order matters and is the same contract every real multi-host tpukit launch
follows: configure the platform (import tpukit), then `initialize_runtime()`
BEFORE any backend-initializing JAX call, then run the recipe untouched.
This file is the CPU-localhost twin of `torchrun main-fsdp.py` on two nodes
(reference main-ddp.py:1-6, main-fsdp.py:1-6).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import tpukit  # noqa: F401  (TPUKIT_CPU_DEVICES -> cpu platform config)
from tpukit.mesh import initialize_runtime  # noqa: E402
from tpukit.recovery import TrainingAborted  # noqa: E402

initialize_runtime()

import jax  # noqa: E402


def main() -> None:
    recipe = sys.argv[1]  # e.g. "main-fsdp.py"
    workdir = sys.argv[2]  # shared dir: checkpoints + outputs land here
    out_path = sys.argv[3]
    recipe_args = sys.argv[4:]

    spec = importlib.util.spec_from_file_location(
        recipe.replace("-", "_").replace(".py", ""), REPO / recipe
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    os.chdir(workdir)
    try:
        result = mod.main(recipe_args)
    except TrainingAborted as exc:
        # The recipes' __main__ guard maps these onto the documented exit
        # codes (tpukit/recovery.py); the worker must honor the same
        # contract so the SIGTERM kill-midrun harness can assert on it.
        print(f"{type(exc).__name__}: {exc}", file=sys.stderr)
        sys.exit(exc.exit_code)

    out = {
        "rank": jax.process_index(),
        "world": jax.process_count(),
        "global_devices": len(jax.devices()),
        "eval_loss": float(result.metrics["eval"]["loss"]),
        "eval_accuracy": float(result.metrics["eval"]["accuracy"]),
        "train_tokens": int(result.metrics["train_tokens"]),
        "step": int(jax.device_get(result.state.step)),
        "checkpoint": str(result.checkpoint_path),
        "checkpoint_exists": result.checkpoint_path is not None
        and Path(result.checkpoint_path).exists(),
    }
    with open(out_path, "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
