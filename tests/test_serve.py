"""Continuous-batching serving engine (tpukit/serve, round 14, ROADMAP #1).

Contracts pinned here:
  - the batched KV-cached decode is token-for-token the SERIAL cached
    decode — greedy and fixed-seed sampling, ragged prompt lengths, and
    under mid-stream admit/evict slot reuse;
  - the scheduler's slot ring: eviction on EOS and on length, free-list
    reuse, bucket selection, admission rejection beyond the bucket set;
  - the serve path's compile budget is the DECLARED bucket set: one
    prefill program per bucket used + one decode program, asserted via
    the jit cache sizes;
  - the TP-mesh decode step's per-step collectives match the closed form
    `serve.decode_step_comm` exactly against compiled HLO, with zero
    involuntary-remat warnings (the round-10/12 audit discipline);
  - dropless-pallas MoE cached decode equals the full-reforward decode
    (the round-14 `use_cache` auto-resolve satellite);
  - `kind="serve"` / `kind="serve_summary"` JSONL records land and
    `tools/report.py` renders the serving section.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpukit.data import WordTokenizer, synthetic_stories
from tpukit.model import GPTConfig, init_params
from tpukit.sampling import _cached_decode_exact, _decode_loop_cached, generate
from tpukit.serve import (
    Request,
    ServeConfig,
    ServeEngine,
    decode_step,
    decode_step_comm,
    prefill_slots,
    synthetic_request_stream,
)
from tpukit.serve.decode import decode_loop

MAX_NEW = 10


@pytest.fixture(scope="module")
def tok():
    return WordTokenizer(synthetic_stories(64))


@pytest.fixture(scope="module")
def cfg(tok):
    return GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(1), cfg)


def _serial_cached(params, cfg, ids, max_new, eos_id, temperature=0.0,
                   top_k=0, seed=0):
    """Reference: the serial single-sequence cached decode on exact ids."""
    ids = np.asarray(ids, np.int32)
    buf = np.zeros((1, len(ids) + max_new), np.int32)
    buf[0, : len(ids)] = ids
    out, length = _decode_loop_cached(
        params, cfg, jnp.asarray(buf), len(ids), max_new, int(eos_id),
        temperature=float(temperature),
        top_k=min(int(top_k), cfg.padded_vocab_size),
        rng=jnp.asarray(np.asarray(jax.random.PRNGKey(seed)))
        if temperature > 0.0
        else None,
    )
    return np.asarray(out)[0, : int(length)]


# ---------------------------------------------------------------------------
# Batched cached decode (decode_loop): parity with the serial cached decode.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,seed",
    [(0.0, 0, 0), (0.9, 0, 3), (1.1, 5, 7)],
    ids=["greedy", "sampled", "sampled_topk"],
)
def test_decode_loop_matches_serial_cached(tok, cfg, params, temperature, top_k, seed):
    """Ragged prompt lengths in one [N, W] buffer: every row must decode
    the exact token sequence the serial cached decode produces for that
    prompt alone — greedy, and sampling under one fixed seed (the rows
    share the seed and fold their own cursors, like serial `generate`)."""
    prompts = ["One day, ", "The big brown cat sat on a mat ", "She said "]
    enc = tok(prompts, truncation=True, max_length=40)["input_ids"]
    lens = np.asarray([len(r) for r in enc], np.int32)
    buf = np.zeros((3, int(lens.max()) + MAX_NEW), np.int32)
    for i, r in enumerate(enc):
        buf[i, : len(r)] = r
    out, lengths = decode_loop(
        params, cfg, jnp.asarray(buf), jnp.asarray(lens), MAX_NEW,
        int(tok.eos_token_id), temperature=temperature, top_k=top_k,
        rng=jnp.asarray(np.asarray(jax.random.PRNGKey(seed)))
        if temperature > 0.0
        else None,
    )
    out, lengths = np.asarray(out), np.asarray(lengths)
    for i, ids in enumerate(enc):
        want = _serial_cached(params, cfg, ids, MAX_NEW, tok.eos_token_id,
                              temperature, top_k, seed)
        got = out[i, : int(lengths[i])]
        np.testing.assert_array_equal(got, want, err_msg=prompts[i])


# ---------------------------------------------------------------------------
# Engine: continuous batching with mid-stream admit/evict must stay serial-
# exact per request.
# ---------------------------------------------------------------------------


def _run_engine(params, cfg, tok, requests, serve):
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    comps = eng.run(list(requests), max_wall_s=300)
    return eng, comps


def test_engine_admit_evict_parity_greedy(tok, cfg, params):
    """8 requests through 3 slots forces mid-decode eviction + slot reuse
    + admissions while other slots are mid-sequence; every completion must
    still be token-for-token the serial cached decode of its own prompt."""
    serve = ServeConfig(slots=3, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 8, seed=3, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16))
    eng, comps = _run_engine(params, cfg, tok, reqs, serve)
    assert len(comps) == 8
    assert eng.admitted == 8 and not eng._lanes and len(eng._free) == 3
    for c in comps:
        want = _serial_cached(params, cfg, c.ids[: c.prompt_len], MAX_NEW,
                              tok.eos_token_id)
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {c.rid}")


def test_engine_admit_evict_parity_sampled(tok, cfg, params):
    """Same contract under per-request seeded sampling (temperature + top-k
    are engine-static; each request's key folds its own cursor), including
    arrivals spaced so admissions land mid-decode."""
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=MAX_NEW,
                        temperature=0.9, top_k=5, window_steps=8)
    reqs = synthetic_request_stream(tok, 6, seed=11, max_new_tokens=MAX_NEW,
                                    buckets=(8, 16), qps=50.0)
    eng, comps = _run_engine(params, cfg, tok, reqs, serve)
    assert len(comps) == 6
    for c in comps:
        want = _serial_cached(
            params, cfg, c.ids[: c.prompt_len], MAX_NEW, tok.eos_token_id,
            temperature=0.9, top_k=5, seed=11 + c.rid,
        )
        np.testing.assert_array_equal(c.ids, want, err_msg=f"rid {c.rid}")


def test_engine_evicts_on_eos_and_reuses_slot(tok, cfg, params):
    """Force a real EOS eviction: pick eos_id = the 3rd token the model
    would greedily generate, and check the slot retires with reason "eos",
    exactly 3 generated tokens (stop BEFORE appending, the reference
    semantics), returns to the free list, and serves the next request."""
    ids = tok(["One day, "], truncation=True, max_length=8)["input_ids"][0]
    free_run = _serial_cached(params, cfg, ids, MAX_NEW, eos_id=-1)
    eos = int(free_run[len(ids) + 3])  # the 4th generated token
    serve = ServeConfig(slots=1, buckets=(8,), max_new_tokens=MAX_NEW,
                        window_steps=4)
    reqs = [
        Request(rid=0, ids=tuple(int(x) for x in ids), max_new_tokens=MAX_NEW),
        Request(rid=1, ids=tuple(int(x) for x in ids), max_new_tokens=2),
    ]
    eng = ServeEngine(params, cfg, serve, eos_id=eos)
    comps = eng.run(reqs, max_wall_s=300)
    by_rid = {c.rid: c for c in comps}
    assert by_rid[0].reason == "eos" and by_rid[0].generated == 3
    np.testing.assert_array_equal(
        by_rid[0].ids, free_run[: len(ids) + 3]
    )
    # the single slot was reused for rid 1, which retires on length
    assert by_rid[1].reason == "length" and by_rid[1].generated == 2
    assert eng.evicted == {"eos": 1, "length": 1, "deadline": 0}
    assert list(eng._free) == [0]


def test_scheduler_buckets_and_validation(tok, cfg, params):
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=4)
    eng = ServeEngine(params, cfg, serve, eos_id=1)
    assert eng.bucket_for(1) == 8 and eng.bucket_for(8) == 8
    assert eng.bucket_for(9) == 16 and eng.bucket_for(16) == 16
    with pytest.raises(ValueError, match="largest declared bucket"):
        eng.bucket_for(17)
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(buckets=(16, 8))
    with pytest.raises(ValueError, match="slots"):
        ServeConfig(slots=0)
    with pytest.raises(ValueError, match="smaller than the largest bucket"):
        # a ring narrower than the largest bucket would crash at prefill
        ServeConfig(buckets=(16, 32), max_len=20)
    with pytest.raises(ValueError, match="position table"):
        # width 60 + 10 = 70 > max_position_embeddings 64
        ServeEngine(params, cfg, ServeConfig(slots=1, buckets=(60,),
                                             max_new_tokens=10), eos_id=1)


def test_synthetic_stream_deterministic(tok):
    a = synthetic_request_stream(tok, 6, seed=5, qps=10.0)
    b = synthetic_request_stream(tok, 6, seed=5, qps=10.0)
    assert [(r.ids, r.arrival_s, r.seed) for r in a] == [
        (r.ids, r.arrival_s, r.seed) for r in b
    ]
    assert all(x.arrival_s <= y.arrival_s for x, y in zip(a, a[1:]))
    c = synthetic_request_stream(tok, 6, seed=6, qps=10.0)
    assert [r.ids for r in a] != [r.ids for r in c]


# ---------------------------------------------------------------------------
# Compile budget: the serve path compiles one prefill program per declared
# (bucket, power-of-two admit size) pair plus one decode step — continuous
# batching must not retrace per request, occupancy, or prompt length.
# ---------------------------------------------------------------------------


def test_compile_count_bounded_by_declared_budget(tok, cfg, params):
    buckets = (8, 16)
    serve = ServeConfig(slots=2, buckets=buckets, max_new_tokens=6,
                        window_steps=8)
    # 2 slots -> admit sizes {1, 2}: budget = 1 decode + 2 buckets x 2
    assert serve.compile_budget == 5
    prefill0 = prefill_slots._cache_size()
    decode0 = decode_step._cache_size()
    reqs = synthetic_request_stream(tok, 10, seed=2, max_new_tokens=6,
                                    buckets=buckets)
    eng, comps = _run_engine(params, cfg, tok, reqs, serve)
    assert len(comps) == 10
    assert eng.buckets_used <= set(buckets)
    # 10 requests with ragged prompts over 2 slots: serve-path compiles
    # bounded by the DECLARED budget, with exactly one decode program
    prefill_added = prefill_slots._cache_size() - prefill0
    decode_added = decode_step._cache_size() - decode0
    assert decode_added <= 1
    assert prefill_added + decode_added <= serve.compile_budget
    # a second engine over the same buckets must add ZERO compiles
    prefill1 = prefill_slots._cache_size()
    decode1 = decode_step._cache_size()
    _run_engine(params, cfg, tok, synthetic_request_stream(
        tok, 4, seed=9, max_new_tokens=6, buckets=buckets), serve)
    assert prefill_slots._cache_size() == prefill1
    assert decode_step._cache_size() == decode1


# ---------------------------------------------------------------------------
# Sharded serving: params at their TP training shardings, KV ring sharded
# (heads over `model`, slots over `data`) — per-step collectives must match
# the closed form exactly, with zero involuntary-remat warnings.
# ---------------------------------------------------------------------------


def _tp_decode_state(cfg, mesh, slots, width):
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tpukit.model import gpt
    from tpukit.shardings import TensorParallel

    strat = TensorParallel(mesh)
    params = init_params(jax.random.PRNGKey(0), cfg)
    psh = strat.state_sharding(jax.eval_shape(lambda: params))
    params = jax.tree.map(jax.device_put, params, psh)
    sh = lambda spec: NamedSharding(mesh, spec)
    da = "data" if "data" in mesh.axis_names else None
    buf = jax.device_put(np.zeros((slots, width), np.int32), sh(P(da, None)))
    cache = jax.tree.map(
        lambda c: jax.device_put(c, sh(P(None, da, "model", None, None))),
        gpt.init_kv_cache(cfg, slots, width),
    )
    cursors = jax.device_put(np.full((slots,), 5, np.int32), sh(P(da)))
    active = jax.device_put(np.ones((slots,), bool), sh(P(da)))
    limits = jax.device_put(np.full((slots,), 12, np.int32), sh(P(da)))
    keys = jax.device_put(np.zeros((slots, 2), np.uint32), sh(P(da, None)))
    return params, buf, cache, cursors, active, limits, keys


@pytest.mark.parametrize(
    "axes,slots,temperature,top_k",
    [
        ({"data": 2, "model": 4}, 4, 0.0, 0),
        ({"data": 2, "model": 4}, 4, 0.9, 5),
        ({"data": 4, "model": 2}, 8, 0.0, 0),
    ],
    ids=["d2m4_greedy", "d2m4_topk", "d4m2_greedy"],
)
def test_tp_decode_step_hlo_comm_audit(axes, slots, temperature, top_k):
    """The decode step under the TP mesh must move EXACTLY the closed-form
    collectives (`decode_step_comm`): the Megatron all-reduce pair per
    layer + the embedding-gather psum, the one deliberate logits
    all-gather, and (top-k only) lax.top_k's data-axis gather — nothing
    else, and zero GSPMD involuntary-remat fallbacks. f32 compute so the
    byte counts are exact on the CPU wire (round-12 lesson)."""
    from tpukit.mesh import create_mesh
    from tpukit.obs.xla import capture_compiler_stderr, collective_bytes

    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=160,
        max_position_embeddings=64, compute_dtype=jnp.float32,
    )
    mesh = create_mesh(axes)
    params, buf, cache, cursors, active, limits, keys = _tp_decode_state(
        cfg, mesh, slots, width=24
    )
    # check=True raises on any involuntary-remat warning at capture exit
    with capture_compiler_stderr(check=True):
        compiled = decode_step.lower(
            params, cfg, buf, cache, cursors, active, limits, keys,
            1, temperature, top_k, mesh,
        ).compile()
    measured = collective_bytes(compiled.as_text())
    expected = decode_step_comm(cfg, mesh, slots, top_k=top_k)
    assert measured == expected, (measured, expected)


def test_tp_engine_decode_parity(tok, cfg, params):
    """Value check on top of the byte audit: the engine under the TP mesh
    (params TP-sharded, KV ring sharded over heads x slots) decodes the
    same tokens as the meshless engine."""
    from tpukit.mesh import create_mesh
    from tpukit.shardings import TensorParallel

    mesh = create_mesh({"data": 2, "model": 4})
    strat = TensorParallel(mesh)
    tp_params = jax.tree.map(
        jax.device_put, params, strat.state_sharding(jax.eval_shape(lambda: params))
    )
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=6,
                        window_steps=8)
    reqs = synthetic_request_stream(tok, 4, seed=4, max_new_tokens=6,
                                    buckets=(8, 16))
    eng_tp = ServeEngine(tp_params, cfg, serve, eos_id=int(tok.eos_token_id),
                         mesh=mesh)
    comps_tp = {c.rid: c for c in eng_tp.run(list(reqs), max_wall_s=300)}
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id))
    comps = {c.rid: c for c in eng.run(list(reqs), max_wall_s=300)}
    assert comps_tp.keys() == comps.keys()
    for rid in comps:
        np.testing.assert_array_equal(comps_tp[rid].ids, comps[rid].ids)


def test_engine_slot_mesh_divisibility():
    from tpukit.mesh import create_mesh

    cfg = GPTConfig(dim=32, head_dim=8, heads=4, num_layers=1, vocab_size=97,
                    max_position_embeddings=64, compute_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = create_mesh({"data": 4, "model": 2})
    with pytest.raises(ValueError, match="multiple of the mesh's data axis"):
        ServeEngine(params, cfg, ServeConfig(slots=3, buckets=(8,)),
                    eos_id=1, mesh=mesh)
    with pytest.raises(ValueError, match="heads"):
        decode_step_comm(cfg.replace(heads=3), mesh, 4)


# ---------------------------------------------------------------------------
# Dropless-pallas MoE: cached decode is exact (the use_cache auto-resolve
# satellite) — and the predicate's truth table.
# ---------------------------------------------------------------------------


def test_cached_decode_exact_predicate(cfg):
    assert _cached_decode_exact(cfg)  # dense
    moe = cfg.replace(num_experts=2)
    assert not _cached_decode_exact(moe)  # xla buffer dispatch
    assert not _cached_decode_exact(moe.replace(moe_dispatch="a2a"))
    assert _cached_decode_exact(moe.replace(moe_dispatch="pallas"))
    assert not _cached_decode_exact(
        moe.replace(moe_dispatch="pallas", moe_capacity=4)
    )


def test_moe_pallas_cached_equals_uncached(tok):
    """Dropless pallas MoE: per-token routing is chunk-composition-
    independent and nothing is dropped, so the KV-cached decode must equal
    the full-reforward decode token-for-token (greedy and seeded
    sampling) — the justification for lifting the num_experts==0 guard in
    generate's use_cache auto-resolve (gpt._apply_moe_ffn docstring)."""
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=2, vocab_size=tok.vocab_size,
        max_position_embeddings=64, compute_dtype=jnp.float32,
        num_experts=2, moe_dispatch="pallas",
    )
    params = init_params(jax.random.PRNGKey(6), cfg)
    for prompt, kw in [
        ("One day, ", {}),
        ("She said ", dict(temperature=0.9, top_k=3, seed=5)),
    ]:
        cached = generate(params, cfg, prompt, tok, max_new_tokens=6,
                          use_cache=True, **kw)
        uncached = generate(params, cfg, prompt, tok, max_new_tokens=6,
                            use_cache=False, **kw)
        assert cached == uncached, (prompt, kw)


# ---------------------------------------------------------------------------
# Serving telemetry: JSONL windows + summary land and report.py renders.
# ---------------------------------------------------------------------------


def test_serve_jsonl_windows_and_report(tok, cfg, params, tmp_path):
    from tpukit.obs import FlightRecorder, StepLogger

    log = tmp_path / "serve.jsonl"
    logger = StepLogger(str(log))
    recorder = FlightRecorder(capacity=64)
    serve = ServeConfig(slots=2, buckets=(8, 16), max_new_tokens=8,
                        window_steps=4)
    reqs = synthetic_request_stream(tok, 5, seed=8, max_new_tokens=8,
                                    buckets=(8, 16))
    eng = ServeEngine(params, cfg, serve, eos_id=int(tok.eos_token_id),
                      logger=logger, recorder=recorder)
    eng.run(reqs, max_wall_s=300)
    logger.close()

    recs = [json.loads(l) for l in log.read_text().splitlines()]
    windows = [r for r in recs if r["kind"] == "serve"]
    summaries = [r for r in recs if r["kind"] == "serve_summary"]
    assert windows and len(summaries) == 1
    for w in windows:
        assert w["steps"] > 0 and 0.0 <= w["occupancy"] <= 1.0
        assert {"prefill", "decode", "sync"} & set(w["seconds"])
    s = summaries[0]
    assert s["requests"] == 5
    assert s["generated_tokens"] == sum(
        w["new_tokens"] for w in windows
    )
    assert s["tokens_per_sec"] > 0 and s["p99_e2e_s"] >= s["p50_e2e_s"]
    assert s["p99_token_s"] >= s["p50_token_s"] > 0
    assert set(s["buckets_used"]) <= set(s["buckets"])
    assert s["decode_s"] > 0 and s["sync_s"] >= 0 and s["prefill_s"] > 0
    # the flight recorder saw the same windows
    ring = [r for r in recorder.snapshot() if r["kind"] == "serve"]
    assert len(ring) == len(windows)

    # tools/report.py renders a serving section from the same file
    import importlib

    report = importlib.import_module("tools.report")
    text = report.summarize(recs)
    assert "== serving ==" in text
    assert "tokens/s" in text and "occupancy" in text
