"""Tensor-parallel strategy: Megatron-style GSPMD sharding must reproduce the
single-device step, and the sharding rules must hit the intended dims."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpukit.mesh import create_mesh
from tpukit.model import GPTConfig, init_params
from tpukit.shardings import SingleDevice, TensorParallel
from tpukit.train import create_train_state, make_optimizer, make_step_fns

SEQ = 32


@pytest.fixture(scope="module")
def cfg():
    # inner=32, ffn hidden=128, vocab 160: all divide the 8-way model axis
    return GPTConfig(
        dim=32,
        head_dim=8,
        heads=4,
        num_layers=2,
        vocab_size=160,
        max_position_embeddings=SEQ,
        compute_dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def batch(cfg):
    rng = np.random.RandomState(11)
    ids = rng.randint(3, cfg.vocab_size, size=(8, SEQ)).astype(np.int32)
    mask = np.zeros((8, SEQ), dtype=bool)
    mask[1, 25:] = True
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    targets[mask] = -100
    model_batch = {
        "input_ids": ids,
        "position_ids": np.ascontiguousarray(
            np.broadcast_to(np.arange(SEQ, dtype=np.int32), ids.shape)
        ),
        "mask": mask,
    }
    return model_batch, targets


def _one_step(strategy, cfg, batch, targets):
    opt = make_optimizer(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    train_step, eval_step, _ = make_step_fns(cfg, opt, strategy, shapes)
    new_state, loss = train_step(state, batch, targets)
    eval_loss, _ = eval_step(new_state, batch, targets)
    return jax.device_get(new_state.params), float(loss), float(eval_loss)


def test_tp_matches_single(cfg, batch):
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    tp = _one_step(TensorParallel(create_mesh({"model": 8})), cfg, model_batch, targets)
    assert abs(tp[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        tp[0],
        ref[0],
    )


def test_tp_data_hybrid_matches_single(cfg, batch):
    model_batch, targets = batch
    ref = _one_step(SingleDevice(), cfg, model_batch, targets)
    tp = _one_step(
        TensorParallel(create_mesh({"data": 2, "model": 4})), cfg, model_batch, targets
    )
    assert abs(tp[1] - ref[1]) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4),
        tp[0],
        ref[0],
    )


def test_tp_sharding_rules(cfg):
    strategy = TensorParallel(create_mesh({"model": 8}))
    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg, opt)
    )
    sh = strategy.state_sharding(shapes)
    p = sh.params
    # column parallel: qkv + ffn up shard their output dim
    assert p["layers"]["attn"]["q"]["kernel"].spec == P(None, None, "model")
    assert p["layers"]["ffn"]["up"]["kernel"].spec == P(None, None, "model")
    assert p["layers"]["ffn"]["up"]["bias"].spec == P(None, "model")
    # row parallel: attn out + ffn down shard their input dim
    assert p["layers"]["attn"]["out"]["kernel"].spec == P(None, "model", None)
    assert p["layers"]["ffn"]["down"]["kernel"].spec == P(None, "model", None)
    # row-parallel biases and norms replicate
    assert p["layers"]["attn"]["out"]["bias"].spec == P()
    assert p["layers"]["norm1"]["scale"].spec == P()
    # vocab sharding
    assert p["lm_head"]["kernel"].spec == P(None, "model")
    assert p["embeddings"]["token"].spec == P("model", None)
    # optimizer state mirrors params
    assert sh.opt_state[0].mu["layers"]["attn"]["q"]["kernel"].spec == P(None, None, "model")


def test_tp_undividable_dims_replicate():
    cfg = GPTConfig(
        dim=30, head_dim=6, heads=5, num_layers=1, vocab_size=151, ffn_mult=3,
        max_position_embeddings=16, compute_dtype=jnp.float32,
        vocab_pad_multiple=1,  # keep vocab at 151 so no dim divides the axis
    )
    strategy = TensorParallel(create_mesh({"model": 8}))
    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(lambda: create_train_state(jax.random.PRNGKey(0), cfg, opt))
    sh = strategy.state_sharding(shapes)
    # inner=30, hidden=90, vocab=151 — none divide 8 -> everything replicated
    for leaf in jax.tree_util.tree_leaves(
        jax.tree.map(lambda s: s.spec, sh.params)
    ):
        assert leaf == P() or leaf == P(None)


def test_tp_loss_fn_disables_fused_qkv():
    """TP must compute q/k/v as three column-parallel matmuls: concatenating
    the column-sharded kernels would re-lay-out weights every step (verified
    in review: the fused form emits dozens of all-to-alls in HLO)."""
    captured = {}
    import tpukit.model.gpt as gpt_mod

    orig = gpt_mod._apply_attention

    def spy(layer, cfg, *args, **kw):
        captured["fuse_qkv"] = cfg.fuse_qkv
        return orig(layer, cfg, *args, **kw)

    strategy = TensorParallel(create_mesh({"model": 8}))
    cfg = GPTConfig(
        dim=32, head_dim=8, heads=4, num_layers=1, vocab_size=97,
        max_position_embeddings=16, compute_dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    ids = np.zeros((2, 8), np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.broadcast_to(np.arange(8, dtype=np.int32), ids.shape).copy(),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    targets = np.zeros_like(ids)
    gpt_mod._apply_attention = spy
    try:
        strategy.loss_fn(params, cfg, batch, targets)
    finally:
        gpt_mod._apply_attention = orig
    assert captured["fuse_qkv"] is False
