"""Tests for the round-6 telemetry subsystem (tpukit/obs) + its satellites.

Covers the four pillars on the virtual CPU mesh: span-timeline accounting
(seconds sum to wall clock, goodput in (0, 1]), XLA static analysis of a
compiled DP train step (FLOPs, memory, all-reduce comm bytes from the
HLO), in-jit grad norms vs an eager reference, the loss-spike/NaN sentinel,
heartbeat liveness files, and the end-to-end `fit()` JSONL contract that
`tools/report.py` renders. Satellite regressions ride along: the analytic
loader schedule vs brute-force enumeration, the fail-loud sampling cache
check, and `time_windows(warmup=0)`.
"""

import json
import time

import jax
import numpy as np
import optax
import pytest

from tpukit.obs import (
    Heartbeat,
    SpanTimeline,
    SpikeSentinel,
    collective_bytes,
    compiled_stats,
    format_breakdown,
)


# ---------------------------------------------------------------------------
# span timeline
# ---------------------------------------------------------------------------


def test_span_timeline_sums_to_wall_clock():
    tl = SpanTimeline()
    with tl.span("step"):
        time.sleep(0.02)
    with tl.span("data"):
        time.sleep(0.01)
    with tl.span("sync"):
        time.sleep(0.01)
    time.sleep(0.005)  # unattributed -> "other"
    win = tl.window()
    assert win["total_s"] >= 0.045
    assert abs(sum(win["seconds"].values()) - win["total_s"]) < 1e-6
    assert abs(sum(win["fractions"].values()) - 1.0) < 1e-6
    assert 0.0 < win["goodput"] <= 1.0
    # goodput is exactly the step+sync share
    assert win["goodput"] == pytest.approx(
        win["fractions"]["step"] + win["fractions"]["sync"]
    )
    assert win["seconds"]["other"] >= 0.004
    # window() resets: an immediate second window is ~empty
    win2 = tl.window()
    assert win2["seconds"].get("step", 0.0) == 0.0


def test_nested_spans_attribute_to_outer_only():
    tl = SpanTimeline()
    with tl.span("eval"):
        with tl.span("telemetry"):  # e.g. capture_xla inside the eval phase
            time.sleep(0.01)
    win = tl.window()
    assert "telemetry" not in win["seconds"]
    assert win["seconds"]["eval"] >= 0.009


def test_epoch_breakdown_spans_windows():
    tl = SpanTimeline()
    with tl.span("step"):
        time.sleep(0.01)
    tl.window()
    with tl.span("step"):
        time.sleep(0.01)
    ep = tl.epoch()  # covers both windows
    assert ep["seconds"]["step"] >= 0.018
    assert abs(sum(ep["seconds"].values()) - ep["total_s"]) < 1e-6
    assert "goodput" in format_breakdown(ep)


# ---------------------------------------------------------------------------
# XLA static analysis
# ---------------------------------------------------------------------------


def test_collective_bytes_parses_hlo():
    hlo = """
  %ar = f32[8,128]{1,0} all-reduce(f32[8,128]{1,0} %x), replica_groups={}
  %t = (f32[16]{0}, bf16[4,4]{1,0}) all-reduce(%a, %b), channel_id=1
  %ag = bf16[64,32]{1,0} all-gather(bf16[8,32]{1,0} %y), dimensions={0}
  %cp = f32[2,2]{1,0} collective-permute-start(f32[2,2]{1,0} %z)
  %cpd = f32[2,2]{1,0} collective-permute-done(f32[2,2]{1,0} %cp)
  %rs = f32[8]{0} reduce-scatter(f32[64]{0} %w), dimensions={0}
"""
    got = collective_bytes(hlo)
    assert got["all-reduce"]["count"] == 2
    assert got["all-reduce"]["bytes"] == 8 * 128 * 4 + 16 * 4 + 16 * 2
    assert got["all-gather"] == {"count": 1, "bytes": 64 * 32 * 2}
    # async pairs count once (the -start; -done carries no new payload)
    assert got["collective-permute"] == {"count": 1, "bytes": 16}
    assert got["reduce-scatter"] == {"count": 1, "bytes": 32}
    assert collective_bytes("%a = f32[2] add(%b, %c)") == {}


def test_collective_bytes_counts_async_result_half_only():
    """TPU-optimized HLO emits async pairs whose -start result tuple
    carries (operands..., results..., ctx scalars...): only the results
    half is moved volume — summing the whole tuple would double it."""
    hlo = """
  %ag = (bf16[4,64]{1,0}, bf16[8,64]{1,0}) all-gather-start(bf16[4,64]{1,0} %x)
  %agd = bf16[8,64]{1,0} all-gather-done((bf16[4,64]{1,0}, bf16[8,64]{1,0}) %ag)
  %cp = (f32[8,128]{1,0}, f32[8,128]{1,0}, u32[], u32[]) collective-permute-start(f32[8,128]{1,0} %y)
  %ar = (f32[16]{0}, bf16[4]{0}) all-reduce-start(%a, %b)
"""
    got = collective_bytes(hlo)
    assert got["all-gather"] == {"count": 1, "bytes": 8 * 64 * 2}  # post-gather
    assert got["collective-permute"] == {"count": 1, "bytes": 8 * 128 * 4}
    # all-reduce-start's tuple holds ONLY results (XLA's combiner fuses
    # buffers into one variadic all-reduce) — never halved
    assert got["all-reduce"] == {"count": 1, "bytes": 16 * 4 + 4 * 2}


def _batch_structs(batch_size, seq):
    batch = {
        "input_ids": jax.ShapeDtypeStruct((batch_size, seq), np.int32),
        "position_ids": jax.ShapeDtypeStruct((batch_size, seq), np.int32),
        "mask": jax.ShapeDtypeStruct((batch_size, seq), np.bool_),
    }
    return batch, jax.ShapeDtypeStruct((batch_size, seq), np.int32)


def test_compiled_stats_on_cpu_mesh(tiny_config):
    """Acceptance: cost/memory analysis + comm bytes captured on the CPU
    mesh — the DP grad psum must surface as all-reduce traffic."""
    from tpukit.shardings import DataParallel
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    opt = make_optimizer(1e-3)
    strat = DataParallel()
    state_shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), tiny_config, opt)
    )
    step, _, _ = make_step_fns(tiny_config, opt, strat, state_shapes)
    batch, targets = _batch_structs(8, 16)
    stats = compiled_stats(step, state_shapes, batch, targets)
    assert stats is not None
    assert stats["flops"] is not None and stats["flops"] > 0
    assert stats["bytes_accessed"] is not None and stats["bytes_accessed"] > 0
    coll = stats["collectives"]
    assert coll and "all-reduce" in coll
    assert coll["all-reduce"]["count"] >= 1
    assert coll["all-reduce"]["bytes"] > 0
    # XLA:CPU supports memory_analysis (tools/pipeline_memory.py relies on
    # it); peak estimate must cover at least the argument (state) bytes
    mem = stats["memory"]
    assert mem is not None
    assert mem["temp_size_in_bytes"] >= 0
    assert mem["peak_bytes_estimate"] > 0


def test_compiled_stats_is_none_on_lowering_failure():
    assert compiled_stats(jax.jit(lambda x: x)) is None  # missing avals


# ---------------------------------------------------------------------------
# grad-norm sentinels (in-jit half)
# ---------------------------------------------------------------------------


def _train_batch(rng, cfg, batch_size=8, seq=16):
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seq)).astype(np.int32)
    batch = {
        "input_ids": ids,
        "position_ids": np.broadcast_to(
            np.arange(seq, dtype=np.int32), ids.shape
        ).copy(),
        "mask": np.zeros_like(ids, dtype=bool),
    }
    return batch, np.roll(ids, -1, axis=1).astype(np.int32)


def test_grad_norms_match_eager_reference(tiny_config, rng):
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    cfg = tiny_config
    opt = make_optimizer(1e-3)
    strat = SingleDevice()
    state = create_train_state(jax.random.PRNGKey(0), cfg, opt)
    shapes = jax.eval_shape(lambda: state)
    step, _, _ = make_step_fns(cfg, opt, strat, shapes, log_grad_norms=True)
    batch, targets = _train_batch(rng, cfg)

    # reference grads on the PRE-step params (copied before donation)
    params_before = jax.tree.map(np.asarray, state.params)
    ref_grads = jax.jit(
        jax.grad(lambda p: strat.loss_fn(p, cfg, batch, targets)[0])
    )(params_before)
    ref_norm = float(optax.global_norm(ref_grads))

    new_state, loss, norms = step(state, batch, targets)
    assert set(norms) == {"grad_norm", "update_norm", "param_norm"}
    assert float(norms["grad_norm"]) == pytest.approx(ref_norm, rel=1e-4)
    # param_norm is the POST-update parameter norm
    assert float(norms["param_norm"]) == pytest.approx(
        float(optax.global_norm(new_state.params)), rel=1e-5
    )
    assert float(norms["update_norm"]) > 0.0
    assert np.isfinite(float(loss))


def test_train_step_unchanged_without_norm_flag(tiny_config):
    """Flag off -> the step's output arity (and traced graph) is exactly the
    pre-telemetry one; flag on only APPENDS the norms dict."""
    from tpukit.shardings import SingleDevice
    from tpukit.train import create_train_state, make_optimizer, make_step_fns

    opt = make_optimizer(1e-3)
    shapes = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), tiny_config, opt)
    )
    batch, targets = _batch_structs(4, 16)
    step_off, _, _ = make_step_fns(tiny_config, opt, SingleDevice(), shapes)
    step_on, _, _ = make_step_fns(
        tiny_config, opt, SingleDevice(), shapes, log_grad_norms=True
    )
    out_off = jax.eval_shape(step_off, shapes, batch, targets)
    out_on = jax.eval_shape(step_on, shapes, batch, targets)
    assert len(out_off) == 2
    assert len(out_on) == 3 and set(out_on[2]) == {
        "grad_norm", "update_norm", "param_norm",
    }


# ---------------------------------------------------------------------------
# loss-spike sentinel (host half)
# ---------------------------------------------------------------------------


def test_spike_sentinel_fires_on_injected_spike():
    s = SpikeSentinel(threshold=3.0, min_history=4)
    for i in range(8):  # steady-ish baseline
        assert s.observe(2.0 + 0.01 * (i % 2), step=i) is None
    ev = s.observe(5.0, step=8)
    assert ev is not None and ev.kind == "spike" and ev.step == 8
    assert ev.loss == 5.0 and 1.9 < ev.mean < 2.1
    # the spike was not absorbed into the baseline: a sustained divergence
    # keeps firing
    assert s.observe(5.0, step=9) is not None
    rec = ev.record()
    assert rec["event"] == "spike" and "kind" not in rec


def test_spike_sentinel_fires_on_nan_and_inf():
    s = SpikeSentinel(threshold=3.0)
    assert s.observe(float("nan"), step=1).kind == "nan"
    assert s.observe(float("inf"), step=2).kind == "nan"


def test_spike_sentinel_quiet_on_descent_and_noise():
    s = SpikeSentinel(threshold=3.0)
    rng = np.random.RandomState(0)
    loss = 6.0
    for i in range(64):  # normal training: decreasing + noise
        loss = loss * 0.99 + rng.randn() * 0.01
        assert s.observe(loss, step=i) is None


def test_spike_sentinel_rejects_bad_threshold():
    with pytest.raises(ValueError, match="threshold"):
        SpikeSentinel(threshold=0.0)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------


def test_heartbeat_write_check_and_stragglers(tmp_path):
    h0 = Heartbeat(tmp_path, process_index=0, process_count=3, timeout_s=60)
    h1 = Heartbeat(tmp_path, process_index=1, process_count=3, timeout_s=60)
    h0.beat(10)
    h1.beat(8)
    beats = h0.read_all()
    assert set(beats) == {0, 1}
    assert beats[0]["step"] == 10 and beats[1]["step"] == 8

    # process 2 never wrote
    stragglers = h0.check()
    assert [(s["process"], s["reason"]) for s in stragglers] == [(2, "missing")]

    # everything is stale an hour later
    stale = h0.check(now=time.time() + 3600)
    assert {s["process"] for s in stale} == {0, 1, 2}
    assert {s["reason"] for s in stale} == {"stale", "missing"}

    # step lag: process 2 alive but far behind
    h2 = Heartbeat(tmp_path, process_index=2, process_count=3, timeout_s=60)
    h2.beat(1)
    lag = h0.check(step_lag=5)
    assert [(s["process"], s["reason"]) for s in lag] == [(2, "lagging")]
    assert lag[0]["behind"] == 9

    # torn/foreign files are skipped, never raised on
    (tmp_path / "heartbeat-p00099.json").write_text("{not json")
    assert set(h0.read_all()) == {0, 1, 2}


def test_heartbeat_timeout_scales_with_beat_cadence(tmp_path):
    """Beats land once per PRINT_FREQ window; when a big-model window is
    longer than the fixed timeout, the checker must scale its staleness
    threshold from the observed cadence instead of flagging every healthy
    peer on every check."""
    h = Heartbeat(tmp_path, process_index=0, process_count=1, timeout_s=10)
    t0 = 1_000_000.0
    h.beat(1, now=t0)
    h.beat(2, now=t0 + 100)  # observed window cadence 100s >> timeout 10s
    # 150s-old beat is healthy under the 3x-cadence threshold (300s)...
    assert h.check(now=t0 + 250) == []
    # ...but past it the stale report still fires
    stale = h.check(now=t0 + 100 + 301)
    assert [s["reason"] for s in stale] == ["stale"]


# ---------------------------------------------------------------------------
# loader satellite: analytic global schedule == brute-force enumeration
# ---------------------------------------------------------------------------


def _make_dataset(n, seq=8):
    from tpukit.data import ArrayDataset

    ids = np.arange(n * seq, dtype=np.int32).reshape(n, seq) % 97 + 3
    return ArrayDataset(ids, np.ones_like(ids))


@pytest.mark.parametrize("pad_mode", ["wrap", "empty"])
@pytest.mark.parametrize("drop_last", [False, True])
@pytest.mark.parametrize(
    "n,reps,bs",
    [(253, 2, 32), (64, 1, 16), (64, 2, 8), (100, 3, 8), (7, 4, 4), (33, 8, 2), (5, 2, 8)],
)
def test_global_real_row_counts_matches_enumeration(n, reps, bs, drop_last, pad_mode):
    from tpukit.loader import DataLoader

    ds = _make_dataset(n)
    loaders = [
        DataLoader(
            ds, bs, shuffle=True, seed=7, num_replicas=reps, rank=r,
            drop_last=drop_last, pad_to_batch=True, pad_mode=pad_mode,
        )
        for r in range(reps)
    ]
    for epoch in (0, 3):  # schedule must be shuffle-epoch-invariant
        for ld in loaders:
            ld.set_epoch(epoch)
        analytic = loaders[0].global_real_row_counts()
        # brute force: enumerate every rank's real mask per batch
        brute = None
        for ld in loaders:
            _, real = ld._indices()
            stop = (len(real) // bs) * bs if drop_last else len(real)
            per = np.array(
                [real[s : s + bs].sum() for s in range(0, stop, bs)], np.int64
            )
            brute = per if brute is None else brute + per
        np.testing.assert_array_equal(analytic, brute)
        if not drop_last:
            assert int(analytic.sum()) == n  # every original row exactly once


def test_global_real_row_counts_respects_subclass_schedule():
    """ADVICE r5 #3: a subclass overriding `_indices` must not silently get
    the base-class closed form — the method falls back to enumerating the
    subclass's actual schedule."""
    from tpukit.loader import DataLoader

    class HalfLoader(DataLoader):
        # keeps only the first half of the dataset (custom schedule)
        def _indices(self):
            idx, real = super()._indices()
            keep = len(self.dataset) // (2 * self.num_replicas)
            return idx[:keep], real[:keep]

    ds = _make_dataset(64)
    loaders = [
        HalfLoader(ds, 8, shuffle=True, seed=3, num_replicas=2, rank=r)
        for r in range(2)
    ]
    analytic = loaders[0].global_real_row_counts()
    brute = None
    for ld in loaders:
        _, real = ld._indices()
        per = np.array(
            [real[s : s + 8].sum() for s in range(0, len(real), 8)], np.int64
        )
        brute = per if brute is None else brute + per
    np.testing.assert_array_equal(analytic, brute)
    assert int(analytic.sum()) == 32  # half of 64, not the base schedule's 64


def test_global_real_row_counts_agrees_with_iterated_real_rows():
    """The schedule must match what the loaders actually YIELD (the
    real_rows field the meter consumes)."""
    from tpukit.loader import DataLoader

    ds = _make_dataset(253)
    loaders = [
        DataLoader(
            ds, 32, shuffle=True, seed=1, num_replicas=2, rank=r,
            pad_to_batch=True,
        )
        for r in range(2)
    ]
    for ld in loaders:
        ld.set_epoch(2)
    analytic = loaders[0].global_real_row_counts()
    yielded = [
        np.array([b["real_rows"] for b in ld], dtype=np.int64) for ld in loaders
    ]
    np.testing.assert_array_equal(analytic, yielded[0] + yielded[1])


# ---------------------------------------------------------------------------
# remaining satellites
# ---------------------------------------------------------------------------


def test_generate_use_cache_with_temperature_samples(tiny_config, tiny_params):
    """Round 11 (ROADMAP #1 first rung): the cached decode loop implements
    temperature sampling — the explicit use_cache=True + temperature>0
    combination that raised through round 10 (VERDICT r5 #5) now decodes,
    reproducibly under a fixed seed. Token-level cached-vs-uncached
    same-seed equivalence lives in tests/test_sampling.py."""
    from tpukit.data import get_tokenizer
    from tpukit.sampling import generate

    tok = get_tokenizer()
    a = generate(
        tiny_params, tiny_config, "The big brown cat ", tok,
        max_new_tokens=6, use_cache=True, temperature=0.7, seed=3,
    )
    b = generate(
        tiny_params, tiny_config, "The big brown cat ", tok,
        max_new_tokens=6, use_cache=True, temperature=0.7, seed=3,
    )
    assert isinstance(a, str) and a == b


def test_generate_auto_cache_with_temperature_uses_cached_loop(
    tiny_config, tiny_params, monkeypatch
):
    """The long-buffer heuristic no longer downgrades sampling runs: with
    use_cache auto-resolved (caller passed None) and a >=512-token buffer,
    temperature>0 routes to the CACHED loop with the temperature intact
    (through round 10 it silently fell back to the O(S^2) re-forward loop
    because the cached loop was greedy-only)."""
    import tpukit.sampling as sampling
    from tpukit.data import get_tokenizer

    seen = {}

    def fake_loop(params, cfg, buf, prompt_len, max_new, eos,
                  temperature=0.0, top_k=0, rng=None):
        seen["temperature"] = temperature
        seen["has_rng"] = rng is not None
        return buf, np.int32(int(prompt_len))

    monkeypatch.setattr(sampling, "_decode_loop_cached", fake_loop)
    cfg = tiny_config.replace(max_position_embeddings=1024)
    tok = get_tokenizer()
    out = sampling.generate(
        tiny_params, cfg, "The big brown cat ", tok,
        max_new_tokens=600, temperature=0.7,
    )
    assert seen["temperature"] == 0.7 and seen["has_rng"]
    assert isinstance(out, str)


def test_time_windows_zero_warmup():
    from tools.bench_ladder import time_windows

    def step(state, b, t):
        return state, np.float32(1.5)

    times, _, last = time_windows(step, None, None, None, steps=2, windows=1, warmup=0)
    assert len(times) == 1 and last == 1.5


def test_moe_config_fails_loudly_from_direct_value_and_grad(tiny_config):
    """ADVICE r5 #1: the curated MoE ValueError (not a TypeError about
    aux_out) from direct strategy.value_and_grad calls."""
    from tpukit.mesh import create_mesh
    from tpukit.pipeline import Pipeline, Pipeline1F1B
    from tpukit.shardings import ContextParallel, TensorParallel

    cfg = tiny_config.replace(num_experts=4)
    dummy = {"input_ids": None}
    for strat, match in [
        (ContextParallel(create_mesh({"seq": 2})), "ExpertParallel"),
        (TensorParallel(create_mesh({"model": 2})), "ExpertParallel"),
        (Pipeline(create_mesh({"stage": 2})), "ExpertParallel"),
        (Pipeline1F1B(create_mesh({"stage": 2})), "ExpertParallel"),
    ]:
        with pytest.raises(ValueError, match=match):
            strat.value_and_grad({}, cfg, dummy, None)


# ---------------------------------------------------------------------------
# fit() end to end: the JSONL contract tools/report.py renders
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    import os

    from tpukit.flags import TrainFlags
    from tpukit.shardings import SingleDevice
    from tpukit.train import fit

    tmp = tmp_path_factory.mktemp("obs")
    log = tmp / "run.jsonl"
    hb = tmp / "hb"
    flags = TrainFlags(
        batch_size=8, epochs=1, sequence_length=33, dim=32, head_dim=8,
        heads=4, num_layers=2, learning_rate=1e-3, dataset_slice="80",
        num_workers=0, disable_amp=True, seed=0,
        metrics_log=str(log), log_grad_norms=True, spike_threshold=8.0,
        heartbeat_dir=str(hb),
    )
    cwd = os.getcwd()
    os.chdir(tmp)  # checkpoints/ lands in tmp
    try:
        result = fit(flags, SingleDevice())
    finally:
        os.chdir(cwd)
    records = [json.loads(line) for line in log.read_text().splitlines()]
    return flags, result, records, log, hb


def test_fit_emits_goodput_windows(telemetry_run):
    _, _, records, _, _ = telemetry_run
    train = [r for r in records if r["kind"] == "train"]
    assert train, "no window record (dataset too small for PRINT_FREQ?)"
    for r in train:
        assert 0.0 < r["goodput"] <= 1.0
        assert abs(sum(r["spans"].values()) - 1.0) < 1e-6
        assert r["window_s"] > 0
        for key in ("grad_norm", "update_norm", "param_norm"):
            assert r[key] > 0.0
        assert np.isfinite(r["loss"])


def test_fit_emits_xla_analysis_once_per_compile(telemetry_run):
    _, _, records, _, _ = telemetry_run
    xla = [r for r in records if r["kind"] == "xla"]
    fns = {r["fn"] for r in xla}
    assert {"train_step", "eval_step"} <= fns
    assert len(xla) == len(fns)  # once per compile, not per step/window
    train_rec = next(r for r in xla if r["fn"] == "train_step")
    assert train_rec["flops"] > 0
    assert train_rec["bytes_accessed"] > 0
    assert train_rec["memory"]["peak_bytes_estimate"] > 0
    assert train_rec["strategy"] == "single"
    assert train_rec["collectives"] == {}  # single device: no comm


def test_fit_xla_records_carry_hlolint_verdict(telemetry_run):
    """Round 16: every xla record carries the rule-engine summary
    (tpukit/analysis) — on the single-device world the verdict is clean
    (donated state aliases, no collectives, no async pairs)."""
    _, _, records, _, _ = telemetry_run
    xla = [r for r in records if r["kind"] == "xla"]
    for r in xla:
        verdict = r.get("hlolint")
        assert verdict is not None, r["fn"]
        assert verdict["clean"] is True, (r["fn"], verdict)
        assert verdict["errors"] == 0


def test_fit_emits_epoch_and_validation_records(telemetry_run):
    _, _, records, _, _ = telemetry_run
    ep = next(r for r in records if r["kind"] == "epoch")
    assert abs(sum(ep["fractions"].values()) - 1.0) < 1e-6
    assert 0.0 < ep["goodput"] <= 1.0
    assert ep["seconds"]["eval"] > 0 and ep["seconds"]["generate"] > 0
    val = next(r for r in records if r["kind"] == "validation")
    assert np.isfinite(val["loss"])


def test_fit_writes_heartbeat_and_counts_no_spikes(telemetry_run):
    _, result, _, _, hb = telemetry_run
    files = list(hb.glob("heartbeat-p*.json"))
    assert len(files) == 1  # one per process
    beat = json.loads(files[0].read_text())
    assert beat["process"] == 0
    assert beat["step"] == int(result.state.step)
    assert result.metrics["spike_events"] == 0


def test_report_renders_run(telemetry_run):
    from tools.report import load, summarize

    _, _, _, log, _ = telemetry_run
    text = summarize(load(str(log)))
    assert "goodput" in text
    assert "xla static analysis: train_step" in text
    assert "val loss" in text


def test_report_flags_unexpected_collectives():
    """A strategy that DECLARES no collectives (comm_ops = ()) must have
    every measured collective flagged; a foreign log without the key
    cannot flag anything."""
    from tools.report import summarize

    base = {
        "kind": "xla", "fn": "train_step", "strategy": "single",
        "flops": 1.0, "bytes_accessed": 1.0, "memory": None, "time": 0,
        "collectives": {"all-gather": {"count": 1, "bytes": 1024}},
    }
    declared_empty = summarize([dict(base, expected_comm_ops=[])])
    assert "UNEXPECTED" in declared_empty
    declared_match = summarize([dict(base, expected_comm_ops=["all-gather"])])
    assert "UNEXPECTED" not in declared_match
    undeclared = summarize([base])
    assert "UNEXPECTED" not in undeclared


# ---------------------------------------------------------------------------
# round-7 satellites: line-buffered StepLogger, compile-cache accounting,
# report.py prefetch rendering + --min_goodput gate
# ---------------------------------------------------------------------------


def test_steplogger_line_visible_without_close(tmp_path):
    """Line-buffered single-write records: every logged line is durable on
    disk immediately (no close/flush needed), so a killed run's log is
    readable up to its last complete record."""
    from tpukit.obs import StepLogger

    path = tmp_path / "log.jsonl"
    logger = StepLogger(str(path))
    logger.log(kind="train", step=1, loss=2.5)
    logger.log(kind="train", step=2, loss=2.25)
    lines = path.read_text().splitlines()  # BEFORE close
    assert [json.loads(l)["step"] for l in lines] == [1, 2]
    logger.close()
    logger.close()  # idempotent
    StepLogger("").log(kind="noop")  # empty path stays a no-op


def test_compile_cache_misses_then_hits(tmp_path):
    """enable_compilation_cache mid-process: first compile misses and
    writes an entry; an identical fresh jit then HITS — counted through
    jax's own monitoring events."""
    from tpukit.cache import enable_compilation_cache

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        stats = enable_compilation_cache(str(tmp_path / "cc"))
        jax.jit(lambda x: x @ x + 5)(np.ones((32, 32), np.float32)).block_until_ready()
        s1 = stats.stats()
        assert s1["requests"] >= 1 and s1["misses"] >= 1
        assert s1["new_entries"] >= 1  # the executable landed on disk

        stats2 = enable_compilation_cache(str(tmp_path / "cc"))
        jax.jit(lambda x: x @ x + 5)(np.ones((32, 32), np.float32)).block_until_ready()
        s2 = stats2.stats()
        assert s2["hits"] >= 1 and s2["misses"] == 0
    finally:
        # hand the suite back its conftest-configured cache
        if prev_dir:
            enable_compilation_cache(prev_dir, min_compile_time_secs=prev_min)
        else:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min
            )


def test_report_renders_prefetch_and_compile_cache():
    from tools.report import summarize

    recs = [
        {
            "kind": "train", "step": 8, "loss": 2.0, "goodput": 0.9,
            "tokens_per_sec": 1000.0, "window_s": 2.0,
            "spans": {"prefetch_stall": 0.05, "step": 0.2, "sync": 0.7,
                      "other": 0.05},
            "prefetch_stall_s": 0.1, "prefetch_occupancy": 1.8, "time": 0,
        },
        {
            "kind": "compile_cache", "dir": "/x/cache", "entries": 5,
            "new_entries": 2, "requests": 5, "hits": 3, "misses": 2,
            "time": 1,
        },
    ]
    text = summarize(recs)
    assert "prefetch: stall 5.0% of window wall-clock" in text
    assert "occupancy mean 1.80" in text
    assert "compile cache" in text and "hits 3" in text and "misses 2" in text


def test_report_min_goodput_gate(tmp_path):
    from tools.report import check_min_goodput
    from tools.report import main as report_main

    recs = [
        {"kind": "train", "step": 8, "loss": 2.0, "goodput": 0.9, "time": 0},
        {"kind": "train", "step": 16, "loss": 1.9, "goodput": 0.7, "time": 1},
    ]
    ok, msg = check_min_goodput(recs, 0.75)  # mean 0.8
    assert ok and "OK" in msg
    ok, msg = check_min_goodput(recs, 0.85)
    assert not ok and "FAIL" in msg
    assert not check_min_goodput([{"kind": "epoch"}], 0.5)[0]  # no windows

    log = tmp_path / "r.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert report_main([str(log), "--min_goodput", "0.75"]) == 0
    assert report_main([str(log), "--min_goodput", "0.85"]) == 2
    assert report_main([str(log)]) == 0  # gate off by default


# ---------------------------------------------------------------------------
# multi-host heartbeats, for real (reuses the 2-process world harness)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_heartbeat_files_in_two_process_world(tmp_path):
    from test_multiprocess import _launch_world

    hb = tmp_path / "hb"
    _launch_world(
        "main-ddp.py", tmp_path,
        extra=["--heartbeat_dir", str(hb), "--heartbeat_timeout", "300"],
    )
    files = sorted(p.name for p in hb.glob("heartbeat-p*.json"))
    assert files == ["heartbeat-p00000.json", "heartbeat-p00001.json"]
    recs = [json.loads((hb / f).read_text()) for f in files]
    assert {r["process"] for r in recs} == {0, 1}
    assert all(r["step"] > 0 for r in recs)  # the epoch-end beat
