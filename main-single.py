#!/usr/bin/env python
"""Recipe 1: single-device training.

TPU-native twin of reference `main-single.py`: train the GPT-style decoder LM
on TinyStories (or the offline fixture corpus) on one device. The reference's
`.to("cuda" if available else "cpu")` (main-single.py:21) becomes a trivial
one-device mesh; `torch.compile` (main-single.py:38-39) becomes the always-on
jitted train step. The entire train/eval/generate/checkpoint loop —
duplicated per recipe in the reference — lives in `tpukit.train.fit`; this
recipe is just flags + strategy.

Run: `python main-single.py --batch_size 64 --epochs 5 ...`
(same 12 flags as the reference CLI, main-single.py:156-167).
"""

from tpukit.flags import parse_flags
from tpukit.shardings import SingleDevice
from tpukit.train import fit


def main(argv=None):
    flags = parse_flags(argv)
    return fit(flags, SingleDevice())


if __name__ == "__main__":
    import sys

    from tpukit.recovery import run_recipe

    # Exit-code contract (docs/DESIGN.md "recovery", README): 0 clean,
    # 75 preempted-and-checkpointed, 76 anomaly abort, 77 rollback budget
    # exhausted — what a babysitter script keys its relaunch decision on.
    sys.exit(run_recipe(main))
