"""Ring attention: causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all — its attention is a
dense single-device S x S matmul and the max sequence is 256 (SURVEY §2.4,
§5; reference models/gpt.py:79-99, data.py:18). tpukit makes long-context a
first-class axis: shard the *sequence* over a `seq` mesh axis and compute
exact causal attention with the classic ring schedule — each device keeps
its local Q block and online-softmax state while K/V (and the padding-mask
slice that travels with them, the CP analogue of the reference pipeline's
(x, mask) tuple threading) rotate around the ring via `lax.ppermute`, one
hop per step, P steps total. Peak memory per device is O(S/P * S/P) scores
and O(S/P) activations; the collective rides ICI.

Schedule efficiency (VERDICT r3 #3):
  - **Causally-unreachable hops are skipped.** After i hops a device holds
    the K/V block that originated at (my_index - i) mod P; blocks with
    src > my_index lie entirely in the causal future of every local query,
    so the whole [B,h,S_loc,S_loc] score/softmax/PV computation (and its
    backward) is gated off with `lax.cond` — only the ppermute runs. Across
    the ring that cuts total attention FLOPs from P^2 blocks to P(P+1)/2
    (~2x at P=8). The predicate is device-varying but the gated region is
    collective-free (the permutes happen outside it), so the cond is legal
    under shard_map.
  - **Matmuls stay in the input dtype** (bf16 under the default training
    policy) with float32 accumulation (`preferred_element_type`) — the MXU
    path — instead of upcasting Q/K to f32 first; only the softmax state
    (m, l, acc) is carried in f32, matching the dense XLA path's
    "logits in compute dtype, softmax in f32" split (ops/attention.py).
  - **Transfer/compute overlap**: each hop's ppermute depends only on the
    carried K/V, never on that hop's score math, and is issued before it —
    XLA's async collective scheduler overlaps the ICI transfer with the
    current hop's compute (double buffering by dataflow).

Masking matches tpukit/ops/attention.py: -1e9 additive causal term on
*global* positions (each device knows its ring offset), then finfo.min
overwrite for padded keys. As with the flash kernel, a fully-padded query
row attends uniformly over its causal prefix rather than over all S (the
XLA path's quirk); such rows are loss-ignored.

Runs inside `shard_map` (Manual mesh axes) — see the ContextParallel
strategy in tpukit/shardings.py. Autodiff through `ppermute`/`scan`/`cond`
gives the backward ring for free (and the cond gates the backward FLOPs of
skipped hops too).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpukit.compat import axis_size as compat_axis_size
from tpukit.ops.attention import NEG_INF


def zigzag_order(seq_len: int, ring: int) -> np.ndarray:
    """Token permutation for the causally-balanced zigzag layout.

    Splits `seq_len` into 2*ring chunks and orders them so a CONTIGUOUS
    shard over `ring` devices gives device d chunks (d, 2*ring-1-d): one
    early chunk (few causal keys) and one late chunk (many) — every device
    then does the same attention work per hop, fixing the contiguous ring's
    critical-path imbalance (device P-1 saw P reachable hops, device 0 one).
    Host-side numpy; apply as `x[:, zigzag_order(S, P)]` before sharding.
    """
    if seq_len % (2 * ring):
        raise ValueError(f"zigzag needs seq_len % (2*ring) == 0, got {seq_len} over {ring}")
    c = seq_len // (2 * ring)
    idx = []
    for d in range(ring):
        idx.append(np.arange(d * c, (d + 1) * c))
        idx.append(np.arange((2 * ring - 1 - d) * c, (2 * ring - d) * c))
    return np.concatenate(idx)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    axis_name: str,
    pad_mask: jax.Array | None = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) context parallelism: the second
    long-context schedule next to the ring.

    Inside shard_map each device holds `[B, h, S/P, d]`. One `all_to_all`
    re-partitions from sequence-sharded to HEAD-sharded (`[B, h/P, S, d]`),
    each device runs ordinary full-sequence causal attention on its head
    subset — which on TPU is the Pallas flash kernel, the fastest attention
    path in the framework — and a second all_to_all restores the sequence
    sharding. Two collectives total per attention call (vs P ppermute hops
    for the ring), at the cost of requiring heads % P == 0 and O(S) per
    device transient activations for the exchanged heads.

    Works on the CONTIGUOUS sequence layout (positions are implicit in the
    gathered order), unlike the ring's zigzag. Exactness: the local
    computation is the standard causal attention over the full sequence —
    no online-state stitching at all.
    """
    ring = compat_axis_size(axis_name)
    heads = q.shape[1]
    if heads % ring:
        raise ValueError(
            f"ulysses attention needs heads ({heads}) divisible by the "
            f"'{axis_name}' axis size ({ring}); use the ring schedule"
        )

    # One stacked exchange for q/k/v (axes shift by one under the stack):
    # a single all_to_all instead of three dependency-free launches.
    qkv = jnp.stack([q, k, v])  # [3, B, h, S/P, d]
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3, tiled=True)  # lint: allow(collective-spelling): ulysses head re-partition — activation re-layout inside the attention schedule (CP comm_ops audits it), not a grad/dispatch wire
    qh, kh, vh = qkv[0], qkv[1], qkv[2]  # [B, h/P, S, d] each
    if pad_mask is not None:
        pad_mask = jax.lax.all_gather(pad_mask, axis_name, axis=1, tiled=True)  # lint: allow(collective-spelling): boolean pad-mask broadcast for the gathered sequence — bytes are negligible and audited by CP comm_ops, not a payload wire

    from tpukit.ops.attention import causal_attention

    out = causal_attention(qh, kh, vh, scale=scale, pad_mask=pad_mask, impl="auto")
    # heads -> seq: the inverse exchange
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)  # lint: allow(collective-spelling): ulysses inverse head re-partition — same activation re-layout as the forward exchange


def _online_update(m, l, acc, s, v_blk):
    """One online-softmax merge of score block `s` (f32, masks applied) into
    the running (max, denom, numerator) state. The PV matmul runs in v's
    dtype (MXU) with f32 accumulation. Shared by both ring schedules."""
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    axis_name: str,
    pad_mask: jax.Array | None = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Exact causal attention over sequence shards.

    Args (all LOCAL shards, inside shard_map over `axis_name`):
      q, k, v: `[B, heads, S_local, head_dim]`.
      pad_mask: optional `[B, S_local]` bool, True = padding.
      layout: "contiguous" (device d holds global rows [d*Sl, (d+1)*Sl)) or
        "zigzag" (device d holds chunks d and 2P-1-d of 2P, i.e. the caller
        permuted the sequence with `zigzag_order` before sharding — the
        causally load-balanced schedule).

    Returns `[B, heads, S_local, head_dim]` in v's dtype.
    """
    if layout == "zigzag":
        return _zigzag_ring(q, k, v, scale=scale, axis_name=axis_name, pad_mask=pad_mask)
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}")
    ring = compat_axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, _, s_local, _ = q.shape
    if pad_mask is None:
        pad_mask = jnp.zeros((batch, s_local), dtype=jnp.bool_)

    rows = my_index * s_local + jnp.arange(s_local)  # global query positions

    # Each hop sends K/V/mask to the *next* device, so after i steps a device
    # holds the block that originated at (my_index - i) mod ring.
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, _):
        m, l, acc, k_c, v_c, mask_c, src = carry

        # Rotate first: the sends depend only on the carried K/V, so the
        # collective-permute overlaps this hop's compute.
        k_next = jax.lax.ppermute(k_c, axis_name, perm)
        v_next = jax.lax.ppermute(v_c, axis_name, perm)
        mask_next = jax.lax.ppermute(mask_c, axis_name, perm)

        def hop(state):
            m, l, acc = state
            cols = src * s_local + jnp.arange(s_local)  # global key positions
            s = (
                jnp.einsum(
                    "bhqd,bhkd->bhqk", q, k_c,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            # For strictly-lower hops (src < my_index) this compare is
            # all-true and folds to a no-op pass; only the diagonal hop
            # actually masks. One fused VPU pass either way.
            s = s + jnp.where(cols[None, :] <= rows[:, None], 0.0, NEG_INF)
            s = jnp.where(
                mask_c[:, None, None, :], jnp.finfo(jnp.float32).min, s
            )
            return _online_update(m, l, acc, s, v_c)

        # src > my_index: the whole block is in the causal future of every
        # local query — skip scores, softmax, PV and their backward.
        m, l, acc = jax.lax.cond(src <= my_index, hop, lambda s: s, (m, l, acc))
        return (m, l, acc, k_next, v_next, mask_next, (src - 1) % ring), None

    init = (
        jnp.full(q.shape[:3], -jnp.inf, jnp.float32),  # running max
        jnp.zeros(q.shape[:3], jnp.float32),  # running denom
        jnp.zeros(q.shape, jnp.float32),  # running numerator
        k,
        v,
        pad_mask,
        my_index,
    )
    (m, l, acc, *_), _ = jax.lax.scan(step, init, None, length=ring)
    return (acc / l[..., None]).astype(v.dtype)


def _zigzag_ring(q, k, v, *, scale, axis_name, pad_mask):
    """Causally load-balanced ring: the zigzag layout (see `zigzag_order`).

    Device d's local rows are chunks (a=d, b=2P-1-d) of 2P; the K/V block
    from ring source s carries chunks (s, 2P-1-s). Chunk-level causal
    reachability (row chunk >= key chunk) reduces each hop to HALF the
    dense block, the same half on every device:

      s < d : [Q_a; Q_b] x K_s           (both sub-blocks fully unmasked)
      s == d: full 2c x 2c block with the exact positional causal mask
              (the two diagonal sub-blocks plus Q_b x K_s)
      s > d : Q_b x [K_s; K_{2P-1-s}]    (both sub-blocks fully unmasked)

    so per-hop work is 2c^2 everywhere (4c^2 on the single diagonal hop) vs
    the contiguous schedule's 4c^2 on every reachable hop concentrated on
    high-index devices. Total FLOPs halve AND the critical path halves —
    the contiguous ring's skip gating couldn't shorten the critical path
    because device P-1 computed a full block every hop.

    Matmuls stay in the input dtype (MXU) with f32 accumulation; softmax
    state is f32; the ppermutes issue before the hop compute for overlap.
    """
    ring = compat_axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, _, s_local, _ = q.shape
    if s_local % 2:
        raise ValueError(f"zigzag local sequence must be even, got {s_local}")
    c = s_local // 2
    if pad_mask is None:
        pad_mask = jnp.zeros((batch, s_local), dtype=jnp.bool_)

    ar = jnp.arange(c)
    # global positions of the local rows: chunk d then chunk 2P-1-d
    rows = jnp.concatenate([my_index * c + ar, (2 * ring - 1 - my_index) * c + ar])
    finfo_min = jnp.finfo(jnp.float32).min
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, _):
        m, l, acc, k_c, v_c, mask_c, src = carry

        k_next = jax.lax.ppermute(k_c, axis_name, perm)
        v_next = jax.lax.ppermute(v_c, axis_name, perm)
        mask_next = jax.lax.ppermute(mask_c, axis_name, perm)

        def hop_lower(state):
            # src < d: all local rows attend the source's EARLY chunk only
            # (its late chunk 2P-1-src is in every local row's future).
            m, l, acc = state
            k_blk, v_blk, msk = k_c[:, :, :c], v_c[:, :, :c], mask_c[:, :c]
            s = (
                jnp.einsum("bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32)
                * scale
            )
            s = jnp.where(msk[:, None, None, :], finfo_min, s)
            return _online_update(m, l, acc, s, v_blk)

        def hop_diag(state):
            # src == d: the one hop with intra-chunk causal structure —
            # full 2c x 2c block under the exact positional mask.
            m, l, acc = state
            cols = jnp.concatenate([src * c + ar, (2 * ring - 1 - src) * c + ar])
            s = (
                jnp.einsum("bhqd,bhkd->bhqk", q, k_c, preferred_element_type=jnp.float32)
                * scale
            )
            s = s + jnp.where(cols[None, :] <= rows[:, None], 0.0, NEG_INF)
            s = jnp.where(mask_c[:, None, None, :], finfo_min, s)
            return _online_update(m, l, acc, s, v_c)

        def hop_upper(state):
            # src > d: only the local LATE chunk attends, but it reaches
            # both of the source's chunks.
            m, l, acc = state
            qb = q[:, :, c:]
            s = (
                jnp.einsum("bhqd,bhkd->bhqk", qb, k_c, preferred_element_type=jnp.float32)
                * scale
            )
            s = jnp.where(mask_c[:, None, None, :], finfo_min, s)
            mb, lb, accb = _online_update(m[:, :, c:], l[:, :, c:], acc[:, :, c:], s, v_c)
            return (
                jnp.concatenate([m[:, :, :c], mb], axis=2),
                jnp.concatenate([l[:, :, :c], lb], axis=2),
                jnp.concatenate([acc[:, :, :c], accb], axis=2),
            )

        branch = jnp.clip(jnp.sign(src - my_index) + 1, 0, 2)
        m, l, acc = jax.lax.switch(branch, [hop_lower, hop_diag, hop_upper], (m, l, acc))
        return (m, l, acc, k_next, v_next, mask_next, (src - 1) % ring), None

    init = (
        jnp.full(q.shape[:3], -jnp.inf, jnp.float32),
        jnp.zeros(q.shape[:3], jnp.float32),
        jnp.zeros(q.shape, jnp.float32),
        k,
        v,
        pad_mask,
        my_index,
    )
    (m, l, acc, *_), _ = jax.lax.scan(step, init, None, length=ring)
    return (acc / l[..., None]).astype(v.dtype)
