"""Ring attention: causal attention over a sequence-sharded mesh axis.

The reference has no sequence/context parallelism at all — its attention is a
dense single-device S x S matmul and the max sequence is 256 (SURVEY §2.4,
§5; reference models/gpt.py:79-99, data.py:18). tpukit makes long-context a
first-class axis: shard the *sequence* over a `seq` mesh axis and compute
exact causal attention with the classic ring schedule — each device keeps
its local Q block and online-softmax state while K/V (and the padding-mask
slice that travels with them, the CP analogue of the reference pipeline's
(x, mask) tuple threading) rotate around the ring via `lax.ppermute`, one
hop per step, P steps total. Peak memory per device is O(S/P * S/P) scores
and O(S/P) activations; the collective rides ICI.

Masking matches tpukit/ops/attention.py: -1e9 additive causal term on
*global* positions (each device knows its ring offset), then finfo.min
overwrite for padded keys. As with the flash kernel, a fully-padded query
row attends uniformly over its causal prefix rather than over all S (the
XLA path's quirk); such rows are loss-ignored.

Runs inside `shard_map` (Manual mesh axes) — see the ContextParallel
strategy in tpukit/shardings.py. Autodiff through `ppermute`/`scan` gives
the backward ring for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpukit.ops.attention import NEG_INF


def ring_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    axis_name: str,
    pad_mask: jax.Array | None = None,
) -> jax.Array:
    """Exact causal attention over sequence shards.

    Args (all LOCAL shards, inside shard_map over `axis_name`):
      q, k, v: `[B, heads, S_local, head_dim]`.
      pad_mask: optional `[B, S_local]` bool, True = padding.

    Returns `[B, heads, S_local, head_dim]` in v's dtype.
    """
    ring = jax.lax.axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, _, s_local, _ = q.shape
    if pad_mask is None:
        pad_mask = jnp.zeros((batch, s_local), dtype=jnp.bool_)

    rows = my_index * s_local + jnp.arange(s_local)  # global query positions
    qf = q.astype(jnp.float32)

    # Each hop sends K/V/mask to the *next* device, so after i steps a device
    # holds the block that originated at (my_index - i) mod ring.
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, _):
        m, l, acc, k_c, v_c, mask_c, src = carry

        cols = src * s_local + jnp.arange(s_local)  # global key positions
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_c.astype(jnp.float32)) * scale
        s = s + jnp.where(cols[None, :] <= rows[:, None], 0.0, NEG_INF)
        s = jnp.where(
            mask_c[:, None, None, :], jnp.finfo(jnp.float32).min, s
        )

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32)
        )

        k_next = jax.lax.ppermute(k_c, axis_name, perm)
        v_next = jax.lax.ppermute(v_c, axis_name, perm)
        mask_next = jax.lax.ppermute(mask_c, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next, mask_next, (src - 1) % ring), None

    init = (
        jnp.full(q.shape[:3], -jnp.inf, jnp.float32),  # running max
        jnp.zeros(q.shape[:3], jnp.float32),  # running denom
        jnp.zeros(qf.shape, jnp.float32),  # running numerator
        k,
        v,
        pad_mask,
        my_index,
    )
    (m, l, acc, *_), _ = jax.lax.scan(step, init, None, length=ring)
    return (acc / l[..., None]).astype(v.dtype)
