"""Runtime initialization and device-mesh construction.

TPU-native replacement for the reference's L0 comms layer (SURVEY §2.5):
`dist.init_process_group("nccl")` + torchrun/c10d rendezvous + manual
rank->`cuda:{rank % ndev}` binding (reference main-ddp.py:25-35, docstring
main-ddp.py:1-6). Under JAX there is no backend string and no launcher
incantation: the PJRT runtime owns the devices, `jax.distributed.initialize`
does the multi-host rendezvous (driven by the TPU runtime's own metadata),
and parallelism is expressed as a `jax.sharding.Mesh` over the device grid.
The compiler emits the ICI/DCN collectives from sharding annotations.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

_initialized = False


def initialize_runtime() -> None:
    """Multi-host rendezvous (twin of init_mp, reference main-ddp.py:25-31).

    On a single host this is a no-op: the TPU runtime already knows its
    topology. On multi-host deployments (JAX_COORDINATOR_ADDRESS or a TPU pod
    environment), `jax.distributed.initialize()` wires up DCN — the
    capability the reference delegates to torchrun + c10d rendezvous.
    """
    global _initialized
    if _initialized:
        return
    if _distributed_client_active():
        _initialized = True  # launcher/runtime already did the rendezvous
        return
    # NB: must run BEFORE any backend-initializing JAX call (jax.devices(),
    # jax.process_count(), ...) — jax.distributed.initialize() refuses to run
    # after the XLA backend exists. So multi-host detection here is env-only.
    explicit = bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if explicit or _pod_env_detected():
        # jax.distributed.initialize has no env-var fallback for the process
        # count/rank (only launchers/cluster detection supply them), so an
        # explicit-coordinator launch passes them through from the
        # environment: the torchrun-style contract (reference main-ddp.py:1-6
        # rendezvous) without a launcher dependency.
        kwargs = {}
        if explicit:
            kwargs["coordinator_address"] = os.environ["JAX_COORDINATOR_ADDRESS"]
            n_procs = os.environ.get("JAX_NUM_PROCESSES")
            proc_id = os.environ.get("JAX_PROCESS_ID")
            # The pair must be set (or unset) together: passing only one to
            # jax.distributed.initialize fails with an opaque error deep in
            # JAX instead of naming the missing variable (ADVICE r4).
            if bool(n_procs) != bool(proc_id):
                missing = "JAX_PROCESS_ID" if n_procs else "JAX_NUM_PROCESSES"
                raise RuntimeError(
                    f"JAX_COORDINATOR_ADDRESS is set but only one of the "
                    f"process-identity pair is: {missing} is missing. Set "
                    "both JAX_NUM_PROCESSES and JAX_PROCESS_ID (or neither, "
                    "to let a launcher/cluster environment supply them)."
                )
            if n_procs:
                kwargs["num_processes"] = int(n_procs)
                kwargs["process_id"] = int(proc_id)
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as exc:
            msg = str(exc).lower()
            # Actual JAX error texts for the two benign races:
            # "distributed.initialize should only be called once" and
            # "... must be called before any JAX calls that might initialise
            # the XLA backend" (when the launcher initialized both for us and
            # a client is now active).
            # "already been called"/"already initialized" are double-init
            # races (benign); a bare "already" substring would also swallow
            # genuine failures like "address already in use".
            if (
                "only be called once" in msg
                or "already been called" in msg
                or "already initialized" in msg
                or _distributed_client_active()
            ):
                pass  # initialized by the launcher/runtime — fine
            elif explicit:
                # The operator asked for a multi-host run. Silently falling
                # back would train N independent single-host copies — the
                # worst possible failure mode on a pod. Fail loudly instead.
                raise RuntimeError(
                    "JAX_COORDINATOR_ADDRESS is set but "
                    "jax.distributed.initialize() failed; refusing to "
                    "silently degrade to independent single-host training. "
                    f"Original error: {exc}"
                ) from exc
            else:
                import warnings

                warnings.warn(
                    f"jax.distributed.initialize() failed ({exc}); "
                    "continuing single-host",
                    stacklevel=2,
                )
    _initialized = True


def _distributed_client_active() -> bool:
    """True when `jax.distributed` is already wired up (by us, a launcher,
    or the TPU runtime) — detected via the distributed client object, not by
    string-matching error messages."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def _pod_env_detected() -> bool:
    """Env-var-only sniff for a multi-host environment (no JAX calls, so the
    backend stays uninitialized and `jax.distributed.initialize()` is still
    legal). Covers Cloud TPU pod slices, megascale, SLURM and OMPI launchers
    — the environments JAX's own cluster auto-detection understands. Each
    signal must show MORE THAN ONE host (single-host TPU VMs also export
    TPU_WORKER_HOSTNAMES, as a one-entry list)."""
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):  # pod slice
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):  # multislice
        return True
    for k in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(k, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def is_process_zero() -> bool:
    """Twin of the reference's `rank == 0` gating (main-ddp.py:106,170,180)."""
    return jax.process_index() == 0


def create_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a named device mesh.

    `axes` maps axis name -> size, e.g. `{"data": 8}` for DP/FSDP,
    `{"stage": 4}` for pipeline, `{"data": 2, "stage": 4}` for the 2-D
    hybrid. A size of -1 means "all remaining devices". With `axes=None`,
    returns a trivial 1-device mesh (the single-device recipe).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if axes is None:
        return Mesh(devices[:1].reshape(1), ("data",))

    names = tuple(axes.keys())
    sizes = list(axes.values())
    n = devices.size
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes {axes}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    return Mesh(devices[:total].reshape(sizes), names)


def place_host_array(x, sharding):
    """Place a host array at `sharding`, multi-host safe: single-process
    uses `device_put`; multi-process builds the global array from each
    host's addressable shards (`device_put` onto a sharding spanning
    non-addressable devices would raise). Every process must call this with
    the same value. Shared by checkpoint restore, resume placement and the
    decode-buffer path."""
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx, x=x: x[idx])


def device_kind() -> str:
    return jax.devices()[0].device_kind


def sync_global_devices(tag: str = "barrier") -> None:
    """Host-level sync where one is truly needed (twin of `dist.barrier()`,
    reference main-ddp.py:176,179 — but note SPMD needs none of the
    reference's barriers; this exists for multi-host checkpoint sequencing)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
